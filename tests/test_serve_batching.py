"""Continuous micro-batching (ISSUE 13): batch keys, coalesced
dispatch, bit-identity, bucketing, attribution, degradation.

The load-bearing contracts: queued requests sharing a batch key (same
pipeline structure, shapes, dtypes, terminal and sharding — across
tenants) coalesce into ONE stacked dispatch whose every lane is
BIT-IDENTICAL to its standalone dispatch; partial batches pad to
bucketed widths so steady state runs zero fresh XLA compiles; a lone
request takes the standalone path untouched; per-request and
per-tenant attribution survive coalescing; any claim/dispatch failure
degrades every request to its standalone dispatch (batching is an
optimisation, never a new failure mode); and ``Server.stop`` with
queued-but-unstarted requests fails their futures pointedly — no hang,
zero arbiter bytes leaked — batched or not.
"""

import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import bolt_tpu as bolt
from bolt_tpu import analysis, engine, serve
from bolt_tpu.tpu import batched

pytestmark = pytest.mark.serve


ADD1 = lambda v: v + 1        # hoisted: same-key requests must share
MUL2 = lambda v: v * 2        # stage callables (identity-keyed)


@pytest.fixture(autouse=True)
def _no_leaked_server():
    yield
    assert serve.active() is None, "a test leaked an active server"


def _bases(mesh, n=6, shape=(32, 8)):
    return [bolt.array(
        np.random.RandomState(i).randn(*shape).astype(np.float32),
        mesh).cache() for i in range(n)]


# ---------------------------------------------------------------------
# the batch key
# ---------------------------------------------------------------------

def test_batch_key_equality_and_difference(mesh):
    bs = _bases(mesh, 2)
    k1 = batched.batch_key(bs[0].map(ADD1).sum())
    k2 = batched.batch_key(bs[1].map(ADD1).sum())
    assert k1 is not None and k1 == k2          # same shape/func/terminal
    # terminal differs
    assert batched.batch_key(bs[0].map(ADD1).min()) != k1
    # chain differs (different callable identity)
    assert batched.batch_key(bs[0].map(MUL2).sum()) != k1
    # shape differs
    other = bolt.array(np.ones((16, 8), np.float32), mesh)
    assert batched.batch_key(other.map(ADD1).sum()) != k1
    # axis spec differs
    assert batched.batch_key(bs[0].map(ADD1).sum(axis=(0, 1))) != k1
    # the chain-materialise form is its own key family
    kc = batched.batch_key(bs[0].map(ADD1))
    assert kc is not None and kc[0] == "chain" and kc != k1


def test_batch_key_ineligible_shapes(mesh):
    bs = _bases(mesh, 1)
    # deferred filter: no key
    assert batched.batch_key(bs[0].filter(lambda v: v.sum() > 0)) is None
    # concrete array (nothing lazy): no key
    assert batched.batch_key(bs[0]) is None
    # streaming source: no key (streams batch per slab in the executor)
    x = np.ones((16, 8), np.float32)
    src = bolt.fromcallback(lambda idx: x[idx], (16, 8), mesh,
                            dtype=np.float32, chunks=4)
    assert batched.batch_key(src.map(ADD1).sum()) is None
    # a donating chain refuses to batch (donation semantics stay eager)
    with engine.donation(0):
        donating = bolt.array(x, mesh).map(ADD1)
        assert batched.batch_key(donating.sum()) is None


# ---------------------------------------------------------------------
# coalesced dispatch: bit-identity, bucketing, counters
# ---------------------------------------------------------------------

def test_batched_stat_bit_identical_and_counted(mesh):
    bs = _bases(mesh)
    refs = [np.asarray(b.map(ADD1).sum().toarray()) for b in bs]
    c0 = engine.counters()
    with serve.serving(workers=2, batching={"max_batch": 8,
                                            "linger": 0.02}) as sv:
        futs = [sv.submit(bs[i % 6].map(ADD1).sum(),
                          tenant="t%d" % (i % 3)) for i in range(12)]
        outs = [np.asarray(f.result(timeout=120).toarray())
                for f in futs]
    c1 = engine.counters()
    for i, out in enumerate(outs):
        assert out.dtype == refs[i % 6].dtype
        assert np.array_equal(out, refs[i % 6])
    assert c1["batched_dispatches"] > c0["batched_dispatches"]
    assert c1["batched_requests"] - c0["batched_requests"] >= 2
    # coalesced futures carry their batch attribution
    widths = [f.batch_width for f in futs if f.batch_width]
    assert widths and all(w >= 2 for w in widths)
    asm = [f.assembly_seconds for f in futs if f.batch_width]
    assert all(a is not None and a >= 0 for a in asm)


def test_partial_bucket_pads_and_stays_bit_identical(mesh):
    bs = _bases(mesh, 3)
    refs = [np.asarray(b.map(ADD1).sum().toarray()) for b in bs]
    with serve.serving(workers=1, batching={"max_batch": 8,
                                            "linger": 0.05}) as sv:
        gate = threading.Event()
        blocker = sv.submit(gate.wait)           # park the ONE worker
        futs = [sv.submit(bs[i].map(ADD1).sum()) for i in range(3)]
        gate.set()
        outs = [np.asarray(f.result(timeout=120).toarray())
                for f in futs]
        blocker.result(timeout=30)
    for out, ref in zip(outs, refs):
        assert np.array_equal(out, ref)
    # 3 requests pad into the 4-bucket: ONE coalesced dispatch
    assert [f.batch_width for f in futs] == [3, 3, 3]


def test_multistat_group_rides_one_batched_dispatch(mesh):
    bs = _bases(mesh, 4)

    def group(i):
        m = bs[i].map(ADD1)
        return m.sum(), m.var()

    refs = []
    for i in range(4):
        s, v = bolt.compute(*group(i))
        refs.append((np.asarray(s.toarray()), np.asarray(v.toarray())))
    with serve.serving(workers=1, batching={"max_batch": 4,
                                            "linger": 0.05}) as sv:
        gate = threading.Event()
        blocker = sv.submit(gate.wait)
        pairs = [group(i) for i in range(4)]
        futs = [sv.submit(p[0]) for p in pairs]   # submit ONE member
        gate.set()
        outs_s = [np.asarray(f.result(timeout=120).toarray())
                  for f in futs]
        blocker.result(timeout=30)
        # the sibling member resolved in the SAME batched dispatch
        outs_v = [np.asarray(p[1].toarray()) for p in pairs]
    for i in range(4):
        assert np.array_equal(outs_s[i], refs[i][0])
        assert np.array_equal(outs_v[i], refs[i][1])


def test_chain_materialise_batched(mesh):
    bs = _bases(mesh, 4)
    refs = [np.asarray(b.map(ADD1).toarray()) for b in bs]
    with serve.serving(workers=1, batching={"max_batch": 4,
                                            "linger": 0.05}) as sv:
        gate = threading.Event()
        blocker = sv.submit(gate.wait)
        futs = [sv.submit(bs[i].map(ADD1)) for i in range(4)]
        gate.set()
        outs = [np.asarray(f.result(timeout=120).toarray())
                for f in futs]
        blocker.result(timeout=30)
    for out, ref in zip(outs, refs):
        assert np.array_equal(out, ref)
    assert all(f.batch_width == 4 for f in futs)


def test_zero_fresh_compiles_across_bucketed_widths(mesh):
    bs = _bases(mesh)

    def make(i=0):
        return bs[i % 6].map(ADD1).sum()

    make().cache()                     # standalone program
    with serve.serving(workers=1, batching={"max_batch": 8,
                                            "linger": 0.05}) as sv:
        warmed = batched.warm(make, buckets=sv.batching.buckets)
        assert warmed == (2, 4, 8)
        c0 = engine.counters()
        for burst in (1, 2, 3, 5, 8):  # every width buckets to 2/4/8
            gate = threading.Event()
            blocker = sv.submit(gate.wait)
            futs = [sv.submit(make(i)) for i in range(burst)]
            gate.set()
            [f.result(timeout=120) for f in futs]
            blocker.result(timeout=30)
        c1 = engine.counters()
    assert c1["misses"] == c0["misses"]
    assert c1["aot_compiles"] == c0["aot_compiles"]


def test_single_request_takes_the_standalone_path(mesh):
    bs = _bases(mesh, 1)
    ref = np.asarray(bs[0].map(ADD1).sum().toarray())
    c0 = engine.counters()
    with serve.serving(workers=1, batching=True) as sv:
        f = sv.submit(bs[0].map(ADD1).sum())
        out = np.asarray(f.result(timeout=120).toarray())
    c1 = engine.counters()
    assert np.array_equal(out, ref)
    assert f.batch_width is None and f.assembly_seconds is None
    assert c1["batched_dispatches"] == c0["batched_dispatches"]


# ---------------------------------------------------------------------
# the deferred reduce door
# ---------------------------------------------------------------------

def test_reduce_defers_only_under_a_batching_server(mesh):
    bs = _bases(mesh, 4)
    # no batching server: reduce is eager (concrete immediately)
    out = bs[0].map(ADD1).reduce(jnp.add)
    assert out._spending is None
    ref = [np.asarray(b.map(ADD1).reduce(jnp.add).toarray()) for b in bs]
    with serve.serving(workers=1, batching={"max_batch": 4,
                                            "linger": 0.05}) as sv:
        # armed: reduce defers as a pending handle...
        lone = bs[0].map(ADD1).reduce(jnp.add)
        assert lone._spending is not None
        # ...whose standalone read is bit-identical to eager
        assert np.array_equal(np.asarray(lone.toarray()), ref[0])
        # and a queued burst coalesces, bit-identically
        gate = threading.Event()
        blocker = sv.submit(gate.wait)
        futs = [sv.submit(bs[i].map(ADD1).reduce(jnp.add))
                for i in range(4)]
        gate.set()
        outs = [np.asarray(f.result(timeout=120).toarray())
                for f in futs]
        blocker.result(timeout=30)
    for got, want in zip(outs, ref):
        assert np.array_equal(got, want)
    assert all(f.batch_width == 4 for f in futs)
    # the server closed: the door is shut again
    assert bs[0].map(ADD1).reduce(jnp.add)._spending is None


def test_deferred_reduce_keeps_eager_error_contracts(mesh):
    b = _bases(mesh, 1)[0]
    with serve.serving(workers=1, batching=True):
        # a reducer that breaks the value-shape contract must refuse
        # the lazy door and raise at CALL time, like the eager path
        with pytest.raises(ValueError, match="value shape"):
            b.map(ADD1).reduce(lambda a, c: jnp.stack([a, c]))
        # empty reduce raises eagerly too
        empty = bolt.array(np.ones((0, 4), np.float32), mesh)
        with pytest.raises(TypeError, match="empty"):
            empty.reduce(jnp.add)
    serve.stop()


# ---------------------------------------------------------------------
# attribution, fair share, stats
# ---------------------------------------------------------------------

def test_per_tenant_accounting_survives_coalescing(mesh):
    bs = _bases(mesh, 4)
    tenants = ("acct-a", "acct-b")     # unique: registry groups are
    #                                    process-wide across tests
    with serve.serving(workers=1, batching={"max_batch": 4,
                                            "linger": 0.05}) as sv:
        before = {t: sv.stats()["tenants"].get(t, {}) for t in tenants}
        gate = threading.Event()
        blocker = sv.submit(gate.wait)
        futs = [sv.submit(bs[i].map(ADD1).sum(),
                          tenant=tenants[i % 2]) for i in range(4)]
        gate.set()
        [f.result(timeout=120) for f in futs]
        blocker.result(timeout=30)
        st = sv.stats()
    for t in tenants:
        entry = st["tenants"][t]
        b4 = before[t]
        assert entry["submitted"] - b4.get("submitted", 0) == 2
        assert entry["completed"] - b4.get("completed", 0) == 2
        assert entry["run_seconds"] > b4.get("run_seconds", 0.0)
        assert entry["queue_wait_seconds"] >= 0.0


def test_stats_batching_block_and_degraded_shapes(mesh):
    from bolt_tpu.obs import metrics as _metrics
    with serve.serving(workers=1) as sv:
        assert sv.stats()["batching"] == {}       # documented degraded
        #          shape on a server without a batching policy
    _metrics.registry().histogram(
        "serve.batch_occupancy.hist", lo=0, hi=9).reset()
    with serve.serving(workers=1, batching={"max_batch": 4,
                                            "linger": 0.01}) as sv:
        st = sv.stats()["batching"]
        assert st["max_batch"] == 4 and st["buckets"] == (2, 4)
        assert st["occupancy"] == {}              # no coalesced dispatch
        b = _bases(mesh, 2)
        gate = threading.Event()
        blocker = sv.submit(gate.wait)
        futs = [sv.submit(b[i].map(ADD1).sum(), tenant="statsq")
                for i in (0, 1)]
        # live queue depth is visible while parked
        assert sv.stats()["tenants"]["statsq"]["queue_depth"] == 2
        gate.set()
        [f.result(timeout=120) for f in futs]
        blocker.result(timeout=30)
        assert sv.stats()["tenants"]["statsq"]["queue_depth"] == 0
        occ = sv.stats()["batching"]["occupancy"]
        assert occ["dispatches"] >= 1 and occ["mean"] >= 2


def test_blt015_forecast_gated_on_a_batching_server(mesh):
    b = _bases(mesh, 1)[0]
    assert not analysis.check(b.map(ADD1).sum()).has("BLT015")
    with serve.serving(workers=1, batching=True):
        assert analysis.check(b.map(ADD1).sum()).has("BLT015")
        assert analysis.check(b.map(ADD1)).has("BLT015")
        # ineligible pipelines stay quiet
        assert not analysis.check(
            b.map(ADD1).filter(lambda v: v.sum() > 0)).has("BLT015")
    serve.stop()
    assert not analysis.check(b.map(ADD1).sum()).has("BLT015")


def test_batch_policy_validation():
    with pytest.raises(ValueError, match="max_batch"):
        serve.BatchPolicy(max_batch=1)
    with pytest.raises(ValueError, match="linger"):
        serve.BatchPolicy(linger=-1)
    with pytest.raises(ValueError, match="buckets"):
        serve.BatchPolicy(buckets=(1, 2))
    with pytest.raises(ValueError, match="bucket"):
        serve.BatchPolicy(max_batch=16, buckets=(2, 4))
    with pytest.raises(ValueError, match="bucket"):
        # a bucket WIDER than max_batch would pad every dispatch past
        # the promised widest width
        serve.BatchPolicy(max_batch=4, buckets=(8,))
    pol = serve.BatchPolicy(buckets=(4, 8))
    assert pol.max_batch == 8 and pol.buckets == (4, 8)
    with pytest.raises(ValueError, match="batching"):
        serve.Server(batching="yes")


# ---------------------------------------------------------------------
# degradation and races: batching must never be a failure mode
# ---------------------------------------------------------------------

def test_dispatch_failure_degrades_to_standalone(mesh, monkeypatch):
    bs = _bases(mesh, 4)
    refs = [np.asarray(b.map(ADD1).sum().toarray()) for b in bs]

    def boom(batch, buckets):
        raise RuntimeError("injected batched-dispatch failure")

    monkeypatch.setattr(batched, "dispatch", boom)
    with serve.serving(workers=1, batching={"max_batch": 4,
                                            "linger": 0.05}) as sv:
        gate = threading.Event()
        blocker = sv.submit(gate.wait)
        futs = [sv.submit(bs[i].map(ADD1).sum()) for i in range(4)]
        gate.set()
        outs = [np.asarray(f.result(timeout=120).toarray())
                for f in futs]
        blocker.result(timeout=30)
        assert sv.stats()["arbiter"]["in_use_bytes"] == 0
    for out, ref in zip(outs, refs):
        assert np.array_equal(out, ref)       # standalone fallback ran
    # degraded requests ran STANDALONE: no batch attribution
    assert all(f.batch_width is None for f in futs)
    assert all(f.assembly_seconds is None for f in futs)


def test_concurrent_reader_waits_for_the_claimed_fill(mesh):
    bs = _bases(mesh, 2)
    refs = [np.asarray(b.map(ADD1).sum().toarray()) for b in bs]
    arrs = [b.map(ADD1).sum() for b in bs]
    key = batched.batch_key(arrs[0])
    b = batched.claim(arrs, key)
    assert b is not None
    got = {}

    def reader():
        # resolve() during the claim window must WAIT for the batched
        # fill, then adopt it — never double-dispatch
        got["v"] = np.asarray(arrs[0].toarray())

    th = threading.Thread(target=reader)
    th.start()
    time.sleep(0.05)
    batched.dispatch(b, (2,))
    th.join(timeout=30)
    assert not th.is_alive()
    assert np.array_equal(got["v"], refs[0])
    assert np.array_equal(np.asarray(arrs[1].toarray()), refs[1])


def test_partial_claim_keeps_the_healthy_majority(mesh):
    # one raced member (its group resolved concurrently) must not cost
    # the rest their coalescing: the batch serves the claimable subset
    bs = _bases(mesh, 3)
    refs = [np.asarray(b.map(ADD1).sum().toarray()) for b in bs]
    with serve.serving(workers=1, batching={"max_batch": 4,
                                            "linger": 0.05}) as sv:
        gate = threading.Event()
        blocker = sv.submit(gate.wait)
        arrs = [bs[i].map(ADD1).sum() for i in range(3)]
        futs = [sv.submit(a) for a in arrs]
        # a user thread resolves request 1 while it sits queued
        raced = np.asarray(arrs[1].toarray())
        gate.set()
        outs = [np.asarray(f.result(timeout=120).toarray())
                for f in futs]
        blocker.result(timeout=30)
    assert np.array_equal(raced, refs[1])
    for out, ref in zip(outs, refs):
        assert np.array_equal(out, ref)
    # the two healthy requests coalesced; the raced one ran standalone
    assert futs[1].batch_width is None
    assert futs[0].batch_width == 2 and futs[2].batch_width == 2


def test_unclaim_releases_readers_to_standalone(mesh):
    bs = _bases(mesh, 2)
    arrs = [b.map(ADD1).sum() for b in bs]
    key = batched.batch_key(arrs[0])
    b = batched.claim(arrs, key)
    assert b is not None
    batched.unclaim(b)
    # un-claimed handles resolve standalone, bit-identically
    for arr, base in zip(arrs, bs):
        assert np.array_equal(np.asarray(arr.toarray()),
                              np.asarray(base.map(ADD1).sum().toarray()))


def test_claimed_group_declines_new_members(mesh):
    b = _bases(mesh, 1)[0]
    m = b.map(ADD1)
    h = m.sum()
    other = _bases(mesh, 2)[1].map(ADD1).sum()
    bt = batched.claim([h, other], batched.batch_key(h))
    assert bt is not None
    # a sibling terminal arriving mid-claim starts a FRESH group
    # (try_join declines) instead of joining one it could never ride
    v = m.var()
    assert v._spending is None or v._spending.group is not h._spending.group
    batched.dispatch(bt, (2,))
    assert np.array_equal(np.asarray(h.toarray()),
                          np.asarray(b.map(ADD1).sum().toarray()))


def test_deferred_reduce_ignores_accumulate_like_eager(mesh):
    # eager reduce always IGNORED accumulate (runs exact, no error);
    # arming a batching server must not make compute(handle,
    # accumulate=...) start raising in unrelated user code
    b = _bases(mesh, 1)[0]
    eager = bolt.compute(b.map(ADD1).reduce(jnp.add), accumulate="bf16")
    with serve.serving(workers=1, batching=True):
        h = b.map(ADD1).reduce(jnp.add)
        assert h._spending is not None            # the door is armed
        deferred = bolt.compute(h, accumulate="bf16")
        assert np.array_equal(np.asarray(deferred.toarray()),
                              np.asarray(eager.toarray()))
    serve.stop()


def test_estimate_fast_path_matches_admission_floor(mesh):
    # serve._estimate's chain-group fast path must agree with the
    # analysis layer's admission floor — one source of truth for BLT010
    from bolt_tpu.analysis import admission_floor_bytes
    from bolt_tpu.serve import _estimate
    b = _bases(mesh, 1)[0]
    for arr in (b.map(ADD1).sum(), b.map(ADD1).var()):
        assert _estimate(arr) == admission_floor_bytes(arr)


def test_warm_dispatches_not_counted_as_realised_coalescing(mesh):
    b = _bases(mesh, 1)[0]

    def make():
        return b.map(ADD1).sum()

    with serve.serving(workers=1, batching={"max_batch": 4,
                                            "linger": 0.01}) as sv:
        c0 = engine.counters()
        batched.warm(make, buckets=sv.batching.buckets)
        c1 = engine.counters()
        assert c1["batched_dispatches"] == c0["batched_dispatches"]
        assert c1["batched_requests"] == c0["batched_requests"]
        # warm DID run the bucket programs (fresh compiles, or cache
        # hits when an earlier test already built them)
        assert (c1["hits"] + c1["misses"]) > (c0["hits"] + c0["misses"])
        assert c1["dispatches"] > c0["dispatches"]


def test_failed_constructor_does_not_leak_the_armed_door(mesh):
    assert not batched.armed()
    with pytest.raises(ValueError, match="weight"):
        serve.Server(batching=True, weights={"a": 0})
    # the failed construction must not leave the lazy-reduce door open
    assert not batched.armed()
    b = _bases(mesh, 1)[0]
    assert b.map(ADD1).reduce(jnp.add)._spending is None


def test_gather_width_capped_by_the_arbiter_budget(mesh):
    # 4 queued same-key requests whose COMBINED batched footprint
    # (members + stacked copy ~ 2x) exceeds the budget: the gather must
    # cap the width so coalescing cannot bypass the arbitration that
    # would have serialised them standalone
    shape = (4096, 32)                     # 512 KB per request
    bs = _bases(mesh, 4, shape=shape)
    refs = [np.asarray(b.map(ADD1).sum().toarray()) for b in bs]
    est = bs[0]._data.nbytes
    budget = int(4.5 * est)                # fits 2 lanes + stack, not 4
    with serve.serving(workers=1, budget_bytes=budget,
                       batching={"max_batch": 4, "linger": 0.05}) as sv:
        gate = threading.Event()
        blocker = sv.submit(gate.wait)
        futs = [sv.submit(bs[i].map(ADD1).sum()) for i in range(4)]
        gate.set()
        outs = [np.asarray(f.result(timeout=120).toarray())
                for f in futs]
        blocker.result(timeout=30)
        assert sv.stats()["arbiter"]["in_use_bytes"] == 0
    for out, ref in zip(outs, refs):
        assert np.array_equal(out, ref)
    assert all((f.batch_width or 1) <= 2 for f in futs)


def test_occupancy_counts_realised_dispatches_only(mesh, monkeypatch):
    from bolt_tpu.obs import metrics as _metrics
    h = _metrics.registry().histogram("serve.batch_occupancy.hist",
                                      lo=0, hi=9)
    h.reset()
    bs = _bases(mesh, 3)

    def boom(batch, buckets):
        raise RuntimeError("injected")

    monkeypatch.setattr(batched, "dispatch", boom)
    with serve.serving(workers=1, batching={"max_batch": 4,
                                            "linger": 0.05}) as sv:
        gate = threading.Event()
        blocker = sv.submit(gate.wait)
        futs = [sv.submit(bs[i].map(ADD1).sum()) for i in range(3)]
        gate.set()
        [f.result(timeout=120) for f in futs]
        blocker.result(timeout=30)
    # the gather degraded to standalone dispatches: NO occupancy sample
    assert h.snapshot()["count"] == 0


# ---------------------------------------------------------------------
# Server.stop with queued-but-unstarted requests (ISSUE 13 satellite)
# ---------------------------------------------------------------------

def _park_and_queue(sv, mesh, batchable):
    bs = _bases(mesh, 4)
    gate = threading.Event()
    blocker = sv.submit(lambda: gate.wait(10))
    time.sleep(0.05)                   # the worker is inside the blocker
    if batchable:
        futs = [sv.submit(bs[i].map(ADD1).sum()) for i in range(4)]
    else:
        futs = [sv.submit(lambda i=i: i) for i in range(4)]
    return gate, blocker, futs


@pytest.mark.parametrize("batching", [None, {"max_batch": 4,
                                             "linger": 0.01}])
@pytest.mark.parametrize("batchable", [True, False])
def test_stop_fails_queued_unstarted_futures_pointedly(
        mesh, batching, batchable):
    sv = serve.start(workers=1, batching=batching)
    try:
        gate, blocker, futs = _park_and_queue(sv, mesh, batchable)
        releaser = threading.Thread(target=lambda: (time.sleep(0.2),
                                                    gate.set()))
        releaser.start()
        t0 = time.perf_counter()
        serve.stop(wait=False)
        elapsed = time.perf_counter() - t0
        releaser.join()
        assert elapsed < 8.0                        # no hang
        for f in futs:
            with pytest.raises(RuntimeError,
                               match="closed before this job ran"):
                f.result(timeout=5)
            assert f.done() and f.batch_width is None
        # the parked job itself was already running: it completes or
        # fails, but every queued-unstarted future failed pointedly and
        # the arbiter holds nothing
        assert sv.arbiter.in_use() == 0
        assert sv.arbiter.waiting() == 0
    finally:
        if serve.active() is sv:
            serve.stop(wait=False)


def test_close_wait_true_drains_queued_batched_jobs(mesh):
    bs = _bases(mesh, 3)
    refs = [np.asarray(b.map(ADD1).sum().toarray()) for b in bs]
    sv = serve.start(workers=1, batching={"max_batch": 4,
                                          "linger": 0.01})
    try:
        gate = threading.Event()
        blocker = sv.submit(lambda: gate.wait(10))
        time.sleep(0.05)
        futs = [sv.submit(bs[i].map(ADD1).sum()) for i in range(3)]
        gate.set()
        serve.stop(wait=True)          # drain: queued jobs RUN first
        for f, ref in zip(futs, refs):
            assert np.array_equal(np.asarray(
                f.result(timeout=5).toarray()), ref)
        assert blocker.done()
    finally:
        if serve.active() is sv:
            serve.stop(wait=False)


# ---------------------------------------------------------------------
# span / arbiter hygiene
# ---------------------------------------------------------------------

def test_batched_serving_leaks_no_spans_or_bytes(mesh):
    from bolt_tpu import obs
    bs = _bases(mesh, 4)
    obs.clear()
    obs.enable()
    try:
        with serve.serving(workers=2, batching={"max_batch": 4,
                                                "linger": 0.02}) as sv:
            futs = [sv.submit(bs[i % 4].map(ADD1).sum(),
                              tenant="t%d" % (i % 2)) for i in range(8)]
            [f.result(timeout=120) for f in futs]
            assert sv.stats()["arbiter"]["in_use_bytes"] == 0
        assert obs.active_count() == 0
        names = {s.name for s in obs.spans()}
        assert "serve.batch" in names
        assert "serve.batched_dispatch" in names
    finally:
        obs.disable()
        obs.clear()


# ---------------------------------------------------------------------
# width autotuning scaffold (ISSUE 14 satellite: BatchPolicy.autotune)
# ---------------------------------------------------------------------

def test_autotune_buckets_derive_from_occupancy_histogram():
    # synthetic log2-band histogram: mass at widths <=4 and a thin tail
    hist = [(1.0, 0), (2.0, 10), (4.0, 30), (8.0, 1), (16.0, 0),
            (float("inf"), 0)]
    got = batched.autotune_buckets(hist, max_batch=16, min_share=0.05)
    # the 8-band holds 1/41 < 5%: dropped; max_batch always closes
    assert got == (2, 4, 16)
    # overflow mass maps to max_batch; nothing observed -> None
    assert batched.autotune_buckets(
        [(2.0, 1), (float("inf"), 5)], max_batch=8) == (2, 8)
    assert batched.autotune_buckets([(2.0, 0)], max_batch=8) is None


def test_autotune_exact_power_occupancy_keeps_its_width():
    # a steady occupancy of EXACTLY 4 lands in the log2 band [4, 8):
    # both band edges must derive, so those batches dispatch at width 4
    # instead of padding every one of them to 8 (the review finding)
    got = batched.autotune_buckets([(8.0, 100)], max_batch=16)
    assert got == (4, 8, 16)
    assert batched.bucket_width(4, got) == 4


def test_batch_policy_rearm_respects_the_autotune_knob():
    static = serve.BatchPolicy(max_batch=16)
    before = static.buckets
    assert static.rearm([(4.0, 100), (float("inf"), 0)]) is False
    assert static.buckets == before            # static knobs untouched

    tuned = serve.BatchPolicy(max_batch=16, autotune=True)
    assert "autotune" in repr(tuned)
    assert tuned.rearm([(4.0, 100), (float("inf"), 0)]) is True
    assert tuned.buckets == (2, 4, 16)         # band [2,4): both edges
    assert tuned.buckets[-1] == tuned.max_batch
    # nothing observed yet: a no-op, buckets keep their last value
    assert tuned.rearm([(4.0, 0)]) is False
    assert tuned.buckets == (2, 4, 16)


def test_warm_rearms_an_autotune_policy_from_live_occupancy(mesh):
    bs = _bases(mesh, 8)

    def make(i=0):
        return bs[i % 8].map(ADD1).sum()

    pol = serve.BatchPolicy(max_batch=8, linger=0.05, autotune=True)
    with serve.serving(workers=1, queue_limit=64, batching=pol) as sv:
        assert sv.batching is pol
        # park the worker so a 4-wide batch assembles, realising
        # occupancy observations in serve.batch_occupancy.hist
        sv.stats()["batching"]  # touch the door
        gate = threading.Event()
        blocker = sv.submit(gate.wait)
        futs = [sv.submit(make(i), tenant="t") for i in range(4)]
        gate.set()
        [f.result(timeout=60) for f in futs]
        blocker.result(timeout=30)
        # re-arm on warm(): buckets re-derive from the realised mix
        before = tuple(pol.buckets)
        warmed = batched.warm(make, policy=pol)
        assert tuple(warmed) == tuple(pol.buckets)
        assert pol.buckets[-1] == pol.max_batch
        assert set(pol.buckets) <= set(before) | {pol.max_batch}


def test_warm_with_static_policy_keeps_buckets(mesh):
    bs = _bases(mesh, 4)

    def make(i=0):
        return bs[i % 4].map(ADD1).sum()

    pol = serve.BatchPolicy(max_batch=4)
    with serve.serving(workers=1, batching=pol) as sv:
        before = tuple(sv.batching.buckets)
        warmed = batched.warm(make, policy=pol)
        assert tuple(pol.buckets) == before    # autotune off: untouched
        assert tuple(warmed) == before
