"""The streamed two-phase shuffle (ISSUE 18): parity and contracts.

Parity is the load-bearing half: a ``swap`` recorded on a STREAMED
source resolves through the two-phase shuffle — phase 1 re-buckets each
uploaded slab on device, phase 2 concatenates resident buckets or
re-streams spilled ones — and must equal the materialise-first in-memory
swap BIT for bit (a transpose moves bytes, it never rounds).  Geometry
edges ride along: uneven last slabs, 1-record slabs, multi-value-axis
permutations, the key↔value round trip, and the budget≈one-bucket
forced-spill path.

Operational contracts: the swap stays LAZY until a consumer arrives,
terminals (sum / map / chunk().map()) consume the swapped stream without
full materialisation, a second identical pass compiles NOTHING new, the
BLT017 forecast agrees with the measured resident/spill decision, chaos
raises are absorbed in place by the ``stream.retries`` fence, and the
dict codec + spill-file layer keep their format contracts.
"""

import glob
import os

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu import _chaos, analysis, checkpoint, engine, stream
from bolt_tpu.tpu import codec as codec_mod

N, V0, V1 = 24, 6, 5
SHAPE = (N, V0, V1)


def _data(dtype=np.float32):
    n = int(np.prod(SHAPE))
    if np.issubdtype(np.dtype(dtype), np.integer):
        return ((np.arange(n) % 11) - 5).astype(dtype).reshape(SHAPE)
    return (np.arange(n, dtype=np.float64) * 0.37 - 100.0).astype(
        dtype).reshape(SHAPE)


def _source(data, mesh, chunks, codec=None):
    return bolt.fromcallback(lambda idx: data[idx], data.shape, mesh,
                             dtype=data.dtype, chunks=chunks,
                             codec=codec)


def _mat_swap(data, mesh, kaxes, vaxes):
    """The materialise-first oracle: concrete array, in-memory swap."""
    m = bolt.array(data, mesh)
    return np.asarray(m.swap(kaxes, vaxes)._data)


# ---------------------------------------------------------------------
# streamed vs materialised parity
# ---------------------------------------------------------------------

@pytest.mark.parametrize("chunks", [4, 5, 1])   # even, uneven tail, 1-record
@pytest.mark.parametrize("kaxes,vaxes", [
    ((0,), (0,)),          # the canonical key<->value exchange
    ((0,), (1,)),          # trailing value axis to the keys
    ((0,), (0, 1)),        # one key for BOTH value axes (new_split=2)
])
def test_streamed_swap_parity_bitexact(mesh, chunks, kaxes, vaxes):
    data = _data()
    s = _source(data, mesh, chunks).swap(kaxes, vaxes)
    assert s._stream is not None          # still lazy after the record
    got = np.asarray(s._data)
    assert np.array_equal(got, _mat_swap(data, mesh, kaxes, vaxes))


def test_swap_roundtrip_restores_source_bits(mesh):
    data = _data()
    rt = _source(data, mesh, 4).swap((0,), (0,)).swap((0,), (0,))
    assert np.array_equal(np.asarray(rt._data), data)


def test_swap_stays_lazy_until_consumed(mesh):
    calls = []

    def loader(idx):
        calls.append(idx)
        return _data()[idx]

    s = bolt.fromcallback(loader, SHAPE, mesh, dtype=np.float32,
                          chunks=4).swap((0,), (0,))
    assert calls == []                    # recording is free
    np.asarray(s._data)
    assert calls                          # resolution streamed the source


def test_swap_sum_terminal_consumes_stream(mesh):
    data = _data(np.float64)              # integer-free exactness n/a:
    data = np.round(data)                 # integer-valued f64 sums exact
    got = np.asarray(_source(data, mesh, 4).swap((0,), (0,)).sum())
    assert np.array_equal(got, np.transpose(data, (1, 0, 2)).sum(axis=0))


def test_swap_then_map_parity(mesh):
    data = _data()
    got = np.asarray(_source(data, mesh, 4).swap((0,), (0,))
                     .map(lambda v: v * 2.0)._data)
    assert np.array_equal(got, np.transpose(data, (1, 0, 2)) * 2.0)


def test_swap_then_chunk_map_parity(mesh):
    data = _data()
    got = np.asarray(_source(data, mesh, 4).swap((0,), (0,))
                     .chunk((3, 5)).map(lambda blk: blk + 1.0)
                     .unchunk()._data)
    assert np.array_equal(got, np.transpose(data, (1, 0, 2)) + 1.0)


def test_streamed_swap_under_dict_codec(mesh):
    """A lossless-codec source swaps streamed (phase 1 decodes the wire
    slab on device before the transpose) — still bit-identical."""
    data = _data(np.int32)
    s = _source(data, mesh, 4, codec="dict").swap((0,), (0,))
    assert s._stream is not None
    assert np.array_equal(np.asarray(s._data),
                          np.transpose(data, (1, 0, 2)))


def test_lossy_codec_swap_falls_back_to_materialise(mesh):
    """A LOSSY codec refuses the streamed shuffle (phase 1 would decode
    once and a later lossy terminal would quantise AGAIN — drift) — the
    swap silently takes the materialised path and stays correct."""
    data = _data()
    s = _source(data, mesh, 4, codec="bf16").swap((0,), (0,))
    assert s._stream is None              # materialised at record time
    got = np.asarray(s._data)
    assert got.shape == (V0, N, V1)


# ---------------------------------------------------------------------
# the forced-spill path (budget ~ one bucket)
# ---------------------------------------------------------------------

def test_forced_spill_bitexact_and_cleared(mesh, tmp_path):
    data = _data()
    td = str(tmp_path)
    c0 = engine.counters()
    with stream.spill(dir=td, budget=1):
        got = np.asarray(_source(data, mesh, 4).swap((0,), (0,))._data)
    c1 = engine.counters()
    assert np.array_equal(got, np.transpose(data, (1, 0, 2)))
    assert c1["spill_bytes"] > c0["spill_bytes"]
    assert c1["shuffle_bytes"] > c0["shuffle_bytes"]
    assert checkpoint.spill_pending(td)
    checkpoint.spill_clear(td)
    assert not checkpoint.spill_pending(td)
    assert not glob.glob(os.path.join(td, "bolt-spill-*"))


def test_forced_spill_chunk_map_rides_phase_two(mesh, tmp_path):
    """chunk().map() AFTER the swap streams through the spilled
    phase-2 source — the whole chain completes past the budget without
    full materialisation."""
    data = _data()
    with stream.spill(dir=str(tmp_path), budget=1):
        got = np.asarray(_source(data, mesh, 4).swap((0,), (0,))
                         .chunk((3, 5)).map(lambda blk: blk * 3.0)
                         .unchunk()._data)
    assert np.array_equal(got, np.transpose(data, (1, 0, 2)) * 3.0)


def test_spill_without_dir_refuses_pointedly(mesh):
    data = _data()
    with stream.spill(budget=1):          # budget but NO directory
        s = _source(data, mesh, 4).swap((0,), (0,))
        with pytest.raises(RuntimeError, match="spill"):
            s._data


# ---------------------------------------------------------------------
# compile-once and forecast contracts
# ---------------------------------------------------------------------

def test_zero_second_pass_recompiles(mesh):
    data = _data()

    def run():
        return np.asarray(_source(data, mesh, 4).swap((0,), (0,))._data)

    first = run()
    c0 = engine.counters()
    second = run()
    c1 = engine.counters()
    assert c1["misses"] == c0["misses"], "second pass compiled programs"
    assert np.array_equal(first, second)


def test_blt017_forecast_matches_runtime_decision(mesh, tmp_path):
    data = _data()

    def blt017(arr):
        rep = analysis.check(arr)
        ds = [d for d in rep.diagnostics if d.code == "BLT017"]
        assert len(ds) == 1, rep.diagnostics
        return ds[0]

    # resident forecast -> the run spills nothing
    s = _source(data, mesh, 4).swap((0,), (0,))
    d = blt017(s)
    assert d.severity == "info" and "resident" in d.message
    c0 = engine.counters()
    np.asarray(s._data)
    assert engine.counters()["spill_bytes"] == c0["spill_bytes"]

    # spill forecast (same planner, same budget resolution) -> it spills
    with stream.spill(dir=str(tmp_path), budget=1):
        s2 = _source(data, mesh, 4).swap((0,), (0,))
        d2 = blt017(s2)
        assert d2.severity == "info" and "spill" in d2.message
        np.asarray(s2._data)
    assert engine.counters()["spill_bytes"] > c0["spill_bytes"]

    # spill forecast with NO dir -> warning, and the run refuses
    with stream.spill(budget=1):
        s3 = _source(data, mesh, 4).swap((0,), (0,))
        d3 = blt017(s3)
        assert d3.severity == "warning"


def test_shuffle_chaos_raise_absorbed_in_place(mesh):
    data = _data()
    ref = np.transpose(data, (1, 0, 2))
    for seam in ("stream.shuffle", "stream.spill"):
        _chaos.inject(seam, nth=2)
        c0 = engine.counters()
        try:
            with stream.retries(1), stream.spill(budget=None):
                if seam == "stream.spill":
                    import tempfile
                    td = tempfile.mkdtemp(prefix="bolt-swapchaos-")
                    with stream.spill(dir=td, budget=1):
                        got = np.asarray(
                            _source(data, mesh, 4).swap((0,), (0,))._data)
                    checkpoint.spill_clear(td)
                else:
                    got = np.asarray(
                        _source(data, mesh, 4).swap((0,), (0,))._data)
        finally:
            _chaos.clear()
        c1 = engine.counters()
        assert c1["stream_retries"] - c0["stream_retries"] == 1, seam
        assert np.array_equal(got, ref), seam


# ---------------------------------------------------------------------
# the dict codec (satellite: ROADMAP item 5 remainder)
# ---------------------------------------------------------------------

def test_dict_codec_registered():
    assert "dict" in codec_mod.names()
    c = codec_mod.get("dict")
    assert c.lossless and c.sidecar


@pytest.mark.parametrize("dtype", [np.int64, np.int32, np.int8, np.bool_])
def test_dict_codec_roundtrip_bitexact(dtype):
    c = codec_mod.get("dict")
    block = (np.arange(60) % 2 if dtype == np.bool_
             else (np.arange(60) % 7) * 3 - 5).astype(dtype).reshape(12, 5)
    wire, side = c.encode(block, delta_ok=False)
    assert wire.dtype == np.uint8 and wire.shape == block.shape
    assert len(side) == 1 and side[0].shape == (256,)
    assert side[0].dtype == block.dtype
    out = np.asarray(c.decode(wire, side, np.dtype(dtype),
                              delta_ok=False))
    assert np.array_equal(out, block)


def test_dict_codec_refuses_floats_pointedly():
    c = codec_mod.get("dict")
    with pytest.raises(ValueError, match="dictionary"):
        c.wire_dtype(np.float32)
    with pytest.raises(ValueError, match="dictionary"):
        c.encode(np.ones((4, 4), np.float64))


def test_dict_codec_cardinality_contract():
    with pytest.raises(ValueError, match="256"):
        codec_mod.get("dict").encode(np.arange(300, dtype=np.int32))


def test_dict_codec_streamed_sum_and_wire_ratio(mesh):
    """End to end through the uploader pool: int64 slabs ship as uint8
    indices (1/8 the wire bytes) and the decoded sum is exact."""
    data = _data(np.int64)
    c0 = engine.counters()
    got = np.asarray(_source(data, mesh, 4, codec="dict").sum())
    c1 = engine.counters()
    assert np.array_equal(got, data.sum(axis=0))
    raw = c1["codec_bytes_raw"] - c0["codec_bytes_raw"]
    wire = c1["codec_bytes_wire"] - c0["codec_bytes_wire"]
    assert raw == 8 * wire


# ---------------------------------------------------------------------
# the spill-file layer (checkpoint.py)
# ---------------------------------------------------------------------

def test_spill_save_load_roundtrip(tmp_path):
    td, fp = str(tmp_path), ("fp-a", 1)
    ints = ((np.arange(40) % 5) - 2).astype(np.int64).reshape(8, 5)
    nb = checkpoint.spill_save(td, fp, 0, 0, ints, 16)
    assert nb > 0
    out, row0 = checkpoint.spill_load(td, fp, 0, 0)
    assert np.array_equal(out, ints) and out.dtype == ints.dtype
    assert row0 == 16

    floats = _data()[:8, :, 0]            # raw path (no dict for floats)
    checkpoint.spill_save(td, fp, 0, 1, floats, 0)
    out2, _ = checkpoint.spill_load(td, fp, 0, 1)
    assert np.array_equal(out2, floats)

    wide = np.arange(300, dtype=np.int32)  # > 256 uniques: raw fallback
    checkpoint.spill_save(td, fp, 1, 0, wide, 0)
    out3, _ = checkpoint.spill_load(td, fp, 1, 0)
    assert np.array_equal(out3, wide)


def test_spill_manifest_and_fingerprint_isolation(tmp_path):
    td, fp = str(tmp_path), ("fp-a",)
    assert checkpoint.spill_manifest(td, fp) == set()
    checkpoint.spill_slab_done(td, fp, 0)
    checkpoint.spill_slab_done(td, fp, 3)
    assert checkpoint.spill_manifest(td, fp) == {0, 3}
    # a different fingerprint hashes to a different directory
    assert checkpoint.spill_manifest(td, ("fp-b",)) == set()
    assert checkpoint.spill_pending(td)
    checkpoint.spill_clear(td)
    assert not checkpoint.spill_pending(td)


def test_spill_load_missing_bucket_refuses_pointedly(tmp_path):
    with pytest.raises(checkpoint.CheckpointCorruptError, match="spill"):
        checkpoint.spill_load(str(tmp_path), ("fp",), 0, 0)
