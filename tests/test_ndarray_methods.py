"""The inherited-ndarray method surface (VERDICT r2 missing-2).

The local backend gets ``sort``/``ravel``/``repeat``/``diagonal``/
``trace``/``nonzero``/``searchsorted``/``real``/``imag``/``conj`` (and
in-place ``__setitem__``) for free from ``numpy.ndarray``; the TPU
backend implements the same surface natively, plus the shared functional
``set``.  This suite ENUMERATES the methods and asserts
same-result-or-same-error on both backends (reference:
``bolt/local/array.py`` — the ndarray subclass; symbol cite, SURVEY §0).
"""

import numpy as np
import pytest

import bolt_tpu as bolt


def _f():
    return np.random.RandomState(7).randn(8, 4, 5)


def _i():
    return np.random.RandomState(8).randint(-3, 4, size=(8, 4, 5))


def _i8():
    return np.random.RandomState(9).randint(0, 3, size=(8, 4, 5)).astype(np.int8)


def _c():
    rs = np.random.RandomState(10)
    return (rs.randn(8, 4, 5) + 1j * rs.randn(8, 4, 5))


def _s():                           # sorted 1-d, for searchsorted
    return np.sort(np.random.RandomState(11).randn(24))


def _sort(axis=-1, kind=None):
    def fn(b):
        assert b.sort(axis=axis, kind=kind) is None   # ndarray convention
        return b
    return fn


# (name, array builder, method call) — every entry runs on BOTH backends
# and must produce the same value/shape/dtype or raise the same error
CASES = [
    ("sort", _f, _sort()),
    ("sort-axis0", _f, _sort(axis=0)),
    ("sort-stable", _i, _sort(kind="stable")),
    ("sort-bad-kind", _f, _sort(kind="bogus")),
    ("sort-axis-none", _f, _sort(axis=None)),
    ("sort-axis-oob", _f, _sort(axis=7)),
    ("ravel", _f, lambda b: b.ravel()),
    ("ravel-F", _f, lambda b: b.ravel("F")),
    ("ravel-A", _f, lambda b: b.ravel("A")),
    ("flatten", _i, lambda b: b.flatten()),
    ("flatten-F", _i, lambda b: b.flatten("F")),
    ("repeat-scalar", _f, lambda b: b.repeat(3)),
    ("repeat-axis", _f, lambda b: b.repeat(2, axis=1)),
    ("repeat-axis-neg", _f, lambda b: b.repeat(2, axis=-1)),
    ("repeat-array", _f, lambda b: b.repeat([2, 0, 1, 3], axis=1)),
    ("repeat-size1-array", _f, lambda b: b.repeat([2], axis=1)),
    ("repeat-float-truncates", _i, lambda b: b.repeat(2.7, axis=0)),
    ("repeat-negative", _f, lambda b: b.repeat(-1)),
    ("repeat-len-mismatch", _f, lambda b: b.repeat([1, 2], axis=0)),
    ("repeat-2d", _f, lambda b: b.repeat(np.ones((1, 6), int), axis=0)),
    ("diagonal", _f, lambda b: b.diagonal()),
    ("diagonal-offset", _f, lambda b: b.diagonal(1)),
    ("diagonal-offset-neg", _f, lambda b: b.diagonal(-2)),
    ("diagonal-value-axes", _f, lambda b: b.diagonal(0, 1, 2)),
    ("diagonal-kv-axes", _f, lambda b: b.diagonal(0, 0, 2)),
    ("diagonal-same-axis", _f, lambda b: b.diagonal(0, 1, 1)),
    ("trace", _f, lambda b: b.trace()),
    ("trace-offset", _f, lambda b: b.trace(1)),
    ("trace-int8-promotes", _i8, lambda b: b.trace()),
    ("trace-dtype-arg", _i, lambda b: b.trace(dtype=np.float64)),
    ("nonzero-int", _i, lambda b: b.nonzero()),
    ("nonzero-float", _f, lambda b: b.nonzero()),
    ("searchsorted-scalar", _s, lambda b: b.searchsorted(0.0)),
    ("searchsorted-array", _s,
     lambda b: b.searchsorted(np.linspace(-2, 2, 7))),
    ("searchsorted-right", _s,
     lambda b: b.searchsorted(np.linspace(-2, 2, 7), side="right")),
    ("searchsorted-2d-v", _s,
     lambda b: b.searchsorted(np.zeros((2, 3)))),
    ("searchsorted-bad-side", _s, lambda b: b.searchsorted(0.0, side="up")),
    ("searchsorted-2d-self", _f, lambda b: b.searchsorted(0.0)),
    ("real-float", _f, lambda b: b.real),
    ("imag-float", _f, lambda b: b.imag),
    ("real-complex", _c, lambda b: b.real),
    ("imag-complex", _c, lambda b: b.imag),
    ("conj-complex", _c, lambda b: b.conj()),
    ("conjugate-float", _f, lambda b: b.conjugate()),
    ("conj-int", _i, lambda b: b.conj()),
    ("set-slice", _f, lambda b: b.set(np.s_[1:3], 0.5)),
    ("set-int", _f, lambda b: b.set(2, 7.0)),
    ("set-neg-int", _f, lambda b: b.set(-1, 7.0)),
    ("set-ellipsis", _f, lambda b: b.set(np.s_[..., 2], -1.0)),
    ("set-list", _f, lambda b: b.set(([4, 0, 2],), 9.0)),
    ("set-array-value", _f,
     lambda b: b.set(np.s_[1:3, 2], np.arange(5.0))),
    ("set-cast-truncates", _i, lambda b: b.set(0, 2.9)),
    ("set-bool-mask", _f,
     lambda b: b.set((np.arange(8) % 2 == 0,), 0.0)),
    ("set-orthogonal", _f,
     lambda b: b.set(([0, 2], slice(None), [1, 3]),
                     np.arange(2 * 4 * 2.0).reshape(2, 4, 2))),
    ("set-extra-leading-1s", _f,
     lambda b: b.set(1, np.ones((1, 1, 4, 5)))),
    ("set-bad-broadcast", _f, lambda b: b.set(1, np.zeros((3, 5)))),
    ("set-oob", _f, lambda b: b.set(99, 0.0)),
    ("set-scalar-after-advanced", _f,
     lambda b: b.set(([0, 1], 2), np.arange(5.0))),
    ("set-advanced-after-scalar", _f,
     lambda b: b.set((2, [1, 3]), np.arange(5.0) + 1)),
    ("item", _f, lambda b: b.item(3)),
    ("item-neg", _f, lambda b: b.item(-1)),
    ("item-multi", _f, lambda b: b.item(1, 2, 3)),
    ("item-tuple", _f, lambda b: b.item((1, 2, 3))),
    ("item-oob", _f, lambda b: b.item(10 ** 6)),
    ("item-not-size1", _f, lambda b: b.item()),
    ("tolist", _i, lambda b: b.tolist()),
]


def _run(fn, b):
    try:
        return ("ok", fn(b))
    except Exception as exc:                      # noqa: BLE001
        return ("err", type(exc))


def _assert_same(name, lo, tp):
    if isinstance(lo, tuple):
        assert isinstance(tp, tuple) and len(lo) == len(tp), name
        for a, b in zip(lo, tp):
            _assert_same(name, a, b)
        return
    if isinstance(lo, list) or lo is None or np.isscalar(lo):
        assert np.array_equal(np.asarray(lo), np.asarray(tp)), name
        return
    an, bn = np.asarray(lo), np.asarray(tp)
    assert an.shape == bn.shape, (name, an.shape, bn.shape)
    assert an.dtype == bn.dtype, (name, an.dtype, bn.dtype)
    assert np.allclose(an, bn, equal_nan=True), name


@pytest.mark.parametrize("layout", ["keys1d", "keys2d"])
@pytest.mark.parametrize("name,make,fn", CASES, ids=[c[0] for c in CASES])
def test_method_parity(request, layout, name, make, fn):
    # every case runs on a split=1 array over the 1-d mesh AND a
    # split=2 array genuinely sharded over both axes of the 2-d mesh —
    # the method surface must be split-agnostic
    if layout == "keys1d":
        m, axis = request.getfixturevalue("mesh"), (0,)
    else:
        m, axis = request.getfixturevalue("mesh2d"), (0, 1)
    x = make()
    if x.ndim < 2 and layout == "keys2d":
        pytest.skip("1-d inputs have a single key axis")
    lo_status, lo = _run(fn, bolt.array(x.copy()))
    tp_status, tp = _run(fn, bolt.array(x.copy(), m, axis=axis))
    assert lo_status == tp_status, (name, lo, tp)
    if lo_status == "err":
        # same-error: identical class, or one a subclass of the other
        # (e.g. np.AxisError IS a ValueError)
        assert lo is tp or issubclass(tp, lo) or issubclass(lo, tp), \
            (name, lo, tp)
    else:
        _assert_same(name, lo, tp)


def test_sort_matches_numpy(mesh):
    x = _f()
    b = bolt.array(x, mesh)
    assert b.sort(axis=0) is None
    assert np.array_equal(b.toarray(), np.sort(x, axis=0))
    # sorting a deferred chain materialises the fused chain, sorted
    m = bolt.array(x, mesh).map(lambda v: v * -1)
    m.sort()
    assert np.allclose(m.toarray(), np.sort(-x, axis=-1))


def test_set_does_not_mutate(mesh):
    x = _f()
    for b in (bolt.array(x), bolt.array(x, mesh)):
        out = b.set(0, 0.0)
        assert np.allclose(b.toarray(), x), b.mode        # original intact
        assert np.allclose(np.asarray(out.toarray())[0], 0.0)
        assert out.shape == x.shape
    t = bolt.array(x, mesh).set(0, 0.0)
    assert t.split == 1


def test_setitem_tpu_raises_pointing_to_set(mesh):
    b = bolt.array(_f(), mesh)
    with pytest.raises(TypeError, match="set"):
        b[0] = 1.0


def test_setitem_local_orthogonal_matches_set(mesh):
    # >=2 advanced indices: in-place assignment covers the ORTHOGONAL
    # region, same as set() and __getitem__ on both backends
    x = _f()
    lo = bolt.array(x.copy())
    lo[[0, 2], :, [1, 3]] = -5.0
    via_set = bolt.array(x).set(([0, 2], slice(None), [1, 3]), -5.0)
    assert np.allclose(np.asarray(lo), np.asarray(via_set.toarray()))
    tpu_set = bolt.array(x, mesh).set(([0, 2], slice(None), [1, 3]), -5.0)
    assert np.allclose(np.asarray(lo), tpu_set.toarray())
    # the region is the cross product: exactly those 2*4*2 entries changed
    changed = np.asarray(lo) != x
    assert changed.sum() == 2 * 4 * 2
    # single advanced index keeps numpy's (identical) semantics
    lo2 = bolt.array(x.copy())
    lo2[[1, 3]] = 0.0
    assert np.allclose(np.asarray(lo2)[[1, 3]], 0.0)


def test_set_getitem_roundtrip(mesh):
    # the region set() assigns is the region __getitem__ reads: writing a
    # value shaped exactly like b[idx] always succeeds — including
    # scalar-mixed-with-advanced indices, where keeping the scalar axis
    # as a length-1 dim would reject it (r3 review finding)
    x = _f()
    for idx in [np.s_[1:3], (2,), ([0, 1], 2), (2, [1, 3]),
                ([0, 2], slice(None), [1, 3]), (slice(None), 1, [0, 4]),
                np.s_[..., 2], ([4, 0], 1, 2)]:
        for b in (bolt.array(x), bolt.array(x, mesh)):
            region = np.asarray(b[idx].toarray())
            out = b.set(idx, region * 0 - 1.0)
            changed = np.asarray(out.toarray()) != x
            assert changed.sum() == region.size, (b.mode, idx)
            # and the round-trip restores the original exactly
            back = out.set(idx, region)
            assert np.allclose(back.toarray(), x), (b.mode, idx)


def test_item_fetches_one_element_not_the_array(mesh, monkeypatch):
    # item() gathers ONE element on device; the full array never moves
    # (r3 review finding: it used to route through toarray())
    x = _f()
    b = bolt.array(x, mesh)
    called = []
    monkeypatch.setattr(type(b), "toarray",
                        lambda self: called.append(1) or x)
    assert abs(b.item(3) - x.reshape(-1)[3]) < 1e-12
    assert abs(b.item(1, 2, 3) - x[1, 2, 3]) < 1e-12
    assert not called
    # size-1 no-arg form
    one = bolt.array(np.full((1, 1), 42.0), mesh)
    assert one.item() == 42.0


def test_nonzero_two_phase_and_values(mesh):
    x = np.zeros((5, 4))
    x[1, 2] = 3.0
    x[4, 0] = -1.0
    t = bolt.array(x, mesh).nonzero()
    expect = x.nonzero()
    assert len(t) == 2
    for a, b in zip(t, expect):
        assert a.dtype == np.int64
        assert np.array_equal(a, b)
    # a deferred chain fuses into both phases
    m = bolt.array(x, mesh).map(lambda v: v * 0 + (v > 2))
    got = m.nonzero()
    want = (x > 2).nonzero()
    for a, b in zip(got, want):
        assert np.array_equal(a, b)


def test_cross_mesh_operands_rejected(mesh):
    # bolt-array operands from a foreign mesh get the loud binary-op
    # rejection, not a deep GSPMD error (the _check_mesh contract)
    import jax
    x = _f()
    b = bolt.array(x, mesh)
    other_mesh = jax.make_mesh((4, 2), ("a", "b"))
    foreign = bolt.array(np.zeros((4, 5)), other_mesh)
    with pytest.raises(ValueError, match="different meshes"):
        b.set(np.s_[0:1, 0:4], foreign)
    s = bolt.array(np.sort(x.ravel()), mesh)
    with pytest.raises(ValueError, match="different meshes"):
        s.searchsorted(bolt.array(np.zeros(3), other_mesh))


def test_searchsorted_sorter(mesh):
    x = np.random.RandomState(12).randn(16)
    order = np.argsort(x)
    v = np.linspace(-1, 1, 5)
    for b in (bolt.array(x), bolt.array(x, mesh)):
        got = b.searchsorted(v, sorter=order)
        assert np.array_equal(np.asarray(got), np.searchsorted(x, v, sorter=order)), b.mode
    with pytest.raises(ValueError):
        bolt.array(x, mesh).searchsorted(0.0, sorter=np.arange(3))


def test_repeat_split_and_chain(mesh):
    x = _f()
    # axis=None flattens: flat key axis (filter's convention)
    t = bolt.array(x, mesh).repeat(2)
    assert t.split == 1 and t.shape == (x.size * 2,)
    # key-axis repeat keeps the split
    t = bolt.array(x, mesh).repeat(3, axis=0)
    assert t.split == 1 and t.shape == (24, 4, 5)
    # deferred chain fuses in
    m = bolt.array(x, mesh).map(lambda v: v + 1).repeat(2, axis=2)
    assert np.allclose(m.toarray(), (x + 1).repeat(2, axis=2))


def test_ravel_and_diagonal_splits(mesh):
    x = _f()
    b = bolt.array(x, mesh, axis=(0, 1))
    r = b.ravel()
    assert r.split == 1 and np.allclose(r.toarray(), x.ravel())
    d = b.diagonal(0, 0, 2)          # one key + one value axis removed
    assert d.split == 1
    assert np.allclose(d.toarray(), x.diagonal(0, 0, 2))
    tr = b.trace(0, 0, 1)            # both key axes reduced
    assert tr.split == 0
    assert np.allclose(tr.toarray(), x.trace(0, 0, 1))
