"""TPU-backend chunking: plans, padding, per-chunk map, axis exchange
(reference area: ``test/test_spark_chunking.py``, SURVEY §4; BASELINE
config 5)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.utils import allclose, prod


def _x(shape=(8, 6, 4)):
    rs = np.random.RandomState(9)
    return rs.randn(*shape)


def test_chunk_is_a_view(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    c = b.chunk(size=(2,), axis=(0,))
    assert c.plan == (2, 4)
    assert c.padding == (0, 0)
    assert c.kshape == (8,)
    assert c.vshape == (6, 4)
    assert c.grid == (3, 1)
    assert c.uniform
    # unchunk is a no-op unwrap
    assert c.unchunk() is b


def test_chunk_mb_budget(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    # 64 bytes budget: 6*4*8B = 192B blocks must be split down
    c = b.chunk(size=str(64 / 1e6))
    block_bytes = prod(c.plan) * b.dtype.itemsize
    assert block_bytes <= 64 or all(p == 1 for p in c.plan)
    # default budget is huge relative to this array: one chunk
    assert bolt.array(x, mesh).chunk().plan == (6, 4)


def test_chunk_validation(mesh):
    b = bolt.array(_x(), mesh)
    with pytest.raises(ValueError):
        b.chunk(size=(2,), axis=(5,))
    with pytest.raises(ValueError):
        b.chunk(size=(0,), axis=(0,))
    with pytest.raises(ValueError):
        b.chunk(size=(2,), axis=(0,), padding=2)  # padding >= chunk


def test_map_uniform(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    c = b.chunk(size=(3, 2), axis=(0, 1))
    out = c.map(lambda blk: blk * 2)
    assert out.plan == (3, 2)
    assert allclose(out.unchunk().toarray(), x * 2)


def test_map_uniform_shape_changing(mesh):
    # per-chunk gram matrix: (3, 4) block -> (4, 4); rank preserved
    # (the shape-changing regime BASELINE config 5's per-chunk SVD needs)
    x = _x((4, 6, 4))
    b = bolt.array(x, mesh)
    c = b.chunk(size=(3,), axis=(0,))
    out = c.map(lambda blk: blk.T @ blk)
    assert out.plan == (4, 4)
    assert out.unchunk().shape == (4, 8, 4)
    expected = np.concatenate(
        [x[k, i * 3:(i + 1) * 3].T @ x[k, i * 3:(i + 1) * 3]
         for k in range(4) for i in range(2)], axis=0).reshape(4, 8, 4)
    assert allclose(out.unchunk().toarray(), expected)


def test_map_ragged(mesh):
    x = _x((8, 5, 4))
    b = bolt.array(x, mesh)
    c = b.chunk(size=(2,), axis=(0,))  # 5 = 2+2+1 ragged
    assert not c.uniform
    out = c.map(lambda blk: blk * 2 + 1)
    assert allclose(out.unchunk().toarray(), x * 2 + 1)


def test_map_padding_trim(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    c = b.chunk(size=(2,), axis=(0,), padding=1)
    assert c.padding == (1, 0)
    # elementwise func: halo is trimmed away, result identical to unpadded
    out = c.map(lambda blk: blk * 3)
    assert allclose(out.unchunk().toarray(), x * 3)


def test_map_padding_halo_visible(mesh):
    # a halo-dependent, shape-preserving func: per-block max broadcast.
    # interior blocks see neighbours through the halo.
    x = np.zeros((1, 6))
    x[0, 3] = 10.0  # lives in block 1 (cols 2:4)
    b = bolt.array(x, mesh)
    c = b.chunk(size=(2,), axis=(0,), padding=1)
    out = c.map(lambda blk: blk * 0 + blk.max()).unchunk().toarray()
    # block 0 covers cols 0:2, halo reaches col 2 (value 0) — but block 1's
    # halo spans cols 1:5 so sees the 10; block 2 (cols 4:6) halo sees col 3
    assert out[0, 2] == 10.0 and out[0, 3] == 10.0  # block 1 core
    assert out[0, 4] == 10.0  # block 2 saw the 10 through its halo
    assert out[0, 0] == 0.0   # block 0 never saw it


def test_map_padding_requires_shape_preserving(mesh):
    b = bolt.array(_x(), mesh)
    c = b.chunk(size=(2,), axis=(0,), padding=1)
    with pytest.raises(ValueError):
        c.map(lambda blk: blk[:1])


def test_per_chunk_svd_config5(mesh):
    # BASELINE config 5: tall-skinny PCA — per-chunk SVD of (N, features)
    import jax.numpy as jnp
    x = _x((4, 20, 3))
    b = bolt.array(x, mesh)
    c = b.chunk(size=(10,), axis=(0,))
    # singular values per (10, 3) chunk -> rank-preserving (1, 3) block
    out = c.map(lambda blk: jnp.linalg.svd(blk, compute_uv=False)[None, :])
    assert out.unchunk().shape == (4, 2, 3)
    expected = np.stack([
        np.stack([np.linalg.svd(x[k, i * 10:(i + 1) * 10], compute_uv=False)
                  for i in range(2)]) for k in range(4)])
    assert allclose(out.unchunk().toarray(), expected)


def test_map_padding_per_record(mesh):
    # the padded/ragged path must apply func per RECORD (vmapped over key
    # axes), like the uniform path and the reference's per-(key, chunk)
    # records — a block-max must not leak across keys
    x = np.zeros((2, 6))
    x[0, 3] = 10.0  # only record 0 contains the spike
    b = bolt.array(x, mesh)
    c = b.chunk(size=(2,), axis=(0,), padding=1)
    out = c.map(lambda blk: blk * 0 + blk.max()).unchunk().toarray()
    assert out[0].max() == 10.0
    assert out[1].max() == 0.0  # record 1 never saw record 0's spike


def test_map_general_trace_cost_independent_of_grid(mesh):
    # the general path groups blocks into ≤4 static categories per chunked
    # axis: func trace count must NOT grow with the number of chunks
    x = _x((2, 257, 3))  # 257 = 128 chunks of 2 + ragged tail of 1
    b = bolt.array(x, mesh)
    c = b.chunk(size=(2,), axis=(0,), padding=1)
    calls = []

    def f(blk):
        calls.append(blk.shape)
        return blk * 2.0

    out = c.map(f)
    assert allclose(out.unchunk().toarray(), x * 2)
    assert len(calls) <= 4


def test_map_ragged_padded_categories(mesh):
    # exercise every clamp category: short tail (tail < padding is
    # impossible since pad < chunk, but tail < chunk clips the
    # penultimate block's upper halo), two-chunk and one-chunk grids
    for n, size, p in [(9, 4, 3), (8, 4, 3), (5, 4, 3), (4, 4, 3),
                       (13, 4, 2), (12, 4, 1), (7, 3, 2), (3, 3, 2)]:
        x = _x((2, n))
        b = bolt.array(x, mesh)
        c = b.chunk(size=(size,), axis=(0,), padding=p)
        # halo-dependent shape-preserving func: running sum within block
        out = c.map(lambda blk: blk * 0 + blk.sum()).unchunk().toarray()
        # oracle: per record, per block, sum over the clamped padded span
        g = -(-n // size)
        exp = np.zeros_like(x)
        for k in range(2):
            for i in range(g):
                c0, c1 = i * size, min(n, (i + 1) * size)
                p0, p1 = max(0, c0 - p), min(n, c1 + p)
                exp[k, c0:c1] = x[k, p0:p1].sum()
        assert allclose(out, exp), (n, size, p)


def test_keys_to_values(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))  # keys (8, 6), values (4,)
    c = b.chunk(size=(2,), axis=(0,))
    k2v = c.keys_to_values((1,))
    # key axis 1 (size 6) moved to the front of the values
    assert k2v.kshape == (8,)
    assert k2v.vshape == (6, 4)
    assert k2v.plan == (6, 2)
    assert allclose(k2v.unchunk().toarray(), x)
    # with an explicit chunk size for the moved axis
    k2v = c.keys_to_values((1,), size=(3,))
    assert k2v.plan == (3, 2)


def test_values_to_keys(mesh):
    x = _x()
    b = bolt.array(x, mesh)  # keys (8,), values (6, 4)
    c = b.chunk(size=(2, 2), axis=(0, 1))
    v2k = c.values_to_keys((0,))
    # value axis 0 (size 6) appended to the keys
    assert v2k.kshape == (8, 6)
    assert v2k.vshape == (4,)
    assert v2k.plan == (2,)
    assert allclose(v2k.unchunk().toarray(), np.transpose(x, (0, 1, 2)))
    with pytest.raises(ValueError):
        c.values_to_keys((9,))


def test_keys_to_values_unsorted_order(mesh):
    # axes move in the order GIVEN; the plan must track that order
    x = _x((4, 2, 3, 5))
    b = bolt.array(x, mesh, axis=(0, 1, 2))  # keys (4,2,3), values (5,)
    c = b.chunk(size=(5,), axis=(0,))
    k2v = c.keys_to_values((2, 1))
    assert k2v.kshape == (4,)
    assert k2v.vshape == (3, 2, 5)
    assert k2v.plan == (3, 2, 5)
    assert k2v.uniform
    assert allclose(k2v.unchunk().toarray(), np.transpose(x, (0, 2, 1, 3)))


def test_keys_to_values_all_keys(mesh):
    # moving every key axis is legal on the chunk primitives (split=0
    # intermediate); values_to_keys restores keys
    x = _x((4, 6, 5))
    b = bolt.array(x, mesh, axis=(0,))
    c = b.chunk(size=(3,), axis=(0,))
    k2v = c.keys_to_values((0,))
    assert k2v.split == 0
    assert k2v.vshape == (4, 6, 5)
    restored = k2v.values_to_keys((0,))
    assert restored.split == 1
    assert allclose(restored.unchunk().toarray(), x)
    with pytest.raises(ValueError):
        c.keys_to_values((3,))


def test_keys_reshape_trailing_one(mesh):
    # the keys view states the boundary explicitly: a trailing size-1 key
    # axis stays a KEY axis
    x = _x((4, 3))
    b = bolt.array(np.ones((4, 3)), mesh)
    out = b.keys.reshape(4, 1)
    assert out.shape == (4, 1, 3)
    assert out.split == 2
    out = b.values.reshape(3, 1)
    assert out.shape == (4, 3, 1)
    assert out.split == 1


def test_swap_equivalence_via_chunk(mesh):
    # swap == chunk → keys_to_values → values_to_keys → unchunk
    # (the reference's own decomposition, SURVEY §3.3)
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    direct = b.swap((0,), (0,))
    via_chunk = b.chunk().keys_to_values((0,)).values_to_keys((1,)).unchunk()
    assert direct.shape == via_chunk.shape
    assert direct.split == via_chunk.split
    assert allclose(direct.toarray(), via_chunk.toarray())


def test_repr(mesh):
    c = bolt.array(_x(), mesh).chunk(size=(2,), axis=(0,))
    r = repr(c)
    assert "plan" in r and "grid" in r and "padding" in r


def test_chunk_map_value_shape_and_dtype_hints(mesh):
    # reference-parity hints: value_shape validates, dtype casts
    rs = np.random.RandomState(80)
    x = rs.randn(8, 6, 4)
    c = bolt.array(x, mesh).chunk(size=(3,), axis=(0,))
    out = c.map(lambda blk: blk * 2, dtype=np.float32).unchunk()
    assert out.dtype == np.float32
    assert np.allclose(out.toarray(), (x * 2).astype(np.float32))
    with pytest.raises(ValueError):
        c.map(lambda blk: blk * 2, value_shape=(9, 9))
    # a correct hint passes
    ok = c.map(lambda blk: blk * 2, value_shape=(3, 4)).unchunk()
    assert np.allclose(ok.toarray(), x * 2)
