"""Fused multi-terminal statistics (ISSUE 7): ``bolt.compute`` /
``a.stats("sum", ...)`` parity and accounting.

Parity is the load-bearing half: every FUSED result must be
bit-identical to its STANDALONE terminal (the acceptance contract) —
compared across local, materialised, chunked and streamed arrays,
including uneven tails and filter-fused predicates.  Accounting rides
along: a fused group of N terminals costs exactly ONE engine compile
and ONE dispatch (N−1 dispatches saved), ``ptp`` rides the fused
min/max pair, donation fires once for the whole group, and the checker
forecasts the fusion (BLT009) with zero compiles.  The opt-in
reduced-precision accumulation path is parity-locked: default exact,
"f32" bit-identical for f32 pipelines, "bf16" within the documented
~1e-2 relative envelope.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bolt_tpu as bolt
from bolt_tpu import analysis, engine
from bolt_tpu import _precision

STATS = ("sum", "mean", "var", "std", "min", "max", "prod")


def _x(shape=(16, 6, 4), seed=0):
    return np.random.RandomState(seed).randn(*shape)


def _bits(a, b):
    """Bit-compare two results (NaNs equal)."""
    a, b = np.asarray(a), np.asarray(b)
    return a.shape == b.shape and a.dtype == b.dtype \
        and np.array_equal(a, b, equal_nan=np.issubdtype(
            a.dtype, np.floating))


# ---------------------------------------------------------------------
# laziness: validation eager, dispatch deferred, reads transparent
# ---------------------------------------------------------------------

def test_stat_terminal_is_lazy_then_transparent(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    s = b.map(lambda v: v * 3).sum()
    assert s._spending is not None            # nothing dispatched yet
    assert s.shape == (6, 4)                  # metadata known abstractly
    assert s.dtype == np.float64
    assert "lazy sum() terminal" in repr(s)
    assert np.allclose(np.asarray(s.toarray()), (x * 3).sum(axis=0))
    assert s._spending is None                # the read resolved it


def test_invalid_axis_still_raises_eagerly(mesh):
    b = bolt.array(_x(), mesh)
    with pytest.raises(ValueError):
        b.sum(axis=(9,))


def test_zero_size_extrema_raise_at_call(mesh):
    b = bolt.array(np.zeros((0, 4)), mesh)
    with pytest.raises(ValueError):
        b.min()


# ---------------------------------------------------------------------
# fused vs standalone parity: materialised arrays
# ---------------------------------------------------------------------

@pytest.mark.parametrize("name", STATS)
def test_fused_bit_identical_to_standalone(mesh, name):
    x = np.abs(_x(seed=1)) * 0.25 + 0.5       # prod-safe magnitudes
    f = lambda v: jnp.sqrt(v) + 1.0           # noqa: E731

    def standalone():
        return getattr(bolt.array(x, mesh).map(f), name)()

    want = np.asarray(standalone().toarray())
    m = bolt.array(x, mesh).map(f)
    handles = {n: getattr(m, n)() for n in STATS}
    bolt.compute(*handles.values())
    assert _bits(handles[name].toarray(), want)


def test_fused_group_costs_one_compile_one_dispatch(mesh):
    # geometry UNIQUE to this test so every engine key is fresh
    x = _x(shape=(12, 5, 3), seed=2)

    def add7(v):
        return v + 7.0

    m = bolt.array(x, mesh).map(add7)
    hs = [m.sum(), m.var(), m.min(), m.max()]
    c0 = engine.counters()
    bolt.compute(*hs)
    c1 = engine.counters()
    d = {k: c1[k] - c0[k] for k in c1}
    # ONE compile + ONE dispatch for N=4 terminals: N-1 = 3 saved
    assert d["misses"] == 1 and d["aot_compiles"] == 1
    assert d["dispatches"] == 1
    assert d["fused_stat_groups"] == 1
    assert d["fused_stat_terminals"] == 4
    # a second identical group hits the cache: zero new compiles
    m2 = bolt.array(x, mesh).map(add7)
    c2 = engine.counters()
    bolt.compute(m2.sum(), m2.var(), m2.min(), m2.max())
    c3 = engine.counters()
    assert c3["misses"] == c2["misses"]
    assert c3["aot_compiles"] == c2["aot_compiles"]
    assert c3["dispatches"] - c2["dispatches"] == 1


def test_read_of_any_member_resolves_whole_group(mesh):
    x = _x(seed=3)
    m = bolt.array(x, mesh).map(lambda v: v - 2)
    s, v = m.sum(), m.var()
    c0 = engine.counters()
    got = np.asarray(s.toarray())             # auto-fuses the siblings
    c1 = engine.counters()
    assert c1["dispatches"] - c0["dispatches"] == 1
    assert np.allclose(got, (x - 2).sum(axis=0))
    assert v._spending.result is not None     # resolved in the same pass
    assert np.allclose(np.asarray(v.toarray()), (x - 2).var(axis=0))


def test_mixed_sources_fall_back_per_group(mesh):
    x, y = _x(seed=4), _x(seed=5)
    ma = bolt.array(x, mesh).map(lambda v: v + 1)
    mb = bolt.array(y, mesh).map(lambda v: v + 1)
    c0 = engine.counters()
    s1, s2, v1 = bolt.compute(ma.sum(), mb.sum(), ma.var())
    c1 = engine.counters()
    # two groups: (ma.sum, ma.var) fused, mb.sum standalone
    assert c1["dispatches"] - c0["dispatches"] == 2
    assert np.allclose(np.asarray(s1.toarray()), (x + 1).sum(axis=0))
    assert np.allclose(np.asarray(s2.toarray()), (y + 1).sum(axis=0))
    assert np.allclose(np.asarray(v1.toarray()), (x + 1).var(axis=0))


def test_compute_passes_through_concrete_and_local():
    x = _x()
    lo = bolt.array(x)                        # local oracle
    out = bolt.compute(lo.sum(axis=0), 3.5)
    assert np.allclose(np.asarray(out[0]), x.sum(axis=0))
    assert out[1] == 3.5
    with pytest.raises(TypeError):
        bolt.compute()


def test_axes_keepdims_ddof_specs_fuse(mesh):
    x = _x(seed=6)
    m = bolt.array(x, mesh).map(lambda v: v * 2)
    a, b, c = bolt.compute(m.sum(axis=(0,), keepdims=True),
                           m.var(ddof=1), m.mean(axis=(0, 1)))
    assert _bits(a.toarray(),
                 bolt.array(x, mesh).map(lambda v: v * 2)
                 .sum(axis=(0,), keepdims=True).toarray())
    assert _bits(b.toarray(),
                 bolt.array(x, mesh).map(lambda v: v * 2)
                 .var(ddof=1).toarray())
    assert _bits(c.toarray(),
                 bolt.array(x, mesh).map(lambda v: v * 2)
                 .mean(axis=(0, 1)).toarray())


# ---------------------------------------------------------------------
# ptp rides the fused min/max pair
# ---------------------------------------------------------------------

def test_ptp_routes_through_min_max_pair(mesh):
    from bolt_tpu.tpu import array as array_mod
    x = _x(shape=(10, 7, 3), seed=7)
    b = bolt.array(x, mesh)

    def ptp_keys():
        # ("stat", "ptp", ...) entries (other paths — e.g. a resolved
        # filter's eager ptp — may legitimately own one; THIS lazy ptp
        # must not add any)
        return sum(1 for k in array_mod._JIT_CACHE
                   if isinstance(k, tuple) and len(k) > 1
                   and k[0] == "stat" and k[1] == "ptp")

    n0 = ptp_keys()
    got = np.asarray(b.ptp().toarray())
    assert np.allclose(got, np.ptp(x, axis=0))
    # one fewer program key: ptp shares the multi-stat pair program
    # instead of adding a ("stat", "ptp", ...) entry
    assert ptp_keys() == n0
    assert any(k[0] == "multi-stat" for k in array_mod._JIT_CACHE
               if isinstance(k, tuple))
    # compute(ptp, min, max) dedups to the same two extrema slots
    b2 = bolt.array(x, mesh)
    p, mn, mx = bolt.compute(b2.ptp(), b2.min(), b2.max())
    assert _bits(p.toarray(), np.asarray(mx.toarray())
                 - np.asarray(mn.toarray()))


def test_ptp_axis_variants_match_numpy(mesh):
    x = _x(seed=8)
    b = bolt.array(x, mesh)
    assert np.allclose(np.asarray(b.ptp(axis=(0, 1, 2)).toarray()),
                       np.ptp(x))
    assert np.allclose(np.asarray(b.ptp(axis=(1,)).toarray()),
                       np.ptp(x, axis=1))


# ---------------------------------------------------------------------
# filter-fused predicates
# ---------------------------------------------------------------------

PRED = lambda v: v.sum() > 0                  # noqa: E731


def _keep(x):
    return x[[v.sum() > 0 for v in x]]


@pytest.mark.parametrize("name", ["sum", "mean", "var", "std", "prod"])
def test_filtered_fused_bit_identical_to_standalone(mesh, name):
    x = _x(seed=9) * 0.5
    keep = _keep(x)
    want = np.asarray(
        getattr(bolt.array(x, mesh).filter(PRED), name)().toarray())
    f = bolt.array(x, mesh).filter(PRED)
    hs = {n: getattr(f, n)() for n in ("sum", "mean", "var", "std",
                                       "prod")}
    c0 = engine.counters()
    bolt.compute(*hs.values())
    c1 = engine.counters()
    assert c1["dispatches"] - c0["dispatches"] == 1   # one masked pass
    assert _bits(hs[name].toarray(), want)
    ref = getattr(keep, name)(axis=0)
    assert np.allclose(np.asarray(hs[name].toarray()), ref, atol=1e-10)


def test_filtered_min_max_stay_eager_with_error_contract(mesh):
    x = _x(seed=10)
    b = bolt.array(x, mesh)
    nothing = lambda v: v.sum() > 1e9         # noqa: E731
    with pytest.raises(ValueError, match="zero-size"):
        b.filter(nothing).max()               # raises AT CALL, as ever
    got = b.filter(PRED).min()                # eager: already concrete
    assert got._spending is None
    assert np.allclose(np.asarray(got.toarray()), _keep(x).min(axis=0))


# ---------------------------------------------------------------------
# chunked views delegate through the same lazy terminals
# ---------------------------------------------------------------------

def test_chunked_view_stats_fuse(mesh):
    x = _x(seed=11)
    cv = bolt.array(x, mesh).map(lambda v: v + 1).chunk(size=(3,),
                                                        axis=(0,))
    s, v = bolt.compute(cv.sum(), cv.var())
    assert np.allclose(np.asarray(s.toarray()), (x + 1).sum(axis=0))
    assert np.allclose(np.asarray(v.toarray()), (x + 1).var(axis=0))


# ---------------------------------------------------------------------
# streamed multi-stat: one ingest pass, bit-exact on power-of-two slabs
# ---------------------------------------------------------------------

SHAPE = (16, 6, 4)


def _intdata(shape=SHAPE):
    return ((np.arange(np.prod(shape)) % 13) - 6).astype(
        np.float64).reshape(shape)


def _source(data, mesh, chunks):
    return bolt.fromcallback(lambda idx: data[idx], data.shape, mesh,
                             dtype=data.dtype, chunks=chunks)


def test_streamed_multi_stat_single_ingest_pass(mesh):
    data = _intdata()
    s = _source(data, mesh, 4)                # 4 power-of-two slabs
    c0 = engine.counters()
    su, va, mn, mx = bolt.compute(s.sum(), s.var(), s.min(), s.max())
    c1 = engine.counters()
    d = {k: c1[k] - c0[k] for k in c1}
    assert d["stream_chunks"] == 4            # ONE pass over the source
    assert d["transfer_bytes"] == data.nbytes
    assert d["fused_stat_terminals"] == 4
    # bit-exact vs the materialised terminals (power-of-two slab count)
    mat = bolt.array(data, mesh)
    assert _bits(su.toarray(), mat.sum().toarray())
    assert _bits(va.toarray(), mat.var().toarray())
    assert _bits(mn.toarray(), mat.min().toarray())
    assert _bits(mx.toarray(), mat.max().toarray())


@pytest.mark.parametrize("chunks", [3, 5, 1])
def test_streamed_multi_stat_uneven_tails(mesh, chunks):
    data = _intdata()
    s = _source(data, mesh, chunks)
    su, me, mn = bolt.compute(s.sum(), s.mean(), s.min())
    # integer-valued data: sum/min exact under any fold order; the
    # mean's Chan denominators are only bit-exact on power-of-two
    # EQUAL slab counts (the documented contract) — uneven tails get
    # ulp-level tolerance
    assert np.array_equal(np.asarray(su.toarray()), data.sum(axis=0))
    assert np.allclose(np.asarray(me.toarray()), data.mean(axis=0),
                       rtol=1e-12, atol=1e-12)
    assert np.array_equal(np.asarray(mn.toarray()), data.min(axis=0))


def test_streamed_standalone_still_bit_exact_and_lazy(mesh):
    data = _intdata()
    s = _source(data, mesh, 4).sum()
    c0 = engine.counters()
    assert c0 is not None and s._spending is not None
    got = np.asarray(s.toarray())
    assert np.array_equal(got, data.sum(axis=0))


def test_streamed_filtered_multi_stat(mesh):
    data = _intdata()
    s = _source(data, mesh, 4).filter(PRED)
    su, me = bolt.compute(s.sum(), s.mean())
    keep = _keep(data)
    assert np.array_equal(np.asarray(su.toarray()), keep.sum(axis=0))
    # the masked per-slab counts merge through the Chan recurrence:
    # ulp-level tolerance off power-of-two survivor splits
    assert np.allclose(np.asarray(me.toarray()), keep.mean(axis=0),
                       rtol=1e-12, atol=1e-12)


def test_streamed_ptp_is_one_pass(mesh):
    data = _intdata()
    c0 = engine.counters()
    p = _source(data, mesh, 4).ptp()
    got = np.asarray(p.toarray())
    c1 = engine.counters()
    assert c1["stream_chunks"] - c0["stream_chunks"] == 4
    assert np.array_equal(got, np.ptp(data, axis=0))


def test_materialised_source_does_not_rejoin_stream_group(mesh):
    data = _intdata()

    def gen():
        yield data[:8]
        yield data[8:]

    it = bolt.fromiter(gen(), SHAPE, mesh, dtype=np.float64)
    h = it.sum()                      # stream group forms
    assert np.array_equal(it.toarray(), data)   # burns the iterator
    h2 = it.mean()                    # computes from the CONCRETE data
    assert np.array_equal(np.asarray(h2.toarray()), data.mean(axis=0))
    # the pre-materialise handle kept its recorded one-shot source: the
    # pointed re-stream error surfaces at ITS read, not a silent wrong
    # answer
    with pytest.raises(RuntimeError, match="already streamed"):
        h.toarray()


def test_one_shot_fromiter_serves_all_members_in_one_pass(mesh):
    data = _intdata()

    def gen():
        yield data[:8]
        yield data[8:]

    it = bolt.fromiter(gen(), SHAPE, mesh, dtype=np.float64)
    su, me, sd = bolt.compute(it.sum(), it.mean(), it.std())
    assert np.array_equal(np.asarray(su.toarray()), data.sum(axis=0))
    assert np.array_equal(np.asarray(me.toarray()), data.mean(axis=0))
    assert np.allclose(np.asarray(sd.toarray()), data.std(axis=0))


# ---------------------------------------------------------------------
# the fluent a.stats("sum", ...) form + the local oracle
# ---------------------------------------------------------------------

def test_fluent_stats_tpu_vs_local_oracle(mesh):
    x = _x(seed=12)
    t = bolt.array(x, mesh).stats("sum", "var", "min", "ptp")
    lo = bolt.array(x).stats("sum", "var", "min", "ptp")
    assert list(t) == ["sum", "var", "min", "ptp"]
    for name in t:
        assert np.allclose(np.asarray(t[name].toarray()),
                           np.asarray(lo[name]), atol=1e-10), name


def test_fluent_stats_is_one_pass(mesh):
    x = _x(seed=13)
    b = bolt.array(x, mesh).map(lambda v: v + 5)
    c0 = engine.counters()
    out = b.stats("sum", "mean", "max")
    c1 = engine.counters()
    assert c1["dispatches"] - c0["dispatches"] == 1
    assert np.allclose(np.asarray(out["max"].toarray()),
                       (x + 5).max(axis=0))


def test_fluent_stats_rejects_unknown_names(mesh):
    b = bolt.array(_x(), mesh)
    with pytest.raises(ValueError, match="unknown statistic"):
        b.stats("sum", "median")
    with pytest.raises(ValueError, match="unknown statistic"):
        bolt.array(_x()).stats("nope")


def test_stats_statcounter_contract_unchanged(mesh):
    x = _x(seed=14)
    st = bolt.array(x, mesh).stats()
    assert np.allclose(np.asarray(st.mean()), x.mean(axis=0))
    st2 = bolt.array(x, mesh).stats(("mean", "var"))
    assert np.allclose(np.asarray(st2.variance()), x.var(axis=0))
    st3 = bolt.array(x, mesh).stats(axis=(1,))
    assert np.allclose(np.asarray(st3.mean()), x.mean(axis=1))
    # the legacy POSITIONAL axis form keeps working on both backends
    st4 = bolt.array(x, mesh).stats(("mean",), (1,))
    assert np.allclose(np.asarray(st4.mean()), x.mean(axis=1))
    st5 = bolt.array(x).stats(("mean",), (1,))
    assert np.allclose(np.asarray(st5.mean()), x.mean(axis=1))
    with pytest.raises(TypeError, match="axis twice"):
        bolt.array(x, mesh).stats(("mean",), (1,), axis=(0,))


def test_fluent_stats_mixed_names_on_one_shot_stream(mesh):
    # a non-streamable name (prod) in the SAME fluent call must not
    # consume a one-shot iterator out from under the streamed siblings:
    # the source materialises once up front and every name computes
    # from the concrete data (order-independent)
    data = _intdata()

    def gen():
        yield data[:8]
        yield data[8:]

    for names in (("sum", "prod"), ("prod", "sum")):
        it = bolt.fromiter(gen(), SHAPE, mesh, dtype=np.float64)
        out = it.stats(*names)
        assert np.array_equal(np.asarray(out["sum"].toarray()),
                              data.sum(axis=0)), names
        assert np.allclose(np.asarray(out["prod"].toarray()),
                           data.prod(axis=0)), names


def test_materialised_chain_source_starts_fresh_group(mesh):
    # after a chain materialises, new terminals must reduce the
    # CONCRETE buffer, not rejoin the old group and re-run the map
    # chain from the base (the one-pass cost model would silently
    # double)
    x = _x(seed=19)
    m = bolt.array(x, mesh).map(lambda v: v * 3)
    s = m.sum()                       # chain group forms
    m.cache()                         # materialises the chain
    v = m.var()
    assert v._spending.group is not s._spending.group
    assert v._spending.group.funcs == ()      # reduces concrete data
    assert np.allclose(np.asarray(v.toarray()), (x * 3).var(axis=0))
    assert np.allclose(np.asarray(s.toarray()), (x * 3).sum(axis=0))


# ---------------------------------------------------------------------
# donation: one donate serves the whole fused group
# ---------------------------------------------------------------------

def test_group_donates_once_and_guards_source(mesh):
    x = _x(seed=15)
    with engine.donation(0):
        d = bolt.array(x, mesh).map(lambda v: v + 1)
        n0 = engine.counters()["donations"]
        s = d.sum()                           # consumes the sole owner
        assert engine.counters()["donations"] == n0 + 1
        v = d.var()                           # joins the SAME group
        assert engine.counters()["donations"] == n0 + 1
        su, va = bolt.compute(s, v)
        assert np.allclose(np.asarray(su.toarray()), (x + 1).sum(axis=0))
        assert np.allclose(np.asarray(va.toarray()), (x + 1).var(axis=0))
        assert engine.counters()["donations"] == n0 + 1   # ONE donate
        with pytest.raises(RuntimeError, match="donated"):
            d.toarray()
        # after the group dispatched, further terminals hit the guard
        with pytest.raises(RuntimeError, match="donated"):
            d.mean()


# ---------------------------------------------------------------------
# reduced-precision accumulation (opt-in; default exact)
# ---------------------------------------------------------------------

def _acc_data(mesh):
    x = (np.random.RandomState(16).rand(32, 8, 4)
         .astype(np.float32) * 3 + 0.5)
    return x, bolt.array(x, mesh)


def test_accumulate_default_is_bit_exact(mesh):
    x, b = _acc_data(mesh)
    s1 = bolt.compute(bolt.array(x, mesh).map(lambda v: v * 1.7).sum())
    m = b.map(lambda v: v * 1.7)
    s2, _v = bolt.compute(m.sum(), m.var())
    assert _bits(s1.toarray(), s2.toarray())


def test_accumulate_f32_exact_for_f32_pipeline(mesh):
    x, b = _acc_data(mesh)
    want = np.asarray(
        bolt.compute(bolt.array(x, mesh).sum()).toarray())
    got = bolt.compute(bolt.array(x, mesh).sum(), accumulate="f32")
    assert _bits(got.toarray(), want)


def test_accumulate_bf16_within_documented_envelope(mesh):
    x, b = _acc_data(mesh)
    exact = np.asarray(bolt.compute(bolt.array(x, mesh).sum(),
                                    bolt.array(x, mesh).var())
                       [0].toarray())
    s, v, mn = bolt.compute(b.sum(), b.var(), b.min(),
                            accumulate="bf16")
    got = np.asarray(s.toarray())
    assert got.dtype == np.float32            # accumulate-in-f32 result
    rel = np.max(np.abs(got - exact) / np.maximum(np.abs(exact), 1e-6))
    assert rel < 1e-2                         # the documented envelope
    # order statistics stay exact regardless of the mode
    assert _bits(mn.toarray(), x.min(axis=0))


def test_accumulate_scope_and_validation(mesh):
    x, _b = _acc_data(mesh)
    with _precision.accumulate("bf16"):
        s = bolt.compute(bolt.array(x, mesh).sum())
        assert np.asarray(s.toarray()).dtype == np.float32
    with pytest.raises(ValueError, match="accumulate mode"):
        bolt.compute(bolt.array(x, mesh).sum(), accumulate="f16")
    # integer pipelines ignore the cast (counts stay exact)
    xi = np.arange(48, dtype=np.int64).reshape(12, 4)
    si = bolt.compute(bolt.array(xi, mesh).sum(), accumulate="bf16")
    assert np.array_equal(np.asarray(si.toarray()), xi.sum(axis=0))


def test_accumulate_rejects_streamed_groups_explicitly(mesh):
    data = _intdata()
    with pytest.raises(ValueError, match="in-memory"):
        bolt.compute(_source(data, mesh, 4).sum(), accumulate="bf16")


# ---------------------------------------------------------------------
# analysis: BLT009 fusion forecast, zero compiles
# ---------------------------------------------------------------------

def test_check_forecasts_fusion_with_zero_compiles(mesh):
    x = _x(seed=17)
    m = bolt.array(x, mesh).map(lambda v: v + 1)
    s, v = m.sum(), m.var()
    c0 = engine.counters()
    rep = analysis.check(s)                   # the pending-stat array
    rep_src = analysis.check(m)               # the source carrying the group
    c1 = engine.counters()
    for k in ("misses", "aot_compiles", "dispatches"):
        assert c1[k] == c0[k], k
    assert rep.ok and rep.has("BLT009")
    assert rep.shape == (6, 4)
    assert np.dtype(rep.dtype) == np.float64
    assert rep_src.has("BLT009")
    txt = analysis.explain(s)
    assert "fusable terminal set" in txt and "ONE" in txt
    # the handles were NOT resolved by the check
    assert s._spending is not None and v._spending is not None
    # forecast on a streamed plan too
    src = _source(_intdata(), mesh, 4)
    h = src.sum()
    rep2 = analysis.check(h)
    assert rep2.has("BLT009")
    assert h._spending is not None


def test_strict_gate_still_fires_at_call(mesh):
    from bolt_tpu.tpu.array import BoltArrayTPU
    base = bolt.array(_x(seed=18), mesh)._data
    bad = BoltArrayTPU._deferred(
        base, (lambda v: v @ jnp.ones((99, 2)),), 1, mesh,
        jax.ShapeDtypeStruct((16, 2), np.float64))
    with analysis.strict():
        with pytest.raises(analysis.PipelineError, match="BLT001"):
            bad.sum()


# ---------------------------------------------------------------------
# int8 accumulate (ISSUE 8 satellite): the integer twin of bf16 —
# int8 values, int32 accumulator, integer additive terminals only
# ---------------------------------------------------------------------

def _xi(shape=(16, 6, 4)):
    # int8-range values (the documented contract)
    return ((np.arange(np.prod(shape)) % 101) - 50).astype(
        np.int32).reshape(shape)


def test_accumulate_int8_parity_locked_for_int_pipeline(mesh):
    xi = _xi()
    got = bolt.compute(bolt.array(xi, mesh).map(lambda v: v).sum(),
                       accumulate="int8")
    # the accumulate-in-i32 contract: int8 values, int32 accumulator —
    # the numpy oracle with the same dtypes is EXACT parity
    oracle = np.sum(xi.astype(np.int8), axis=0, dtype=np.int32)
    out = np.asarray(got.toarray())
    assert out.dtype == np.int32
    assert np.array_equal(out, oracle)


def test_accumulate_int8_fused_group_mixes_exact_order_stats(mesh):
    xi = _xi()
    m = bolt.array(xi, mesh).map(lambda v: v * 2)
    s, mn, mx = bolt.compute(m.sum(), m.min(), m.max(),
                             accumulate="int8")
    vals = xi * 2               # doubled values may exceed int8: wrap,
    #                             exactly like the cast contract says
    oracle = np.sum(vals.astype(np.int8), axis=0, dtype=np.int32)
    assert np.array_equal(np.asarray(s.toarray()), oracle)
    # order statistics are ALWAYS exact, whatever the accumulate mode
    assert np.array_equal(np.asarray(mn.toarray()), vals.min(axis=0))
    assert np.array_equal(np.asarray(mx.toarray()), vals.max(axis=0))


def test_accumulate_int8_leaves_float_pipelines_and_moments_exact(mesh):
    x = _x(seed=21)
    b = bolt.array(x, mesh).map(lambda v: v + 1)
    s, v = bolt.compute(b.sum(), b.var(), accumulate="int8")
    exact = bolt.array(x, mesh).map(lambda v: v + 1)
    assert _bits(s.toarray(), bolt.compute(exact.sum()).toarray())
    # an INT pipeline's moment terminals are float-valued: int8 must
    # not touch them either
    xi = _xi()
    mean8 = bolt.compute(bolt.array(xi, mesh).map(lambda v: v).mean(),
                         accumulate="int8")
    assert _bits(mean8.toarray(),
                 bolt.array(xi, mesh).mean().toarray())


def test_accumulate_int8_scope_and_stream_rejection(mesh):
    xi = _xi()
    with _precision.accumulate("int8"):
        got = bolt.compute(bolt.array(xi, mesh).map(lambda v: v).sum())
    assert np.asarray(got.toarray()).dtype == np.int32
    with pytest.raises(ValueError, match="in-memory"):
        bolt.compute(_source(_intdata(), mesh, 4).sum(),
                     accumulate="int8")


# ---------------------------------------------------------------------
# concurrency (ISSUE 8 satellite): try_join racing resolve, and
# lock-consistent fused-counter snapshots
# ---------------------------------------------------------------------

def test_try_join_racing_resolve_never_strands_a_member(mesh):
    import threading
    x = _x((32, 4), seed=5)
    oracle_sum = (x * 2).sum(axis=0)
    oracle_var = (x * 2).var(axis=0)
    for _ in range(20):                   # many interleavings
        b = bolt.array(x, mesh).map(lambda v: v * 2)
        first = b.sum()
        got = {}

        def reader():
            got["sum"] = np.asarray(first.toarray())   # resolves group

        def joiner():
            h = b.var()                   # try_join may hit a group
            got["var"] = np.asarray(h.toarray())       # mid-resolve

        ts = [threading.Thread(target=reader, daemon=True),
              threading.Thread(target=joiner, daemon=True)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        # whichever group each member landed in, both values are right
        assert np.allclose(got["sum"], oracle_sum)
        assert np.allclose(got["var"], oracle_var)


def test_fused_counter_snapshots_are_lock_consistent(mesh):
    import threading
    x = _x((8, 3), seed=9)
    c0 = engine.counters()
    stopped = threading.Event()
    bad = []

    def snapshotter():
        while not stopped.is_set():
            c = engine.counters()
            dg = c["fused_stat_groups"] - c0["fused_stat_groups"]
            dt = c["fused_stat_terminals"] - c0["fused_stat_terminals"]
            # every fused dispatch lands groups+terminals in ONE atomic
            # update (2 terminals per group here): a snapshot must never
            # interleave with a half-applied tally
            if dt != 2 * dg:
                bad.append((dg, dt))

    def hammer():
        for _ in range(10):
            m = bolt.array(x, mesh).map(lambda v: v + 3)
            bolt.compute(m.sum(), m.max())

    snap = threading.Thread(target=snapshotter, daemon=True)
    workers = [threading.Thread(target=hammer, daemon=True)
               for _ in range(3)]
    snap.start()
    for w in workers:
        w.start()
    for w in workers:
        w.join(120)
    stopped.set()
    snap.join(10)
    assert not bad
    c1 = engine.counters()
    assert c1["fused_stat_groups"] - c0["fused_stat_groups"] == 30
    assert c1["fused_stat_terminals"] - c0["fused_stat_terminals"] == 60
