"""The scoped matmul-precision policy (VERDICT r4 weak-3/4): the
documented ~2x precision trade on pca/halo/matmul-class ops must be
user-accessible — a ``bolt.precision`` scope plus per-call kwargs —
with defaults unchanged.

On the CPU verification mesh every jax precision computes in f32/f64,
so the two policies agree numerically here; the suite pins the POLICY
semantics (resolution order, nesting, per-executable caching, the full
op surface accepting the scope) and runs every family under BOTH modes
against the oracle with the documented tolerances.  The real-chip
divergence envelope (~1e-2 relative under "default") is pinned by the
chip gate (tests/test_chip.py)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.precision import MODES, precision, resolve


def test_resolution_order():
    # pinned default outside any scope
    assert resolve() == "highest"
    assert resolve(pinned="default") == "default"
    # scope overrides the pin
    with precision("default"):
        assert resolve() == "default"
        # nesting: innermost wins
        with precision("high"):
            assert resolve() == "high"
        assert resolve() == "default"
    assert resolve() == "highest"
    # explicit kwarg beats the scope
    with precision("default"):
        assert resolve("highest") == "highest"


def test_invalid_mode_rejected():
    with pytest.raises(ValueError, match="precision mode"):
        with precision("bf16"):
            pass
    with pytest.raises(ValueError, match="precision mode"):
        resolve("fast")


def test_scope_is_exception_safe():
    try:
        with precision("default"):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert resolve() == "highest"


@pytest.mark.parametrize("mode", MODES)
def test_matmul_family_under_both_policies(mesh, mode):
    rs = np.random.RandomState(21)
    x = rs.randn(8, 6)
    w = rs.randn(6, 4)
    b = bolt.array(x, mesh)
    # CPU mesh: all modes compute alike — the suite asserts the policy
    # SURFACE serves every family; the chip gate owns the numeric gap
    with precision(mode):
        assert np.allclose((b @ w).toarray(), x @ w)
        assert np.allclose(b.dot(w).toarray(), x @ w)
        assert np.allclose(np.asarray(np.einsum("ij,jk->ik", b, w)
                                      .toarray()), x @ w)
        assert np.allclose(np.asarray(np.tensordot(b, w, axes=1)
                                      .toarray()), x @ w)
        assert np.allclose(np.asarray(np.inner(b, w.T).toarray()),
                           np.inner(x, w.T))


@pytest.mark.parametrize("mode", MODES)
def test_pca_cov_under_both_policies(mesh, mode):
    from bolt_tpu.ops import corrcoef, cov, pca
    rs = np.random.RandomState(22)
    x = rs.randn(16, 5)
    b = bolt.array(x, mesh)
    with precision(mode):
        scores, comps, sv = pca(b, k=3, center=True)
        s2, c2, v2 = pca(bolt.array(x), k=3, center=True)
        # components match up to per-column sign
        sign = np.sign(np.sum(comps * c2, axis=0))
        assert np.allclose(comps * sign, c2, atol=1e-5)
        assert np.allclose(sv, v2, atol=1e-5)
        assert np.allclose(cov(b), np.cov(x, rowvar=False), atol=1e-6)
        assert np.allclose(corrcoef(b), np.corrcoef(x, rowvar=False),
                           atol=1e-6)
    # per-call kwarg form, outside any scope
    assert np.allclose(cov(b, precision="default"),
                       np.cov(x, rowvar=False), atol=1e-6)
    pca(b, k=2, precision="high")


def test_filters_under_both_policies(mesh):
    from bolt_tpu.ops import gaussian, smooth
    rs = np.random.RandomState(23)
    x = rs.randn(8, 16, 256)
    b = bolt.array(x, mesh)
    lo = bolt.array(x)
    for mode in MODES:
        with precision(mode):
            g = gaussian(b, 2.0, axis=(0,))
            e = gaussian(lo, 2.0, axis=(0,))
            assert np.allclose(np.asarray(g.toarray()),
                               np.asarray(e.toarray()), atol=1e-6)
    # per-call kwarg form
    s = smooth(b, 3, axis=(0,), precision="default")
    e = smooth(lo, 3, axis=(0,))
    assert np.allclose(np.asarray(s.toarray()), np.asarray(e.toarray()),
                       atol=1e-6)


def test_executables_cache_per_mode(mesh):
    """Scoped and unscoped calls must never share a compiled program:
    the jit-cache key carries the resolved mode."""
    from bolt_tpu.tpu.array import _JIT_CACHE
    rs = np.random.RandomState(24)
    x = rs.randn(8, 6)
    w = rs.randn(6, 6)
    b = bolt.array(x, mesh)
    (b @ w).toarray()
    n0 = len([k for k in _JIT_CACHE if k and k[0] == "matmul"])
    with precision("default"):
        (b @ w).toarray()
    n1 = len([k for k in _JIT_CACHE if k and k[0] == "matmul"])
    assert n1 == n0 + 1
    # repeat under the same scope: cache hit, no new executable
    with precision("default"):
        (b @ w).toarray()
    n2 = len([k for k in _JIT_CACHE if k and k[0] == "matmul"])
    assert n2 == n1


def test_default_unchanged_outside_scope(mesh):
    """The library default stays pinned "highest" — a no-scope call and
    an explicit precision("highest") scope produce the SAME cache key."""
    from bolt_tpu.tpu.array import _JIT_CACHE
    rs = np.random.RandomState(25)
    x = rs.randn(8, 5)
    w = rs.randn(5, 5)
    b = bolt.array(x, mesh)
    (b @ w).toarray()
    n0 = len([k for k in _JIT_CACHE if k and k[0] == "matmul"])
    with precision("highest"):
        (b @ w).toarray()
    assert len([k for k in _JIT_CACHE
                if k and k[0] == "matmul"]) == n0
