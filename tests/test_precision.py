"""The scoped matmul-precision policy (VERDICT r4 weak-3/4): the
documented ~2x precision trade on pca/halo/matmul-class ops must be
user-accessible — a ``bolt.precision`` scope plus per-call kwargs —
with defaults unchanged.

On the CPU verification mesh every jax precision computes in f32/f64,
so the two policies agree numerically here; the suite pins the POLICY
semantics (resolution order, nesting, per-executable caching, the full
op surface accepting the scope) and runs every family under BOTH modes
against the oracle with the documented tolerances.  The real-chip
divergence envelope (~1e-2 relative under "default") is pinned by the
chip gate (tests/test_chip.py)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu._precision import MODES, precision, resolve


def test_resolution_order():
    # pinned default outside any scope
    assert resolve() == "highest"
    assert resolve(pinned="default") == "default"
    # scope overrides the pin
    with precision("default"):
        assert resolve() == "default"
        # nesting: innermost wins
        with precision("high"):
            assert resolve() == "high"
        assert resolve() == "default"
    assert resolve() == "highest"
    # explicit kwarg beats the scope
    with precision("default"):
        assert resolve("highest") == "highest"


def test_invalid_mode_rejected():
    with pytest.raises(ValueError, match="precision mode"):
        with precision("bf16"):
            pass
    with pytest.raises(ValueError, match="precision mode"):
        resolve("fast")


def test_scope_is_exception_safe():
    try:
        with precision("default"):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert resolve() == "highest"


@pytest.mark.parametrize("mode", MODES)
def test_matmul_family_under_both_policies(mesh, mode):
    rs = np.random.RandomState(21)
    x = rs.randn(8, 6)
    w = rs.randn(6, 4)
    b = bolt.array(x, mesh)
    # CPU mesh: all modes compute alike — the suite asserts the policy
    # SURFACE serves every family; the chip gate owns the numeric gap
    with precision(mode):
        assert np.allclose((b @ w).toarray(), x @ w)
        assert np.allclose(b.dot(w).toarray(), x @ w)
        assert np.allclose(np.asarray(np.einsum("ij,jk->ik", b, w)
                                      .toarray()), x @ w)
        assert np.allclose(np.asarray(np.tensordot(b, w, axes=1)
                                      .toarray()), x @ w)
        assert np.allclose(np.asarray(np.inner(b, w.T).toarray()),
                           np.inner(x, w.T))


@pytest.mark.parametrize("mode", MODES)
def test_pca_cov_under_both_policies(mesh, mode):
    from bolt_tpu.ops import corrcoef, cov, pca
    rs = np.random.RandomState(22)
    x = rs.randn(16, 5)
    b = bolt.array(x, mesh)
    with precision(mode):
        scores, comps, sv = pca(b, k=3, center=True)
        s2, c2, v2 = pca(bolt.array(x), k=3, center=True)
        # components match up to per-column sign
        sign = np.sign(np.sum(comps * c2, axis=0))
        assert np.allclose(comps * sign, c2, atol=1e-5)
        assert np.allclose(sv, v2, atol=1e-5)
        assert np.allclose(cov(b), np.cov(x, rowvar=False), atol=1e-6)
        assert np.allclose(corrcoef(b), np.corrcoef(x, rowvar=False),
                           atol=1e-6)
    # per-call kwarg form, outside any scope
    assert np.allclose(cov(b, precision="default"),
                       np.cov(x, rowvar=False), atol=1e-6)
    pca(b, k=2, precision="high")


def test_filters_under_both_policies(mesh):
    from bolt_tpu.ops import gaussian, smooth
    rs = np.random.RandomState(23)
    x = rs.randn(8, 16, 256)
    b = bolt.array(x, mesh)
    lo = bolt.array(x)
    for mode in MODES:
        with precision(mode):
            g = gaussian(b, 2.0, axis=(0,))
            e = gaussian(lo, 2.0, axis=(0,))
            assert np.allclose(np.asarray(g.toarray()),
                               np.asarray(e.toarray()), atol=1e-6)
    # per-call kwarg form
    s = smooth(b, 3, axis=(0,), precision="default")
    e = smooth(lo, 3, axis=(0,))
    assert np.allclose(np.asarray(s.toarray()), np.asarray(e.toarray()),
                       atol=1e-6)


def test_executables_cache_per_mode(mesh):
    """Scoped and unscoped calls must never share a compiled program:
    the jit-cache key carries the resolved mode."""
    from bolt_tpu.tpu.array import _JIT_CACHE
    rs = np.random.RandomState(24)
    x = rs.randn(8, 6)
    w = rs.randn(6, 6)
    b = bolt.array(x, mesh)
    (b @ w).toarray()
    n0 = len([k for k in _JIT_CACHE if k and k[0] == "matmul"])
    with precision("default"):
        (b @ w).toarray()
    n1 = len([k for k in _JIT_CACHE if k and k[0] == "matmul"])
    assert n1 == n0 + 1
    # repeat under the same scope: cache hit, no new executable
    with precision("default"):
        (b @ w).toarray()
    n2 = len([k for k in _JIT_CACHE if k and k[0] == "matmul"])
    assert n2 == n1


def test_default_unchanged_outside_scope(mesh):
    """The library default stays pinned "highest" — a no-scope call and
    an explicit precision("highest") scope produce the SAME cache key."""
    from bolt_tpu.tpu.array import _JIT_CACHE
    rs = np.random.RandomState(25)
    x = rs.randn(8, 5)
    w = rs.randn(5, 5)
    b = bolt.array(x, mesh)
    (b @ w).toarray()
    n0 = len([k for k in _JIT_CACHE if k and k[0] == "matmul"])
    with precision("highest"):
        (b @ w).toarray()
    assert len([k for k in _JIT_CACHE
                if k and k[0] == "matmul"]) == n0


def test_resolve_accepts_jax_precision_enums():
    """The 0.4.0 dot(..., precision=...) contract took any jax precision
    spelling: lax.Precision members (and case-insensitive mode names)
    must map onto the three mode strings (ADVICE r5)."""
    from jax import lax
    assert resolve(lax.Precision.DEFAULT) == "default"
    assert resolve(lax.Precision.HIGH) == "high"
    assert resolve(lax.Precision.HIGHEST) == "highest"
    assert resolve("HIGHEST") == "highest"
    with precision(lax.Precision.DEFAULT):
        assert resolve() == "default"


def test_dot_accepts_jax_precision_enum(mesh):
    from jax import lax
    rs = np.random.RandomState(31)
    x, w = rs.randn(8, 6), rs.randn(6, 4)
    b = bolt.array(x, mesh)
    out = b.dot(w, precision=lax.Precision.HIGHEST)
    assert np.allclose(np.asarray(out.toarray()), x @ w)


def test_multi_dot_honours_precision_scope(mesh):
    """multi_dot resolves the scoped policy like every other matmul-class
    op: distinct modes produce DISTINCT executables (the precision rides
    the cache key), same mode reuses one (ADVICE r5 medium)."""
    from bolt_tpu.tpu.array import _JIT_CACHE
    rs = np.random.RandomState(32)
    b = bolt.array(rs.randn(8, 6), mesh)
    mats = [rs.randn(6, 5), rs.randn(5, 4)]
    ref = np.linalg.multi_dot([np.asarray(b.toarray())] + mats)
    out = np.linalg.multi_dot([b] + mats)
    assert np.allclose(np.asarray(out.toarray()), ref)
    n0 = len([k for k in _JIT_CACHE if k and k[0] == "multi_dot"])
    with precision("default"):
        np.linalg.multi_dot([b] + mats)
    n1 = len([k for k in _JIT_CACHE if k and k[0] == "multi_dot"])
    assert n1 == n0 + 1
    with precision("default"):
        np.linalg.multi_dot([b] + mats)
    assert len([k for k in _JIT_CACHE
                if k and k[0] == "multi_dot"]) == n1


def test_multi_dot_integer_dtype_matches_oracle(mesh):
    """Integer chains must come back as (canonicalised) ints, not leak
    the f32 compute dtype (ADVICE r5 low)."""
    rs = np.random.RandomState(33)
    a = rs.randint(-4, 5, (6, 5))
    m1, m2 = rs.randint(-4, 5, (5, 4)), rs.randint(-4, 5, (4, 3))
    b = bolt.array(a, mesh)
    out = np.linalg.multi_dot([b, m1, m2])
    ref = np.linalg.multi_dot([a, m1, m2])
    assert np.issubdtype(out.dtype, np.integer)
    assert np.array_equal(np.asarray(out.toarray()), ref)


def test_tensorsolve_integer_dtype_matches_oracle(mesh):
    """tensorsolve of ints answers in numpy's float solve dtype
    (canonicalised), not a silent float32 (ADVICE r5 low)."""
    rs = np.random.RandomState(34)
    a = np.eye(6, dtype=np.int64) * 2
    bvec = rs.randint(-3, 4, (6,))
    bb = bolt.array(a, mesh)
    out = np.linalg.tensorsolve(bb, bvec)
    ref = np.linalg.tensorsolve(a, bvec)
    assert out.dtype == ref.dtype
    assert np.allclose(np.asarray(out.toarray()), ref)


def test_precision_module_alias():
    """bolt_tpu.precision is callable (the context-manager contract);
    bolt_tpu._precision is the module; the legacy from-import keeps
    working through the alias shim (ADVICE r5 low).  Loading the alias
    module makes the import machinery REPLACE the package attribute
    with the module object — the alias is therefore itself callable and
    delegates, so the public scope spelling works before AND after the
    legacy import (the identity form of this test missed that clobber
    because the from-import was its last statement)."""
    import bolt_tpu
    import bolt_tpu._precision as mod
    assert callable(bolt_tpu.precision)
    from bolt_tpu.precision import resolve as r2
    assert r2 is mod.resolve
    assert callable(bolt_tpu.precision)      # survived the clobber
    with bolt_tpu.precision("default"):
        assert mod.resolve() == "default"
    assert mod.resolve() == "highest"
