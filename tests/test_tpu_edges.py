"""Cross-feature edge interactions the per-feature suites don't cover."""

from operator import add

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.utils import allclose


def _x(shape=(8, 6, 4)):
    rs = np.random.RandomState(50)
    return rs.randn(*shape)


def test_chunk_of_deferred(mesh):
    # chunk() on a deferred map chain: shape comes from the aval; map on
    # the chunks materialises the chain first
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v + 1)
    c = m.chunk(size=(3,), axis=(0,))
    assert c.plan == (3, 4)
    out = c.map(lambda blk: blk * 2).unchunk()
    assert allclose(out.toarray(), (x + 1) * 2)


def test_stacked_of_deferred(mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v * 3)
    out = m.stacked(4).map(lambda blk: blk + 1).unstack()
    assert allclose(out.toarray(), x * 3 + 1)


def test_filter_after_swap(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    s = b.swap((1,), ())  # keys (8,), values (6, 4)
    out = s.filter(lambda v: v.sum() > 0)
    expected = np.asarray([v for v in x if v.sum() > 0])
    assert allclose(out.toarray(), expected)


def test_getitem_on_deferred(mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v + 1)
    assert allclose(m[2:5].toarray(), (x + 1)[2:5])


def test_reduce_after_operators(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = ((b * 2) + 1).reduce(add)
    assert allclose(out.toarray(), (x * 2 + 1).sum(axis=0))


def test_concatenate_deferred_operand(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    m = b.map(lambda v: v + 1)
    out = b.concatenate(m, axis=0)
    assert allclose(out.toarray(), np.concatenate([x, x + 1], axis=0))


def test_welford_on_deferred(mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v - 1)
    st = m.stats()
    assert allclose(st.mean(), (x - 1).mean(axis=0))


def test_keys_view_after_swap(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    s = b.swap((0,), (0,))  # keys (6, 4), values (8,)
    assert s.keys.shape == (6, 4)
    out = s.keys.reshape(24)
    assert out.split == 1
    assert out.shape == (24, 8)


def test_with_keys_multiaxis(mesh2d):
    x = _x((4, 2, 5))
    b = bolt.array(x, mesh2d, axis=(0, 1))
    out = b.map(lambda kv: kv[1] + kv[0][0] * 10 + kv[0][1],
                axis=(0, 1), with_keys=True)
    keys0 = np.arange(4).reshape(4, 1, 1)
    keys1 = np.arange(2).reshape(1, 2, 1)
    assert allclose(out.toarray(), x + keys0 * 10 + keys1)


def test_empty_key_axis(mesh):
    # zero-size key axis: degenerate but must not crash
    x = np.zeros((0, 3, 2))
    b = bolt.array(x, mesh)
    assert b.shape == (0, 3, 2)
    assert b.map(lambda v: v + 1).toarray().shape == (0, 3, 2)
    assert b.filter(lambda v: True).toarray().shape == (0, 3, 2)


def test_pipeline_under_disable_jit(mesh):
    # the debugging mode users reach for first: everything must still
    # produce oracle answers eagerly
    import jax
    rs = np.random.RandomState(60)
    x = rs.randn(16, 4)
    with jax.disable_jit():
        b = bolt.array(x, mesh)
        assert np.allclose(b.map(lambda v: v + 1).sum(axis=(0,)).toarray(),
                           (x + 1).sum(axis=0))
        assert np.allclose(np.asarray(b.stats().mean()), x.mean(axis=0))
        f = b.filter(lambda v: v.mean() > 0)
        assert np.allclose(f.toarray(), x[x.mean(axis=1) > 0])
        assert np.allclose(b.swap((0,), (0,)).toarray(), x.T)


# ----------------------------------------------------------------------
# round-2 ADVICE fixes
# ----------------------------------------------------------------------

def test_one_axis_typeerror_matches_ndarray(mesh):
    # non-integral axis raises TypeError on BOTH backends (ndarray's type)
    import bolt_tpu as bolt
    x = np.random.RandomState(0).randn(4, 6)
    tp = bolt.array(x, mesh)
    with pytest.raises(TypeError):
        tp.cumsum(axis=1.5)
    with pytest.raises(TypeError):
        tp.argmax(axis=(0, 1))
    with pytest.raises(TypeError):
        bolt.array(x).cumsum(axis=1.5)   # ndarray raises TypeError too


def test_wide_filter_tight_budget(mesh):
    # a halo wider than the budget-halved chunk plan used to surface as an
    # opaque "padding must be smaller than the chunk size"; the plan is
    # now floored at halo+1 and the filter just runs
    import bolt_tpu as bolt
    from bolt_tpu.ops import gaussian
    x = np.random.RandomState(1).randn(2, 256).astype(np.float64)
    b = bolt.array(x, mesh)
    out = gaussian(b, sigma=8.0, axis=0, size="0.001")   # ~1 kB budget
    lo = gaussian(bolt.array(x), sigma=8.0, axis=0, size="0.001")
    assert bolt.allclose(out.toarray(), lo.toarray())


def test_explicit_small_chunk_vs_halo_names_fix(mesh):
    # explicit per-axis sizes are the user's exact request: still an
    # error, but one that tells them what to change
    import bolt_tpu as bolt
    b = bolt.array(np.random.RandomState(2).randn(2, 64), mesh)
    with pytest.raises(ValueError, match="size="):
        b.chunk(size=4, axis=0, padding=10)


def test_zero_record_local_chunk_probe_no_warn():
    # the zeros probe for empty chunked/stacked maps must not leak numeric
    # warnings from funcs that divide by their input
    import warnings
    import bolt_tpu as bolt
    lo = bolt.array(np.zeros((0, 8)))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = lo.chunk(size=4, axis=0, key_axis=(0,)).map(
            lambda blk: blk / blk).unchunk()
    assert out.shape == (0, 8)
