"""Central dispatch engine tests: AOT executable cache + counters,
persistent on-disk compilation cache, donation-aware pipeline terminals,
and the fused single-pass filter→reduce path (ISSUE 1 tentpole)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bolt_tpu as bolt
from bolt_tpu import engine, profile


def _x():
    x = np.random.RandomState(0).randn(16, 6, 4)
    x[3] = np.nan          # a poison record the filters drop
    return x


PRED = lambda v: ~jnp.isnan(v).any() & (v.sum() > 0)


def _keep(x):
    return x[[bool(not np.isnan(v).any() and v.sum() > 0) for v in x]]


# ----------------------------------------------------------------------
# counters
# ----------------------------------------------------------------------

def test_counters_monotonic_and_hit_miss(mesh):
    b = bolt.array(_x(), mesh)
    f = lambda v: v * 2
    c0 = engine.counters()
    b.map(f).sum().toarray()
    c1 = engine.counters()
    # a fresh pipeline must MISS (new key) and dispatch at least once
    assert c1["misses"] > c0["misses"]
    assert c1["dispatches"] > c0["dispatches"]
    assert c1["dispatch_seconds"] >= c0["dispatch_seconds"]
    b.map(f).sum().toarray()
    c2 = engine.counters()
    # the identical pipeline must HIT (same key, no new build)
    assert c2["hits"] > c1["hits"]
    assert c2["misses"] == c1["misses"]
    # every counter is monotonic
    for k in c2:
        assert c2[k] >= c0[k], k


def test_aot_compiles_once_per_key(mesh):
    b = bolt.array(np.random.RandomState(1).randn(8, 5), mesh)
    f = lambda v: v + 3
    b.map(f).sum().toarray()
    c1 = engine.counters()
    for _ in range(3):
        b.map(f).sum().toarray()
    c2 = engine.counters()
    # three more identical dispatches: zero new XLA compiles
    assert c2["aot_compiles"] == c1["aot_compiles"]
    assert c2["dispatches"] >= c1["dispatches"] + 3


def test_counters_through_profile(mesh):
    bolt.ones((8, 3), mesh).sum().toarray()
    c = profile.engine_counters()
    for key in ("hits", "misses", "aot_compiles", "lower_seconds",
                "compile_seconds", "dispatches", "dispatch_seconds",
                "donations", "persistent_hits"):
        assert key in c
    txt = profile.engine_report()
    assert "aot_compiles" in txt and "compile_seconds" in txt


def test_cached_entries_stay_inspectable(mesh):
    # the HLO-contract tests read collectives out of cached entries:
    # engine entries must answer .lower like the jitted callables they wrap
    from bolt_tpu.tpu import array as array_mod
    b = bolt.array(np.random.RandomState(2).randn(8, 4), mesh)
    b.map(lambda v: v * 5).sum().toarray()
    fns = [v for k, v in array_mod._JIT_CACHE.items() if k[0] == "stat"]
    assert fns
    txt = fns[-1].lower(b._data).compile().as_text()
    assert txt  # lowered+compiled HLO text


# ----------------------------------------------------------------------
# persistent on-disk compilation cache
# ----------------------------------------------------------------------

def test_persistent_cache_roundtrip(tmp_path, mesh):
    d = str(tmp_path / "xla-cache")
    try:
        got = engine.persistent_cache(d)
        assert got == d
        assert engine.persistent_cache_dir() == d
        b = bolt.array(np.random.RandomState(3).randn(16, 8), mesh)
        b.map(lambda v: v * 7 + 1).sum().toarray()
        import os
        entries = os.listdir(d)
        if not entries:
            pytest.skip("backend does not serialize executables")
        # drop the engine's in-memory executables: the SAME program must
        # now load from disk (persistent hit) instead of recompiling
        engine.clear()
        h0 = engine.counters()["persistent_hits"]
        b.map(lambda v: v * 7 + 1).sum().toarray()
        assert engine.counters()["persistent_hits"] > h0
    finally:
        engine.persistent_cache(enable=False)
        assert engine.persistent_cache_dir() is None


# ----------------------------------------------------------------------
# donation-aware terminals
# ----------------------------------------------------------------------

def test_sole_owned_chain_donates_and_guards(mesh):
    x = _x()
    with engine.donation(0):
        d = bolt.array(x, mesh).map(lambda v: v + 1)   # parent is a temp
        n0 = engine.counters()["donations"]
        out = d.sum()
        assert engine.counters()["donations"] == n0 + 1
        assert np.allclose(np.asarray(out.toarray()),
                           (x + 1).sum(axis=0), equal_nan=True)
        # the consumed chain raises the existing donation guard
        with pytest.raises(RuntimeError, match="donated"):
            d.toarray()


def test_referenced_parent_never_donates(mesh):
    x = _x()
    with engine.donation(0):
        src = bolt.array(x, mesh)                      # parent stays live
        d = src.map(lambda v: v * 2)
        n0 = engine.counters()["donations"]
        d.sum().toarray()
        assert engine.counters()["donations"] == n0
        # both the parent and the deferred chain remain readable
        assert np.allclose(src.toarray(), x, equal_nan=True)
        assert np.allclose(d.toarray(), x * 2, equal_nan=True)


def test_clone_shared_chain_blocks_donation(mesh):
    # _clone (np.sort/np.rot90(k=0)/... return paths) shares the CHAIN
    # TUPLE with the original; donation must see the shared tuple and
    # refuse, or the clone would read a deleted buffer
    x = _x()
    with engine.donation(0):
        b = bolt.array(x, mesh).map(lambda v: v + 1)   # sole-owned base
        c = b._clone()
        n0 = engine.counters()["donations"]
        b.sum()
        assert engine.counters()["donations"] == n0
        assert np.allclose(c.toarray(), x + 1, equal_nan=True)


def test_zero_survivor_raise_leaves_donated_guard(mesh):
    # the donating fused program consumes the base BEFORE the
    # zero-survivor error: later reads must hit the guard, not the
    # deleted buffer
    x = _x()
    with engine.donation(0):
        f = bolt.array(x, mesh).filter(lambda v: v.sum() > 1e9)
        with pytest.raises(TypeError, match="empty"):
            f.reduce(np.add)
        with pytest.raises(RuntimeError, match="donated"):
            f.toarray()


def test_donation_floor_defaults_keep_small_arrays_readable(mesh):
    # below the floor nothing donates, so interactive reuse keeps working
    assert engine.donation_min_bytes() >= 1
    d = bolt.array(_x(), mesh).map(lambda v: v + 1)
    d.sum()
    d.mean()                                           # still readable
    assert d.toarray().shape == (16, 6, 4)


def test_donating_reduce_and_chunked_map(mesh):
    x = np.abs(_x())
    x[3] = 1.0                                         # drop the NaNs here
    with engine.donation(0):
        d = bolt.array(x, mesh).map(lambda v: v + 1)
        out = d.reduce(np.maximum)
        assert np.allclose(np.asarray(out.toarray()), (x + 1).max(axis=0))
        with pytest.raises(RuntimeError, match="donated"):
            d.cache()
        d2 = bolt.array(x, mesh).map(lambda v: v * 3)
        got = d2.chunk(size=(3,), axis=(0,)).map(lambda blk: blk * 2)
        assert np.allclose(got.unchunk().toarray(), x * 6)
        with pytest.raises(RuntimeError, match="donated"):
            d2.toarray()


# ----------------------------------------------------------------------
# fused single-pass filter→reduce
# ----------------------------------------------------------------------

def test_filter_stat_fuses_without_compaction(mesh):
    from bolt_tpu.tpu import array as array_mod
    x = _x()
    b = bolt.array(x, mesh)
    keep = _keep(x)
    n_compact = sum(1 for k in array_mod._JIT_CACHE
                    if k[0] == "filter-fused")
    out = b.filter(PRED).sum()
    got = np.asarray(out.toarray())       # first read dispatches (lazy)
    # ONE pass: the mask folded into the reduce — no compaction program
    assert sum(1 for k in array_mod._JIT_CACHE
               if k[0] == "filter-fused") == n_compact
    assert any(k[0] == "filter-stat" for k in array_mod._JIT_CACHE)
    assert np.allclose(got, keep.sum(axis=0))


@pytest.mark.parametrize("name", ["sum", "prod", "any", "all", "mean",
                                  "var", "std", "max", "min"])
def test_fused_filter_stat_parity(mesh, name):
    x = _x()
    b = bolt.array(x, mesh)
    keep = _keep(x)
    got = getattr(b.filter(PRED), name)()
    # the eager 3-pass oracle: resolve the compaction first, then reduce
    eager = b.filter(PRED)
    eager._resolve_fpending()
    want = getattr(eager, name)()
    assert np.allclose(np.asarray(got.toarray()),
                       np.asarray(want.toarray()), atol=1e-10)
    ref = getattr(keep, name)(axis=0) if hasattr(keep, name) else None
    if ref is not None:
        assert np.allclose(np.asarray(got.toarray()), ref, atol=1e-10)


def test_fused_filter_reduce_parity_and_nan_records(mesh):
    x = _x()                       # row 3 is NaN and must stay inert
    b = bolt.array(x, mesh)
    keep = _keep(x)
    got = b.filter(PRED).reduce(np.maximum)
    assert np.allclose(np.asarray(got.toarray()), np.maximum.reduce(keep))
    got2 = b.filter(PRED).reduce(lambda p, q: p + q)
    assert np.allclose(np.asarray(got2.toarray()), keep.sum(axis=0))


def test_fused_filter_all_false_mask(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    nothing = lambda v: v.sum() > 1e9
    assert np.allclose(np.asarray(b.filter(nothing).sum().toarray()),
                       np.zeros((6, 4)))
    assert np.isnan(np.asarray(b.filter(nothing).mean().toarray())).all()
    with pytest.raises(ValueError, match="zero-size"):
        b.filter(nothing).max()
    with pytest.raises(TypeError, match="empty"):
        b.filter(nothing).reduce(np.add)


def test_fused_filter_keepdims_and_ddof(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    keep = _keep(x)
    out = b.filter(PRED).sum(keepdims=True)
    assert np.asarray(out.toarray()).shape == (1, 6, 4)
    v = b.filter(PRED).var(ddof=1)
    assert np.allclose(np.asarray(v.toarray()), keep.var(axis=0, ddof=1),
                       atol=1e-8)


def test_deferred_filter_still_resolves_for_other_consumers(mesh):
    # non-reduction consumers get exactly the old pending semantics
    x = _x()
    b = bolt.array(x, mesh)
    keep = _keep(x)
    f = b.filter(PRED)
    assert f.pending
    assert f.dtype == x.dtype      # known without dispatching
    assert f.shape == keep.shape   # resolves
    assert not f.pending
    assert np.allclose(f.toarray(), keep)
    # toarray straight off the deferred state (batched fetch path)
    f2 = b.filter(PRED)
    assert np.allclose(f2.toarray(), keep)
    # map chains still consume filter output
    f3 = b.filter(PRED).map(lambda v: v * 2)
    assert np.allclose(f3.toarray(), keep * 2)


# ----------------------------------------------------------------------
# counters: consistent snapshots (ISSUE 2 satellite) + diagnostics feed
# ----------------------------------------------------------------------

def test_counters_snapshot_is_consistent_under_concurrent_increments():
    import threading
    n_threads, per_thread = 4, 500
    start = engine.counters()["diagnostics"]
    seen = []
    stop = threading.Event()

    def snapshotter():
        while not stop.is_set():
            seen.append(engine.counters()["diagnostics"])

    def hammer():
        for _ in range(per_thread):
            engine.record_diagnostics(1)

    snap = threading.Thread(target=snapshotter)
    snap.start()
    workers = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    stop.set()
    snap.join()
    # lock-protected increments: nothing lost, snapshots monotonic
    assert engine.counters()["diagnostics"] == start + n_threads * per_thread
    assert seen == sorted(seen)
    # and counters() returns a SNAPSHOT, not a live view
    c = engine.counters()
    c["diagnostics"] += 10 ** 6
    assert engine.counters()["diagnostics"] != c["diagnostics"]


def test_engine_counters_include_analysis_tallies(mesh):
    c = engine.counters()
    for key in ("diagnostics", "strict_checks", "strict_rejections"):
        assert key in c
    txt = profile.engine_report()
    assert "diagnostics" in txt and "strict_rejections" in txt


def test_fused_filter_donates_sole_owned_base(mesh):
    x = _x()
    keep = _keep(x)
    with engine.donation(0):
        d = bolt.array(x, mesh).filter(PRED)
        n0 = engine.counters()["donations"]
        out = d.sum()
        assert engine.counters()["donations"] == n0 + 1
        assert np.allclose(np.asarray(out.toarray()), keep.sum(axis=0))
        with pytest.raises(RuntimeError, match="donated"):
            d.toarray()


# ---------------------------------------------------------------------
# cross-tenant coalescing (ISSUE 8): concurrent identical builds and
# compiles collapse to ONE, counter-proven
# ---------------------------------------------------------------------

def test_concurrent_same_key_builds_coalesce(mesh):
    import threading
    import time as _time
    calls = []

    def builder():
        calls.append(1)
        _time.sleep(0.3)          # widen the race window: every other
        #                           thread must arrive mid-build
        return jax.jit(lambda t: t + 1)

    key = ("test-coalesce-build", object())
    c0 = engine.counters()
    outs = []

    def go():
        outs.append(engine.get(key, builder))

    threads = [threading.Thread(target=go, daemon=True) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    c1 = engine.counters()
    assert len(calls) == 1                    # the builder ran ONCE
    assert all(o is outs[0] for o in outs)    # everyone shares the entry
    assert c1["misses"] - c0["misses"] == 1
    # every lookup is accounted exactly once: 1 miss + 5 waits/hits
    assert (c1["hits"] - c0["hits"]
            + c1["coalesced_builds"] - c0["coalesced_builds"]) == 5


def test_concurrent_same_signature_compiles_once(mesh):
    import threading
    key = ("test-coalesce-compile", object())
    entry = engine.get(key, lambda: jax.jit(lambda t: t * 3))
    x = jnp.arange(8.0)
    c0 = engine.counters()
    outs = []

    def go():
        outs.append(np.asarray(entry(x)))

    threads = [threading.Thread(target=go, daemon=True) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    c1 = engine.counters()
    # ONE aot compile for the signature, however many threads raced it
    assert c1["aot_compiles"] - c0["aot_compiles"] == 1
    assert all(np.array_equal(o, np.arange(8.0) * 3) for o in outs)


def test_failed_build_wakes_waiters_who_rebuild(mesh):
    import threading
    import time as _time
    state = {"n": 0}

    def flaky_builder():
        state["n"] += 1
        if state["n"] == 1:
            _time.sleep(0.2)
            raise RuntimeError("first build fails")
        return jax.jit(lambda t: t - 1)

    key = ("test-coalesce-fail", object())
    results = []

    def go():
        try:
            results.append(engine.get(key, flaky_builder))
        except RuntimeError as exc:
            results.append(exc)

    threads = [threading.Thread(target=go, daemon=True) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    # exactly one caller saw the failure; the waiters rebuilt and share
    # a live entry (no thread hangs on the dead build's event)
    errs = [r for r in results if isinstance(r, RuntimeError)]
    live = [r for r in results if not isinstance(r, RuntimeError)]
    assert len(errs) == 1 and len(live) == 2
    assert live[0] is live[1]


# ---------------------------------------------------------------------
# per-tenant counter scoping (ISSUE 8)
# ---------------------------------------------------------------------

def test_tenant_scope_mirrors_engine_counters(mesh):
    t0 = engine.tenant_counters("unit-tenant")
    g0 = engine.counters()
    with engine.tenant("unit-tenant"):
        bolt.ones((8, 4), mesh).map(lambda v: v + 1).sum().toarray()
    t1 = engine.tenant_counters("unit-tenant")
    g1 = engine.counters()
    assert t1["dispatches"] > t0["dispatches"]
    # the tenant's tally is a SUBSET of the global one — never more
    assert t1["dispatches"] - t0["dispatches"] \
        <= g1["dispatches"] - g0["dispatches"]
    # outside the scope, nothing mirrors
    t2 = engine.tenant_counters("unit-tenant")
    bolt.ones((8, 4), mesh).sum().toarray()
    assert engine.tenant_counters("unit-tenant") == t2
