"""Pod fault-tolerance suite, the IN-PROCESS half (ISSUE 11).

Covers the liveness layer (``bolt_tpu.parallel.podwatch``) without a
cluster: transports, the heartbeat watch and its death latch, the
collective watchdog (``wait_ready``/``reraise``/``check``), the
watchdog barrier, the serve-layer integration (admission drain on peer
death, resume on reform, ``PeerLostError``-aware retries), the
checkpoint layer's pod ABORT format (``rendezvous=False``, advance-only
meta, torn-abort atomicity) and topology-remap load, and the BLT013
diagnostic.  "Peers" here are FAKES — the test writes their heartbeat
files — so everything runs single-process; the REAL 3→2 kill -9
scenario lives in tests/test_multihost.py on the localhost cluster.
"""

import os
import threading
import time

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu import _chaos, checkpoint, obs, serve
from bolt_tpu.parallel import multihost, podwatch
from bolt_tpu.parallel.podwatch import (FileTransport, PeerLostError,
                                        is_transport_error)

pytestmark = pytest.mark.podwatch


@pytest.fixture
def watchdir(tmp_path):
    """A clean watch per test: no stray callbacks, no running watch."""
    with podwatch._CB_LOCK:
        saved_d = dict(podwatch._DEATH_CBS)
        saved_r = dict(podwatch._REFORM_CBS)
        podwatch._DEATH_CBS.clear()
        podwatch._REFORM_CBS.clear()
    yield str(tmp_path)
    podwatch.stop()
    _chaos.clear()
    with podwatch._CB_LOCK:
        podwatch._DEATH_CBS.clear()
        podwatch._REFORM_CBS.clear()
        podwatch._DEATH_CBS.update(saved_d)
        podwatch._REFORM_CBS.update(saved_r)
    # the serve counters are a PROCESS-global registry group and
    # tests/test_serve.py asserts absolute totals — put back the zeros
    # this test's servers consumed
    from bolt_tpu.obs import metrics as _metrics
    reg = _metrics.registry()
    for name in list(reg.names()):
        if name == "serve" or name.startswith("serve/"):
            m = reg.get(name)
            if hasattr(m, "reset"):
                m.reset()


class _FakePeer:
    """A background thread impersonating pod process ``pid`` on the
    file transport: beats until told to die (or to say farewell)."""

    def __init__(self, transport, pid, interval=0.03):
        self.transport = transport
        self.pid = pid
        self.interval = interval
        self.stop_ev = threading.Event()
        self.seq = 0
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        while not self.stop_ev.is_set():
            self.seq += 1
            self.transport.beat(self.pid, self.seq)
            self.stop_ev.wait(self.interval)

    def kill(self):
        self.stop_ev.set()
        self.thread.join()

    def farewell(self):
        self.transport.farewell(self.pid)
        self.kill()


def _start(watchdir, nproc=2, pid=0, interval=0.05, timeout=0.4):
    assert podwatch.start(nproc, pid, dir=watchdir, interval=interval,
                          timeout=timeout)
    return podwatch._WATCH.transport


# ---------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------

def test_peerlost_error_attrs():
    e = PeerLostError("gone", peer=2, slab=7, phase="slab program")
    assert e.peer == 2 and e.slab == 7 and e.phase == "slab program"
    assert isinstance(e, RuntimeError)


def test_transport_error_classifier():
    assert is_transport_error(ValueError(
        "UNKNOWN: Gloo all-reduce failed: Connection closed by peer"))
    assert is_transport_error(RuntimeError(
        "UNAVAILABLE: failed to send RPC to coordination service"))
    assert not is_transport_error(ValueError("shape mismatch (3, 4)"))


def test_file_transport_roundtrip(tmp_path):
    t = FileTransport(str(tmp_path), epoch=3)
    t.beat(0, 1)
    t.beat(1, 5)
    assert t.read() == {0: 1, 1: 5}
    t.beat(1, 6)
    assert t.read()[1] == 6
    assert t.read_farewells() == set()
    t.farewell(1)
    assert t.read_farewells() == {1}
    # barrier markers
    t.barrier_mark("ck", 0, 0)
    t.barrier_mark("ck", 0, 1)
    assert t.barrier_seen("ck", 0) == {0, 1}
    t.barrier_mark("ck", 2, 0)
    t.barrier_sweep("ck", 2, 0)       # removes own generation-0 marker
    assert t.barrier_seen("ck", 0) == {1}


def test_watch_defaults_off_single_process(watchdir):
    assert podwatch.start(1, 0, dir=watchdir) is False
    assert not podwatch.active()
    assert podwatch.deadline() is None
    assert podwatch.dead_peers() == ()
    podwatch.check()                  # no-op without a watch
    podwatch.wait_ready(object())     # ditto
    assert podwatch.start(4, 0, dir=watchdir, timeout=0) is False


# ---------------------------------------------------------------------
# the death latch
# ---------------------------------------------------------------------

def test_peer_death_detected_and_latched(watchdir):
    deaths = []
    podwatch.on_peer_death(deaths.append)
    t = _start(watchdir)
    peer = _FakePeer(t, 1)
    try:
        deadline = time.monotonic() + 2.0
        while 1 not in {p for p, st in podwatch.peers().items()
                        if st["alive"]} and time.monotonic() < deadline:
            time.sleep(0.02)
        assert podwatch.peers()[1]["alive"]
        peer.kill()                   # the preemption
        t0 = time.monotonic()
        while not podwatch.dead_peers() and \
                time.monotonic() - t0 < 5 * 0.4:
            time.sleep(0.02)
        took = time.monotonic() - t0
        assert podwatch.dead_peers() == (1,)
        # the watchdog bound: verdict within 2x the deadline
        assert took < 2 * 0.4 + 0.2
        assert deaths == [1]
        assert podwatch.alive_peers() == (0,)
        with pytest.raises(PeerLostError) as ei:
            podwatch.check(phase="unit", slab=3)
        assert ei.value.peer == 1 and ei.value.slab == 3
    finally:
        peer.kill()


def test_farewelled_peer_is_not_dead(watchdir):
    deaths = []
    podwatch.on_peer_death(deaths.append)
    t = _start(watchdir)
    peer = _FakePeer(t, 1)
    try:
        time.sleep(0.15)
        peer.farewell()               # leaves for a reform: silent, alive
        time.sleep(1.2)               # >> timeout
        assert podwatch.dead_peers() == ()
        assert deaths == []
        assert 1 in podwatch.alive_peers()
    finally:
        peer.kill()


def test_mark_dead_and_callbacks_once(watchdir):
    deaths = []
    h = podwatch.on_peer_death(deaths.append)
    _start(watchdir, nproc=3)
    podwatch.mark_dead(2)
    podwatch.mark_dead(2)             # latched: fires once
    assert deaths == [2]
    podwatch.remove_callback(h)
    podwatch.mark_dead(1)
    assert deaths == [2]              # deregistered


def test_coordination_error_latch(watchdir):
    """The out-of-band coordination-failure door: a status naming a
    task latches that peer dead; an anonymous one latches coord_error
    (check() raises either way)."""
    deaths = []
    podwatch.on_peer_death(deaths.append)
    _start(watchdir, nproc=3)
    podwatch.coordination_error(
        "UNAVAILABLE: Task /job:jax_worker/replica:0/task:2 heartbeat "
        "timeout.")
    assert deaths == [2]
    with pytest.raises(PeerLostError):
        podwatch.check()


def test_heartbeat_chaos_seam(watchdir):
    _chaos.inject("podwatch.heartbeat", nth=2, times=1)
    _start(watchdir)
    deadline = time.monotonic() + 2.0
    while _chaos.stats("podwatch.heartbeat")[0] < 3 and \
            time.monotonic() < deadline:
        time.sleep(0.02)
    hits, trips = _chaos.stats("podwatch.heartbeat")
    assert hits >= 3 and trips == 1   # the raise was absorbed, the
    w = podwatch._WATCH               # watch kept beating
    assert w.beat_errors == 1


# ---------------------------------------------------------------------
# the collective watchdog
# ---------------------------------------------------------------------

class _NeverReady:
    def is_ready(self):
        return False


class _ReadyAfter:
    def __init__(self, n):
        self.n = n

    def is_ready(self):
        self.n -= 1
        return self.n <= 0


def test_wait_ready_returns_when_ready(watchdir):
    t = _start(watchdir)
    peer = _FakePeer(t, 1)
    try:
        podwatch.wait_ready(_ReadyAfter(3), phase="unit")
        import jax.numpy as jnp
        podwatch.wait_ready(jnp.arange(3.0) + 1)      # real jax leaves
    finally:
        peer.kill()


def test_wait_ready_raises_on_dead_peer(watchdir):
    _start(watchdir, timeout=0.3)
    # peer 1 never beats: latched dead ~one timeout after start
    with pytest.raises(PeerLostError) as ei:
        podwatch.wait_ready(_NeverReady(), phase="slab-partial sync",
                            slab=5)
    assert ei.value.slab == 5
    assert ei.value.peer == 1


def test_reraise_classifies_transport_errors(watchdir):
    _start(watchdir, timeout=0.2)
    podwatch.mark_dead(1)
    gloo = ValueError("UNKNOWN: Gloo all-reduce failed: Connection "
                      "closed by peer [127.0.0.1]:1234")
    with pytest.raises(PeerLostError) as ei:
        podwatch.reraise(gloo, phase="slab program", slab=2)
    assert ei.value.peer == 1
    assert ei.value.__cause__ is gloo
    # an unrelated error passes through untouched
    boom = ValueError("shape mismatch")
    podwatch._WATCH.dead.clear()
    with pytest.raises(ValueError, match="shape mismatch"):
        podwatch.reraise(boom, wait=False)


def test_reraise_classifies_secondary_deleted_array(watchdir):
    """A failed async collective invalidates its output buffers; the
    NEXT dispatch consuming them raises "Array has been deleted" — the
    one-step-removed shape of a dead peer.  It converts to
    PeerLostError only when the heartbeat actually latched someone."""
    _start(watchdir, timeout=0.2)
    deleted = RuntimeError("Array has been deleted with shape=float32[8].")
    assert podwatch.is_secondary_sign(deleted)
    assert not podwatch.is_transport_error(deleted)
    # nobody dead: the genuine deleted-array bug surfaces untouched
    with pytest.raises(RuntimeError, match="has been deleted"):
        podwatch.reraise(deleted, wait=False)
    # a latched dead peer: classified, chained, named
    podwatch.mark_dead(1)
    with pytest.raises(PeerLostError) as ei:
        podwatch.reraise(deleted, phase="slab program", slab=3)
    assert ei.value.peer == 1
    assert ei.value.__cause__ is deleted
    # the grace window: the peer latches dead WHILE reraise waits
    podwatch._WATCH.dead.clear()
    import threading
    t = threading.Timer(0.1, podwatch.mark_dead, args=(1,))
    t.start()
    try:
        with pytest.raises(PeerLostError) as ei:
            podwatch.reraise(deleted, phase="slab program", slab=4)
        assert ei.value.peer == 1
    finally:
        t.cancel()


def test_guard_contextmanager(watchdir):
    _start(watchdir, timeout=0.2)
    with podwatch.guard("unit"):
        pass                          # clean body passes through
    podwatch.mark_dead(1)
    with pytest.raises(PeerLostError):
        with podwatch.guard("unit"):
            raise AssertionError("body must not run on a latched peer")


# ---------------------------------------------------------------------
# the watchdog barrier
# ---------------------------------------------------------------------

def test_barrier_completes_with_live_peer(watchdir):
    t = _start(watchdir)
    peer = _FakePeer(t, 1)
    try:
        done = []

        def arrive_late():
            time.sleep(0.15)
            t.barrier_mark("sync", 0, 1)
            done.append(True)

        th = threading.Thread(target=arrive_late, daemon=True)
        th.start()
        podwatch.barrier("sync")
        th.join()
        assert done == [True]
        # generation counting: a SECOND barrier of the same name waits
        # for generation 1 markers, not the stale generation-0 ones
        t.barrier_mark("sync", 1, 1)
        podwatch.barrier("sync")
    finally:
        peer.kill()


def test_barrier_converts_dead_peer(watchdir):
    t = _start(watchdir, timeout=0.3)
    peer = _FakePeer(t, 1)
    try:
        time.sleep(0.1)
        peer.kill()                   # dies before ever arriving
        t0 = time.monotonic()
        with pytest.raises(PeerLostError) as ei:
            podwatch.barrier("ckpt_w4")
        assert time.monotonic() - t0 < 2 * 0.3 + 0.3
        assert ei.value.peer == 1
        assert "barrier" in (ei.value.phase or "")
    finally:
        peer.kill()


def test_multihost_barrier_routes_through_watch(watchdir, monkeypatch):
    """multihost.barrier hits the chaos seam and the podwatch path when
    a watch is armed (single-process short-circuits first, so the
    process count is faked)."""
    t = _start(watchdir)
    peer = _FakePeer(t, 1)
    try:
        monkeypatch.setattr(multihost, "process_count", lambda: 2)
        _chaos.inject("multihost.barrier", nth=1)
        with pytest.raises(_chaos.ChaosError):
            multihost.barrier("seamcheck")
        _chaos.clear()
        peer.kill()
        with pytest.raises(PeerLostError):
            multihost.barrier("deadcheck")
    finally:
        peer.kill()


# ---------------------------------------------------------------------
# serve integration: drain on death, resume on reform, retryable loss
# ---------------------------------------------------------------------

def test_serve_drains_and_resumes_on_pod_events(watchdir):
    t = _start(watchdir)
    peer = _FakePeer(t, 1)
    try:
        with serve.serving(workers=1, policy="reject") as sv:
            assert not sv.pod_paused()
            peer.kill()
            t0 = time.monotonic()
            while not sv.pod_paused() and time.monotonic() - t0 < 3:
                time.sleep(0.02)
            assert sv.pod_paused()
            assert sv.stats()["pod"]["paused"]
            assert sv.stats()["totals"]["peer_losses"] == 1
            with pytest.raises(serve.AdmissionError,
                               match="pod peer 1 was lost"):
                sv.submit(lambda: 42)
            podwatch.notify_reform()  # the reform completed
            assert not sv.pod_paused()
            assert sv.submit(lambda: 42).result(timeout=30) == 42
    finally:
        peer.kill()


def test_serve_retry_waits_out_the_reform(watchdir):
    t = _start(watchdir)
    peer = _FakePeer(t, 1)
    try:
        with serve.serving(workers=1) as sv:
            attempts = []

            def flaky():
                attempts.append(1)
                if len(attempts) == 1:
                    podwatch.mark_dead(1)     # the pod outage
                    raise PeerLostError("lost", peer=1)
                return "recovered"

            fut = sv.submit(flaky, tenant="t", retries=1)
            time.sleep(0.3)
            assert not fut.done()     # held behind the drain
            podwatch.notify_reform()
            assert fut.result(timeout=30) == "recovered"
            assert len(attempts) == 2
            assert sv.stats()["totals"]["retried"] == 1
    finally:
        peer.kill()


def test_serve_queue_policy_blocks_submit_during_drain(watchdir):
    t = _start(watchdir)
    peer = _FakePeer(t, 1)
    try:
        with serve.serving(workers=1, policy="queue") as sv:
            peer.kill()
            t0 = time.monotonic()
            while not sv.pod_paused() and time.monotonic() - t0 < 3:
                time.sleep(0.02)
            got = []

            def submit_blocked():
                got.append(sv.submit(lambda: "ok").result(timeout=30))

            th = threading.Thread(target=submit_blocked, daemon=True)
            th.start()
            time.sleep(0.3)
            assert got == []          # backpressure while draining
            podwatch.notify_reform()
            th.join(timeout=30)
            assert got == ["ok"]
    finally:
        peer.kill()


def test_serve_close_terminates_during_held_retry(watchdir):
    """close(wait=True) must terminate even while a PeerLostError
    retry is held behind the admission drain and the reform never
    comes — the hold loop yields to a stopping server."""
    t = _start(watchdir)
    peer = _FakePeer(t, 1)
    try:
        sv = serve.start(workers=1)
        try:
            def doomed():
                podwatch.mark_dead(1)
                raise PeerLostError("lost", peer=1)

            fut = sv.submit(doomed, tenant="t", retries=3)
            t0 = time.monotonic()
            while not sv.pod_paused() and time.monotonic() - t0 < 3:
                time.sleep(0.02)
            assert sv.pod_paused()
        finally:
            t0 = time.monotonic()
            serve.stop(wait=True)     # must NOT deadlock
        assert time.monotonic() - t0 < 10
        assert isinstance(fut.exception(timeout=1), RuntimeError)
    finally:
        peer.kill()


def test_sustained_transport_failure_is_a_liveness_verdict(watchdir):
    """A transport that stops answering for a whole deadline (the
    coordinator-death case under the KV transport) latches a
    coordination error, so guarded syncs raise instead of polling a
    silent watch forever."""
    import shutil
    _start(watchdir, timeout=0.3)
    time.sleep(0.1)
    shutil.rmtree(watchdir)           # the store is gone: every beat
    t0 = time.monotonic()             # now fails
    while time.monotonic() - t0 < 5 * 0.3:
        try:
            podwatch.check(phase="unit")
        except PeerLostError as e:
            assert "liveness transport failing" in str(e)
            break
        time.sleep(0.05)
    else:
        raise AssertionError("transport failure never latched")


# ---------------------------------------------------------------------
# span hygiene
# ---------------------------------------------------------------------

def test_watch_leaks_no_spans(watchdir):
    obs.clear()
    obs.enable()
    try:
        t = _start(watchdir)
        peer = _FakePeer(t, 1)
        time.sleep(0.3)
        peer.kill()
        deadline = time.monotonic() + 2.0
        while not podwatch.dead_peers() and time.monotonic() < deadline:
            time.sleep(0.02)
        with pytest.raises(PeerLostError):
            podwatch.barrier("leakcheck")
        podwatch.stop()
        assert obs.active_count() == 0
    finally:
        obs.disable()


# ---------------------------------------------------------------------
# checkpoint: pod abort format + topology remap
# ---------------------------------------------------------------------

@pytest.fixture
def pod3(monkeypatch):
    """Fake a 3-process runtime for the checkpoint-layer units: the
    barriers are no-ops (no real peers) and the process index is a
    settable cell."""
    cell = {"pid": 0}
    monkeypatch.setattr(multihost, "process_count", lambda: 3)
    monkeypatch.setattr(multihost, "process_index",
                        lambda: cell["pid"])
    monkeypatch.setattr(multihost, "barrier", lambda name: None)
    return cell


def _save_all(tmp_path, pod3, fp, slabs, records, val, nproc=3):
    for pid in range(nproc):
        pod3["pid"] = pid
        checkpoint.stream_save(str(tmp_path), fp, slabs, records,
                               ([np.full(3, val, np.float32)], None),
                               multiprocess=True)
    pod3["pid"] = 0


def test_pod_abort_save_meta_advances_only(tmp_path, pod3):
    fp = ("fp-abort",)
    _save_all(tmp_path, pod3, fp, 4, 48, 4.0)
    # an abort at a LOWER watermark must not regress the meta
    checkpoint.stream_save(str(tmp_path), fp, 3, 36,
                           ([np.full(3, 3.0, np.float32)], None),
                           multiprocess=True, rendezvous=False)
    got = checkpoint.stream_load(str(tmp_path), fp, multiprocess=True)
    assert got[0] == 4
    # an abort at a HIGHER watermark advances it (state-first, no
    # barrier, "abort" recorded)
    checkpoint.stream_save(str(tmp_path), fp, 5, 60,
                           ([np.full(3, 5.0, np.float32)], None),
                           multiprocess=True, rendezvous=False)
    got = checkpoint.stream_load(str(tmp_path), fp, multiprocess=True)
    assert got[0] == 5
    assert np.array_equal(got[2][0][0], np.full(3, 5.0, np.float32))
    assert checkpoint._read_meta(str(tmp_path)).get("abort") is True


def test_torn_abort_never_flips_meta(tmp_path, pod3):
    """A fault between the abort's state write and its meta rename
    (the checkpoint.meta chaos seam) leaves the OLD meta intact and
    loadable — meta can never name a watermark whose write tore."""
    fp = ("fp-torn",)
    _save_all(tmp_path, pod3, fp, 4, 48, 4.0)
    _chaos.inject("checkpoint.meta", nth=1)
    try:
        with pytest.raises(_chaos.ChaosError):
            checkpoint.stream_save(
                str(tmp_path), fp, 6, 72,
                ([np.full(3, 6.0, np.float32)], None),
                multiprocess=True, rendezvous=False)
    finally:
        _chaos.clear()
    got = checkpoint.stream_load(str(tmp_path), fp, multiprocess=True)
    assert got[0] == 4                # the old checkpoint still stands
    assert np.array_equal(got[2][0][0], np.full(3, 4.0, np.float32))


def test_topology_remap_load_after_shrink(tmp_path, pod3, monkeypatch):
    """A checkpoint cut by a 3-process pod loads on a 2-process (and a
    1-process) topology: the fold partials are replicated global
    values, so any surviving shard file is a complete resume point —
    and the remap is reported through ``info``."""
    fp = ("fp-remap",)
    _save_all(tmp_path, pod3, fp, 4, 48, 7.0)
    # the shrunk pod: 2 processes; old p1's file may even be missing
    os.remove(os.path.join(str(tmp_path), "stream_state.p1.w4.npz"))
    monkeypatch.setattr(multihost, "process_count", lambda: 2)
    for newpid in (0, 1):
        pod3["pid"] = newpid
        info = {}
        got = checkpoint.stream_load(str(tmp_path), fp,
                                     multiprocess=True, info=info)
        assert got is not None and got[0] == 4 and got[1] == 48
        assert np.array_equal(got[2][0][0],
                              np.full(3, 7.0, np.float32))
        assert info == {"remapped_from": 3}
    # ...and on a single process (multiprocess=False -> nproc 1)
    monkeypatch.setattr(multihost, "process_count", lambda: 1)
    pod3["pid"] = 0
    info = {}
    got = checkpoint.stream_load(str(tmp_path), fp, multiprocess=False,
                                 info=info)
    assert got is not None and got[0] == 4
    assert info == {"remapped_from": 3}
    # a resumed run's next save records the remap for the audit trail
    monkeypatch.setattr(multihost, "process_count", lambda: 2)
    for newpid in (0, 1):
        pod3["pid"] = newpid
        checkpoint.stream_save(str(tmp_path), fp, 6, 72,
                               ([np.full(3, 9.0, np.float32)], None),
                               multiprocess=True, remap_from=3)
    meta = checkpoint._read_meta(str(tmp_path))
    assert meta["nproc"] == 2 and meta["remapped_from"] == 3
    # clearing on the SHRUNK pod sweeps every pid's shard files (pid 0
    # sweeps the dead peers' leftovers too)
    pod3["pid"] = 0
    checkpoint.stream_clear(str(tmp_path), multiprocess=True)
    assert [p for p in os.listdir(str(tmp_path))
            if p.startswith("stream_")] == []


def test_single_process_clear_sweeps_pod_files(tmp_path, pod3,
                                               monkeypatch):
    fp = ("fp-sweep",)
    _save_all(tmp_path, pod3, fp, 2, 24, 1.0)
    monkeypatch.setattr(multihost, "process_count", lambda: 1)
    checkpoint.stream_clear(str(tmp_path), multiprocess=False)
    assert [p for p in os.listdir(str(tmp_path))
            if p.startswith("stream_")] == []


# ---------------------------------------------------------------------
# BLT013: multi-process stream without a recovery path
# ---------------------------------------------------------------------

ADD1 = lambda v: v + 1  # noqa: E731 — module-level: stable fingerprint


def _streamed():
    x = np.zeros((8, 4), np.float32)
    return bolt.fromcallback(lambda i: x[i], (8, 4), mode="tpu",
                             dtype=np.float32, chunks=4).map(ADD1)


def _fake_pod(monkeypatch):
    """Make the CHECKER see a 2-process mesh on this 1-process host —
    applied AFTER the pipeline is built (the factory itself routes
    per_process ingest off the topology, and building under the fake
    would materialise instead of stream).  The BLT012 divisibility
    rule is quieted — it has its own tests."""
    monkeypatch.setattr(multihost, "mesh_process_count", lambda mesh: 2)
    monkeypatch.setattr(multihost, "slab_divisibility_error",
                        lambda *a: None)


def test_blt013_no_checkpoint_dir(monkeypatch):
    from bolt_tpu import analysis
    arr = _streamed()
    _fake_pod(monkeypatch)
    rep = analysis.check(arr)
    assert rep.has("BLT013")
    d = [d for d in rep.diagnostics if d.code == "BLT013"][0]
    assert d.severity == "warning"
    assert "NO checkpoint dir" in d.message
    assert rep.ok                     # warning, not error


def test_blt013_quiet_with_checkpoint_dir(monkeypatch, tmp_path):
    from bolt_tpu import analysis, stream
    arr = _streamed()
    _fake_pod(monkeypatch)
    with stream.resumable(str(tmp_path)):
        rep = analysis.check(arr)
    assert not rep.has("BLT013")


def test_blt013_sub_pod_mesh(monkeypatch, tmp_path):
    from bolt_tpu import analysis, stream
    arr = _streamed()
    _fake_pod(monkeypatch)
    monkeypatch.setattr(multihost, "process_count", lambda: 4)
    with stream.resumable(str(tmp_path)):
        rep = analysis.check(arr)
    assert rep.has("BLT013")
    d = [d for d in rep.diagnostics if d.code == "BLT013"][0]
    assert "SUB-POD" in d.message


def test_explain_shows_recovery_plan(monkeypatch, tmp_path):
    from bolt_tpu import analysis, stream
    arr = _streamed()
    arr2 = _streamed()
    _fake_pod(monkeypatch)
    txt = analysis.explain(arr)
    assert "recovery plan" in txt
    assert "PeerLostError" in txt
    assert "BLT013" in txt            # the no-checkpoint shape
    with stream.resumable(str(tmp_path)):
        txt2 = analysis.explain(arr2)
    assert "resume topology" in txt2 and str(tmp_path) in txt2


def test_config_reports_watchdog(watchdir):
    cfg = podwatch.config()
    assert set(cfg) == {"timeout", "interval", "transport", "nproc"}
    _start(watchdir, nproc=3, interval=0.07, timeout=0.9)
    cfg = podwatch.config()
    assert cfg["timeout"] == 0.9 and cfg["interval"] == 0.07
    assert cfg["transport"] == "file" and cfg["nproc"] == 3


def test_blt108_exempts_podwatch():
    """The heartbeat thread lives in a blessed BLT108 home."""
    from bolt_tpu.analysis import astlint
    assert any(e.endswith(os.path.join("parallel", "podwatch.py"))
               for e in astlint._EXEMPT["BLT108"])
