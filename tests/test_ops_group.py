"""Segmented (grouped) reductions — the reduceByKey analog — and
bincount, on both backends vs a NumPy mirror."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.ops import bincount, segment_reduce
from bolt_tpu.utils import allclose


def _x(shape=(12, 4, 3), seed=80):
    return np.random.RandomState(seed).randn(*shape)


def _mirror(x, labels, nseg, op):
    out = []
    for g in range(nseg):
        rows = x[labels == g]
        if len(rows) == 0:
            if op in ("sum", "mean"):
                out.append(np.zeros(x.shape[1:]))
            else:
                out.append(np.full(x.shape[1:],
                                   -np.inf if op == "max" else np.inf))
        elif op == "sum":
            out.append(rows.sum(axis=0))
        elif op == "mean":
            out.append(rows.mean(axis=0))
        elif op == "max":
            out.append(rows.max(axis=0))
        else:
            out.append(rows.min(axis=0))
    return np.stack(out)


@pytest.mark.parametrize("op", ["sum", "mean", "max", "min"])
def test_segment_reduce_parity(mesh, op):
    x = _x()
    labels = np.array([0, 2, 1, 0, 2, 2, 1, 0, 3, 3, 0, 2])
    expected = _mirror(x, labels, 4, op)
    for b in (bolt.array(x), bolt.array(x, mesh)):
        out = segment_reduce(b, labels, op=op)
        assert out.shape == (4,) + x.shape[1:]
        assert allclose(out.toarray(), expected), (b.mode, op)
    t = segment_reduce(bolt.array(x, mesh), labels, op=op)
    assert t.split == 1


def test_segment_reduce_empty_group_and_num_segments(mesh):
    x = _x((6, 2))
    labels = np.array([0, 0, 3, 3, 3, 0])       # groups 1, 2 empty
    for b in (bolt.array(x), bolt.array(x, mesh)):
        out = np.asarray(segment_reduce(b, labels, num_segments=5).toarray())
        assert out.shape == (5, 2)
        assert np.allclose(out[1], 0) and np.allclose(out[2], 0)
        assert np.allclose(out[4], 0)
        assert np.allclose(out[0], x[labels == 0].sum(axis=0))


def test_segment_reduce_deferred_chain(mesh):
    x = _x()
    labels = np.arange(12) % 3
    b = bolt.array(x, mesh).map(lambda v: v * 2)   # deferred chain fuses in
    out = segment_reduce(b, labels, op="sum")
    assert allclose(out.toarray(), _mirror(x * 2, labels, 3, "sum"))


def test_segment_reduce_int_mean(mesh):
    x = np.arange(24, dtype=np.int64).reshape(8, 3)
    labels = np.array([0, 1, 0, 1, 0, 1, 0, 1])
    for b in (bolt.array(x), bolt.array(x, mesh)):
        out = np.asarray(segment_reduce(b, labels, op="mean").toarray())
        assert np.issubdtype(out.dtype, np.floating)
        assert np.allclose(out, _mirror(x.astype(float), labels, 2, "mean"))


def test_segment_reduce_errors(mesh):
    b = bolt.array(_x(), mesh)
    with pytest.raises(ValueError):
        segment_reduce(b, np.arange(5))           # wrong length
    with pytest.raises(ValueError):
        segment_reduce(b, np.arange(12), op="prod")
    with pytest.raises(ValueError):
        segment_reduce(b, np.arange(12) - 1)      # negative label
    with pytest.raises(ValueError):
        segment_reduce(b, np.arange(12), num_segments=5)  # label 11 > 4
    with pytest.raises(ValueError):
        segment_reduce(b, np.arange(12.0))        # non-integer labels


def test_bincount_parity(mesh):
    x = np.random.RandomState(81).randint(0, 9, size=(16, 5))
    for b in (bolt.array(x), bolt.array(x, mesh)):
        got = bincount(b)
        assert got.dtype == np.int64
        assert np.array_equal(got, np.bincount(x.reshape(-1)))
        got = bincount(b, minlength=20)
        assert np.array_equal(got, np.bincount(x.reshape(-1), minlength=20))
    with pytest.raises(TypeError):
        bincount(bolt.array(_x(), mesh))          # floats rejected
    with pytest.raises(ValueError):
        bincount(bolt.array(x - 5, mesh))         # negatives rejected


def test_segment_reduce_multi_key_axes(mesh):
    # split > 1: labels still address axis-0 records; the other key axes
    # ride along in the value block on BOTH backends
    x = _x((4, 2, 3, 2))
    labels = np.array([0, 1, 0, 1])
    lo = segment_reduce(bolt.array(x), labels, op="sum")
    tp = segment_reduce(bolt.array(x, mesh, axis=(0, 1)), labels, op="sum")
    expected = np.stack([x[labels == g].sum(axis=0) for g in range(2)])
    assert allclose(lo.toarray(), expected)
    assert allclose(tp.toarray(), expected)


def test_segment_reduce_device_labels_no_host_bounce(mesh, monkeypatch):
    # a jax.Array (or bolt TPU array) labels input must stay on device:
    # the label DATA never passes through np.asarray (ADVICE r2 / VERDICT
    # r2 #4 — through the real chip's ~17 MB/s tunnel the bounce costs
    # seconds); only the two-scalar range validation syncs
    import jax.numpy as jnp
    from bolt_tpu.ops import group
    x = _x()
    labels_host = np.array([0, 2, 1, 0, 2, 2, 1, 0, 3, 3, 0, 2])
    expected = _mirror(x, labels_host, 4, "sum")
    dev_labels = jnp.asarray(labels_host)

    bounced = []
    real_asarray = np.asarray

    def spy(a, *args, **kwargs):
        if a is dev_labels:
            bounced.append(a)
        return real_asarray(a, *args, **kwargs)

    monkeypatch.setattr(group.np, "asarray", spy)
    b = bolt.array(x, mesh)
    for nseg in (None, 4):
        out = segment_reduce(b, dev_labels, num_segments=nseg, op="sum")
        assert allclose(out.toarray(), expected)
    assert not bounced
    # bolt TPU-array labels unwrap to the device array, same guarantee
    blabels = bolt.array(labels_host, mesh)
    out = segment_reduce(b, blabels, op="sum")
    assert allclose(out.toarray(), expected)
    # device labels still validate range
    with pytest.raises(ValueError):
        segment_reduce(b, jnp.asarray(labels_host - 1))
    with pytest.raises(ValueError):
        segment_reduce(b, dev_labels, num_segments=2)
    # foreign-mesh bolt labels are rejected loudly, like binary operands
    import jax
    other_mesh = jax.make_mesh((4, 2), ("a", "b"))
    with pytest.raises(ValueError, match="different meshes"):
        segment_reduce(b, bolt.array(labels_host, other_mesh))


def test_bincount_chunked_accumulation(mesh, monkeypatch):
    # force the x32-wraparound chunked path (ADVICE r2): int32 partials
    # per chunk, host-int64 combine — result identical to the one-shot
    # program at any chunk size, including a ragged tail
    from bolt_tpu.ops import group
    x = np.random.RandomState(84).randint(0, 9, size=(16, 5))
    expected = np.bincount(x.reshape(-1), minlength=11)
    monkeypatch.setattr(group, "_BINCOUNT_CHUNK", 17)   # 80 elems -> 5 chunks
    got = bincount(bolt.array(x, mesh), minlength=11)
    assert got.dtype == np.int64
    assert np.array_equal(got, expected)
    monkeypatch.setattr(group, "_BINCOUNT_CHUNK", 80)   # exact fit: no chunking
    assert np.array_equal(bincount(bolt.array(x, mesh), minlength=11), expected)


def test_segment_reduce_one_program_many_labels(mesh):
    # labels are a traced argument: distinct label vectors reuse ONE
    # compiled program (keying on label bytes would recompile per vector)
    from bolt_tpu.tpu.array import _JIT_CACHE
    x = _x()
    b = bolt.array(x, mesh)
    segment_reduce(b, np.arange(12) % 4, num_segments=4, op="sum")
    n_before = sum(1 for k in _JIT_CACHE if k[0] == "segreduce")
    segment_reduce(b, np.arange(12) % 2 * 3, num_segments=4, op="sum")
    segment_reduce(b, np.zeros(12, dtype=int), num_segments=4, op="sum")
    assert sum(1 for k in _JIT_CACHE if k[0] == "segreduce") == n_before


def test_bincount_empty(mesh):
    e = bolt.array(np.zeros((0, 3), np.int64), mesh)
    assert np.array_equal(bincount(e, minlength=4), np.zeros(4, np.int64))
    assert np.array_equal(bincount(bolt.array(np.zeros((0,), np.int64)),
                                   minlength=2), np.zeros(2, np.int64))


def test_unique_parity(mesh):
    from bolt_tpu.ops import unique
    x = np.random.RandomState(82).randint(0, 7, size=(9, 4)).astype(np.float64)
    for b in (bolt.array(x), bolt.array(x, mesh)):
        u = unique(b)
        assert np.array_equal(u, np.unique(x)), b.mode
        u, c = unique(b, return_counts=True)
        un, cn = np.unique(x, return_counts=True)
        assert np.array_equal(u, un) and np.array_equal(c, cn), b.mode
    # ints, all-same, and deferred chains
    i = bolt.array(np.full((4, 3), 5), mesh)
    u, c = unique(i, return_counts=True)
    assert np.array_equal(u, [5]) and np.array_equal(c, [12])
    m = bolt.array(x, mesh).map(lambda v: v * 0 + 2.0)
    assert np.array_equal(unique(m), [2.0])
    # empty
    e = bolt.array(np.zeros((0, 3)), mesh)
    u, c = unique(e, return_counts=True)
    assert u.size == 0 and c.size == 0


def test_unique_nan_semantics(mesh):
    from bolt_tpu.ops import unique
    x = np.array([[1.0, np.nan], [np.nan, 1.0]])
    un, cn = np.unique(x, return_counts=True)
    for b in (bolt.array(x), bolt.array(x, mesh)):
        u, c = unique(b, return_counts=True)
        # modern numpy collapses NaNs to one entry; counts aggregate
        assert u.shape == un.shape, b.mode
        assert np.isnan(u[-1]) and u[0] == 1.0
        assert np.array_equal(c, cn), b.mode


def test_topk_parity(mesh):
    from bolt_tpu.ops import topk
    x = np.random.RandomState(83).randn(8, 6, 5)
    for b in (bolt.array(x), bolt.array(x, mesh)):
        for axis in (-1, 0, 1):
            v, i = topk(b, 3, axis=axis)
            moved = np.moveaxis(x, axis, -1)
            ref_i = np.argsort(-moved, axis=-1, kind="stable")[..., :3]
            ref_v = np.take_along_axis(moved, ref_i, axis=-1)
            assert allclose(v.toarray(), np.moveaxis(ref_v, -1, axis)), (b.mode, axis)
            assert np.array_equal(np.asarray(i.toarray()),
                                  np.moveaxis(ref_i, -1, axis)), (b.mode, axis)
    t, _ = topk(bolt.array(x, mesh), 3, axis=2)
    assert t.split == 1 and t.shape == (8, 6, 3)
    # key-axis topk keeps the key role
    t, _ = topk(bolt.array(x, mesh), 2, axis=0)
    assert t.split == 1 and t.shape == (2, 6, 5)
    # ties: lower index first on both backends
    z = np.zeros((4, 4))
    for b in (bolt.array(z), bolt.array(z, mesh)):
        _, i = topk(b, 2)
        assert np.array_equal(np.asarray(i.toarray()), np.tile([0, 1], (4, 1)))


def test_topk_errors(mesh):
    from bolt_tpu.ops import topk
    b = bolt.array(_x(), mesh)
    with pytest.raises(ValueError):
        topk(b, 0)
    with pytest.raises(ValueError):
        topk(b, 99, axis=0)
    with pytest.raises(ValueError):
        topk(b, 1, axis=9)
    with pytest.raises(TypeError):
        topk(b, 1, axis=1.5)
    # deferred chain fuses in
    v, _ = topk(bolt.array(_x(), mesh).map(lambda r: -r), 2, axis=0)
    moved = np.moveaxis(-_x(), 0, -1)
    ref = np.moveaxis(np.take_along_axis(
        moved, np.argsort(-moved, axis=-1, kind="stable")[..., :2], -1), -1, 0)
    assert allclose(v.toarray(), ref)


def test_topk_dtype_and_nan_parity(mesh):
    # the review's repro set: unsigned wrap, INT_MIN, bools, NaNs — both
    # backends must agree with lax.top_k semantics
    from bolt_tpu.ops import topk
    cases = [
        np.array([[5, 0, 3]], dtype=np.uint32),
        np.array([[np.iinfo(np.int32).min, 4, -2]], dtype=np.int32),
        np.array([[True, False, True]]),
        np.array([[1.0, np.nan, 3.0, 2.0]]),
    ]
    for x in cases:
        lo_v, lo_i = topk(bolt.array(x), 2)
        tp_v, tp_i = topk(bolt.array(x, mesh), 2)
        lv, tv = np.asarray(lo_v.toarray()), np.asarray(tp_v.toarray())
        assert np.array_equal(lv, tv, equal_nan=True), (x.dtype, lv, tv)
        assert np.array_equal(np.asarray(lo_i.toarray()),
                              np.asarray(tp_i.toarray())), x.dtype
    with pytest.raises(TypeError):
        topk(bolt.array(cases[0], mesh), 2.7)


def test_segment_reduce_matmul_path(mesh):
    """Round-5: the one-hot MXU form (auto-picked for small segment
    counts) must match the scatter combine and the oracle exactly-
    enough, with numpy semantics for non-finite records preserved by
    the runtime fallback."""
    from bolt_tpu.ops import segment_reduce
    rs = np.random.RandomState(33)
    x = rs.randn(32, 6, 4)
    lab = rs.randint(0, 5, 32)
    b, lo = bolt.array(x, mesh), bolt.array(x)
    for op in ("sum", "mean"):
        gm = np.asarray(segment_reduce(
            b, lab, num_segments=5, op=op, method="matmul").toarray())
        gs = np.asarray(segment_reduce(
            b, lab, num_segments=5, op=op, method="scatter").toarray())
        e = np.asarray(segment_reduce(
            lo, lab, num_segments=5, op=op).toarray())
        assert np.allclose(gm, gs, rtol=1e-6, atol=1e-9)
        assert np.allclose(gm, e, rtol=1e-6, atol=1e-9)
    # per-call precision kwarg and the scoped policy both serve
    gm = segment_reduce(b, lab, num_segments=5, method="matmul",
                        precision="high")
    with bolt.precision("default"):
        gd = segment_reduce(b, lab, num_segments=5, method="matmul")
    assert np.allclose(np.asarray(gm.toarray()), np.asarray(gd.toarray()),
                       rtol=1e-5, atol=1e-8)


def test_segment_reduce_matmul_nonfinite_fallback(mesh):
    """0 x NaN would poison whole value columns through the one-hot
    matmul; the fused isfinite guard must fall back to scatter
    semantics at runtime — NaN/Inf stay confined to their own
    segment."""
    from bolt_tpu.ops import segment_reduce
    rs = np.random.RandomState(34)
    x = rs.randn(16, 5)
    x[3, 2] = np.nan
    x[7, 1] = np.inf
    x[9, 1] = -np.inf
    lab = rs.randint(0, 4, 16)
    b, lo = bolt.array(x, mesh), bolt.array(x)
    g = np.asarray(segment_reduce(
        b, lab, num_segments=4, method="matmul").toarray())
    e = np.asarray(segment_reduce(lo, lab, num_segments=4).toarray())
    assert np.array_equal(np.isnan(g), np.isnan(e))
    assert np.array_equal(np.isposinf(g), np.isposinf(e))
    assert np.array_equal(np.isneginf(g), np.isneginf(e))
    fin = np.isfinite(e)
    assert np.allclose(g[fin], e[fin])


def test_segment_reduce_method_validation(mesh):
    from bolt_tpu.ops import segment_reduce
    b = bolt.array(np.ones((8, 3), np.int32), mesh)
    with pytest.raises(ValueError, match="method"):
        segment_reduce(b, [0] * 8, num_segments=1, method="magic")
    # int sum cannot ride the (inexact) matmul; int MEAN promotes first
    with pytest.raises(ValueError, match="matmul"):
        segment_reduce(b, [0] * 8, num_segments=1, method="matmul")
    out = segment_reduce(b, [0] * 8, num_segments=1, op="mean",
                         method="matmul")
    assert np.allclose(np.asarray(out.toarray()), 1.0)
    with pytest.raises(ValueError, match="matmul"):
        segment_reduce(bolt.array(np.ones((4, 2)), mesh), [0] * 4,
                       num_segments=1, op="max", method="matmul")
    # the SAME invalid call rejects identically on the local oracle
    with pytest.raises(ValueError, match="matmul"):
        segment_reduce(bolt.array(np.ones((4, 2))), [0] * 4,
                       num_segments=1, op="max", method="matmul")
    # empty leading axis: forced matmul degrades to the (identical)
    # zeros result instead of crashing in a 0-size reshape
    z = bolt.array(np.zeros((0, 3)), mesh)
    out = segment_reduce(z, np.array([], dtype=np.int64), num_segments=4,
                         method="matmul")
    assert out.shape == (4, 3) and not np.asarray(out.toarray()).any()
