"""Elementwise operator tests (a deliberate superset of the reference: its
Spark array routes elementwise math through ``map`` — SURVEY §2.2)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu._compat import OLD_JAX
from bolt_tpu.utils import allclose


def _x():
    rs = np.random.RandomState(12)
    return rs.randn(8, 4, 5)


def test_scalar_ops(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    assert allclose((b + 1).toarray(), x + 1)
    assert allclose((1 + b).toarray(), 1 + x)
    assert allclose((b - 2).toarray(), x - 2)
    assert allclose((2 - b).toarray(), 2 - x)
    assert allclose((b * 3).toarray(), x * 3)
    assert allclose((b / 2).toarray(), x / 2)
    assert allclose((2 / (b + 10)).toarray(), 2 / (x + 10))
    assert allclose((b ** 2).toarray(), x ** 2)
    assert allclose((-b).toarray(), -x)
    assert allclose(abs(b).toarray(), abs(x))


def test_scalar_ops_defer(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    m = (b + 1) * 2 - 3
    assert m.deferred  # scalar ops fuse into the map chain
    assert allclose(m.toarray(), (x + 1) * 2 - 3)


def test_array_operand(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    other = np.random.RandomState(13).randn(*x.shape)
    assert allclose((b + other).toarray(), x + other)
    assert allclose((b * other).toarray(), x * other)
    # broadcasting into the full shape
    row = np.random.RandomState(14).randn(5)
    assert allclose((b + row).toarray(), x + row)
    with pytest.raises(ValueError):
        b + np.ones((9, 1, 1))  # incompatible shapes still reject


def test_array_operand_broadcast_outgrows_self(mesh):
    """numpy broadcasting is symmetric: np.ones(8) * b_scalar outgrows
    the device operand (this is how np.fft.fftfreq(n, d_device) is
    served compositionally).  Keys survive only while they stay the
    leading axes with unchanged lengths."""
    x = _x()
    b = bolt.array(x, mesh)
    s = b.mean(axis=(0, 1, 2))         # 0-d device scalar
    out = np.ones(8) * s
    assert isinstance(out, type(b)) and out.split == 0
    assert allclose(out.toarray(), np.ones(8) * x.mean())
    assert allclose((np.arange(6.0) + s).toarray(),
                    np.arange(6.0) + x.mean())
    # value-dim growth keeps the keys
    col = bolt.array(x[:, :, :1], mesh)
    grown = col * np.ones(5)
    assert grown.split == 1 and grown.shape == (8, 4, 5)
    assert allclose(grown.toarray(), x[:, :, :1] * np.ones(5))
    # leading-dim growth replicates
    led = b + np.ones((3, 8, 4, 5))
    assert led.split == 0
    assert allclose(led.toarray(), x + np.ones((3, 8, 4, 5)))


def test_bolt_operand(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    c = bolt.array(x * 2, mesh)
    out = b + c
    assert out.split == 1
    assert allclose(out.toarray(), x * 3)
    # local bolt array operand
    out = b + bolt.array(np.ones_like(x))
    assert allclose(out.toarray(), x + 1)


def test_comparisons(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    assert allclose((b > 0).toarray(), x > 0)
    assert allclose((b <= 0.5).toarray(), x <= 0.5)
    assert (b == b).toarray().all()
    assert not (b != b).toarray().any()
    assert (b > 0).dtype == np.bool_


def test_value_shaped_result_ops(mesh):
    # operators on a split=0 reduction result
    x = _x()
    s = bolt.array(x, mesh).sum()
    assert s.split == 0
    assert allclose((s + 1).toarray(), x.sum(axis=0) + 1)
    assert allclose(abs(s).toarray(), abs(x.sum(axis=0)))


def test_mixed_expression(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = ((b + 1) * (b - 1)).mean()
    assert allclose(out.toarray(), ((x + 1) * (x - 1)).mean(axis=0))


def test_numpy_left_operand_reflects(mesh):
    # numpy must defer to __radd__ etc. instead of gathering via __array__
    x = _x()
    b = bolt.array(x, mesh)
    out = np.ones_like(x) + b
    assert isinstance(out, type(b))
    assert allclose(out.toarray(), x + 1)
    out = np.float64(2.0) * b
    assert isinstance(out, type(b))
    assert allclose(out.toarray(), x * 2)


def test_eq_sentinel(mesh):
    b = bolt.array(_x(), mesh)
    assert (b == None) is False      # noqa: E711 — the point of the test
    assert (b != None) is True       # noqa: E711
    assert (b == "nope") is False


def test_neg_bool_parity(mesh):
    x = _x()
    with pytest.raises(TypeError):
        -(x > 0)                     # the numpy oracle rejects bool negate
    with pytest.raises(TypeError):
        -(bolt.array(x, mesh) > 0)   # and so must the TPU backend


def test_scalar_ops_cache_stable(mesh):
    from bolt_tpu.tpu.array import _JIT_CACHE
    b = bolt.array(_x(), mesh)
    (b + 1.0).sum().toarray()
    before = len(_JIT_CACHE)
    for _ in range(5):
        (b + 1.0).sum().toarray()
    assert len(_JIT_CACHE) == before  # identical expressions reuse programs


# ----------------------------------------------------------------------
# round-2 surface: floordiv, matmul, in-place forms, and numpy-ufunc
# dispatch into the deferred chain (VERDICT r1 weak-3 / next-5)
# ----------------------------------------------------------------------

def test_floordiv(mesh):
    x = _x() * 10
    b = bolt.array(x, mesh)
    assert allclose((b // 3).toarray(), x // 3)
    assert allclose((100 // (abs(b) + 1)).toarray(), 100 // (abs(x) + 1))
    other = np.random.RandomState(15).randn(*x.shape) + 5
    assert allclose((b // other).toarray(), x // other)


def test_mod_reflected(mesh):
    x = abs(_x()) + 1
    b = bolt.array(x, mesh)
    assert allclose((b % 2).toarray(), x % 2)
    assert allclose((7 % b).toarray(), 7 % x)
    assert allclose((2.0 ** b).toarray(), 2.0 ** x)


def test_matmul_batched_over_keys(mesh):
    x = _x()                       # (8, 4, 5), keys (8,)
    w = np.random.RandomState(16).randn(5, 3)
    b = bolt.array(x, mesh)
    out = b @ w
    assert out.split == 1          # keys survive as batch dims
    assert allclose(out.toarray(), x @ w)


def test_matmul_2d_and_reflected(mesh):
    rs = np.random.RandomState(17)
    x = rs.randn(8, 5)
    w = rs.randn(5, 8)
    b = bolt.array(x, mesh)
    assert allclose((b @ w).toarray(), x @ w)
    assert allclose((w @ b).toarray(), w @ x)
    assert allclose(np.matmul(w, b).toarray(), w @ x)


def test_matmul_bolt_operand(mesh):
    rs = np.random.RandomState(18)
    x, y = rs.randn(8, 4, 5), rs.randn(8, 5, 2)
    b, c = bolt.array(x, mesh), bolt.array(y, mesh)
    out = b @ c                    # stacked matmul over the shared key axis
    assert out.split == 1
    assert allclose(out.toarray(), x @ y)


def test_matmul_bad_shapes_raise(mesh):
    b = bolt.array(_x(), mesh)
    with pytest.raises(ValueError):
        b @ np.ones((7, 2))        # contraction mismatch: numpy's ValueError


def test_inplace_forms(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    orig = b
    b += 1
    b *= 2
    b //= 1
    assert allclose(b.toarray(), ((x + 1) * 2) // 1)
    # functional rebinding: the original array is untouched (jax immutability)
    assert allclose(orig.toarray(), x)


def test_numpy_ufunc_dispatch(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = np.sin(b)
    assert isinstance(out, type(b))
    assert out.deferred            # routed into the deferred map chain
    assert allclose(out.toarray(), np.sin(x))
    assert allclose(np.exp(b).toarray(), np.exp(x))
    assert allclose(np.add(b, 1).toarray(), x + 1)
    assert allclose(np.add(np.ones_like(x), b).toarray(), x + 1)
    assert allclose(np.maximum(b, 0).toarray(), np.maximum(x, 0))
    assert np.isnan(b).toarray().sum() == 0


def test_numpy_ufunc_parity_both_backends(mesh):
    x = _x()
    lo, tp = bolt.array(x), bolt.array(x, mesh)
    for uf in (np.sin, np.exp, np.sqrt, np.tanh):
        a = uf(abs(lo) + 1).toarray()
        c = uf(abs(tp) + 1).toarray()
        assert allclose(a, c)


def test_ufunc_unsupported_methods_raise(mesh):
    b = bolt.array(_x(), mesh)
    with pytest.raises(TypeError):
        np.add.at(b, [0], 1.0)     # in-place scatter: explicit no
    with pytest.raises(TypeError):
        np.add.reduce(b, out=np.empty(b.shape[1:]))
    with pytest.raises(TypeError):
        np.add.reduce(b, where=np.zeros(b.shape, bool))
    with pytest.raises(TypeError):
        np.add(b, 1, out=np.empty(b.shape))


def test_ufunc_reduce_parity(mesh):
    """np.add.reduce(b) answers identically on both backends (VERDICT r4
    missing-3: the TPU side used to raise where ndarray served it)."""
    x = _x()
    lo, tp = bolt.array(x), bolt.array(x, mesh)
    cases = [
        lambda b: np.add.reduce(b),                    # default axis=0
        lambda b: np.add.reduce(b, axis=None),         # all axes
        lambda b: np.add.reduce(b, axis=(0, 2)),
        lambda b: np.add.reduce(b, axis=1, keepdims=True),
        lambda b: np.add.reduce(b, axis=()),           # no-op reduce
        lambda b: np.maximum.reduce(b, initial=100.0),
        lambda b: np.multiply.reduce(b, axis=2),
        lambda b: np.hypot.reduce(b),                  # frompyfunc twin
        lambda b: np.hypot.reduce(b, axis=(0, 1)),     # sequential path
        lambda b: np.add.reduce(b, axis=(0, 1), initial=7.0),
        lambda b: np.logical_and.reduce(abs(b) > 0.01),
        lambda b: np.logical_xor.reduce(b > 0),        # key-axis parity
        lambda b: np.logical_xor.reduce(b > 0, axis=(0, 1)),
        lambda b: np.logical_xor.reduce(b > 0, axis=2),
        lambda b: np.add.reduce(b, axis=(), initial=7.0),
        lambda b: np.subtract.reduce(b, axis=(), initial=7.0),
        lambda b: np.subtract.reduce(b, axis=1),       # left-fold parity
        lambda b: np.add.reduce(b, where=np.True_),    # semantic default
        lambda b: np.add.reduce(b, initial=np.array(5.0)),  # 0-d initial
    ]
    for f in cases:
        a, c = np.asarray(f(lo)), np.asarray(f(tp).toarray())
        assert a.shape == c.shape
        assert allclose(a, c)
    out = np.add.reduce(tp, axis=0)
    assert isinstance(out, type(tp)) and out.split == 0
    # duplicate axes: numpy's exact ValueError on both backends
    for b in (lo, tp):
        with pytest.raises(ValueError, match="duplicate value in 'axis'"):
            np.add.reduce(b, axis=(0, 0))
        # non-reorderable multi-axis reduce: numpy's ValueError, never an
        # order-dependent sequential value
        with pytest.raises(ValueError, match="reorderable"):
            np.subtract.reduce(b, axis=(0, 1))
    # numpy's generic non-reorderable reduce uses a buffer-striding order
    # that is not a fold at all (power.reduce([2,3,2,1.5]) == 2**1.5);
    # the TPU backend rejects loudly instead of serving different numbers
    with pytest.raises(TypeError):
        np.power.reduce(tp)
    with pytest.raises(TypeError):
        np.arctan2.reduce(tp)
    # bitwise_xor over the SHARDED key axis: XLA has no cross-partition
    # xor combine — loud reject; value-axis reduce still serves
    ti = bolt.array((np.arange(24).reshape(8, 3)), tp.mesh)
    with pytest.raises(TypeError):
        np.bitwise_xor.reduce(ti)
    assert allclose(np.asarray(np.bitwise_xor.reduce(ti, axis=1).toarray()),
                    np.bitwise_xor.reduce(np.arange(24).reshape(8, 3),
                                          axis=1))


@pytest.mark.xfail(
    condition=OLD_JAX,
    strict=False,
    reason="known old-jax residual (seed-present): 0.4.x jnp lacks the "
           "jnp.ufunc accumulate/reduceat surface this dispatch lowers "
           "to (np.maximum.accumulate raises in the fused program); "
           "fixed on runtimes with jax.shard_map")
def test_ufunc_accumulate_reduceat_parity(mesh):
    x = _x()
    lo, tp = bolt.array(x), bolt.array(x, mesh)
    cases = [
        lambda b: np.add.accumulate(b),                # default axis=0
        lambda b: np.add.accumulate(b, axis=2),
        lambda b: np.multiply.accumulate(b, axis=1),
        lambda b: np.maximum.accumulate(b),
        lambda b: np.add.reduceat(b, [0, 2, 5], axis=0),
        lambda b: np.add.reduceat(b, [0, 3], axis=1),
    ]
    for f in cases:
        a, c = np.asarray(f(lo)), np.asarray(f(tp).toarray())
        assert a.shape == c.shape
        assert allclose(a, c)
    out = np.add.accumulate(tp)
    assert isinstance(out, type(tp)) and out.split == tp.split
    # distributed index operand: fused on device, never np.asarray'd
    idx = bolt.array(np.array([0, 2, 5]), tp.mesh)
    got = np.add.reduceat(tp, idx)
    assert allclose(np.asarray(got.toarray()),
                    np.add.reduceat(np.asarray(lo), [0, 2, 5], axis=0))
    # host indices validate up front: numpy's IndexError on both
    # backends, not jax's silent clamp
    for b in (lo, tp):
        with pytest.raises(IndexError):
            np.add.reduceat(b, [0, 99], axis=0)
        with pytest.raises(IndexError):
            np.add.reduceat(b, [0, -2], axis=0)
        with pytest.raises(ValueError, match="does not allow multiple"):
            np.add.accumulate(b, axis=None)
        with pytest.raises(ValueError, match="does not allow multiple"):
            np.add.reduceat(b, [0], axis=None)
    # zero-length axis: index 0 is out of bounds on BOTH backends
    z_lo, z_tp = bolt.array(np.zeros((0, 3))), bolt.array(
        np.zeros((0, 3)), mesh)
    for b in (z_lo, z_tp):
        with pytest.raises(IndexError):
            np.add.reduceat(b, [0], axis=0)
    # where=1 is numpy's semantic default: served on both backends
    assert allclose(np.asarray(np.add.reduce(tp, where=1).toarray()),
                    np.add.reduce(np.asarray(lo), where=1))


def test_ufunc_outer_parity(mesh):
    x = _x()[:, 0, 0]              # 1-d keys
    w = np.linspace(-1.0, 1.0, 3)
    lo, tp = bolt.array(x), bolt.array(x, mesh)
    for f in (lambda b: np.subtract.outer(b, w),
              lambda b: np.add.outer(w, b),
              lambda b: np.add.outer(b, w, dtype=np.float32),
              lambda b: np.multiply.outer(b, np.ones((2, 2)))):
        a, c = np.asarray(f(lo)), np.asarray(f(tp).toarray())
        assert a.shape == c.shape
        assert allclose(a, c)
    # keys survive only on the leading operand
    assert np.subtract.outer(tp, w).split == 1
    assert np.add.outer(w, tp).split == 0


def test_matmul_2d_keeps_row_keys(mesh):
    # the canonical row-sharded case: (N, d) @ (d, k) keeps keys on N
    rs = np.random.RandomState(19)
    x, w = rs.randn(8, 5), rs.randn(5, 3)
    b = bolt.array(x, mesh)
    out = b @ w
    assert out.split == 1
    assert allclose(out.toarray(), x @ w)
    # matrix @ vector too
    v = rs.randn(5)
    out = b @ v
    assert out.split == 1
    assert allclose(out.toarray(), x @ v)
    # reverse 2-d contracts the keys: re-keyed to split=0
    y = rs.randn(3, 8)
    out = y @ b
    assert out.split == 0
    assert allclose(out.toarray(), y @ x)


def test_multi_output_ufuncs_unsupported(mesh):
    b = bolt.array(_x(), mesh)
    with pytest.raises(TypeError):
        np.modf(b)
    with pytest.raises(TypeError):
        np.divmod(b, 2.0)


def test_mesh_mismatch_raises(mesh):
    import jax
    x = _x()
    b = bolt.array(x, mesh)
    half = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("k",))
    c = bolt.array(x, half)
    with pytest.raises(ValueError, match="different meshes"):
        b + c
    with pytest.raises(ValueError, match="different meshes"):
        b.concatenate(c)
    with pytest.raises(ValueError, match="different meshes"):
        b @ c.values.reshape(5, 4)
    # explicit move works
    out = b + c.tolocal().totpu(context=mesh)
    assert bolt.allclose(out.toarray(), x * 2)


def test_jax_array_operands_no_host_roundtrip(mesh):
    # a jax.Array operand must feed the compiled op directly — routing it
    # through np.asarray would fetch it to host and re-upload on EVERY
    # call (measured 12 s/call for a 0.27 GB weight through a remote
    # attach). np.asarray on a non-fully-addressable array would also
    # simply crash, so this path is correctness too, not just speed.
    import jax
    import jax.numpy as jnp
    import numpy as np_mod
    x = _x()
    b = bolt.array(x, mesh)
    w = jnp.asarray(np_mod.ones(x.shape[1:], np_mod.float32))
    orig = np_mod.asarray
    seen = []
    def spy(a, *args, **kw):
        if isinstance(a, jax.Array):
            seen.append(type(a))
        return orig(a, *args, **kw)
    np_mod.asarray = spy
    try:
        out1 = (b + w).toarray()
        wj = jnp.asarray(np_mod.ones((5, 3), np_mod.float32))
        out2 = (b @ wj).toarray()
        b.concatenate(jnp.asarray(x.astype(np_mod.float32)))
    finally:
        np_mod.asarray = orig
    assert not seen, "jax operand was bounced through np.asarray"
    assert allclose(out1, x + 1)
    assert allclose(out2, x @ np_mod.ones((5, 3)))


def test_foreign_device_operand_falls_back(mesh):
    # a jax.Array committed OUTSIDE the mesh's devices must take the host
    # coercion path (feeding it to the mesh-sharded jit would raise
    # "incompatible devices"), preserving pre-round-2 behavior
    import jax
    x = _x()
    half = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("k",))
    b = bolt.array(x, half)
    w = jax.device_put(np.ones(x.shape), jax.devices()[6])
    assert allclose((b + w).toarray(), x + 1)


def test_dot_precision_option(mesh):
    # dot(precision=) opts into faster MXU passes; "highest" (default)
    # stays ulp-parity with the oracle, "default" is allclose at ~1e-2
    x = np.random.RandomState(70).randn(32, 16).astype(np.float32)
    w = np.random.RandomState(71).randn(16, 8).astype(np.float32)
    b = bolt.array(x, mesh)
    hi = b.dot(w)
    fast = b.dot(w, precision="default")
    ref = x @ w
    assert np.allclose(np.asarray(hi.toarray()), ref, rtol=1e-6, atol=1e-6)
    assert np.allclose(np.asarray(fast.toarray()), ref, rtol=3e-2, atol=3e-2)
    # distinct precisions are distinct compiled programs
    from bolt_tpu.tpu.array import _JIT_CACHE
    assert sum(1 for k in _JIT_CACHE
               if k[0] == "dot" and k[1] == (32, 16)) >= 2
