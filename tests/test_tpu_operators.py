"""Elementwise operator tests (a deliberate superset of the reference: its
Spark array routes elementwise math through ``map`` — SURVEY §2.2)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.utils import allclose


def _x():
    rs = np.random.RandomState(12)
    return rs.randn(8, 4, 5)


def test_scalar_ops(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    assert allclose((b + 1).toarray(), x + 1)
    assert allclose((1 + b).toarray(), 1 + x)
    assert allclose((b - 2).toarray(), x - 2)
    assert allclose((2 - b).toarray(), 2 - x)
    assert allclose((b * 3).toarray(), x * 3)
    assert allclose((b / 2).toarray(), x / 2)
    assert allclose((2 / (b + 10)).toarray(), 2 / (x + 10))
    assert allclose((b ** 2).toarray(), x ** 2)
    assert allclose((-b).toarray(), -x)
    assert allclose(abs(b).toarray(), abs(x))


def test_scalar_ops_defer(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    m = (b + 1) * 2 - 3
    assert m.deferred  # scalar ops fuse into the map chain
    assert allclose(m.toarray(), (x + 1) * 2 - 3)


def test_array_operand(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    other = np.random.RandomState(13).randn(*x.shape)
    assert allclose((b + other).toarray(), x + other)
    assert allclose((b * other).toarray(), x * other)
    # broadcasting into the full shape
    row = np.random.RandomState(14).randn(5)
    assert allclose((b + row).toarray(), x + row)
    with pytest.raises(ValueError):
        b + np.ones((9, 1, 1))  # does not broadcast into (8, 4, 5)


def test_bolt_operand(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    c = bolt.array(x * 2, mesh)
    out = b + c
    assert out.split == 1
    assert allclose(out.toarray(), x * 3)
    # local bolt array operand
    out = b + bolt.array(np.ones_like(x))
    assert allclose(out.toarray(), x + 1)


def test_comparisons(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    assert allclose((b > 0).toarray(), x > 0)
    assert allclose((b <= 0.5).toarray(), x <= 0.5)
    assert (b == b).toarray().all()
    assert not (b != b).toarray().any()
    assert (b > 0).dtype == np.bool_


def test_value_shaped_result_ops(mesh):
    # operators on a split=0 reduction result
    x = _x()
    s = bolt.array(x, mesh).sum()
    assert s.split == 0
    assert allclose((s + 1).toarray(), x.sum(axis=0) + 1)
    assert allclose(abs(s).toarray(), abs(x.sum(axis=0)))


def test_mixed_expression(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = ((b + 1) * (b - 1)).mean()
    assert allclose(out.toarray(), ((x + 1) * (x - 1)).mean(axis=0))


def test_numpy_left_operand_reflects(mesh):
    # numpy must defer to __radd__ etc. instead of gathering via __array__
    x = _x()
    b = bolt.array(x, mesh)
    out = np.ones_like(x) + b
    assert isinstance(out, type(b))
    assert allclose(out.toarray(), x + 1)
    out = np.float64(2.0) * b
    assert isinstance(out, type(b))
    assert allclose(out.toarray(), x * 2)


def test_eq_sentinel(mesh):
    b = bolt.array(_x(), mesh)
    assert (b == None) is False      # noqa: E711 — the point of the test
    assert (b != None) is True       # noqa: E711
    assert (b == "nope") is False


def test_neg_bool_parity(mesh):
    x = _x()
    with pytest.raises(TypeError):
        -(x > 0)                     # the numpy oracle rejects bool negate
    with pytest.raises(TypeError):
        -(bolt.array(x, mesh) > 0)   # and so must the TPU backend


def test_scalar_ops_cache_stable(mesh):
    from bolt_tpu.tpu.array import _JIT_CACHE
    b = bolt.array(_x(), mesh)
    (b + 1.0).sum().toarray()
    before = len(_JIT_CACHE)
    for _ in range(5):
        (b + 1.0).sum().toarray()
    assert len(_JIT_CACHE) == before  # identical expressions reuse programs
