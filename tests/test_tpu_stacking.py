"""TPU-backend stacking (reference area: ``test/test_spark_stacking.py``,
SURVEY §4)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.utils import allclose


def _x():
    rs = np.random.RandomState(10)
    return rs.randn(8, 4, 5)


def test_stack_view(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    s = b.stacked(size=3)
    assert s.shape == x.shape
    assert s.split == 1
    assert s.size == 3
    assert s.nblocks == 3  # 8 records in blocks of 3 -> 3, 3, 2
    assert s.unstack() is b
    with pytest.raises(ValueError):
        b.stacked(size=0)


def test_stack_map_elementwise(mesh):
    x = _x()
    out = bolt.array(x, mesh).stacked(size=3).map(lambda blk: blk * 2)
    assert allclose(out.unstack().toarray(), x * 2)


def test_stack_map_blockwise(mesh):
    # a genuinely block-level func: normalise within each stack block
    x = _x()
    s = bolt.array(x, mesh).stacked(size=4)
    out = s.map(lambda blk: blk - blk.mean(axis=0)).unstack().toarray()
    expected = np.concatenate(
        [x[i:i + 4] - x[i:i + 4].mean(axis=0) for i in (0, 4)])
    assert allclose(out, expected)


def test_stack_map_value_shape_change(mesh):
    x = _x()
    out = (bolt.array(x, mesh).stacked(size=5)
           .map(lambda blk: blk.sum(axis=2)).unstack())
    assert out.shape == (8, 4)
    assert allclose(out.toarray(), x.sum(axis=2))


def test_stack_map_count_guard(mesh):
    s = bolt.array(_x(), mesh).stacked(size=4)
    with pytest.raises(ValueError):
        s.map(lambda blk: blk[:2])


def test_repr(mesh):
    r = repr(bolt.array(_x(), mesh).stacked(size=3))
    assert "nblocks: 3" in r and "size: 3" in r


def test_stacked_map_trace_cost_is_grid_independent(mesh):
    # func must trace at most twice (vmapped full blocks + ragged tail),
    # not once per block — size=2 over 16 records would otherwise cost 8
    rs = np.random.RandomState(70)
    x = rs.randn(16, 3)
    traces = []

    def f(blk):
        traces.append(blk.shape)
        return blk * 2.0

    out = bolt.array(x, mesh).stacked(size=3).map(f).unstack()
    assert np.allclose(out.toarray(), x * 2.0)
    assert len(traces) <= 2, traces          # 5 full blocks + tail of 1
    # uniform split: single vmapped trace
    traces.clear()
    out = bolt.array(x, mesh).stacked(size=4).map(f).unstack()
    assert np.allclose(out.toarray(), x * 2.0)
    assert len(traces) == 1, traces


def test_stack_map_count_guard_both_branches(mesh):
    rs = np.random.RandomState(71)
    x = rs.randn(8, 3)
    # vmap branch: full blocks violate the contract
    with pytest.raises(ValueError):
        bolt.array(x, mesh).stacked(size=4).map(lambda blk: blk[:2]).unstack()
    # ragged-tail branch: a fixed 3-row output satisfies the full blocks
    # but violates the 2-record tail
    import jax.numpy as jnp
    with pytest.raises(ValueError):
        bolt.array(x, mesh).stacked(size=3).map(
            lambda blk: jnp.zeros((3,) + blk.shape[1:])).unstack()
    # record axis dropped entirely
    with pytest.raises(ValueError):
        bolt.array(x, mesh).stacked(size=4).map(
            lambda blk: blk.sum()).unstack()


def test_stacked_map_zero_records(mesh):
    # a filter with no survivors yields (0, *vshape); stacked.map must
    # return the empty result, not crash on an empty concatenate — and a
    # value-shape/dtype-changing func must produce the SAME output
    # shape/dtype the non-empty branch would
    x = np.random.RandomState(72).randn(8, 3)
    f = bolt.array(x, mesh).filter(lambda v: v.sum() > 1e9)
    out = f.stacked(size=4).map(lambda blk: blk * 2).unstack()
    assert out.shape == (0, 3)
    assert out.toarray().shape == (0, 3)
    out2 = f.stacked(size=4).map(lambda blk: blk[:, :1]).unstack()
    assert out2.shape == (0, 1)
    import jax.numpy as jnp
    out3 = f.stacked(size=4).map(
        lambda blk: blk.astype(jnp.float32)).unstack()
    assert out3.dtype == np.float32
    out4 = f.stacked(size=4).map(lambda blk: blk * 2, dtype=np.float32
                                 ).unstack()
    assert out4.dtype == np.float32 and out4.shape == (0, 3)


def test_stacked_map_value_shape_and_dtype_hints(mesh):
    rs = np.random.RandomState(81)
    x = rs.randn(8, 3)
    s = bolt.array(x, mesh).stacked(size=4)
    out = s.map(lambda blk: blk + 1, dtype=np.float32).unstack()
    assert out.dtype == np.float32
    assert np.allclose(out.toarray(), (x + 1).astype(np.float32), atol=1e-6)
    with pytest.raises(ValueError):
        s.map(lambda blk: blk + 1, value_shape=(7,))
