"""Deferred-map (lazy chain) semantics: the TPU analog of the reference's
lazy RDD transformations — transformations defer, actions fuse and execute
(reference behavior: ``BoltArraySpark`` ops build RDD lineage; a job runs
only on actions like ``collect``/``reduce``/``aggregate``, SURVEY §3)."""

import numpy as np

import bolt_tpu as bolt
from bolt_tpu.utils import allclose


def _x():
    rs = np.random.RandomState(11)
    return rs.randn(8, 4, 5)


def test_map_is_deferred(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    m = b.map(lambda v: v + 1)
    assert m.deferred
    assert m.shape == x.shape          # shape known without executing
    assert m.dtype == x.dtype
    assert "deferred" in repr(m)
    # action materialises
    assert allclose(m.toarray(), x + 1)
    assert not m.deferred


def test_chain_fuses(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    m = b.map(lambda v: v + 1).map(lambda v: v * 2).map(lambda v: v - 3)
    assert m.deferred
    assert len(m._chain[1]) == 3
    assert allclose(m.toarray(), (x + 1) * 2 - 3)


def test_reduce_consumes_chain(mesh):
    from operator import add
    x = _x()
    b = bolt.array(x, mesh)
    m = b.map(lambda v: v + 1)
    r = m.reduce(add)
    assert m.deferred                   # reduce fused; map never materialised
    assert allclose(r.toarray(), (x + 1).sum(axis=0))


def test_stats_consume_chain(mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v * 2)
    out = m.sum()
    assert m.deferred
    assert allclose(out.toarray(), (x * 2).sum(axis=0))
    assert allclose(m.mean(axis=(0, 1)).toarray(), (x * 2).mean(axis=(0, 1)))
    assert m.deferred


def test_cache_forces(mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v + 1)
    assert m.deferred
    m.cache()
    assert not m.deferred
    assert allclose(m.toarray(), x + 1)


def test_astype_defers_and_fuses(mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v + 1).astype(np.float32)
    assert m.deferred
    assert m.dtype == np.float32
    assert allclose(m.toarray(), (x + 1).astype(np.float32))


def test_swap_materialises(mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v + 1)
    s = m.swap((0,), (0,))
    assert not s.deferred
    assert allclose(s.toarray(), np.transpose(x + 1, (1, 0, 2)))


def test_with_keys_map_defers_and_fuses(mesh):
    # with_keys maps are lazy chain entries like plain maps (VERDICT r2
    # weak-5): map(f, with_keys=True).sum() compiles ONE fused program
    import bolt_tpu.profile as profile
    x = _x()
    f = lambda kv: kv[1] + kv[0][0]                      # noqa: E731
    m = bolt.array(x, mesh).map(f, with_keys=True)
    assert m.deferred
    keys = np.arange(x.shape[0]).reshape((-1,) + (1,) * (x.ndim - 1))
    with profile.instrument() as stats:
        out = m.sum().cache()          # first read dispatches the lazy stat
    assert stats.get("stat", {}).get("calls") == 1
    assert "chain" not in stats and "map-wk" not in stats
    assert allclose(np.asarray(out.toarray()), (x + keys).sum(axis=0))
    # chains mixing plain and with_keys entries stay one program
    m2 = (bolt.array(x, mesh).map(lambda v: v * 2)
          .map(f, with_keys=True).map(lambda v: v - 1))
    assert m2.deferred
    assert allclose(m2.toarray(), x * 2 + keys - 1)
    # first() on a (still) deferred with_keys chain runs a ONE-record
    # program (toarray above materialised m2, so build a fresh chain)
    m3 = (bolt.array(x, mesh).map(lambda v: v * 2)
          .map(f, with_keys=True).map(lambda v: v - 1))
    assert m3.deferred
    with profile.instrument() as stats:
        rec = m3.first()
    assert "chain" not in stats          # the full chain never ran
    assert stats.get("first", {}).get("calls") == 1
    assert allclose(rec, x[0] * 2 - 1)
    assert m3.deferred                   # first() left the chain lazy
