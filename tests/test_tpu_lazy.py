"""Deferred-map (lazy chain) semantics: the TPU analog of the reference's
lazy RDD transformations — transformations defer, actions fuse and execute
(reference behavior: ``BoltArraySpark`` ops build RDD lineage; a job runs
only on actions like ``collect``/``reduce``/``aggregate``, SURVEY §3)."""

import numpy as np

import bolt_tpu as bolt
from bolt_tpu.utils import allclose


def _x():
    rs = np.random.RandomState(11)
    return rs.randn(8, 4, 5)


def test_map_is_deferred(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    m = b.map(lambda v: v + 1)
    assert m.deferred
    assert m.shape == x.shape          # shape known without executing
    assert m.dtype == x.dtype
    assert "deferred" in repr(m)
    # action materialises
    assert allclose(m.toarray(), x + 1)
    assert not m.deferred


def test_chain_fuses(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    m = b.map(lambda v: v + 1).map(lambda v: v * 2).map(lambda v: v - 3)
    assert m.deferred
    assert len(m._chain[1]) == 3
    assert allclose(m.toarray(), (x + 1) * 2 - 3)


def test_reduce_consumes_chain(mesh):
    from operator import add
    x = _x()
    b = bolt.array(x, mesh)
    m = b.map(lambda v: v + 1)
    r = m.reduce(add)
    assert m.deferred                   # reduce fused; map never materialised
    assert allclose(r.toarray(), (x + 1).sum(axis=0))


def test_stats_consume_chain(mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v * 2)
    out = m.sum()
    assert m.deferred
    assert allclose(out.toarray(), (x * 2).sum(axis=0))
    assert allclose(m.mean(axis=(0, 1)).toarray(), (x * 2).mean(axis=(0, 1)))
    assert m.deferred


def test_cache_forces(mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v + 1)
    assert m.deferred
    m.cache()
    assert not m.deferred
    assert allclose(m.toarray(), x + 1)


def test_astype_defers_and_fuses(mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v + 1).astype(np.float32)
    assert m.deferred
    assert m.dtype == np.float32
    assert allclose(m.toarray(), (x + 1).astype(np.float32))


def test_swap_materialises(mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v + 1)
    s = m.swap((0,), (0,))
    assert not s.deferred
    assert allclose(s.toarray(), np.transpose(x + 1, (1, 0, 2)))


def test_with_keys_map_is_eager(mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda kv: kv[1] + kv[0][0], with_keys=True)
    assert not m.deferred
