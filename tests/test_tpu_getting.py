"""TPU-backend indexing vs numpy (reference area:
``test/test_spark_getting.py``, SURVEY §4; BASELINE config 4 exercises the
boolean-mask path via filter)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.utils import allclose


def _x():
    rs = np.random.RandomState(6)
    return rs.randn(8, 4, 5)


def test_slices(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    assert allclose(b[:].toarray(), x)
    assert allclose(b[2:6].toarray(), x[2:6])
    assert allclose(b[:, 1:3].toarray(), x[:, 1:3])
    assert allclose(b[::2, :, ::2].toarray(), x[::2, :, ::2])
    assert allclose(b[1:7:2, ::-1].toarray(), x[1:7:2, ::-1])


def test_ints_squeeze(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b[3]
    assert out.shape == x[3].shape
    assert out.split == 0
    assert allclose(out.toarray(), x[3])
    out = b[:, 2]
    assert out.split == 1
    assert allclose(out.toarray(), x[:, 2])
    assert allclose(b[-1, -2, -3].toarray(), np.asarray(x[-1, -2, -3]))


def test_lists_orthogonal(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    assert allclose(b[[0, 3, 5]].toarray(), x[[0, 3, 5]])
    # per-axis advanced indices apply orthogonally (np.ix_ semantics)
    out = b[[0, 1], :, [0, 2, 4]]
    expected = x[np.ix_([0, 1], range(4), [0, 2, 4])]
    assert allclose(out.toarray(), expected)
    assert allclose(b[:, [3, 1]].toarray(), x[:, [3, 1]])
    assert allclose(b[[-1, 0]].toarray(), x[[-1, 0]])


def test_bool_arrays(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    kmask = x[:, 0, 0] > 0
    assert allclose(b[kmask].toarray(), x[kmask])
    vmask = np.array([True, False, True, False, True])
    assert allclose(b[:, :, vmask].toarray(), x[:, :, vmask])


def test_mixed(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b[2:7, [0, 3], ::2]
    expected = x[2:7][:, [0, 3]][:, :, ::2]
    assert allclose(out.toarray(), expected)
    out = b[1, :, [0, 4]]
    expected = x[1][:, [0, 4]]
    assert allclose(out.toarray(), expected)


def test_split_bookkeeping(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    assert b[0].split == 1
    assert b[0, 0].split == 0
    assert b[:, 0].split == 1
    assert b[:, :, 0].split == 2


def test_errors(mesh):
    b = bolt.array(_x(), mesh)
    with pytest.raises(ValueError):
        b[0, 0, 0, 0]
    with pytest.raises(IndexError):
        b[99]


def test_negative_and_mixed_index_forms(mesh):
    # negatives, reversed slices, empty slices, ndarray indices, and
    # int+list+slice mixes — full numpy-oracle parity
    rs = np.random.RandomState(50)
    x = rs.randn(16, 6, 4)
    b = bolt.array(x, mesh)
    assert allclose(b[-1].toarray(), x[-1])
    assert allclose(b[-3:].toarray(), x[-3:])
    assert allclose(b[..., -2:].toarray(), x[..., -2:])
    assert allclose(b[[-1, 0, 2]].toarray(), x[[-1, 0, 2]])
    assert allclose(b[::-1].toarray(), x[::-1])
    assert allclose(b[np.array([1, 3])].toarray(), x[np.array([1, 3])])
    assert allclose(np.asarray(b[2, -1, ::2].toarray()), x[2, -1, ::2])
    assert b[5:2].toarray().shape == x[5:2].shape
    assert allclose(b[1, [0, 2], :].toarray(), x[1][[0, 2], :])


def test_take_parity(mesh):
    # ndarray.take: inherited locally, compiled program on TPU
    x = _x()
    b, lo = bolt.array(x, mesh), bolt.array(x)
    for kwargs in [dict(indices=[2, 0, 5]), dict(indices=[1, -1], axis=0),
                   dict(indices=[3, 1], axis=1),
                   dict(indices=[0, 2, 4], axis=2),
                   dict(indices=[[0, 1], [2, 3]], axis=0),
                   dict(indices=7)]:
        ref = x.take(**kwargs)
        t = b.take(**kwargs)
        l = lo.take(**kwargs)
        assert np.asarray(t.toarray()).shape == ref.shape, kwargs
        assert allclose(t.toarray(), ref), kwargs
        assert allclose(np.asarray(l), ref), kwargs
    # split bookkeeping
    assert b.take([1, 0], axis=0).split == 1
    assert b.take(0, axis=0).split == 0
    assert b.take([1, 0], axis=2).split == 1
    assert b.take([[0, 1], [2, 3]], axis=0).split == 2
    # errors match numpy's classes
    with pytest.raises(IndexError):
        b.take([9999])               # OOB for the flattened 160 elements
    with pytest.raises(IndexError):
        b.take([8], axis=0)
    # deferred chains fuse in
    assert allclose(bolt.array(x, mesh).map(lambda v: v * 2)
                    .take([1, 3], axis=0).toarray(), (x * 2).take([1, 3], 0))


def test_take_numpy_dtype_and_mode_semantics(mesh):
    # numpy's exact quirks: float NDARRAYS rejected, float sequences and
    # scalars truncate, bools are 0/1 indices, mode= clips/wraps
    x = _x()
    b, lo = bolt.array(x, mesh), bolt.array(x)
    for args in [([True, False],), ([2.7],), (1.5,), ([-1.5],)]:
        ref = x.take(*args)
        assert allclose(np.asarray(b.take(*args).toarray()), ref), args
        assert allclose(np.asarray(lo.take(*args)), ref), args
    with pytest.raises(TypeError):
        b.take(np.array([1.5]))
    with pytest.raises(TypeError):
        b.take(np.array([], dtype=float))
    assert allclose(np.asarray(b.take([9999], mode="clip").toarray()),
                    x.take([9999], mode="clip"))
    assert allclose(np.asarray(b.take([-3, 175], axis=None, mode="wrap").toarray()),
                    x.take([-3, 175], mode="wrap"))
    with pytest.raises(ValueError):
        b.take([0], mode="nope")
