"""TPU-backend indexing vs numpy (reference area:
``test/test_spark_getting.py``, SURVEY §4; BASELINE config 4 exercises the
boolean-mask path via filter)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.utils import allclose


def _x():
    rs = np.random.RandomState(6)
    return rs.randn(8, 4, 5)


def test_slices(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    assert allclose(b[:].toarray(), x)
    assert allclose(b[2:6].toarray(), x[2:6])
    assert allclose(b[:, 1:3].toarray(), x[:, 1:3])
    assert allclose(b[::2, :, ::2].toarray(), x[::2, :, ::2])
    assert allclose(b[1:7:2, ::-1].toarray(), x[1:7:2, ::-1])


def test_ints_squeeze(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b[3]
    assert out.shape == x[3].shape
    assert out.split == 0
    assert allclose(out.toarray(), x[3])
    out = b[:, 2]
    assert out.split == 1
    assert allclose(out.toarray(), x[:, 2])
    assert allclose(b[-1, -2, -3].toarray(), np.asarray(x[-1, -2, -3]))


def test_lists_orthogonal(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    assert allclose(b[[0, 3, 5]].toarray(), x[[0, 3, 5]])
    # per-axis advanced indices apply orthogonally (np.ix_ semantics)
    out = b[[0, 1], :, [0, 2, 4]]
    expected = x[np.ix_([0, 1], range(4), [0, 2, 4])]
    assert allclose(out.toarray(), expected)
    assert allclose(b[:, [3, 1]].toarray(), x[:, [3, 1]])
    assert allclose(b[[-1, 0]].toarray(), x[[-1, 0]])


def test_bool_arrays(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    kmask = x[:, 0, 0] > 0
    assert allclose(b[kmask].toarray(), x[kmask])
    vmask = np.array([True, False, True, False, True])
    assert allclose(b[:, :, vmask].toarray(), x[:, :, vmask])


def test_mixed(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b[2:7, [0, 3], ::2]
    expected = x[2:7][:, [0, 3]][:, :, ::2]
    assert allclose(out.toarray(), expected)
    out = b[1, :, [0, 4]]
    expected = x[1][:, [0, 4]]
    assert allclose(out.toarray(), expected)


def test_split_bookkeeping(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    assert b[0].split == 1
    assert b[0, 0].split == 0
    assert b[:, 0].split == 1
    assert b[:, :, 0].split == 2


def test_errors(mesh):
    b = bolt.array(_x(), mesh)
    with pytest.raises(ValueError):
        b[0, 0, 0, 0]
    with pytest.raises(IndexError):
        b[99]


def test_negative_and_mixed_index_forms(mesh):
    # negatives, reversed slices, empty slices, ndarray indices, and
    # int+list+slice mixes — full numpy-oracle parity
    rs = np.random.RandomState(50)
    x = rs.randn(16, 6, 4)
    b = bolt.array(x, mesh)
    assert allclose(b[-1].toarray(), x[-1])
    assert allclose(b[-3:].toarray(), x[-3:])
    assert allclose(b[..., -2:].toarray(), x[..., -2:])
    assert allclose(b[[-1, 0, 2]].toarray(), x[[-1, 0, 2]])
    assert allclose(b[::-1].toarray(), x[::-1])
    assert allclose(b[np.array([1, 3])].toarray(), x[np.array([1, 3])])
    assert allclose(np.asarray(b[2, -1, ::2].toarray()), x[2, -1, ::2])
    assert b[5:2].toarray().shape == x[5:2].shape
    assert allclose(b[1, [0, 2], :].toarray(), x[1][[0, 2], :])
