"""Multi-tenant serving layer (ISSUE 8): scheduler, arbiter, admission.

The load-bearing contracts: N tenants submitting identical pipelines
get BIT-IDENTICAL results to their single-tenant runs while compiling
exactly once across all of them (engine build/compile coalescing); the
device-memory arbiter keeps concurrent streams inside ONE process-wide
bytes budget (fair round-robin across tenants, in-order per stream,
degrading to a shallower pipeline — never a deadlock — when the budget
is smaller than a run's full ring); admission control rejects or
queues by policy, with BLT010 refusing pipelines that could never fit;
and every tenant's engine/obs counters are scoped so per-tenant bytes
and wait times are attributable.
"""

import threading
import time

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu import analysis, engine, serve
from bolt_tpu.obs import metrics as _metrics

pytestmark = pytest.mark.serve


ADD1 = lambda v: v + 1    # hoisted: tenants must SHARE stage callables
#                           for cross-tenant executable coalescing


def _x(shape=(64, 8, 4)):
    return np.arange(np.prod(shape), dtype=np.float32).reshape(shape)


def _pipeline(x, mesh, chunks=16):
    src = bolt.fromcallback(lambda idx: x[idx], x.shape, mesh,
                            dtype=np.float32, chunks=chunks)
    return src.map(ADD1).sum()


@pytest.fixture(autouse=True)
def _no_leaked_server():
    yield
    assert serve.active() is None, "a test leaked an active server"


# ---------------------------------------------------------------------
# the scheduler: submit, futures, results
# ---------------------------------------------------------------------

def test_submit_array_pipeline_and_callable(mesh):
    x = _x()
    ref = (x + 1).sum(axis=0)
    with serve.serving(workers=2) as sv:
        f1 = sv.submit(_pipeline(x, mesh), tenant="a")
        f2 = sv.submit(lambda: 41 + 1, tenant="b")
        out = f1.result(timeout=60)
        assert np.allclose(np.asarray(out.toarray()), ref)
        assert f2.result(timeout=60) == 42
        assert f1.done() and f2.done()
        assert f1.wait_seconds >= 0.0 and f1.run_seconds > 0.0


def test_submit_rejects_non_pipelines(mesh):
    with serve.serving(workers=1) as sv:
        with pytest.raises(TypeError):
            sv.submit(42)


def test_future_delivers_the_pipeline_exception(mesh):
    def boom():
        raise ValueError("tenant bug")
    with serve.serving(workers=1) as sv:
        f = sv.submit(boom, tenant="a")
        with pytest.raises(ValueError, match="tenant bug"):
            f.result(timeout=60)
        assert isinstance(f.exception(), ValueError)


def test_module_level_submit_lazy_default_server(mesh):
    try:
        f = serve.submit(lambda: "ok")
        assert f.result(timeout=60) == "ok"
        assert serve.active() is not None
    finally:
        serve.stop()


def test_start_refuses_a_second_server(mesh):
    with serve.serving(workers=1):
        with pytest.raises(RuntimeError, match="already active"):
            serve.start()


# ---------------------------------------------------------------------
# the acceptance contract: N tenants, bit-identical, ONE compile
# ---------------------------------------------------------------------

def test_tenants_bit_identical_to_single_tenant_run(mesh):
    x = _x()
    ref = np.asarray(_pipeline(x, mesh).toarray())     # single-tenant run
    with serve.serving(workers=4) as sv:
        futs = [sv.submit(_pipeline(x, mesh), tenant="t%d" % i)
                for i in range(4)]
        outs = [f.result(timeout=120) for f in futs]
    for out in outs:
        got = np.asarray(out.toarray())
        assert got.dtype == ref.dtype and np.array_equal(got, ref)


def test_n_identical_tenants_compile_exactly_once(mesh):
    x = _x()
    _pipeline(x, mesh).toarray()          # warm python paths
    engine.clear()
    c0 = engine.counters()
    with serve.serving(workers=4) as sv:
        futs = [sv.submit(_pipeline(x, mesh), tenant="t%d" % i)
                for i in range(4)]
        [f.result(timeout=120) for f in futs]
    c1 = engine.counters()
    four = {k: c1[k] - c0[k] for k in ("misses", "aot_compiles")}
    engine.clear()
    c0 = engine.counters()
    _pipeline(x, mesh).toarray()
    c1 = engine.counters()
    one = {k: c1[k] - c0[k] for k in ("misses", "aot_compiles")}
    # the coalescing proof: 4 concurrent cold tenants build and compile
    # EXACTLY what one cold tenant does
    assert four == one, (four, one)


def test_per_tenant_engine_counters_scoped(mesh):
    x = _x()
    t0 = {t: engine.tenant_counters(t)["transfer_bytes"]
          for t in ("scoped-a", "scoped-b")}
    with serve.serving(workers=2) as sv:
        fa = sv.submit(_pipeline(x, mesh), tenant="scoped-a")
        fb = sv.submit(_pipeline(x, mesh), tenant="scoped-b")
        fa.result(timeout=120)
        fb.result(timeout=120)
        st = sv.stats()
    for t in ("scoped-a", "scoped-b"):
        moved = engine.tenant_counters(t)["transfer_bytes"] - t0[t]
        assert moved == x.nbytes, (t, moved)     # the whole ingest, ONCE
        assert st["tenants"][t]["completed"] == 1
        assert st["tenants"][t]["transfer_bytes"] >= x.nbytes


def test_tenant_scope_nests_and_restores(mesh):
    assert engine.current_tenant() is None
    with engine.tenant("outer"):
        assert engine.current_tenant() == "outer"
        with engine.tenant("inner"):
            assert engine.current_tenant() == "inner"
        assert engine.current_tenant() == "outer"
    assert engine.current_tenant() is None


# ---------------------------------------------------------------------
# the device-memory arbiter
# ---------------------------------------------------------------------

def test_arbiter_grants_fifo_within_round_robin_across_tenants():
    arb = serve.DeviceArbiter(10)
    assert arb.acquire(10, "hold")
    order = []
    threads = []

    def waiter(name, tenant):
        assert arb.acquire(10, tenant)
        order.append(name)
        arb.release(10)

    # enqueue a1, a2 (tenant A) then b1 (tenant B), deterministically
    for name, tenant in (("a1", "A"), ("a2", "A"), ("b1", "B")):
        th = threading.Thread(target=waiter, args=(name, tenant),
                              daemon=True)
        th.start()
        threads.append(th)
        deadline = time.time() + 5
        while arb.waiting() < len(threads) and time.time() < deadline:
            time.sleep(0.005)
    assert arb.waiting() == 3
    arb.release(10)
    for th in threads:
        th.join(timeout=10)
    # round-robin ACROSS tenants: A's head, then B's, then A's second
    assert order == ["a1", "b1", "a2"]
    assert arb.in_use() == 0


def test_arbiter_oversized_request_runs_alone():
    arb = serve.DeviceArbiter(100)
    assert arb.acquire(1000, "big")       # larger than the whole budget
    assert arb.in_use() == 1000
    got = []
    th = threading.Thread(target=lambda: got.append(arb.acquire(10, "s")),
                          daemon=True)
    th.start()
    time.sleep(0.05)
    assert not got                        # blocked while the giant holds
    arb.release(1000)
    th.join(timeout=10)
    assert got == [True]
    arb.release(10)


def test_arbiter_large_request_survives_sustained_small_traffic():
    # the anti-starvation barrier: a near-budget request must be seated
    # even while another tenant streams small acquisitions continuously
    arb = serve.DeviceArbiter(100)
    stop = threading.Event()
    got = []

    def small_traffic():
        while not stop.is_set():
            if arb.acquire(10, "chatty", stop=stop):
                time.sleep(0.001)
                arb.release(10)

    chatty = [threading.Thread(target=small_traffic, daemon=True)
              for _ in range(2)]
    for th in chatty:
        th.start()
    time.sleep(0.05)                       # traffic established

    def big():
        got.append(arb.acquire(90, "big"))
    th = threading.Thread(target=big, daemon=True)
    th.start()
    th.join(30)                            # bounded starvation
    stop.set()
    for c in chatty:
        c.join(10)
    assert got == [True]
    arb.release(90)


def test_close_wait_true_drains_leased_jobs(mesh):
    # a queued IN-MEMORY job blocked on the arbiter while a clean
    # close(wait=True) runs must complete, not fail as "cancelled" —
    # only close(wait=False) may abort a pending lease wait
    x = _x((32, 8, 4))
    pipe = bolt.array(x, mesh).map(ADD1).sum()
    est = analysis.working_set_bytes(pipe)
    assert est and est > 0
    sv = serve.start(workers=2, budget_bytes=est + 40, queue_limit=8)
    try:
        def holder():
            lease = sv.arbiter.lease("holder")
            assert lease.acquire(est + 30)     # leaves < est available
            time.sleep(0.4)
            lease.close()
            return "held"
        f1 = sv.submit(holder, tenant="a")
        time.sleep(0.1)                        # holder owns the budget
        f2 = sv.submit(bolt.array(x, mesh).map(ADD1).sum(), tenant="b")
    finally:
        serve.stop(wait=True)                  # drain DURING f2's wait
    assert f1.result(timeout=10) == "held"
    out = f2.result(timeout=10)                # ran after the drain
    assert np.allclose(np.asarray(out.toarray()), (x + 1).sum(axis=0))
    arb = serve.DeviceArbiter(10)
    assert arb.acquire(10, "hold")
    stop = threading.Event()
    out = []
    th = threading.Thread(
        target=lambda: out.append(arb.acquire(10, "w", stop=stop)),
        daemon=True)
    th.start()
    time.sleep(0.05)
    stop.set()
    th.join(timeout=10)
    assert out == [False] and arb.waiting() == 0
    arb.release(10)


def test_lease_close_returns_outstanding_bytes():
    arb = serve.DeviceArbiter(100)
    lease = arb.lease("t")
    assert lease.acquire(60) and lease.acquire(30)
    lease.release(40)
    assert arb.in_use() == 50 and lease.outstanding() == 50
    lease.close()
    assert arb.in_use() == 0
    lease.close()                          # idempotent
    lease.release(10 ** 9)                 # clamped, never negative
    assert arb.in_use() == 0


def _reset_arbiter_high_water():
    # serve metrics are process-cumulative (registry semantics, like the
    # engine counters); reset the high-water gauge so THIS test's bound
    # is what gets asserted
    g = _metrics.registry().gauge("serve.arbiter_in_use_high_water")
    g.reset()
    return g


def test_streamed_run_respects_budget_smaller_than_ring(mesh):
    # budget below slab x ring: the starvation valve must shallow the
    # pipeline, not deadlock; result stays bit-exact and in-use bytes
    # never pass the budget
    x = _x((64, 8, 4))
    ref = (x + 1).sum(axis=0)
    slab_bytes = 16 * 8 * 4 * 4
    hw = _reset_arbiter_high_water()
    with serve.serving(workers=1, budget_bytes=slab_bytes + 1) as sv:
        out = sv.submit(_pipeline(x, mesh, chunks=16),
                        tenant="tight").result(timeout=120)
    assert np.allclose(np.asarray(out.toarray()), ref)
    assert 0 < hw.value <= slab_bytes + 1


def test_concurrent_streams_share_the_budget(mesh):
    x = _x((64, 8, 4))
    ref = (x + 1).sum(axis=0)
    hw = _reset_arbiter_high_water()
    with serve.serving(workers=3, budget_bytes=x.nbytes) as sv:
        futs = [sv.submit(_pipeline(x, mesh), tenant="t%d" % i)
                for i in range(3)]
        outs = [f.result(timeout=120) for f in futs]
    assert 0 < hw.value <= x.nbytes        # never past the global budget
    for out in outs:
        assert np.allclose(np.asarray(out.toarray()), ref)


# ---------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------

def test_reject_policy_raises_when_queue_full(mesh):
    gate = threading.Event()
    with serve.serving(workers=1, queue_limit=1, policy="reject") as sv:
        r0 = sv.stats()["totals"]["rejected"]   # counters are cumulative
        running = threading.Event()

        def slow():
            running.set()
            gate.wait(30)
            return "slow"
        f1 = sv.submit(slow, tenant="a")
        assert running.wait(10)            # worker busy; queue empty
        f2 = sv.submit(lambda: "queued", tenant="a")   # fills the queue
        with pytest.raises(serve.AdmissionError, match="queue is full"):
            sv.submit(lambda: "over", tenant="a")
        gate.set()
        assert f1.result(timeout=60) == "slow"
        assert f2.result(timeout=60) == "queued"
        st = sv.stats()
        assert st["totals"]["rejected"] - r0 == 1
        assert st["queue_depth"] == 0


def test_queue_policy_blocks_submitter_until_room(mesh):
    gate = threading.Event()
    with serve.serving(workers=1, queue_limit=1, policy="queue") as sv:
        running = threading.Event()

        def slow():
            running.set()
            gate.wait(30)
            return "slow"
        sv.submit(slow, tenant="a")
        assert running.wait(10)
        sv.submit(lambda: 1, tenant="a")   # fills the bounded queue
        done = []

        def blocked_submit():
            done.append(sv.submit(lambda: 2, tenant="a"))
        th = threading.Thread(target=blocked_submit, daemon=True)
        th.start()
        time.sleep(0.1)
        assert not done                    # backpressure: submit blocked
        gate.set()
        th.join(timeout=30)
        assert done and done[0].result(timeout=60) == 2


def test_blt010_rejects_impossible_pipeline_and_check_forecasts(mesh):
    x = _x((64, 8, 4))   # ONE slab of 8 KB: can never degrade into 4 KB
    with serve.serving(workers=1, budget_bytes=4096) as sv:
        r0 = sv.stats()["totals"]["rejected"]
        arr = _pipeline(x, mesh, chunks=64)
        rep = analysis.check(arr)
        assert rep.has("BLT010") and not rep.ok
        with pytest.raises(serve.AdmissionError, match="BLT010"):
            sv.submit(arr, tenant="a")
        assert sv.stats()["totals"]["rejected"] - r0 == 1
        # a slab-shrunk twin of the same pipeline IS admissible: the
        # floor is the slab, not the ring
        small = _pipeline(x, mesh, chunks=8)
        assert not analysis.check(small).has("BLT010")
        out = sv.submit(small, tenant="a").result(timeout=120)
        assert np.allclose(np.asarray(out.toarray()), (x + 1).sum(axis=0))
    # without a serving arbiter the same pipeline checks clean
    rep = analysis.check(_pipeline(x, mesh, chunks=64))
    assert not rep.has("BLT010")


def test_working_set_estimates(mesh):
    from bolt_tpu import stream as _stream
    x = _x((64, 8, 4))
    src = bolt.fromcallback(lambda idx: x[idx], x.shape, mesh,
                            dtype=np.float32, chunks=16)
    ring = _stream.prefetch_depth() + _stream.pool_size(src._stream)
    est = analysis.working_set_bytes(src.map(ADD1))
    assert est == 16 * 8 * 4 * 4 * ring
    b = bolt.array(x, mesh).map(ADD1)
    assert analysis.working_set_bytes(b) == 2 * x.nbytes
    assert analysis.working_set_bytes(np.ones(3)) is None


def test_close_without_wait_fails_pending_jobs(mesh):
    gate = threading.Event()
    sv = serve.start(workers=1, queue_limit=4)
    try:
        running = threading.Event()

        def slow():
            running.set()
            gate.wait(30)
        sv.submit(slow, tenant="a")
        assert running.wait(10)
        f2 = sv.submit(lambda: 2, tenant="a")
        gate.set()
    finally:
        serve.stop(wait=False)
    with pytest.raises(RuntimeError):
        f2.result(timeout=60)
    with pytest.raises(RuntimeError, match="closed"):
        sv.submit(lambda: 3)


# ---------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------

def test_serve_metrics_and_no_leaked_spans(mesh):
    from bolt_tpu import obs
    x = _x()
    obs.clear()
    obs.enable()
    try:
        with serve.serving(workers=2) as sv:
            futs = [sv.submit(_pipeline(x, mesh), tenant="m%d" % i)
                    for i in range(3)]
            [f.result(timeout=120) for f in futs]
        assert obs.active_count() == 0     # every serve.run span closed
        names = [s.name for s in obs.spans()]
        assert "serve.run" in names
        reg = _metrics.registry().snapshot()
        assert reg["serve.completed"] >= 3
        assert reg["serve.queue_wait_seconds.hist"]["count"] >= 3
    finally:
        obs.disable()


def test_concurrent_streamed_runs_aggregate_faster_than_serial(mesh):
    # the load-generator contract at test scale: tenants whose ingest
    # has storage-class latency must OVERLAP under the scheduler.  The
    # assertion is deliberately loose (1.3x on 3 tenants) and retried:
    # tier-1 shares one core with the whole suite.
    from bolt_tpu.obs.trace import clock
    x = _x((48, 8, 4))
    lat = 0.01

    def make():
        def read(idx):
            time.sleep(lat)
            return x[idx]
        src = bolt.fromcallback(read, x.shape, mesh, dtype=np.float32,
                                chunks=8)
        return src.map(ADD1).sum()

    make().toarray()                       # compile everything once
    for attempt in range(3):
        t0 = clock()
        for _ in range(3):
            make().toarray()
        serial = clock() - t0
        with serve.serving(workers=3) as sv:
            t0 = clock()
            futs = [sv.submit(make(), tenant="t%d" % i) for i in range(3)]
            [f.result(timeout=120) for f in futs]
            concurrent = clock() - t0
        if concurrent < serial / 1.3:
            return
    pytest.fail("3 concurrent latency-bound tenants never beat serial "
                "(serial %.3fs, concurrent %.3fs)" % (serial, concurrent))


# ---------------------------------------------------------------------
# fault policy (ISSUE 9): tenant-failure isolation, per-submit
# retries= / deadline=
# ---------------------------------------------------------------------

def test_tenant_stream_failure_returns_lease_and_isolates(mesh):
    # ONE tenant's streamed pipeline dies mid-run: its future carries
    # the original error, its arbiter lease bytes come back, and the
    # OTHER tenants' futures are untouched
    x = _x()
    boom = RuntimeError("tenant-a storage died")
    fired = []

    def flaky(idx):
        fired.append(idx)
        if len(fired) >= 2:
            raise boom
        return x[idx]

    ref = (x + 1).sum(axis=0)
    with serve.serving(workers=2, budget_bytes=64 << 20) as sv:
        bad = bolt.fromcallback(flaky, x.shape, mesh, dtype=np.float32,
                                chunks=16).map(ADD1).sum()
        fa = sv.submit(bad, tenant="iso-a")
        fbs = [sv.submit(_pipeline(x, mesh), tenant="iso-b")
               for _ in range(3)]
        with pytest.raises(RuntimeError, match="storage died"):
            fa.result(timeout=120)
        for f in fbs:                      # neighbours unaffected
            assert np.allclose(np.asarray(f.result(timeout=120)
                                          .toarray()), ref)
        st = sv.stats()
        assert st["arbiter"]["in_use_bytes"] == 0   # lease returned
        assert st["tenants"]["iso-a"]["failed"] == 1
        assert st["tenants"]["iso-b"]["completed"] == 3
        assert st["tenants"]["iso-b"]["failed"] == 0


def test_submit_retries_reruns_and_counts(mesh):
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ValueError("transient %d" % len(calls))
        return "ok"

    with serve.serving(workers=1) as sv:
        f = sv.submit(flaky, tenant="r", retries=2)
        assert f.result(timeout=60) == "ok"
        assert len(calls) == 3
        st = sv.stats()
        assert st["totals"]["retried"] == 2
        assert st["tenants"]["r"]["retried"] == 2
        assert st["tenants"]["r"]["completed"] == 1


def test_submit_retries_exhausted_chains_attempts(mesh):
    def always():
        raise ValueError("still broken")

    with serve.serving(workers=1) as sv:
        f = sv.submit(always, tenant="r", retries=1)
        exc = f.exception(timeout=60)
    assert isinstance(exc, RuntimeError) and "after 1 retries" in str(exc)
    assert isinstance(exc.__cause__, ValueError)          # final attempt
    assert isinstance(exc.__cause__.__cause__, ValueError)  # original


def test_submit_deadline_expires_in_queue(mesh):
    release = threading.Event()

    def blocker():
        release.wait(30)
        return 1

    with serve.serving(workers=1) as sv:
        f1 = sv.submit(blocker, tenant="x")
        f2 = sv.submit(lambda: 2, tenant="x", deadline=0.05)
        time.sleep(0.2)                    # the deadline passes queued
        release.set()
        assert f1.result(timeout=60) == 1
        with pytest.raises(serve.DeadlineError,
                           match="before the job started"):
            f2.result(timeout=60)
        st = sv.stats()
        assert st["totals"]["expired"] == 1
        assert st["totals"]["failed"] >= 1


def test_submit_deadline_stops_retries(mesh):
    calls = []

    def failing():
        calls.append(1)
        time.sleep(0.08)
        raise ValueError("attempt %d" % len(calls))

    with serve.serving(workers=1) as sv:
        f = sv.submit(failing, tenant="d", retries=50, deadline=0.1)
        exc = f.exception(timeout=60)
    assert isinstance(exc, (ValueError, RuntimeError))
    assert len(calls) < 50                 # the deadline cut retries off


def test_submit_deadline_validation(mesh):
    with serve.serving(workers=1) as sv:
        with pytest.raises(ValueError, match="positive"):
            sv.submit(lambda: 1, deadline=0)


# ---------------------------------------------------------------------
# weighted fair share (ISSUE 10 satellite)
# ---------------------------------------------------------------------

def test_weights_validation(mesh):
    with pytest.raises(ValueError, match="positive integer"):
        serve.Server(workers=1, weights={"a": 0}).close()


def _ordered_pops(weights, jobs):
    """Submit ``jobs`` (a list of tenant tags) while ONE worker is held
    on a blocker job, release, and return the order the scheduler ran
    them in — the weighted-round-robin observable."""
    order = []
    gate = threading.Event()

    def blocker():
        gate.wait(30)

    def tagged(t):
        return lambda: order.append(t)

    with serve.serving(workers=1, weights=weights) as sv:
        hold = sv.submit(blocker, tenant="hold")
        time.sleep(0.15)              # the worker is inside blocker now
        futs = [sv.submit(tagged(t), tenant=t) for t in jobs]
        gate.set()
        hold.result(timeout=60)
        for f in futs:
            f.result(timeout=60)
    return order


def test_default_weights_keep_round_robin_order():
    # a then b queued; weight 1 each -> strict alternation (bit-for-bit
    # the pre-weights scheduler)
    order = _ordered_pops(None, ["a"] * 4 + ["b"] * 4)
    assert order == ["a", "b", "a", "b", "a", "b", "a", "b"]


def test_weighted_fair_share_serves_weight_jobs_per_turn():
    # weight 3 vs 1: each rotation serves up to 3 of a's jobs, then one
    # of b's — the integer-credit generalisation
    order = _ordered_pops({"a": 3}, ["a"] * 6 + ["b"] * 2)
    assert order == ["a", "a", "a", "b", "a", "a", "a", "b"]


def test_weighted_fair_share_starvation_freedom():
    # a floods with a big weight; b (weight 1) is still served within
    # ONE rotation — at most weight(a) pops after the turn starts
    order = _ordered_pops({"a": 5}, ["a"] * 12 + ["b"])
    assert "b" in order
    assert order.index("b") <= 5, order


def test_weight_turn_forfeited_when_queue_drains():
    # a has weight 3 but only 2 jobs: its turn ends early, b runs next
    order = _ordered_pops({"a": 3}, ["a", "a", "b", "b"])
    assert order == ["a", "a", "b", "b"]


# ---------------------------------------------------------------------
# fleet-warm start (ROADMAP item 4 remainder)
# ---------------------------------------------------------------------

def test_start_warm_serves_first_request_without_fresh_compiles(
        mesh, tmp_path):
    """A pre-seeded persistent cache + Server(start_warm=dir): the
    warmed server's first request re-lowers but runs ZERO fresh XLA
    compiles (persistent_misses flat), and every disk-served compile is
    counted as a persistent_warm_hits."""
    import os
    cache = str(tmp_path / "warm-xla")
    x = _x((32, 8, 4))

    def make():
        return bolt.array(x, mesh).map(ADD1).sum()

    try:
        # seed: an earlier process ran the fleet's pipeline shape
        # (clear first — an identical program compiled earlier in THIS
        # suite would otherwise serve from the in-memory cache and
        # never reach the disk layer)
        engine.clear()
        engine.persistent_cache(cache)
        np.asarray(make().toarray())
        if not os.listdir(cache):
            pytest.skip("backend does not serialize executables")
        engine.persistent_cache(enable=False)

        # "fresh process": drop the in-memory executables, then serve
        # with start_warm -- the first request must hit disk only
        engine.clear()
        c0 = engine.counters()
        with serve.serving(workers=1, start_warm=cache) as sv:
            assert sv.warm_dir == cache
            out = sv.submit(make(), tenant="w").result(timeout=120)
        c1 = engine.counters()
        assert np.allclose(np.asarray(out.toarray()),
                           (x + 1).sum(axis=0))
        assert c1["persistent_warm_hits"] > c0["persistent_warm_hits"]
        assert c1["persistent_misses"] == c0["persistent_misses"], \
            "warm start paid a fresh XLA compile"
        assert c1["aot_compiles"] > c0["aot_compiles"]
    finally:
        engine.persistent_cache(enable=False)
