"""HBM-scale guards (VERDICT r2 weak-4): ops with input-multiple
transients switch to bounded chunked paths above ``_CHUNK_MAX_BYTES``
(forced small here), and ops with inherently input-sized outputs check
their demand up front — a clear MemoryError (known limit) or
HBMPressureWarning (assumed limit) instead of an opaque XLA OOM."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu._compat import OLD_JAX
from bolt_tpu.base import HBMPressureWarning
from bolt_tpu.tpu import array as array_mod


def _x(shape=(32, 8, 6), seed=40):
    return np.random.RandomState(seed).randn(*shape)


def test_unique_chunked_parity(mesh, monkeypatch):
    # force the CHUNKED path (the shard-local path would otherwise serve
    # this multi-device layout first)
    import bolt_tpu.ops.group as group
    monkeypatch.setattr(group, "_unique_sharded", lambda *a: None)
    monkeypatch.setattr(array_mod, "_CHUNK_MAX_BYTES", 256)
    x = np.random.RandomState(41).randint(0, 13, size=(16, 9)).astype(float)
    b = bolt.array(x, mesh)
    u, c = bolt.ops.unique(b, return_counts=True)
    un, cn = np.unique(x, return_counts=True)
    assert np.array_equal(u, un) and np.array_equal(c, cn)
    assert u.dtype == un.dtype and c.dtype == np.int64
    # the chunked programs actually ran
    assert any(k[0] == "unique-chunk-sort" for k in array_mod._JIT_CACHE)
    # no-counts variant
    assert np.array_equal(bolt.ops.unique(b), un)


def test_unique_chunked_nan_merge(mesh, monkeypatch):
    # NaNs collapse to ONE entry across chunks, counts aggregated —
    # same as modern numpy on the whole array
    import bolt_tpu.ops.group as group
    monkeypatch.setattr(group, "_unique_sharded", lambda *a: None)
    monkeypatch.setattr(array_mod, "_CHUNK_MAX_BYTES", 64)
    x = np.array([[1.0, np.nan, 2.0, np.nan]] * 8)
    b = bolt.array(x, mesh)
    u, c = bolt.ops.unique(b, return_counts=True)
    un, cn = np.unique(x, return_counts=True)
    assert u.shape == un.shape
    assert np.isnan(u[-1]) and np.array_equal(u[:-1], un[:-1])
    assert np.array_equal(c, cn)


def test_unique_chunked_deferred_chain(mesh, monkeypatch):
    import bolt_tpu.ops.group as group
    monkeypatch.setattr(group, "_unique_sharded", lambda *a: None)
    monkeypatch.setattr(array_mod, "_CHUNK_MAX_BYTES", 128)
    x = np.random.RandomState(42).randint(0, 5, size=(12, 6)).astype(float)
    m = bolt.array(x, mesh).map(lambda v: v * 3)
    assert np.array_equal(bolt.ops.unique(m), np.unique(x * 3))


def test_argsort_chunked_parity(mesh, monkeypatch):
    monkeypatch.setattr(array_mod, "_CHUNK_MAX_BYTES", 512)
    x = _x()
    b = bolt.array(x, mesh)
    for axis, kind in [(1, None), (0, "stable"), (-1, "stable"), (2, None)]:
        got = b.argsort(axis=axis, kind=kind)
        assert got.split == b.split
        assert np.array_equal(np.asarray(got.toarray()),
                              x.argsort(axis=axis, kind="stable")
                              if kind else np.asarray(
                                  bolt.array(x).argsort(axis=axis).toarray())
                              ), (axis, kind)
    assert any(k[0] == "argsort-slab" for k in array_mod._JIT_CACHE)
    # flat argsort has no slab axis: falls through to the single program
    flat = bolt.array(x, mesh).argsort(axis=None, kind="stable")
    assert np.array_equal(np.asarray(flat.toarray()),
                          x.argsort(axis=None, kind="stable"))


def test_unique_sharded_path_parity(mesh, mesh2d):
    # the shard-local unique: per-shard sort/mask/gather + exact host
    # merge, zero collectives — serves every common multi-device layout
    from bolt_tpu.ops import unique
    import bolt_tpu.ops.group as group
    x = np.random.RandomState(45).randint(0, 9, size=(16, 6)).astype(float)
    x[3, 2] = np.nan
    x[9, 1] = np.nan
    for m in (mesh, mesh2d):
        import bolt_tpu as _b
        b = _b.array(x, m, axis=(0,) if m is mesh else (0, 1))
        u, c = unique(b, return_counts=True)
        un, cn = np.unique(x, return_counts=True)
        assert u.shape == un.shape
        assert np.array_equal(u[:-1], un[:-1]) and np.isnan(u[-1])
        assert np.array_equal(c, cn)
        # THIS mesh's shard program ran (key carries the mesh — without
        # this the 2-d iteration could pass on the 1-d mesh's entry);
        # compare by topology: ensure_auto may rebuild the Mesh object
        assert any(k[0] == "unique-shard-sort"
                   and k[-1].axis_names == m.axis_names
                   for k in array_mod._JIT_CACHE), m
    # deferred chains materialise through it
    mch = bolt.array(np.full((8, 4), 2.0), mesh).map(lambda v: v + 1)
    assert np.array_equal(unique(mch), [3.0])


@pytest.mark.xfail(
    condition=OLD_JAX,
    strict=False,
    reason="known old-jax residual (seed-present): 0.4.x rejects the "
           "uneven device_put through pjit_check_aval_sharding with "
           "different wording, so the 'evenly divide' match in part (b) "
           "of this gate never fires; fixed on runtimes with "
           "jax.shard_map")
def test_unique_sharded_declines_ineligible_layouts(mesh):
    # layouts the gate declines fall back to the whole-array program
    # with CORRECT COUNTS (a wrongly-accepting gate on a replicated
    # layout would multiply counts by the device count — values alone
    # would merge clean and hide it)
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from bolt_tpu.ops import unique
    from bolt_tpu.tpu.array import BoltArrayTPU
    # (a) replicated: 6 keys cannot divide 8 devices under key_spec
    x = np.random.RandomState(46).randint(0, 5, size=(6, 4)).astype(float)
    b = bolt.array(x, mesh)
    u, c = unique(b, return_counts=True)
    un, cn = np.unique(x, return_counts=True)
    assert np.array_equal(u, un) and np.array_equal(c, cn)
    # (b) uneven splits cannot even be CONSTRUCTED in this jax version
    # (NamedSharding rejects them at device_put) — the divisibility gate
    # in _unique_sharded is defense in depth for future/other layouts
    xu = np.zeros((12, 4))
    with pytest.raises(ValueError, match="evenly divide"):
        jax.device_put(xu, NamedSharding(mesh, P("k", None)))
    _ = BoltArrayTPU      # imported above; gate itself exercised in (a)


def test_unique_fallback_lowering_pinned(mesh, monkeypatch):
    # the whole-array fallback (declined layouts) still global-sorts;
    # pin its program so a GSPMD partitioner change is NOTICED (its
    # operand gather is the one documented lowering exception)
    import bolt_tpu.ops.group as group
    from bolt_tpu.ops import unique
    from bolt_tpu.tpu import array as array_mod
    monkeypatch.setattr(group, "_unique_sharded", lambda *a: None)
    x = np.random.RandomState(48).randint(0, 7, size=(64, 4)).astype(float)
    b = bolt.array(x, mesh)
    assert np.array_equal(unique(b), np.unique(x))
    fns = [v for k, v in array_mod._JIT_CACHE.items()
           if k[0] == "unique-sort"]
    assert fns
    txt = fns[-1].lower(b._data).compile().as_text()
    assert "sort" in txt


def test_topk_chunked_parity(mesh, monkeypatch):
    monkeypatch.setattr(array_mod, "_CHUNK_MAX_BYTES", 512)
    x = _x()
    b = bolt.array(x, mesh)
    for axis in (0, 1):
        v, i = bolt.ops.topk(b, 3, axis=axis)
        lv, li = bolt.ops.topk(bolt.array(x), 3, axis=axis)
        assert np.allclose(np.asarray(v.toarray()),
                           np.asarray(lv.toarray())), axis
        assert np.array_equal(np.asarray(i.toarray()),
                              np.asarray(li.toarray())), axis
    assert any(k[0] == "topk-slab" for k in array_mod._JIT_CACHE)


def test_topk_chunked_split_key(mesh, monkeypatch):
    # two arrays of the same shape but different splits must NOT share a
    # compiled cat program (r3 review finding: the key omitted split, so
    # the second call's outputs were constrained to the first's split)
    monkeypatch.setattr(array_mod, "_CHUNK_MAX_BYTES", 512)
    x = _x((8, 8, 6))
    v1, _ = bolt.ops.topk(bolt.array(x, mesh, axis=(0,)), 2, axis=1)
    v2, _ = bolt.ops.topk(bolt.array(x, mesh, axis=(0, 1)), 2, axis=2)
    assert v1.split == 1 and v2.split == 2
    lv1, _ = bolt.ops.topk(bolt.array(x), 2, axis=1)
    lv2, _ = bolt.ops.topk(bolt.array(x), 2, axis=2)
    assert np.allclose(np.asarray(v1.toarray()), np.asarray(lv1.toarray()))
    assert np.allclose(np.asarray(v2.toarray()), np.asarray(lv2.toarray()))


def test_np_quantile_numpy_only_method_falls_back(mesh):
    # jnp.quantile lacks numpy's other estimators; the dispatch serves
    # them on the host path instead of erroring (r3 review finding)
    x = _x()
    b = bolt.array(x, mesh)
    got = np.quantile(b, 0.5, method="inverted_cdf")
    assert np.allclose(got, np.quantile(x, 0.5, method="inverted_cdf"))


def test_slab_plan_picks_largest_carry_axis(monkeypatch):
    # a small first axis cannot cut slabs fine enough to honour the
    # byte bound; the plan must pick the LARGEST other axis (r3 review)
    monkeypatch.setattr(array_mod, "_CHUNK_MAX_BYTES", 1 << 10)
    cax, pairs = array_mod.slab_plan((2, 64, 8), axis=2, in_bytes=1 << 13)
    assert cax == 1
    assert len(pairs) == 8              # 8 KB / 1 KB target
    assert pairs[0][0] == 0 and pairs[-1][1] == 64
    assert array_mod.slab_plan((1, 16), axis=1, in_bytes=1 << 20) is None


def test_topk_hbm_check_engages(mesh, monkeypatch):
    # topk's unchunked paths carry the same up-front demand check as
    # sort/argsort (r3 review finding: it had none)
    monkeypatch.setattr(array_mod, "_HBM_LIMIT_OVERRIDE", 1 << 10)
    b = bolt.array(_x(), mesh)
    with pytest.raises(MemoryError, match="topk"):
        bolt.ops.topk(b, 2, axis=-1)


def test_small_inputs_skip_chunked_paths(mesh):
    # below the threshold nothing slab-shaped compiles
    x = _x((6, 4))
    bolt.ops.unique(bolt.array(x, mesh))
    bolt.array(x, mesh).argsort(axis=0)
    bolt.ops.topk(bolt.array(x, mesh), 2, axis=0)
    assert not any(k[0] in ("unique-chunk-sort", "argsort-slab",
                            "topk-slab")
                   for k in array_mod._JIT_CACHE
                   if len(k) > 1 and k[1] in ((6, 4), (24,)))


def test_hbm_check_known_limit_raises(mesh, monkeypatch):
    monkeypatch.setattr(array_mod, "_HBM_LIMIT_OVERRIDE", 1 << 10)
    b = bolt.array(_x(), mesh)
    with pytest.raises(MemoryError, match="cumsum"):
        b.cumsum()
    with pytest.raises(MemoryError, match="sort"):
        b.sort()
    with pytest.raises(MemoryError, match="argsort"):
        b.argsort(axis=None)
    # env var is honoured the same way
    monkeypatch.setattr(array_mod, "_HBM_LIMIT_OVERRIDE", None)
    monkeypatch.setenv("BOLT_HBM_BYTES", str(1 << 10))
    with pytest.raises(MemoryError, match="cumprod"):
        b.cumprod()


def test_hbm_check_assumed_limit_warns(mesh, monkeypatch):
    monkeypatch.setattr(array_mod, "_hbm_limit", lambda: (1 << 10, False))
    b = bolt.array(_x(), mesh)
    with pytest.warns(HBMPressureWarning, match="ASSUMED"):
        out = b.cumsum(axis=0)
    # the op still runs (larger chips may fit it)
    assert np.allclose(np.asarray(out.toarray()), _x().cumsum(axis=0))


def test_hbm_check_under_limit_is_silent(mesh, monkeypatch):
    import warnings
    monkeypatch.setattr(array_mod, "_HBM_LIMIT_OVERRIDE", 1 << 40)
    b = bolt.array(_x(), mesh)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        b.cumsum(axis=0)
        b.argsort(axis=0)
