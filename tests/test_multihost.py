"""Pod-scale execution suite: a REAL 2-process ``jax.distributed``
localhost CPU cluster (scripts/multihost_harness.py) proving the
per-process streaming contract end to end.

What the cluster runs (ISSUE 10 acceptance):

* streamed ``fromcallback(..., per_process=True)`` ``sum`` AND fused
  ``stats("sum", "var")`` BIT-IDENTICAL to the single-process run of
  the same crafted data (power-of-two slab counts, period-aligned
  shards — the crafted-Welford exactness trick);
* each process compiles the slab programs EXACTLY once (engine
  counters: a second streamed pass adds zero misses/aot compiles);
* each process produces and uploads ONLY its own shard of every slab
  (the loader's observed row count is its per-process fraction);
* uneven-tail slabs refuse with the pointed BLT012 error, and
  ``analysis.check`` forecasts the same code;
* ``fromiter`` streams re-iterable block lists per process and refuses
  one-shot iterators pointedly (the BLT011 reasoning);
* ``kill -9`` of ONE process surfaces as a pointed harness error
  naming the dead process (peers are unblocked from the dead
  collective);
* a checkpointed run SIGKILLed on every process resumes from the
  rendezvous-consistent per-process shard checkpoint, bit-identically.

The in-process half (no cluster) unit-tests the
``parallel.multihost`` helpers on a single-process mesh.
"""

import os
import shutil
import tempfile

import numpy as np
import pytest

import jax

import bolt_tpu as bolt
from bolt_tpu.parallel import default_mesh, multihost

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the localhost cluster needs the CPU cross-process collective
# transport (gloo); probe the config flag without touching a backend
_HAS_GLOO = "jax_cpu_collectives_implementation" in getattr(
    jax.config, "values", {}) or hasattr(
    jax.config, "jax_cpu_collectives_implementation")

needs_cluster = pytest.mark.skipif(
    not _HAS_GLOO,
    reason="no CPU cross-process collective transport on this jax")

pytestmark = pytest.mark.multihost


def _harness():
    from bolt_tpu.utils import load_script
    return load_script("multihost_harness")


# ---------------------------------------------------------------------
# in-process helpers (single-process mesh)
# ---------------------------------------------------------------------

def test_topology_single_process():
    assert multihost.process_count() == 1
    assert multihost.process_index() == 0
    assert not multihost.is_multiprocess()
    assert multihost.topology_token() is None
    mesh = default_mesh()
    assert multihost.mesh_process_count(mesh) == 1
    assert not multihost.is_multiprocess(mesh)


def test_local_slab_spec_identity_single_process():
    mesh = default_mesh()
    spec = multihost.local_slab_spec(mesh, (64, 8), 1)
    assert spec.nproc == 1
    assert spec.local_range(0, 16) == (0, 16)
    assert spec.local_range(48, 64) == (48, 64)
    # source-like duck typing (a StreamSource)
    src = bolt.fromcallback(lambda idx: np.zeros((8, 4), np.float32)[idx],
                            (8, 4), mesh, dtype=np.float32)._stream
    spec2 = multihost.local_slab_spec(src)
    assert spec2.shape == (8, 4) and spec2.split == 1


def test_slab_divisibility_single_process_is_quiet():
    mesh = default_mesh()
    assert multihost.slab_divisibility_error(
        mesh, (7, 3), 1, [(0, 7)]) is None


def test_barrier_noop_single_process():
    multihost.barrier("test")          # must not dispatch anything


def test_local_value_roundtrip():
    mesh = default_mesh()
    b = bolt.array(np.arange(6.0).reshape(2, 3), mesh)
    assert np.array_equal(multihost.local_value(b._data),
                          np.arange(6.0).reshape(2, 3))
    assert np.array_equal(multihost.local_value(np.ones(3)), np.ones(3))


def test_per_process_flag_single_process_parity():
    """per_process=True on a one-process mesh is the plain streaming
    path — one loader runs unchanged from laptop to pod."""
    mesh = default_mesh()
    x = np.arange(64 * 4, dtype=np.float32).reshape(64, 4)

    def make(per_process):
        return bolt.fromcallback(lambda idx: x[idx], (64, 4), mesh,
                                 dtype=np.float32, chunks=16,
                                 per_process=per_process)

    a = np.asarray(make(True).map(lambda v: v + 1).sum().toarray())
    b = np.asarray(make(False).map(lambda v: v + 1).sum().toarray())
    assert np.array_equal(a, b)


def test_per_process_requires_dtype():
    mesh = default_mesh()
    with pytest.raises(ValueError, match="explicit dtype"):
        bolt.fromcallback(lambda idx: np.zeros((4, 2))[idx], (4, 2),
                          mesh, per_process=True)


def test_initialize_idempotent_single_process():
    # single-process: jax.distributed declines (no coordinator), the
    # helper reports False and stays un-armed
    assert multihost.initialize() is False
    assert not multihost.is_initialized()
    assert multihost.shutdown() is False


# ---------------------------------------------------------------------
# the 2-process cluster (module-scoped: ONE cluster serves many asserts)
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def parity():
    if not _HAS_GLOO:
        pytest.skip("no CPU cross-process collective transport")
    mh = _harness()
    # devs=2: two devices per process, so the payload also exercises a
    # mesh axis that REPLICATES the slab within a process
    results, out, _ = mh.run_cluster("stream_parity", nproc=2, devs=2)
    mh.run_cluster("single_ref", nproc=1, devs=4, out_dir=out)
    yield results, out
    shutil.rmtree(out, ignore_errors=True)


@needs_cluster
def test_streamed_sum_bit_identical_across_pod(parity):
    _, out = parity
    ref = np.load(os.path.join(out, "ref_sum.npy"))
    for pid in (0, 1):
        got = np.load(os.path.join(out, "sum.%d.npy" % pid))
        assert np.array_equal(got, ref), pid


@needs_cluster
def test_streamed_stats_bit_identical_across_pod(parity):
    _, out = parity
    for name in ("stats_sum", "stats_var"):
        ref = np.load(os.path.join(out, "ref_%s.npy" % name))
        for pid in (0, 1):
            got = np.load(os.path.join(out, "%s.%d.npy" % (name, pid)))
            assert np.array_equal(got, ref), (name, pid)


@needs_cluster
def test_fromiter_reiterable_streams_per_process(parity):
    _, out = parity
    ref = np.load(os.path.join(out, "ref_fromiter_sum.npy"))
    for pid in (0, 1):
        got = np.load(os.path.join(out, "fromiter_sum.%d.npy" % pid))
        assert np.array_equal(got, ref), pid


@needs_cluster
def test_each_process_compiles_exactly_once(parity):
    results, _ = parity
    for r in results:
        assert r["aot_first_pass"] > 0
        assert r["recompiles_second_pass"] == 0, r


@needs_cluster
def test_per_process_ingest_contract(parity):
    """Each host produced only its own shard of every slab (the loader
    saw exactly its per-process record fraction), and the transfer
    counters tallied LOCAL bytes."""
    results, _ = parity
    for r in results:
        assert r["rows_produced"] == r["rows_expected"], r
        # two streamed sum passes of 32 local records x 8 f32 values
        assert r["transfer_bytes"] == 2 * 32 * 8 * 4, r


@needs_cluster
def test_replicating_mesh_axis_folds_exactly(parity):
    """A 2-axis mesh whose second axis does NOT shard the key
    replicates each per-process shard across local devices; the
    per-process split must still resolve (replica boxes deduped) and
    the collective fold — over the participating axis only — must stay
    exact."""
    results, _ = parity
    for r in results:
        assert r.get("replicated_axis_ok") is True, r


@needs_cluster
def test_blt012_uneven_slab_refused_and_forecast(parity):
    results, _ = parity
    for r in results:
        assert r["blt012_refused"] is True, r
        assert r["blt012_forecast"] is True, r


@needs_cluster
def test_oneshot_iterator_pointed_error_and_hygiene(parity):
    results, _ = parity
    for r in results:
        assert r["oneshot_refused"] is True, r
        assert r["explain_multiprocess"] is True, r
        assert r["leaked_spans"] == 0, r


@needs_cluster
def test_kill_one_process_raises_pointed_error():
    """kill -9 of ONE worker mid-stream: its peer blocks on the dead
    collective, and the harness terminates it and names the dead
    process — the pod's fault story."""
    mh = _harness()
    ck = tempfile.mkdtemp(prefix="bolt-mh-kill1-")
    try:
        with pytest.raises(RuntimeError,
                           match=r"process 1 died \(exit code -9\)"):
            mh.run_cluster(
                "resume", nproc=2, devs=1, timeout=120,
                env={"BOLT_MH_CKPT": ck},
                worker_env={1: {"BOLT_CHAOS": "stream.upload:3:kill"}})
    finally:
        shutil.rmtree(ck, ignore_errors=True)


# ---------------------------------------------------------------------
# ISSUE 11: pod fault tolerance — kill -9 -> PeerLostError on every
# survivor -> reform 3->2 -> resume, on a REAL localhost cluster
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def reform():
    """ONE 3-process kill -9 scenario (plus its clean 2-process
    baseline) serves every reform assertion below — see
    scripts/multihost_harness.py run_reform_bench/payload_reform."""
    if not _HAS_GLOO:
        pytest.skip("no CPU cross-process collective transport")
    mh = _harness()
    return mh.run_reform_bench()


@needs_cluster
def test_kill_raises_peerlost_on_every_survivor(reform):
    """kill -9 of ONE process: every survivor raises the pointed
    PeerLostError (no hang), the victim named by the liveness watch
    within 2x the watchdog deadline."""
    assert reform["victim_rc"] == -9
    assert reform["survivors"] == 2
    assert reform["peer_lost_everywhere"]
    assert reform["detection_s"] <= 2 * reform["pod_timeout"], reform


@needs_cluster
def test_watchdog_barrier_converts_on_survivors(reform):
    """A barrier taken next to the dead peer fails with PeerLostError
    on every survivor — within 2x the deadline, never an infinite
    gloo hang."""
    assert reform["barrier_peerlost"]
    assert reform["barrier_s"] <= 2 * reform["pod_timeout"], reform


@needs_cluster
def test_reform_and_resume_bit_identical(reform):
    """multihost.reform onto the 2 survivors + resume from the
    3-process checkpoint (topology remap) reproduces the unkilled
    2-process run BIT for bit — for the streamed sum AND the fused
    stats("sum","var") (whose resume rides the pod ABORT-path
    checkpoint write)."""
    assert reform["bit_identical"]
    assert reform["sum_resumes"] >= 2        # one per survivor
    assert reform["stats_resumes"] >= 2


@needs_cluster
def test_reform_recovery_bounded_and_clean(reform):
    """Recovery (learn -> barrier probe -> reform -> resume) stays
    under 2x the clean 2-process wall, and the scenario leaves no
    stale checkpoint files and no leaked spans on any survivor."""
    assert reform["recovery_over_clean"] < 2.0, reform
    assert reform["stale_checkpoint_files"] == []
    assert reform["leaked_spans"] == 0


# ---------------------------------------------------------------------
# ISSUE 12: self-healing pods — kill -9 under Server(supervise=True)
# -> automatic 3->2 shrink, a restarted replacement rejoins -> 2->3
# re-expansion, ZERO caller intervention, on a REAL localhost cluster
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def elastic():
    """ONE 3→2→3 supervised scenario (kill -9 mid-stream, replacement
    rejoin mid-stream, plus its clean 3-process reference) serves every
    self-healing assertion below — see scripts/multihost_harness.py
    run_supervise_bench/payload_supervise."""
    if not _HAS_GLOO:
        pytest.skip("no CPU cross-process collective transport")
    mh = _harness()
    return mh.run_supervise_bench()


@needs_cluster
def test_supervised_shrink_is_automatic(elastic):
    """kill -9 of one worker under Server(supervise=True): survivors'
    futures SUCCEED with zero caller intervention — the held retry
    resumes once the supervisor's automatic 3→2 reform lands — and
    detection stays within 2x BOLT_POD_TIMEOUT."""
    assert elastic["victim_rc"] == -9
    assert elastic["survivors"] == 2
    assert elastic["detection_s"] <= 2 * elastic["pod_timeout"], elastic
    # >= 1 somewhere, not "one per survivor per job": under full-suite
    # load the kill can land before a survivor's first A checkpoint, and
    # when recovery rides the backend-heal path job A re-runs from
    # scratch (zero A resumes) — so the resume PATH is proven by at
    # least one resume across the scenario (A or the mid-B rejoin),
    # correctness by bit-identity (the PR 13/18 flake)
    assert elastic["a_resumes"] >= 1 or elastic["b_resumes"] >= 1
    assert elastic["reforms"] >= 1
    # degraded-capacity admission: the arbiter budget rescaled to the
    # surviving share after the shrink
    assert abs(elastic["budget_share_after_a"] - 2 / 3) < 1e-6


@needs_cluster
def test_rejoin_re_expands_the_pod(elastic):
    """A restarted replacement process rings the rejoin door MID-B:
    incumbents quiesce at a slab-boundary checkpoint, reform 2→3, and
    B resumes on the re-expanded pod — full capacity restored."""
    assert elastic["rejoined"] == 1
    assert elastic["rejoins"] >= 1
    assert elastic["nproc_final"] == 3
    assert elastic["b_resumes"] >= 1          # see the a_resumes note
    assert elastic["budget_share_after_b"] == 1.0


@needs_cluster
def test_elastic_bit_identical_and_bounded(elastic):
    """Every artifact of the 3→2→3 scenario — streamed sums A and B,
    fused stats("sum","var") C — is BIT-IDENTICAL to the unkilled
    3-process run, the whole scenario stays under 2.5x the clean wall,
    and nothing leaks: arbiter bytes, spans, stale checkpoints, stale
    transport markers."""
    assert elastic["bit_identical"]
    # the wall bound is bimodal (PR 18 diagnosis): the common mode
    # recovers in < 2.5x the clean wall, but when the kill lands while
    # a survivor is blocked INSIDE a gloo collective the recovery eats
    # two uninterruptible C++ waits — gloo's ~30s GetKeyValue timeout
    # plus XLA's 2m topology-exchange window on the rebuild (healed,
    # not crashed, by multihost.heal_backend_init) — so the slow mode
    # is gated by its absolute coordination-stall budget instead
    assert (elastic["scenario_over_clean"] < 2.5
            or elastic["scenario_s"] - elastic["clean_s"] < 165.0), \
        elastic
    assert elastic["arbiter_bytes"] == 0
    assert elastic["leaked_spans"] == 0
    assert elastic["stale_ckpt"] == []
    assert elastic["stale_markers"] == 0


@needs_cluster
def test_blt014_and_explain_on_the_live_pod(elastic):
    """On the re-expanded pod the checker flags a fromiter source as
    BLT014 (a rejoined process could never re-ingest its shard) and
    explain() renders the SUPERVISED recovery plan."""
    assert elastic["blt014"]
    assert elastic["explain_supervised"]


@needs_cluster
def test_pre_collective_death_bounded():
    """A peer killed BEFORE the first collective: the survivor's
    readiness rendezvous raises the pointed PeerLostError within 2x
    BOLT_POD_TIMEOUT — not gloo's ~30s connect timeout (the documented
    PR 11 bound, now closed)."""
    mh = _harness()
    r = mh.run_precollective_probe()
    assert r["victim_rc"] == -9
    assert r["pre_peerlost"] is True, r
    assert r["pre_elapsed"] <= 2 * r["pod_timeout"], r
    assert "ready" in (r["pre_phase"] or "")


@needs_cluster
def test_serve_pod_degrades_instead_of_deadlocking():
    """A serving tenant's in-flight future FAILS with PeerLostError
    when a pod peer dies mid-stream, the arbiter reads zero bytes
    after the abort, and admission drains until the reform
    notification resumes the queue."""
    import tempfile
    mh = _harness()
    base = tempfile.mkdtemp(prefix="bolt-mh-servepod-")
    try:
        res, out, rcs = mh.run_cluster(
            "serve_pod", nproc=2, devs=1, timeout=200, tolerate={1},
            env={"BOLT_POD_TIMEOUT": 2, "BOLT_MH_HARD_EXIT": "1",
                 "BOLT_POD_HB_DIR": os.path.join(base, "hb")},
            worker_env={1: {"BOLT_CHAOS": "stream.upload:5:kill"}})
        assert rcs[1] == -9
        r = res[0]
        assert r["future_error"] == "PeerLostError", r
        assert r["future_peer"] == 1
        assert r["arbiter_bytes_after_abort"] == 0
        assert r["pod_paused"] and r["pod_resumed"]
        assert r["leaked_spans"] == 0
        shutil.rmtree(out, ignore_errors=True)
    finally:
        shutil.rmtree(base, ignore_errors=True)


@needs_cluster
def test_checkpoint_resume_across_restarted_pod():
    """The full fault-tolerance loop on a pod: a clean 2-process
    reference, a run SIGKILLed on EVERY process mid-stream (leaving the
    rendezvous-consistent per-process shard checkpoint), and a
    restarted 2-process run that RESUMES — bit-identical, with
    stream_resumes counted and no stale checkpoint left behind."""
    mh = _harness()
    ck_clean = tempfile.mkdtemp(prefix="bolt-mh-ckA-")
    ck = tempfile.mkdtemp(prefix="bolt-mh-ckB-")
    outs = []
    try:
        res, out, _ = mh.run_cluster("resume", nproc=2, devs=1,
                                     env={"BOLT_MH_CKPT": ck_clean})
        outs.append(out)
        ref = np.load(os.path.join(out, "resume_sum.0.npy"))
        assert all(r["resumes"] == 0 and r["slabs"] == 8 for r in res)
        # a finished run leaves no stale checkpoint
        assert not os.path.exists(os.path.join(ck_clean,
                                               "stream_meta.json"))

        # kill -9 EVERY process at its 7th upload; cadence 1 keeps the
        # peers in checkpoint lockstep, so a consistent watermark exists
        _, out2, rcs = mh.run_cluster(
            "resume", nproc=2, devs=1, expect_dead=True,
            env={"BOLT_MH_CKPT": ck,
                 "BOLT_CHAOS": "stream.upload:7:kill",
                 "BOLT_CHECKPOINT_EVERY": "1"})
        outs.append(out2)
        assert all(rc == -9 for rc in rcs), rcs
        assert os.path.exists(os.path.join(ck, "stream_meta.json"))
        shards = [p for p in os.listdir(ck)
                  if p.startswith("stream_state.p")]
        # one rendezvous-consistent shard file per process
        assert {p.split(".")[1] for p in shards} == {"p0", "p1"}, shards

        res3, out3, _ = mh.run_cluster(
            "resume", nproc=2, devs=1,
            env={"BOLT_MH_CKPT": ck, "BOLT_CHECKPOINT_EVERY": "1"})
        outs.append(out3)
        got = np.load(os.path.join(out3, "resume_sum.0.npy"))
        assert np.array_equal(got, ref)
        for r in res3:
            assert r["resumes"] == 1, r
            assert r["slabs"] < 8, r          # only the tail re-streamed
        assert not os.path.exists(os.path.join(ck, "stream_meta.json"))
    finally:
        for d in outs + [ck_clean, ck]:
            shutil.rmtree(d, ignore_errors=True)


# ---------------------------------------------------------------------
# codec-encoded ingest on a pod (ISSUE 14)
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def codec_pod():
    if not _HAS_GLOO:
        pytest.skip("no CPU cross-process collective transport")
    mh = _harness()
    results, out, _ = mh.run_cluster("codec_pod", nproc=2, devs=1)
    yield results, out
    shutil.rmtree(out, ignore_errors=True)


@needs_cluster
def test_codec_pod_local_shards_encode_and_fold(codec_pod):
    """Per-process shards ENCODE locally: each process ships half the
    bytes under bf16 (its own DCN/gloo link shrinks), the lossless
    delta-f32 pod fold is BIT-IDENTICAL to the raw pod fold on every
    process, and the sidecar codec (int8) refuses the multi-process
    mesh pointedly."""
    results, out = codec_pod
    raw0 = np.load(os.path.join(out, "codec_raw.0.npy"))
    for pid in (0, 1):
        assert np.array_equal(
            np.load(os.path.join(out, "codec_delta.%d.npy" % pid)),
            raw0), pid
        bf = np.load(os.path.join(out, "codec_bf16.%d.npy" % pid))
        assert np.allclose(bf, raw0, rtol=1e-2), pid
    for r in results:
        assert r["bf16_bytes"] * 2 == r["raw_bytes"], r
        assert r["delta_bytes"] == r["raw_bytes"], r
        assert r["sidecar_refused"] is True, r
        assert r["leaked_spans"] == 0, r


# ---------------------------------------------------------------------
# the dispatch-schedule verifier on the live pod (ISSUE 17)
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def sched_pod():
    if not _HAS_GLOO:
        pytest.skip("no CPU cross-process collective transport")
    mh = _harness()
    hb = tempfile.mkdtemp(prefix="bolt-sched-hb-")
    try:
        results, out, _ = mh.run_cluster(
            "sched_verify", nproc=2, devs=1,
            env={"BOLT_POD_HB_DIR": hb},
            worker_env={1: {"BOLT_CHAOS": "mh.sched.skew:1:raise"}})
        yield results
        shutil.rmtree(out, ignore_errors=True)
    finally:
        shutil.rmtree(hb, ignore_errors=True)


@needs_cluster
def test_schedule_digests_match_across_pod(sched_pod):
    """Matched schedules verify bit-identically: every process folded
    the same program keys in the same order into the same digest."""
    r0, r1 = sched_pod
    assert r0["count_matched"] > 0
    assert r0["count_matched"] == r1["count_matched"]
    assert r0["digest_matched"] == r1["digest_matched"]
    assert r0["sum"] == r1["sum"]


@needs_cluster
def test_schedule_skew_raises_pointed_divergence(sched_pod):
    """A chaos-injected extra enqueue on ONE process turns the next
    verify into a pointed ScheduleDivergenceError on EVERY process —
    naming the diverging peer and the first divergent slot — instead
    of a silent gloo hang."""
    r0, r1 = sched_pod
    assert r1["skewed"] is True and r0["skewed"] is False
    assert r0["divergence"]["peer"] == 1
    assert r1["divergence"]["peer"] == 0
    for r in (r0, r1):
        d = r["divergence"]
        assert d is not None, r
        assert "diverged" in d["message"]
        # the skew was ONE extra program appended after the matched
        # prefix: the first divergent slot is exactly the shared count
        assert d["index"] == r["count_matched"]
    # the skewed process's key log names the extra program it enqueued
    assert r1["divergence"]["local_key"], r1["divergence"]


# ---------------------------------------------------------------------
# the streamed two-phase shuffle on a real pod (ISSUE 18)
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def swap_pod():
    if not _HAS_GLOO:
        pytest.skip("no CPU cross-process collective transport")
    mh = _harness()
    results, out, _ = mh.run_cluster("swap", nproc=2, devs=1)
    yield results, out
    shutil.rmtree(out, ignore_errors=True)


@needs_cluster
def test_pod_streamed_swap_bit_identical(swap_pod):
    """The acceptance bit-compare: a streamed ``swap`` on a real
    2-process cluster — one ``lax.all_to_all`` per slab inside
    shard_map — equals the materialise-first in-memory swap BIT for
    bit on every process's shard, and equals the oracle transpose of
    the crafted source.  The swap stayed LAZY until consumed, moved
    bytes through the shuffle counters, spilled nothing (resident
    plan), refused pod spill pointedly, and leaked no spans."""
    results, out = swap_pod
    x = _harness()._crafted(64, 8)
    oracle = np.transpose(x, (1, 0))
    rows = oracle.shape[0] // 2
    for pid in (0, 1):
        streamed = np.load(
            os.path.join(out, "swap_streamed.%d.npy" % pid))
        mat = np.load(
            os.path.join(out, "swap_materialised.%d.npy" % pid))
        assert np.array_equal(streamed, mat), pid
        assert np.array_equal(
            streamed, oracle[pid * rows:(pid + 1) * rows]), pid
    for r in results:
        assert r["lazy_after_swap"] is True, r
        assert r["shuffle_bytes"] > 0, r
        assert r["spill_bytes"] == 0, r
        assert r["pod_spill_refused"] is True, r
        assert r["leaked_spans"] == 0, r
