"""Pallas kernel tests (interpret mode on the CPU mesh).

The kernels mirror XLA's fused reductions (measured at parity on TPU for
the 10 GB north-star shape); these tests pin their correctness and the
fallback behavior."""

import numpy as np
import pytest

import jax.numpy as jnp

from bolt_tpu.ops import fused_map_reduce, fused_stats
from bolt_tpu.ops.kernels import _block_plan


def test_block_plan_alignment():
    # unaligned minor dim: no plan (would force a padded relayout copy)
    assert _block_plan((64, 64), 4) is None
    assert _block_plan((3200, 200, 64, 64), 4) is None
    # aligned: tiles the leading axis
    grid, block = _block_plan((256, 384), 4)
    assert grid[0] * block[0] == 256
    assert block[1] == 384
    # huge trailing block: falls to 2-d grid
    plan = _block_plan((4, 512, 64, 128), 4)
    assert plan is not None
    grid, block = plan
    assert len(grid) in (1, 2)


def test_fused_map_reduce():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(32, 256).astype(np.float32))
    got = float(fused_map_reduce(x, lambda v: v * 2 + 1, interpret=True))
    expected = float(jnp.sum(x * 2 + 1))
    assert abs(got - expected) < 1e-2
    # identity fn
    got = float(fused_map_reduce(x, interpret=True))
    assert abs(got - float(x.sum())) < 1e-2


def test_fused_map_reduce_fallback():
    x = jnp.asarray(np.ones((5, 7), np.float32))  # unaligned: jnp fallback
    assert float(fused_map_reduce(x, lambda v: v + 1, interpret=True)) == 70.0


def test_integer_inputs_fall_back():
    # same-dtype accumulation would overflow small ints; ints take the
    # jnp path regardless of tiling
    x = jnp.full((8, 128), 100, dtype=jnp.int16)
    assert int(fused_map_reduce(x, interpret=True)) == 102400
    xi = jnp.arange(16 * 128, dtype=jnp.int32).reshape(16, 128)
    s, sq, mn, mx = fused_stats(xi, interpret=True)
    assert int(mn) == 0 and int(mx) == 16 * 128 - 1


def test_fused_stats():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(16, 128).astype(np.float32))
    s, sq, mn, mx = fused_stats(x, interpret=True)
    assert np.allclose(float(s), float(x.sum()), rtol=1e-4)
    assert np.allclose(float(sq), float((x * x).sum()), rtol=1e-4)
    assert float(mn) == float(x.min())
    assert float(mx) == float(x.max())


def test_fused_stats_2d_grid():
    rs = np.random.RandomState(2)
    # trailing block too big for one VMEM tile: forces the 2-d grid path
    x = jnp.asarray(rs.randn(3, 1024, 16, 128).astype(np.float32))
    grid, block = _block_plan(x.shape, 4)
    assert len(grid) == 2
    s, sq, mn, mx = fused_stats(x, interpret=True)
    assert np.allclose(float(s), float(x.sum()), rtol=1e-3)
    assert float(mx) == float(x.max())
    got = float(fused_map_reduce(x, lambda v: v + 1, interpret=True))
    assert np.allclose(got, float((x + 1).sum()), rtol=1e-3)


def test_svdvals_tall_skinny_matches_numpy():
    import numpy as np
    from bolt_tpu.ops import svdvals
    rs = np.random.RandomState(9)
    x = rs.randn(1024, 16).astype(np.float32)
    got = np.asarray(svdvals(jnp.asarray(x)))
    expect = np.linalg.svd(x, compute_uv=False)
    assert np.allclose(got, expect, rtol=1e-3, atol=1e-3)
    # batched
    xb = rs.randn(4, 512, 8).astype(np.float32)
    gotb = np.asarray(svdvals(jnp.asarray(xb)))
    expectb = np.stack([np.linalg.svd(m, compute_uv=False) for m in xb])
    assert np.allclose(gotb, expectb, rtol=1e-3, atol=1e-3)
    # wide input falls back to full SVD
    xw = rs.randn(8, 64).astype(np.float32)
    assert np.allclose(np.asarray(svdvals(jnp.asarray(xw))),
                       np.linalg.svd(xw, compute_uv=False), rtol=1e-3, atol=1e-3)


def test_tallskinny_pca_reconstructs_spectrum():
    import numpy as np
    from bolt_tpu.ops import tallskinny_pca
    rs = np.random.RandomState(10)
    x = rs.randn(2048, 12).astype(np.float32)
    comps, svals = tallskinny_pca(jnp.asarray(x), k=5)
    u, s, vt = np.linalg.svd(x, full_matrices=False)
    assert np.allclose(np.asarray(svals), s[:5], rtol=1e-3, atol=1e-3)
    # components match up to sign
    for i in range(5):
        c = np.asarray(comps)[:, i]
        assert min(np.linalg.norm(c - vt[i]), np.linalg.norm(c + vt[i])) < 1e-2


def test_svdvals_dtype_breadth():
    import numpy as np
    from bolt_tpu.ops import svdvals, tallskinny_pca
    import pytest
    rs = np.random.RandomState(11)
    # float64 under x64 must take the Gram path without TypeError
    x64 = rs.randn(512, 8)
    got = np.asarray(svdvals(jnp.asarray(x64)))
    assert np.allclose(got, np.linalg.svd(x64, compute_uv=False), rtol=1e-6)
    # complex: Gram needs the conjugate transpose; spectrum is real
    xc = (rs.randn(512, 8) + 1j * rs.randn(512, 8)).astype(np.complex128)
    gotc = np.asarray(svdvals(jnp.asarray(xc)))
    assert not np.iscomplexobj(gotc)
    assert np.allclose(gotc, np.linalg.svd(xc, compute_uv=False), rtol=1e-6)
    # wide input to tallskinny_pca is rejected, not silently wrong
    with pytest.raises(ValueError):
        tallskinny_pca(jnp.asarray(rs.randn(8, 64)))


# ----------------------------------------------------------------------
# fused_welford: the single-HBM-pass moments kernel (round 2) and its
# wiring into stats()
# ----------------------------------------------------------------------

def test_fused_welford_direct():
    from bolt_tpu.ops.kernels import fused_welford, welford_plan
    for shape in [(64, 256), (128, 4, 128), (96, 8, 2, 128)]:
        x = np.random.RandomState(1).randn(*shape).astype(np.float32)
        plan = welford_plan(shape, 4)
        assert plan is not None, shape
        mu, m2, mn, mx = (np.asarray(v) for v in fused_welford(jnp.asarray(x)))
        assert np.allclose(mu, x.mean(axis=0), rtol=1e-5, atol=1e-6)
        assert np.allclose(m2, ((x - x.mean(axis=0)) ** 2).sum(axis=0),
                           rtol=1e-4, atol=1e-4)
        assert np.array_equal(mn, x.min(axis=0))
        assert np.array_equal(mx, x.max(axis=0))


def test_fused_welford_fallbacks():
    from bolt_tpu.ops.kernels import fused_welford
    assert fused_welford(jnp.zeros((64, 100))) is None       # unaligned
    assert fused_welford(jnp.zeros((64, 128), jnp.int32)) is None
    assert fused_welford(jnp.zeros((1, 128))) is None        # one row


def test_stats_kernel_path_parity(mesh):
    # shard shapes chosen so welford_plan ENGAGES inside the shard_map
    # body (128-aligned minor dim, >=2 local rows) — the stats() result
    # must match the local oracle either way
    import bolt_tpu as bolt
    from bolt_tpu.ops.kernels import welford_plan
    x = np.random.RandomState(2).randn(32, 4, 128)
    shard_shape = (32 // 8,) + x.shape[1:]
    assert welford_plan(shard_shape, x.itemsize) is not None
    b, lo = bolt.array(x, mesh), bolt.array(x)
    for axes in [(0,), (0, 1)]:
        t, a = b.stats(axis=axes), lo.stats(axis=axes)
        assert np.allclose(t.mean(), a.mean())
        assert np.allclose(t.variance(), a.variance())
        assert np.allclose(t.stdev(), a.stdev())
        assert np.array_equal(t.min(), a.min())
        assert np.array_equal(t.max(), a.max())
        assert t.count() == a.count()


def test_sepfilter1d_parity_all_axes():
    # the one-HBM-pass window kernel vs a numpy oracle, every axis and
    # mode (interpret mode off-TPU; same code path as hardware)
    from bolt_tpu.ops.kernels import sepfilter1d
    rs = np.random.RandomState(60)
    x = jnp.asarray(rs.randn(6, 16, 256).astype(np.float32))
    taps = np.asarray([0.25, 0.5, 0.25])

    def oracle(a, ax, taps, mode):
        pad = [(0, 0)] * a.ndim
        pad[ax] = (len(taps) // 2,) * 2
        ap = np.pad(np.asarray(a), pad, mode=mode)
        out = np.zeros_like(np.asarray(a))
        for off, t in enumerate(taps):
            sl = [slice(None)] * a.ndim
            sl[ax] = slice(off, off + a.shape[ax])
            out += ap[tuple(sl)] * t
        return out

    for ax in (0, 1, 2):
        for mode in ("constant", "edge", "reflect", "symmetric"):
            got = sepfilter1d(x, taps, ax, mode=mode, interpret=True)
            assert got is not None, (ax, mode)
            assert np.allclose(np.asarray(got), oracle(x, ax, taps, mode),
                               rtol=1e-5, atol=1e-6), (ax, mode)


def test_sepfilter1d_gates():
    from bolt_tpu.ops import kernels
    # non-float input, unaligned minor dim: kernel declines
    assert kernels.sepfilter1d(jnp.ones((8, 256), jnp.int32),
                               [1.0], 0, interpret=True) is None
    assert kernels.sepfilter1d(jnp.ones((8, 100), jnp.float32),
                               [0.5, 0.5, 0.0], 0, interpret=True) is None
    # minor-axis windows wider than the direct-path crossover (9) take
    # the banded-matmul path (round 4)...
    wide = [1.0 / 15] * 15
    x = jnp.asarray(np.random.RandomState(61).randn(4, 128, 256)
                    .astype(np.float32))
    got = kernels.sepfilter1d(x, wide, 2, interpret=True)
    assert got is not None
    ap = np.pad(np.asarray(x), ((0, 0), (0, 0), (7, 7)))
    expect = sum(ap[:, :, o:o + 256] * w for o, w in enumerate(wide))
    assert np.allclose(np.asarray(got), expect, rtol=1e-5, atol=1e-6)
    # ...which no longer needs the second-minor dim aligned (the old
    # transpose detour did)
    x2 = jnp.asarray(np.random.RandomState(62).randn(4, 100, 256)
                     .astype(np.float32))
    got2 = kernels.sepfilter1d(x2, wide, 2, interpret=True)
    assert got2 is not None
    ap2 = np.pad(np.asarray(x2), ((0, 0), (0, 0), (7, 7)))
    exp2 = sum(ap2[:, :, o:o + 256] * w for o, w in enumerate(wide))
    assert np.allclose(np.asarray(got2), exp2, rtol=1e-5, atol=1e-6)
    # non-constant boundary modes keep the transpose detour, which DOES
    # need the second-minor dim aligned — unaligned declines
    assert kernels.sepfilter1d(x2, wide, 2, mode="reflect",
                               interpret=True) is None
    # an unaligned lane dim with an unaligned second-minor dim declines
    # every path (band needs the lane 128-aligned, the detour needs the
    # second-minor)
    assert kernels.sepfilter1d(jnp.ones((4, 100, 250), jnp.float32),
                               wide, 2, interpret=True) is None
    # plan gating mirrors the direct-path cap
    assert kernels.sepfilter_plan((4, 128, 256), 4, 2, w=11) is None
    assert kernels.sepfilter_plan((4, 128, 256), 4, 2, w=9) is not None


def test_lane_band_paths():
    # the banded-matmul lane filter (round 4): pallas and XLA-conv
    # forms vs the shifted-slice oracle, exact to machine precision
    from bolt_tpu.ops import kernels
    from bolt_tpu.ops.overlap import _filter1d
    rs = np.random.RandomState(63)
    for shape, w in [((4, 6, 256), 17), ((3, 128), 11), ((2, 256), 255)]:
        x = rs.randn(*shape)
        taps = tuple((rs.rand(w) / w).tolist())
        want = _filter1d(x, len(shape) - 1, taps, "constant", np)
        for fn in (lambda a: kernels.lane_band_pallas(a, taps,
                                                      interpret=True),
                   lambda a: kernels.lane_band_conv(a, taps)):
            got = fn(jnp.asarray(x))
            assert got is not None, (shape, w)
            assert np.allclose(np.asarray(got), want, rtol=1e-12,
                               atol=1e-12), (shape, w)
    # refusals: unaligned lane dim, radius past one tile, int dtype
    assert kernels.lane_band_pallas(jnp.ones((4, 100)), (0.5,) * 17,
                                    interpret=True) is None
    assert kernels.lane_band_conv(jnp.ones((4, 256)), (0.1,) * 259) is None
    assert kernels.lane_band_conv(jnp.ones((4, 256), jnp.int32),
                                  (1.0,) * 11) is None
    # capability gate includes the band path — and is mode-aware, so it
    # cannot disagree with what sepfilter1d actually accepts
    assert kernels.sepfilter_capable((4, 100, 256), 4, 2, 17)
    assert not kernels.sepfilter_capable((4, 100, 250), 4, 2, 17)
    assert not kernels.sepfilter_capable((4, 100, 256), 4, 2, 17,
                                         mode="reflect")
    assert kernels.sepfilter_capable((4, 128, 256), 4, 2, 17,
                                     mode="reflect")   # detour serves it


def test_whole_array_sepfilter_failure_memo(mesh, monkeypatch):
    # a compile failure degrades ONCE to the chunked path — never crash,
    # never re-pay the failed compile per call
    import bolt_tpu as bolt
    import bolt_tpu.ops.overlap as ov
    from bolt_tpu.ops import smooth
    x = np.random.RandomState(62).randn(8, 16, 256).astype(np.float32)
    b = bolt.array(x, mesh)
    calls = []
    import bolt_tpu.tpu.array as arr
    real = arr._cached_jit

    def exploding_cached_jit(key, build):
        if key[0] == "sepfilter":
            calls.append(key)
            raise RuntimeError("simulated Mosaic compile crash")
        return real(key, build)

    monkeypatch.setattr(ov, "_SEPFILTER_FAILED", set())
    monkeypatch.setattr(arr, "_cached_jit", exploding_cached_jit)
    out = smooth(b, 3, axis=(0,))
    expect = smooth(bolt.array(x), 3, axis=(0,))
    assert np.allclose(out.toarray(), expect.toarray(),
                       rtol=1e-5, atol=1e-6)
    n_first = len(calls)
    assert n_first >= 1
    smooth(b, 3, axis=(0,))                 # second call: memoised
    assert len(calls) == n_first
