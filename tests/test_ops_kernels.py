"""Pallas kernel tests (interpret mode on the CPU mesh).

The kernels mirror XLA's fused reductions (measured at parity on TPU for
the 10 GB north-star shape); these tests pin their correctness and the
fallback behavior."""

import numpy as np
import pytest

import jax.numpy as jnp

from bolt_tpu.ops import fused_map_reduce, fused_stats
from bolt_tpu.ops.kernels import _block_plan


def test_block_plan_alignment():
    # unaligned minor dim: no plan (would force a padded relayout copy)
    assert _block_plan((64, 64), 4) is None
    assert _block_plan((3200, 200, 64, 64), 4) is None
    # aligned: tiles the leading axis
    grid, block = _block_plan((256, 384), 4)
    assert grid[0] * block[0] == 256
    assert block[1] == 384
    # huge trailing block: falls to 2-d grid
    plan = _block_plan((4, 512, 64, 128), 4)
    assert plan is not None
    grid, block = plan
    assert len(grid) in (1, 2)


def test_fused_map_reduce():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(32, 256).astype(np.float32))
    got = float(fused_map_reduce(x, lambda v: v * 2 + 1, interpret=True))
    expected = float(jnp.sum(x * 2 + 1))
    assert abs(got - expected) < 1e-2
    # identity fn
    got = float(fused_map_reduce(x, interpret=True))
    assert abs(got - float(x.sum())) < 1e-2


def test_fused_map_reduce_fallback():
    x = jnp.asarray(np.ones((5, 7), np.float32))  # unaligned: jnp fallback
    assert float(fused_map_reduce(x, lambda v: v + 1, interpret=True)) == 70.0


def test_integer_inputs_fall_back():
    # same-dtype accumulation would overflow small ints; ints take the
    # jnp path regardless of tiling
    x = jnp.full((8, 128), 100, dtype=jnp.int16)
    assert int(fused_map_reduce(x, interpret=True)) == 102400
    xi = jnp.arange(16 * 128, dtype=jnp.int32).reshape(16, 128)
    s, sq, mn, mx = fused_stats(xi, interpret=True)
    assert int(mn) == 0 and int(mx) == 16 * 128 - 1


def test_fused_stats():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(16, 128).astype(np.float32))
    s, sq, mn, mx = fused_stats(x, interpret=True)
    assert np.allclose(float(s), float(x.sum()), rtol=1e-4)
    assert np.allclose(float(sq), float((x * x).sum()), rtol=1e-4)
    assert float(mn) == float(x.min())
    assert float(mx) == float(x.max())


def test_fused_stats_2d_grid():
    rs = np.random.RandomState(2)
    # trailing block too big for one VMEM tile: forces the 2-d grid path
    x = jnp.asarray(rs.randn(3, 1024, 16, 128).astype(np.float32))
    grid, block = _block_plan(x.shape, 4)
    assert len(grid) == 2
    s, sq, mn, mx = fused_stats(x, interpret=True)
    assert np.allclose(float(s), float(x.sum()), rtol=1e-3)
    assert float(mx) == float(x.max())
    got = float(fused_map_reduce(x, lambda v: v + 1, interpret=True))
    assert np.allclose(got, float((x + 1).sum()), rtol=1e-3)
