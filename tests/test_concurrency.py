"""Concurrency analysis layer (ISSUE 17).

Three coordinated pieces, each tested here:

* the STATIC pass — ``analysis/concurrency.py`` rules BLT111–BLT114
  (inventory-routed lock creation, rank-ordered nesting, no indefinite
  blocking under a lock, order-locked enqueues); zero findings on
  ``bolt_tpu/`` is a tier-1 invariant and every rule has a seeded
  violation below;
* the RUNTIME witness — ``bolt_tpu/_lockdep``: rank inversions,
  self-deadlocks and dispatch-under-lock recorded (or raised) only
  while armed, with edges/cycles/stats inspection;
* the HYGIENE gates that ride along — the diagnostics-table drift gate
  (code tables vs ``docs/API.md`` vs ``lint_bolt.py --codes``), the
  stale-pragma audit, the ``DeviceArbiter.resize`` race hammer under
  the armed witness, and the ``obs.thread_census()`` leak check.

The cross-process schedule-digest exchange is exercised on a real
2-process cluster in ``tests/test_multihost.py`` (``sched_verify``
payload); here only the single-process surface is covered.
"""

import os
import re
import threading

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu import _lockdep, engine, obs, serve, utils
from bolt_tpu.analysis import astlint, diagnostics
from bolt_tpu.analysis import concurrency as conc
from bolt_tpu.parallel import multihost

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PRELUDE = "from bolt_tpu import _lockdep\n"


def _codes(src, path="bolt_tpu/somewhere.py"):
    return [f.code for f in conc.lint_source(src, path)]


# ---------------------------------------------------------------------
# static pass: the tier-1 invariant
# ---------------------------------------------------------------------

@pytest.mark.lint
def test_concurrency_lint_package_zero_findings():
    found = conc.lint_package()
    assert found == [], "\n".join(f.render() for f in found)


# ---------------------------------------------------------------------
# static pass: seeded violations, one (or more) per rule
# ---------------------------------------------------------------------

@pytest.mark.lint
def test_blt111_raw_lock_construction():
    src = "import threading\nL = threading.Lock()\n"
    assert _codes(src) == ["BLT111"]
    # every primitive, any import spelling
    assert _codes("from threading import Condition\nC = Condition()\n") \
        == ["BLT111"]
    assert _codes("import threading as t\nR = t.RLock()\n") == ["BLT111"]
    # the witness itself, tests and scripts build raw primitives freely
    assert conc.lint_source(src, "bolt_tpu/_lockdep.py") == []
    assert conc.lint_source(src, "tests/test_foo.py") == []
    assert conc.lint_source(src, "scripts/bench_all.py") == []
    # the pragma escape hatch documents a deliberate exception
    ok = ("import threading\n"
          "L = threading.Lock()  # lint: allow(BLT111 scratch harness)\n")
    assert _codes(ok) == []


@pytest.mark.lint
def test_blt111_factory_names_must_be_declared_literals():
    # unknown inventory name: static table and runtime witness agree
    assert _codes(_PRELUDE + "L = _lockdep.lock('no.such.lock')\n") \
        == ["BLT111"]
    # non-literal name: the static pass cannot rank it
    assert _codes(_PRELUDE + "L = _lockdep.lock(name)\n") == ["BLT111"]
    # a declared literal is the blessed form
    assert _codes(_PRELUDE + "L = _lockdep.rlock('engine.cache')\n") == []


@pytest.mark.lint
def test_blt112_static_rank_inversion():
    decl = (_PRELUDE
            + "OUTER = _lockdep.lock('serve.scheduler')\n"   # rank 34
            + "LEAF = _lockdep.lock('engine.cache')\n")      # rank 54
    bad = decl + ("def f():\n"
                  "    with LEAF:\n"
                  "        with OUTER:\n"
                  "            pass\n")
    found = conc.lint_source(bad, "bolt_tpu/x.py")
    assert [f.code for f in found] == ["BLT112"]
    assert "inverts the declared order" in found[0].message
    # the declared order is clean
    good = decl + ("def f():\n"
                   "    with OUTER:\n"
                   "        with LEAF:\n"
                   "            pass\n")
    assert conc.lint_source(good, "bolt_tpu/x.py") == []
    # a nested def's body runs LATER, not under the lock
    closure = decl + ("def f():\n"
                      "    with LEAF:\n"
                      "        def cb():\n"
                      "            with OUTER:\n"
                      "                pass\n"
                      "        return cb\n")
    assert conc.lint_source(closure, "bolt_tpu/x.py") == []
    # instance-attribute bindings resolve too
    attr = (_PRELUDE
            + "class C:\n"
            + "    def __init__(self):\n"
            + "        self.lk = _lockdep.lock('engine.cache')\n"
            + "        self.outer = _lockdep.lock('serve.scheduler')\n"
            + "    def f(self):\n"
            + "        with self.lk:\n"
            + "            with self.outer:\n"
            + "                pass\n")
    assert _codes(attr) == ["BLT112"]


@pytest.mark.lint
def test_blt113_blocking_call_under_ranked_lock():
    decl = _PRELUDE + "L = _lockdep.lock('engine.cache')\n"
    # bare waits with no timeout block indefinitely
    assert _codes(decl + "def f(fut):\n"
                         "    with L:\n"
                         "        fut.result()\n") == ["BLT113"]
    # a bounded wait is fine
    assert _codes(decl + "def f(fut):\n"
                         "    with L:\n"
                         "        fut.result(5)\n") == []
    # a collective under a lock is the classic cross-process deadlock
    found = conc.lint_source(
        decl + "from bolt_tpu.parallel import multihost as mh\n"
               "def f():\n"
               "    with L:\n"
               "        mh.barrier('x')\n", "bolt_tpu/x.py")
    assert [f.code for f in found] == ["BLT113"]
    assert "collective" in found[0].message
    # parking the thread under a lock stalls every contender
    assert _codes(decl + "import time\n"
                         "def f():\n"
                         "    with L:\n"
                         "        time.sleep(1)\n") == ["BLT113"]
    # the same calls OUTSIDE any lock are untouched
    assert _codes(decl + "import time\n"
                         "def f(fut):\n"
                         "    fut.result()\n"
                         "    time.sleep(1)\n") == []


@pytest.mark.lint
def test_blt114_enqueue_outside_order_lock():
    # direct .jitted(...) call
    bad = ("class D:\n"
           "    def run(self, args):\n"
           "        return self.jitted(*args)\n")
    assert _codes(bad) == ["BLT114"]
    # .lower() on the jitted object is NOT a dispatch
    assert _codes("class D:\n"
                  "    def low(self, args):\n"
                  "        return self.jitted.lower(*args)\n") == []
    # under the order lock: the blessed form
    ok = ("from bolt_tpu.engine import order_lock\n"
          "class D:\n"
          "    def run(self, args):\n"
          "        with order_lock():\n"
          "            return self.jitted(*args)\n")
    assert _codes(ok) == []
    # names bound from .compile() / .compiled.get(...) are enqueues too
    bound = ("def run(lowered, args):\n"
             "    fn = lowered.compile()\n"
             "    return fn(*args)\n")
    assert _codes(bound) == ["BLT114"]
    cached = ("from bolt_tpu.engine import order_lock\n"
              "class D:\n"
              "    def run(self, sig, args):\n"
              "        fn = self.compiled.get(sig)\n"
              "        with order_lock():\n"
              "            return fn(*args)\n")
    assert _codes(cached) == []


# ---------------------------------------------------------------------
# satellite: diagnostics-table drift gate
# ---------------------------------------------------------------------

@pytest.mark.lint
def test_all_diagnostic_codes_documented_in_api_md():
    """docs/API.md, the checker table, the (merged) lint registry and
    the CLI must agree on ONE set of BLT codes — a rule added in code
    but not documented (or vice versa) fails here."""
    with open(os.path.join(REPO, "docs", "API.md"),
              encoding="utf-8") as fh:
        api = fh.read()
    # the concurrency rules are merged into the astlint registry: one
    # BLT1xx namespace, one Finding.title resolution, one --codes table
    assert set(conc.RULES) <= set(astlint.RULES)
    known = set(diagnostics.CODES) | set(astlint.RULES)
    documented = set(re.findall(r"BLT\d{3}", api))
    missing = sorted(known - documented)
    assert not missing, "codes missing from docs/API.md: %s" % missing
    phantom = sorted(documented - known)
    assert not phantom, \
        "docs/API.md documents unknown codes: %s" % phantom


@pytest.mark.lint
def test_lint_bolt_codes_table_matches_registry(capsys):
    lint = utils.load_script("lint_bolt")
    assert lint.main(["--codes"]) == 0
    out = capsys.readouterr().out
    listed = set(re.findall(r"^(BLT\d{3})\b", out, re.M))
    assert listed == set(astlint.RULES)
    for code in ("BLT111", "BLT112", "BLT113", "BLT114"):
        assert code in listed


# ---------------------------------------------------------------------
# satellite: stale-pragma audit (lint_bolt.py --check)
# ---------------------------------------------------------------------

@pytest.mark.lint
def test_stale_pragma_audit_fails_the_check_gate(tmp_path, capsys):
    lint = utils.load_script("lint_bolt")
    # a pragma naming an unknown code
    unknown = tmp_path / "unknown.py"
    unknown.write_text("x = 1  # lint: allow(BLT999 never existed)\n")
    assert lint.main(["--check", str(unknown)]) == 1
    assert "unknown code 'BLT999'" in capsys.readouterr().out
    # a pragma that no longer suppresses anything
    stale = tmp_path / "stale.py"
    stale.write_text("x = 1  # lint: allow(BLT104 fixed long ago)\n")
    assert lint.main(["--check", str(stale)]) == 1
    assert "no longer suppresses" in capsys.readouterr().out
    # a live pragma passes: it suppresses a real finding on its line
    live = tmp_path / "live.py"
    live.write_text("import threading\n"
                    "L = threading.Lock()"
                    "  # lint: allow(BLT111 scratch)\n")
    assert lint.main(["--check", str(live)]) == 0


# ---------------------------------------------------------------------
# runtime witness: unit surface
# ---------------------------------------------------------------------
#
# These tests RECORD violations on purpose, so they must not run under
# the suite-wide autouse witness assertion (this module is not in
# conftest._LOCKDEP_SUITES); the local fixture arms, and resets the
# global record on the way out so later tests see a clean slate.

@pytest.fixture
def witness():
    was = _lockdep.enabled()
    _lockdep.reset()
    _lockdep.enable()
    yield _lockdep
    _lockdep.disable()
    _lockdep.reset()
    if was:
        _lockdep.enable()


def test_factory_rejects_undeclared_names():
    with pytest.raises(ValueError, match="not in the declared"):
        _lockdep.lock("no.such.lock")
    with pytest.raises(ValueError, match="BLT111"):
        _lockdep.condition("also.not.a.lock")


def test_witness_records_rank_inversion(witness):
    outer = witness.lock("engine.cache")       # rank 54
    inner = witness.lock("serve.scheduler")    # rank 34
    with outer:
        with inner:
            pass
    v = witness.violations()
    assert len(v) == 1 and "inversion" in v[0]
    assert "'serve.scheduler' (rank 34)" in v[0]
    assert "'engine.cache' (rank 54)" in v[0]
    # the correct order records an EDGE, not a violation
    witness.reset()
    with inner:
        with outer:
            pass
    assert witness.violations() == []
    assert ("serve.scheduler", "engine.cache") in witness.edges()
    assert witness.check() == []               # and no cycle


def test_witness_raise_mode_throws_at_the_acquisition(witness):
    witness.enable(raise_on_violation=True)
    outer = witness.lock("engine.cache")
    inner = witness.lock("serve.scheduler")
    with outer:
        with pytest.raises(witness.LockOrderError, match="inversion"):
            inner.acquire()
    witness.reset()


def test_witness_rlock_reentry_is_exempt(witness):
    rl = witness.rlock("engine.order")
    with rl:
        with rl:
            assert witness.held_names() == ["engine.order"]
    assert witness.violations() == []
    assert witness.held_names() == []


def test_witness_flags_nonreentrant_self_deadlock(witness):
    lk = witness.lock("tpu.lru")
    lk.acquire()
    try:
        # non-blocking, so the test itself cannot deadlock; the
        # witness notes the hazard before touching the primitive
        assert lk.acquire(blocking=False) is False
    finally:
        lk.release()
    assert any("self-deadlock" in x for x in witness.violations())


def test_witness_off_means_no_tracking(witness):
    witness.disable()
    outer = witness.lock("engine.cache")
    inner = witness.lock("serve.scheduler")
    with outer:
        with inner:                       # inverted — but unobserved
            assert witness.held_names() == []
    assert witness.violations() == []


def test_witness_stats_count_acquires(witness):
    base = witness.stats()["acquires"]
    lk = witness.lock("tpu.lru")
    for _ in range(5):
        with lk:
            pass
    st = witness.stats()
    assert st["acquires"] >= base + 5
    assert st["violations"] == 0
    # the flush lands in the obs registry group (flattened keys)
    snap = obs.registry().snapshot()
    assert snap.get("lockdep.acquires", 0) >= 5


def test_note_dispatch_flags_held_locks_except_dispatch_safe(witness):
    lk = witness.lock("serve.arbiter")
    with lk:
        witness.note_dispatch("test.dispatch")
    v = witness.violations()
    assert len(v) == 1 and "dispatch-under-lock" in v[0]
    assert "'serve.arbiter'" in v[0]
    witness.reset()
    # multistat.group holds its lock across resolve() BY DESIGN
    grp = witness.lock("multistat.group")
    with grp:
        witness.note_dispatch("test.dispatch")
    assert witness.violations() == []
    # and with nothing held there is nothing to flag
    witness.note_dispatch("test.dispatch")
    assert witness.violations() == []


# ---------------------------------------------------------------------
# satellite: DeviceArbiter.resize two-thread race under the witness
# ---------------------------------------------------------------------

@pytest.mark.serve
@pytest.mark.lockdep
def test_arbiter_resize_race_is_clean_under_lockdep():
    """One thread oscillates the budget while workers lease through it;
    the autouse lockdep fixture (this test carries the marker) fails
    the test on any recorded inversion, and the end-state assertions
    catch lost grants/releases."""
    arb = serve.DeviceArbiter(1 << 20)
    stop = threading.Event()
    errors = []

    def resizer():
        budgets = [1 << 18, 1 << 20, 1 << 16, 1 << 21]
        i = 0
        while not stop.is_set():
            arb.resize(budgets[i % len(budgets)])
            i += 1

    def worker(tenant):
        try:
            lease = arb.lease(tenant)
            for k in range(200):
                nbytes = 1 << (10 + k % 8)
                arb.acquire(nbytes, tenant=tenant)
                arb.release(nbytes)
                assert lease.acquire(nbytes)
                lease.release(nbytes)
            assert lease.outstanding() == 0
            lease.close()
        except Exception as exc:                # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=("t%d" % i,))
               for i in range(4)]
    rs = threading.Thread(target=resizer)
    rs.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    stop.set()
    rs.join(timeout=10)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)
    assert arb.in_use() == 0                   # conservation: all paid back
    assert arb.waiting() == 0


# ---------------------------------------------------------------------
# satellite: thread-census hygiene
# ---------------------------------------------------------------------

@pytest.mark.obs
def test_thread_census_empty_after_server_teardown(mesh):
    assert obs.thread_census() == {}, \
        "another test leaked worker threads"
    x = np.arange(64, dtype=np.float64).reshape(8, 8)
    with serve.serving(workers=2) as sv:
        census = obs.thread_census()
        assert census.get("bolt-serve-worker") == 2
        src = bolt.fromcallback(lambda idx: x[idx], x.shape, mesh,
                                dtype=np.float64, chunks=4)
        f = sv.submit(src.map(lambda v: v + 1).sum())
        assert np.allclose(np.asarray(f.result(timeout=60).toarray()),
                           (x + 1).sum(axis=0))
    assert obs.thread_census() == {}, "server teardown leaked threads"


# ---------------------------------------------------------------------
# schedule digest: single-process surface (the 2-process exchange and
# the chaos-skew divergence run in tests/test_multihost.py)
# ---------------------------------------------------------------------

def test_schedule_digest_advances_per_enqueue(mesh):
    x = np.arange(48, dtype=np.float64).reshape(8, 6)
    c0, d0 = engine.schedule_digest()
    np.asarray(bolt.array(x, mesh).map(lambda v: v * 2).sum().toarray())
    c1, d1 = engine.schedule_digest()
    assert c1 > c0 and d1 != d0
    assert engine.schedule_recent()            # always-on tail context


def test_stable_key_strips_object_addresses():
    def f():
        pass
    a = engine._stable_key(("sig", f, (8, 6)))
    assert "0x" not in a
    assert "at 0x%x" % id(f) not in a
    assert f.__name__ in a


def test_schedule_log_arm_and_reset(mesh):
    assert engine.schedule_log() is None       # off by default
    engine.schedule_log_arm(True)
    try:
        x = np.arange(16, dtype=np.float64).reshape(8, 2)
        np.asarray(bolt.array(x, mesh).map(lambda v: v + 3).toarray())
        log = engine.schedule_log()
        assert log and all("0x" not in k for k in log)
        count, _ = engine.schedule_digest()
        assert len(log) <= count               # armed after start
    finally:
        engine.schedule_log_arm(False)
    assert engine.schedule_log() is None


def test_verify_schedule_single_process_returns_digest():
    got = multihost.verify_schedule("t17")
    assert got == engine.schedule_digest()[1]
