"""Checkpoint/restore tests (SURVEY §5 checkpoint row — a capability the
reference lacks entirely)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu import checkpoint
from bolt_tpu.utils import allclose


def _x():
    rs = np.random.RandomState(30)
    return rs.randn(8, 4, 6)


def test_save_load_roundtrip(tmp_path, mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, b)
    b2 = checkpoint.load(path, context=mesh)
    assert b2.mode == "tpu"
    assert b2.split == 2
    assert b2.shape == b.shape
    assert b2.dtype == b.dtype
    assert allclose(b2.toarray(), x)
    # restored array is live: ops work
    assert allclose(b2.map(lambda v: v + 1).toarray(), x + 1)


def test_load_onto_different_mesh(tmp_path, mesh, mesh2d):
    x = _x()
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, bolt.array(x, mesh))
    b2 = checkpoint.load(path, context=mesh2d)
    assert allclose(b2.toarray(), x)
    assert len(b2._data.sharding.device_set) >= 1


def test_save_deferred_materialises(tmp_path, mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v * 2)
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, m)
    assert allclose(checkpoint.load(path, mesh).toarray(), x * 2)


def test_save_rejects_local(tmp_path):
    with pytest.raises(TypeError):
        checkpoint.save(str(tmp_path / "c"), bolt.array(_x()))


def test_checkpoint_deferred_and_pending_states(mesh, tmp_path):
    # save() must materialise a deferred chain and resolve a pending
    # filter; restore round-trips both
    rs = np.random.RandomState(40)
    x = rs.randn(16, 4)
    b = bolt.array(x, mesh).map(lambda v: v * 2)
    checkpoint.save(str(tmp_path / "a"), b)
    r = checkpoint.load(str(tmp_path / "a"), context=mesh)
    assert np.allclose(r.toarray(), x * 2)

    f = bolt.array(x, mesh).filter(lambda v: v.mean() > 0)
    checkpoint.save(str(tmp_path / "b"), f)
    r2 = checkpoint.load(str(tmp_path / "b"), context=mesh)
    keep = x[x.mean(axis=1) > 0]
    assert r2.shape == keep.shape and np.allclose(r2.toarray(), keep)


def test_checkpoint_tuple_spec_sharding(mesh2d, tmp_path):
    # a lone key axis on a 2-d mesh shards over BOTH axes (tuple spec
    # entry); orbax must round-trip that layout
    x = np.random.RandomState(41).randn(16, 4, 6)
    b = bolt.array(x, mesh2d, axis=(0,))
    assert len(b._data.addressable_shards) == 8
    checkpoint.save(str(tmp_path / "c"), b)
    r = checkpoint.load(str(tmp_path / "c"), context=mesh2d)
    assert r.split == 1 and np.allclose(r.toarray(), x)
