"""Checkpoint/restore tests (SURVEY §5 checkpoint row — a capability the
reference lacks entirely)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu import checkpoint
from bolt_tpu.utils import allclose


def _x():
    rs = np.random.RandomState(30)
    return rs.randn(8, 4, 6)


def test_save_load_roundtrip(tmp_path, mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, b)
    b2 = checkpoint.load(path, context=mesh)
    assert b2.mode == "tpu"
    assert b2.split == 2
    assert b2.shape == b.shape
    assert b2.dtype == b.dtype
    assert allclose(b2.toarray(), x)
    # restored array is live: ops work
    assert allclose(b2.map(lambda v: v + 1).toarray(), x + 1)


def test_load_onto_different_mesh(tmp_path, mesh, mesh2d):
    x = _x()
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, bolt.array(x, mesh))
    b2 = checkpoint.load(path, context=mesh2d)
    assert allclose(b2.toarray(), x)
    assert len(b2._data.sharding.device_set) >= 1


def test_save_deferred_materialises(tmp_path, mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v * 2)
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, m)
    assert allclose(checkpoint.load(path, mesh).toarray(), x * 2)


def test_save_rejects_local(tmp_path):
    with pytest.raises(TypeError):
        checkpoint.save(str(tmp_path / "c"), bolt.array(_x()))
