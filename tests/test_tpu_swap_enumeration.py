"""Exhaustive swap enumeration (the reference's brute-force shaping-test
style, SURVEY §4) plus donate semantics, Ellipsis indexing, len/iter."""

from itertools import combinations

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.utils import allclose


def _x(shape=(4, 2, 3, 2)):
    rs = np.random.RandomState(40)
    return rs.randn(*shape)


def _expected_perm(split, ndim, kaxes, vaxes):
    keys_rest = [k for k in range(split) if k not in kaxes]
    values_rest = [v for v in range(ndim - split) if v not in vaxes]
    return (keys_rest + [split + v for v in vaxes]
            + list(kaxes) + [split + v for v in values_rest])


@pytest.mark.parametrize("split", [1, 2, 3])
def test_swap_exhaustive(mesh, split):
    x = _x()
    b = bolt.array(x, mesh, axis=tuple(range(split)))
    nv = x.ndim - split
    for nk in range(split + 1):
        for kaxes in combinations(range(split), nk):
            for nvx in range(nv + 1):
                for vaxes in combinations(range(nv), nvx):
                    if len(kaxes) == split and len(vaxes) == 0:
                        continue  # guarded
                    s = b.swap(kaxes, vaxes)
                    perm = _expected_perm(split, x.ndim, list(kaxes), list(vaxes))
                    assert s.split == split - len(kaxes) + len(vaxes)
                    assert allclose(s.toarray(), np.transpose(x, perm)), \
                        (split, kaxes, vaxes)


def test_swap_roundtrip_property(mesh):
    # swapping out then back restores the original layout
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    s = b.swap((1,), (0,))     # keys (4, 3), values (2, 2)
    back = s.swap((1,), (0,))  # keys (4, 2), values (3, 2)
    assert back.shape == b.shape
    assert allclose(back.toarray(), x)


def test_swap_donate(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    s = b.swap((0,), (0,), donate=True)
    assert allclose(s.toarray(), np.transpose(x, (1, 2, 0, 3)))
    with pytest.raises(RuntimeError):
        b.toarray()  # the donated source is no longer readable
    with pytest.raises(RuntimeError):
        b.map(lambda v: v)


def test_swap_donate_repr_and_children(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    child = b.map(lambda v: v + 1)     # deferred child aliases b's buffer
    b.swap((0,), (0,), donate=True)
    r = repr(b)
    assert "donated" in r              # repr must not crash post-donation
    # CPU ignores donation (buffer intact → child still computes); on TPU
    # the consumed buffer must surface as OUR clear error, not a raw
    # "Array has been deleted"
    try:
        assert allclose(child.toarray(), x + 1)
    except RuntimeError as e:
        assert "donated" in str(e)
    # an unrelated array is unaffected
    assert allclose(bolt.array(x, mesh).map(lambda v: v + 1).sum().toarray(),
                    (x + 1).sum(axis=0))


def test_iter_single_compile(mesh):
    from bolt_tpu.tpu.array import _JIT_CACHE
    b = bolt.array(_x(), mesh)
    items = list(b)
    before = len(_JIT_CACHE)
    items2 = list(b)                   # same program re-used for every index
    assert len(_JIT_CACHE) == before
    assert allclose(items2[1].toarray(), _x()[1])


def test_ellipsis_indexing(mesh):
    x = _x((4, 2, 3, 5))
    b = bolt.array(x, mesh)
    assert allclose(b[..., 1].toarray(), x[..., 1])
    assert allclose(b[1, ...].toarray(), x[1, ...])
    assert allclose(b[1, ..., 2].toarray(), x[1, ..., 2])
    assert allclose(b[...].toarray(), x)
    with pytest.raises(IndexError):
        b[..., 1, ...]


def test_len_iter(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    assert len(b) == 4
    items = list(b)
    assert len(items) == 4
    assert items[0].split == 0
    assert allclose(items[2].toarray(), x[2])
    with pytest.raises(TypeError):
        len(b.sum(axis=(0, 1, 2, 3)))
