"""Codec-encoded streaming ingest (ISSUE 14, bolt_tpu/tpu/codec.py).

The parity contract under test: the LOSSLESS ``delta-f32`` codec is
BIT-IDENTICAL to uncompressed streaming; lossy codecs (``bf16``/
``f16``/``int8``) hold their documented envelopes
(``_precision.codec_bound``); order statistics and integer pipelines
REFUSE lossy codecs pointedly; wire bytes shrink by the codec ratio in
the transfer counters and the arbiter/admission floors; checkpoints
fingerprint the codec id (a codec change restarts, never resumes
wrong); the ``stream.encode`` chaos seam rides the existing retry
fence; and the opt-in Pallas decode-and-reduce kernel parity-locks
against the XLA decode path.
"""

import json
import os
import tempfile
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bolt_tpu as bolt
from bolt_tpu import _chaos, _precision, analysis, engine, obs, stream
from bolt_tpu import checkpoint as ckptlib
from bolt_tpu.tpu import codec as codeclib

pytestmark = pytest.mark.codec

SHAPE = (64, 16, 8)


def _intdata(shape=SHAPE, lo=-6, hi=7):
    n = int(np.prod(shape))
    return ((np.arange(n) % (hi - lo)) + lo).astype(np.float32).reshape(
        shape)


def _posdata(shape=SHAPE):
    rs = np.random.RandomState(7)
    return (np.abs(rs.randn(*shape)) + 0.5).astype(np.float32)


def _src(x, mesh, chunks=8, codec=None, ck=None, dtype=None):
    return bolt.fromcallback(lambda idx: x[idx], x.shape, mesh,
                             dtype=dtype or x.dtype, chunks=chunks,
                             codec=codec, checkpoint=ck)


# ---------------------------------------------------------------------
# registry + contract units
# ---------------------------------------------------------------------

def test_registry_names_and_pointed_unknown():
    assert set(codeclib.names()) >= {"bf16", "f16", "int8", "delta-f32"}
    with pytest.raises(ValueError) as ei:
        codeclib.get("zstd")
    assert "unknown codec" in str(ei.value) and "bf16" in str(ei.value)
    # a Codec instance passes through get() (custom-codec door)
    c = codeclib.get("bf16")
    assert codeclib.get(c) is c


def test_wire_dtype_ratios():
    assert codeclib.get("bf16").ratio(np.float32) == 0.5
    assert codeclib.get("f16").ratio(np.float32) == 0.5
    assert codeclib.get("int8").ratio(np.float32) == 0.25
    assert codeclib.get("delta-f32").ratio(np.float32) == 1.0


def test_lossy_refuses_integer_pipeline_pointedly():
    for name in ("bf16", "f16", "int8"):
        with pytest.raises(ValueError) as ei:
            codeclib.get(name).wire_dtype(np.int32)
        assert name in str(ei.value) and "int32" in str(ei.value)
    with pytest.raises(ValueError):
        codeclib.get("delta-f32").wire_dtype(np.float64)


def test_precision_codec_bounds_table():
    assert _precision.codec_bound("delta-f32") == (True, None)
    lossless, env = _precision.codec_bound("bf16")
    assert not lossless and env == 1e-2
    assert _precision.codec_bound("no-such") == (False, None)


def test_delta_roundtrip_bit_exact_incl_nan():
    c = codeclib.get("delta-f32")
    x = np.random.RandomState(0).randn(6, 16).astype(np.float32)
    x[2, 3] = np.nan
    x[4, 0] = np.inf
    wire, side = c.encode(x)
    assert wire.dtype == np.uint32 and side == ()
    back = np.asarray(c.decode(jnp.asarray(wire), (), np.float32))
    assert np.array_equal(back.view(np.uint32), x.view(np.uint32))


def test_delta_all_key_axes_source_skips_the_delta():
    c = codeclib.get("delta-f32")
    x = np.random.RandomState(1).randn(16).astype(np.float32)
    wire, _ = c.encode(x, delta_ok=False)
    assert np.array_equal(wire, x.view(np.uint32))
    back = np.asarray(c.decode(jnp.asarray(wire), (), np.float32,
                               delta_ok=False))
    assert np.array_equal(back, x)


def test_int8_roundtrip_within_half_scale():
    c = codeclib.get("int8")
    x = np.random.RandomState(2).randn(8, 32).astype(np.float32) * 5
    wire, (scale, zp) = c.encode(x)
    assert wire.dtype == np.uint8
    back = np.asarray(c.decode(jnp.asarray(wire),
                               (jnp.float32(scale), jnp.float32(zp)),
                               np.float32))
    assert np.max(np.abs(back - x)) <= float(scale) / 2 + 1e-6


def test_int8_constant_slab_is_exact():
    c = codeclib.get("int8")
    x = np.full((4, 8), 3.25, np.float32)
    wire, (scale, zp) = c.encode(x)
    back = np.asarray(c.decode(jnp.asarray(wire),
                               (jnp.float32(scale), jnp.float32(zp)),
                               np.float32))
    assert np.array_equal(back, x)


# ---------------------------------------------------------------------
# streamed parity
# ---------------------------------------------------------------------

def test_streamed_delta_bit_identical(mesh):
    x = np.random.RandomState(3).randn(*SHAPE).astype(np.float32)
    raw = np.asarray(_src(x, mesh).sum().toarray())
    enc = np.asarray(_src(x, mesh, codec="delta-f32").sum().toarray())
    assert np.array_equal(raw, enc)


def test_streamed_delta_uneven_tail_and_tiny_slabs(mesh):
    x = np.random.RandomState(4).randn(19, 8, 8).astype(np.float32)
    raw = np.asarray(_src(x, mesh, chunks=4).mean().toarray())
    enc = np.asarray(_src(x, mesh, chunks=4,
                          codec="delta-f32").mean().toarray())
    assert np.array_equal(raw, enc)
    raw1 = np.asarray(_src(x, mesh, chunks=1).sum().toarray())
    enc1 = np.asarray(_src(x, mesh, chunks=1,
                           codec="delta-f32").sum().toarray())
    assert np.array_equal(raw1, enc1)


def test_streamed_fromiter_delta_bit_identical(mesh):
    x = _intdata()
    blocks = [x[i:i + 16] for i in range(0, SHAPE[0], 16)]
    raw = np.asarray(bolt.fromiter(
        [b for b in blocks], x.shape, mesh,
        dtype=np.float32).sum().toarray())
    enc = np.asarray(bolt.fromiter(
        [b for b in blocks], x.shape, mesh, dtype=np.float32,
        codec="delta-f32").sum().toarray())
    assert np.array_equal(raw, enc)


def test_streamed_bf16_within_documented_envelope(mesh):
    x = _posdata()
    raw = np.asarray(_src(x, mesh).sum().toarray())
    enc = np.asarray(_src(x, mesh, codec="bf16").sum().toarray())
    _, bound = _precision.codec_bound("bf16")
    assert np.allclose(enc, raw, rtol=bound)
    assert not np.array_equal(enc, raw)     # genuinely lossy opt-in


def test_streamed_f16_within_documented_envelope(mesh):
    x = _posdata()
    raw = np.asarray(_src(x, mesh).mean().toarray())
    enc = np.asarray(_src(x, mesh, codec="f16").mean().toarray())
    _, bound = _precision.codec_bound("f16")
    assert np.allclose(enc, raw, rtol=bound)


def test_streamed_int8_within_slab_scale_bound(mesh):
    x = _posdata()
    raw = np.asarray(_src(x, mesh).sum().toarray())
    enc = np.asarray(_src(x, mesh, codec="int8").sum().toarray())
    # worst case: half a quantisation step per record, summed — derive
    # the concrete bound from the data's range like the docstring says
    step = (x.max() - x.min()) / 255.0
    assert np.max(np.abs(enc - raw)) <= step / 2 * SHAPE[0] + 1e-4


def test_streamed_multi_stat_delta_bit_identical(mesh):
    x = np.random.RandomState(5).randn(*SHAPE).astype(np.float32)
    raw = _src(x, mesh).stats("sum", "var", "min")
    enc = _src(x, mesh, codec="delta-f32").stats("sum", "var", "min")
    for k in raw:
        assert np.array_equal(np.asarray(raw[k].toarray()),
                              np.asarray(enc[k].toarray())), k


def test_streamed_stages_and_filter_ride_the_codec(mesh):
    x = _intdata()
    raw = np.asarray(_src(x, mesh).map(lambda v: v * 2).filter(
        lambda v: v.sum() > 0).sum().toarray())
    enc = np.asarray(_src(x, mesh, codec="delta-f32").map(
        lambda v: v * 2).filter(lambda v: v.sum() > 0).sum().toarray())
    assert np.array_equal(raw, enc)


# ---------------------------------------------------------------------
# refusals
# ---------------------------------------------------------------------

def test_lossy_codec_refuses_order_stats_pointedly(mesh):
    x = _posdata()
    with pytest.raises(ValueError) as ei:
        _src(x, mesh, codec="bf16").stats("min")
    msg = str(ei.value)
    assert "order-statistic" in msg and "delta-f32" in msg
    with pytest.raises(ValueError):
        _src(x, mesh, codec="int8").stats("sum", "max")


def test_lossless_codec_allows_order_stats(mesh):
    x = np.random.RandomState(6).randn(*SHAPE).astype(np.float32)
    raw = _src(x, mesh).stats("min", "max")
    enc = _src(x, mesh, codec="delta-f32").stats("min", "max")
    for k in raw:
        assert np.array_equal(np.asarray(raw[k].toarray()),
                              np.asarray(enc[k].toarray()))


def test_lossy_codec_refuses_integer_stream_pointedly(mesh):
    x = (np.arange(np.prod(SHAPE)) % 7).astype(np.int32).reshape(SHAPE)
    with pytest.raises(ValueError) as ei:
        _src(x, mesh, codec="bf16").sum().toarray()
    assert "int32" in str(ei.value)


def test_sidecar_codec_error_names_the_pod_rule(monkeypatch):
    from bolt_tpu.parallel import multihost
    monkeypatch.setattr(multihost, "mesh_process_count", lambda m: 3)
    msg = multihost.sidecar_codec_error(codeclib.get("int8"), None)
    assert "int8" in msg and "sidecar" in msg and "bf16" in msg
    assert multihost.sidecar_codec_error(codeclib.get("bf16"),
                                         None) is None
    assert multihost.sidecar_codec_error(None, None) is None


def test_unknown_codec_refused_at_scope_and_source(mesh):
    with pytest.raises(ValueError):
        with stream.codec("lz4"):
            pass
    # pointed at the CONSTRUCTION boundary (a typo must not surface as
    # a checker crash or a first-terminal surprise — review finding)
    x = _posdata()
    with pytest.raises(ValueError) as ei:
        _src(x, mesh, codec="lz4")
    assert "unknown codec" in str(ei.value)
    with pytest.raises(ValueError):
        bolt.fromiter([x], x.shape, mesh, dtype=np.float32, codec="lz4")


def test_checker_never_crashes_on_a_hand_built_bad_codec(mesh):
    # the public doors all validate; a hand-built source with a bogus
    # name must degrade to "no forecast", never crash check() — the
    # run itself still refuses at resolve_codec
    src = _src(_posdata(), mesh)
    src._stream.codec = "bogus"
    rep = analysis.check(src)
    assert not rep.has("BLT016")
    assert analysis.admission_floor_bytes(src) is not None
    with pytest.raises(ValueError):
        src.sum().toarray()


def test_serve_propagates_the_submitters_codec_scope(mesh):
    """`with stream.codec(...)` around serve.submit: the scope is
    thread-local, so the server re-enters the SUBMITTER's effective
    codec on the worker thread — the tenant's opt-in is honoured and
    the admission floor (priced on the submit thread) matches what the
    run actually leases (review finding)."""
    from bolt_tpu import serve
    x = _posdata()
    with serve.serving(workers=1, budget_bytes=64 << 20) as sv:
        c0 = engine.counters()
        with stream.codec("bf16"):
            fut = sv.submit(_src(x, mesh).sum(), tenant="scoped")
            out = np.asarray(fut.result(timeout=120).toarray())
        c1 = engine.counters()
        assert sv.stats()["arbiter"]["in_use_bytes"] == 0
    # the worker streamed ENCODED: wire bytes are half the raw bytes
    assert c1["transfer_bytes"] - c0["transfer_bytes"] == x.nbytes // 2
    assert c1["codec_bytes_wire"] - c0["codec_bytes_wire"] \
        == x.nbytes // 2
    raw = np.asarray(_src(x, mesh).sum().toarray())
    _, bound = _precision.codec_bound("bf16")
    assert np.allclose(out, raw, rtol=bound)


# ---------------------------------------------------------------------
# scopes, counters, arbiter
# ---------------------------------------------------------------------

def test_codec_scope_is_thread_local(mesh):
    seen = {}

    def other():
        seen["other"] = stream.current_codec()

    with stream.codec("bf16"):
        assert stream.current_codec() == "bf16"
        with stream.codec(None):
            assert stream.current_codec() is None
        th = threading.Thread(target=other)
        th.start()
        th.join()
    assert seen["other"] is None
    assert stream.current_codec() is None


def test_set_codec_process_default_scopes_override():
    try:
        stream.set_codec("delta-f32")
        assert stream.current_codec() == "delta-f32"
        with stream.codec(None):
            assert stream.current_codec() is None
    finally:
        stream.set_codec(None)
    with pytest.raises(ValueError):
        stream.set_codec("nope")


def test_source_codec_wins_over_scope(mesh):
    x = _posdata()
    src = _src(x, mesh, codec="bf16")
    with stream.codec("delta-f32"):
        assert stream.resolve_codec(src._stream).name == "bf16"
    assert stream.resolve_codec(_src(x, mesh)._stream) is None
    with stream.codec("delta-f32"):
        assert stream.resolve_codec(
            _src(x, mesh)._stream).name == "delta-f32"


def test_wire_bytes_and_codec_counters(mesh):
    x = _posdata()
    c0 = engine.counters()
    _src(x, mesh, codec="bf16").sum().toarray()
    c1 = engine.counters()
    wire = c1["transfer_bytes"] - c0["transfer_bytes"]
    # the transfer counters tally the WIRE bytes: half the raw f32
    assert wire == x.nbytes // 2
    assert c1["codec_bytes_raw"] - c0["codec_bytes_raw"] == x.nbytes
    assert c1["codec_bytes_wire"] - c0["codec_bytes_wire"] \
        == x.nbytes // 2
    assert c1["codec_encode_seconds"] > c0["codec_encode_seconds"]


def test_admission_floor_recomputes_via_codec_ratio(mesh):
    x = _posdata()
    raw_floor = analysis.admission_floor_bytes(_src(x, mesh))
    bf16_floor = analysis.admission_floor_bytes(
        _src(x, mesh, codec="bf16"))
    i8_floor = analysis.admission_floor_bytes(
        _src(x, mesh, codec="int8"))
    assert bf16_floor == raw_floor // 2
    assert i8_floor == raw_floor // 4
    # the scope form reshapes the floor too (thread-local at check time)
    with stream.codec("bf16"):
        assert analysis.admission_floor_bytes(
            _src(x, mesh)) == raw_floor // 2


def test_arbiter_leases_wire_bytes_and_returns_them(mesh):
    from bolt_tpu import serve
    x = _posdata()
    with serve.serving(workers=1, budget_bytes=32 << 20) as sv:
        fut = sv.submit(_src(x, mesh, codec="bf16").sum(), tenant="c")
        out = np.asarray(fut.result(timeout=120).toarray())
        assert sv.stats()["arbiter"]["in_use_bytes"] == 0
    raw = np.asarray(_src(x, mesh).sum().toarray())
    _, bound = _precision.codec_bound("bf16")
    assert np.allclose(out, raw, rtol=bound)


def test_codec_span_hygiene_and_names(mesh):
    x = _posdata()
    obs.clear()
    obs.enable()
    try:
        _src(x, mesh, codec="int8").sum().toarray()
        assert obs.active_count() == 0
        names = {s.name for s in obs.spans()}
        assert "stream.encode" in names and "stream.decode" in names
        enc = [s for s in obs.spans() if s.name == "stream.encode"]
        assert all(s.attrs.get("codec") == "int8" for s in enc)
        assert all(s.attrs.get("bytes_wire", 0)
                   < s.attrs.get("bytes_raw", 0) for s in enc)
    finally:
        obs.disable()


# ---------------------------------------------------------------------
# fault paths: chaos seam, retry fence, checkpoint consistency
# ---------------------------------------------------------------------

def test_chaos_encode_raise_retries_in_place(mesh):
    x = _intdata()
    clean = np.asarray(_src(x, mesh, codec="int8").sum().toarray())
    _chaos.inject("stream.encode", nth=3)
    c0 = engine.counters()
    try:
        with stream.retries(1):
            got = np.asarray(_src(x, mesh,
                                  codec="int8").sum().toarray())
    finally:
        _chaos.clear()
    c1 = engine.counters()
    assert np.array_equal(got, clean)
    assert c1["stream_retries"] - c0["stream_retries"] == 1


def test_chaos_encode_exhausted_budget_chains_original(mesh):
    x = _intdata()
    _chaos.inject("stream.encode", nth=2, times=None)
    try:
        with stream.retries(1):
            with pytest.raises(RuntimeError) as ei:
                _src(x, mesh, codec="int8").sum().toarray()
    finally:
        _chaos.clear()
    # the exhausted-budget error chains back to the ORIGINAL ChaosError
    exc = ei.value
    seen = []
    while exc is not None:
        seen.append(type(exc).__name__)
        exc = exc.__cause__
    assert "ChaosError" in seen


def test_chaos_encode_failfast_keeps_original_at_budget_zero(mesh):
    x = _intdata()
    _chaos.inject("stream.encode", nth=2)
    try:
        with pytest.raises(_chaos.ChaosError):
            _src(x, mesh, codec="int8").sum().toarray()
    finally:
        _chaos.clear()


def test_int8_resume_sidecar_scales_checkpoint_consistent(mesh):
    """A killed int8-encoded run resumes BIT-IDENTICALLY to the clean
    int8 run: encode is deterministic per block, so the resumed tail's
    sidecar scales equal the ones the clean run derived — the fold
    state and the re-encoded slabs line up exactly."""
    x = _posdata()
    clean = np.asarray(_src(x, mesh, codec="int8").sum().toarray())
    d = tempfile.mkdtemp(prefix="bolt-codec-resume-")
    _chaos.inject("stream.upload", nth=5)
    try:
        with stream.uploaders(1):
            _src(x, mesh, codec="int8", ck=d).sum().cache()
        raise AssertionError("chaos child was supposed to die")
    except _chaos.ChaosError:
        pass
    finally:
        _chaos.clear()
    assert ckptlib.stream_pending(d)
    meta = json.load(open(os.path.join(d, "stream_meta.json")))
    assert meta.get("codec") == "int8"       # the audit-trail row
    c0 = engine.counters()
    resumed = np.asarray(_src(x, mesh, codec="int8",
                              ck=d).sum().toarray())
    c1 = engine.counters()
    assert c1["stream_resumes"] - c0["stream_resumes"] == 1
    assert np.array_equal(resumed, clean)
    assert not ckptlib.stream_pending(d)


def test_int8_kill9_resume_bit_identical_to_clean_encoded():
    """The subprocess preemption proof over an int8-encoded source:
    kill -9 mid-run, restart, resume — bit-identical to the clean
    encoded child (the satellite's sidecar-consistency gate)."""
    from bolt_tpu.utils import load_script
    cr = load_script("chaos_run")
    wd = tempfile.mkdtemp(prefix="bolt-codec-kill-")
    ck = os.path.join(wd, "ck")
    clean_out = os.path.join(wd, "clean.npy")
    res_out = os.path.join(wd, "resumed.npy")
    proc = cr._run_stream_child(ck, clean_out, codec="int8")
    assert proc.returncode == 0, proc.stderr
    proc = cr._run_stream_child(ck, res_out,
                                arm="stream.upload:6:kill",
                                codec="int8")
    assert proc.returncode == -9, (proc.returncode, proc.stderr)
    assert ckptlib.stream_pending(ck)
    proc = cr._run_stream_child(ck, res_out, codec="int8")
    assert proc.returncode == 0, proc.stderr
    with open(res_out + ".json") as f:
        resumed = json.load(f)
    assert resumed["resumes"] >= 1
    assert np.array_equal(np.load(clean_out), np.load(res_out))
    assert not ckptlib.stream_pending(ck)


def test_codec_change_restarts_instead_of_resuming(mesh):
    x = _posdata()
    d = tempfile.mkdtemp(prefix="bolt-codec-switch-")
    _chaos.inject("stream.upload", nth=5)
    try:
        with stream.uploaders(1):
            _src(x, mesh, codec="int8", ck=d).sum().cache()
    except _chaos.ChaosError:
        pass
    finally:
        _chaos.clear()
    assert ckptlib.stream_pending(d)
    c0 = engine.counters()
    got = np.asarray(_src(x, mesh, codec="delta-f32",
                          ck=d).sum().toarray())
    c1 = engine.counters()
    # fingerprint mismatch: the int8 checkpoint is ignored, the run
    # restarts from slab 0 under the new codec — never resumed wrong
    assert c1["stream_resumes"] - c0["stream_resumes"] == 0
    assert np.array_equal(got, np.asarray(_src(x, mesh).sum().toarray()))


# ---------------------------------------------------------------------
# the opt-in Pallas decode-and-reduce kernel
# ---------------------------------------------------------------------

def test_fused_decode_sum_parity_locked():
    from bolt_tpu.ops.kernels import fused_decode_sum
    q = np.random.RandomState(8).randint(0, 256, size=(16, 8, 128),
                                         dtype=np.uint8)
    out = fused_decode_sum(jnp.asarray(q), 0.031, -2.25, interpret=True)
    assert out is not None
    ref = np.sum(q.astype(np.float32) * np.float32(0.031)
                 + np.float32(-2.25), axis=0)
    assert np.allclose(np.asarray(out), ref, rtol=1e-6, atol=1e-4)


def test_fused_decode_sum_declines_off_plan():
    from bolt_tpu.ops.kernels import fused_decode_sum
    # unaligned minor dim / wrong dtype / rank-1: the XLA path serves
    assert fused_decode_sum(jnp.zeros((16, 100), jnp.uint8),
                            1.0, 0.0) is None
    assert fused_decode_sum(jnp.zeros((16, 128), jnp.float32),
                            1.0, 0.0) is None
    assert fused_decode_sum(jnp.zeros((128,), jnp.uint8),
                            1.0, 0.0) is None


def test_kernel_path_parity_end_to_end(mesh, monkeypatch):
    x = (np.random.RandomState(9).rand(32, 256) * 10).astype(np.float32)
    off = np.asarray(_src(x, mesh, chunks=8,
                          codec="int8").sum().toarray())
    monkeypatch.setenv("BOLT_CODEC_KERNEL", "1")
    assert codeclib.kernel_enabled()
    on = np.asarray(_src(x, mesh, chunks=8,
                         codec="int8").sum().toarray())
    assert np.allclose(on, off, rtol=1e-5, atol=1e-3)


# ---------------------------------------------------------------------
# analysis: BLT016
# ---------------------------------------------------------------------

def test_blt016_forecasts_bytes_saved_zero_compiles(mesh):
    x = _posdata()
    arr = _src(x, mesh, codec="bf16").map(lambda v: v + 1)
    c0 = engine.counters()
    rep = analysis.check(arr)
    c1 = engine.counters()
    assert c1["misses"] - c0["misses"] == 0
    assert c1["aot_compiles"] - c0["aot_compiles"] == 0
    assert rep.has("BLT016")
    d = next(d for d in rep.diagnostics if d.code == "BLT016")
    assert d.severity == "info" and "bf16" in d.message
    assert "0.50x" in d.message


def test_blt016_lossless_notes_bit_identity(mesh):
    rep = analysis.check(_src(_posdata(), mesh, codec="delta-f32"))
    d = next(d for d in rep.diagnostics if d.code == "BLT016")
    assert "bit-identical" in d.message


def test_blt016_warns_lossy_meets_order_member(mesh):
    # the pending-group walk knows the member names: handles created
    # codec-free, then CHECKED under a lossy scope — the checker
    # forecasts the refusal the executor would raise, as a WARNING
    x = np.random.RandomState(10).randn(*SHAPE).astype(np.float32)
    h = _src(x, mesh).stats("sum", "min")["min"]
    with stream.codec("bf16"):
        rep = analysis.check(h)
    d = next(d for d in rep.diagnostics if d.code == "BLT016")
    assert d.severity == "warning" and "min" in d.message
    h.toarray()                             # scope gone: resolves raw


def test_blt016_info_for_lossless_order_member(mesh):
    x = np.random.RandomState(11).randn(*SHAPE).astype(np.float32)
    src = _src(x, mesh, codec="delta-f32")
    h = src.stats("sum", "min")["min"]      # lossless: allowed
    rep = analysis.check(h)
    d = next(d for d in rep.diagnostics if d.code == "BLT016")
    assert d.severity == "info"
    h.toarray()


def test_blt016_warns_unsupported_dtype(mesh):
    x = (np.arange(np.prod(SHAPE)) % 7).astype(np.int32).reshape(SHAPE)
    with stream.codec("bf16"):
        rep = analysis.check(_src(x, mesh))
    d = next(d for d in rep.diagnostics if d.code == "BLT016")
    assert d.severity == "warning" and "refuse" in d.message


def test_no_codec_no_blt016(mesh):
    rep = analysis.check(_src(_posdata(), mesh))
    assert not rep.has("BLT016")
