"""Value-axis sharding (sequence/context-parallel analog) and explicit
halo exchange — the long-context machinery (SURVEY §2.4 block/chunk
decomposition row; §5 long-context subsystem)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import bolt_tpu as bolt
from bolt_tpu._compat import shard_map as _shard_map
from bolt_tpu.parallel import combined_spec, exchange_halo
from bolt_tpu.utils import allclose


def _x(shape=(4, 16, 6)):
    rs = np.random.RandomState(20)
    return rs.randn(*shape)


def test_combined_spec(mesh2d):
    spec = combined_spec(mesh2d, (4, 16, 6), 1, {0: "b"})
    assert tuple(spec) == ("a", "b", None)
    # an explicit value request wins: the key assignment yields 'a' and
    # takes 'b' instead (reservation-first; used to be an error)
    spec = combined_spec(mesh2d, (4, 16, 6), 1, {0: "a"})
    assert tuple(spec) == ("b", "a", None)
    with pytest.raises(ValueError):
        # two value axes asking for the same mesh axis IS an error
        combined_spec(mesh2d, (4, 16, 6), 1, {0: "b", 1: "b"})
    with pytest.raises(ValueError):
        combined_spec(mesh2d, (4, 15, 6), 1, {0: "b"})  # 15 % 2 != 0
    with pytest.raises(ValueError):
        combined_spec(mesh2d, (4, 16, 6), 1, {0: "zz"})  # unknown axis


def test_chunk_shard_places_data(mesh2d):
    x = _x()
    b = bolt.array(x, mesh2d)  # key (4,) on 'a'; 'b' free
    c = b.chunk(size=(8,), axis=(0,)).shard("b")
    assert c.vshard == {0: "b"}
    data = c._barray._data
    assert len(data.addressable_shards) == 8
    # (4/4, 16/2, 6) per shard
    assert data.addressable_shards[0].data.shape == (1, 8, 6)
    assert allclose(c.unchunk().toarray(), x)


def test_sharded_chunk_map(mesh2d):
    x = _x()
    c = bolt.array(x, mesh2d).chunk(size=(8,), axis=(0,)).shard("b")
    out = c.map(lambda blk: blk * 2 + 1)
    assert out.vshard == {0: "b"}
    assert allclose(out.unchunk().toarray(), x * 2 + 1)
    # output keeps the value-axis shard (no silent re-replication)
    spec = out._barray._data.sharding.spec
    assert tuple(spec)[:2] == ("a", "b")


def test_sharded_padded_map(mesh2d):
    # halo-padded block map across a SHARDED value axis: GSPMD supplies the
    # neighbour data for the overlapping slices
    x = _x()
    c = bolt.array(x, mesh2d).chunk(size=(4,), axis=(0,), padding=1).shard("b")
    out = c.map(lambda blk: blk * 3)
    assert allclose(out.unchunk().toarray(), x * 3)


def test_shard_default_axis(mesh2d):
    x = _x()
    c = bolt.array(x, mesh2d).chunk(size=(8,), axis=(0,))
    assert c.shard("b").vshard == {0: "b"}


def test_exchange_halo(mesh):
    # moving-sum across shard boundaries: explicit ppermute halo
    n = 8
    x = np.arange(n * 4, dtype=np.float64).reshape(n * 4)
    xg = jax.device_put(
        jnp.asarray(x), jax.sharding.NamedSharding(mesh, P("k")))

    def kernel(local):
        padded = exchange_halo(local, 1, 0, "k", mode="zero")
        # window sum over [i-1, i, i+1]
        return padded[:-2] + padded[1:-1] + padded[2:]

    out = jax.jit(_shard_map(kernel, mesh=mesh, in_specs=P("k"),
                                out_specs=P("k")))(xg)
    padded_np = np.concatenate([[0.0], x, [0.0]])
    expected = padded_np[:-2] + padded_np[1:-1] + padded_np[2:]
    assert allclose(np.asarray(jax.device_get(out)), expected)


def test_exchange_halo_wrap(mesh):
    x = np.arange(16, dtype=np.float64)
    xg = jax.device_put(
        jnp.asarray(x), jax.sharding.NamedSharding(mesh, P("k")))

    def kernel(local):
        padded = exchange_halo(local, 1, 0, "k", mode="wrap")
        return padded[:-2] + padded[1:-1] + padded[2:]

    out = jax.jit(_shard_map(kernel, mesh=mesh, in_specs=P("k"),
                                out_specs=P("k")))(xg)
    padded_np = np.concatenate([[x[-1]], x, [x[0]]])
    expected = padded_np[:-2] + padded_np[1:-1] + padded_np[2:]
    assert allclose(np.asarray(jax.device_get(out)), expected)


def test_vshard_survives_axis_exchange(mesh2d):
    # keys_to_values / values_to_keys must re-apply (re-index) value shards
    x = _x()
    c = bolt.array(x, mesh2d, axis=(0,)).chunk(size=(8,), axis=(0,)).shard("b")
    k2v = c.keys_to_values((0,))
    # old value axis 0 shifted right by the 1 moved-in key axis
    assert k2v.vshard == {1: "b"}
    spec = tuple(k2v._barray._data.sharding.spec)
    assert "b" in spec
    assert allclose(k2v.unchunk().toarray(), x)
    # moving the sharded axis itself into the keys drops its value shard
    v2k = c.values_to_keys((0,))
    assert v2k.vshard == {}


def test_vshard_dropped_with_warning_on_indivisible_map(mesh2d):
    import warnings
    x = _x((4, 16, 6))
    c = bolt.array(x, mesh2d).chunk(size=(16,), axis=(0,)).shard("b")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = c.map(lambda blk: blk[:15])  # 16 -> 15: no longer divides 'b'
    assert out.vshard == {}  # metadata matches reality
    assert any("replicated" in str(x.message) for x in w)
    assert allclose(out.unchunk().toarray(), x[:, :15, :])


def test_halo_pad_exceeds_shard(mesh):
    import jax
    from jax.sharding import PartitionSpec as P
    def kernel(local):
        return exchange_halo(local, 5, 0, "k")  # shard extent is 2
    with pytest.raises(ValueError):
        jax.jit(_shard_map(kernel, mesh=mesh, in_specs=P("k"),
                              out_specs=P("k")))(jnp.ones(16))


def test_exchange_halo_validation(mesh):
    def kernel(local):
        return exchange_halo(local, 1, 0, "k", mode="bogus")
    with pytest.raises(ValueError):
        jax.jit(_shard_map(kernel, mesh=mesh, in_specs=P("k"),
                              out_specs=P("k")))(jnp.ones(16))


def test_value_shard_survives_key_axis_absorption(mesh2d):
    # a lone key axis would absorb BOTH mesh axes; an explicit value-axis
    # shard reserves its mesh axis so chunk.shard still works
    from bolt_tpu.parallel.sharding import key_spec
    spec = combined_spec(mesh2d, (8, 4, 6), 1, {0: "b"})
    assert tuple(spec) == ("a", "b", None)
    # and without the reservation the key axis takes the whole mesh
    assert tuple(key_spec(mesh2d, (8, 4, 6), 1)) == (("a", "b"), None, None)
    # end to end through the public chunk API
    x = np.random.RandomState(20).randn(8, 4, 6)
    b = bolt.array(x, mesh2d, axis=(0,))
    cs = b.chunk(size=(2,), axis=(0,)).shard("b", axis=0)
    assert cs.vshard == {0: "b"}
    out = cs.map(lambda blk: blk * 2.0).unchunk()
    assert np.allclose(out.toarray(), x * 2.0)
