"""End-to-end analysis workflows chaining the session's ops across both
backends — the integration surface a Thunder user actually exercises:
preprocess (detrend/zscore/filters) → reduce (stats/quantile/cov/pca) →
select (filter/argmax), with local as the oracle at every stage."""

import numpy as np
import scipy.signal

import bolt_tpu as bolt
from bolt_tpu.ops import (cov, detrend, gaussian, median_filter, pca,
                          smooth, zscore)
from bolt_tpu.utils import allclose


def _both(x, mesh):
    return bolt.array(x), bolt.array(x, mesh, axis=(0,))


def test_calcium_imaging_workflow(mesh):
    # pixels x time with drift + one shared latent oscillation
    rs = np.random.RandomState(42)
    npix, T = 64, 48
    sig = np.sin(np.linspace(0, 4 * np.pi, T))
    x = (rs.randn(npix, T) * 0.3
         + np.linspace(0, 2, T)[None, :]
         + np.outer(rs.randn(npix), sig))
    lb, tb = _both(x, mesh)

    def pipeline(b):
        clean = zscore(detrend(b, order=1), epsilon=1e-9)
        sm = smooth(clean, 3, axis=(0,), size=(12,))
        return sm

    lclean, tclean = pipeline(lb), pipeline(tb)
    assert allclose(lclean.toarray(), tclean.toarray(), rtol=1e-6,
                    atol=1e-8)

    # reductions agree cross-backend and with scipy-built oracles
    for (l, t) in ((lclean, tclean),):
        assert allclose(np.asarray(l.stats().mean()),
                        np.asarray(t.stats().mean()), atol=1e-8)
        assert allclose(l.quantile(0.9).toarray(),
                        t.quantile(0.9).toarray(), rtol=1e-6)
    cl, ct = cov(lclean), cov(tclean)
    assert allclose(cl, ct, rtol=1e-5, atol=1e-8)

    # PCA on the cleaned data recovers the latent oscillation
    _, comps_l, sv_l = pca(lclean, k=2)
    _, comps_t, sv_t = pca(tclean, k=2)
    assert allclose(sv_l, sv_t, rtol=1e-6)
    ref = scipy.signal.detrend(x, axis=1)
    ref = (ref - ref.mean(1, keepdims=True)) / (ref.std(1, keepdims=True)
                                                + 1e-9)
    # smoothing preserves the dominant temporal mode's direction
    c0 = comps_t[:, 0]
    sm_sig = np.convolve(sig - sig.mean(), np.ones(3) / 3, "same")
    assert abs(np.dot(c0, sm_sig / np.linalg.norm(sm_sig))) > 0.9


def test_image_stack_workflow(mesh2d):
    # time x H x W stack on a 2-d mesh: denoise spatially, select the
    # brightest frames, locate each frame's peak pixel
    rs = np.random.RandomState(7)
    x = rs.rand(8, 12, 10) ** 2
    lb = bolt.array(x)
    tb = bolt.array(x, mesh2d, axis=(0,))

    def denoise(b):
        return gaussian(median_filter(b, 3, axis=(0, 1), size=(6, 5)),
                        1.0, axis=(0, 1), size=(6, 5))

    ld, td = denoise(lb), denoise(tb)
    assert allclose(ld.toarray(), td.toarray(), rtol=1e-6, atol=1e-9)

    means = ld.toarray().reshape(8, -1).mean(axis=1)
    thresh = float(np.median(means))
    lf = ld.filter(lambda v: v.mean() > thresh)
    tf = td.filter(lambda v: v.mean() > thresh)
    assert lf.shape == tf.shape
    assert allclose(lf.toarray(), tf.toarray(), rtol=1e-6, atol=1e-9)

    # per-frame peak pixel of the flattened image (argmax over values)
    lpk = np.asarray([np.argmax(f) for f in lf.toarray()])
    peak = lambda v: v.reshape(-1).argmax()
    got = tf.map(peak, axis=(0,)).toarray()
    assert allclose(np.asarray(got), lpk)
    assert allclose(np.asarray(lf.map(peak, axis=(0,)).toarray()), lpk)