"""Streaming out-of-core executor (ISSUE 3): the parity suite plus the
pipeline's operational contracts.

Parity is the load-bearing half: streamed ``map/sum/mean/var/std/
filter(...).sum()/reduce`` must agree with BOTH the local (NumPy) oracle
and the materialised TPU path.  Integer-valued float64 data makes
``sum``/``mean`` exact under ANY fold order, so those compare
bit-identically; a crafted equal-slab-mean dataset makes the Welford/
Chan moment merge exact too, so ``mean/var/std`` ALSO compare
bit-identically there; random data covers the general case at f64
tolerance.  Geometry edges ride along: uneven last slabs, 1-record
slabs, ragged value-chunk plans, halo padding.

Operational contracts: laziness (no callback call before a consumer),
engine counters (the per-slab executable compiles EXACTLY once across a
uniform stream; transfer bytes are exact), overlap (ingest demonstrably
hidden behind compute: ``overlap_efficiency > 0``), fault injection (a
mid-stream source failure joins the prefetch thread, releases the ring
and re-raises the original exception), the BLT105 lint rule, and the
abstract checker's streaming-plan support.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bolt_tpu as bolt
from bolt_tpu import analysis, engine, profile, stream
from bolt_tpu.tpu.array import BoltArrayTPU


N, V0, V1 = 16, 6, 4
SHAPE = (N, V0, V1)


def _intdata():
    """Integer-valued float64: sums are exact under any fold order."""
    return ((np.arange(np.prod(SHAPE)) % 13) - 6).astype(
        np.float64).reshape(SHAPE)


def _source(data, mesh, chunks):
    return bolt.fromcallback(lambda idx: data[idx], data.shape, mesh,
                             dtype=data.dtype, chunks=chunks)


ADD1 = lambda v: v + 1.0
DOUBLE = lambda blk: blk * 2.0
POSSUM = lambda v: v.sum() > 0


# ---------------------------------------------------------------------
# the out-of-core parity suite (satellite: streamed vs local vs
# materialised TPU, uneven last chunks, chunk sizes of 1)
# ---------------------------------------------------------------------

@pytest.mark.parametrize("chunks", [1, 4, 5, 16])
def test_stream_sum_mean_parity_bitexact(mesh, chunks):
    data = _intdata()
    src = _source(data, mesh, chunks)
    streamed_sum = np.asarray(src.map(ADD1).sum().toarray())
    streamed_mean = np.asarray(_source(data, mesh, chunks)
                               .map(ADD1).mean().toarray())
    # local oracle
    lo = bolt.array(data).map(ADD1, axis=(0,))
    assert np.array_equal(streamed_sum, np.asarray(lo.sum(axis=0)))
    # materialised TPU path (same chain, standard programs)
    mat = bolt.array(data, mesh).map(ADD1)
    assert np.array_equal(streamed_sum, np.asarray(mat.sum().toarray()))
    want_mean = np.asarray(mat.mean().toarray())
    if N % chunks == 0:
        # even power-of-two slab structure: every Chan-merge denominator
        # is a power of two, so the streamed mean is BIT-identical
        assert np.array_equal(streamed_mean, want_mean)
    else:
        # ragged tail (slabs 5,5,5,1): s/5 rounds — ULP-level agreement
        assert np.allclose(streamed_mean, want_mean, rtol=1e-14,
                           atol=1e-14)


@pytest.mark.parametrize("chunks", [1, 3, 4])
def test_stream_var_std_parity(mesh, chunks):
    rs = np.random.RandomState(3)
    data = rs.randn(*SHAPE)
    for name, kw in (("var", {}), ("std", {}), ("var", {"ddof": 1}),
                     ("std", {"ddof": 1})):
        got = np.asarray(getattr(_source(data, mesh, chunks), name)(
            **kw).toarray())
        want_local = getattr(np, name)(data, axis=0, **kw)
        want_mat = np.asarray(getattr(bolt.array(data, mesh), name)(
            **kw).toarray())
        assert np.allclose(got, want_local, rtol=1e-12, atol=1e-12)
        assert np.allclose(got, want_mat, rtol=1e-12, atol=1e-12)


def test_stream_welford_bitexact_crafted(mesh):
    # every slab holds equal counts of 3.0 and 7.0 per value slot, so
    # slab means are exactly 5.0, Chan deltas are exactly 0, and every
    # moment intermediate is exactly representable — streamed mean/var/
    # std must be BIT-identical to the materialised path
    data = np.where((np.arange(N) % 2 == 0)[:, None, None],
                    3.0, 7.0) * np.ones(SHAPE)
    src_kw = dict(chunks=4)                 # slabs of 4: 2+2 per slab
    mat = bolt.array(data, mesh)
    for name in ("mean", "var", "std"):
        got = np.asarray(getattr(_source(data, mesh, **src_kw),
                                 name)().toarray())
        want = np.asarray(getattr(mat, name)().toarray())
        assert np.array_equal(got, want), name
        assert np.array_equal(got, getattr(np, "mean" if name == "mean"
                                           else name)(data, axis=0)), name


@pytest.mark.parametrize("chunks", [1, 4, 7])
def test_stream_filter_sum_parity(mesh, chunks):
    data = _intdata()
    got = np.asarray(_source(data, mesh, chunks)
                     .filter(POSSUM).sum().toarray())
    keep = data[data.sum(axis=(1, 2)) > 0]
    assert np.array_equal(got, keep.sum(axis=0))
    # materialised twin: the PR-1 fused filter->sum terminal
    mat = np.asarray(bolt.array(data, mesh).filter(POSSUM).sum().toarray())
    assert np.array_equal(got, mat)


def test_stream_filter_all_false_and_empty_mean(mesh):
    data = _intdata()
    never = lambda v: v.sum() > 1e9
    got = np.asarray(_source(data, mesh, 4).filter(never).sum().toarray())
    assert np.array_equal(got, np.zeros((V0, V1)))    # identity fold
    m = np.asarray(_source(data, mesh, 4).filter(never).mean().toarray())
    assert np.all(np.isnan(m))                        # 0/0, like the
    mat = np.asarray(bolt.array(data, mesh).filter(never).mean().toarray())
    assert np.all(np.isnan(mat))                      # fused terminal


def test_stream_filter_mean_parity(mesh):
    data = _intdata()
    got = np.asarray(_source(data, mesh, 4).filter(POSSUM).mean().toarray())
    keep = data[data.sum(axis=(1, 2)) > 0]
    assert np.allclose(got, keep.mean(axis=0), rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("func", [np.maximum, np.minimum])
def test_stream_reduce_parity(mesh, func):
    data = _intdata()
    got = np.asarray(_source(data, mesh, 5).reduce(func).toarray())
    want = func.reduce(data, axis=0)
    assert np.array_equal(got, want)
    mat = np.asarray(bolt.array(data, mesh).reduce(func).toarray())
    assert np.array_equal(got, mat)


@pytest.mark.parametrize("size,axis", [((3,), (0,)), ((4, 3), (0, 1)),
                                       ((5,), (0,))])
def test_stream_chunked_map_parity(mesh, size, axis):
    # (5,) over a 6-long axis is a RAGGED plan: the general (clamp-
    # category) body runs per slab, identically to the materialised one
    data = _intdata()
    got = np.asarray(_source(data, mesh, 4)
                     .chunk(size=size, axis=axis).map(DOUBLE)
                     .sum().toarray())
    mat = bolt.array(data, mesh).chunk(size=size, axis=axis).map(DOUBLE)
    assert np.array_equal(got, np.asarray(mat.sum().toarray()))
    assert np.array_equal(got, (data * 2).sum(axis=0))


def test_stream_chunked_map_padding_parity(mesh):
    # halo padding: shape-preserving func, halos trimmed — the general
    # body per slab must agree with the materialised program
    data = _intdata()
    smooth = lambda blk: blk * 0.5
    got = np.asarray(_source(data, mesh, 4)
                     .chunk(size=(3,), axis=(0,), padding=(1,))
                     .map(smooth).mean().toarray())
    mat = bolt.array(data, mesh).chunk(size=(3,), axis=(0,),
                                       padding=(1,)).map(smooth)
    assert np.array_equal(got, np.asarray(mat.mean().toarray()))


def test_stream_chunked_shape_changing_map(mesh):
    # uniform plans allow per-block shape changes; the streamed view's
    # plan metadata must match the materialised one
    data = _intdata()
    colsum = lambda blk: blk.sum(axis=0, keepdims=True)
    sv = _source(data, mesh, 4).chunk(size=(3, V1), axis=(0, 1)).map(colsum)
    mv = bolt.array(data, mesh).chunk(size=(3, V1), axis=(0, 1)).map(colsum)
    assert sv.plan == mv.plan
    assert np.array_equal(np.asarray(sv.sum().toarray()),
                          np.asarray(mv.sum().toarray()))


def test_stream_stacked_map_parity(mesh):
    data = _intdata()
    zblock = lambda blk: blk - blk.mean(axis=0)    # mixes records IN a block
    # aligned: slab (8) is a multiple of the stack size (4) -> streams
    sv = _source(data, mesh, 8).stacked(4).map(zblock)
    assert sv.unstack().streaming
    mat = bolt.array(data, mesh).stacked(4).map(zblock)
    assert np.array_equal(np.asarray(sv.unstack().sum().toarray()),
                          np.asarray(mat.unstack().sum().toarray()))
    # misaligned (slab 6, size 4): block grouping would differ, so the
    # stage is refused and the map materialises — results still agree
    sv2 = _source(data, mesh, 6).stacked(4).map(zblock)
    assert not sv2.unstack().streaming
    assert np.array_equal(np.asarray(sv2.unstack().sum().toarray()),
                          np.asarray(mat.unstack().sum().toarray()))


def test_fromiter_parity_and_errors(mesh):
    data = _intdata()
    blocks = [data[0:5], data[5:6], data[6:16]]     # ragged block sizes
    it = bolt.fromiter(blocks, SHAPE, mesh, dtype=np.float64)
    assert it.streaming
    assert np.array_equal(np.asarray(it.sum().toarray()),
                          data.sum(axis=0))
    # a list re-streams; materialisation assembles on host
    assert np.array_equal(it.toarray(), data)
    # local twin
    lo = bolt.fromiter(blocks, SHAPE, dtype=np.float64)
    assert lo.mode == "local" and np.array_equal(np.asarray(lo), data)
    with pytest.raises(ValueError, match="explicit dtype"):
        bolt.fromiter(blocks, SHAPE, mesh)
    with pytest.raises(ValueError, match="cover only"):
        bolt.fromiter([data[0:5]], SHAPE, mesh,
                      dtype=np.float64).sum().cache()
    with pytest.raises(ValueError, match="overrun"):
        bolt.fromiter([data, data[:1]], SHAPE, mesh,
                      dtype=np.float64).sum().cache()


def test_stream_map_dtype_and_cast_stage(mesh):
    data = _intdata()
    out = _source(data, mesh, 4).map(ADD1, dtype=np.float32)
    assert out.streaming and out.dtype == np.float32
    got = np.asarray(out.sum().toarray())
    want = (data + 1).astype(np.float32).sum(axis=0, dtype=np.float32)
    assert np.allclose(got, want, rtol=1e-6)


# ---------------------------------------------------------------------
# laziness and materialisation
# ---------------------------------------------------------------------

def test_fromcallback_explicit_dtype_is_lazy(mesh):
    data = _intdata()
    calls = []

    def loader(idx):
        calls.append(idx)
        return data[idx]

    b = bolt.fromcallback(loader, SHAPE, mesh, dtype=np.float64, chunks=4)
    assert b.streaming and calls == []          # nothing produced yet
    assert b.shape == SHAPE and b.dtype == np.float64 and calls == []
    b.sum().cache()                             # the read streams: 4 slabs
    assert len(calls) == 4
    assert all(isinstance(s, slice) for idx in calls for s in idx)
    calls.clear()
    # a non-streaming consumer materialises per device shard
    assert np.array_equal(b.toarray(), data)
    assert len(calls) == len(mesh.devices.ravel())
    assert not b.streaming                      # adopted concrete state
    # dtype=None keeps the eager contract (type inferred from blocks)
    calls.clear()
    e = bolt.fromcallback(loader, SHAPE, mesh)
    assert not e.streaming and len(calls) == len(mesh.devices.ravel())


def test_stream_filtered_shape_materialises(mesh):
    data = _intdata()
    f = _source(data, mesh, 4).filter(POSSUM)
    assert f.streaming and f.dtype == np.float64
    want = data[data.sum(axis=(1, 2)) > 0]
    assert f.shape == want.shape                # materialises + count sync
    assert np.array_equal(f.toarray(), want)


# ---------------------------------------------------------------------
# engine counters: exact transfer accounting, compile-exactly-once
# ---------------------------------------------------------------------

def test_stream_counters_and_compile_once(mesh):
    def add_one(v):                             # stable identity per run
        return v + 1.0

    # geometry UNIQUE to this test, so every engine key is fresh
    data = ((np.arange(12 * 3 * 5) % 11) - 5).astype(
        np.float64).reshape(12, 3, 5)

    c0 = engine.counters()
    src = _source(data, mesh, 3)                # 4 even slabs
    out = src.map(add_one).sum().cache()        # the read streams (lazy)
    c1 = engine.counters()
    d = {k: c1[k] - c0[k] for k in c1}
    assert d["stream_chunks"] == 4
    assert d["transfer_bytes"] == data.nbytes
    assert c1["stream_prefetch_depth"] >= 1
    assert c1["stream_upload_threads"] >= 1
    assert c1["stream_inflight_high_water"] >= 1
    # EXACTLY one executable per program: the per-slab partial (even
    # slabs), its acc-fused twin (odd slabs — the level-0 fold fused
    # into the slab dispatch), and ONE tree merge.  Dispatches are
    # 4 slabs + 1 level-1 merge — the level-0 merges cost nothing,
    # vs 4 + 3 before the fusion (>= 2x fewer fold dispatches).
    assert d["misses"] == 3 and d["aot_compiles"] == 3
    assert d["dispatches"] == 4 + 1
    assert d["stream_ingest_seconds"] > 0
    assert d["stream_wall_seconds"] > 0
    # a second identical run reuses ALL executables: zero new compiles
    c2 = engine.counters()
    out2 = _source(data, mesh, 3).map(add_one).sum().cache()
    c3 = engine.counters()
    d2 = {k: c3[k] - c2[k] for k in c3}
    assert d2["misses"] == 0 and d2["aot_compiles"] == 0
    assert d2["dispatches"] == 4 + 1
    assert np.array_equal(np.asarray(out.toarray()),
                          np.asarray(out2.toarray()))


def test_stream_prefetch_depth_scope():
    before = stream.prefetch_depth()
    assert before >= 1
    with stream.prefetch(5):
        assert stream.prefetch_depth() == 5
    assert stream.prefetch_depth() == before
    stream.set_prefetch_depth(0)            # clamped to >= 1
    assert stream.prefetch_depth() == 1
    stream.set_prefetch_depth(before)


# ---------------------------------------------------------------------
# overlap: transfer demonstrably hidden behind compute
# ---------------------------------------------------------------------

def test_stream_overlap_efficiency_positive(mesh):
    n, d0 = 12, 128
    data = np.arange(n * d0 * d0, dtype=np.float64).reshape(
        (n, d0, d0)) % 7

    def slow_loader(idx):
        time.sleep(0.004)                       # host ingest cost
        return data[idx]

    def heavy(v):                               # real device compute
        for _ in range(6):
            v = jnp.tanh(v @ v.T)
        return v

    src = bolt.fromcallback(slow_loader, data.shape, mesh,
                            dtype=np.float64, chunks=2)
    # the wall-clock overlap is physical but probabilistic under heavy
    # machine load (a saturated host can serialise the prefetch thread
    # behind compute); a couple of retries keep the assertion about the
    # PIPELINE, not the scheduler
    d = None
    for _ in range(3):
        c0 = engine.counters()
        src.map(heavy).sum().cache()
        c1 = engine.counters()
        d = {k: c1[k] - c0[k] for k in c1}
        assert d["stream_chunks"] == 6
        if d["stream_overlap_seconds"] > 0.0:
            break
    # the prefetch thread ingested slab i+1 while the executable ran on
    # slab i: ingest + compute strictly exceeds the wall clock
    assert d["stream_overlap_seconds"] > 0.0
    eff = d["stream_overlap_seconds"] / d["stream_ingest_seconds"]
    assert eff > 0.0
    # the cumulative counter view agrees
    assert profile.overlap_efficiency() > 0.0


# ---------------------------------------------------------------------
# fault injection: mid-stream failures abort cleanly
# ---------------------------------------------------------------------

def test_stream_fault_mid_stream_aborts_cleanly(mesh):
    data = _intdata()
    boom = RuntimeError("storage went away")
    seen = []

    def flaky(idx):
        seen.append(idx)
        if len(seen) == 3:
            raise boom
        return data[idx]

    src = bolt.fromcallback(flaky, SHAPE, mesh, dtype=np.float64,
                            chunks=4)
    threads_before = threading.active_count()
    with pytest.raises(RuntimeError) as ei:
        src.sum().cache()                       # the read streams (lazy)
    assert ei.value is boom                     # the ORIGINAL exception
    # prefetch thread joined, no leak
    assert stream._LAST_THREAD is not None
    assert not stream._LAST_THREAD.is_alive()
    assert threading.active_count() <= threads_before
    # the executor is not poisoned: a healthy stream runs right after
    ok = np.asarray(_source(data, mesh, 4).sum().toarray())
    assert np.array_equal(ok, data.sum(axis=0))


def test_stream_fault_bad_block_shape(mesh):
    bad = bolt.fromcallback(lambda idx: np.zeros((1, 1)), SHAPE, mesh,
                            dtype=np.float64, chunks=4)
    with pytest.raises(ValueError, match="returned shape"):
        bad.sum().cache()
    assert not stream._LAST_THREAD.is_alive()


def test_stream_materialise_failure_is_retryable(mesh):
    # a TRANSIENT source failure during materialisation must leave the
    # array streaming (not half-cleared): the retry re-raises nothing
    # and succeeds, instead of crashing on None state
    data = _intdata()
    calls = []

    def flaky(idx):
        calls.append(idx)
        if len(calls) == 1:
            raise IOError("storage hiccup")
        return data[idx]

    src = bolt.fromcallback(flaky, SHAPE, mesh, dtype=np.float64,
                            chunks=SHAPE[0])
    with pytest.raises(IOError, match="storage hiccup"):
        src.toarray()                           # materialising consumer
    assert src.streaming                        # still a lazy source
    assert np.array_equal(np.asarray(src.toarray()), data)


def test_fromiter_exhausted_restream_raises_pointed_error(mesh):
    # generators are one-shot: a second streamed terminal must say SO,
    # not blame the block count ("cover only 0 of N records")
    data = _intdata()

    def gen():
        yield data[:SHAPE[0] // 2]
        yield data[SHAPE[0] // 2:]

    src = bolt.fromiter(gen(), SHAPE, mesh, dtype=np.float64)
    first = np.asarray(src.sum().toarray())
    assert np.array_equal(first, data.sum(axis=0))
    with pytest.raises(RuntimeError, match="already streamed"):
        src.sum().cache()
    # derived sources share the iterator (with_stage), so the budget is
    # shared too
    src2 = bolt.fromiter(gen(), SHAPE, mesh, dtype=np.float64)
    src2.map(lambda v: v * 2).sum().cache()
    with pytest.raises(RuntimeError, match="already streamed"):
        src2.sum().cache()
    # RE-ITERABLE sources (a list of blocks) stream repeatedly — the
    # guard is for one-shot iterators only
    lst = bolt.fromiter([data], SHAPE, mesh, dtype=np.float64)
    a = np.asarray(lst.sum().toarray())
    b = np.asarray(lst.sum().toarray())
    assert np.array_equal(a, b)


# ---------------------------------------------------------------------
# static analysis: streaming plans + BLT105
# ---------------------------------------------------------------------

def test_analysis_check_streaming_plan_zero_compiles(mesh):
    data = _intdata()
    p = (_source(data, mesh, 4).chunk(size=(3,), axis=(0,))
         .map(DOUBLE).filter(POSSUM))
    c0 = engine.counters()
    rep = analysis.check(p)
    c1 = engine.counters()
    compiled = (c1["misses"] - c0["misses"]
                + c1["aot_compiles"] - c0["aot_compiles"]
                + c1["dispatches"] - c0["dispatches"])
    assert compiled == 0
    assert "streaming" in rep.target
    assert rep.dynamic and rep.has("BLT008")
    assert rep.shape == (None, V0, V1)
    assert np.dtype(rep.dtype) == np.float64
    assert len(rep.stages) == 3                 # source, chunk-map, filter
    # a static streamed plan predicts exactly
    rep2 = analysis.check(_source(data, mesh, 4).map(ADD1))
    assert rep2.shape == SHAPE and not rep2.dynamic


def test_analysis_strict_gates_streamed_terminal(mesh):
    data = _intdata()
    base = _source(data, mesh, 4)
    # hand-append a NON-SCALAR predicate (the public filter() rejects it
    # eagerly): strict must refuse the streamed terminal before any
    # upload or compile
    src2 = base._stream.with_stage(("filter", lambda v: v > 0))
    arr = BoltArrayTPU._streamed(src2)
    c0 = engine.counters()
    with analysis.strict():
        with pytest.raises(analysis.PipelineError, match="BLT007"):
            arr.sum()
    c1 = engine.counters()
    assert c1["strict_rejections"] - c0["strict_rejections"] == 1
    assert c1["misses"] == c0["misses"]
    assert c1["transfer_bytes"] == c0["transfer_bytes"]
    # a healthy streamed terminal passes the gate
    with analysis.strict():
        out = _source(data, mesh, 4).sum()
    assert np.array_equal(np.asarray(out.toarray()), data.sum(axis=0))


def test_clone_preserves_stream_source(mesh):
    # functional forms (np.copy/np.sort/...) go through _clone: the
    # clone must share the lazy source, not become an unreadable husk
    data = _intdata()
    src = _source(data, mesh, 4)
    c = np.copy(src)
    assert np.array_equal(np.asarray(c), data)
    # the original is untouched and still streams
    assert src.streaming
    assert np.array_equal(np.asarray(src.sum().toarray()),
                          data.sum(axis=0))


def test_fromiter_rejects_missing_dtype_only_single_host(mesh):
    # the multihost guard message exists (can't build a multi-process
    # mesh here; the single-host path must NOT trip it)
    data = _intdata()
    out = bolt.fromiter([data], SHAPE, mesh, dtype=np.float64)
    assert out.streaming


@pytest.mark.lint
def test_lint_exemption_is_path_anchored():
    from bolt_tpu.analysis import astlint
    jitbad = "import jax\n\ndef f(g):\n    return jax.jit(g)\n"
    putbad = "import jax\n\ndef f(x):\n    return jax.device_put(x)\n"
    # files merely ENDING in an exempt name must not inherit the pass
    assert any(f.code == "BLT101"
               for f in astlint.lint_source(jitbad, "bolt_tpu/myengine.py"))
    assert any(f.code == "BLT105"
               for f in astlint.lint_source(putbad, "bolt_tpu/upstream.py"))
    # the real exempt files still pass
    assert not astlint.lint_source(jitbad, "bolt_tpu/engine.py")
    assert not astlint.lint_source(putbad, "bolt_tpu/stream.py")


@pytest.mark.lint
def test_blt105_device_put_rule():
    from bolt_tpu.analysis import astlint
    bad = "import jax\n\ndef f(x, s):\n    return jax.device_put(x, s)\n"
    found = astlint.lint_source(bad, "bolt_tpu/tpu/somewhere.py")
    assert any(f.code == "BLT105" for f in found)
    # alias-aware
    bad2 = ("from jax import device_put\n\n"
            "def f(x):\n    return device_put(x)\n")
    assert any(f.code == "BLT105"
               for f in astlint.lint_source(bad2, "bolt_tpu/x.py"))
    # the transfer layer itself is the sanctioned home
    assert not astlint.lint_source(bad, "bolt_tpu/stream.py")
    # and the whole package still lints clean (BLT105 included)
    assert astlint.lint_package() == []


# ---------------------------------------------------------------------
# parallel ingest (ISSUE 5): the uploader pool, slab-order
# re-sequencing, the async in-flight window, and pool fault paths
# ---------------------------------------------------------------------

def test_uploaders_scope_and_pool_size(mesh):
    data = _intdata()
    src = _source(data, mesh, 4)._stream
    before = stream.upload_threads()
    try:
        stream.set_upload_threads(0)            # auto
        assert stream.pool_size(src) == min(len(mesh.devices.ravel()), 4)
        with stream.uploaders(7):
            assert stream.upload_threads() == 7
            assert stream.pool_size(src) == 7
        assert stream.upload_threads() == 0
        stream.set_upload_threads(2)
        assert stream.pool_size(src) == 2
        # sequential sources always stream through ONE prefetch thread
        it = bolt.fromiter([data], SHAPE, mesh, dtype=np.float64)._stream
        with stream.uploaders(6):
            assert stream.pool_size(it) == 1
    finally:
        stream.set_upload_threads(before)


def test_stream_concurrent_uploaders_counted(mesh):
    # two workers provably ingest AT THE SAME TIME: the loader blocks at
    # a 2-party barrier, so two pool threads must be mid-ingest together
    # before either can finish — the counter records that high-water
    data = _intdata()
    bar = threading.Barrier(2, timeout=20)

    def loader(idx):
        try:
            bar.wait()
        except threading.BrokenBarrierError:
            pass                                # odd tail: proceed alone
        return data[idx]

    src = bolt.fromcallback(loader, SHAPE, mesh, dtype=np.float64,
                            chunks=4)           # 4 slabs, pool >= 2
    c0 = engine.counters()
    with stream.uploaders(2):
        got = np.asarray(src.sum().toarray())
    c1 = engine.counters()
    assert np.array_equal(got, data.sum(axis=0))
    assert c1["stream_upload_threads"] >= 2     # > 1 concurrent uploader
    assert c1["stream_inflight_high_water"] >= 1


def test_stream_sharded_multidevice_parity_bitexact(mesh):
    # slabs that REALLY shard: 32 records, slabs of 8 over the 8-way
    # mesh — each device uploads its own sub-block of every slab via the
    # per-device placement path.  Integer-valued data: sum/mean must be
    # BIT-identical to the materialised path; var/std at f64 tolerance.
    n = 32
    data = ((np.arange(n * V0 * V1) % 17) - 8).astype(
        np.float64).reshape(n, V0, V1)
    mat = bolt.array(data, mesh)
    for chunks in (8, 16):                      # power-of-two slab counts
        for name in ("sum", "mean"):
            got = np.asarray(getattr(_source(data, mesh, chunks),
                                     name)().toarray())
            want = np.asarray(getattr(mat, name)().toarray())
            assert np.array_equal(got, want), (name, chunks)
    for chunks in (5, 1):                       # uneven tail + 1-record
        for name, tol in (("sum", 0.0), ("var", 1e-12), ("std", 1e-12)):
            got = np.asarray(getattr(_source(data, mesh, chunks),
                                     name)().toarray())
            want = np.asarray(getattr(mat, name)().toarray())
            if tol:
                assert np.allclose(got, want, rtol=tol, atol=tol), \
                    (name, chunks)
            else:
                assert np.array_equal(got, want), (name, chunks)


def test_stream_out_of_order_upload_folds_in_slab_order(mesh, monkeypatch):
    # slab 0's upload is HELD BACK until another slab has finished: the
    # re-sequencer must still hand slabs to the fold in slab order, so
    # the result stays bit-identical to the materialised path
    data = _intdata()
    orig = stream._upload_slab
    done = []

    def held_back(block, mesh_, split):
        lo = int(block[0, 0, 0] == data[0, 0, 0] and
                 np.array_equal(block, data[:block.shape[0]]))
        if lo:                                  # slab 0: wait for a peer
            t0 = time.time()
            while not done and time.time() - t0 < 10:
                time.sleep(0.002)
        out = orig(block, mesh_, split)
        done.append(lo)
        return out

    monkeypatch.setattr(stream, "_upload_slab", held_back)
    with stream.uploaders(3):
        got = np.asarray(_source(data, mesh, 4).mean().toarray())
    assert done and done[0] == 0                # slab 0 finished LATE
    assert 1 in done
    want = np.asarray(bolt.array(data, mesh).mean().toarray())
    assert np.array_equal(got, want)            # fold order unaffected


def test_stream_fault_in_uploader_worker_aborts_cleanly(mesh,
                                                        monkeypatch):
    # a raise inside ONE pool worker (not the source callback): the
    # whole pool is joined, ring permits are released, and the ORIGINAL
    # exception re-raises in the consumer
    data = _intdata()
    boom = RuntimeError("device link dropped")
    orig = stream._upload_slab
    calls = []

    def flaky_upload(block, mesh_, split):
        calls.append(block.shape)
        if len(calls) == 2:
            raise boom
        return orig(block, mesh_, split)

    monkeypatch.setattr(stream, "_upload_slab", flaky_upload)
    src = _source(data, mesh, 4)
    with stream.uploaders(2):
        with pytest.raises(RuntimeError) as ei:
            src.sum().cache()
    assert ei.value is boom                     # the ORIGINAL exception
    # the WHOLE pool (dispenser + workers) is joined, nothing leaks
    assert stream._LAST_POOL
    assert all(not t.is_alive() for t in stream._LAST_POOL)
    # the executor is not poisoned: a healthy stream runs right after
    monkeypatch.setattr(stream, "_upload_slab", orig)
    ok = np.asarray(_source(data, mesh, 4).sum().toarray())
    assert np.array_equal(ok, data.sum(axis=0))


def test_stream_dead_pool_thread_raises_pointed_error(mesh, monkeypatch):
    # the q.get()-blocks-forever bug: a pool thread that dies WITHOUT
    # enqueueing anything (teardown-killed before its fault handler ran)
    # must surface as a pointed RuntimeError naming the dead thread, not
    # hang the consumer.  Simulated by muting the fault funnel.
    data = _intdata()
    monkeypatch.setattr(stream._Reseq, "fault",
                        lambda self, exc: None)

    def dying(idx):
        raise RuntimeError("this error is swallowed by the mute")

    src = bolt.fromcallback(dying, SHAPE, mesh, dtype=np.float64,
                            chunks=4)
    with pytest.raises(RuntimeError, match="died without delivering"):
        src.sum().cache()
    with pytest.raises(RuntimeError, match="bolt-stream"):
        bolt.fromcallback(dying, SHAPE, mesh, dtype=np.float64,
                          chunks=4).sum().cache()
    # the harder shape: MORE slabs than the ring, so the dispenser is
    # still alive, blocked on ring permits, when every worker dies —
    # dead workers must trip the guard anyway (nothing can ever arrive)
    with stream.uploaders(2), stream.prefetch(1):   # ring 3 << 16 slabs
        with pytest.raises(RuntimeError, match="died without delivering"):
            bolt.fromcallback(dying, SHAPE, mesh, dtype=np.float64,
                              chunks=1).sum().cache()


def test_stream_inflight_window_bounds_and_records(mesh):
    # a long stream (16 one-record slabs, depth 1, one uploader) must
    # keep the in-flight window bounded by the ring and record the
    # high-water; the ring permits keep cycling (no deadlock, exact sum)
    data = _intdata()
    c0 = engine.counters()
    with stream.prefetch(1), stream.uploaders(1):
        got = np.asarray(_source(data, mesh, 1).sum().toarray())
    c1 = engine.counters()
    assert np.array_equal(got, data.sum(axis=0))
    assert c1["stream_inflight_high_water"] >= 1
    d = {k: c1[k] - c0[k] for k in c1}
    assert d["stream_chunks"] == 16


# ---------------------------------------------------------------------
# chunked-view terminals on MATERIALISED arrays (delegation parity)
# ---------------------------------------------------------------------

def test_chunked_terminals_materialised(mesh):
    data = _intdata()
    cv = bolt.array(data, mesh).chunk(size=(3,), axis=(0,))
    b = bolt.array(data, mesh)
    assert np.array_equal(np.asarray(cv.sum().toarray()),
                          np.asarray(b.sum().toarray()))
    assert np.array_equal(np.asarray(cv.mean().toarray()),
                          np.asarray(b.mean().toarray()))
    assert np.array_equal(np.asarray(cv.std(ddof=1).toarray()),
                          np.asarray(b.std(ddof=1).toarray()))
    assert np.array_equal(np.asarray(cv.reduce(np.maximum).toarray()),
                          np.asarray(b.reduce(np.maximum).toarray()))
    f = cv.filter(POSSUM)
    assert f.shape == b.filter(POSSUM).shape


# ---------------------------------------------------------------------
# scope thread-locality (ISSUE 8 regression): concurrent streams on
# different threads must not leak uploaders()/prefetch() scope values
# into each other — under the multi-tenant serving layer every tenant
# runs on its own worker thread
# ---------------------------------------------------------------------

def test_uploaders_and_prefetch_scopes_are_thread_local(mesh):
    default_uploaders = stream.upload_threads()
    default_depth = stream.prefetch_depth()
    barrier = threading.Barrier(2, timeout=10)
    seen = {}
    fail = []

    def run(name, n, k):
        try:
            with stream.uploaders(n), stream.prefetch(k):
                barrier.wait()          # both threads inside their scopes
                seen[name] = (stream.upload_threads(),
                              stream.prefetch_depth())
                barrier.wait()          # hold the scopes open until both
        except Exception as exc:        # sampled under the other's scope
            fail.append(exc)

    t1 = threading.Thread(target=run, args=("a", 7, 5), daemon=True)
    t2 = threading.Thread(target=run, args=("b", 2, 3), daemon=True)
    t1.start()
    t2.start()
    t1.join(20)
    t2.join(20)
    assert not fail
    assert seen["a"] == (7, 5)          # each thread saw ITS scope only
    assert seen["b"] == (2, 3)
    # the main thread (and the process default) never saw either scope
    assert stream.upload_threads() == default_uploaders
    assert stream.prefetch_depth() == default_depth


def test_scoped_pool_size_resolves_per_thread(mesh):
    data = _intdata()
    src = _source(data, mesh, 4)._stream
    got = {}

    def other():
        with stream.uploaders(3):
            got["other"] = stream.pool_size(src)

    with stream.uploaders(1):
        th = threading.Thread(target=other, daemon=True)
        th.start()
        th.join(10)
        got["main"] = stream.pool_size(src)
    assert got == {"other": 3, "main": 1}
