"""Profiling/timing instrumentation tests (the tracing slot of SURVEY §5)."""

import os

import numpy as np

import bolt_tpu as bolt
from bolt_tpu import profile


def test_timeit_and_throughput(mesh):
    b = bolt.ones((8, 32), mesh)
    result, secs = profile.timeit(lambda: b.map(lambda v: v * 2).sum()._data,
                                  iters=2, warmup=1)
    assert secs > 0
    assert np.allclose(np.asarray(result), np.full(32, 16.0))
    gbps = profile.throughput(profile.array_bytes(b), secs)
    assert gbps > 0


def test_array_bytes(mesh):
    b = bolt.ones((8, 4), mesh, dtype=np.float32)
    assert profile.array_bytes(b) == 8 * 4 * 4


def test_annotate_and_trace(tmp_path, mesh):
    with profile.annotate("bolt-test-region"):
        bolt.ones((8, 2), mesh).sum().toarray()
    logdir = str(tmp_path / "trace")
    with profile.trace(logdir):
        bolt.ones((8, 2), mesh).sum().toarray()
    assert os.path.isdir(logdir)


def test_debug_nans_toggle():
    import jax
    profile.debug_nans(True)
    assert jax.config.jax_debug_nans
    profile.debug_nans(False)
    assert not jax.config.jax_debug_nans


def test_memory_stats_dict():
    from bolt_tpu.profile import memory_stats
    s = memory_stats()
    assert isinstance(s, dict)  # CPU backend may expose {} or counters
    for k, v in s.items():
        assert isinstance(k, str) and isinstance(v, int)


def test_instrument_counts_ops_and_builds(mesh):
    import bolt_tpu as bolt
    from bolt_tpu import profile
    x = np.random.RandomState(0).randn(8, 4, 5)
    b = bolt.array(x, mesh)
    f = lambda v: v * 2
    with profile.instrument() as stats:
        for _ in range(3):
            b.map(f).sum().toarray()
        b.stats()
    assert "stat" in stats and stats["stat"]["calls"] == 3
    # one compiled program serves all three identical pipelines
    assert stats["stat"]["builds"] == 1
    assert "welford" in stats
    assert stats["stat"]["dispatch_s"] >= 0.0
    txt = profile.report(stats)
    assert "stat" in txt and "builds" in txt
    # the patch is scoped: outside the context the plain cache is back
    import bolt_tpu.tpu.array as arr
    import bolt_tpu.tpu.stats as stats_mod
    assert arr._cached_jit is stats_mod._cached_jit


def test_instrument_detects_recompiles(mesh):
    import bolt_tpu as bolt
    from bolt_tpu import profile
    b = bolt.array(np.random.RandomState(1).randn(8, 4), mesh)
    with profile.instrument() as stats:
        for _ in range(3):
            b.map(lambda v: v + 1).sum().toarray()   # fresh lambda: rebuilds
    assert stats["stat"]["builds"] == 3              # the smoking gun
