"""Profiling/timing instrumentation tests (the tracing slot of SURVEY §5)."""

import os

import numpy as np

import bolt_tpu as bolt
from bolt_tpu import profile


def test_timeit_and_throughput(mesh):
    b = bolt.ones((8, 32), mesh)
    result, secs = profile.timeit(lambda: b.map(lambda v: v * 2).sum()._data,
                                  iters=2, warmup=1)
    assert secs > 0
    assert np.allclose(np.asarray(result), np.full(32, 16.0))
    gbps = profile.throughput(profile.array_bytes(b), secs)
    assert gbps > 0


def test_array_bytes(mesh):
    b = bolt.ones((8, 4), mesh, dtype=np.float32)
    assert profile.array_bytes(b) == 8 * 4 * 4


def test_annotate_and_trace(tmp_path, mesh):
    with profile.annotate("bolt-test-region"):
        bolt.ones((8, 2), mesh).sum().toarray()
    logdir = str(tmp_path / "trace")
    with profile.trace(logdir):
        bolt.ones((8, 2), mesh).sum().toarray()
    assert os.path.isdir(logdir)


def test_debug_nans_toggle():
    import jax
    profile.debug_nans(True)
    assert jax.config.jax_debug_nans
    profile.debug_nans(False)
    assert not jax.config.jax_debug_nans


def test_memory_stats_dict():
    from bolt_tpu.profile import memory_stats
    s = memory_stats()
    assert isinstance(s, dict)  # CPU backend may expose {} or counters
    for k, v in s.items():
        assert isinstance(k, str) and isinstance(v, int)
