"""Resumable streams (ISSUE 9): slab-level checkpointing, uploader
retry with fencing, and the chaos-injection harness.

The load-bearing contract is the kill-mid-run proof: with a
deterministic fault killing an uploader — a thread-level raise AND a
subprocess ``kill -9`` — a streamed ``sum`` / ``stats("sum", "var")``
over ≥ 8 slabs resumes from the last retired-slab checkpoint and the
result is BIT-IDENTICAL to the uninterrupted run.  Around it: the
``_chaos`` registry's determinism, the in-run retry budget (absorbed
faults, chained exhaustion, re-sequencer fencing against double-folds),
checkpoint hygiene (fingerprint mismatch refused, success clears, no
torn meta), the orbax-less checkpoint degradation, BLT011, BLT109, and
the deduped dead-thread report.
"""

import importlib.util
import os
import sys
import threading

import numpy as np
import pytest

import jax

import bolt_tpu as bolt
from bolt_tpu import _chaos as chaos
from bolt_tpu import analysis, checkpoint, engine, stream

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N = 32
SHAPE = (N, 6, 4)


@pytest.fixture(autouse=True)
def _no_armed_chaos():
    """Every test leaves the fault registry empty (an armed point would
    sabotage whichever test streams next)."""
    chaos.clear()
    yield
    chaos.clear()


def _intdata():
    """Integer-valued f64: sums are exact under any fold order, so
    'bit-identical' is checkable against the oracle too."""
    return ((np.arange(np.prod(SHAPE)) % 13) - 6).astype(
        np.float64).reshape(SHAPE)


def _source(data, mesh, ck=None, chunks=4):
    return bolt.fromcallback(lambda idx: data[idx], data.shape, mesh,
                             dtype=np.float64, chunks=chunks,
                             checkpoint=ck)


# ---------------------------------------------------------------------
# the chaos registry
# ---------------------------------------------------------------------

def test_chaos_nth_hit_and_times():
    chaos.inject("t.seam", nth=3)
    chaos.hit("t.seam")
    chaos.hit("t.seam")
    with pytest.raises(chaos.ChaosError, match="t.seam"):
        chaos.hit("t.seam")
    chaos.hit("t.seam")                  # times=1: disarmed after 1 trip
    assert chaos.stats("t.seam") == (4, 1)
    chaos.clear("t.seam")
    assert chaos.active() == []


def test_chaos_custom_exc_and_unbounded_times():
    chaos.inject("t.seam2", nth=1, exc=IOError("link down"), times=None)
    for _ in range(3):
        with pytest.raises(IOError, match="link down"):
            chaos.hit("t.seam2")
    assert chaos.stats("t.seam2") == (3, 3)


def test_chaos_env_form(monkeypatch):
    monkeypatch.setenv("BOLT_CHAOS", "x.y:2:raise:disk gone")
    chaos._load_env()
    chaos.hit("x.y")
    with pytest.raises(chaos.ChaosError, match="disk gone"):
        chaos.hit("x.y")
    with pytest.raises(ValueError, match="point:nth"):
        monkeypatch.setenv("BOLT_CHAOS", "malformed")
        chaos._load_env()


def test_chaos_rejects_unknown_action():
    with pytest.raises(ValueError, match="raise.*kill"):
        chaos.inject("t.x", action="explode")


def test_chaos_disarmed_is_free():
    # the production cost: one module-global check, no lookup
    assert not chaos._ARMED
    chaos.hit("never.armed")             # no-op, no counting
    assert chaos.stats("never.armed") == (0, 0)


# ---------------------------------------------------------------------
# in-run retry: absorbed faults, chained exhaustion, fencing
# ---------------------------------------------------------------------

def test_retry_scope_and_env(monkeypatch):
    before = stream.retry_limit()
    assert before == 0                   # default: fail-fast
    with stream.retries(3):
        assert stream.retry_limit() == 3
    assert stream.retry_limit() == before
    stream.set_retries(2)
    try:
        assert stream.retry_limit() == 2
    finally:
        stream.set_retries(before)


def test_retry_absorbs_uploader_fault_bit_identical(mesh):
    data = _intdata()
    clean = np.asarray(_source(data, mesh).sum().toarray())
    chaos.inject("stream.upload", nth=3)         # one trip, then healthy
    c0 = engine.counters()
    with stream.retries(2):
        got = np.asarray(_source(data, mesh).sum().toarray())
    c1 = engine.counters()
    assert np.array_equal(got, clean)
    assert c1["stream_retries"] - c0["stream_retries"] == 1


def test_retry_exhausted_chains_back_to_original(mesh):
    data = _intdata()
    chaos.inject("stream.upload", nth=2, times=None)   # never heals
    with stream.retries(2):
        with pytest.raises(RuntimeError, match="after 2 retries") as ei:
            _source(data, mesh).sum().cache()
    # final error -> last attempt -> ... -> the ORIGINAL failure
    e = ei.value.__cause__
    depth = 0
    while e is not None:
        assert isinstance(e, chaos.ChaosError)
        e = e.__cause__
        depth += 1
    assert depth == 3                    # 1 original + 2 retries


def test_default_zero_retries_keeps_original_exception(mesh):
    data = _intdata()
    boom = RuntimeError("storage went away")
    chaos.inject("stream.upload", nth=2, exc=boom)
    with pytest.raises(RuntimeError) as ei:
        _source(data, mesh).sum().cache()
    assert ei.value is boom              # untouched, unchained


def test_retry_covers_fromiter_upload(mesh):
    data = _intdata()
    clean = np.asarray(bolt.fromiter([data], SHAPE, mesh,
                                     dtype=np.float64).sum().toarray())
    chaos.inject("stream.upload", nth=1)
    c0 = engine.counters()
    with stream.retries(1):
        got = np.asarray(bolt.fromiter([data], SHAPE, mesh,
                                       dtype=np.float64).sum().toarray())
    c1 = engine.counters()
    assert np.array_equal(got, clean)
    assert c1["stream_retries"] - c0["stream_retries"] == 1


def test_reseq_fences_duplicate_deliveries():
    r = stream._Reseq()
    assert r.put(0, "a") and r.put(1, "b")
    assert not r.put(1, "late duplicate")        # still queued
    got = r.next([threading.current_thread()])
    assert got == (0, "a")
    assert not r.put(0, "after retirement")      # already folded
    assert r.fenced == 2
    assert r.next([threading.current_thread()]) == (1, "b")


def test_dead_workers_each_named_once(mesh, monkeypatch):
    # TWO dead workers: the pointed error must name each exactly once
    # (the dedupe satellite), not repeat the list per poll
    monkeypatch.setattr(stream._Reseq, "fault", lambda self, exc: None)

    def dying(idx):
        raise RuntimeError("swallowed by the mute")

    src = bolt.fromcallback(dying, SHAPE, mesh, dtype=np.float64,
                            chunks=4)
    with stream.uploaders(2):
        with pytest.raises(RuntimeError,
                           match="died without delivering") as ei:
            src.sum().cache()
    msg = str(ei.value)
    for w in ("'bolt-stream-upload-0'", "'bolt-stream-upload-1'"):
        assert msg.count(w) == 1, (w, msg)


def test_dead_error_fires_once_per_dead_set():
    r = stream._Reseq()

    class _T:
        def __init__(self, name):
            self.name = name
            self.ident = id(self)

        def is_alive(self):
            return False

    a, b = _T("w-0"), _T("w-1")
    e1 = r._dead([a, b])
    e2 = r._dead([a, b])
    assert e1 is e2                      # same set -> the SAME error
    assert str(e1).count("'w-0'") == 1 and str(e1).count("'w-1'") == 1
    c = _T("w-2")
    assert r._dead([a, b, c]) is not e1  # a new set is a new report


# ---------------------------------------------------------------------
# the kill-mid-run proof, thread-raise variant (>= 8 slabs)
# ---------------------------------------------------------------------

def test_resume_sum_bit_identical_thread_raise(mesh, tmp_path):
    data = _intdata()
    ck = str(tmp_path / "ck")
    clean = np.asarray(_source(data, mesh).sum().toarray())
    chaos.inject("stream.upload", nth=5)         # die at slab 5 of 8
    c0 = engine.counters()
    with pytest.raises(chaos.ChaosError):
        with stream.uploaders(1):
            _source(data, mesh, ck=ck).sum().cache()
    chaos.clear()
    c1 = engine.counters()
    assert checkpoint.stream_pending(ck)         # the watermark survived
    assert c1["checkpoint_bytes"] > c0["checkpoint_bytes"]
    assert c1["checkpoint_seconds"] > c0["checkpoint_seconds"]
    got = np.asarray(_source(data, mesh, ck=ck).sum().toarray())
    c2 = engine.counters()
    assert np.array_equal(got, clean)            # BIT-identical
    assert np.array_equal(got, (data).sum(axis=0))
    assert c2["stream_resumes"] - c1["stream_resumes"] == 1
    # the resumed run streamed FEWER than all 8 slabs
    assert c2["stream_chunks"] - c1["stream_chunks"] < 8
    assert not checkpoint.stream_pending(ck)     # success cleared it


def test_resume_multi_stat_bit_identical(mesh, tmp_path):
    # streamed stats("sum", "var"): the fused tuple accumulator (sum +
    # (n, mu, M2) moments) must checkpoint and resume bit-identically
    rs = np.random.RandomState(5)
    data = rs.randn(*SHAPE)
    ck = str(tmp_path / "ck")

    def run(ckdir=None):
        out = _source(data, mesh, ck=ckdir).stats("sum", "var")
        return {k: np.asarray(v.toarray()) for k, v in out.items()}

    ref = run()
    chaos.inject("stream.upload", nth=5)
    with pytest.raises(chaos.ChaosError):
        with stream.uploaders(1):
            vals = _source(data, mesh, ck=ck).stats("sum", "var")
            [v.cache() for v in vals.values()]
    chaos.clear()
    assert checkpoint.stream_pending(ck)
    got = run(ckdir=ck)
    for k in ref:
        assert np.array_equal(got[k], ref[k]), k
    assert not checkpoint.stream_pending(ck)


def test_resume_var_through_resumable_scope(mesh, tmp_path):
    # the scope form (no per-source dir) + a moments terminal
    data = _intdata()
    ck = str(tmp_path / "ck")
    clean = np.asarray(_source(data, mesh).var().toarray())
    chaos.inject("stream.upload", nth=5)
    with pytest.raises(chaos.ChaosError):
        with stream.resumable(ck), stream.uploaders(1):
            _source(data, mesh).var().cache()
    chaos.clear()
    assert checkpoint.stream_pending(ck)
    with stream.resumable(ck):
        got = np.asarray(_source(data, mesh).var().toarray())
    assert np.array_equal(got, clean)
    assert not checkpoint.stream_pending(ck)


def test_resume_fromiter_reiterable(mesh, tmp_path):
    data = _intdata()
    blocks = [data[:8], data[8:16], data[16:24], data[24:]]
    ck = str(tmp_path / "ck")

    def make(ckdir=None):
        return bolt.fromiter(blocks, SHAPE, mesh, dtype=np.float64,
                             checkpoint=ckdir)

    clean = np.asarray(make().mean().toarray())
    chaos.inject("stream.upload", nth=3)
    with pytest.raises(chaos.ChaosError):
        make(ck).mean().cache()
    chaos.clear()
    assert checkpoint.stream_pending(ck)
    got = np.asarray(make(ck).mean().toarray())
    assert np.array_equal(got, clean)


def test_resume_fromiter_layout_drift_refused(mesh, tmp_path):
    data = _intdata()
    ck = str(tmp_path / "ck")
    blocks = [data[:8], data[8:16], data[16:24], data[24:]]
    chaos.inject("stream.upload", nth=3)
    with pytest.raises(chaos.ChaosError):
        bolt.fromiter(blocks, SHAPE, mesh, dtype=np.float64,
                      checkpoint=ck).sum().cache()
    chaos.clear()
    assert checkpoint.stream_pending(ck)
    # a DIFFERENT block layout cannot satisfy the record watermark
    drifted = [data[:16], data[16:]]
    with pytest.raises(RuntimeError, match="drifted|ended after"):
        bolt.fromiter(drifted, SHAPE, mesh, dtype=np.float64,
                      checkpoint=ck).sum().cache()


def test_stale_checkpoint_other_pipeline_ignored(mesh, tmp_path):
    # a checkpoint cut from sum() must NOT seed a mean() over the same
    # dir: the fingerprint mismatch means a from-scratch (correct) run
    data = _intdata()
    ck = str(tmp_path / "ck")
    chaos.inject("stream.upload", nth=5)
    with pytest.raises(chaos.ChaosError):
        with stream.uploaders(1):
            _source(data, mesh, ck=ck).sum().cache()
    chaos.clear()
    assert checkpoint.stream_pending(ck)
    c0 = engine.counters()
    got = np.asarray(_source(data, mesh, ck=ck).mean().toarray())
    c1 = engine.counters()
    assert c1["stream_resumes"] == c0["stream_resumes"]   # no resume
    assert c1["stream_chunks"] - c0["stream_chunks"] == 8  # full stream
    want = np.asarray(bolt.array(data, mesh).mean().toarray())
    assert np.array_equal(got, want)


def test_checkpoint_write_failure_surfaces_then_heals(mesh, tmp_path):
    # the checkpoint-write seam is itself a chaos point: a failing
    # write aborts the run (the fault funnel), and the executor is not
    # poisoned afterwards
    data = _intdata()
    ck = str(tmp_path / "ck")
    chaos.inject("stream.checkpoint", nth=1)
    with pytest.raises(chaos.ChaosError):
        _source(data, mesh, ck=ck).sum().cache()
    chaos.clear()
    got = np.asarray(_source(data, mesh, ck=ck).sum().toarray())
    assert np.array_equal(got, data.sum(axis=0))
    assert not checkpoint.stream_pending(ck)


def test_resumed_run_zero_new_compiles_second_resume(mesh, tmp_path):
    # resuming twice over the same geometry reuses every executable the
    # first resume compiled (the host-array acc signature included)
    data = _intdata()
    ck = str(tmp_path / "ck")
    clean = np.asarray(_source(data, mesh).sum().toarray())
    # first kill at upload 5 of 8; the SECOND run resumes (only ~4
    # slabs left) and is killed again at its upload 2
    for nth in (5, 2):
        chaos.inject("stream.upload", nth=nth)
        with pytest.raises(chaos.ChaosError):
            with stream.uploaders(1):
                _source(data, mesh, ck=ck).sum().cache()
        chaos.clear()
    c0 = engine.counters()
    got = np.asarray(_source(data, mesh, ck=ck).sum().toarray())
    c1 = engine.counters()
    assert np.array_equal(got, clean)
    assert c1["misses"] - c0["misses"] <= 2      # resume-signature twins


# ---------------------------------------------------------------------
# the kill-mid-run proof, subprocess kill -9 variant
# ---------------------------------------------------------------------

def test_subprocess_kill9_resume_bit_identical(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "chaos_run", os.path.join(REPO, "scripts", "chaos_run.py"))
    chaos_run = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(chaos_run)
    r = chaos_run.run_resume_bench(workdir=str(tmp_path))
    assert r["killed_rc"] != 0                   # SIGKILL, not an exit
    assert r["resumes"] >= 1                     # resumed, not restarted
    assert r["slabs_resumed"] < r["slabs_total"]
    assert r["identical"]                        # bit-identical result
    assert not r["stale_checkpoint"]


# ---------------------------------------------------------------------
# checkpoint layer: atomicity order, orbax degradation
# ---------------------------------------------------------------------

def test_stream_meta_written_last_state_first(tmp_path, monkeypatch):
    # a crash between the two renames must leave NO meta (checkpoint
    # "does not exist") rather than meta pointing at missing state
    calls = []
    real_replace = os.replace

    def tracing_replace(a, b):
        calls.append(os.path.basename(b))
        return real_replace(a, b)

    monkeypatch.setattr(os, "replace", tracing_replace)
    checkpoint.stream_save(str(tmp_path), ("fp",), 2, 8,
                           ([np.ones(3)], None))
    assert calls == ["stream_state.npz", "stream_meta.json"]


def test_stream_clear_removes_meta_first(tmp_path, monkeypatch):
    checkpoint.stream_save(str(tmp_path), ("fp",), 2, 8,
                           ([np.ones(3)], None))
    removed = []
    real_remove = os.remove

    def tracing_remove(p):
        removed.append(os.path.basename(p))
        return real_remove(p)

    monkeypatch.setattr(os, "remove", tracing_remove)
    checkpoint.stream_clear(str(tmp_path))
    assert removed == ["stream_meta.json", "stream_state.npz"]
    checkpoint.stream_clear(str(tmp_path))       # idempotent


def test_torn_meta_state_pair_refused(tmp_path):
    # a kill BETWEEN the state rename and the meta rename leaves the
    # OLD meta next to the NEW state; the watermark cross-check inside
    # the state file must refuse the pair (resuming it would fold the
    # stale watermark onto the newer accumulator — double-counting)
    import shutil
    fp = ("fp",)
    checkpoint.stream_save(str(tmp_path), fp, 2, 8, ([np.ones(3)], None))
    meta = os.path.join(str(tmp_path), "stream_meta.json")
    shutil.copy(meta, meta + ".old")
    checkpoint.stream_save(str(tmp_path), fp, 4, 16,
                           ([np.full(3, 2.0)], None))
    assert checkpoint.stream_load(str(tmp_path), fp) is not None
    os.replace(meta + ".old", meta)      # the torn window, reproduced
    assert checkpoint.stream_load(str(tmp_path), fp) is None
    # a consistent pair loads again
    checkpoint.stream_save(str(tmp_path), fp, 6, 24, ([np.ones(3)], None))
    assert checkpoint.stream_load(str(tmp_path), fp)[0] == 6


def test_edited_pipeline_fingerprint_refused(mesh, tmp_path):
    # same dir, same geometry, EDITED stage body: the bytecode-token
    # fingerprint must refuse the checkpoint — both lambdas are
    # "<lambda>" by name, which is exactly why names are not enough
    data = _intdata()
    ck = str(tmp_path / "ck")
    chaos.inject("stream.upload", nth=5)
    with pytest.raises(chaos.ChaosError):
        with stream.uploaders(1):
            _source(data, mesh, ck=ck).map(lambda v: v + 1).sum().cache()
    chaos.clear()
    assert checkpoint.stream_pending(ck)
    c0 = engine.counters()
    got = np.asarray(_source(data, mesh, ck=ck)
                     .map(lambda v: v * 2).sum().toarray())
    c1 = engine.counters()
    assert c1["stream_resumes"] == c0["stream_resumes"]   # refused
    assert np.array_equal(got, (data * 2).sum(axis=0))    # correct


def test_code_token_distinguishes_lambda_bodies():
    from bolt_tpu.utils import code_token
    a = code_token(lambda v: v + 1)
    b = code_token(lambda v: v * 2)
    c = code_token(lambda v: v + 2)      # same bytecode, different const
    assert a != b and a != c and b != c
    assert a.startswith("<lambda>#")
    assert code_token(np.maximum) == "maximum"   # no bytecode: name
    # stable across definitions of the same source shape
    assert code_token(lambda v: v + 1) == a


def test_checkpoint_save_without_orbax_npy_fallback(mesh, tmp_path,
                                                    monkeypatch):
    x = np.random.RandomState(1).randn(8, 4)
    b = bolt.array(x, mesh)
    monkeypatch.setitem(sys.modules, "orbax", None)
    monkeypatch.setitem(sys.modules, "orbax.checkpoint", None)
    path = str(tmp_path / "ck")
    checkpoint.save(path, b)                     # degrades, no raise
    assert os.path.exists(os.path.join(path, "array.npy"))
    r = checkpoint.load(path, context=mesh)      # loads without orbax
    assert np.allclose(np.asarray(r.toarray()), x)
    assert r.split == 1


def test_checkpoint_npy_format_readable_with_orbax_back(mesh, tmp_path,
                                                        monkeypatch):
    x = np.random.RandomState(2).randn(8, 4)
    with monkeypatch.context() as m:
        m.setitem(sys.modules, "orbax", None)
        m.setitem(sys.modules, "orbax.checkpoint", None)
        checkpoint.save(str(tmp_path / "ck"), bolt.array(x, mesh))
    # orbax restored: the npy-format checkpoint still loads
    r = checkpoint.load(str(tmp_path / "ck"), context=mesh)
    assert np.allclose(np.asarray(r.toarray()), x)


def test_checkpoint_multiprocess_without_orbax_pointed_error(
        mesh, tmp_path, monkeypatch):
    b = bolt.array(np.ones((4, 2)), mesh)
    monkeypatch.setitem(sys.modules, "orbax", None)
    monkeypatch.setitem(sys.modules, "orbax.checkpoint", None)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(ImportError, match="orbax-checkpoint"):
        checkpoint.save(str(tmp_path / "c"), b)


# ---------------------------------------------------------------------
# BLT011 + BLT109
# ---------------------------------------------------------------------

def test_blt011_one_shot_iterator_under_resumable(mesh, tmp_path):
    data = _intdata()

    def gen():
        yield data

    src = bolt.fromiter(gen(), SHAPE, mesh, dtype=np.float64,
                        checkpoint=str(tmp_path))
    rep = analysis.check(src)
    assert rep.has("BLT011")
    assert rep.ok                        # warning severity, not error
    [d] = [d for d in rep.diagnostics if d.code == "BLT011"]
    assert d.severity == "warning" and "one-shot" in d.message
    # re-iterable block lists resume fine: no finding
    lst = bolt.fromiter([data], SHAPE, mesh, dtype=np.float64)
    with stream.resumable(str(tmp_path)):
        assert not analysis.check(lst).has("BLT011")
    # no checkpointing armed: quiet
    assert not analysis.check(
        bolt.fromiter(gen(), SHAPE, mesh, dtype=np.float64)).has("BLT011")


@pytest.mark.lint
def test_blt109_signal_rule_seeded():
    from bolt_tpu.analysis import astlint
    bad = "import os\n\ndef f(pid):\n    os.kill(pid, 9)\n"
    assert any(f.code == "BLT109"
               for f in astlint.lint_source(bad, "bolt_tpu/somewhere.py"))
    badsig = "import signal\n\nsignal.signal(2, None)\n"
    found = astlint.lint_source(badsig, "bolt_tpu/elsewhere.py")
    assert any(f.code == "BLT109" for f in found)
    # alias-aware, like every chain rule
    bad3 = "import os as o\n\ndef f(p):\n    o.kill(p, 9)\n"
    assert any(f.code == "BLT109"
               for f in astlint.lint_source(bad3, "bolt_tpu/x.py"))
    # the blessed homes pass
    assert not astlint.lint_source(bad, "bolt_tpu/_chaos.py")
    assert not astlint.lint_source(bad, "tests/test_whatever.py")
    assert not astlint.lint_source(bad, "scripts/chaos_run.py")
    # and the whole package still lints clean (BLT109 included)
    assert astlint.lint_package() == []


# ---------------------------------------------------------------------
# obs + arbiter hygiene under failure
# ---------------------------------------------------------------------

def test_failed_and_resumed_runs_leak_no_spans(mesh, tmp_path):
    from bolt_tpu import obs
    data = _intdata()
    ck = str(tmp_path / "ck")
    obs.clear()
    obs.enable()
    try:
        chaos.inject("stream.upload", nth=5)
        with pytest.raises(chaos.ChaosError):
            with stream.uploaders(1):
                _source(data, mesh, ck=ck).sum().cache()
        chaos.clear()
        assert obs.active_count() == 0           # failed run: no leaks
        _source(data, mesh, ck=ck).sum().cache()
        assert obs.active_count() == 0           # resumed run: no leaks
        names = {s.name for s in obs.spans()}
        assert "stream.checkpoint" in names
    finally:
        obs.disable()
        obs.clear()
