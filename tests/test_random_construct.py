"""Random sharded constructors (extension beyond the reference factory:
``rand``/``randn`` generate each shard on its own device — the same
no-host-materialisation rule as ``ones``/``zeros``)."""

import numpy as np
import pytest

import jax

import bolt_tpu as bolt


def test_randn_sharded_and_deterministic(mesh):
    b = bolt.randn((16, 4, 3), mesh, axis=(0,), dtype=np.float32, seed=7)
    assert b.mode == "tpu" and b.split == 1
    assert b.shape == (16, 4, 3) and b.dtype == np.float32
    # sharded over the mesh, not replicated
    assert not b.tojax().sharding.is_fully_replicated
    # same seed reproduces, different seed differs
    again = bolt.randn((16, 4, 3), mesh, axis=(0,), dtype=np.float32, seed=7)
    other = bolt.randn((16, 4, 3), mesh, axis=(0,), dtype=np.float32, seed=8)
    assert np.array_equal(b.toarray(), again.toarray())
    assert not np.array_equal(b.toarray(), other.toarray())


def test_randn_moments(mesh):
    b = bolt.randn((64, 32, 16), mesh, dtype=np.float32, seed=0)
    x = b.toarray()
    assert abs(x.mean()) < 0.02 and abs(x.std() - 1.0) < 0.02


def test_rand_range_and_mode_dispatch(mesh):
    b = bolt.rand((32, 8), mesh, dtype=np.float32)
    x = b.toarray()
    assert x.min() >= 0.0 and x.max() < 1.0
    # local dispatch without a mesh
    lo = bolt.rand((32, 8))
    assert lo.mode == "local" and lo.shape == (32, 8)
    lo2 = bolt.randn((32, 8), seed=3)
    assert lo2.mode == "local"
    assert np.array_equal(np.asarray(lo2),
                          np.asarray(bolt.randn((32, 8), seed=3)))


def test_random_local_rejects_non_float():
    # local must match the TPU contract, not silently truncate to zeros
    with pytest.raises(ValueError):
        bolt.rand((8, 4), dtype=np.int32)
    with pytest.raises(ValueError):
        bolt.randn((8, 4), dtype=np.int64)


def test_random_pipeline_end_to_end(mesh):
    # generated arrays are ordinary bolt arrays: map/stats/swap all work
    b = bolt.randn((8, 6, 4), mesh, axis=(0, 1), dtype=np.float32, seed=1)
    assert b.split == 2
    m = b.map(lambda v: v * 2.0, axis=(0, 1))
    assert np.allclose(m.toarray(), b.toarray() * 2.0)
    assert np.allclose(np.asarray(b.stats().mean()),
                       b.toarray().mean(axis=(0, 1)), atol=1e-6)


def test_random_rejects_non_float(mesh):
    with pytest.raises(ValueError):
        bolt.randn((8, 4), mesh, dtype=np.int32)


def test_random_key_axis_moves_front(mesh):
    # axis=(1,) distributes that axis; it moves to the front like array()
    b = bolt.randn((6, 16, 3), mesh, axis=(1,), dtype=np.float32)
    assert b.shape == (16, 6, 3) and b.split == 1


def test_random_program_cache_reused_across_seeds(mesh):
    # seed is a traced argument: new seeds must NOT grow the jit cache
    from bolt_tpu.tpu.array import _JIT_CACHE
    bolt.randn((8, 4), mesh, dtype=np.float32, seed=0)
    size = len(_JIT_CACHE)
    for seed in (1, 2, 3):
        bolt.randn((8, 4), mesh, dtype=np.float32, seed=seed)
    assert len(_JIT_CACHE) == size


def test_random_sharding_keyed_by_split(mesh2d):
    # (kind, shape, dtype, mesh)-equal calls with different key-axis counts
    # must NOT share a compiled program: shardings differ
    a = bolt.randn((8, 4), mesh2d, axis=(0,), dtype=np.float32)
    b = bolt.randn((8, 4), mesh2d, axis=(0, 1), dtype=np.float32)
    assert a.split == 1 and b.split == 2
    sa = a.tojax().sharding.spec
    sb = b.tojax().sharding.spec
    assert tuple(sa)[:1] != tuple(sb)[:2] or len(tuple(sa)) != len(tuple(sb)) \
        or sa != sb
    # the value axis of `a` must not be mesh-sharded
    assert len([p for p in tuple(sa) if p is not None]) <= 1


def test_random_negative_and_huge_seeds(mesh):
    # any Python int seed works, matching the local backend
    a = bolt.randn((8, 4), mesh, dtype=np.float32, seed=-1)
    b = bolt.randn((8, 4), mesh, dtype=np.float32, seed=2 ** 40 + 5)
    assert np.all(np.isfinite(a.toarray()))
    assert not np.array_equal(a.toarray(), b.toarray())
    lo = bolt.randn((8, 4), seed=-1)
    assert lo.mode == "local"
