"""TPU-backend construction tests (reference area:
``test/test_spark_construct.py``, SURVEY §4)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.tpu.array import BoltArrayTPU
from bolt_tpu.utils import allclose


def _x():
    rs = np.random.RandomState(1)
    return rs.randn(8, 4, 5)


def test_array_dispatch(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    assert isinstance(b, BoltArrayTPU)
    assert b.mode == "tpu"
    assert b.shape == x.shape
    assert b.split == 1
    assert allclose(b.toarray(), x)
    # keyword context
    assert bolt.array(x, context=mesh).mode == "tpu"
    # explicit mode with default mesh
    assert bolt.array(x, mode="tpu").mode == "tpu"


def test_array_from_sequence(mesh):
    # plain Python sequences are valid array-likes (regression: the
    # device-array fast path must not reach .shape before coercion)
    rows = [[1.0, 2.0, 3.0, 4.0]] * 8
    b = bolt.array(rows, mesh)
    assert b.shape == (8, 4)
    assert allclose(b.toarray(), np.asarray(rows))
    t = bolt.array(tuple(map(tuple, rows)), mesh, dtype=np.float32)
    assert t.dtype == np.float32
    assert allclose(t.toarray(), np.asarray(rows, dtype=np.float32))


def test_array_axis(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    assert b.split == 2
    assert allclose(b.toarray(), x)
    # non-leading key axis: moved to the front of the logical shape
    b = bolt.array(x, mesh, axis=(1,))
    assert b.shape == (4, 8, 5)
    assert allclose(b.toarray(), np.transpose(x, (1, 0, 2)))


def test_array_sharded(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    # the key axis (8) divides the mesh (8): one shard per device
    assert len(b._data.sharding.device_set) == 8


def test_ones_zeros(mesh):
    b = bolt.ones((8, 3, 2), mesh)
    assert allclose(b.toarray(), np.ones((8, 3, 2)))
    assert b.dtype == np.float64
    b = bolt.zeros((8, 3), mesh, dtype=np.float32)
    assert allclose(b.toarray(), np.zeros((8, 3)))
    assert b.dtype == np.float32
    # built directly sharded on device
    assert len(b._data.sharding.device_set) == 8


def test_full_scalar_and_array_fill(mesh):
    # scalar fill: engine-keyed constant program, numpy full semantics
    b = bolt.full((8, 3), 2.5, mesh)
    assert allclose(b.toarray(), np.full((8, 3), 2.5))
    # array-like fill broadcasts like np.full — unhashable, so it rides
    # as a program ARGUMENT, not an engine cache key (regression: the
    # engine-routed path must not TypeError on hashing an ndarray)
    fill = np.array([1.0, 2.0, 3.0])
    a = bolt.full((8, 3), fill, mesh)
    assert allclose(a.toarray(), np.full((8, 3), fill))
    # and a repeat of each geometry HITS the executable cache
    from bolt_tpu import engine
    c0 = engine.counters()["misses"]
    bolt.full((8, 3), 2.5, mesh)
    bolt.full((8, 3), np.array([9.0, 8.0, 7.0]), mesh)
    assert engine.counters()["misses"] == c0
    # NaN fills must cache too (NaN != NaN would never match a raw
    # value key): first call may miss, repeats must hit
    n = bolt.full((8, 3), np.nan, mesh)
    assert np.isnan(np.asarray(n.toarray())).all()
    c1 = engine.counters()["misses"]
    bolt.full((8, 3), np.nan, mesh)
    bolt.full((8, 3), np.nan, mesh)
    assert engine.counters()["misses"] == c1


def test_ones_axis(mesh):
    b = bolt.ones((3, 8), mesh, axis=(1,))
    assert b.shape == (8, 3)
    assert b.split == 1


def test_indivisible_key_axis(mesh):
    # 7 does not divide 8: replicated but still correct
    x = np.arange(7.0 * 3).reshape(7, 3)
    b = bolt.array(x, mesh)
    assert allclose(b.toarray(), x)
    assert allclose(b.map(lambda v: v * 2).toarray(), x * 2)


def test_concatenate(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = bolt.concatenate((b, b), axis=1)
    assert isinstance(out, BoltArrayTPU)
    assert allclose(out.toarray(), np.concatenate([x, x], axis=1))


def test_context_validation():
    with pytest.raises(ValueError):
        bolt.array(np.ones(3), context="not a mesh", mode="tpu")


def test_construct_from_device_array(mesh):
    # jax.Array / BoltArrayTPU inputs stay on device (no host round-trip)
    import jax.numpy as jnp
    x = _x()
    d = jnp.asarray(x)
    b = bolt.array(d, mesh)
    assert isinstance(b, BoltArrayTPU)
    assert allclose(b.toarray(), x)
    # re-keying an existing distributed array
    b2 = bolt.array(b, mesh, axis=(1,))
    assert b2.shape == (4, 8, 5)
    assert allclose(b2.toarray(), np.transpose(x, (1, 0, 2)))


def test_lazy_submodules():
    import bolt
    assert hasattr(bolt.profile, "timeit")
    assert hasattr(bolt.parallel, "exchange_halo")
    assert hasattr(bolt.checkpoint, "save")
    with pytest.raises(AttributeError):
        bolt.no_such_submodule


def test_conversions(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    loc = b.tolocal()
    assert loc.mode == "local"
    assert allclose(loc.toarray(), x)
    back = loc.totpu(mesh)
    assert back.mode == "tpu"
    assert allclose(back.toarray(), x)
    assert b.totpu() is b
