"""Batched Jacobi eigensolver tests (CPU mesh).

``jacobi_eigh`` is the TPU-first engine behind the Gram-route
``svdvals``/``tallskinny_pca`` (BASELINE config 5); the oracle is
``numpy.linalg.eigvalsh`` in float64."""

import numpy as np
import pytest

import jax.numpy as jnp

from bolt_tpu.ops import jacobi_eigh


def _gram(rs, b, n):
    x = rs.randn(b, 4 * n, n)
    return np.einsum("bni,bnj->bij", x, x)


@pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 16, 17, 33, 64])
def test_matches_numpy_across_sizes(n):
    rs = np.random.RandomState(n)
    g = _gram(rs, 6, n)
    ref = np.linalg.eigvalsh(g)
    got = np.asarray(jacobi_eigh(jnp.asarray(g)))
    scale = np.abs(ref).max(axis=-1, keepdims=True)
    assert np.max(np.abs(got - ref) / scale) < 5e-11


def test_float32_precision_and_dtype():
    rs = np.random.RandomState(0)
    g = _gram(rs, 8, 16).astype(np.float32)
    got = jacobi_eigh(jnp.asarray(g))
    assert got.dtype == jnp.float32
    ref = np.linalg.eigvalsh(g.astype(np.float64))
    assert np.max(np.abs(np.asarray(got) - ref)
                  / np.abs(ref).max(axis=-1, keepdims=True)) < 1e-5


def test_indefinite_and_degenerate_spectra():
    rs = np.random.RandomState(1)
    # indefinite: symmetric but not PSD
    a = rs.randn(4, 12, 12)
    a = (a + np.swapaxes(a, -1, -2)) / 2
    ref = np.linalg.eigvalsh(a)
    got = np.asarray(jacobi_eigh(jnp.asarray(a)))
    assert np.allclose(got, ref, atol=1e-10)
    # repeated eigenvalues: identity and zero matrices are fixed points
    assert np.allclose(np.asarray(jacobi_eigh(jnp.eye(7))), np.ones(7))
    assert np.allclose(np.asarray(jacobi_eigh(jnp.zeros((3, 9, 9)))), 0.0)
    # diagonal input returns the sorted diagonal
    d = np.diag([3.0, -1.0, 2.0, 0.0, 5.0])
    assert np.allclose(np.asarray(jacobi_eigh(jnp.asarray(d))),
                       np.sort(np.diag(d)))


def test_eigenvectors():
    rs = np.random.RandomState(2)
    for n in (2, 3, 8, 17):
        a = rs.randn(5, n, n)
        a = (a + np.swapaxes(a, -1, -2)) / 2
        w, v = jacobi_eigh(jnp.asarray(a), vectors=True)
        w, v = np.asarray(w), np.asarray(v)
        # columns are orthonormal and diagonalize a: a @ v = v * w
        eye = np.broadcast_to(np.eye(n), (5, n, n))
        assert np.allclose(np.swapaxes(v, -1, -2) @ v, eye, atol=1e-10)
        assert np.allclose(a @ v, v * w[..., None, :], atol=1e-9)
        assert np.allclose(w, np.linalg.eigvalsh(a), atol=1e-10)


@pytest.mark.parametrize("n", [5, 6])  # odd n: the padded-dummy path
def test_extreme_scales_no_overflow(n):
    # the atan2 rotation must survive scales where tau = (aqq-app)/(2*apq)
    # would overflow f32 (the classic formula NaNs near convergence), and
    # the odd-n dummy sentinel must not square the entries (f32 1e30-scale
    # inputs would overflow to an inf sentinel and NaN the whole batch)
    rs = np.random.RandomState(3)
    base = _gram(rs, 2, n)
    for scale in (1e-30, 1e30):
        got = np.asarray(jacobi_eigh(jnp.asarray(base * scale)))
        assert np.all(np.isfinite(got))
        ref = np.linalg.eigvalsh(base * scale)
        assert np.allclose(got, ref, rtol=1e-9)
    got32 = np.asarray(jacobi_eigh(jnp.asarray(
        (base[0] * 1e30).astype(np.float32))))
    assert np.all(np.isfinite(got32))
    ref = np.linalg.eigvalsh(base[0] * 1e30)
    assert np.allclose(got32, ref, rtol=1e-4)


def test_integer_input_promotes():
    a = jnp.asarray([[2, 1], [1, 2]], jnp.int32)
    got = np.asarray(jacobi_eigh(a))
    assert np.allclose(got, [1.0, 3.0])


def test_complex_falls_back():
    rs = np.random.RandomState(4)
    x = rs.randn(6, 4) + 1j * rs.randn(6, 4)
    h = x.conj().T @ x
    got = np.asarray(jacobi_eigh(jnp.asarray(h)))
    assert np.allclose(got, np.linalg.eigvalsh(h), rtol=1e-9)
    w, v = jacobi_eigh(jnp.asarray(h), vectors=True)
    assert np.allclose(np.asarray(v) @ np.diag(np.asarray(w))
                       @ np.asarray(v).conj().T, h, atol=1e-9)


def test_bad_shape_rejected():
    with pytest.raises(ValueError):
        jacobi_eigh(jnp.zeros((3, 4)))
    with pytest.raises(ValueError):
        jacobi_eigh(jnp.zeros((5,)))


def test_jit_and_vmap_compose():
    import jax
    rs = np.random.RandomState(5)
    g = jnp.asarray(_gram(rs, 4, 8))
    ref = np.linalg.eigvalsh(np.asarray(g))
    got = np.asarray(jax.jit(jacobi_eigh)(g))
    assert np.allclose(got, ref, atol=1e-10)
    got_v = np.asarray(jax.vmap(jacobi_eigh)(g))
    assert np.allclose(got_v, ref, atol=1e-10)


def test_tsqr_matches_qr():
    import jax.numpy as jnp
    from bolt_tpu.ops import tsqr
    rs = np.random.RandomState(6)
    for shape in [(64, 8), (3, 100, 12), (40, 1)]:
        x = rs.randn(*shape)
        q, r = tsqr(jnp.asarray(x))
        q, r = np.asarray(q), np.asarray(r)
        d = shape[-1]
        eye = np.broadcast_to(np.eye(d), r.shape)
        assert np.allclose(np.swapaxes(q, -1, -2) @ q, eye, atol=1e-12)
        assert np.allclose(q @ r, x, atol=1e-12)
        # upper triangular with positive diagonal (unlike np.linalg.qr,
        # whose sign convention is unspecified)
        assert np.allclose(np.tril(r, -1), 0.0, atol=1e-12)
        assert np.all(np.diagonal(r, axis1=-2, axis2=-1) > 0)


def test_tsqr_f32_and_int_and_errors():
    import jax.numpy as jnp
    from bolt_tpu.ops import tsqr
    rs = np.random.RandomState(7)
    x = rs.randn(256, 6).astype(np.float32)
    q, r = tsqr(jnp.asarray(x))
    assert np.asarray(q).dtype == np.float32
    assert np.allclose(np.asarray(q) @ np.asarray(r), x, atol=1e-4)
    qi, ri = tsqr(jnp.asarray((x * 10).astype(np.int32)))
    assert np.issubdtype(np.asarray(qi).dtype, np.floating)
    with pytest.raises(ValueError):
        tsqr(jnp.zeros((4, 8)))


def test_tallskinny_svd_matches_numpy():
    from bolt_tpu.ops import tallskinny_svd
    rs = np.random.RandomState(8)
    for shape in [(128, 10), (4, 96, 6)]:
        x = rs.randn(*shape)
        u, s, vh = (np.asarray(a) for a in tallskinny_svd(jnp.asarray(x)))
        d = shape[-1]
        # reconstruction, orthonormality, descending spectrum
        assert np.allclose(u * s[..., None, :] @ vh, x, atol=1e-9)
        eye = np.broadcast_to(np.eye(d), s.shape[:-1] + (d, d))
        assert np.allclose(np.swapaxes(u, -1, -2) @ u, eye, atol=1e-8)
        assert np.allclose(s, np.linalg.svd(x, compute_uv=False), rtol=1e-9)
    # truncation
    x = rs.randn(64, 8)
    u, s, vh = tallskinny_svd(jnp.asarray(x), k=3)
    assert u.shape == (64, 3) and s.shape == (3,) and vh.shape == (3, 8)
    assert np.allclose(np.asarray(s),
                       np.linalg.svd(x, compute_uv=False)[:3], rtol=1e-9)


def test_tallskinny_svd_rank_deficient_and_errors():
    from bolt_tpu.ops import tallskinny_svd
    rs = np.random.RandomState(9)
    # rank-1 input: zero singular values give zero u columns, not NaN
    col = rs.randn(40, 1)
    x = col @ rs.randn(1, 5)
    u, s, vh = (np.asarray(a) for a in tallskinny_svd(jnp.asarray(x)))
    assert np.all(np.isfinite(u)) and np.all(np.isfinite(s))
    assert np.allclose(s[1:], 0.0, atol=1e-6 * s[0])
    assert np.allclose(u * s[None, :] @ vh, x, atol=1e-8 * abs(x).max())
    with pytest.raises(ValueError):
        tallskinny_svd(jnp.zeros((4, 8)))


def test_component_count_validated_across_family():
    from bolt_tpu.ops import tallskinny_pca, tallskinny_svd
    x = jnp.asarray(np.random.RandomState(10).randn(20, 5))
    for bad in (-1, 0, 99):
        with pytest.raises(ValueError):
            tallskinny_svd(x, k=bad)
        with pytest.raises(ValueError):
            tallskinny_pca(x, k=bad)


def test_jacobi_routing_branches():
    # the Jacobi-vs-QDWH route: big batches and vmapped contexts take
    # Jacobi; single small matrices and d > 64 take QDWH
    import jax
    from bolt_tpu.ops.linalg import _use_jacobi
    rs = np.random.RandomState(12)
    small = jnp.asarray(np.eye(8))
    assert not _use_jacobi(small)                      # batch*d = 8 < 2048
    big_batch = jnp.zeros((512, 8, 8))
    assert _use_jacobi(big_batch)                      # 512*8 >= 2048
    assert not _use_jacobi(jnp.zeros((4, 128, 128)))   # d > 64
    # correctness through each route (svdvals under vmap = config 5b path)
    x = rs.randn(32, 1024, 16).astype(np.float32)
    from bolt_tpu.ops import svdvals
    got = np.asarray(jax.jit(jax.vmap(svdvals))(jnp.asarray(x)))
    expect = np.stack([np.linalg.svd(m.astype(np.float64), compute_uv=False)
                       for m in x])
    assert np.allclose(got, expect, rtol=1e-3, atol=1e-2)
    # big-batch eager route
    got2 = np.asarray(svdvals(jnp.asarray(x)))
    assert np.allclose(got2, expect, rtol=1e-3, atol=1e-2)


def test_jacobi_routing_true_batch_under_vmap():
    # a small vmapped batch must NOT force the Jacobi route: the true
    # batch (outer vmap dims included) feeds the work threshold
    import jax
    from bolt_tpu.ops.linalg import _use_jacobi, _true_batch
    seen = {}
    def probe(tag):
        def f(g):
            seen[tag] = (_true_batch(g), _use_jacobi(g))
            return g
        return f
    jax.vmap(probe("small"))(jnp.zeros((4, 8, 8)))
    assert seen["small"] == (4, False)                  # 4*8 < 2048
    jax.vmap(probe("big"))(jnp.zeros((512, 8, 8)))
    assert seen["big"] == (512, True)                   # 512*8 >= 2048
    jax.vmap(jax.vmap(probe("nested")))(jnp.zeros((32, 16, 8, 8)))
    assert seen["nested"] == (512, True)                # nested vmaps compose


def test_jacobi_is_differentiable():
    # plain-lax iteration means AD needs no custom rules (XLA's eigh ships
    # hand-written JVPs): eigenvalue gradients match the analytic forms
    import jax
    rs = np.random.RandomState(13)
    a = rs.randn(6, 6)
    a = (a + a.T) / 2
    # d(sum of eigenvalues)/dA = I (trace identity)
    g = jax.grad(lambda m: jacobi_eigh(m).sum())(jnp.asarray(a))
    assert np.allclose(np.asarray(g), np.eye(6), atol=1e-8)
    # d(largest eigenvalue)/dA = v v^T of the top eigenvector
    g2 = jax.grad(lambda m: jacobi_eigh(m)[-1])(jnp.asarray(a))
    _, v = np.linalg.eigh(a)
    assert np.allclose(np.asarray(g2), np.outer(v[:, -1], v[:, -1]),
                       atol=1e-6)
    # and through the Gram-route svdvals pipeline vs finite differences —
    # BATCHED so the eigensolve really routes to jacobi_eigh (an unbatched
    # (6, 6) Gram would take XLA's eigh and test its JVP instead)
    from bolt_tpu.ops import svdvals
    from bolt_tpu.ops.linalg import _use_jacobi
    assert _use_jacobi(jnp.zeros((400, 6, 6)))
    x = rs.randn(400, 64, 6)
    g3 = np.asarray(jax.grad(
        lambda m: svdvals(m).sum())(jnp.asarray(x)))
    eps = 1e-6
    for i in range(3):
        xp = x.copy(); xp[7, 0, i] += eps
        xm = x.copy(); xm[7, 0, i] -= eps
        num = (np.linalg.svd(xp[7], compute_uv=False).sum()
               - np.linalg.svd(xm[7], compute_uv=False).sum()) / (2 * eps)
        assert abs(g3[7, 0, i] - num) < 1e-5


def test_lstsq_matches_numpy():
    from bolt_tpu.ops import lstsq
    rs = np.random.RandomState(14)
    a = rs.randn(200, 7)
    # matrix rhs
    b = rs.randn(200, 3)
    x = np.asarray(lstsq(jnp.asarray(a), jnp.asarray(b)))
    ref = np.linalg.lstsq(a, b, rcond=None)[0]
    assert np.allclose(x, ref, atol=1e-10)
    # vector rhs keeps the vector shape
    bv = rs.randn(200)
    xv = np.asarray(lstsq(jnp.asarray(a), jnp.asarray(bv)))
    assert xv.shape == (7,)
    assert np.allclose(xv, np.linalg.lstsq(a, bv, rcond=None)[0], atol=1e-10)
    # batched
    ab = rs.randn(4, 64, 5)
    bb = rs.randn(4, 64, 2)
    xb = np.asarray(lstsq(jnp.asarray(ab), jnp.asarray(bb)))
    refb = np.stack([np.linalg.lstsq(ab[i], bb[i], rcond=None)[0]
                     for i in range(4)])
    assert np.allclose(xb, refb, atol=1e-9)
    # conditioned columns: still accurate well inside the tsqr envelope
    ac = rs.randn(500, 6) * np.logspace(0, 3, 6)
    bc = rs.randn(500)
    xc = np.asarray(lstsq(jnp.asarray(ac), jnp.asarray(bc)))
    assert np.allclose(xc, np.linalg.lstsq(ac, bc, rcond=None)[0],
                       rtol=1e-7)
    with pytest.raises(ValueError):
        lstsq(jnp.zeros((4, 8)), jnp.zeros(4))     # wide
    with pytest.raises(ValueError):
        lstsq(jnp.zeros((8, 4)), jnp.zeros(7))     # row mismatch


def test_lstsq_dtype_promotion_and_complex_rejection():
    from bolt_tpu.ops import lstsq
    rs = np.random.RandomState(15)
    a32 = rs.randn(64, 4).astype(np.float32)
    b64 = rs.randn(64)
    x = lstsq(jnp.asarray(a32), jnp.asarray(b64))
    assert np.asarray(x).dtype == np.float64   # promoted, not narrowed
    with pytest.raises(ValueError):
        lstsq(jnp.asarray(a32), jnp.asarray(b64 + 1j * b64))
