"""Cross-backend parity locks (VERDICT r1 weak-2/3): the SAME expression
must give the SAME result on both backends — advanced indexing applies
orthogonally on both, and ``reduce`` uses one fixed pairwise tree so f32
accumulation is bit-exact across backends.

Reference area: ``test/generic.py`` cross-backend suites plus
``bolt/spark/array.py :: _getadvanced`` (symbol cites, SURVEY §0)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.utils import allclose


def _x(seed=11, shape=(8, 4, 5)):
    return np.random.RandomState(seed).randn(*shape)


def _both(x, mesh, axis=(0,)):
    return (bolt.array(x, axis=axis),
            bolt.array(x, mesh, axis=axis))


# ----------------------------------------------------------------------
# advanced indexing: multiple advanced indices apply orthogonally
# (np.ix_ semantics) on BOTH backends
# ----------------------------------------------------------------------

INDEXES = [
    (np.s_[[0, 1], :, [0, 2]], "two lists"),
    (np.s_[[0, 1], :, [0, 2, 4]], "two lists, different lengths"),
    (np.s_[[2, 0], [1, 3], [4, 0]], "three lists"),
    (np.s_[..., [1, 0], [0, 2]], "ellipsis + two lists"),
    (np.s_[np.array([1, 5]), 1:3, np.array([0, 3])], "ndarrays + slice"),
    (np.s_[[-1, 0], :, [-2, -1]], "negative entries"),
]


@pytest.mark.parametrize("index,label", INDEXES,
                         ids=[label for _, label in INDEXES])
def test_multi_advanced_orthogonal_both_backends(mesh, index, label):
    x = _x()
    lo, tp = _both(x, mesh)
    a = lo[index].toarray()
    b = tp[index].toarray()
    assert a.shape == b.shape, (label, a.shape, b.shape)
    assert allclose(a, b)


def test_multi_advanced_matches_ix(mesh):
    # both backends implement the documented np.ix_ semantics
    x = _x()
    lo, tp = _both(x, mesh)
    expected = x[np.ix_([0, 1], range(x.shape[1]), [0, 2])]
    assert allclose(lo[[0, 1], :, [0, 2]].toarray(), expected)
    assert allclose(tp[[0, 1], :, [0, 2]].toarray(), expected)


def test_bool_plus_list_orthogonal(mesh):
    x = _x()
    lo, tp = _both(x, mesh)
    kmask = x[:, 0, 0] > 0
    a = lo[kmask, :, [0, 3]].toarray()
    b = tp[kmask, :, [0, 3]].toarray()
    expected = x[np.ix_(np.nonzero(kmask)[0], range(x.shape[1]), [0, 3])]
    assert allclose(a, expected)
    assert allclose(b, expected)


def test_int_with_two_lists(mesh):
    x = _x()
    lo, tp = _both(x, mesh)
    a = lo[2, [0, 1], [0, 2, 4]].toarray()
    b = tp[2, [0, 1], [0, 2, 4]].toarray()
    expected = x[2][np.ix_([0, 1], [0, 2, 4])]
    assert allclose(a, expected)
    assert allclose(b, expected)


def test_single_advanced_still_numpy(mesh):
    # a single advanced index is identical under zipped and orthogonal
    # conventions; the local backend must keep ndarray behavior exactly
    x = _x()
    lo = bolt.array(x)
    assert allclose(lo[[0, 3, 5]].toarray(), x[[0, 3, 5]])
    assert allclose(lo[:, [3, 1]].toarray(), x[:, [3, 1]])
    assert allclose(lo[2:7, [0, 3], ::2].toarray(), x[2:7][:, [0, 3]][:, :, ::2])
    mask = x[:, 0, 0] > 0
    assert allclose(lo[mask].toarray(), x[mask])


def test_local_basic_indexing_untouched():
    # the override must not disturb basic (view) indexing or types
    x = _x()
    lo = bolt.array(x)
    assert isinstance(lo[1:3], bolt.BoltArrayLocal)
    assert allclose(lo[1:3].toarray(), x[1:3])
    assert allclose(np.asarray(lo[3, 1]), x[3, 1])
    assert float(lo[0, 0, 0]) == float(x[0, 0, 0])


# ----------------------------------------------------------------------
# reduce: one fixed pairwise tree on both backends
# ----------------------------------------------------------------------

def test_reduce_f32_bitexact_cross_backend(mesh):
    x = np.random.RandomState(7).randn(13, 5).astype(np.float32)
    lo, tp = _both(x, mesh)
    a = lo.reduce(np.add).toarray()
    b = tp.reduce(np.add).toarray()
    assert a.dtype == b.dtype == np.float32
    # BIT-exact, not allclose: identical combine tree + IEEE f32 adds
    assert np.array_equal(a, b)


def test_reduce_nonassociative_parity(mesh):
    # a non-associative reducer gives the same (tree-order) answer on both
    x = np.random.RandomState(8).randn(11, 3)
    lo, tp = _both(x, mesh)
    f = lambda a, b: a - 0.5 * b
    assert np.array_equal(lo.reduce(f).toarray(), tp.reduce(f).toarray())


@pytest.mark.parametrize("n", [1, 2, 3, 7, 8, 16])
def test_reduce_tree_every_count(mesh, n):
    x = np.random.RandomState(n).randn(n, 4).astype(np.float32)
    lo, tp = _both(x, mesh)
    assert np.array_equal(lo.reduce(np.add).toarray(),
                          tp.reduce(np.add).toarray())


def test_reduce_empty_raises():
    lo = bolt.array(np.zeros((0, 3)))
    with pytest.raises(TypeError):
        lo.reduce(np.add)


def test_zero_d_array_index_is_scalar(mesh):
    # np.argmax results are 0-d arrays; they must squeeze like scalars,
    # never shift later axes through a degenerate take
    x = _x(shape=(4, 5, 6))
    lo, tp = _both(x, mesh)
    i, j = np.array(0), np.array(1)
    expected = x[0, 1, :]
    for b in (lo, tp):
        out = b[i, j]
        assert out.shape == (6,), out.shape
        assert allclose(out.toarray(), expected)
    # mixed with a real advanced index
    expected = x[0][:, [0, 2]]
    for b in (lo, tp):
        assert allclose(b[np.array(0), :, [0, 2]].toarray(), expected)


def test_reduce_empty_raises_both_backends(mesh):
    lo = bolt.array(np.zeros((0, 3)))
    tp = bolt.array(np.zeros((4, 3)), mesh).filter(lambda v: False)
    with pytest.raises(TypeError):
        lo.reduce(np.add)
    with pytest.raises(TypeError):
        tp.reduce(np.add)


def test_scalar_plus_list_separated_by_slice(mesh):
    # numpy would move the advanced result axis to the front here; both
    # backends must keep the documented orthogonal (in-place) semantics
    x = _x()
    lo, tp = _both(x, mesh)
    expected = x[1][:, [0, 4]]           # shape (4, 2), not numpy's (2, 4)
    a = lo[1, :, [0, 4]].toarray()
    b = tp[1, :, [0, 4]].toarray()
    assert a.shape == expected.shape
    assert allclose(a, expected)
    assert allclose(b, expected)


def test_multi_d_advanced_index_rejected(mesh):
    # the per-axis orthogonal contract is 1-d index lists; a 2-d array
    # would silently shift later axes through the take loop
    x = _x()
    lo, tp = _both(x, mesh)
    bad = np.array([[0, 1], [2, 3]])
    with pytest.raises(IndexError):
        tp[bad, :, [0, 2]]
    with pytest.raises(IndexError):
        lo[bad, :, [0, 2]]
    with pytest.raises(IndexError):
        tp[bad]
