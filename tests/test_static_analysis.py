"""bolt_tpu.analysis: abstract pipeline checker + repo invariant linter.

Two halves (ISSUE 2 tentpole):

* the CHECKER — ``analysis.check``/``explain`` abstractly interpret a
  deferred pipeline (``_chain``/``_pending``/``_fpending``) with zero
  XLA compiles, predicting result shape/dtype/sharding per stage and
  emitting ``BLT0xx`` diagnostics; ``analysis.strict()`` makes every
  dispatching terminal run the checker first and refuse on
  error-severity findings;
* the LINTER — ``analysis.astlint`` enforces the repo invariants
  (``BLT1xx``: engine-routed jit, _compat-routed version-sensitive jax,
  resolver-routed precision, gate-routed ``._concrete``); zero findings
  on ``bolt_tpu/`` itself is a tier-1 invariant (also runnable
  standalone: ``pytest -m lint`` / ``scripts/lint_bolt.py --check``).

Every diagnostic code and every lint rule has a seeded violation here.
"""

import importlib.util
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bolt_tpu as bolt
from bolt_tpu import analysis, engine
from bolt_tpu.analysis import PipelineError, astlint
from bolt_tpu.tpu.array import BoltArrayTPU

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _x():
    return np.random.RandomState(0).randn(16, 6, 4)


def _no_new_compiles(c0, c1):
    for k in ("misses", "aot_compiles", "dispatches"):
        assert c1[k] == c0[k], (k, c0[k], c1[k])


# ----------------------------------------------------------------------
# checker: predictions
# ----------------------------------------------------------------------

def test_check_concrete_array(mesh):
    b = bolt.array(_x(), mesh)
    c0 = engine.counters()
    rep = analysis.check(b)
    _no_new_compiles(c0, engine.counters())
    assert rep.ok and rep.shape == (16, 6, 4)
    assert np.dtype(rep.dtype) == np.float64
    assert rep.stages[0].spec is not None


def test_check_predicts_chain_shape_and_dtype(mesh):
    b = bolt.array(_x(), mesh).map(lambda v: v * 2).map(
        lambda v: v.sum(axis=0)).map(lambda v: v.astype(np.float32))
    c0 = engine.counters()
    rep = analysis.check(b)
    _no_new_compiles(c0, engine.counters())
    assert rep.ok
    assert len(rep.stages) == 4            # base + three map stages
    assert rep.shape == (16, 4)
    assert np.dtype(rep.dtype) == np.float32
    got = np.asarray(b.toarray())
    assert got.shape == rep.shape and got.dtype == rep.dtype


def test_check_with_keys_stage(mesh):
    b = bolt.array(_x(), mesh).map(lambda kv: kv[1] + kv[0][0],
                                   with_keys=True)
    rep = analysis.check(b)
    assert rep.ok and rep.shape == (16, 6, 4)
    assert "with_keys" in rep.stages[1].op


def test_check_deferred_filter_is_dynamic_and_does_not_resolve(mesh):
    b = bolt.array(_x(), mesh).map(lambda v: v + 1).filter(
        lambda v: v.mean() > 0)
    c0 = engine.counters()
    rep = analysis.check(b)
    _no_new_compiles(c0, engine.counters())
    assert b.pending                       # the checker did NOT resolve it
    assert rep.ok and rep.dynamic
    assert rep.shape == (None, 6, 4)
    assert rep.max_shape == (16, 6, 4)
    assert rep.has("BLT008")
    # reality check: resolving matches the predicted value dims/dtype
    got = np.asarray(b.toarray())
    assert got.shape[1:] == rep.shape[1:]
    assert got.dtype == np.dtype(rep.dtype)


def test_check_views_and_explain(mesh):
    b = bolt.array(_x(), mesh).map(lambda v: v * 3)
    rep = analysis.check(b.chunk(size=(3,), axis=(0,)))
    assert rep.ok and "chunked view" in rep.target
    rep2 = analysis.check(b.stacked(size=4))
    assert rep2.ok and "stacked view" in rep2.target
    txt = analysis.explain(b)
    assert "stage 0" in txt and "map" in txt and "OK" in txt


def test_check_local_array_trivial():
    b = bolt.array(_x())
    rep = analysis.check(b)
    assert rep.ok and rep.shape == (16, 6, 4)


# ----------------------------------------------------------------------
# checker: seeded diagnostics, one per code
# ----------------------------------------------------------------------

def test_blt001_stage_trace_failure(mesh):
    base = bolt.array(_x(), mesh)._data
    bad = BoltArrayTPU._deferred(
        base, (lambda v: v @ jnp.ones((99, 2)),), 1, mesh,
        jax.ShapeDtypeStruct((16, 2), np.float64))
    rep = analysis.check(bad)
    assert not rep.ok and rep.has("BLT001")
    d = [e for e in rep.errors if e.code == "BLT001"][0]
    assert d.stage == 1 and "abstract tracing" in d.message


def test_blt002_recorded_aval_lie(mesh):
    base = bolt.array(_x(), mesh)._data
    liar = BoltArrayTPU._deferred(
        base, (lambda v: v * 2,), 1, mesh,
        jax.ShapeDtypeStruct((16, 99), np.float32))   # lies twice
    rep = analysis.check(liar)
    assert not rep.ok and rep.has("BLT002")
    assert "(16, 99)" in str(rep)


def test_blt003_dtype_widening(mesh):
    b = bolt.array(_x().astype(np.float32), mesh).map(
        lambda v: v * np.float64(2))
    rep = analysis.check(b)
    assert rep.ok                          # warning, not error
    assert rep.has("BLT003")
    assert np.dtype(rep.dtype) == np.float64
    assert np.asarray(b.toarray()).dtype == np.float64   # it predicted reality


def test_blt004_indivisible_keys(mesh):
    b = bolt.array(np.random.RandomState(1).randn(6, 4), mesh)
    rep = analysis.check(b)
    assert rep.ok and rep.has("BLT004")
    w = [d for d in rep.warnings if d.code == "BLT004"][0]
    assert "mesh devices" in w.message and "(6,)" in w.message


def test_blt005_use_after_donate_names_operation(mesh):
    x = _x()
    with engine.donation(0):
        d = bolt.array(x, mesh).map(lambda v: v + 1)
        d.sum()                            # donates the sole-owned base
        rep = analysis.check(d)
        assert not rep.ok and rep.has("BLT005")
        assert "sum()" in rep.errors[0].message
        with pytest.raises(RuntimeError, match=r"donated to sum\(\)"):
            d.toarray()


def test_blt006_donation_forecast_is_side_effect_free(mesh):
    x = _x()
    with engine.donation(0):
        d = bolt.array(x, mesh).map(lambda v: v + 1)
        rep = analysis.check(d)
        assert rep.ok and rep.has("BLT006")
        # the forecast consumed nothing: the terminal still donates
        n0 = engine.counters()["donations"]
        d.sum()
        assert engine.counters()["donations"] == n0 + 1
    # outside the scope (default 64 MB floor) small chains do not donate
    d2 = bolt.array(x, mesh).map(lambda v: v + 1)
    assert not analysis.check(d2).has("BLT006")


def test_check_survives_malformed_split_state(mesh):
    # hand-built deferred state with split beyond the base rank: the
    # checker must DIAGNOSE (BLT001 from the impossible vmap), not crash
    # deriving shardings — and strict must refuse, not IndexError
    base = bolt.array(np.ones((8, 4)), mesh)._data
    bad = BoltArrayTPU._deferred(
        base, (lambda v: v,), 5, mesh,
        jax.ShapeDtypeStruct((8, 4), np.float64))
    rep = analysis.check(bad)
    assert not rep.ok and rep.has("BLT001")
    with analysis.strict():
        with pytest.raises(PipelineError):
            bad.sum()


def test_blt007_nonscalar_predicate_seeded(mesh):
    b = bolt.array(_x(), mesh)
    bad = BoltArrayTPU(None, 1, mesh)
    bad._fpending = (b._data, (), lambda v: v > 0, 1, (6, 4), 16,
                     np.dtype(np.float64))
    rep = analysis.check(bad)
    assert not rep.ok and rep.has("BLT007")
    assert "scalar" in str(rep)


def test_donated_filter_metadata_raises_named_guard(mesh):
    # a filter array consumed by a donating fused terminal has no
    # recorded aval (its count was never synced): shape/dtype must hit
    # the NAMED donation guard, not AttributeError on the None aval
    with engine.donation(0):
        f = bolt.array(_x(), mesh).filter(lambda v: v.mean() > 0)
        f.sum()
        for read in (lambda: f.shape, lambda: f.dtype, lambda: f.toarray()):
            with pytest.raises(RuntimeError,
                               match=r"donated to filter\(\)\.sum\(\)"):
                read()


def test_donated_array_repr_never_raises(mesh):
    # printing an array is how users diagnose a donation — repr must
    # show the consuming terminal, not raise the guard itself
    with engine.donation(0):
        f = bolt.array(_x(), mesh).filter(lambda v: v.mean() > 0)
        f.sum()
        assert "filter().sum()" in repr(f)
        d = bolt.array(_x(), mesh).map(lambda v: v + 1)
        d.sum()
        r = repr(d)
        assert "sum()" in r and "(16, 6, 4)" in r


def test_donation_scope_is_thread_local(mesh):
    x = _x()
    floors = []
    inner = threading.Event()
    done = threading.Event()

    def other_thread():
        inner.wait(5)
        floors.append(engine.donation_min_bytes())
        # this thread is OUTSIDE the scope: the small chain must NOT
        # donate, and stays readable after its terminal
        d = bolt.array(x, mesh).map(lambda v: v + 1)
        d.sum()
        floors.append(d.toarray().shape)
        done.set()

    t = threading.Thread(target=other_thread)
    t.start()
    with engine.donation(0):
        inner.set()
        assert done.wait(30)
    t.join()
    assert floors[0] == engine.donation_min_bytes()   # default, not 0
    assert floors[0] and floors[0] >= 1
    assert floors[1] == (16, 6, 4)


def test_precision_alias_import_keeps_package_scope_callable(mesh):
    # loading the legacy alias module clobbers the package attribute
    # with the module object; the alias must stay CALLABLE so
    # bolt.precision("default") keeps working afterwards
    from bolt_tpu.precision import resolve as r   # triggers the clobber
    import bolt_tpu
    with bolt_tpu.precision("default"):
        assert r() == "default"
    assert r() == "highest"


def test_diagnostics_counter_fed_by_checker(mesh):
    c0 = engine.counters()["diagnostics"]
    analysis.check(bolt.array(_x(), mesh).filter(lambda v: v.mean() > 0))
    assert engine.counters()["diagnostics"] > c0   # >= the BLT008 info


# ----------------------------------------------------------------------
# strict scope: the engine's pre-dispatch gate
# ----------------------------------------------------------------------

def test_strict_clean_pipeline_dispatches(mesh):
    x = _x()
    with analysis.strict():
        c0 = engine.counters()["strict_checks"]
        out = bolt.array(x, mesh).map(lambda v: v + 1).sum()
        assert engine.counters()["strict_checks"] > c0
    assert np.allclose(np.asarray(out.toarray()), (x + 1).sum(axis=0),
                       equal_nan=True)


def test_strict_refuses_error_findings_before_any_compile(mesh):
    base = bolt.array(_x(), mesh)._data
    bad = BoltArrayTPU._deferred(
        base, (lambda v: v @ jnp.ones((99, 2)),), 1, mesh,
        jax.ShapeDtypeStruct((16, 2), np.float64))
    c0 = engine.counters()
    with analysis.strict():
        with pytest.raises(PipelineError, match="BLT001"):
            bad.sum()
        with pytest.raises(PipelineError, match="refusing to dispatch"):
            bad.reduce(np.add)
    c1 = engine.counters()
    _no_new_compiles(c0, c1)               # refused BEFORE compiling
    assert c1["strict_rejections"] >= c0["strict_rejections"] + 2
    # outside the scope the gate is disarmed: the failure is jax's own,
    # surfacing at the lazy terminal's first read
    with pytest.raises(Exception):
        bad.sum().cache()


def test_strict_gates_views_and_filters(mesh):
    base = bolt.array(_x(), mesh)._data
    bad = BoltArrayTPU._deferred(
        base, (lambda v: v @ jnp.ones((99, 2)),), 1, mesh,
        jax.ShapeDtypeStruct((16, 2), np.float64))
    with analysis.strict():
        with pytest.raises(PipelineError):
            bad.chunk(size=(3,), axis=(0,)).map(lambda blk: blk * 2)
        with pytest.raises(PipelineError):
            bad.stacked(size=4).map(lambda blk: blk - 1)
        with pytest.raises(PipelineError):
            bad.toarray()                  # chain materialisation
    # the scope unwound: a clean pipeline needs no strict bookkeeping
    assert bolt.array(_x(), mesh).map(lambda v: v).sum() is not None


def test_strict_is_thread_local(mesh):
    errs = []

    def other_thread():
        try:
            assert not analysis.in_strict()
            bolt.array(np.ones((8, 3)), mesh).map(lambda v: v + 1).sum()
        except Exception as exc:           # pragma: no cover
            errs.append(exc)

    with analysis.strict():
        assert analysis.in_strict()
        t = threading.Thread(target=other_thread)
        t.start()
        t.join()
    assert not errs
    assert not analysis.in_strict()


# ----------------------------------------------------------------------
# use-after-donate coverage for the view terminals (satellite):
# the guard names the donating operation; check flags it BEFORE the
# next dispatch is attempted
# ----------------------------------------------------------------------

def test_chunk_map_donation_guard_names_operation(mesh):
    x = np.abs(_x())
    with engine.donation(0):
        d = bolt.array(x, mesh).map(lambda v: v * 3)
        got = d.chunk(size=(3,), axis=(0,)).map(lambda blk: blk * 2)
        assert np.allclose(got.unchunk().toarray(), x * 6)
        rep = analysis.check(d)            # flagged before any dispatch
        assert not rep.ok and rep.has("BLT005")
        assert "chunk().map()" in rep.errors[0].message
        with pytest.raises(RuntimeError,
                           match=r"donated to chunk\(\)\.map\(\)"):
            d.toarray()


def test_stack_map_donation_guard_names_operation(mesh):
    x = np.abs(_x())
    with engine.donation(0):
        d = bolt.array(x, mesh).map(lambda v: v - 1)
        got = d.stacked(size=4).map(lambda blk: blk * 2)
        assert np.allclose(got.unstack().toarray(), (x - 1) * 2)
        rep = analysis.check(d)
        assert not rep.ok and rep.has("BLT005")
        assert "stacked().map()" in rep.errors[0].message
        with pytest.raises(RuntimeError,
                           match=r"donated to stacked\(\)\.map\(\)"):
            d.sum()


def test_swap_donation_guard_names_operation(mesh):
    with engine.donation(0):
        b = bolt.array(_x(), mesh)
        b.swap((0,), (0,), donate=True)
        rep = analysis.check(b)
        assert not rep.ok and rep.has("BLT005")
        with pytest.raises(RuntimeError, match=r"swap"):
            b.toarray()


# ----------------------------------------------------------------------
# bench configs: the checker predicts every scripts/bench_all.py
# pipeline with zero XLA compiles (acceptance criterion)
# ----------------------------------------------------------------------

def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_all_configs_check_clean(mesh):
    bench = _load_script("bench_all")
    for name, arr in bench.pipelines(mesh=mesh):
        c0 = engine.counters()
        rep = analysis.check(arr)
        _no_new_compiles(c0, engine.counters())
        assert rep.ok, (name, rep.diagnostics)
        target = arr.unchunk() if hasattr(arr, "unchunk") else arr
        got_shape = tuple(target.shape)
        got_dtype = np.dtype(target.dtype)
        if rep.dynamic:
            assert rep.shape[0] is None
            assert rep.shape[1:] == got_shape[1:], name
        else:
            assert rep.shape == got_shape, name
        assert np.dtype(rep.dtype) == got_dtype, name


# ----------------------------------------------------------------------
# the linter: zero findings on the package itself, and a seeded
# violation per rule
# ----------------------------------------------------------------------

@pytest.mark.lint
def test_lint_package_reports_zero_findings():
    findings = astlint.lint_package()
    assert findings == [], "\n".join(f.render() for f in findings)


@pytest.mark.lint
def test_lint_blt101_bare_jit():
    src = "import jax\nfn = jax.jit(lambda x: x + 1)\n"
    f = astlint.lint_source(src, "bolt_tpu/somewhere.py")
    assert [x.code for x in f] == ["BLT101"]
    # the engine-builder pattern is the sanctioned route
    ok = ("import jax\n"
          "def op(key):\n"
          "    def build():\n"
          "        return jax.jit(lambda x: x * 2)\n"
          "    return _cached_jit(key, build)\n")
    assert astlint.lint_source(ok, "bolt_tpu/somewhere.py") == []
    # inline lambda builders too
    ok2 = ("import jax\n"
           "fn = _cached_jit(('k',), lambda: jax.jit(lambda x: x))\n")
    assert astlint.lint_source(ok2, "bolt_tpu/somewhere.py") == []
    # engine.py itself is exempt; pragmas document exceptions
    assert astlint.lint_source(src, "bolt_tpu/engine.py") == []
    pragma = ("import jax\n"
              "@jax.jit  # lint: allow(BLT101 documented exception)\n"
              "def f(x):\n    return x\n")
    assert astlint.lint_source(pragma, "bolt_tpu/somewhere.py") == []
    # a bare decorator without the pragma is a finding
    dec = "import jax\n@jax.jit\ndef f(x):\n    return x\n"
    assert [x.code for x in astlint.lint_source(
        dec, "bolt_tpu/somewhere.py")] == ["BLT101"]
    # builder names resolve within the sink's ENCLOSING scope only: a
    # same-named local builder elsewhere must not whitelist a
    # direct-called jit
    cross = ("import jax\n"
             "def a(key):\n"
             "    def build():\n"
             "        return jax.jit(lambda x: x)\n"
             "    return _cached_jit(key, build)\n"
             "def b():\n"
             "    def build():\n"
             "        return jax.jit(lambda x: x)\n"
             "    return build()\n")
    found = astlint.lint_source(cross, "bolt_tpu/somewhere.py")
    assert [x.code for x in found] == ["BLT101"] and found[0].line == 8


@pytest.mark.lint
def test_lint_blt102_version_sensitive_jax():
    src = "from jax.experimental.shard_map import shard_map\n"
    assert [x.code for x in astlint.lint_source(
        src, "bolt_tpu/ops/foo.py")] == ["BLT102"]
    src2 = "import jax\nn = jax.lax.axis_size('k')\n"
    assert [x.code for x in astlint.lint_source(
        src2, "bolt_tpu/ops/foo.py")] == ["BLT102"]
    src3 = "import jax\nt = jax.sharding.AxisType.Auto\n"
    assert [x.code for x in astlint.lint_source(
        src3, "bolt_tpu/ops/foo.py")] == ["BLT102"]
    # _compat.py IS the shim: exempt
    assert astlint.lint_source(src, "bolt_tpu/_compat.py") == []
    # the blessed route is clean
    ok = "from bolt_tpu._compat import shard_map, axis_size\n"
    assert astlint.lint_source(ok, "bolt_tpu/ops/foo.py") == []


@pytest.mark.lint
def test_lint_blt103_precision_literals():
    src = ("import jax.numpy as jnp\n"
           "y = jnp.matmul(a, b, precision='highest')\n")
    assert [x.code for x in astlint.lint_source(
        src, "bolt_tpu/ops/foo.py")] == ["BLT103"]
    enum = ("from jax import lax\n"
            "y = lax.dot(a, b, precision=lax.Precision.HIGHEST)\n")
    assert [x.code for x in astlint.lint_source(
        enum, "bolt_tpu/ops/foo.py")] == ["BLT103"]
    # alias-aware: a renamed Precision import must not slip through
    aliased = ("from jax.lax import Precision as P\n"
               "y = jnp.matmul(a, b, precision=P.HIGHEST)\n")
    assert [x.code for x in astlint.lint_source(
        aliased, "bolt_tpu/ops/foo.py")] == ["BLT103"]
    # resolver-routed calls and pinned DEFAULTS are the sanctioned forms
    ok = ("import jax.numpy as jnp\n"
          "from bolt_tpu._precision import resolve\n"
          "def f(a, b, precision='highest'):\n"
          "    return jnp.matmul(a, b, precision=resolve(precision))\n")
    assert astlint.lint_source(ok, "bolt_tpu/ops/foo.py") == []


@pytest.mark.lint
def test_lint_blt104_concrete_bypass():
    src = "def f(b):\n    return b._concrete.shape\n"
    assert [x.code for x in astlint.lint_source(
        src, "bolt_tpu/ops/foo.py")] == ["BLT104"]
    # the gate's own module is exempt
    assert astlint.lint_source(src, "bolt_tpu/tpu/array.py") == []
    ok = "def f(b):\n    return b._data.shape\n"
    assert astlint.lint_source(ok, "bolt_tpu/ops/foo.py") == []


@pytest.mark.lint
def test_lint_blt107_stray_sync_points():
    # method form: x.block_until_ready()
    src = "def f(x):\n    return x.block_until_ready()\n"
    assert [x.code for x in astlint.lint_source(
        src, "bolt_tpu/ops/foo.py")] == ["BLT107"]
    # module-function form: jax.block_until_ready(tree)
    src2 = "import jax\n\ndef f(t):\n    return jax.block_until_ready(t)\n"
    assert [x.code for x in astlint.lint_source(
        src2, "bolt_tpu/tpu/chunk.py")] == ["BLT107"]
    # from-import form
    src3 = ("from jax import block_until_ready\n\n"
            "def f(t):\n    return block_until_ready(t)\n")
    assert any(x.code == "BLT107" for x in astlint.lint_source(
        src3, "bolt_tpu/tpu/stack.py"))
    # the sanctioned sync owners are exempt
    for home in ("bolt_tpu/stream.py", "bolt_tpu/engine.py",
                 "bolt_tpu/profile.py"):
        assert astlint.lint_source(src, home) == []
        assert astlint.lint_source(src2, home) == []
    # path anchoring: upstream.py does not inherit stream.py's pass
    assert any(x.code == "BLT107" for x in astlint.lint_source(
        src, "bolt_tpu/upstream.py"))
    # and the whole package lints clean with the rule armed
    assert astlint.lint_package() == []


@pytest.mark.lint
def test_lint_blt108_thread_construction_outside_blessed_homes():
    # dotted form
    src = ("import threading\n\n"
           "def f():\n    return threading.Thread(target=print)\n")
    assert [x.code for x in astlint.lint_source(
        src, "bolt_tpu/ops/foo.py")] == ["BLT108"]
    # from-import alias form
    src2 = ("from threading import Thread\n\n"
            "def f():\n    return Thread(target=print)\n")
    assert [x.code for x in astlint.lint_source(
        src2, "bolt_tpu/tpu/chunk.py")] == ["BLT108"]
    # pool executors count as thread construction too
    src3 = ("from concurrent.futures import ThreadPoolExecutor\n\n"
            "def f():\n    return ThreadPoolExecutor(4)\n")
    assert [x.code for x in astlint.lint_source(
        src3, "bolt_tpu/checkpoint.py")] == ["BLT108"]
    # renamed plain import must not dodge the rule
    src4 = ("import threading as t\n\n"
            "def f():\n    return t.Thread(target=print)\n")
    assert [x.code for x in astlint.lint_source(
        src4, "bolt_tpu/obs/trace.py")] == ["BLT108"]
    # the two blessed concurrency homes pass
    for home in ("bolt_tpu/stream.py", "bolt_tpu/serve.py"):
        for s in (src, src2, src3):
            assert astlint.lint_source(s, home) == []
    # path anchoring: preserve.py does not inherit serve.py's pass
    assert any(x.code == "BLT108" for x in astlint.lint_source(
        src, "bolt_tpu/preserve.py"))
    # locks/events/conditions are NOT construction — no finding
    ok = ("import threading\n\n"
          "L = threading.Lock()\nE = threading.Event()\n"
          "C = threading.Condition()\nT = threading.local()\n")
    assert astlint.lint_source(ok, "bolt_tpu/ops/foo.py") == []
    # the repo itself holds at zero findings with the rule armed
    assert astlint.lint_package() == []


@pytest.mark.lint
def test_lint_cli_check_mode_passes_on_repo():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint_bolt.py"),
         "--check"], capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 finding(s)" in out.stdout
    # seeded violation through the CLI: nonzero exit
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        bad = os.path.join(td, "bad.py")
        with open(bad, "w") as fh:
            fh.write("import jax\nf = jax.jit(lambda x: x)\n")
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "lint_bolt.py"),
             "--check", bad], capture_output=True, text=True, timeout=120)
        assert out.returncode == 1
        assert "BLT101" in out.stdout


@pytest.mark.lint
def test_lint_blt110_process_topology_calls():
    """BLT110: jax.distributed / jax.process_index / jax.process_count
    are confined to parallel/multihost.py (+ _compat.py) — the one
    process-topology home."""
    from bolt_tpu.analysis import astlint
    src = ("import jax\n\n"
           "def f():\n    return jax.process_index()\n")
    assert [x.code for x in astlint.lint_source(
        src, "bolt_tpu/ops/foo.py")] == ["BLT110"]
    src2 = ("import jax\n\n"
            "def f():\n    return jax.process_count() > 1\n")
    assert [x.code for x in astlint.lint_source(
        src2, "bolt_tpu/tpu/construct.py")] == ["BLT110"]
    # the bootstrap chain itself (attribute + call forms)
    src3 = ("import jax\n\n"
            "def up():\n    jax.distributed.initialize()\n")
    assert [x.code for x in astlint.lint_source(
        src3, "bolt_tpu/checkpoint.py")] == ["BLT110"]
    # import forms
    src4 = "import jax.distributed\n"
    assert [x.code for x in astlint.lint_source(
        src4, "bolt_tpu/ops/foo.py")] == ["BLT110"]
    src5 = "from jax import distributed\n"
    assert [x.code for x in astlint.lint_source(
        src5, "bolt_tpu/ops/foo.py")] == ["BLT110"]
    # alias-aware: a renamed jax must not dodge the rule
    src6 = ("import jax as j\n\n"
            "def f():\n    return j.process_index()\n")
    assert [x.code for x in astlint.lint_source(
        src6, "bolt_tpu/serve.py")] == ["BLT110"]
    # DEVICE attributes are data, not topology calls: no finding
    ok = ("def f(mesh):\n"
          "    return {d.process_index for d in mesh.devices.flat}\n")
    assert astlint.lint_source(ok, "bolt_tpu/ops/foo.py") == []
    # the blessed homes pass
    for home in ("bolt_tpu/parallel/multihost.py", "bolt_tpu/_compat.py"):
        for s in (src, src2, src3, src4, src5):
            assert astlint.lint_source(s, home) == []
    # path anchoring: mymultihost.py does not inherit the pass
    assert any(x.code == "BLT110" for x in astlint.lint_source(
        src, "bolt_tpu/parallel/mymultihost.py"))
    # pragma escape hatch
    pragma = ("import jax\n"
              "n = jax.process_count()  "
              "# lint: allow(BLT110 documented exception)\n")
    assert astlint.lint_source(pragma, "bolt_tpu/ops/foo.py") == []
    # the repo itself holds at zero findings with the rule armed
    assert astlint.lint_package() == []


def test_blt012_registered_and_single_process_quiet(mesh):
    """BLT012 is a registered error-severity code, and a single-process
    mesh never emits it (the divisibility rule is multi-process only —
    the 2-process cluster suite proves the firing side)."""
    from bolt_tpu.analysis.diagnostics import CODES
    assert CODES["BLT012"][0] == "error"
    x = np.arange(14 * 3, dtype=np.float32).reshape(14, 3)
    src = bolt.fromcallback(lambda idx: x[idx], (14, 3), mesh,
                            dtype=np.float32, chunks=3)  # uneven tail
    rep = analysis.check(src.map(lambda v: v + 1))
    assert not rep.has("BLT012")
