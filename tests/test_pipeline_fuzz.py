"""Compositional pipeline fuzzing: random SEQUENCES of operations on the
TPU backend vs a NumPy mirror.

The single-op property suite (test_property.py) fuzzes each operation in
isolation; real workloads chain them, and the deferred/pending/sharding
state machine has interactions no single-op test reaches (a swap of a
deferred chain of a filter result, a getitem after an astype after a
chunked map, ...).  Each case draws 2-5 ops from the pool below, applies
them to both representations, and asserts `allclose` parity at the end."""

import os

import numpy as np
import pytest
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

import bolt_tpu as bolt
from bolt_tpu.utils import allclose

from tests.generic import HYPOTHESIS_SETTINGS as SETTINGS


def _assert_checker_parity(b, x, applied):
    """ISSUE 2 satellite: every fuzzed pipeline first runs
    ``analysis.check`` — the abstract checker must predict the executed
    result's shape and dtype, with ZERO compiles/dispatches of its own
    (the engine counters are the proof) and no error findings."""
    from bolt_tpu import analysis, engine
    c0 = engine.counters()
    rep = analysis.check(b)
    c1 = engine.counters()
    for k in ("misses", "aot_compiles", "dispatches"):
        assert c1[k] == c0[k], (k, applied)
    assert rep.ok, (applied, rep.diagnostics)
    if rep.dynamic:
        # un-synced filter count: the leading extent is unknowable
        # statically; the value dims and dtype are still exact
        assert rep.shape[0] is None, applied
        assert rep.shape[1:] == x.shape[1:], (applied, rep.shape, x.shape)
    else:
        assert rep.shape == x.shape, (applied, rep.shape, x.shape)
    assert np.dtype(rep.dtype) == x.dtype, (applied, rep.dtype, x.dtype)


def _op_map_affine(draw, b, x):
    a = draw(st.sampled_from([-2.0, 0.5, 3.0]))
    c = draw(st.sampled_from([-1.0, 0.0, 2.5]))
    return b.map(lambda v, _a=a, _c=c: v * _a + _c), x * a + c


def _op_operator(draw, b, x):
    c = draw(st.sampled_from([1.5, -0.5]))
    return b + c, x + c


def _op_slice0(draw, b, x):
    n = x.shape[0]
    if n < 2:
        return b, x
    lo = draw(st.integers(0, n - 2))
    hi = draw(st.integers(lo + 1, n))
    return b[lo:hi], x[lo:hi]


def _op_swap(draw, b, x):
    if b.split < 1 or b.ndim - b.split < 1:
        return b, x
    s = b.split
    perm = ([k for k in range(s) if k != 0] + [s]
            + [0] + list(range(s + 1, b.ndim)))
    return b.swap((0,), (0,)), np.transpose(x, perm)


def _op_vtranspose(draw, b, x):
    nv = b.ndim - b.split
    if nv < 2:
        return b, x
    axes = tuple(reversed(range(nv)))
    return (b.values.transpose(*axes),
            np.transpose(x, tuple(range(b.split))
                         + tuple(b.split + a for a in axes)))


def _op_astype(draw, b, x):
    dt = draw(st.sampled_from([np.float32, np.float64]))
    return b.astype(dt), x.astype(dt)


def _op_filter(draw, b, x):
    if b.split != 1 or x.shape[0] < 2:
        return b, x
    thresh = draw(st.sampled_from([-0.5, 0.0, 0.5]))
    keep = x.reshape(x.shape[0], -1).mean(axis=1) > thresh
    return (b.filter(lambda v, _t=thresh: v.mean() > _t), x[keep])


def _op_chunked_map(draw, b, x):
    nv = b.ndim - b.split
    if nv < 1 or x.shape[b.split] < 2:
        return b, x
    c = draw(st.integers(1, x.shape[b.split]))
    p = draw(st.integers(0, max(0, c - 1)))  # random halo: exercises the
    out = b.chunk(size=(c,), axis=(0,), padding=p).map(   # padded/trim path
        lambda blk: blk * 2.0).unchunk()
    return out, x * 2.0


def _op_smooth(draw, b, x):
    from bolt_tpu.ops import smooth
    nv = b.ndim - b.split
    if nv < 1 or x.shape[b.split] < 3:
        return b, x
    length = x.shape[b.split]
    w = draw(st.sampled_from([3, 5]))
    c = draw(st.integers(w // 2 + 1, length))
    out = smooth(b, w, axis=(0,), size=(c,))
    # independent mirror: zero-padded windowed mean along the first
    # value axis of the full array
    ax = b.split
    h = w // 2
    pad = [(0, 0)] * x.ndim
    pad[ax] = (h, h)
    xpad = np.pad(x, pad)
    sl = lambda o: tuple(slice(None) if i != ax else slice(o, o + length)
                         for i in range(x.ndim))
    mirror = sum(xpad[sl(o)] for o in range(w)) / w
    return out, mirror


def _op_stacked_map(draw, b, x):
    if b.split < 1 or x.shape[0] < 1:
        return b, x
    size = draw(st.integers(1, max(1, x.shape[0])))
    return (b.stacked(size=size).map(lambda blk: blk - 1.0).unstack(),
            x - 1.0)


def _op_clip(draw, b, x):
    # round is deliberately NOT fuzzed in chains: it discretises values,
    # making exact-threshold record means (the filter knife edge) likely
    lo = draw(st.sampled_from([-1.0, -0.25, 0.0]))
    hi = draw(st.sampled_from([0.5, 1.5]))
    return b.clip(lo, hi), x.clip(lo, hi)


def _op_normalize(draw, b, x):
    from bolt_tpu.ops import normalize
    if b.ndim - b.split < 1 or x.shape[b.split] < 2:
        return b, x
    ax = b.split
    mu = x.mean(axis=ax, keepdims=True)
    if np.any(np.abs(mu) < 0.05):
        # near-zero baselines sit on the sign-aware-epsilon knife edge:
        # backend and oracle could land on opposite sides on ULP noise
        return b, x
    # the result is zero-mean by construction — shift it so downstream
    # sign-sensitive ops (filter thresholds, another normalize) stay off
    # the knife edge
    out = normalize(b, baseline="mean") + 3.0
    return out, (x - mu) / mu + 3.0


def _op_ufunc(draw, b, x):
    # numpy-ufunc dispatch (round 2): np.tanh(b) must defer into the map
    # chain on the TPU backend and hit ndarray's machinery locally —
    # IDENTICAL SPELLING on both.  tanh is bounded and smooth: no
    # knife-edge thresholds for downstream filters
    uf = draw(st.sampled_from([np.tanh, np.sin]))
    return uf(b), uf(x)


def _op_ufunc_method(draw, b, x):
    # round-5 ufunc METHOD surface (VERDICT r4 missing-3): the
    # shape-preserving accumulate must lower to one fused device program
    # on TPU and hit ndarray's native machinery locally — identical
    # spelling on both.  add keeps magnitudes bounded (×axis-length)
    ax = draw(st.integers(0, x.ndim - 1))
    return np.add.accumulate(b, axis=ax), np.add.accumulate(x, axis=ax)


def _op_matmul(draw, b, x):
    # @ over the last value axis (round 2): shape-preserving
    # well-conditioned weight, batched over every leading axis
    if b.ndim - b.split < 1:
        return b, x
    d = x.shape[-1]
    a = draw(st.sampled_from([1.5, -0.5]))
    w = np.eye(d) * a + 0.05
    return b @ w, x @ w


def _op_concat_self(draw, b, x):
    if b.split < 1 or x.shape[0] < 1 or x.shape[0] > 8:
        return b, x
    return b.concatenate(b, axis=0), np.concatenate([x, x], axis=0)


def _op_keys_reshape(draw, b, x):
    if b.split != 1:
        return b, x
    n = x.shape[0]
    divs = [d for d in range(2, n) if n % d == 0]
    if not divs:
        return b, x
    d = draw(st.sampled_from(divs))
    return (b.keys.reshape(d, n // d),
            x.reshape((d, n // d) + x.shape[1:]))


def _op_set(draw, b, x):
    # round-3 functional mutation: assign a scalar into a leading-axis
    # record; the oracle copies (set never mutates)
    if x.shape[0] < 1:
        return b, x
    i = draw(st.integers(0, x.shape[0] - 1))
    c = draw(st.sampled_from([-3.0, 0.0, 7.5]))
    x2 = x.copy()
    x2[i] = c
    return b.set(i, c), x2


def _op_with_keys(draw, b, x):
    # round-3 deferred with_keys chain entry
    if b.split < 1:
        return b, x
    # keys match x's dtype: numpy's array-array promotion would lift an
    # f32 oracle to f64 (int64 keys) while the device stays f32, pushing
    # the terminal parity check onto the wrong tolerance branch
    keys = np.arange(x.shape[0]).reshape(
        (-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
    return (b.map(lambda kv: kv[1] + kv[0][0], with_keys=True),
            x + keys)


def _op_np_sort(draw, b, x):
    # round-3 __array_function__: functional np.sort on device
    return np.sort(b, axis=-1), np.sort(x, axis=-1)


def _op_take0(draw, b, x):
    if x.shape[0] < 2:
        return b, x
    n = x.shape[0]
    ids = draw(st.lists(st.integers(-n, n - 1), min_size=1, max_size=4))
    return b.take(ids, axis=0), x.take(ids, axis=0)


def _op_np_roll(draw, b, x):
    # round-4 dispatch tail: shape-preserving device roll
    ax = draw(st.integers(0, x.ndim - 1))
    sh = draw(st.sampled_from([-2, 1, 3]))
    return np.roll(b, sh, axis=ax), np.roll(x, sh, axis=ax)


def _op_np_pad(draw, b, x):
    # round-4 dispatch tail: one-program pad (value axes only, to keep
    # the chain's key shape divisible states varied but valid)
    if x.ndim < 2:
        return b, x
    mode = draw(st.sampled_from(["constant", "edge", "wrap"]))
    w = draw(st.integers(1, 2))
    pw = tuple((0, 0) if i < 1 else (w, w) for i in range(x.ndim))
    return np.pad(b, pw, mode=mode), np.pad(x, pw, mode=mode)


def _op_np_stack_self(draw, b, x):
    # round-4 dispatch tail: rank-raising stack at a drawn position
    ax = draw(st.integers(0, x.ndim))
    return np.stack([b, b], axis=ax), np.stack([x, x], axis=ax)


def _op_np_fftshift(draw, b, x):
    # round-4 batch 5: shape-preserving device fftshift
    ax = draw(st.integers(0, x.ndim - 1))
    return np.fft.fftshift(b, axes=ax), np.fft.fftshift(x, axes=ax)


def _op_np_nanmean(draw, b, x):
    # round-4 batch 2: nan-aware reduction over a drawn value axis
    # (key-axis reductions would end the chain's parallelism early)
    if x.ndim < 2:
        return b, x
    ax = draw(st.integers(1, x.ndim - 1))
    return np.nanmean(b, axis=ax), np.nanmean(x, axis=ax)


def _op_np_expand(draw, b, x):
    ax = draw(st.integers(0, x.ndim))
    return np.expand_dims(b, ax), np.expand_dims(x, ax)


def _op_np_delete(draw, b, x):
    # round-5 tail: static-selector delete along a drawn axis exercises
    # shape bookkeeping through the chain on both backends
    ax = draw(st.integers(0, x.ndim - 1))
    if x.shape[ax] < 2:
        return b, x
    i = draw(st.integers(0, x.shape[ax] - 1))
    return np.delete(b, i, axis=ax), np.delete(x, i, axis=ax)


def _op_np_take_along(draw, b, x):
    # round-5 tail: take_along_axis with a flip permutation (shape-
    # preserving, deterministic) along a drawn axis
    ax = draw(st.integers(0, x.ndim - 1))
    n = x.shape[ax]
    shp = [1] * x.ndim
    shp[ax] = n
    idx = np.arange(n - 1, -1, -1).reshape(shp)
    idx = np.broadcast_to(idx, x.shape)
    return (np.take_along_axis(b, idx, axis=ax),
            np.take_along_axis(x, idx, axis=ax))


_OPS = [_op_map_affine, _op_operator, _op_slice0, _op_swap, _op_vtranspose,
        _op_astype, _op_filter, _op_chunked_map, _op_stacked_map,
        _op_concat_self, _op_keys_reshape, _op_smooth, _op_normalize,
        _op_clip, _op_ufunc, _op_matmul, _op_set, _op_with_keys,
        _op_np_sort, _op_take0, _op_np_roll, _op_np_pad,
        _op_np_stack_self, _op_np_fftshift, _op_np_nanmean,
        _op_np_expand, _op_ufunc_method, _op_np_delete, _op_np_take_along]


# ----------------------------------------------------------------------
# the same game on the LOCAL backend: random chains over the NumPy-
# subclass oracle (map/filter/chunked/stacked/smooth interplay has its
# own state to get wrong — e.g. key_axis normalisation and view classes)
# ----------------------------------------------------------------------

def _lop_map(draw, b, x):
    a = draw(st.sampled_from([-2.0, 0.5, 3.0]))
    return b.map(lambda v, _a=a: v * _a, axis=(0,)), x * a


def _lop_filter(draw, b, x):
    if x.shape[0] < 2 or x.ndim < 2:
        return b, x
    thresh = draw(st.sampled_from([-0.5, 0.0, 0.5]))
    keep = x.reshape(x.shape[0], -1).mean(axis=1) > thresh
    return (b.filter(lambda v, _t=thresh: v.mean() > _t, axis=(0,)), x[keep])


def _lop_chunked_map(draw, b, x):
    if x.ndim < 2 or x.shape[1] < 2:
        return b, x
    c = draw(st.integers(1, x.shape[1]))
    p = draw(st.integers(0, max(0, c - 1)))
    out = b.chunk(size=(c,), axis=(0,), padding=p).map(
        lambda blk: blk * 2.0).unchunk()
    return out, x * 2.0


def _lop_stacked_map(draw, b, x):
    if x.shape[0] < 1:
        return b, x
    size = draw(st.integers(1, max(1, x.shape[0])))
    return (b.stacked(size=size).map(lambda blk: blk - 1.0).unstack(),
            x - 1.0)


def _lop_smooth(draw, b, x):
    from bolt_tpu.ops import smooth
    if x.ndim < 2 or x.shape[1] < 3:
        return b, x
    length = x.shape[1]
    w = draw(st.sampled_from([3, 5]))
    c = draw(st.integers(w // 2 + 1, length))
    h = w // 2
    pad = [(0, 0)] * x.ndim
    pad[1] = (h, h)
    xpad = np.pad(x, pad)
    sl = lambda o: (slice(None), slice(o, o + length))
    mirror = sum(xpad[sl(o)] for o in range(w)) / w
    return smooth(b, w, axis=(0,), size=(c,)), mirror


def _lop_matmul(draw, b, x):
    # the local array has no intrinsic split; treat axis 0 as the key
    if x.ndim < 2:
        return b, x
    d = x.shape[-1]
    a = draw(st.sampled_from([1.5, -0.5]))
    w = np.eye(d) * a + 0.05
    return b @ w, x @ w


def _lop_concat_self(draw, b, x):
    if x.shape[0] < 1 or x.shape[0] > 8:
        return b, x
    return b.concatenate(b, axis=0), np.concatenate([x, x], axis=0)


def _lop_normalize(draw, b, x):
    from bolt_tpu.ops import normalize
    if x.ndim < 2 or x.shape[1] < 2:
        return b, x
    mu = x.mean(axis=1, keepdims=True)
    if np.any(np.abs(mu) < 0.05):
        return b, x                       # knife edge — see _op_normalize
    return (normalize(b, baseline="mean") + 3.0, (x - mu) / mu + 3.0)


# _op_operator/_op_slice0/_op_clip/_op_ufunc are backend-agnostic
_LOCAL_OPS = [_lop_map, _op_operator, _op_slice0, _op_clip, _lop_filter,
              _lop_chunked_map, _lop_stacked_map, _lop_smooth,
              _lop_concat_self, _lop_normalize, _op_ufunc, _lop_matmul,
              _op_set, _op_np_sort, _op_take0, _op_ufunc_method,
              _op_np_delete]
# _op_np_take_along is TPU-only: numpy's take_along_axis drives fancy
# indexing that the local array's orthogonal-indexing contract restricts
# (reference-faithful — upstream's ndarray subclass restricts the same
# way), so the local oracle rejects what the device backend serves


@given(st.data(), st.integers(0, 2 ** 16), st.integers(2, 5))
@settings(**SETTINGS)
def test_local_random_pipelines_match_numpy(data, seed, depth):
    rs = np.random.RandomState(seed)
    shape = tuple(rs.randint(2, 6, size=rs.randint(2, 4)))
    x = rs.randn(*shape)
    b = bolt.array(x)
    assert b.mode == "local"
    applied = []
    for _ in range(depth):
        op = data.draw(st.sampled_from(_LOCAL_OPS))
        b, x = op(data.draw, b, x)
        applied.append(op.__name__)
        if x.shape[0] == 0:
            break
    _assert_checker_parity(b, x, applied)
    assert b.shape == x.shape, (applied, b.shape, x.shape)
    assert allclose(b.toarray(), x), applied
    if x.shape[0] > 0:
        got = np.asarray(b.reduce(np.add, axis=(0,)).toarray())
        assert np.allclose(got, x.sum(axis=0), rtol=1e-6), applied


@given(st.data(), st.integers(0, 2 ** 16), st.integers(2, 5))
@settings(**SETTINGS)
def test_random_pipelines_match_numpy(mesh, data, seed, depth):
    rs = np.random.RandomState(seed)
    shape = tuple(rs.randint(2, 6, size=rs.randint(2, 4)))
    x = rs.randn(*shape)
    b = bolt.array(x, mesh, axis=(0,))
    applied = []
    for _ in range(depth):
        op = data.draw(st.sampled_from(_OPS))
        b, x = op(data.draw, b, x)
        applied.append(op.__name__)
        if x.shape[0] == 0:
            break                        # filtered everything away
    # checker-vs-reality parity BEFORE anything resolves: the abstract
    # interpretation must agree with what execution then produces
    _assert_checker_parity(b, x, applied)
    assert b.shape == x.shape, (applied, b.shape, x.shape)
    # dtype-aware tolerance: after an astype(f32) step, device and numpy
    # transcendentals (tanh, …) differ by ~1 ulp and downstream affine
    # steps amplify that past allclose's default rtol=1e-5/atol=1e-8
    # (hypothesis found the seed); f64 chains keep the tight default
    if x.dtype == np.float32:
        assert np.allclose(np.asarray(b.toarray()), x,
                           rtol=1e-4, atol=1e-5), applied
    else:
        assert allclose(b.toarray(), x), applied
    # and a terminal reduction agrees when records remain (dtype-aware
    # tolerance: f32 sums are ulp-close, not bit-exact, across different
    # summation orders — docs/DESIGN.md numerical-parity policy)
    if x.shape[0] > 0 and b.split >= 1:
        got = np.asarray(b.sum(axis=(0,)).toarray())
        loose = x.dtype == np.float32
        assert np.allclose(got, x.sum(axis=0),
                           rtol=1e-5 if loose else 1e-6,
                           atol=1e-5 if loose else 1e-8), applied
