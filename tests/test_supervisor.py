"""Self-healing pod suite, the IN-PROCESS half (ISSUE 12).

Covers the recovery supervisor (``bolt_tpu.parallel.supervisor``)
without a cluster: the transport's rejoin door / reform-plan channel /
quiesce markers and their hygiene sweeps, the watch's rejoin scan, the
pre-collective readiness rendezvous, the slab-boundary quiesce gate,
the supervisor's elect → plan → reform drive (coordinator AND
follower), backoff + double-failure folding, the giveup budget, the
quarantine latch, ``serve.Server(supervise=True)`` degraded-capacity
admission, the checkpoint integrity digests (``checkpoint.corrupt``
seam), and the BLT014 diagnostic.  "Peers" here are FAKES — the test
writes their heartbeat/barrier markers — so everything runs
single-process and ``multihost.reform`` is monkeypatched to a
recorder; the REAL 3→2→3 ``kill -9`` + restart scenario lives in
tests/test_multihost.py on the localhost cluster.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu import _chaos, checkpoint, obs, serve
from bolt_tpu.parallel import multihost, podwatch, supervisor
from bolt_tpu.parallel.podwatch import (FileTransport, PeerLostError,
                                        PodQuiesceError)
from bolt_tpu.parallel.supervisor import SuperviseError, Supervisor

pytestmark = pytest.mark.podwatch


@pytest.fixture
def watchdir(tmp_path):
    """A clean watch/supervisor per test: no stray callbacks, no
    running watch, no leftover quiesce latch or armed chaos."""
    with podwatch._CB_LOCK:
        saved = {name: dict(getattr(podwatch, name)) for name in
                 ("_DEATH_CBS", "_REFORM_CBS", "_REJOIN_CBS")}
        for name in saved:
            getattr(podwatch, name).clear()
    yield str(tmp_path)
    sup = supervisor.active()
    if sup is not None:
        sup.close()
    podwatch.stop()
    podwatch.clear_quiesce()
    _chaos.clear()
    with podwatch._CB_LOCK:
        for name, cbs in saved.items():
            getattr(podwatch, name).clear()
            getattr(podwatch, name).update(cbs)
    # serve/supervisor counters are PROCESS-global registry groups and
    # other suites assert absolute totals — put the zeros back
    from bolt_tpu.obs import metrics as _metrics
    reg = _metrics.registry()
    for name in list(reg.names()):
        if name.split("/")[0] in ("serve", "supervisor"):
            m = reg.get(name)
            if hasattr(m, "reset"):
                m.reset()


class _FakePeer:
    """A background thread impersonating pod process ``pid`` on the
    file transport: beats (and arrives at every barrier generation)
    until told to die."""

    def __init__(self, transport, pid, interval=0.03, barriers=()):
        self.transport = transport
        self.pid = pid
        self.interval = interval
        self.barriers = barriers      # names marked at every generation
        self.stop_ev = threading.Event()
        self.seq = 0
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        while not self.stop_ev.is_set():
            self.seq += 1
            self.transport.beat(self.pid, self.seq)
            for name in self.barriers:
                for gen in range(8):
                    self.transport.barrier_mark(name, gen, self.pid)
            self.stop_ev.wait(self.interval)

    def kill(self):
        self.stop_ev.set()
        self.thread.join()


def _start(watchdir, nproc=2, pid=0, interval=0.05, timeout=0.4,
           **kw):
    assert podwatch.start(nproc, pid, dir=watchdir, interval=interval,
                          timeout=timeout, **kw)
    return podwatch._WATCH.transport


def _wait(pred, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise AssertionError("%s never became true" % msg)
        time.sleep(0.02)


class _ReformRecorder:
    """Stands in for ``multihost.reform``: records each drive and
    fires the reform notification like the real door."""

    def __init__(self, fail_times=0, exc=None):
        self.calls = []
        self.fail_times = fail_times
        self.exc = exc

    def __call__(self, addr, num_processes, process_id=None, epoch=None,
                 init_timeout=None):
        if self.fail_times > 0:
            self.fail_times -= 1
            raise self.exc or RuntimeError("reform bring-up failed")
        self.calls.append({"addr": addr, "nproc": int(num_processes),
                           "pid": process_id, "epoch": epoch,
                           "init_timeout": init_timeout})
        podwatch.notify_reform()
        return process_id


@pytest.fixture
def reform_recorder(monkeypatch):
    rec = _ReformRecorder()
    monkeypatch.setattr(multihost, "reform", rec)
    return rec


# ---------------------------------------------------------------------
# transport: rejoin door, plan channel, quiesce markers, hygiene
# ---------------------------------------------------------------------

def test_transport_rejoin_and_plan_roundtrip(tmp_path):
    t = FileTransport(str(tmp_path), epoch=2)
    assert t.read_rejoin_marks() == set()
    t.rejoin_mark("w1b")
    t.rejoin_mark("odd/../ident")      # sanitised, never a path escape
    marks = t.read_rejoin_marks()
    assert "w1b" in marks and len(marks) == 2
    assert all(os.sep not in m for m in marks)
    t.rejoin_clear("w1b")
    assert "w1b" not in t.read_rejoin_marks()
    # the plan channel
    assert t.plan_gens() == [] and t.plan_get(1) is None
    t.plan_set(1, '{"gen": 1}')
    t.plan_set(3, '{"gen": 3}')
    assert t.plan_gens() == [1, 3]
    assert json.loads(t.plan_get(3)) == {"gen": 3}
    # quiesce markers are epoch-scoped
    assert not t.quiesce_seen(4)
    t.quiesce_mark(4)
    assert t.quiesce_seen(4) and not t.quiesce_seen(5)
    assert not FileTransport(str(tmp_path), epoch=3).quiesce_seen(4)


def test_transport_sweeps_and_stale_count(tmp_path):
    old = FileTransport(str(tmp_path), epoch=1)
    old.beat(0, 1)
    old.beat(1, 1)
    old.quiesce_mark(2)
    for g in (1, 2, 3, 4):
        old.plan_set(g, '{"gen": %d}' % g)
    new = FileTransport(str(tmp_path), epoch=3)
    new.beat(0, 1)
    assert new.stale_marker_count() == 3      # two beats + one quiesce
    new.sweep_epochs(keep_from=2)
    assert new.stale_marker_count() == 0
    assert new.plan_gens() == [3, 4]          # two-generation grace
    assert new.read()[0] == 1                 # own epoch untouched
    # the dead-peer sweep removes one pid's markers only
    new.beat(1, 5)
    new.sweep_peer(1)
    assert 1 not in new.read() and 0 in new.read()


def test_stream_clear_sweeps_dead_markers(watchdir, tmp_path,
                                          monkeypatch):
    """checkpoint.stream_clear sweeps latched-DEAD peers' heartbeat
    markers alongside its shard sweep (ISSUE 12 satellite)."""
    t = _start(watchdir, nproc=2)
    t.beat(0, 1)
    t.beat(1, 7)                      # the (dead) peer's droppings
    podwatch.mark_dead(1)
    monkeypatch.setattr(multihost, "process_count", lambda: 2)
    monkeypatch.setattr(multihost, "process_index", lambda: 0)
    monkeypatch.setattr(multihost, "barrier", lambda name: None)
    ck = tmp_path / "ck"
    ck.mkdir()
    checkpoint.stream_clear(str(ck), multiprocess=True)
    assert 1 not in t.read()
    assert 0 in t.read()              # own beats stay


# ---------------------------------------------------------------------
# the rejoin door + the watch's rejoin scan
# ---------------------------------------------------------------------

def test_rejoin_requires_a_shared_medium(monkeypatch, tmp_path):
    monkeypatch.setattr(podwatch, "_ENV_HB_DIR", None)
    with pytest.raises(RuntimeError, match="BOLT_POD_HB_DIR"):
        podwatch.rejoin("w1b")
    tr = podwatch.rejoin("w1b", dir=str(tmp_path))
    assert "w1b" in tr.read_rejoin_marks()


def test_watch_scans_rejoin_once_per_ident(watchdir):
    seen = []
    podwatch.on_rejoin(seen.append)
    t = _start(watchdir)
    peer = _FakePeer(t, 1)
    try:
        podwatch.rejoin("w1b")        # rides the running watch transport
        _wait(lambda: seen == ["w1b"], msg="rejoin fanout")
        time.sleep(0.2)               # marker still present: no re-fire
        assert seen == ["w1b"]
        podwatch.rejoin("w2b")
        _wait(lambda: seen == ["w1b", "w2b"], msg="second rejoin")
    finally:
        peer.kill()


# ---------------------------------------------------------------------
# pre-collective readiness rendezvous + the quiesce gate
# ---------------------------------------------------------------------

def test_ready_rendezvous_noop_without_watch():
    assert podwatch.ready_rendezvous() is False


def test_ready_rendezvous_live_peer_passes(watchdir):
    t = _start(watchdir)
    peer = _FakePeer(t, 1, barriers=("bolt_stream_ready",))
    try:
        assert podwatch.ready_rendezvous() is True
    finally:
        peer.kill()


def test_ready_rendezvous_converts_pre_collective_death(watchdir):
    """A peer dead BEFORE the first collective dispatch surfaces as
    PeerLostError within ~2x the deadline — the closed ~30s gloo
    connect bound."""
    _start(watchdir, timeout=0.3)     # peer 1 never beats
    t0 = time.monotonic()
    with pytest.raises(PeerLostError) as ei:
        podwatch.ready_rendezvous()
    assert time.monotonic() - t0 < 2 * 0.3 + 0.3
    assert ei.value.peer == 1


def test_quiesce_gate_raises_at_the_watermark(watchdir):
    t = _start(watchdir)
    peer = _FakePeer(t, 1, barriers=("bolt_quiesce_gate",))
    try:
        podwatch.quiesce_gate(3)      # no request: passes through
        podwatch.request_quiesce("rejoin w1b")
        assert podwatch.quiesce_requested() == "rejoin w1b"
        with pytest.raises(PodQuiesceError) as ei:
            podwatch.quiesce_gate(4)
        assert ei.value.slab == 4 and ei.value.peer is None
        assert isinstance(ei.value, PeerLostError)   # retryable alike
        assert "rejoin w1b" in str(ei.value)
        # every process sees the same watermark marker
        assert t.quiesce_seen(4) and not t.quiesce_seen(3)
        podwatch.clear_quiesce()
        podwatch.quiesce_gate(5)      # cleared: passes through again
    finally:
        peer.kill()


def test_quiesce_gate_fenced_needs_no_barrier(watchdir):
    """The per-checkpoint path: process 0 publishes its decision with
    quiesce_pre BEFORE the checkpoint, whose own rendezvous barriers
    fence the marker — quiesce_gate(fenced=True) then decides without
    a second standalone barrier.  The fake peer here never marks the
    gate barrier, so any barrier wait would latch it dead and raise
    PeerLostError instead of the expected outcomes."""
    t = _start(watchdir)
    peer = _FakePeer(t, 1)            # no gate-barrier marks
    try:
        podwatch.quiesce_pre(7)       # no request: no marker
        podwatch.quiesce_gate(7, fenced=True)     # passes through
        assert not t.quiesce_seen(7)
        podwatch.request_quiesce("rejoin w1b")
        podwatch.quiesce_pre(8)       # pre-checkpoint publish
        assert t.quiesce_seen(8)
        with pytest.raises(PodQuiesceError) as ei:
            podwatch.quiesce_gate(8, fenced=True)
        assert ei.value.slab == 8
        podwatch.clear_quiesce()
    finally:
        podwatch.clear_quiesce()
        peer.kill()


def test_quiesce_gate_latches_peer_decision(watchdir):
    """Process 0 can decide the quiesce BEFORE this process's own
    supervisor scanned the rejoin marker: the gate must latch the
    LOCAL quiesce state when it sees the marker, so the serving layer
    holds the retry instead of re-running into a reforming pod."""
    t = _start(watchdir, nproc=2, pid=1)
    peer = _FakePeer(t, 0, barriers=("bolt_quiesce_gate",))
    try:
        t.quiesce_mark(6)             # the peer decider's marker
        assert podwatch.quiesce_requested() is None
        with pytest.raises(PodQuiesceError):
            podwatch.quiesce_gate(6)
        assert "peer quiesce" in podwatch.quiesce_requested()
    finally:
        peer.kill()


def test_serve_retry_holds_during_latched_quiesce(watchdir):
    """A PeerLostError retry must hold while the local quiesce latch
    is set even though the pod is NOT paused yet (the gate-trips-first
    window of the rejoin reform)."""
    with serve.serving(workers=1) as sv:
        attempts = []

        def flaky():
            attempts.append(1)
            if len(attempts) == 1:
                podwatch.request_quiesce("rejoin ['w2b']")
                raise PodQuiesceError("quiesced", slab=4)
            return "resumed"

        fut = sv.submit(flaky, tenant="t", retries=1)
        time.sleep(0.4)
        assert not fut.done()         # held on the latch alone
        podwatch.clear_quiesce()      # the recovery completed
        assert fut.result(timeout=30) == "resumed"
        assert len(attempts) == 2


def test_pod_busy_accounting():
    assert podwatch.pod_busy() == 0
    podwatch.pod_enter()
    podwatch.pod_enter()
    assert podwatch.pod_busy() == 2
    podwatch.pod_exit()
    podwatch.pod_exit()
    podwatch.pod_exit()               # never below zero
    assert podwatch.pod_busy() == 0


def test_epoch_pinning_and_doors(watchdir):
    assert podwatch.transport() is None
    _start(watchdir, epoch=7)
    assert podwatch.epoch() == 7
    assert podwatch.transport() is podwatch._WATCH.transport
    podwatch.stop()
    _start(watchdir)                  # unpinned: bumps past the pin
    assert podwatch.epoch() == 8


# ---------------------------------------------------------------------
# the supervisor: elect -> plan -> reform
# ---------------------------------------------------------------------

def test_supervisor_coordinator_drives_reform(watchdir, reform_recorder):
    """Peer death on the lowest-rank survivor: it elects itself,
    publishes the plan through the transport, and drives reform onto
    the survivors — hooks and counters around it."""
    t = _start(watchdir, nproc=3)
    peer1 = _FakePeer(t, 1)
    peer2 = _FakePeer(t, 2)
    events = []
    sup = Supervisor(backoff=0.05,
                     on_pause=lambda r: events.append(("pause", r)),
                     on_resume=lambda i: events.append(("resume", i)))
    try:
        _wait(lambda: set(podwatch.alive_peers()) == {0, 1, 2},
              msg="3-wide pod")
        epoch0 = podwatch.epoch()
        peer2.kill()
        _wait(lambda: sup.stats()["reforms"] == 1, timeout=10,
              msg="supervised reform")
        assert sup.wait_recovered(timeout=10)
        assert [c["nproc"] for c in reform_recorder.calls] == [2]
        call = reform_recorder.calls[0]
        assert call["pid"] == 0                      # lowest alive rank
        assert call["epoch"] == epoch0 + 2           # probe slot skipped
        plan = json.loads(t.plan_get(1))
        assert plan["members"] == [["i", 0], ["i", 1]]
        assert plan["addr"].split(":")[1] == call["addr"].split(":")[1]
        assert events[0] == ("pause", "peer death [2]")
        assert events[1][0] == "resume"
        assert events[1][1]["nproc"] == 2 and events[1][1]["rejoined"] == []
        st = sup.stats()
        assert st["peer_losses"] == 1 and st["reforms"] == 1
        assert st["giveups"] == 0 and st["failed"] is None
        assert st["last_reform_seconds"] >= 0
        assert st["generation"] == 1
    finally:
        sup.close()
        peer1.kill()
        peer2.kill()


def test_supervisor_follower_adopts_published_plan(watchdir,
                                                   reform_recorder):
    """A NON-lowest survivor polls the transport for the coordinator's
    plan and reforms from it (no out-of-band agreement anywhere)."""
    t = _start(watchdir, nproc=3, pid=1)
    peer0 = _FakePeer(t, 0)
    peer2 = _FakePeer(t, 2)
    sup = Supervisor(backoff=0.05)
    try:
        _wait(lambda: set(podwatch.alive_peers()) == {0, 1, 2},
              msg="3-wide pod")
        peer2.kill()
        _wait(lambda: podwatch.dead_peers() == (2,), msg="death latch")
        # the "coordinator" (fake peer 0) publishes a fresh generation
        # every beat until the follower adopts one — the follower polls
        # for generations NEWER than what it saw at attempt start, so a
        # single fixed generation could race its snapshot
        stop = threading.Event()

        def publish():
            g = 1
            while not stop.is_set():
                t.plan_set(g, json.dumps(
                    {"addr": "127.0.0.1:45678",
                     "members": [["i", 0], ["i", 1]],
                     "epoch": podwatch.epoch() + 2, "gen": g}))
                g += 1
                stop.wait(0.1)

        pub = threading.Thread(target=publish, daemon=True)
        pub.start()
        try:
            _wait(lambda: reform_recorder.calls, timeout=10,
                  msg="follower adoption")
        finally:
            stop.set()
            pub.join()
        assert sup.wait_recovered(timeout=10)
        call = reform_recorder.calls[0]
        assert call["addr"] == "127.0.0.1:45678"
        assert call["nproc"] == 2 and call["pid"] == 1
    finally:
        sup.close()
        peer0.kill()
        peer2.kill()


def test_supervisor_follower_adopts_pre_published_plan(watchdir,
                                                       reform_recorder):
    """The coordinator detects the death on its OWN clock: its plan
    can land on the transport BEFORE this follower's latch fires.  The
    follower must adopt that already-published generation (floor =
    last gen DRIVEN + 1, not max(existing) + 1 — the latter skips the
    plan forever and burns the whole retry budget)."""
    t = _start(watchdir, nproc=3, pid=2)
    peer0 = _FakePeer(t, 0)
    peer1 = _FakePeer(t, 1)
    sup = Supervisor(backoff=0.05)
    try:
        _wait(lambda: set(podwatch.alive_peers()) == {0, 1, 2},
              msg="3-wide pod")
        # the plan is ALREADY on the transport when the death latches
        t.plan_set(1, json.dumps(
            {"addr": "127.0.0.1:45679",
             "members": [["i", 0], ["i", 2]],
             "epoch": podwatch.epoch() + 2, "gen": 1}))
        peer1.kill()
        _wait(lambda: reform_recorder.calls, timeout=10,
              msg="pre-published plan adoption")
        assert sup.wait_recovered(timeout=10)
        call = reform_recorder.calls[0]
        assert call["addr"] == "127.0.0.1:45679"
        assert call["nproc"] == 2 and call["pid"] == 1
        assert sup.stats()["backoffs"] == 0   # adopted on attempt 1
    finally:
        sup.close()
        peer0.kill()
        peer1.kill()


def test_serve_giveup_releases_held_retries_and_submit(watchdir,
                                                       monkeypatch):
    """An abandoned recovery must not wedge the server: a held
    PeerLostError retry is delivered (loudly) once the supervisor
    gives up, and a queue-policy submitter blocked on the drain is
    rejected naming the giveup instead of waiting forever."""
    monkeypatch.setattr(supervisor, "_DEF_RETRIES", 1)
    monkeypatch.setattr(supervisor, "_DEF_BACKOFF", 0.02)
    rec = _ReformRecorder(fail_times=99)
    monkeypatch.setattr(multihost, "reform", rec)
    t = _start(watchdir, nproc=2)
    peer = _FakePeer(t, 1)
    try:
        with serve.serving(workers=1, supervise=True) as sv:

            def lost():
                # surface the loss once the drain is engaged, so the
                # retry actually HOLDS before the giveup releases it
                _wait(lambda: sv.pod_paused(), msg="drain before loss")
                raise PeerLostError("pod peer lost: process 1 died",
                                    peer=1)

            fut = sv.submit(lost, tenant="t", retries=5)
            peer.kill()
            _wait(lambda: sv.supervisor.stats()["giveups"] == 1,
                  timeout=15, msg="giveup")
            # the held retry releases and delivers the loss
            with pytest.raises((PeerLostError, RuntimeError)):
                fut.result(timeout=30)
            # a blocked submitter is rejected pointedly, not wedged
            with pytest.raises(serve.AdmissionError,
                               match="recovery abandoned"):
                sv.submit(lambda: 1, tenant="t")
    finally:
        peer.kill()


def test_supervisor_second_failure_mid_reform_folds_in(watchdir,
                                                       reform_recorder):
    """The chaos seam fails attempt 1; a SECOND death lands during the
    backoff — attempt 2 re-enters on the new survivor set and reforms
    onto it (the double-failure contract), with the backoff counted."""
    t = _start(watchdir, nproc=3)
    peer1 = _FakePeer(t, 1)
    peer2 = _FakePeer(t, 2)
    _chaos.inject("supervisor.elect", nth=1, times=1)
    sup = Supervisor(backoff=1.0)
    try:
        _wait(lambda: set(podwatch.alive_peers()) == {0, 1, 2},
              msg="3-wide pod")
        peer2.kill()
        # attempt 1 tripped; during its backoff the second victim dies
        _wait(lambda: _chaos.stats("supervisor.elect")[1] == 1,
              msg="first attempt tripped")
        peer1.kill()
        podwatch.mark_dead(1)
        _wait(lambda: sup.stats()["reforms"] == 1, timeout=15,
              msg="second-attempt reform")
        assert sup.wait_recovered(timeout=15)
        assert [c["nproc"] for c in reform_recorder.calls] == [1]
        plan = json.loads(t.plan_get(1))
        assert plan["members"] == [["i", 0]]
        st = sup.stats()
        assert st["backoffs"] == 1 and st["reforms"] == 1
        assert st["peer_losses"] == 2
    finally:
        sup.close()
        peer1.kill()
        peer2.kill()


def test_supervisor_giveup_exhausts_budget(watchdir, monkeypatch):
    """Every attempt fails and the budget runs out: the recovery is
    abandoned LOUDLY — wait_recovered raises the chained SuperviseError
    and the giveup is counted.  The pod stays drained but manual
    reform remains possible (the error says so)."""
    rec = _ReformRecorder(fail_times=99)
    monkeypatch.setattr(multihost, "reform", rec)
    t = _start(watchdir, nproc=2)
    peer = _FakePeer(t, 1)
    sup = Supervisor(retries=1, backoff=0.02)
    try:
        _wait(lambda: set(podwatch.alive_peers()) == {0, 1},
              msg="2-wide pod")
        peer.kill()
        _wait(lambda: sup.stats()["giveups"] == 1, timeout=10,
              msg="giveup")
        with pytest.raises(SuperviseError,
                           match="abandoned after 2 attempt"):
            sup.wait_recovered(timeout=10)
        st = sup.stats()
        assert st["giveups"] == 1 and st["backoffs"] == 1
        assert "reform bring-up failed" in st["failed"]
    finally:
        sup.close()
        peer.kill()


def test_supervisor_rejoin_reforms_up(watchdir, reform_recorder):
    """The rejoin door: an announced identity is folded into the plan
    as rank N, the membership GROWS, the consumed doorbell is swept,
    and a repeat announcement of a now-member is ignored."""
    t = _start(watchdir, nproc=2)
    peer = _FakePeer(t, 1)
    sup = Supervisor(backoff=0.05)
    try:
        _wait(lambda: set(podwatch.alive_peers()) == {0, 1},
              msg="2-wide pod")
        podwatch.rejoin("w2b")
        _wait(lambda: sup.stats()["reforms"] == 1, timeout=10,
              msg="reform-up")
        assert sup.wait_recovered(timeout=10)
        call = reform_recorder.calls[0]
        assert call["nproc"] == 3 and call["pid"] == 0
        plan = json.loads(t.plan_get(1))
        assert plan["members"] == [["i", 0], ["i", 1], ["r", "w2b"]]
        st = sup.stats()
        assert st["rejoins"] == 1 and st["reforms"] == 1
        assert st["peer_losses"] == 0
        _wait(lambda: t.read_rejoin_marks() == set(),
              msg="doorbell sweep")
        # no quiesce latch survives the recovery
        assert podwatch.quiesce_requested() is None
        # a member's re-announcement is a no-op (marker-sweep lag)
        sup._on_rejoin("w2b")
        time.sleep(0.2)
        assert sup.stats()["reforms"] == 1
    finally:
        sup.close()
        peer.kill()


def test_quarantine_tracks_identity_across_rank_remap(watchdir,
                                                      reform_recorder):
    """Strikes attach to the PERSISTENT identity, not the transient
    rank: a replacement that joined as "w1b" and then flaps dies at
    whatever rank the last reform gave it — the strike must land on
    "w1b" (and quarantine it), never on the birth identity "i1" of
    the rank it inherited."""
    t = _start(watchdir, nproc=2)
    peer = _FakePeer(t, 1)
    sup = Supervisor(backoff=0.05, quarantine_after=1)
    try:
        _wait(lambda: set(podwatch.alive_peers()) == {0, 1},
              msg="2-wide pod")
        peer.kill()                   # strike 1 for the ORIGINAL "i1"
        _wait(lambda: sup.stats()["reforms"] == 1, timeout=10,
              msg="shrink reform")
        podwatch.rejoin("w1b")        # replacement, DIFFERENT identity
        _wait(lambda: sup.stats()["rejoins"] == 1, timeout=10,
              msg="rejoin reform")
        # strike 1 latched the ORIGINAL "i1" (quarantine_after=1) —
        # the replacement identity starts clean
        assert sup.quarantined() == ["i1"]
        assert sup._ident_of(1) == "w1b"   # it holds rank 1 now
        sup._on_death(1)              # the REPLACEMENT flaps
        _wait(lambda: sup.stats()["reforms"] == 3, timeout=10,
              msg="second shrink")
        assert sup._strikes.get("w1b") == 1   # struck by identity,
        assert sup._strikes.get("i1") == 1    # NOT the rank's birth id
        _wait(lambda: sup.quarantined() == ["i1", "w1b"],
              msg="identity quarantine")
        n = sup.stats()["reforms"]
        sup._on_rejoin("w1b")         # further announcements ignored
        time.sleep(0.3)
        assert sup.stats()["reforms"] == n
        assert sup.stats()["quarantined"] == 1
    finally:
        sup.close()
        peer.kill()


def test_attach_normalizes_identity(watchdir, reform_recorder):
    """The transport sanitizes marker filenames, so the incumbents'
    plan names the SANITIZED identity — attach("worker:7") must match
    the plan's "worker_7" instead of timing out while every incumbent
    blocks in the reform bring-up."""
    tr = FileTransport(watchdir, epoch=0)
    got = {}

    def join():
        try:
            got["sup"] = supervisor.attach("worker:7", dir=watchdir,
                                           timeout=8)
        except Exception as exc:      # noqa: BLE001 — asserted below
            got["err"] = exc

    th = threading.Thread(target=join, daemon=True)
    th.start()
    _wait(lambda: tr.read_rejoin_marks() == {"worker_7"},
          msg="sanitized doorbell")
    tr.plan_set(1, json.dumps(
        {"addr": "127.0.0.1:45680",
         "members": [["i", 0], ["r", "worker_7"]],
         "epoch": 2, "gen": 1}))
    th.join(timeout=20)
    assert not th.is_alive() and "err" not in got, got.get("err")
    try:
        assert reform_recorder.calls[-1]["pid"] == 1
        assert got["sup"]._ident_of(1) == "worker_7"  # seeded map
        assert got["sup"]._ident_of(0) == "i0"
    finally:
        got["sup"].close()


def test_attach_seeds_generation_and_joined(watchdir, reform_recorder):
    """attach() must seed the new supervisor with the plan it joined
    by: the follower adoption floor is ``_gen + 1``, so a rejoiner
    left at gen 0 could re-adopt a RETAINED stale plan generation on
    its next recovery (sweep_epochs keeps the last two) and reform
    against a dead coordinator; and this plan's rejoiners are members
    now, so their sweep-lag doorbell duplicates must be dropped."""
    tr = FileTransport(watchdir, epoch=0)
    got = {}

    def join():
        got["sup"] = supervisor.attach("w1b", dir=watchdir, timeout=8)

    th = threading.Thread(target=join, daemon=True)
    th.start()
    _wait(lambda: tr.read_rejoin_marks() == {"w1b"}, msg="doorbell")
    tr.plan_set(3, json.dumps(
        {"addr": "127.0.0.1:45681",
         "members": [["i", 0], ["i", 2], ["r", "w1b"]],
         "epoch": 2, "gen": 3}))
    th.join(timeout=20)
    assert not th.is_alive()
    sup = got["sup"]
    try:
        assert sup.stats()["generation"] == 3   # floor starts past 3
        with sup._lock:
            assert "w1b" in sup._joined
        sup._on_rejoin("w1b")         # stale doorbell for a member
        assert sup.stats()["pending_rejoins"] == []
    finally:
        sup.close()


def test_new_recovery_clears_stale_giveup(watchdir, monkeypatch):
    """A stale giveup from a PAST recovery must not abort the next
    one: ``failed`` clears when a new recovery BEGINS — held retries
    and blocked submitters wait for its outcome — not only once it
    succeeds."""
    rec = _ReformRecorder()
    gate = threading.Event()
    seen = {}

    def gated_reform(*a, **kw):
        seen["failed_mid_recovery"] = sup.failed
        assert gate.wait(10)
        return rec(*a, **kw)

    monkeypatch.setattr(multihost, "reform", gated_reform)
    t = _start(watchdir, nproc=2)
    peer = _FakePeer(t, 1)
    sup = Supervisor(backoff=0.05)
    try:
        _wait(lambda: set(podwatch.alive_peers()) == {0, 1},
              msg="2-wide pod")
        sup.failed = RuntimeError("stale giveup")
        peer.kill()
        _wait(lambda: "failed_mid_recovery" in seen, timeout=10,
              msg="recovery reached reform")
        assert seen["failed_mid_recovery"] is None
        gate.set()
        assert sup.wait_recovered(timeout=10)
        assert sup.failed is None
    finally:
        gate.set()
        sup.close()
        peer.kill()


def test_relatch_of_same_death_is_one_strike(watchdir):
    """The liveness re-probe after a failed reform attempt starts a
    fresh watch where the SAME dead peer re-latches and fires the
    death callback again — that is one death, one strike, or a single
    transient reform failure would quarantine (default 2 strikes) a
    peer that never flapped and permanently block its rejoin."""
    sup = Supervisor(retries=0, backoff=0.02, quarantine_after=2)
    try:
        sup._stop.set()               # park the recovery thread:
        #                               intake only, no recovery drive
        sup._on_death(1)
        sup._on_death(1)              # probe re-latch, same death
        assert sup._strikes.get("i1") == 1
        assert sup.quarantined() == []
        assert sup.stats()["peer_losses"] == 1
    finally:
        sup.close()


def test_busy_pod_defers_growth_instead_of_reforming(watchdir,
                                                     reform_recorder,
                                                     monkeypatch):
    """A pod that never goes idle within the drain budget (e.g. an
    UNCHECKPOINTED stream can never observe the quiesce request) must
    NOT be reformed up — that would tear down the XLA backends under
    the live collective schedule.  The growth is deferred: the pod
    resumes untouched, no reform is driven, the quiesce latch clears,
    and the identity's next doorbell rings through again."""
    monkeypatch.setattr(supervisor, "_DEF_DRAIN", 0.3)
    t = _start(watchdir, nproc=2)
    peer = _FakePeer(t, 1)
    events = []
    sup = Supervisor(backoff=0.05,
                     on_resume=lambda i: events.append(i))
    try:
        _wait(lambda: set(podwatch.alive_peers()) == {0, 1},
              msg="2-wide pod")
        podwatch.pod_enter()          # a live pod run that never gates
        podwatch.rejoin("w1b")
        _wait(lambda: events, timeout=10, msg="deferred resume")
        assert events[0]["deferred"] == ["w1b"]
        assert events[0]["rejoined"] == []
        assert reform_recorder.calls == []        # pod untouched
        assert podwatch.quiesce_requested() is None
        assert sup.wait_recovered(timeout=10)
        assert sup.stats()["pending_rejoins"] == []
        # the latch reset lets the next doorbell ring through
        podwatch.pod_exit()
        podwatch.rejoin("w1b")
        _wait(lambda: sup.stats()["reforms"] == 1, timeout=10,
              msg="re-rung growth reforms once idle")
    finally:
        podwatch.clear_quiesce()
        sup.close()
        peer.kill()


def test_rank_never_defaults_to_zero_with_watch_down(watchdir):
    """With the watch down mid-recovery (a failed attempt whose
    re-probe also failed), the member must fail the attempt loudly
    rather than assume rank 0 — a non-zero survivor impersonating
    the coordinator would publish a conflicting plan and claim
    process_id 0 in the bring-up."""
    sup = Supervisor(retries=0, backoff=0.02)
    try:
        assert podwatch._WATCH is None
        with pytest.raises(SuperviseError, match="rank"):
            sup._my_rank()
    finally:
        sup.close()


def test_supervisor_quarantines_flapping_peer(watchdir, reform_recorder):
    """The documented flap contract (dies, rejoins, dies AGAIN =
    quarantine_after=2 strikes): the latch trips at the threshold
    strike itself, so the flapper's very next rejoin announcement is
    ignored — it is never re-admitted for one more reform cycle."""
    t = _start(watchdir, nproc=2)
    peer = _FakePeer(t, 1)
    sup = Supervisor(backoff=0.05, quarantine_after=2)
    try:
        _wait(lambda: set(podwatch.alive_peers()) == {0, 1},
              msg="2-wide pod")
        peer.kill()                   # strike 1 for identity "i1"
        _wait(lambda: sup.stats()["reforms"] == 1, timeout=10,
              msg="shrink reform")
        assert sup.quarantined() == []     # one strike: not latched
        podwatch.rejoin("i1")         # the flapper asks back in
        _wait(lambda: sup.stats()["rejoins"] == 1, timeout=10,
              msg="rejoin reform")
        sup._on_death(1)              # dies AGAIN: strike 2 latches
        _wait(lambda: sup.stats()["reforms"] == 3, timeout=10,
              msg="second shrink")
        _wait(lambda: sup.quarantined() == ["i1"], msg="quarantine")
        n_reforms = sup.stats()["reforms"]
        sup._on_rejoin("i1")          # announcement: ignored outright
        time.sleep(0.3)
        st = sup.stats()
        assert st["quarantined"] == 1
        assert st["reforms"] == n_reforms
        assert st["quarantine"] == ["i1"]
        assert sup.config()["quarantine"] == ["i1"]
    finally:
        sup.close()
        peer.kill()


def test_supervisor_no_transport_is_loud(watchdir):
    """A death with NO liveness watch running (so no surviving
    membership and no transport to carry a plan) abandons pointedly
    instead of spinning."""
    sup = Supervisor(retries=0, backoff=0.02)
    try:
        sup._on_death(1)              # no watch is running
        _wait(lambda: sup.stats()["giveups"] == 1, timeout=10,
              msg="giveup")
        with pytest.raises(SuperviseError, match="no surviving members"):
            sup.wait_recovered(timeout=10)
    finally:
        sup.close()


def test_supervisor_close_is_idempotent_and_detaches(watchdir):
    sup = Supervisor()
    assert supervisor.active() is sup
    sup.close()
    assert supervisor.active() is None
    sup.close()                       # second close: no-op
    # its callbacks are gone: a death after close never wakes it
    podwatch.mark_dead(1)
    assert sup.stats()["peer_losses"] == 0


# ---------------------------------------------------------------------
# serve integration: Server(supervise=True)
# ---------------------------------------------------------------------

def test_serve_supervised_recovery_rescales_budget(watchdir,
                                                   reform_recorder,
                                                   monkeypatch):
    """Peer death under supervise=True: admission drains, the
    supervisor reforms AUTOMATICALLY (no caller intervention), the
    arbiter budget rescales to the surviving share (degraded-capacity
    admission), and the queue resumes."""
    monkeypatch.setattr(multihost, "process_count", lambda: 3)
    t = _start(watchdir, nproc=3)
    peer1 = _FakePeer(t, 1)
    peer2 = _FakePeer(t, 2)
    try:
        with serve.serving(workers=1, budget_bytes=3000,
                           supervise=True) as sv:
            assert sv.supervisor is not None
            assert supervisor.active() is sv.supervisor
            st = sv.stats()["pod"]
            assert st["supervised"] and st["budget_share"] == 1.0
            peer2.kill()
            _wait(lambda: sv.stats()["totals"]["reforms"] == 1,
                  timeout=10, msg="supervised reform")
            assert sv.supervisor.wait_recovered(timeout=10)
            _wait(lambda: not sv.pod_paused(), msg="resume")
            assert reform_recorder.calls[0]["nproc"] == 2
            st = sv.stats()
            assert st["totals"]["reforms"] == 1
            assert st["totals"]["peer_losses"] == 1
            assert st["totals"]["supervise_seconds"] > 0
            assert abs(st["pod"]["budget_share"] - 2 / 3) < 1e-6
            assert sv.arbiter.budget == 2000
            # a job still runs on the degraded pod
            assert sv.submit(lambda: 41 + 1).result(timeout=30) == 42
        assert supervisor.active() is None    # close() took it down
    finally:
        peer1.kill()
        peer2.kill()


def test_serve_adopts_attached_supervisor(watchdir):
    """The rejoiner hands serve an EXISTING Supervisor
    (supervisor.attach's return): the server adopts it — hooks wired,
    not closed with the server (the supervisor outlives it)."""
    sup = Supervisor(backoff=0.05)
    try:
        with serve.serving(workers=1, supervise=sup) as sv:
            assert sv.supervisor is sup
            assert sup.on_pause == sv._sup_pause
        assert supervisor.active() is sup     # still running
        assert sup.on_pause is None           # hooks detached
    finally:
        sup.close()


def test_serve_reject_policy_names_supervised_recovery(watchdir):
    """During a supervised drain the reject-policy refusal names the
    recovery in progress, not a bare peer loss."""
    with serve.serving(workers=1, policy="reject") as sv:
        sv._sup_pause("rejoin ['w2b']")
        with pytest.raises(serve.AdmissionError,
                           match="supervised recovery in progress"):
            sv.submit(lambda: 1)
        sv._sup_resume({"nproc": 0, "rejoined": []})
        assert sv.submit(lambda: 1).result(timeout=30) == 1


# ---------------------------------------------------------------------
# checkpoint integrity digests
# ---------------------------------------------------------------------

def _save1(path, fp, val=3.0, slabs=2, records=24):
    checkpoint.stream_save(str(path), fp, slabs, records,
                           ([np.full(4, val, np.float32)], None))


def test_stream_save_records_digest_and_load_verifies(tmp_path):
    fp = ("fp-digest",)
    _save1(tmp_path, fp)
    meta = checkpoint._read_meta(str(tmp_path))
    assert len(meta["digest"]) == 64          # sha256 hex
    got = checkpoint.stream_load(str(tmp_path), fp)
    assert got[0] == 2 and np.array_equal(
        got[2][0][0], np.full(4, 3.0, np.float32))


def test_corrupt_seam_is_refused_pointedly(tmp_path):
    """The checkpoint.corrupt chaos seam flips bytes under the atomic
    rename; stream_load must REFUSE the shard with an error naming the
    file — never feed a corrupt accumulator into the fold."""
    fp = ("fp-rot",)
    _chaos.inject("checkpoint.corrupt", nth=1)
    try:
        _save1(tmp_path, fp)
    finally:
        _chaos.clear()
    with pytest.raises(checkpoint.CheckpointCorruptError) as ei:
        checkpoint.stream_load(str(tmp_path), fp)
    assert "stream_state" in str(ei.value)    # names the file
    assert "delete the file" in str(ei.value)


def test_truncated_state_is_refused_pointedly(tmp_path):
    fp = ("fp-trunc",)
    _save1(tmp_path, fp)
    (state,) = [p for p in os.listdir(str(tmp_path))
                if p.startswith("stream_state")]
    spath = os.path.join(str(tmp_path), state)
    with open(spath, "r+b") as f:
        f.truncate(os.path.getsize(spath) // 2)
    with pytest.raises(checkpoint.CheckpointCorruptError,
                       match="corrupt"):
        checkpoint.stream_load(str(tmp_path), fp)


def test_pre_digest_checkpoint_still_loads(tmp_path):
    """A checkpoint written before ISSUE 12 has no digest in its meta:
    it must keep loading (no forced restart on upgrade)."""
    fp = ("fp-old",)
    _save1(tmp_path, fp)
    mpath = os.path.join(str(tmp_path), "stream_meta.json")
    with open(mpath) as f:
        meta = json.load(f)
    del meta["digest"]
    with open(mpath, "w") as f:
        json.dump(meta, f)
    got = checkpoint.stream_load(str(tmp_path), fp)
    assert got is not None and got[0] == 2


def test_pod_shard_digest_validates_any_adoption(tmp_path, monkeypatch):
    """Pod partials are psum-replicated, so process 0's meta digest
    validates ANY adopted shard — and refuses a rotted one on the
    topology-remap path."""
    cell = {"pid": 0}
    monkeypatch.setattr(multihost, "process_count", lambda: 3)
    monkeypatch.setattr(multihost, "process_index", lambda: cell["pid"])
    monkeypatch.setattr(multihost, "barrier", lambda name: None)
    fp = ("fp-pod",)
    for pid in range(3):
        cell["pid"] = pid
        checkpoint.stream_save(str(tmp_path), fp, 4, 48,
                               ([np.full(4, 7.0, np.float32)], None),
                               multiprocess=True)
    # the shrunk pod adopts shards and every one passes the digest
    monkeypatch.setattr(multihost, "process_count", lambda: 2)
    for pid in (0, 1):
        cell["pid"] = pid
        got = checkpoint.stream_load(str(tmp_path), fp,
                                     multiprocess=True)
        assert got[0] == 4
    # rot ONE adopted shard: its reader refuses pointedly
    with open(os.path.join(str(tmp_path),
                           "stream_state.p1.w4.npz"), "r+b") as f:
        f.seek(max(0, os.path.getsize(f.name) // 2))
        f.write(b"\xde\xad\xbe\xef")
    cell["pid"] = 1
    with pytest.raises(checkpoint.CheckpointCorruptError):
        checkpoint.stream_load(str(tmp_path), fp, multiprocess=True)


# ---------------------------------------------------------------------
# BLT014 + the supervised recovery plan in explain()
# ---------------------------------------------------------------------

ADD1 = lambda v: v + 1  # noqa: E731 — module-level: stable fingerprint


def _iter_streamed():
    blocks = [np.zeros((4, 4), np.float32), np.zeros((4, 4), np.float32)]
    return bolt.fromiter(blocks, (8, 4), mode="tpu",
                         dtype=np.float32).map(ADD1)


def _cb_streamed():
    x = np.zeros((8, 4), np.float32)
    return bolt.fromcallback(lambda i: x[i], (8, 4), mode="tpu",
                             dtype=np.float32, chunks=4,
                             per_process=True).map(ADD1)


def _fake_pod(monkeypatch):
    monkeypatch.setattr(multihost, "mesh_process_count", lambda mesh: 2)
    monkeypatch.setattr(multihost, "slab_divisibility_error",
                        lambda *a: None)


def test_blt014_fromiter_under_supervision(watchdir, monkeypatch):
    from bolt_tpu import analysis
    arr = _iter_streamed()
    _fake_pod(monkeypatch)
    sup = Supervisor()
    try:
        rep = analysis.check(arr)
    finally:
        sup.close()
    assert rep.has("BLT014")
    d = [d for d in rep.diagnostics if d.code == "BLT014"][0]
    assert d.severity == "warning" and rep.ok
    assert "re-ingest" in d.message
    assert "per_process=True" in d.hint


def test_blt014_quiet_without_supervisor(monkeypatch):
    from bolt_tpu import analysis
    arr = _iter_streamed()
    _fake_pod(monkeypatch)
    assert not analysis.check(arr).has("BLT014")


def test_blt014_quiet_for_per_process_callback(watchdir, monkeypatch):
    from bolt_tpu import analysis
    arr = _cb_streamed()
    _fake_pod(monkeypatch)
    sup = Supervisor()
    try:
        rep = analysis.check(arr)
    finally:
        sup.close()
    assert not rep.has("BLT014")


def test_explain_shows_supervised_contract(watchdir, monkeypatch):
    from bolt_tpu import analysis
    arr = _cb_streamed()
    _fake_pod(monkeypatch)
    sup = Supervisor(retries=4, backoff=0.75)
    try:
        sup._quarantine.add("i9")
        txt = analysis.explain(arr)
    finally:
        sup.close()
    assert "SUPERVISED" in txt
    assert "4 retries" in txt and "0.75s" in txt
    assert "rejoin door" in txt and "i9" in txt
    # without a supervisor the plan stays the manual ISSUE-11 contract
    arr2 = _cb_streamed()
    txt2 = analysis.explain(arr2)
    assert "recovery plan" in txt2 and "SUPERVISED" not in txt2


def test_blt108_exempts_supervisor():
    """The recovery thread lives in a blessed BLT108 home."""
    from bolt_tpu.analysis import astlint
    assert any(e.endswith(os.path.join("parallel", "supervisor.py"))
               for e in astlint._EXEMPT["BLT108"])


def test_supervisor_spans_are_clean(watchdir, reform_recorder):
    """A full supervised recovery leaves zero open spans."""
    obs.clear()
    obs.enable()
    try:
        t = _start(watchdir, nproc=2)
        peer = _FakePeer(t, 1)
        sup = Supervisor(backoff=0.05)
        try:
            _wait(lambda: set(podwatch.alive_peers()) == {0, 1},
                  msg="2-wide pod")
            peer.kill()
            _wait(lambda: sup.stats()["reforms"] == 1, timeout=10,
                  msg="reform")
            assert sup.wait_recovered(timeout=10)
        finally:
            sup.close()
            peer.kill()
        podwatch.stop()
        assert obs.active_count() == 0
    finally:
        obs.disable()
