"""Backend-agnostic assertion suites.

Reference: ``test/generic.py`` — shared map/filter/reduce suites called from
both local and distributed test files, enforcing cross-backend API
equivalence (SURVEY §4).  Each suite takes a constructed bolt array plus the
plain numpy original and asserts parity through ``toarray()``.
"""

from operator import add

import numpy as np

from bolt_tpu.utils import allclose


def map_suite(x, b):
    """``b`` is a bolt array built from ``x`` with ``axis=(0,)``."""
    # identity
    assert allclose(b.map(lambda v: v, axis=(0,)).toarray(), x)
    # elementwise
    assert allclose(b.map(lambda v: v * 2, axis=(0,)).toarray(), x * 2)
    # value-shape-changing
    expected = np.asarray([v.sum(axis=0) for v in x])
    assert allclose(b.map(lambda v: v.sum(axis=0), axis=(0,)).toarray(), expected)
    # multiple key axes
    expected = x * 3
    assert allclose(b.map(lambda v: v * 3, axis=(0, 1)).toarray(), expected)
    # with_keys: add the first key component
    mapped = b.map(lambda kv: kv[1] + kv[0][0], axis=(0,), with_keys=True)
    expected = x + np.arange(x.shape[0]).reshape((-1,) + (1,) * (x.ndim - 1))
    assert allclose(mapped.toarray(), expected)


def filter_suite(x, b):
    # keep blocks whose mean is positive
    pred = lambda v: v.mean() > 0
    expected = np.asarray([v for v in x if v.mean() > 0])
    out = b.filter(pred, axis=(0,)).toarray()
    assert allclose(out, expected)
    # keep everything
    assert allclose(b.filter(lambda v: True, axis=(0,)).toarray(), x)
    # drop everything → shape (0, *value_shape)
    empty = b.filter(lambda v: False, axis=(0,)).toarray()
    assert empty.shape == (0,) + x.shape[1:]


def reduce_suite(x, b):
    assert allclose(b.reduce(add, axis=(0,)).toarray(), x.sum(axis=0))
    mx = b.reduce(np.maximum, axis=(0,)).toarray()
    assert allclose(mx, x.max(axis=0))
    # multi-axis reduce
    assert allclose(b.reduce(add, axis=(0, 1)).toarray(), x.sum(axis=(0, 1)))
    # keepdims
    kd = b.reduce(add, axis=(0,), keepdims=True).toarray()
    assert allclose(kd, x.sum(axis=0, keepdims=True))


# hypothesis knobs shared by the property/fuzz suites:
# BOLT_HYPOTHESIS_EXAMPLES=200 for a deep run; 25 keeps CI fast
import os

HYPOTHESIS_SETTINGS = dict(
    max_examples=int(os.environ.get("BOLT_HYPOTHESIS_EXAMPLES", "25")),
    deadline=None)
