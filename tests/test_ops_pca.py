"""Distributed PCA tests (8-device CPU mesh).

``ops.pca`` runs the reference ecosystem's PCA workload (per-chunk SVD
through Spark — BASELINE config 5) as ONE compiled SPMD program over the
sharded array; the oracle is float64 NumPy SVD."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.ops import pca


def _ref_pca(x2d, k, center=False):
    x = x2d.astype(np.float64)
    if center:
        x = x - x.mean(axis=0, keepdims=True)
    u, s, vt = np.linalg.svd(x, full_matrices=False)
    return (u[:, :k] * s[:k], vt[:k].T, s[:k])


def _assert_matches(scores, comps, svals, ref, atol=1e-4):
    rs_scores, rs_comps, rs_svals = ref
    assert np.allclose(svals, rs_svals, rtol=1e-5, atol=atol)
    got_scores = np.asarray(scores.toarray() if hasattr(scores, "toarray")
                            else scores).reshape(rs_scores.shape)
    for i in range(comps.shape[1]):
        # eigenvector sign is arbitrary but scores and components must flip
        # together: pick the sign from the component, then scores must match
        sign = np.sign(np.dot(comps[:, i], rs_comps[:, i])) or 1.0
        assert np.allclose(sign * comps[:, i], rs_comps[:, i], atol=1e-5)
        assert np.allclose(sign * got_scores[:, i], rs_scores[:, i],
                           atol=atol)


def test_pca_matches_numpy_oracle(mesh):
    rs = np.random.RandomState(0)
    x = rs.randn(64, 12)
    b = bolt.array(x, mesh, axis=(0,))
    scores, comps, svals = pca(b, k=4)
    assert scores.mode == "tpu" and scores.shape == (64, 4)
    assert scores.split == 1
    _assert_matches(scores, comps, svals, _ref_pca(x, 4))


def test_pca_centering(mesh):
    rs = np.random.RandomState(1)
    x = rs.randn(48, 6) + 5.0          # big offset: centering must matter
    b = bolt.array(x, mesh, axis=(0,))
    scores, comps, svals = pca(b, k=3, center=True)
    _assert_matches(scores, comps, svals, _ref_pca(x, 3, center=True))
    # uncentered disagrees (offset dominates the first component)
    _, _, sv_raw = pca(b, k=1)
    assert not np.allclose(sv_raw, svals[:1], rtol=1e-2)


def test_pca_multi_key_axes_keep_shape(mesh2d):
    rs = np.random.RandomState(2)
    x = rs.randn(8, 6, 5)
    b = bolt.array(x, mesh2d, axis=(0, 1))   # 48 samples over a 2-d mesh
    scores, comps, svals = pca(b, k=2)
    assert scores.shape == (8, 6, 2) and scores.split == 2
    _assert_matches(scores, comps, svals, _ref_pca(x.reshape(48, 5), 2))


def test_pca_local_oracle_mode():
    rs = np.random.RandomState(3)
    x = rs.randn(32, 7)
    b = bolt.array(x)                  # mode='local'
    scores, comps, svals = pca(b, k=3)
    assert scores.mode == "local" and scores.shape == (32, 3)
    _assert_matches(scores, comps, svals, _ref_pca(x, 3))


def test_pca_value_axes_flatten(mesh):
    # value shape (4, 3) flattens to 12 features, scores keyed as input
    rs = np.random.RandomState(4)
    x = rs.randn(40, 4, 3)
    b = bolt.array(x, mesh, axis=(0,))
    scores, comps, svals = pca(b, k=5)
    assert scores.shape == (40, 5)
    _assert_matches(scores, comps, svals, _ref_pca(x.reshape(40, 12), 5))


def test_pca_default_k_and_errors(mesh):
    rs = np.random.RandomState(5)
    b = bolt.array(rs.randn(16, 4), mesh, axis=(0,))
    scores, comps, svals = pca(b)
    assert comps.shape == (4, 4) and svals.shape == (4,)
    with pytest.raises(ValueError):
        pca(bolt.array(rs.randn(3, 8), mesh, axis=(0,)))   # n < d
    with pytest.raises(ValueError):
        pca(b, k=9)
    with pytest.raises(TypeError):
        pca(rs.randn(16, 4))                               # not a bolt array


def test_pca_local_complex_conjugates():
    # the local Gram must use the conjugate transpose: a plain x.T @ x is
    # non-Hermitian and np.linalg.eigh silently returns garbage from it
    rs = np.random.RandomState(7)
    x = rs.randn(64, 5) + 1j * rs.randn(64, 5)
    _, _, svals = pca(bolt.array(x), k=5)
    expect = np.linalg.svd(x, compute_uv=False)
    assert np.allclose(svals, expect, rtol=1e-8)


def test_pca_integer_input_widens(mesh):
    # int input must promote to float on BOTH backends (int components
    # would truncate to all zeros)
    rs = np.random.RandomState(8)
    counts = rs.poisson(20.0, size=(40, 6)).astype(np.int64)
    ref = _ref_pca(counts.astype(np.float64), 2)
    for b in (bolt.array(counts), bolt.array(counts, mesh, axis=(0,))):
        scores, comps, svals = pca(b, k=2)
        assert np.issubdtype(comps.dtype, np.floating)
        assert np.abs(comps).max() > 0.1
        _assert_matches(scores, comps, svals, ref)


def test_pca_axis_parameter_matches_across_backends(mesh):
    # axis names the sample axes (map's convention); a non-leading axis
    # aligns by swap on TPU and by moveaxis locally — same result
    rs = np.random.RandomState(9)
    x = rs.randn(6, 48, 5)
    ref = _ref_pca(np.moveaxis(x, 1, 0).reshape(48, 6 * 5), 3)
    bt = bolt.array(x, mesh, axis=(0,))
    st, ct, vt_ = pca(bt, k=3, axis=(1,))
    assert st.shape == (48, 3)
    _assert_matches(st, ct, vt_, ref)
    sl, cl, vl = pca(bolt.array(x), k=3, axis=(1,))
    assert sl.shape == (48, 3)
    _assert_matches(sl, cl, vl, ref)


def test_pca_program_cache_hits(mesh):
    # same shape/dtype/mesh/k must reuse the compiled program
    from bolt_tpu.tpu.array import _JIT_CACHE
    rs = np.random.RandomState(10)
    b = bolt.array(rs.randn(32, 4), mesh, axis=(0,))
    pca(b, k=2)
    n_after_first = len(_JIT_CACHE)
    pca(b, k=2)
    assert len(_JIT_CACHE) == n_after_first


def test_pca_composes_with_map_chain(mesh):
    # a deferred map chain fuses INTO the PCA program — correct result,
    # and the source array stays deferred (no forced materialisation)
    rs = np.random.RandomState(6)
    x = rs.randn(32, 6)
    b = bolt.array(x, mesh, axis=(0,)).map(lambda v: v * 2.0)
    assert b.deferred
    scores, comps, svals = pca(b, k=2)
    assert b.deferred
    _assert_matches(scores, comps, svals, _ref_pca(x * 2.0, 2))


def test_tallskinny_and_svdvals_integer_widen():
    # int input must come back as float principal components / singular
    # values (int would truncate components to all zeros)
    from bolt_tpu.ops import svdvals, tallskinny_pca
    rs = np.random.RandomState(11)
    counts = rs.poisson(20.0, size=(40, 6)).astype(np.int32)
    comps, svals = tallskinny_pca(counts, k=2)
    assert np.issubdtype(np.asarray(comps).dtype, np.floating)
    assert np.abs(np.asarray(comps)).max() > 0.1
    expect = np.linalg.svd(counts.astype(np.float64), compute_uv=False)
    assert np.allclose(np.asarray(svals), expect[:2], rtol=1e-6)
    sv = np.asarray(svdvals(counts))
    assert np.issubdtype(sv.dtype, np.floating)
    assert np.allclose(sv, expect, rtol=1e-6)


def test_lstsq_on_distributed_arrays(mesh):
    # regression over a sharded design matrix: one call, GSPMD distributes
    # the Gram-sized work; matches host lstsq
    from bolt_tpu.ops import lstsq
    rs = np.random.RandomState(16)
    a = rs.randn(64, 5)
    xtrue = rs.randn(5, 2)
    y = a @ xtrue + 0.01 * rs.randn(64, 2)
    ba = bolt.array(a, mesh, axis=(0,))
    by = bolt.array(y, mesh, axis=(0,))
    x = np.asarray(lstsq(ba, by))
    ref = np.linalg.lstsq(a, y, rcond=None)[0]
    assert np.allclose(x, ref, atol=1e-9)
    # vector target as a 1-d bolt array
    bv = bolt.array(y[:, 0], mesh, axis=(0,))
    xv = np.asarray(lstsq(ba, bv))
    assert xv.shape == (5,)
    assert np.allclose(xv, np.linalg.lstsq(a, y[:, 0], rcond=None)[0],
                       atol=1e-9)
    # multi-key-axis design matrix flattens records
    a3 = rs.randn(8, 8, 5)
    y3 = a3.reshape(64, 5) @ xtrue[:, 0]
    b3 = bolt.array(a3, mesh, axis=(0, 1))
    x3 = np.asarray(lstsq(b3, y3))
    assert np.allclose(x3, xtrue[:, 0], atol=1e-6)


def test_lstsq_local_bolt_arrays_match_tpu(mesh):
    # the local oracle flattens records the same way the TPU path does
    from bolt_tpu.ops import lstsq
    rs = np.random.RandomState(17)
    a3 = rs.randn(8, 8, 5)
    y = a3.reshape(64, 5) @ rs.randn(5)
    xt = np.asarray(lstsq(bolt.array(a3, mesh, axis=(0, 1)), y))
    xl = np.asarray(lstsq(bolt.array(a3.reshape(64, 5)),
                          bolt.array(y)))
    assert xt.shape == xl.shape == (5,)
    assert np.allclose(xt, xl, atol=1e-9)


def test_pca_return_mean_projects_new_data(mesh):
    # the subtracted mean comes back so NEW samples project consistently
    from bolt_tpu.ops import pca
    rs = np.random.RandomState(18)
    x = rs.randn(48, 6) + 3.0
    b = bolt.array(x, mesh, axis=(0,))
    scores, comps, svals, mu = pca(b, k=2, center=True, return_mean=True)
    assert np.allclose(mu, x.mean(axis=0), atol=1e-9)
    xnew = rs.randn(5, 6) + 3.0
    proj = (xnew - mu) @ comps
    # projecting the TRAINING data reproduces its scores
    retr = (x - mu) @ comps
    assert np.allclose(retr, np.asarray(scores.toarray()), atol=1e-8)
    assert proj.shape == (5, 2)
    # uncentered: mean returned as zeros
    _, _, _, mu0 = pca(b, k=2, return_mean=True)
    assert np.allclose(mu0, 0.0)
    # local backend agrees
    _, _, _, mul = pca(bolt.array(x), k=2, center=True, return_mean=True)
    assert np.allclose(mul, mu, atol=1e-9)


def test_pca_centering_fold_large_offset(mesh):
    # Round-4 fusion folds centering into the Gram (Gc = G - n mu mu^T),
    # which cancels when ||mu|| >> sigma: the Gram entries lose
    # ~eps_f32 * (mu/sigma)^2 of relative accuracy (measured ~1e-2 at
    # 200 sigma).  Pin that measured point so a change that degrades the
    # fold's conditioning further fails loudly.
    rs = np.random.RandomState(7)
    x = (rs.randn(96, 5) + 200.0).astype(np.float32)
    b = bolt.array(x, mesh, axis=(0,))
    scores, comps, svals = pca(b, k=2, center=True)
    rs_scores, rs_comps, rs_svals = _ref_pca(x, 2, center=True)
    assert np.allclose(svals, rs_svals, atol=5e-2)
    got = np.asarray(scores.toarray())
    for i in range(2):
        sign = np.sign(np.dot(comps[:, i], rs_comps[:, i])) or 1.0
        assert np.allclose(sign * comps[:, i], rs_comps[:, i], atol=0.1)
        assert np.allclose(sign * got[:, i], rs_scores[:, i], atol=0.2)


def test_cov_centering_fold_large_offset(mesh):
    from bolt_tpu.ops import cov
    rs = np.random.RandomState(8)
    x = (rs.randn(80, 4) + 200.0).astype(np.float32)
    b = bolt.array(x, mesh, axis=(0,))
    c = cov(b)
    ref = np.cov(x.astype(np.float64), rowvar=False)
    # fold cancellation at 200 sigma: ~eps_f32 * mu^2 / (n-1) ~ 1e-2
    assert np.allclose(c, ref, atol=3e-2)


def test_pca_tpu_complex_centered(mesh):
    # the TPU centering fold's conjugations (G - n conj(mu) mu^T and the
    # mu @ V projection offset) must match the explicitly-centred oracle:
    # a flipped conj passes every real-valued test while scrambling
    # complex spectra
    rs = np.random.RandomState(9)
    x = (rs.randn(64, 5) + 1j * rs.randn(64, 5)
         + (2.0 - 1.0j)).astype(np.complex64)
    b = bolt.array(x, mesh, axis=(0,))
    scores, comps, svals = pca(b, k=3, center=True)
    xc = x.astype(np.complex128)
    xc = xc - xc.mean(axis=0)
    expect = np.linalg.svd(xc, compute_uv=False)
    assert np.allclose(svals, expect[:3], rtol=1e-3, atol=1e-3)
    # scores must reproduce the centred projection: scores = Xc @ comps
    got = np.asarray(scores.toarray())
    assert np.allclose(got, xc @ comps, rtol=1e-3, atol=1e-3)


def test_cov_tpu_complex_centered(mesh):
    from bolt_tpu.ops import cov
    rs = np.random.RandomState(10)
    x = (rs.randn(48, 4) + 1j * rs.randn(48, 4)
         + (1.0 + 2.0j)).astype(np.complex64)
    b = bolt.array(x, mesh, axis=(0,))
    c = cov(b)
    # np.cov conjugates the SECOND factor (rowvar=False transposes)
    xd = x.astype(np.complex128)
    xc = xd - xd.mean(axis=0)
    ref = (xc.T @ np.conj(xc)) / (len(xd) - 1)
    assert np.allclose(c, ref, rtol=1e-4, atol=1e-4)


def test_cov_fold_diagonal_never_negative(mesh):
    from bolt_tpu.ops import corrcoef, cov
    # tiny variance on a huge offset: the fold's cancellation exceeds the
    # true variance (~1e-6) in f32, which without the diagonal clamp went
    # negative and NaN'd corrcoef's sqrt(diag)
    rs = np.random.RandomState(11)
    x = (rs.randn(64, 3) * 1e-3 + 30.0).astype(np.float32)
    b = bolt.array(x, mesh, axis=(0,))
    c = cov(b)
    assert (np.diag(c) >= 0).all()
    r = corrcoef(b)
    # diag clamped to 0 makes those rows NaN by convention (np.corrcoef
    # does the same for zero variance) — but no sqrt-of-negative warnings
    assert r.shape == (3, 3)
