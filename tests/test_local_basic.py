"""Local-backend (oracle) tests.

Reference test area: ``test/test_local_basic.py`` (SURVEY §4).
"""

from operator import add

import numpy as np

import bolt_tpu as bolt
from bolt_tpu.local.array import BoltArrayLocal
from bolt_tpu.utils import allclose

from tests.generic import filter_suite, map_suite, reduce_suite


def _x():
    rs = np.random.RandomState(0)
    return rs.randn(6, 4, 5)


def test_construct_and_props():
    x = _x()
    b = bolt.array(x)
    assert isinstance(b, BoltArrayLocal)
    assert b.mode == "local"
    assert b.shape == x.shape
    assert b.dtype == x.dtype
    assert allclose(b.toarray(), x)


def test_numpy_inheritance():
    x = _x()
    b = bolt.array(x)
    # the local backend inherits the full numpy surface
    assert allclose((b + 1), x + 1)
    assert allclose(b.mean(axis=(0, 1)), x.mean(axis=(0, 1)))
    assert allclose(b.std(axis=0), x.std(axis=0))
    assert allclose(b.T, x.T)


def test_map():
    x = _x()
    map_suite(x, bolt.array(x))


def test_filter():
    x = _x()
    filter_suite(x, bolt.array(x))


def test_reduce():
    x = _x()
    reduce_suite(x, bolt.array(x))


def test_map_nonleading_axis():
    x = _x()
    b = bolt.array(x)
    # mapping over axis 1: keys become axis 1, result key-leading
    out = b.map(lambda v: v.sum(), axis=(1,)).toarray()
    expected = np.asarray([x[:, i, :].sum() for i in range(x.shape[1])])
    assert allclose(out, expected)


def test_first_concatenate():
    x = _x()
    b = bolt.array(x)
    assert allclose(b.first(), x[0])
    c = b.concatenate(x, axis=0)
    assert allclose(c.toarray(), np.concatenate([x, x], axis=0))


def test_repr():
    b = bolt.array(_x())
    r = repr(b)
    assert "local" in r and "shape" in r


def test_stats():
    x = _x()
    b = bolt.array(x)
    c = b.stats()
    assert c.count() == x.shape[0]
    assert allclose(c.mean(), x.mean(axis=0))
    assert allclose(c.variance(), x.var(axis=0))
    assert allclose(c.stdev(), x.std(axis=0))
    assert allclose(c.max(), x.max(axis=0))
    assert allclose(c.min(), x.min(axis=0))
    c = b.stats(axis=(0, 1))
    assert allclose(c.mean(), x.mean(axis=(0, 1)))


def test_stats_cross_backend(mesh):
    # the same stats() contract on both backends
    x = _x()
    cl = bolt.array(x).stats()
    ct = bolt.array(x, mesh).stats()
    assert cl.count() == ct.count()
    assert allclose(cl.mean(), ct.mean())
    assert allclose(cl.variance(), ct.variance())
