"""On-chip correctness gate (VERDICT r3 next-3 / missing-4).

Every other suite runs on the virtual 8-device CPU mesh with x64 ON —
exactly the configuration production TPU never sees.  This module is a
``-m chip``-marked parity subset that runs against the REAL device with
production numerics (x64 OFF: f32/bf16, Mosaic geometry, XLA:TPU
lowering): operators, map/reduce, stats (incl. the fused-Welford pallas
geometry), filter both paths, swap, chunked/halo map, the separable
filter on both axis classes, npdispatch, indexing, and the linalg ops.

Run via the one-command driver::

    python scripts/chip_gate.py         # sets BOLT_TEST_CHIP=1, -m chip

Oracle comparisons are against numpy in float32 with f32-appropriate
tolerances — the local backend stays the semantic oracle; only the
precision envelope changes.  Off-gate (normal pytest) the module skips.
"""

import numpy as np
import pytest

import bolt_tpu as bolt
from conftest import CHIP_GATE

pytestmark = [
    pytest.mark.chip,
    pytest.mark.skipif(not CHIP_GATE,
                       reason="on-chip gate only (scripts/chip_gate.py)"),
]


@pytest.fixture(scope="module")
def cmesh():
    import jax
    devs = np.array(jax.devices())
    return jax.sharding.Mesh(devs.reshape(devs.size), ("k",))


def _x(shape=(16, 8, 128), seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def _close(got, want, rtol=1e-5, atol=1e-5):
    g = np.asarray(got.toarray() if hasattr(got, "toarray") else got)
    w = np.asarray(want)
    assert g.shape == w.shape, (g.shape, w.shape)
    if np.issubdtype(g.dtype, np.floating):
        # x64 must stay off on chip: a float64 result IS the leak this
        # gate exists to catch
        assert g.dtype == np.float32, g.dtype
    assert np.allclose(g, w, rtol=rtol, atol=atol), np.abs(g - w).max()


def test_chip_backend_is_tpu():
    import jax
    assert jax.devices()[0].platform in ("tpu", "axon", "proxy"), \
        jax.devices()
    assert not jax.config.jax_enable_x64


def test_map_sum_bit_exact_config1(cmesh):
    # BASELINE config 1 on integral-valued floats: bit-exact, the
    # north-star's acceptance condition
    b = bolt.ones((32, 16, 128), context=cmesh, dtype=np.float32)
    out = b.map(lambda v: v + 1).sum(axis=(0, 1, 2))
    assert float(np.asarray(out.toarray())) == 2.0 * 32 * 16 * 128


def test_operators_and_ufuncs(cmesh):
    x = _x()
    b = bolt.array(x, cmesh)
    _close((b + 1) * 2 - b / 2, (x + 1) * 2 - x / 2)
    # TPU's tanh lowering is ~5e-5 off numpy's — production envelope
    _close(np.tanh(b), np.tanh(x), atol=1e-4)
    _close(abs(-b), np.abs(x))
    _close((b > 0).sum(axis=(0, 1, 2)), (x > 0).sum())


def test_stats_welford_fused_geometry(cmesh):
    # minor dim 128-aligned: the pallas fused_welford kernel's geometry;
    # f32 single-pass Welford vs numpy's two-pass in f64, f32 envelope
    x = _x((64, 4, 128), seed=1)
    b = bolt.array(x, cmesh)
    st = b.stats()
    _close(st.mean(), x.mean(axis=0, dtype=np.float64).astype(np.float32),
           rtol=1e-5, atol=1e-5)
    _close(st.variance(), x.var(axis=0, dtype=np.float64).astype(np.float32),
           rtol=1e-4, atol=1e-4)
    _close(b.mean(), x.mean(axis=0, dtype=np.float64), rtol=1e-5)
    _close(b.max(), x.max(axis=0))
    _close(b.min(), x.min(axis=0))
    # unaligned minor dim: the jnp fallback path, same answers
    y = _x((64, 4, 37), seed=2)
    by = bolt.array(y, cmesh)
    _close(by.std(), y.std(axis=0, dtype=np.float64), rtol=1e-4, atol=1e-4)


def test_filter_both_paths(cmesh, monkeypatch):
    import bolt_tpu.tpu.array as mod
    x = _x((32, 4, 8), seed=3)
    keep = np.array([v.mean() > 0 for v in x])
    b = bolt.array(x, cmesh)
    # fused (pending) path
    out = b.filter(lambda v: v.mean() > 0)
    assert out.pending
    _close(out, x[keep])
    # two-phase eager path with the bucketed gather
    monkeypatch.setattr(mod, "_FILTER_FUSED_MAX_BYTES", 0)
    out2 = b.filter(lambda v: v.mean() > 0)
    assert not out2.pending
    _close(out2, x[keep])


def test_swap_and_chunked_halo_map(cmesh):
    x = _x((8, 6, 32), seed=4)
    b = bolt.array(x, cmesh)
    s = b.swap((0,), (0,))          # keys (8,) <-> first value axis (6,)
    assert s.shape == (6, 8, 32)
    _close(s, np.transpose(x, (1, 0, 2)))
    out = b.chunk(size=(3, 16), axis=(0, 1), padding=(1, 0)).map(
        lambda blk: blk * 2.0).unchunk()
    _close(out, x * 2.0)


def test_sepfilter_both_axes(cmesh):
    # the separable gaussian on a major (sublane) axis and on the minor
    # (lane) axis — the two Mosaic code paths (ops/kernels.py crossover)
    from bolt_tpu.ops import gaussian
    x = _x((4, 64, 256), seed=5)
    b = bolt.array(x, cmesh)

    def oracle(arr, sigma, axis):
        # the framework's kernel definition: normalised taps at radius
        # int(4*sigma + 0.5), zero-padded full-axis correlation (the
        # convention the CPU-mesh suite pins in test_ops_overlap)
        radius = int(4.0 * sigma + 0.5)
        g = np.exp(-0.5 * (np.arange(-radius, radius + 1) / sigma) ** 2)
        g = (g / g.sum()).astype(np.float32)
        return np.apply_along_axis(
            lambda v: np.convolve(v, g[::-1], "same"), axis, arr)

    # ops.gaussian's axis is relative to the VALUE group: value axis 0
    # is global axis 1 (major/sublane), value axis 1 is global axis 2
    # (minor/lane)
    g1 = gaussian(b, sigma=1.5, axis=(0,), size="64")     # major axis
    _close(g1, oracle(x, 1.5, 1), rtol=1e-4, atol=1e-4)
    g2 = gaussian(b, sigma=1.5, axis=(1,), size="64")     # minor axis
    _close(g2, oracle(x, 1.5, 2), rtol=1e-4, atol=1e-4)
    # sigma above the 9-tap minor crossover: the wide-kernel path
    g3 = gaussian(b, sigma=4.0, axis=(1,), size="64")
    _close(g3, oracle(x, 4.0, 2), rtol=1e-4, atol=1e-4)


def test_npdispatch_sample(cmesh):
    x = _x((16, 8, 16), seed=6)
    b = bolt.array(x, cmesh)
    _close(np.einsum("ijk,kl->ijl", b, np.ones((16, 4), np.float32)),
           np.einsum("ijk,kl->ijl", x, np.ones((16, 4), np.float32)),
           rtol=1e-4, atol=1e-4)
    _close(np.pad(b, ((0, 0), (2, 1), (0, 0)), mode="reflect"),
           np.pad(x, ((0, 0), (2, 1), (0, 0)), mode="reflect"))
    _close(np.stack([b, b], axis=1), np.stack([x, x], axis=1))
    _close(np.sort(b, axis=2), np.sort(x, axis=2))
    _close(np.quantile(b, [0.25, 0.75]),
           np.quantile(x, [0.25, 0.75]).astype(np.float32), rtol=1e-5)
    m = _x((64, 6), seed=7)
    _close(np.cov(bolt.array(m, cmesh)), np.cov(m).astype(np.float32),
           rtol=1e-3, atol=1e-3)


def test_indexing_and_set(cmesh):
    x = _x((16, 8, 16), seed=8)
    b = bolt.array(x, cmesh)
    _close(b[2:9, [0, 5]], x[2:9][:, [0, 5]])
    _close(b[[3, 1], :, [2, 4]],
           x[np.ix_([3, 1], range(8), [2, 4])])     # orthogonal advanced
    _close(b.set(0, -1.0).toarray()[0], np.full((8, 16), -1.0, np.float32))


def test_linalg_ops(cmesh):
    from bolt_tpu.ops import pca, topk, segment_reduce
    x = _x((4096, 8), seed=9)
    b = bolt.array(x, cmesh)
    _, comps, svals = pca(b, k=3, center=True)
    xc = (x - x.mean(0)).astype(np.float64)
    ref = np.linalg.svd(xc, compute_uv=False)[:3]
    assert np.allclose(svals, ref, rtol=1e-3)
    v, i = topk(bolt.array(_x((256,), seed=10), cmesh), 5)
    ref_i = np.argsort(-_x((256,), seed=10))[:5]
    assert np.array_equal(np.asarray(i), ref_i)
    labels = np.arange(64) % 4
    sr = segment_reduce(bolt.array(_x((64, 16), seed=11), cmesh),
                        labels, num_segments=4, op="sum")
    expect = np.zeros((4, 16), np.float32)
    xx = _x((64, 16), seed=11)
    for lab, row in zip(labels, xx):
        expect[lab] += row
    _close(sr, expect, rtol=1e-4, atol=1e-4)


def test_fft_on_chip(cmesh):
    # device-side complex compute; this environment's tunnel cannot
    # TRANSFER complex buffers (raw-jax limitation, STATUS.md), so the
    # gate fetches real/imag views and real-valued roundtrips
    x = _x((8, 4, 128), seed=13)
    b = bolt.array(x, cmesh)
    g = np.fft.rfft(b)
    e = np.fft.rfft(x)
    _close(g.real, e.real.astype(np.float32), rtol=1e-3, atol=1e-3)
    _close(g.imag, e.imag.astype(np.float32), rtol=1e-3, atol=1e-3)
    back = np.fft.irfft(np.fft.rfft(b), n=128)
    _close(back, x, rtol=1e-4, atol=1e-4)


def test_dtype_policy_x64_off(cmesh):
    # production numerics: float64 requests canonicalise to f32 silently
    b = bolt.array(np.random.RandomState(12).randn(8, 4), cmesh)
    assert b.dtype == np.float32
    assert b.sum().dtype == np.float32
    assert b.astype(np.float64).dtype == np.float32
