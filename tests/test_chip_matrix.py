"""On-chip parity MATRIX (VERDICT r4 missing-2 / next-1).

The 12-test smoke gate (tests/test_chip.py) touched ~1% of the parity
surface on real hardware; this module re-runs the big enumerated
suites — the inherited-ndarray method matrix and a representative slice
of the ``__array_function__`` dispatch table — plus a deterministic
fuzz profile and the precision-policy envelope, against the REAL TPU
with production numerics (x64 OFF, Mosaic lowering).

Chip adaptations, deliberately minimal so the suites stay the same
code paths as the CPU-mesh run (SURVEY §4's "same code paths" idiom):

- inputs cast to production widths (f64→f32, i64→i32, c128→c64) BEFORE
  both backends see them, so the local numpy oracle computes in the
  same width the chip does;
- dtype parity is asserted through jax's canonicalization (the local
  backend keeps f64 results where numpy promotes; the chip answer must
  be the canonical narrow twin, never a silent f64);
- complex results fetch as .real/.imag pairs — this environment's
  attach tunnel cannot transfer complex buffers (raw-jax UNIMPLEMENTED);
- f32-appropriate tolerances.

Run via ``python scripts/chip_gate.py`` (sets BOLT_TEST_CHIP=1, -m
chip).  Off-gate the module skips.
"""

import numpy as np
import pytest

import bolt_tpu as bolt
from conftest import CHIP_GATE

pytestmark = [
    pytest.mark.chip,
    pytest.mark.skipif(not CHIP_GATE,
                       reason="on-chip gate only (scripts/chip_gate.py)"),
]


@pytest.fixture(scope="module")
def cmesh():
    import jax
    devs = np.array(jax.devices())
    return jax.sharding.Mesh(devs.reshape(devs.size), ("k",))


def _narrow(x):
    """Production-width twin of a host array."""
    x = np.asarray(x)
    if x.dtype == np.float64:
        return x.astype(np.float32)
    if x.dtype == np.int64:
        return x.astype(np.int32)
    if x.dtype == np.complex128:
        return x.astype(np.complex64)
    return x


def _fetch(v):
    """Host ndarray of a result; complex device arrays come back as
    real/imag pairs (tunnel limitation, see module docstring)."""
    if hasattr(v, "toarray"):
        if np.issubdtype(np.dtype(v.dtype), np.complexfloating) \
                and v.mode == "tpu":
            re = np.asarray(v.real.toarray())
            im = np.asarray(v.imag.toarray())
            return re + 1j * im
        return np.asarray(v.toarray())
    return np.asarray(v)


def _same(name, lo, tp, rtol=3e-4, atol=3e-5):
    if isinstance(lo, (tuple, list)):
        assert isinstance(tp, (tuple, list)) and len(lo) == len(tp), name
        for a, b in zip(lo, tp):
            _same(name, a, b, rtol, atol)
        return
    a, b = _fetch(lo), _fetch(tp)
    assert a.shape == b.shape, (name, a.shape, b.shape)
    import jax.dtypes
    assert jax.dtypes.canonicalize_dtype(a.dtype) == \
        jax.dtypes.canonicalize_dtype(b.dtype), (name, a.dtype, b.dtype)
    assert np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True), \
        (name, np.abs(np.asarray(a, np.float64) - np.asarray(b, np.float64)
                      ).max() if a.dtype.kind in "fc" else (a, b))


def _run(fn, b):
    try:
        return ("ok", fn(b))
    except Exception as exc:                      # noqa: BLE001
        return ("err", type(exc))


# ----------------------------------------------------------------------
# 1. the inherited-ndarray METHOD matrix, production widths
# ----------------------------------------------------------------------

from test_ndarray_methods import CASES as METHOD_CASES  # noqa: E402


@pytest.mark.parametrize("name,make,fn", METHOD_CASES,
                         ids=[c[0] for c in METHOD_CASES])
def test_chip_method_matrix(cmesh, name, make, fn):
    x = _narrow(make())
    lo_status, lo = _run(fn, bolt.array(x.copy()))
    tp_status, tp = _run(fn, bolt.array(x.copy(), cmesh))
    assert lo_status == tp_status, (name, lo, tp)
    if lo_status == "err":
        assert lo is tp or issubclass(tp, lo) or issubclass(lo, tp), \
            (name, lo, tp)
    else:
        _same(name, lo, tp)


# ----------------------------------------------------------------------
# 2. the __array_function__ dispatch slice: every case list the
#    CPU-mesh suite enumerates, production widths
# ----------------------------------------------------------------------

from test_array_function import (  # noqa: E402
    DEVICE_CASES, TAIL2_CASES, TAIL2_CLEAN, TAIL9_CASES, TAIL_CASES)

_NP_CASES = ([("dev:" + n, c) for n, c in DEVICE_CASES]
             + [("tail:" + n, c) for n, c in TAIL_CASES]
             + [("tail2:" + n, c) for n, c in TAIL2_CASES + TAIL2_CLEAN]
             + [("tail9:" + n, c) for n, c in TAIL9_CASES])


def _np_x(name):
    rs = np.random.RandomState(41)
    if name.startswith("tail2:nan"):
        x = rs.randn(8, 6, 4)
        x.ravel()[::17] = np.nan
        return x.astype(np.float32)
    if name.startswith(("tail:", "tail2:", "tail9:")):
        return rs.randn(8, 6, 4).astype(np.float32)
    return np.random.RandomState(31).randn(16, 6, 4).astype(np.float32)


@pytest.mark.parametrize("name,call", _NP_CASES,
                         ids=[c[0] for c in _NP_CASES])
def test_chip_npdispatch_matrix(cmesh, name, call):
    x = _np_x(name)
    b = bolt.array(x, cmesh)
    lo_status, lo = _run(call, x)
    tp_status, tp = _run(call, b)
    assert lo_status == tp_status, (name, lo, tp)
    if lo_status == "err":
        assert lo is tp or issubclass(tp, lo) or issubclass(lo, tp), \
            (name, lo, tp)
        return
    # quantile/median-class reductions promote to f64 on the numpy side
    # only; values must still agree at f32 precision
    _same(name, lo, tp)


# ----------------------------------------------------------------------
# 3. deterministic fuzz profile: fixed op chains through the SAME op
#    implementations the hypothesis fuzzer draws from
# ----------------------------------------------------------------------

_CHAINS = [
    # portable surface only: the local oracle has no swap (reference-
    # faithful) and restricts take_along_axis's fancy indexing
    # explicit axes throughout — the backends' reduction DEFAULTS differ
    # by design (API.md's axis-default caveat)
    ("affine-swapaxes-sum", lambda b: (b * 2.0 + 1.0).swapaxes(1, 2)
     .sum(axis=(0, 1, 2))),
    ("ufunc-clip-mean", lambda b: np.tanh(b).clip(-0.5, 0.5)
     .mean(axis=(0, 1, 2))),
    ("filter-std", lambda b: b.filter(lambda v: v.mean() > 0)
     .std(axis=(0,))),
    ("accumulate-take", lambda b: np.add.accumulate(
        np.take(b, [0, 2], axis=1), axis=2)),
    # partition's within-partition order is unspecified — exact-compare
    # the deterministic sort family only
    ("sortfam", lambda b: np.sort(np.flip(b, 2), axis=2)),
    ("delete-matmul", lambda b: np.delete(b, 1, axis=2) @ np.ones(
        (3, 2), np.float32)),
    ("chunked-smooth", lambda b: _ops().smooth(
        b, 3, axis=(0,)).var(axis=(0,))),
    ("segment-mean", lambda b: _ops().segment_reduce(
        b, np.arange(8) % 3, num_segments=3, op="mean")),
]


def _ops():
    from bolt_tpu import ops
    return ops


@pytest.mark.parametrize("name,chain", _CHAINS,
                         ids=[c[0] for c in _CHAINS])
def test_chip_fuzz_profile(cmesh, name, chain):
    x = np.random.RandomState(51).randn(8, 6, 4).astype(np.float32)
    lo_status, lo = _run(chain, bolt.array(x))
    tp_status, tp = _run(chain, bolt.array(x, cmesh))
    assert lo_status == tp_status == "ok", (name, lo, tp)
    _same(name, lo, tp, rtol=1e-3, atol=1e-4)


# ----------------------------------------------------------------------
# 4. precision-policy envelope on real hardware: the scoped "default"
#    mode must stay inside its documented ~1e-2 relative envelope and
#    the pinned default must stay f32-tight
# ----------------------------------------------------------------------

def test_chip_precision_policy_envelope(cmesh):
    from bolt_tpu.ops import pca
    rs = np.random.RandomState(52)
    x = rs.randn(2048, 64).astype(np.float32)
    b = bolt.array(x, cmesh)
    _, c_hi, v_hi = pca(b, k=4)
    _, c_np, v_np = pca(bolt.array(x), k=4)
    assert np.allclose(v_hi, v_np, rtol=1e-4)          # pinned: tight
    with bolt.precision("default"):
        _, c_lo, v_lo = pca(b, k=4)
    assert np.allclose(v_lo, v_hi, rtol=5e-2)          # documented trade
    w = rs.randn(64, 16).astype(np.float32)
    hi = np.asarray((b @ w).toarray())
    with bolt.precision("default"):
        lo = np.asarray((b @ w).toarray())
    ref = x @ w
    assert np.abs(hi - ref).max() <= np.abs(lo - ref).max() + 1e-4
    # bf16's absolute error scales with the summands (row norm ~ sqrt(d))
    # rather than the result, which can cancel to ~0 — the envelope is
    # relative to the DATA scale
    assert np.abs(lo - ref).max() <= 5e-2 * np.abs(ref).max()
