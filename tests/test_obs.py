"""bolt_tpu.obs: structured tracing, metrics registry, timeline export.

The PR 4 observability subsystem, tested at its four contracts:

* the TRACER — nested spans, explicit cross-thread parent handoff
  (the streaming prefetch thread's ingest spans parent under the main
  thread's run span), instant events, and near-zero disabled cost (the
  ring stays empty, ``begin`` returns ``None``, no open-span leaks);
* the METRICS registry — typed counters/gauges/log2-bucket histograms,
  lock-consistent counter groups, and the migration invariant:
  ``profile.engine_counters()`` returns the SAME keys/types as before,
  now backed by the registry's ``"engine"`` group;
* the EXPORTERS — Chrome trace-event JSON that reloads with balanced,
  properly nested B/E pairs, the ``obs.report()`` text tree, and the
  ``obs.timeline(path)`` arm-run-write scope;
* the PROFILE satellites — ``timeit`` on pytree outputs + ``iters``
  validation, ``memory_stats`` degraded shape, ``overlap_efficiency``/
  ``engine_report`` empty-counter edges.
"""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

import jax

import bolt_tpu as bolt
from bolt_tpu import engine, obs, profile
from bolt_tpu.obs import metrics as obs_metrics

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _tracer_reset():
    """Every test leaves the process tracer exactly as tier-1 expects:
    disarmed, empty ring, zero active spans."""
    obs.disable()
    obs.clear()
    yield
    obs.disable()
    obs.clear()


# ----------------------------------------------------------------------
# tracer: span API
# ----------------------------------------------------------------------

def test_span_nesting_and_attrs():
    obs.enable()
    with obs.span("outer", kind="test") as sp:
        sp.set(extra=1)
        with obs.span("inner"):
            pass
    got = obs.spans()
    assert [s.name for s in got] == ["inner", "outer"]  # completion order
    inner, outer = got
    assert inner.pid == outer.sid and outer.pid == 0
    assert outer.attrs == {"kind": "test", "extra": 1}
    assert inner.duration is not None and outer.duration >= inner.duration
    assert obs.active_count() == 0


def test_span_decorator_and_event():
    obs.enable()

    @obs.span("decorated", tag="d")
    def work(n):
        obs.event("mark", n=n)
        return n * 2

    assert work(21) == 42
    names = [s.name for s in obs.spans()]
    assert names == ["mark", "decorated"]
    mark = obs.spans()[0]
    assert mark.kind == "I" and mark.attrs == {"n": 21}
    assert mark.pid == obs.spans()[1].sid       # event nests in the span


def test_span_error_attr_and_no_leak():
    obs.enable()
    with pytest.raises(ValueError):
        with obs.span("boom"):
            raise ValueError("x")
    (sp,) = obs.spans()
    assert sp.attrs["error"] == "ValueError"
    assert obs.active_count() == 0


def test_begin_end_cancel_and_ring_bound():
    obs.enable(ring=4)
    sp = obs.begin("probe")
    obs.cancel(sp)                              # abandoned: never lands
    assert obs.spans() == [] and obs.active_count() == 0
    for i in range(10):
        obs.end(obs.begin("s%d" % i))
    got = obs.spans()
    assert len(got) == 4                        # bounded ring, oldest gone
    assert [s.name for s in got] == ["s6", "s7", "s8", "s9"]


def test_disabled_tracer_is_inert_no_ring_growth(mesh):
    """The acceptance edge: tracing DISABLED, the instrumented hot paths
    (engine get/dispatch, terminals, a streamed reduction) must leave
    the ring empty and no span open — counter-only cost."""
    assert not obs.enabled()
    assert obs.begin("anything") is None        # no allocation path
    obs.end(None)                               # and end tolerates it
    x = np.arange(8 * 6, dtype=np.float64).reshape(8, 6)
    bolt.array(x, mesh).map(lambda v: v + 1).sum().toarray()
    src = bolt.fromcallback(lambda idx: x[idx], x.shape, mesh,
                            dtype=np.float64, chunks=2)
    src.sum().toarray()
    assert obs.spans() == []
    assert obs.active_count() == 0


def test_explicit_cross_thread_parent_handoff():
    obs.enable()
    with obs.span("root"):
        parent = obs.current()
        assert parent is not None and parent.name == "root"
        done = threading.Event()

        def worker():
            with obs.span("child", parent=parent):
                pass
            done.set()

        th = threading.Thread(target=worker)
        th.start()
        assert done.wait(10)
        th.join()
    child = [s for s in obs.spans() if s.name == "child"][0]
    root = [s for s in obs.spans() if s.name == "root"][0]
    assert child.pid == root.sid
    assert child.tid != root.tid


# ----------------------------------------------------------------------
# tracer x streaming executor: parenting + overlap evidence
# ----------------------------------------------------------------------

def _slow_blocks(x, nblocks, delay):
    for blk in np.array_split(x, nblocks):
        time.sleep(delay)
        yield blk


def test_stream_prefetch_thread_spans_parent_under_run(mesh):
    """The tentpole wiring: a streamed ``fromiter(...).sum()`` yields a
    real timeline — ingest spans recorded BY THE PREFETCH THREAD parent
    under the main thread's ``stream.run`` span (explicit context
    handoff), and their wall-clock intervals overlap the main thread's
    per-slab compute spans (ingest hidden behind compute — the span
    twin of ``overlap_efficiency() > 0``)."""
    x = np.arange(32 * 4 * 8, dtype=np.float64).reshape(32, 4, 8)
    obs.enable()
    got = bolt.fromiter(_slow_blocks(x, 8, 0.004), x.shape, mesh,
                        dtype=np.float64).sum()
    assert np.allclose(np.asarray(got.toarray()), x.sum(axis=0))
    sp = obs.spans()
    runs = [s for s in sp if s.name == "stream.run"]
    ingest = [s for s in sp if s.name == "stream.ingest"]
    compute = [s for s in sp if s.name == "stream.compute"]
    assert len(runs) == 1 and len(ingest) == 8 and len(compute) == 8
    run = runs[0]
    assert run.attrs["terminal"] == "sum" and run.attrs["slabs"] == 8
    # parenting crossed the thread boundary by explicit handoff
    assert all(s.pid == run.sid for s in ingest)
    assert all(s.tid != run.tid for s in ingest)
    assert all(s.tname == "bolt-stream-prefetch" for s in ingest)
    # compute stays on the run's own thread, nested under it
    assert all(s.pid == run.sid and s.tid == run.tid for s in compute)
    # every span closed inside the run's interval
    assert obs.active_count() == 0
    assert all(run.t0 <= s.t0 and s.t1 <= run.t1 + 1e-9
               for s in ingest + compute)
    # wall-clock overlap: some slab's ingest ran WHILE another computed
    overlapped = any(i.t0 < c.t1 and c.t0 < i.t1
                     for i in ingest for c in compute)
    assert overlapped, "double buffering left no ingest/compute overlap"
    # transfers nest under their ingest span with byte attribution
    transfers = [s for s in sp if s.name == "stream.transfer"]
    ingest_ids = {s.sid for s in ingest}
    assert transfers and all(t.pid in ingest_ids for t in transfers)
    assert sum(t.attrs["bytes"] for t in transfers) == x.nbytes


def test_stream_fault_leaves_no_open_spans(mesh):
    obs.enable()

    def bad_blocks():
        yield np.ones((4, 8), np.float64)
        raise RuntimeError("mid-stream failure")

    src = bolt.fromiter(bad_blocks(), (8, 8), mesh, dtype=np.float64)
    with pytest.raises(RuntimeError, match="mid-stream failure"):
        src.sum().cache()                  # the read streams (lazy)
    assert obs.active_count() == 0


# ----------------------------------------------------------------------
# metrics registry
# ----------------------------------------------------------------------

def test_counter_gauge_types_and_reset():
    reg = obs_metrics.Registry()
    c = reg.counter("calls")
    f = reg.counter("seconds", initial=0.0)
    g = reg.gauge("depth")
    c.inc()
    c.inc(4)
    f.inc(0.25)
    g.set(3)
    g.high_water(7)
    g.high_water(2)
    assert c.value == 5 and isinstance(c.value, int)
    assert f.value == 0.25 and isinstance(f.value, float)
    assert g.value == 7
    assert reg.counter("calls") is c            # get-or-create
    reg.reset()
    assert c.value == 0 and f.value == 0.0 and g.value == 0


def test_histogram_log2_buckets():
    reg = obs_metrics.Registry()
    h = reg.histogram("lat", lo=-4, hi=4)
    for v in (0.0, 0.01, 0.3, 1.0, 1.9, 6.0, 1000.0):
        h.observe(v)
    assert h.count == 7
    assert abs(h.sum - 1009.21) < 1e-9
    buckets = h.buckets()
    assert len(buckets) == (4 - (-4)) + 2
    by_bound = dict(buckets)
    assert by_bound[float(2 ** -4)] == 2        # 0.0 and 0.01 underflow
    assert by_bound[0.5] == 1                   # 0.3 in [0.25, 0.5)
    assert by_bound[2.0] == 2                   # 1.0 and 1.9 in [1, 2)
    assert by_bound[8.0] == 1                   # 6.0 in [4, 8)
    assert by_bound[float("inf")] == 1          # 1000.0 overflow
    snap = h.snapshot()
    assert snap["count"] == 7 and sum(snap["counts"]) == 7


def test_counter_group_update_is_atomic_against_snapshots():
    reg = obs_metrics.Registry()
    grp = reg.group("g", {"a": 0, "b": 0})
    stop = threading.Event()
    torn = []

    def reader():
        while not stop.is_set():
            s = grp.snapshot()
            if s["a"] != s["b"]:
                torn.append(s)
                return

    th = threading.Thread(target=reader)
    th.start()
    for _ in range(3000):
        grp.update(a=1, b=1)
    stop.set()
    th.join()
    assert not torn
    assert grp.snapshot() == {"a": 3000, "b": 3000}
    grp.update(_maxima={"a": 10})               # high-water: no-op here
    assert grp["a"] == 3000


def test_obs_modules_are_stdlib_only():
    """trace/metrics load standalone by path, with NO bolt_tpu/jax
    import — the same property astlint relies on for instant CLI
    startup."""
    for name in ("trace", "metrics"):
        path = os.path.join(REPO, "bolt_tpu", "obs", "%s.py" % name)
        spec = importlib.util.spec_from_file_location("obs_" + name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)            # raises on non-stdlib deps
        src = open(path).read()
        assert "import jax" not in src and "import numpy" not in src


# ----------------------------------------------------------------------
# migration invariant: engine counters unchanged, registry-backed
# ----------------------------------------------------------------------

_EXPECTED_ENGINE_KEYS = {
    # (key, is_float)
    "hits": False, "misses": False, "aot_compiles": False,
    "lower_seconds": True, "compile_seconds": True,
    "dispatches": False, "dispatch_seconds": True, "fallbacks": False,
    "donations": False, "persistent_hits": False,
    "persistent_misses": False, "persistent_warm_hits": False,
    "diagnostics": False,
    "strict_checks": False, "strict_rejections": False,
    "transfer_bytes": False, "transfer_seconds": True,
    "stream_chunks": False, "stream_ingest_seconds": True,
    "stream_compute_seconds": True, "stream_wall_seconds": True,
    "stream_overlap_seconds": True, "stream_prefetch_depth": False,
    "stream_upload_threads": False, "stream_inflight_high_water": False,
    "stream_retries": False, "stream_resumes": False,
    "checkpoint_bytes": False, "checkpoint_seconds": True,
    "fused_stat_groups": False, "fused_stat_terminals": False,
    "coalesced_builds": False, "coalesced_compiles": False,
    "batched_dispatches": False, "batched_requests": False,
    "codec_encode_seconds": True, "codec_bytes_raw": False,
    "codec_bytes_wire": False,
    "shuffle_bytes": False, "spill_bytes": False,
    "shuffle_seconds": True,
}


def test_engine_counters_snapshot_unchanged_post_migration(mesh):
    """The regression gate for the registry migration: identical key
    set, identical int/float types, snapshot-not-live-view semantics,
    and the values ARE the registry's ``engine`` group."""
    bolt.ones((8, 4), mesh).map(lambda v: v * 2).sum().toarray()
    c = profile.engine_counters()
    assert set(c) == set(_EXPECTED_ENGINE_KEYS)
    for k, is_float in _EXPECTED_ENGINE_KEYS.items():
        if is_float:
            assert isinstance(c[k], float), (k, type(c[k]))
        else:
            assert isinstance(c[k], int) and not isinstance(c[k], bool), \
                (k, type(c[k]))
    assert c["dispatches"] > 0 and c["misses"] > 0
    # a snapshot, not a live view
    c["dispatches"] += 10 ** 6
    assert engine.counters()["dispatches"] != c["dispatches"]
    # backed by the obs registry: same numbers through the other door
    reg = obs.registry().snapshot()
    for k in _EXPECTED_ENGINE_KEYS:
        assert reg["engine.%s" % k] == engine.counters()[k], k
    # and the group is THE store, not a copy: an increment lands in both
    d0 = engine.counters()["dispatches"]
    bolt.ones((8, 4), mesh).sum().toarray()
    assert obs.registry().snapshot()["engine.dispatches"] \
        == engine.counters()["dispatches"] >= d0 + 1


def test_dispatch_histogram_rides_along(mesh):
    h = obs.registry().get("engine.dispatch_seconds.hist")
    n0 = h.count
    bolt.ones((8, 3), mesh).map(lambda v: v + 5).sum().toarray()
    assert h.count > n0                         # every dispatch observed
    assert h.sum >= 0.0


# ----------------------------------------------------------------------
# exporters
# ----------------------------------------------------------------------

def test_chrome_export_round_trip_pairs_b_e_events(tmp_path, mesh):
    """Exported JSON reloads, and per thread the B/E events balance with
    stack discipline (every E matches the innermost open B's name)."""
    path = str(tmp_path / "trace.json")
    x = np.arange(16 * 6, dtype=np.float64).reshape(16, 6)
    with obs.timeline(path):
        bolt.array(x, mesh).map(lambda v: v * 3).sum().toarray()
        src = bolt.fromcallback(lambda idx: x[idx], x.shape, mesh,
                                dtype=np.float64, chunks=4)
        src.map(lambda v: v + 1).sum().toarray()
    doc = json.load(open(path))
    evs = doc["traceEvents"]
    assert evs, "empty timeline"
    stacks = {}
    pairs = 0
    for e in evs:
        if e.get("ph") == "B":
            stacks.setdefault(e["tid"], []).append(e)
        elif e.get("ph") == "E":
            st = stacks.get(e["tid"])
            assert st, "E without open B on tid %s" % e["tid"]
            b = st.pop()
            assert b["name"] == e["name"], (b["name"], e["name"])
            assert e["ts"] >= b["ts"]
            pairs += 1
    assert all(not st for st in stacks.values()), "unbalanced B events"
    assert pairs >= 10
    names = {e["name"] for e in evs}
    assert {"stream.run", "stream.ingest", "stream.compute",
            "engine.dispatch"} <= names
    # thread metadata rides along for the viewer's track labels
    assert any(e.get("ph") == "M" and e.get("name") == "thread_name"
               for e in evs)


def test_timeline_restores_disarmed_state_and_writes_on_error(tmp_path):
    path = str(tmp_path / "fail.json")
    assert not obs.enabled()
    with pytest.raises(RuntimeError):
        with obs.timeline(path):
            with obs.span("doomed"):
                pass
            raise RuntimeError("body failed")
    assert not obs.enabled()                    # restored
    doc = json.load(open(path))                 # file written anyway
    assert any(e.get("name") == "doomed" for e in doc["traceEvents"])


def test_report_tree_aggregates(mesh):
    obs.enable()
    bolt.ones((8, 4), mesh).map(lambda v: v + 2).sum().toarray()
    txt = obs.report()
    assert "span" in txt and "total_s" in txt
    assert "array.stat" in txt and "engine.dispatch" in txt
    obs.disable()
    obs.clear()
    assert "no spans recorded" in obs.report()


# ----------------------------------------------------------------------
# profile satellites
# ----------------------------------------------------------------------

def test_timeit_blocks_on_pytree_outputs(mesh):
    b = bolt.ones((8, 4), mesh)

    def fn():
        return {"s": b.sum()._data, "pair": (b.mean()._data, 3.5)}

    result, secs = profile.timeit(fn, iters=2, warmup=1)
    assert secs > 0
    assert np.allclose(np.asarray(result["s"]), np.full(4, 8.0))
    assert result["pair"][1] == 3.5             # non-array leaf survives


def test_timeit_rejects_bad_iters():
    with pytest.raises(ValueError, match="iters >= 1"):
        profile.timeit(lambda: 1, iters=0)
    with pytest.raises(ValueError, match="iters >= 1"):
        profile.timeit(lambda: 1, iters=-3)


def test_overlap_efficiency_empty_and_partial_counters():
    assert profile.overlap_efficiency({}) == 0.0
    assert profile.overlap_efficiency(
        {"stream_ingest_seconds": 0.0, "stream_overlap_seconds": 0.0}) \
        == 0.0
    assert profile.overlap_efficiency({"stream_ingest_seconds": 2.0,
                                       "stream_overlap_seconds": 1.0}) \
        == 0.5
    # a fresh-process shaped dict with keys missing entirely
    assert profile.overlap_efficiency({"hits": 3}) == 0.0


def test_engine_report_no_activity_edge():
    assert "(no engine activity)" in profile.engine_report({})
    zeros = {k: (0.0 if f else 0)
             for k, f in _EXPECTED_ENGINE_KEYS.items()}
    assert "(no engine activity)" in profile.engine_report(zeros)
    live = dict(zeros, dispatches=3, dispatch_seconds=0.5)
    txt = profile.engine_report(live)
    assert "dispatches" in txt and "0.5000" in txt


def test_memory_stats_degrades_to_empty_dict():
    class NoStats:
        pass                                    # no memory_stats at all

    assert profile.memory_stats(NoStats()) == {}

    class RaisesStats:
        def memory_stats(self):
            raise NotImplementedError

    assert profile.memory_stats(RaisesStats()) == {}

    class NoneStats:
        def memory_stats(self):
            return None

    assert profile.memory_stats(NoneStats()) == {}
    s = profile.memory_stats()                  # whatever this backend has
    assert isinstance(s, dict)


# ----------------------------------------------------------------------
# BLT106: the timing-bookkeeping lint rule
# ----------------------------------------------------------------------

@pytest.mark.lint
def test_lint_blt106_perf_counter_outside_obs():
    from bolt_tpu.analysis import astlint
    src = ("import time\n"
           "def f():\n"
           "    t0 = time.perf_counter()\n"
           "    return time.perf_counter() - t0\n")
    found = astlint.lint_source(src, "bolt_tpu/somewhere.py")
    assert [x.code for x in found] == ["BLT106", "BLT106"]
    # renamed plain import must not dodge the rule
    aliased = ("import time as _t\n"
               "x = _t.perf_counter()\n")
    assert [x.code for x in astlint.lint_source(
        aliased, "bolt_tpu/somewhere.py")] == ["BLT106"]
    # from-import form
    frm = ("from time import perf_counter\n"
           "x = perf_counter()\n")
    assert [x.code for x in astlint.lint_source(
        frm, "bolt_tpu/somewhere.py")] == ["BLT106"]
    # the owners are exempt: obs/ (directory-wide) and profile.py
    assert astlint.lint_source(src, "bolt_tpu/obs/trace.py") == []
    assert astlint.lint_source(src, "bolt_tpu/profile.py") == []
    # a directory merely CONTAINING the letters must not inherit it
    assert [x.code for x in astlint.lint_source(
        src, "bolt_tpu/jobs/thing.py")] == ["BLT106", "BLT106"]
    # the sanctioned route is clean
    ok = ("from bolt_tpu.obs.trace import clock\n"
          "def f():\n"
          "    t0 = clock()\n"
          "    return clock() - t0\n")
    assert astlint.lint_source(ok, "bolt_tpu/somewhere.py") == []
    assert "BLT106" in astlint.RULES
