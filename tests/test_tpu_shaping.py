"""TPU-backend shaping: transpose/reshape/squeeze/swap over axis
permutations, with key/value boundary guards (reference area:
``test/test_spark_shaping.py`` — brute-force enumeration over permutations,
SURVEY §4; BASELINE config 3)."""

from itertools import permutations

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.utils import allclose


def _x(shape=(4, 2, 3, 2)):
    rs = np.random.RandomState(5)
    return rs.randn(*shape)


def test_transpose_within_groups(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    # all group-respecting permutations of a (2 key, 2 value) array
    for kperm in permutations(range(2)):
        for vperm in permutations(range(2)):
            perm = tuple(kperm) + tuple(v + 2 for v in vperm)
            out = b.transpose(*perm)
            assert out.split == 2
            assert allclose(out.toarray(), np.transpose(x, perm))


def test_transpose_guard(mesh):
    b = bolt.array(_x(), mesh, axis=(0, 1))
    with pytest.raises(ValueError):
        b.transpose(0, 2, 1, 3)  # crosses the key/value boundary
    with pytest.raises(ValueError):
        b.transpose(0, 0, 1, 2)  # not a permutation


def test_T(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    assert allclose(b.T.toarray(), np.transpose(x, (1, 0, 3, 2)))


def test_swapaxes(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    assert allclose(b.swapaxes(2, 3).toarray(), x.swapaxes(2, 3))
    assert allclose(b.swapaxes(0, 1).toarray(), x.swapaxes(0, 1))


def test_reshape_within_groups(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))  # keys (4,2), values (3,2)
    out = b.reshape(8, 3, 2)
    assert out.split == 1
    assert allclose(out.toarray(), x.reshape(8, 3, 2))
    out = b.reshape(4, 2, 6)
    assert out.split == 2
    assert allclose(out.toarray(), x.reshape(4, 2, 6))
    out = b.reshape((2, 2, 2, 6))
    assert out.split == 3
    assert allclose(out.toarray(), x.reshape(2, 2, 2, 6))


def test_reshape_guards(mesh):
    b = bolt.array(_x(), mesh, axis=(0, 1))
    with pytest.raises(ValueError):
        b.reshape(4, 2, 3, 3)  # wrong size
    with pytest.raises(ValueError):
        b.reshape(3, 16)  # crosses the key/value boundary (8 keys)


def test_squeeze(mesh):
    x = _x((4, 1, 3, 1))
    b = bolt.array(x, mesh, axis=(0, 1))
    out = b.squeeze()
    assert out.shape == (4, 3)
    assert out.split == 1
    assert allclose(out.toarray(), x.squeeze())
    out = b.squeeze(axis=(3,))
    assert out.shape == (4, 1, 3)
    assert out.split == 2
    with pytest.raises(ValueError):
        b.squeeze(axis=(0,))  # size 4, not squeezable


def test_swap_roundtrip(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    s = b.swap((0,), (0,))
    # new keys = (key1,) + (value0,); new values = (key0,) + (value1,)
    assert s.split == 2
    assert s.shape == (2, 3, 4, 2)
    assert allclose(s.toarray(), np.transpose(x, (1, 2, 0, 3)))


def test_swap_all_keys_out_guard(mesh):
    b = bolt.array(_x(), mesh, axis=(0,))
    with pytest.raises(ValueError):
        b.swap((0,), ())


def test_swap_all_values_in(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0,))
    # nothing leaves the keys, every value axis joins them: layout unchanged
    s = b.swap((), (0, 1, 2))
    assert s.split == 4
    assert allclose(s.toarray(), x)
    # move key 0 out and every value in: values lead, old key trails
    s = b.swap((0,), (0, 1, 2))
    assert s.split == 3
    assert allclose(s.toarray(), np.transpose(x, (1, 2, 3, 0)))


def test_swap_validation(mesh):
    b = bolt.array(_x(), mesh, axis=(0, 1))
    with pytest.raises(ValueError):
        b.swap((5,), ())
    with pytest.raises(ValueError):
        b.swap((), (7,))
    with pytest.raises(ValueError):
        b.swap((0, 0), ())


def test_swap_enumerated_4d(mesh):
    """Brute-force: every single-key/single-value swap of a 4D array
    (the reference's enumeration-style shaping tests)."""
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    for ka in range(2):
        for va in range(2):
            s = b.swap((ka,), (va,))
            keys_rest = [k for k in range(2) if k != ka]
            perm = keys_rest + [2 + va] + [ka] + [2 + v for v in range(2) if v != va]
            assert allclose(s.toarray(), np.transpose(x, perm))
            assert s.split == 2
            # roundtrip restores values via the inverse swap
            back = s.swap((s.split - 1,), (0,))
            assert back.shape[0] in (2, 4)


def test_keys_values_views(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    assert b.keys.shape == (4, 2)
    assert b.values.shape == (3, 2)
    out = b.keys.reshape(8)
    assert out.shape == (8, 3, 2)
    assert out.split == 1
    assert allclose(out.toarray(), x.reshape(8, 3, 2))
    out = b.values.reshape(6)
    assert out.shape == (4, 2, 6)
    assert allclose(out.toarray(), x.reshape(4, 2, 6))
    out = b.keys.transpose(1, 0)
    assert allclose(out.toarray(), np.transpose(x, (1, 0, 2, 3)))
    out = b.values.transpose(1, 0)
    assert allclose(out.toarray(), np.transpose(x, (0, 1, 3, 2)))
    with pytest.raises(ValueError):
        b.keys.reshape(7)
    with pytest.raises(ValueError):
        b.values.transpose(0, 2)
    assert "keys" in repr(b.keys) and "values" in repr(b.values)
