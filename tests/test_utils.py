"""Unit tests for ``bolt_tpu/utils.py`` (reference test area:
``test/test_utils``-style direct unit coverage, SURVEY §4)."""

import os

import numpy as np
import pytest

from bolt_tpu.utils import (allclose, argpack, inshape, isreshapeable,
                            istransposeable, iterexpand, listify, prod,
                            slicify, tupleize)


def test_tupleize():
    assert tupleize(1) == (1,)
    assert tupleize((1, 2)) == (1, 2)
    assert tupleize([1, 2]) == (1, 2)
    assert tupleize(range(3)) == (0, 1, 2)
    assert tupleize(((1, 2),)) == (1, 2)
    assert tupleize(None) is None


def test_listify():
    assert listify(1) == [1]
    assert listify((1, 2)) == [1, 2]


def test_argpack():
    assert argpack((1, 2, 3)) == (1, 2, 3)
    assert argpack(((1, 2, 3),)) == (1, 2, 3)
    assert argpack(([1, 2],)) == (1, 2)


def test_inshape():
    inshape((2, 3, 4), (0, 2))
    with pytest.raises(ValueError):
        inshape((2, 3), (2,))
    with pytest.raises(ValueError):
        inshape((2, 3), (-1,))


def test_iterexpand():
    assert iterexpand(2, 3) == (2, 2, 2)
    assert iterexpand((1, 2), 2) == (1, 2)
    with pytest.raises(ValueError):
        iterexpand((1, 2), 3)


def test_slicify():
    assert slicify(slice(None), 5) == slice(0, 5, 1)
    assert slicify(slice(1, None), 5) == slice(1, 5, 1)
    assert slicify(2, 5) == slice(2, 3, 1)
    assert slicify(-1, 5) == slice(4, 5, 1)
    assert list(slicify([1, -1], 5)) == [1, 4]
    assert list(slicify(np.array([True, False, True]), 3)) == [0, 2]
    with pytest.raises(IndexError):
        slicify(5, 5)
    with pytest.raises(IndexError):
        slicify([5], 5)


def test_transposeable_reshapeable():
    assert istransposeable((1, 0), (0, 1))
    assert not istransposeable((0, 2), (0, 1))
    assert isreshapeable((6,), (2, 3))
    assert not isreshapeable((7,), (2, 3))


def test_get_kv_shape_axes():
    from bolt_tpu.utils import get_kv_axes, get_kv_shape
    assert get_kv_axes((2, 3, 4), (0,)) == ((0,), (1, 2))
    assert get_kv_axes((2, 3, 4), (1, 2)) == ((1, 2), (0,))
    assert get_kv_shape((2, 3, 4), (0,)) == ((2,), (3, 4))
    assert get_kv_shape((2, 3, 4), (2, 0)) == ((2, 4), (3,))
    with pytest.raises(ValueError):
        get_kv_shape((2, 3), (5,))


def test_allclose_and_prod():
    assert allclose(np.ones(3), np.ones(3))
    assert not allclose(np.ones(3), np.ones(4))
    assert not allclose(np.ones(3), np.zeros(3))
    assert prod((2, 3, 4)) == 24
    assert prod(()) == 1


def test_version_matches_packaging():
    # VERDICT r3 weak-1: __init__.__version__ drifted from pyproject once
    # (0.2.0 vs 0.3.0); lock them together so a bump touches both or fails.
    import re

    import bolt_tpu

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "pyproject.toml")) as f:
        m = re.search(r'^version = "([^"]+)"', f.read(), re.M)
    assert m, "pyproject.toml lost its version line"
    assert bolt_tpu.__version__ == m.group(1)
