"""median_filter (scipy.ndimage oracle) and the per-record series
transforms detrend/zscore/center — backend parity + independent oracles
(the reference ecosystem's TimeSeries workloads)."""

import numpy as np
import pytest
import scipy.ndimage as ndi
import scipy.signal

import bolt_tpu as bolt
from bolt_tpu.ops import (center, crosscorr, detrend, gaussian,
                          median_filter, zscore)
from bolt_tpu.utils import allclose


def _x(shape=(3, 20, 6)):
    rs = np.random.RandomState(31)
    return rs.randn(*shape)


def test_median_filter_scipy_parity(mesh):
    x = _x()
    lout = median_filter(bolt.array(x), 3, axis=(0,), size=(6,)).toarray()
    tout = median_filter(bolt.array(x, mesh), 3, axis=(0,),
                         size=(6,)).toarray()
    assert allclose(lout, tout)
    expect = np.stack([ndi.median_filter(r, size=(3, 1), mode="reflect")
                       for r in x])
    assert allclose(lout, expect)


def test_median_filter_2d_window(mesh):
    # joint rectangular window (median is not separable)
    x = _x((2, 12, 10))
    lout = median_filter(bolt.array(x), (3, 5), axis=(0, 1),
                         size=(6, 5)).toarray()
    tout = median_filter(bolt.array(x, mesh), (3, 5), axis=(0, 1),
                         size=(6, 5)).toarray()
    assert allclose(lout, tout)
    expect = np.stack([ndi.median_filter(r, size=(3, 5), mode="reflect")
                       for r in x])
    assert allclose(lout, expect)
    with pytest.raises(ValueError):
        median_filter(bolt.array(x), 2)


def test_gaussian_scipy_parity():
    # scipy is present in this image: gaussian taps match ndimage's.
    # np 'reflect' == scipy 'mirror'; scipy's name is accepted as alias
    x = _x((2, 64, 4))
    out = gaussian(bolt.array(x), 2.0, axis=(0,), mode="reflect").toarray()
    expect = np.stack([ndi.gaussian_filter1d(r, 2.0, axis=0, mode="mirror")
                       for r in x])
    assert allclose(out, expect, rtol=1e-6, atol=1e-8)
    alias = gaussian(bolt.array(x), 2.0, axis=(0,), mode="mirror").toarray()
    assert allclose(alias, expect, rtol=1e-6, atol=1e-8)
    near = gaussian(bolt.array(x), 1.0, axis=(0,), mode="nearest").toarray()
    expect_n = np.stack([ndi.gaussian_filter1d(r, 1.0, axis=0,
                                               mode="nearest") for r in x])
    assert allclose(near, expect_n, rtol=1e-6, atol=1e-8)


def test_detrend_parity(mesh):
    x = _x()
    lout = detrend(bolt.array(x), order=1, axis=0).toarray()
    tout = detrend(bolt.array(x, mesh), order=1, axis=0).toarray()
    assert allclose(lout, tout, rtol=1e-6)
    # scipy.signal.detrend removes the linear least-squares trend
    expect = scipy.signal.detrend(x, axis=1, type="linear")
    assert allclose(lout, expect, rtol=1e-6, atol=1e-8)
    # order=0 == mean removal == scipy type='constant'
    l0 = detrend(bolt.array(x), order=0).toarray()
    assert allclose(l0, scipy.signal.detrend(x, axis=1, type="constant"),
                    rtol=1e-8)
    # quadratic trend is removed exactly
    t = np.linspace(-1, 1, 20)
    quad = 3.0 * t ** 2 + 2.0 * t - 1.0
    y = x + quad[None, :, None]
    l2 = detrend(bolt.array(y), order=2).toarray()
    t2 = detrend(bolt.array(y, mesh), order=2).toarray()
    assert allclose(l2, t2, rtol=1e-6)
    assert allclose(l2, detrend(bolt.array(x), order=2).toarray(), rtol=1e-6)
    # integer input promotes to float instead of truncating the
    # projector to zeros
    xi = (np.arange(40) ** 2).reshape(2, 20)
    di = detrend(bolt.array(xi), order=1).toarray()
    assert np.issubdtype(di.dtype, np.floating)
    assert allclose(di, scipy.signal.detrend(xi.astype(float), axis=1),
                    rtol=1e-8)
    with pytest.raises(ValueError):
        detrend(bolt.array(x), order=-1)
    with pytest.raises(ValueError):
        detrend(bolt.array(x), order=25)   # length 20 axis
    with pytest.raises(ValueError):
        detrend(bolt.array(x), axis=7)


def test_detrend_fuses(mesh):
    # detrend is a deferred map: chaining into an action is one program
    x = _x()
    out = detrend(bolt.array(x, mesh).map(lambda v: v * 2.0)).sum(axis=(0,))
    expect = scipy.signal.detrend(x * 2.0, axis=1).sum(axis=0)
    assert allclose(out.toarray(), expect, rtol=1e-6, atol=1e-7)


def test_zscore_center_parity(mesh):
    x = _x()
    for ddof in (0, 1):
        lz = zscore(bolt.array(x), axis=0, ddof=ddof).toarray()
        tz = zscore(bolt.array(x, mesh), axis=0, ddof=ddof).toarray()
        assert allclose(lz, tz, rtol=1e-6)
        mu = x.mean(axis=1, keepdims=True)
        sd = x.std(axis=1, ddof=ddof, keepdims=True)
        assert allclose(lz, (x - mu) / sd, rtol=1e-8)
    lc = center(bolt.array(x), axis=1).toarray()
    tc = center(bolt.array(x, mesh), axis=1).toarray()
    assert allclose(lc, tc, rtol=1e-8)
    assert allclose(lc, x - x.mean(axis=2, keepdims=True), rtol=1e-8)
    # epsilon guards constant records
    const = np.ones((2, 5))
    z = zscore(bolt.array(const), epsilon=1e-6).toarray()
    assert np.allclose(z, 0.0)


def _pearson(a, b):
    return np.corrcoef(a, b)[0, 1]


def test_crosscorr_parity(mesh):
    rs = np.random.RandomState(5)
    x = rs.randn(6, 30)
    sig = rs.randn(30)
    lout = crosscorr(bolt.array(x), sig, lag=3).toarray()
    tout = crosscorr(bolt.array(x, mesh), sig, lag=3).toarray()
    assert lout.shape == (6, 7)
    assert allclose(lout, tout, rtol=1e-6)
    # independent oracle: pearson r over the overlapping window per lag
    for i in range(6):
        for j, k in enumerate(range(-3, 4)):
            if k >= 0:
                r = _pearson(x[i, k:], sig[:30 - k])
            else:
                r = _pearson(x[i, :30 + k], sig[-k:])
            assert np.isclose(lout[i, j], r, rtol=1e-8), (i, k)
    # lag=0 is each record's plain correlation with the signal
    l0 = crosscorr(bolt.array(x), sig).toarray()
    assert l0.shape == (6, 1)
    assert np.isclose(l0[2, 0], _pearson(x[2], sig), rtol=1e-10)
    # a record equal to the shifted signal peaks at that shift
    y = np.stack([np.r_[sig[2:], np.zeros(2)]])   # y[t] = sig[t+2]
    peak = crosscorr(bolt.array(y), sig, lag=3).toarray()[0]
    assert np.argmax(peak) == 1                   # k = -2 -> index 1
    assert peak[1] > 0.99


def test_crosscorr_epsilon_guard():
    # constant records: 0/0 without the guard; 0 with it
    sig = np.random.RandomState(1).randn(10)
    z = crosscorr(bolt.array(np.ones((2, 10))), sig, epsilon=1e-9).toarray()
    assert np.isfinite(z).all() and np.allclose(z, 0.0)


def test_crosscorr_validation():
    x = np.random.randn(3, 10)
    with pytest.raises(ValueError):
        crosscorr(bolt.array(x), np.zeros(7))     # wrong length
    with pytest.raises(ValueError):
        crosscorr(bolt.array(x), np.zeros(10), lag=-1)
    with pytest.raises(ValueError):
        crosscorr(bolt.array(x), np.zeros(10), lag=10)
    with pytest.raises(ValueError):
        # lag = L-1 leaves a single-sample overlap: Pearson undefined
        crosscorr(bolt.array(x), np.zeros(10), lag=9)


def test_crosscorr_multiaxis(mesh):
    # time on value axis 0, channels on value axis 1: correlation
    # computed per channel, axis replaced by the lag dimension
    rs = np.random.RandomState(9)
    x = rs.randn(4, 20, 3)
    sig = rs.randn(20)
    lout = crosscorr(bolt.array(x), sig, lag=2, axis=0).toarray()
    tout = crosscorr(bolt.array(x, mesh), sig, lag=2, axis=0).toarray()
    assert lout.shape == (4, 5, 3)
    assert allclose(lout, tout, rtol=1e-6)
    assert np.isclose(lout[1, 2, 0], _pearson(x[1, :, 0], sig), rtol=1e-8)


def test_fourier_parity(mesh):
    # records built from known sinusoids: coherence peaks at their bin
    T = 64
    t = np.arange(T)
    rs = np.random.RandomState(17)
    phase_in = 0.7
    x = np.stack([
        np.sin(2 * np.pi * 4 * t / T + phase_in),          # pure bin 4
        np.sin(2 * np.pi * 4 * t / T) + rs.randn(T) * 0.1,  # noisy bin 4
        rs.randn(T),                                        # noise
    ])
    from bolt_tpu.ops import fourier
    lcoh, lph = fourier(bolt.array(x), freq=4)
    tcoh, tph = fourier(bolt.array(x, mesh), freq=4)
    assert lcoh.shape == (3,) and lph.shape == (3,)
    assert allclose(lcoh.toarray(), tcoh.toarray(), rtol=1e-6)
    assert allclose(lph.toarray(), tph.toarray(), rtol=1e-5, atol=1e-6)
    lc = np.asarray(lcoh.toarray())
    assert np.isclose(lc[0], 1.0, atol=1e-9)       # pure tone: all energy
    assert lc[1] > 0.8 > lc[2]
    # phase convention: sin(wt + p) -> rfft angle p - pi/2
    assert np.isclose(np.asarray(lph.toarray())[0],
                      phase_in - np.pi / 2, atol=1e-9)
    # oracle for the noise record
    co = np.fft.rfft(x[2] - x[2].mean())
    expect = np.abs(co[4]) / np.sqrt(np.sum(np.abs(co[1:]) ** 2))
    assert np.isclose(lc[2], expect, rtol=1e-10)
    with pytest.raises(ValueError):
        fourier(bolt.array(x), freq=0)
    with pytest.raises(ValueError):
        fourier(bolt.array(x), freq=T)
    # constant records: epsilon guards the 0/0
    c, p = fourier(bolt.array(np.ones((2, 16))), freq=2, epsilon=1e-9)
    assert np.isfinite(c.toarray()).all()
    # deferral contract: fourier outputs are still deferred maps on the
    # TPU backend (nothing materialised yet) and fuse downstream
    tb2 = bolt.array(x, mesh)
    c2, _ = fourier(tb2, freq=4)
    assert c2.deferred
    assert allclose(c2.map(lambda v: v * 2, axis=(0,)).toarray(),
                    np.asarray(lcoh.toarray()) * 2)


def test_normalize_parity(mesh):
    from bolt_tpu.ops import normalize
    rs = np.random.RandomState(23)
    x = rs.rand(5, 40) + 0.5                    # positive baselines
    lout = normalize(bolt.array(x), perc=20).toarray()
    tout = normalize(bolt.array(x, mesh), perc=20).toarray()
    assert allclose(lout, tout, rtol=1e-6)
    base = np.percentile(x, 20, axis=1, keepdims=True)
    assert allclose(lout, (x - base) / base, rtol=1e-8)
    lm = normalize(bolt.array(x), baseline="mean").toarray()
    mu = x.mean(axis=1, keepdims=True)
    assert allclose(lm, (x - mu) / mu, rtol=1e-8)
    # epsilon guards zero baselines
    z = normalize(bolt.array(np.zeros((2, 8))), epsilon=1e-9).toarray()
    assert np.isfinite(z).all()
    # ... and NEGATIVE baselines (sign-aware: the guard must push the
    # denominator away from zero, not across it)
    xn = np.array([[-1e-6, -1e-6, -1e-6, 1.0]])
    zn = normalize(bolt.array(xn), perc=20, epsilon=1e-6).toarray()
    assert np.isfinite(zn).all()
    with pytest.raises(ValueError):
        normalize(bolt.array(x), baseline="windowed")
    with pytest.raises(ValueError):
        normalize(bolt.array(x), perc=150)


def test_series_transforms_differentiable():
    # the block functions are pure jnp pipelines: grads flow through them
    # for users embedding these transforms in larger differentiable models
    import jax
    import jax.numpy as jnp
    from bolt_tpu.ops.series import _detrend_fn, _zscore_fn

    x = jnp.asarray(np.random.RandomState(2).randn(20))
    det = _detrend_fn(20, 1, 0)
    g = jax.grad(lambda v: jnp.sum(det(v) ** 2))(x)
    # analytic: d/dv ||R v||^2 = 2 R^T R v = 2 R v (projector: R^T R = R)
    t = np.linspace(-1, 1, 20)
    a = np.vander(t, 2, increasing=True)
    r = np.eye(20) - a @ np.linalg.pinv(a)
    assert np.allclose(np.asarray(g), 2 * r @ np.asarray(x), atol=1e-10)

    zs = _zscore_fn(0, 0, 1e-9)
    gz = jax.grad(lambda v: jnp.sum(zs(v) ** 2))(x)
    assert np.isfinite(np.asarray(gz)).all()
