"""toarray(out=) and iter_shards (VERDICT r2 weak-6): bounding the HOST
RAM side of the collect — out= writes shard-wise into a caller buffer
(e.g. a memmap), iter_shards skips assembly entirely."""

import numpy as np
import pytest

import bolt_tpu as bolt


def _x():
    return np.random.RandomState(50).randn(16, 6, 4)


def test_toarray_out_both_backends(mesh):
    x = _x()
    for b in (bolt.array(x), bolt.array(x, mesh)):
        out = np.empty_like(x)
        got = b.toarray(out=out)
        assert got is out
        assert np.array_equal(out, x), b.mode


def test_toarray_out_memmap(mesh, tmp_path):
    x = _x()
    b = bolt.array(x, mesh)
    mm = np.lib.format.open_memmap(
        str(tmp_path / "out.npy"), mode="w+", dtype=x.dtype, shape=x.shape)
    got = b.toarray(out=mm)
    assert got is mm
    mm.flush()
    back = np.load(str(tmp_path / "out.npy"))
    assert np.array_equal(back, x)


def test_toarray_out_validation(mesh):
    x = _x()
    for b in (bolt.array(x), bolt.array(x, mesh)):
        with pytest.raises(ValueError, match="shape"):
            b.toarray(out=np.empty((3, 3)))
        with pytest.raises(ValueError, match="cast"):
            b.toarray(out=np.empty(x.shape, np.float32))


def test_toarray_out_materialises_chain_and_pending(mesh):
    x = _x()
    m = bolt.array(x, mesh).map(lambda v: v * 2)
    out = np.empty_like(x)
    m.toarray(out=out)
    assert np.allclose(out, x * 2)
    f = bolt.array(x, mesh).filter(lambda v: v.mean() > 0)
    keep = x[x.mean(axis=(1, 2)) > 0]
    out2 = np.empty_like(keep)
    f.toarray(out=out2)
    assert np.allclose(out2, keep)


def test_iter_shards_blocks_never_alias(mesh):
    # blocks are COPIES on both backends: mutating one must not corrupt
    # the source array (r3 review finding: the local view aliased)
    x = _x()
    for b in (bolt.array(x.copy()), bolt.array(x, mesh)):
        for _, block in b.iter_shards():
            block *= 0.0
        assert np.allclose(np.asarray(b.toarray()), x), b.mode


def test_iter_shards_covers_array(mesh):
    x = _x()
    for b in (bolt.array(x), bolt.array(x, mesh)):
        seen = np.full(x.shape, np.nan)
        total = 0
        for index, block in b.iter_shards():
            seen[index] = block
            total += block.size
        assert np.allclose(seen, x), b.mode     # union covers everything
        assert total == x.size                  # single-process: no overlap
    # the TPU shards are genuinely partial (8-way mesh splits axis 0)
    blocks = [blk for _, blk in bolt.array(x, mesh).iter_shards()]
    assert len(blocks) == 8
    assert all(blk.shape == (2, 6, 4) for blk in blocks)
    # a deferred chain materialises through the iterator
    m = bolt.array(x, mesh).map(lambda v: v + 1)
    seen = np.empty_like(x)
    for index, block in m.iter_shards():
        seen[index] = block
    assert np.allclose(seen, x + 1)
