"""Communication-lowering contract tests (SURVEY §2.5): the compiled HLO of
each distributed operation must contain the collective the design maps it
to — ``swap`` → ``all-to-all`` (the reference's cluster shuffle), Welford
``stats`` → ``all-reduce`` (the reference's ``rdd.aggregate`` tree), halo
exchange → ``collective-permute``.  Inspecting the framework's own cached
compiled programs guards the contract against regressions in how GSPMD
chooses collectives."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import bolt_tpu as bolt
from bolt_tpu._compat import shard_map as _shard_map


def _hlo_of_cached(kind, arg):
    """Compiled HLO text of the framework's most recent cached jit program
    whose cache key starts with ``kind``."""
    from bolt_tpu.tpu import array as array_mod
    fns = [v for k, v in array_mod._JIT_CACHE.items() if k[0] == kind]
    assert fns, "no cached %r program" % kind
    return fns[-1].lower(arg).compile().as_text()


def test_swap_lowers_to_all_to_all(mesh):
    # out key axis (16) divides the 8-device mesh: GSPMD must use the
    # bandwidth-optimal all_to_all, not an all-gather
    x = np.random.RandomState(0).randn(8, 16, 6)
    b = bolt.array(x, mesh)
    s = b.swap((0,), (0,))
    assert s.split == 1
    txt = _hlo_of_cached("swap", b._data)
    assert "all-to-all" in txt
    assert "all-gather" not in txt


def test_swap_nondivisible_still_avoids_full_gather(mesh):
    # out key axis (4) does not divide 8 devices: key_sharding replicates,
    # which costs an all-gather — allowed, but the result must be correct
    x = np.random.RandomState(1).randn(8, 4, 6)
    s = bolt.array(x, mesh).swap((0,), (0,))
    assert np.allclose(s.toarray(), np.transpose(x, (1, 0, 2)))


def test_welford_stats_lowers_to_all_reduce(mesh):
    x = np.random.RandomState(2).randn(16, 4, 6)
    b = bolt.array(x, mesh)
    b.stats()  # populates the shared executable cache
    from bolt_tpu.tpu.array import _JIT_CACHE
    fns = [v for k, v in _JIT_CACHE.items() if k[0] == "welford"]
    assert fns
    txt = fns[-1].lower(b._data).compile().as_text()
    assert "all-reduce" in txt          # psum/pmax/pmin over the mesh axis


def test_halo_exchange_lowers_to_collective_permute(mesh):
    from jax.sharding import NamedSharding
    from bolt_tpu.parallel.halo import exchange_halo

    x = jnp.asarray(np.random.RandomState(3).randn(16, 4))
    sh = jax.device_put(x, NamedSharding(mesh, P("k")))
    f = _shard_map(lambda d: exchange_halo(d, axis=0, pad=1, axis_name="k"),
                      mesh=mesh, in_specs=P("k"), out_specs=P("k"))
    txt = jax.jit(f).lower(sh).compile().as_text()
    assert "collective-permute" in txt


def test_key_reduction_lowers_to_all_reduce(mesh):
    # sum over the sharded key axis: GSPMD inserts the psum tree
    x = np.random.RandomState(4).randn(16, 4, 6)
    b = bolt.array(x, mesh)
    s = b.sum(axis=(0,))
    assert np.allclose(np.asarray(s.toarray()), x.sum(axis=0))
    txt = _hlo_of_cached("stat", b._data)
    assert "all-reduce" in txt


def test_sharded_smooth_lowers_to_neighbour_collective(mesh2d):
    # sequence-parallel filtering: the value axis is mesh-split, so the
    # halo each block borrows must ride an inserted neighbour collective
    # (collective-permute, or all-to-all/all-gather if GSPMD so chooses) —
    # NOT a host round-trip, and the program must communicate
    from bolt_tpu.ops import smooth
    x = np.random.RandomState(5).randn(4, 16, 3)
    b = bolt.array(x, mesh2d, axis=(0,))
    out = smooth(b, 5, axis=(0,), size=(4,), shard={0: "b"})
    oracle = smooth(bolt.array(x), 5, axis=(0,), size=(4,))
    assert np.allclose(out.toarray(), oracle.toarray())
    txt = _hlo_of_cached("chunk-map-g", b._data)
    assert ("collective-permute" in txt or "all-to-all" in txt
            or "all-gather" in txt), "no inter-device halo communication"


# ---------------------------------------------------------------------
# round-2 op families (VERDICT r2 #6): on a sharded input none of these
# may lower to a FULL all-gather of the operand — scatter/sort + the
# collective the design maps them to.  `unique` is the documented
# exception below.
# ---------------------------------------------------------------------


def test_segment_reduce_lowers_to_scatter_all_reduce(mesh):
    import jax.numpy as jnp
    from bolt_tpu.ops import segment_reduce
    from bolt_tpu.tpu import array as array_mod
    x = np.random.RandomState(7).randn(64, 32)
    b = bolt.array(x, mesh)
    labels = np.arange(64) % 5
    out = segment_reduce(b, labels, op="sum")
    assert out.shape == (5, 32)
    fns = [v for k, v in array_mod._JIT_CACHE.items() if k[0] == "segreduce"]
    txt = fns[-1].lower(b._data, jnp.asarray(labels, jnp.int32)) \
        .compile().as_text()
    assert "scatter" in txt             # the segment combine
    assert "all-reduce" in txt          # cross-shard group merge
    assert "all-gather" not in txt      # operand never replicates


def test_take_on_sharded_axis_avoids_full_gather(mesh):
    import jax.numpy as jnp
    from bolt_tpu.tpu import array as array_mod
    x = np.random.RandomState(8).randn(64, 32)
    b = bolt.array(x, mesh)
    out = b.take([3, 1, 9], axis=0)     # gather along the SHARDED axis
    assert np.allclose(out.toarray(), x[[3, 1, 9]])
    fns = [v for k, v in array_mod._JIT_CACHE.items() if k[0] == "take"]
    txt = fns[-1].lower(b._data, jnp.asarray([3, 1, 9], jnp.int32)) \
        .compile().as_text()
    assert "all-gather" not in txt      # masked-sum gather, not replication
    assert "all-reduce" in txt


def test_argsort_along_sharded_axis_uses_all_to_all(mesh):
    from bolt_tpu.tpu import array as array_mod
    x = np.random.RandomState(9).randn(64, 32)
    b = bolt.array(x, mesh)
    out = b.argsort(axis=0, kind="stable")   # global sort ALONG the shards
    assert np.array_equal(np.asarray(out.toarray()),
                          x.argsort(axis=0, kind="stable"))
    fns = [v for k, v in array_mod._JIT_CACHE.items() if k[0] == "argsort"]
    txt = fns[-1].lower(b._data).compile().as_text()
    assert "all-to-all" in txt          # distributed sort exchange
    assert "all-gather" not in txt      # never the full operand


def test_value_axis_sort_argsort_are_collective_free(mesh):
    from bolt_tpu.tpu import array as array_mod
    x = np.random.RandomState(10).randn(64, 32)
    b = bolt.array(x, mesh)
    b.argsort(axis=1)
    c = bolt.array(x, mesh)
    c.sort(axis=1)
    for kind in ("argsort", "sort"):
        fns = [v for k, v in array_mod._JIT_CACHE.items() if k[0] == kind]
        txt = fns[-1].lower(b._data).compile().as_text()
        for coll in ("all-gather", "all-to-all", "all-reduce",
                     "collective-permute"):
            assert coll not in txt, (kind, coll)   # rows are shard-local


def test_topk_is_collective_free_on_value_axis(mesh):
    # lax.top_k all-gathers a sharded operand (measured); the argsort
    # formulation partitions cleanly — rows are shard-local, so top-k
    # along a value axis needs NO communication at all
    from bolt_tpu.ops import topk
    from bolt_tpu.tpu import array as array_mod
    x = np.random.RandomState(11).randn(64, 32)
    b = bolt.array(x, mesh)
    v, i = topk(b, 3, axis=1)
    lv, li = topk(bolt.array(x), 3, axis=1)
    assert np.allclose(np.asarray(v.toarray()), np.asarray(lv.toarray()))
    assert np.array_equal(np.asarray(i.toarray()), np.asarray(li.toarray()))
    fns = [v_ for k, v_ in array_mod._JIT_CACHE.items() if k[0] == "topk"]
    txt = fns[-1].lower(b._data).compile().as_text()
    for coll in ("all-gather", "all-to-all", "all-reduce",
                 "collective-permute"):
        assert coll not in txt, coll


def test_topk_on_sharded_axis_avoids_full_gather(mesh):
    from bolt_tpu.ops import topk
    from bolt_tpu.tpu import array as array_mod
    x = np.random.RandomState(12).randn(64, 32)
    b = bolt.array(x, mesh)
    v, i = topk(b, 3, axis=0)          # selection ALONG the shards
    lv, li = topk(bolt.array(x), 3, axis=0)
    assert np.allclose(np.asarray(v.toarray()), np.asarray(lv.toarray()))
    assert np.array_equal(np.asarray(i.toarray()), np.asarray(li.toarray()))
    fns = [v_ for k, v_ in array_mod._JIT_CACHE.items() if k[0] == "topk"]
    txt = fns[-1].lower(b._data).compile().as_text()
    assert "all-gather" not in txt      # all-to-all sort, not replication


def test_bincount_lowers_to_all_reduce_no_gather(mesh):
    from bolt_tpu.ops import bincount
    from bolt_tpu.tpu import array as array_mod
    x = np.random.RandomState(13).randint(0, 9, size=(64, 8))
    b = bolt.array(x, mesh)
    assert np.array_equal(bincount(b), np.bincount(x.ravel()))
    fns = [v for k, v in array_mod._JIT_CACHE.items() if k[0] == "bincount"]
    txt = fns[-1].lower(b._data).compile().as_text()
    assert "all-reduce" in txt
    assert "all-gather" not in txt


def test_unique_shard_local_is_collective_free(mesh):
    # round-3: unique on a sharded input runs SHARD-LOCAL (per-shard
    # sort/mask/gather via shard_map + exact host merge) — zero
    # collectives, where GSPMD's global 1-d sort would all-gather the
    # whole operand onto every device (measured; constraints and (n,1)
    # reshapes don't help).  Layouts the shard-local gate declines
    # (replicated dims, uneven splits, multi-process) fall back to the
    # whole-array program, whose global-sort gather remains the one
    # documented exception.
    from bolt_tpu.ops import unique
    from bolt_tpu.tpu import array as array_mod
    x = np.random.RandomState(14).randint(0, 7, size=(64, 4)).astype(float)
    b = bolt.array(x, mesh)
    assert np.array_equal(unique(b), np.unique(x))
    for kind in ("unique-shard-sort", "unique-shard-gather"):
        fns = [(k, v) for k, v in array_mod._JIT_CACHE.items()
               if k[0] == kind]
        assert fns, kind
    (k1, f1) = [(k, v) for k, v in array_mod._JIT_CACHE.items()
                if k[0] == "unique-shard-sort"][-1]
    txt = f1.lower(b._data).compile().as_text()
    assert "sort" in txt
    for coll in ("all-gather", "all-to-all", "all-reduce",
                 "collective-permute"):
        assert coll not in txt, coll


def test_quantile_lowers_to_sorted_collective_program(mesh):
    # a key-axis quantile over the sharded axis must sort on device and
    # combine across shards (GSPMD inserts the gather/reduce it needs)
    x = np.random.RandomState(6).randn(16, 6)
    b = bolt.array(x, mesh)
    out = b.quantile(0.5)
    assert np.allclose(out.toarray(), np.median(x, axis=0))
    from bolt_tpu.tpu import array as array_mod
    fns = [v for k, v in array_mod._JIT_CACHE.items() if k[0] == "quantile"]
    assert fns
    txt = fns[-1].lower(b._data, 0.5).compile().as_text()  # q is an ARG
    assert "sort" in txt
