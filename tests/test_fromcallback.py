"""Sharded data-loader constructor (``fromcallback``): each device shard
is produced by one callback call on its global index range — the
streaming replacement for the reference's driver-side ``sc.parallelize``
scatter (which needs the full array in driver memory first)."""

import numpy as np
import pytest

import bolt_tpu as bolt


def _oracle(shape):
    return np.arange(np.prod(shape), dtype=np.float64).reshape(shape)


def test_fromcallback_matches_oracle(mesh):
    full = _oracle((16, 5, 3))
    calls = []

    def loader(index):
        calls.append(index)
        return full[index]

    b = bolt.fromcallback(loader, (16, 5, 3), mesh, axis=(0,))
    assert b.mode == "tpu" and b.split == 1
    assert np.array_equal(b.toarray(), full)
    # one call per device shard, each a proper slice tuple of the shape
    assert len(calls) == len(mesh.devices.ravel())
    for index in calls:
        assert len(index) == 3
        assert all(isinstance(s, slice) for s in index)
    # shards cover the key axis exactly once
    starts = sorted(s[0].indices(16)[0] for s in calls)
    assert starts == [i * 2 for i in range(8)]


def test_fromcallback_streams_without_full_copy(mesh, tmp_path):
    # the canonical use: a memmap on disk, loaded shard by shard
    full = _oracle((8, 6)).astype(np.float32)
    path = tmp_path / "data.npy"
    np.save(path, full)
    mm = np.load(path, mmap_mode="r")
    b = bolt.fromcallback(lambda idx: mm[idx], (8, 6), mesh)
    assert np.array_equal(b.toarray(), full)
    assert b.dtype == np.float32                       # inferred from blocks
    # pipeline works on the loaded array
    assert np.allclose(b.map(lambda v: v * 2).toarray(), full * 2)


def test_fromcallback_dtype_conversion_and_axis(mesh):
    # axis=(1,) moves that axis to the front: the callback sees slices of
    # the key-axes-first shape (8, 4, 2) and must serve that layout
    full = _oracle((4, 8, 2))
    moved = np.moveaxis(full, 1, 0)
    b = bolt.fromcallback(lambda idx: moved[idx], (4, 8, 2), mesh,
                          axis=(1,), dtype=np.float32)
    got = b.toarray()
    assert got.shape == (8, 4, 2) and got.dtype == np.float32
    assert np.array_equal(got, moved.astype(np.float32))


def test_fromcallback_shape_mismatch_rejected(mesh):
    with pytest.raises(ValueError):
        bolt.fromcallback(lambda idx: np.zeros((1, 1)), (8, 4), mesh)


def test_fromcallback_local_mode():
    full = _oracle((6, 4))
    seen = []

    def loader(index):
        seen.append(index)
        return full[index]

    lo = bolt.fromcallback(loader, (6, 4))
    assert lo.mode == "local" and np.array_equal(np.asarray(lo), full)
    assert seen == [(slice(0, 6), slice(0, 4))]
    with pytest.raises(ValueError):
        bolt.fromcallback(lambda idx: np.zeros((2, 2)), (6, 4))


def test_fromcallback_axis_consistent_across_backends(mesh):
    # a loader written against one backend serves the other unchanged:
    # both present key-axes-first slices for axis=(1,)
    full = _oracle((4, 8, 2))
    moved = np.moveaxis(full, 1, 0)
    lo = bolt.fromcallback(lambda idx: moved[idx], (4, 8, 2), axis=(1,))
    tp = bolt.fromcallback(lambda idx: moved[idx], (4, 8, 2), mesh,
                           axis=(1,))
    assert lo.shape == tp.shape == (8, 4, 2)
    assert np.array_equal(np.asarray(lo), tp.toarray())


def test_fromcallback_local_axis_forms(mesh):
    # range/ndarray axis values normalize like the TPU backend (tupleize)
    full = _oracle((4, 6))
    lo = bolt.fromcallback(lambda idx: full[idx], (4, 6), axis=range(1))
    assert np.array_equal(np.asarray(lo), full)
    lo2 = bolt.fromcallback(lambda idx: full[idx], (4, 6),
                            axis=np.array([0]))
    assert np.array_equal(np.asarray(lo2), full)
    with pytest.raises(ValueError):
        bolt.fromcallback(lambda idx: full[idx], (4, 6), axis=(5,))
