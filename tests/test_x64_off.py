"""TPU-production dtype mode: x64 DISABLED (the suite's conftest enables
x64 for bit-parity with the NumPy oracle; real TPU sessions run without
it, where float64 requests canonicalise to float32 at construction —
docs/MIGRATION.md "Dtypes").  Runs in a subprocess so the main process's
x64 config is untouched."""

import os
import subprocess
import sys

import numpy as np

_SCRIPT = r"""
import os
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
assert not jax.config.jax_enable_x64

import numpy as np
import bolt_tpu as bolt

mesh = jax.make_mesh((8,), ("k",))
x64 = np.random.RandomState(0).randn(64, 6, 4)          # float64 input

b = bolt.array(x64, mesh, axis=(0,))
assert b.dtype == np.float32, b.dtype                   # canonicalised
x32 = x64.astype(np.float32)
assert np.array_equal(b.toarray(), x32)

# the full pipeline stays f32 and matches the f32 oracle
m = b.map(lambda v: v * 2 + 1)
assert m.dtype == np.float32
assert np.allclose(m.toarray(), x32 * 2 + 1)
# f32-only accumulation differs from numpy's pairwise order by a few
# ulps: tolerance reflects the documented non-bit-exact f32 mode
assert np.allclose(np.asarray(b.mean(axis=(0,)).toarray()),
                   x32.mean(axis=0), rtol=1e-5, atol=1e-6)
st = b.stats()
assert np.allclose(np.asarray(st.mean()), x32.mean(axis=0),
                   rtol=1e-5, atol=1e-6)

s = b.swap((0,), (0,))
assert s.dtype == np.float32

f = b.filter(lambda v: v.mean() > 0)
keep = x32[x32.mean(axis=(1, 2)) > 0]
assert f.shape == keep.shape and np.allclose(f.toarray(), keep)

# constructors: f64 request comes back f32, ints survive untouched
o = bolt.ones((8, 4), mesh, dtype=np.float64)
assert o.dtype == np.float32
i = bolt.array(np.arange(8, dtype=np.int64).reshape(8, 1), mesh)
assert i.dtype == np.int32                              # jax canonical int

# linalg family under f32-only
from bolt_tpu.ops import pca, tallskinny_svd
scores, comps, svals = pca(b.map(lambda v: v.reshape(24)), k=2)
ref = np.linalg.svd(x32.reshape(64, 24).astype(np.float64),
                    compute_uv=False)[:2]
assert np.allclose(svals, ref, rtol=1e-4)
u, s_, vh = tallskinny_svd(np.asarray(x64.reshape(384, 4)))
assert np.asarray(u).dtype == np.float32

# order statistics, covariance and ndarray-parity methods stay f32-clean
from bolt_tpu.ops import cov
assert b.median().dtype == np.float32
assert np.allclose(np.asarray(b.quantile(0.5).toarray()),
                   np.median(x32, axis=0), atol=1e-6)
assert np.allclose(cov(b.map(lambda v: v.reshape(24))),
                   np.cov(x32.reshape(64, 24).astype(np.float64),
                          rowvar=False), rtol=1e-3, atol=1e-5)
assert np.array_equal(np.asarray(b.argmax(axis=0).toarray()),
                      np.argmax(x32, axis=0))
assert b.clip(-0.5, 0.5).dtype == np.float32
assert np.allclose(np.asarray(b.cumsum(axis=1).toarray()),
                   x32.cumsum(axis=1), rtol=1e-5, atol=1e-5)

# halo filters stay f32 and match the f32 local oracle (taps are python
# floats — weakly typed, no silent f64 promotion on either backend)
from bolt_tpu.ops import smooth
sm = smooth(b, 3, axis=(0,), size=(3,))
assert sm.dtype == np.float32
lo = smooth(bolt.array(x32), 3, axis=(0,), size=(3,))
assert lo.dtype == np.float32
assert np.allclose(sm.toarray(), lo.toarray(), rtol=1e-6, atol=1e-6)

# round-2 surfaces under f32-only production mode
w64 = np.random.RandomState(1).randn(4, 3)              # f64 operand
mm = b @ w64
assert mm.dtype == np.float32                           # no silent f64
assert np.allclose(mm.toarray(), x32 @ w64.astype(np.float32),
                   rtol=1e-5, atol=1e-5)
assert b.dot(w64).dtype == np.float32
assert np.sin(b).dtype == np.float32                    # ufunc dispatch
assert (b // 1.0).dtype == np.float32
assert np.array_equal(np.asarray(b.argsort(axis=0, kind="stable").toarray()),
                      x32.argsort(axis=0, kind="stable"))
# stats() through the fused_welford kernel path (128-aligned shard):
# f32 moments, parity with the f32 oracle
from bolt_tpu.ops.kernels import welford_plan
xk = np.random.RandomState(2).randn(32, 4, 128)
assert welford_plan((32 // 8,) + xk.shape[1:], 4) is not None  # kernel engages
bk = bolt.array(xk, mesh)
stk = bk.stats()
xk32 = xk.astype(np.float32)
assert np.asarray(stk.mean()).dtype == np.float32
assert np.allclose(np.asarray(stk.mean()), xk32.mean(axis=0),
                   rtol=1e-5, atol=1e-6)
assert np.allclose(np.asarray(stk.variance()), xk32.var(axis=0),
                   rtol=1e-4, atol=1e-5)

# grouped/set ops under f32-only
from bolt_tpu.ops import bincount, histogram, segment_reduce, unique
glabels = np.arange(64) % 4
gs = segment_reduce(b, glabels, op="mean")
assert gs.dtype == np.float32
assert np.allclose(np.asarray(gs.toarray()),
                   np.stack([x32[glabels == g].mean(axis=0)
                             for g in range(4)]), rtol=1e-5, atol=1e-6)
# int-input mean promotes through the CANONICAL float on BOTH backends:
# f32 here (x64 off), so the oracle and the TPU path agree on dtype
ints = np.arange(24, dtype=np.int32).reshape(8, 3)
ilabels = np.arange(8) % 2
for ib in (bolt.array(ints), bolt.array(ints, mesh)):
    im = segment_reduce(ib, ilabels, op="mean")
    assert np.asarray(im.toarray()).dtype == np.float32, ib.mode
iv = bolt.array((np.abs(x64) * 3).astype(np.int32), mesh)
assert np.array_equal(bincount(iv),
                      np.bincount((np.abs(x32) * 3).astype(np.int32).ravel()))
cu, eu = histogram(b, bins=8)
assert cu.dtype == np.int64 and cu.sum() == x32.size
uu = unique(bolt.array(np.floor(x64 * 2), mesh))
assert np.array_equal(uu, np.unique(np.floor(x32 * 2)))

# round-3 surfaces under f32-only production mode
bs = bolt.array(x64, mesh)
st = bs.set((0, slice(None), [0, 2]), 9.0)
assert st.dtype == np.float32
xs = x32.copy(); xs[0][:, [0, 2]] = 9.0
assert np.allclose(st.toarray(), xs)
srt = bolt.array(x64, mesh)
assert srt.sort(axis=1) is None and srt.dtype == np.float32
assert np.allclose(srt.toarray(), np.sort(x32, axis=1))
ns = np.sum(bs)                              # np dispatch, device-served
assert ns.mode == "tpu" and np.asarray(ns.toarray()).dtype == np.float32
vq = bs.quantile([0.25, 0.75])
assert vq.dtype == np.float32
assert np.allclose(np.asarray(vq.toarray()),
                   np.quantile(x32, [0.25, 0.75], axis=0), atol=1e-6)
nz = bs.map(lambda v: (v > 1.5).astype(np.int32)).nonzero()
assert all(i.dtype == np.int64 for i in nz)
assert np.array_equal(np.stack(nz, 1),
                      np.stack((x32 > 1.5).nonzero(), 1))
sm2 = smooth(bs, 3, axis=(0, 1))             # sepfilter kernel path
assert sm2.dtype == np.float32
lo2 = smooth(bolt.array(x32), 3, axis=(0, 1))
assert np.allclose(sm2.toarray(), lo2.toarray(), rtol=1e-5, atol=1e-6)
tgt = np.empty(bs.shape, np.float32)
assert bs.toarray(out=tgt) is tgt and np.array_equal(tgt, x32)

print("X64-OFF-OK")
"""


def test_pipeline_without_x64():
    env = dict(os.environ)
    env.pop("JAX_ENABLE_X64", None)
    env["PALLAS_AXON_POOL_IPS"] = ""       # no TPU plugin in the subprocess
    out = subprocess.run([sys.executable, "-c", _SCRIPT], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "X64-OFF-OK" in out.stdout
