"""Cross-feature interaction parity: combinations of deferred chains,
re-axis, chunking, filtering, and indexing that no single-feature suite
exercises together.  Oracle idiom as everywhere (SURVEY §4): compute the
same thing with NumPy and assert ``allclose`` on ``toarray()``."""

import numpy as np

import bolt_tpu as bolt
from bolt_tpu.utils import allclose


def _x(shape=(8, 4, 6), seed=11):
    return np.random.RandomState(seed).randn(*shape)


def test_deferred_map_then_swap(mesh):
    x = _x()
    s = bolt.array(x, mesh).map(lambda v: v * 2).swap((0,), (1,))
    assert allclose(s.toarray(), np.transpose(x * 2, (2, 0, 1)))


def test_deferred_concat_deferred_value_axis(mesh):
    x = _x()
    a1 = bolt.array(x, mesh).map(lambda v: v + 1)
    a2 = bolt.array(x, mesh).map(lambda v: v - 1)
    c = a1.concatenate(a2, axis=2)
    assert allclose(c.toarray(), np.concatenate([x + 1, x - 1], axis=2))


def test_filter_map_reduce_chain(mesh):
    x = _x()
    out = (bolt.array(x, mesh)
           .filter(lambda v: v.mean() > 0)
           .map(lambda v: v * 2)
           .reduce(np.add))
    keep = x[x.mean(axis=(1, 2)) > 0]
    assert allclose(out.toarray(), (keep * 2).sum(axis=0))


def test_shape_changing_map_then_swap(mesh):
    x = _x()
    b = bolt.array(x, mesh).map(lambda v: v.reshape(24)[:5])
    s = b.swap((0,), (0,))
    expected = np.stack([r.reshape(24)[:5] for r in x])
    assert allclose(s.toarray(), expected.T)


def test_chained_swaps_compose(mesh):
    x = _x()

    def perm(split, ndim, kaxes, vaxes):
        keys_rest = [k for k in range(split) if k not in kaxes]
        values_rest = [v for v in range(ndim - split) if v not in vaxes]
        return tuple(keys_rest + [split + v for v in vaxes]
                     + list(kaxes) + [split + v for v in values_rest])

    s = bolt.array(x, mesh, axis=(0, 1)).swap((0,), (0,)).swap((0,), (0,))
    e = np.transpose(np.transpose(x, perm(2, 3, [0], [0])), perm(2, 3, [0], [0]))
    assert allclose(s.toarray(), e)


def test_deferred_padded_chunk_identity(mesh):
    x = _x()
    c = (bolt.array(x, mesh).map(lambda v: v - 1)
         .chunk(size=(2, 3), axis=(0, 1), padding=(1, 1))
         .map(lambda blk: blk).unchunk())
    assert allclose(c.toarray(), x - 1)


def test_reduce_keepdims_then_squeeze(mesh):
    x = _x()
    s = bolt.array(x, mesh).reduce(np.add, keepdims=True).squeeze()
    assert allclose(s.toarray(), x.sum(axis=0))


def test_bool_mask_on_deferred(mesh):
    x = _x()
    m = np.array([True, False] * 4)
    g = bolt.array(x, mesh).map(lambda v: v + 2)[m]
    assert allclose(g.toarray(), (x + 2)[m])


def test_values_view_on_deferred(mesh):
    x = _x()
    r = bolt.array(x, mesh).map(lambda v: v + 5).values.reshape(6, 4)
    assert allclose(r.toarray(), (x + 5).reshape(8, 6, 4))


def test_explicit_axis_mesh_pipeline():
    import jax
    em = jax.make_mesh((len(jax.devices()),), ("k",))
    x = _x((7, 4, 6))  # non-divisible key axis
    b = bolt.array(x, em)
    assert allclose(b.toarray(), x)
    assert allclose(np.asarray(b.mean().toarray()), x.mean(axis=0))


def test_with_keys_two_axis_parity(mesh):
    x = _x()
    f = lambda kv: kv[1] + kv[0][0] + 10 * kv[0][1]
    m = bolt.array(x, mesh, axis=(0, 1)).map(f, axis=(0, 1), with_keys=True)
    lo = bolt.array(x).map(f, axis=(0, 1), with_keys=True)
    assert allclose(m.toarray(), np.asarray(lo))


def test_wrong_value_shape_raises(mesh):
    import pytest
    x = _x()
    with pytest.raises((ValueError, TypeError)):
        bolt.array(x, mesh).map(lambda v: v * 2, value_shape=(9, 9)).toarray()


def test_tojax_unwraps_engine_native(mesh):
    import jax
    x = _x()
    b = bolt.array(x, mesh).map(lambda v: v + 1)
    j = b.tojax()
    assert isinstance(j, jax.Array) and j.shape == x.shape
    assert allclose(np.asarray(j), x + 1)
    lo = bolt.array(x)
    j2 = lo.tojax(mesh)
    assert isinstance(j2, jax.Array)
    assert allclose(np.asarray(j2), x)


def test_exotic_dtype_parity(mesh):
    rs = np.random.RandomState(3)
    xb = rs.rand(8, 4) > 0.5
    b = bolt.array(xb, mesh)
    assert b.dtype == np.bool_
    assert (b.map(lambda v: ~v).toarray() == ~xb).all()
    assert np.asarray(b.sum(axis=(0,)).toarray()).dtype == xb.sum(axis=0).dtype

    xc = (rs.randn(8, 4) + 1j * rs.randn(8, 4)).astype(np.complex128)
    c = bolt.array(xc, mesh)
    assert c.dtype == np.complex128
    assert allclose(c.map(lambda v: v * (1 + 2j)).toarray(), xc * (1 + 2j))
    assert np.allclose(np.asarray(c.mean().toarray()), xc.mean(axis=0))

    xh = rs.randn(8, 4).astype(np.float16)
    assert allclose(bolt.array(xh, mesh).map(lambda v: v + 1).toarray(), xh + 1)

    xu = rs.randint(0, 255, (8, 4)).astype(np.uint8)
    assert (bolt.array(xu, mesh).map(lambda v: v // 2).toarray() == xu // 2).all()


def test_filter_of_filter_chains_pending(mesh):
    # the second filter consumes a still-pending first filter
    rs = np.random.RandomState(31)
    x = rs.randn(16, 6, 4)
    b = bolt.array(x, mesh, axis=(0,))
    ff = b.filter(lambda v: v.mean() > -10).filter(lambda v: v.sum() > 0)
    keep = x[x.reshape(16, -1).sum(axis=1) > 0]
    assert ff.shape == keep.shape
    assert np.allclose(ff.toarray(), keep)


def test_pending_filter_into_map_sum_without_shape_read(mesh):
    # consumers must work off the pending result without a host sync first
    rs = np.random.RandomState(32)
    x = rs.randn(16, 6, 4)
    b = bolt.array(x, mesh, axis=(0,))
    f = b.filter(lambda v: v.mean() > 0)
    r = f.map(lambda v: v * 0 + 1).sum(axis=(0,))
    expect = np.ones((6, 4)) * (x.mean(axis=(1, 2)) > 0).sum()
    assert np.allclose(r.toarray(), expect)


def test_operator_expressions_on_deferred_chain(mesh):
    rs = np.random.RandomState(33)
    x = rs.randn(8, 5)
    b = bolt.array(x, mesh, axis=(0,))
    e = (2.0 * b.map(lambda v: v + 1) - 1.0) / 4.0
    assert np.allclose(e.toarray(), (2 * (x + 1) - 1) / 4)


def test_chunk_and_stack_maps_fuse_deferred_chains(mesh):
    # chunk.map / stacked.map pull an unmaterialised chain into their own
    # program: the source array must STAY deferred (no intermediate in HBM)
    x = _x()
    b = bolt.array(x, mesh).map(lambda v: v + 1)
    assert b.deferred
    out = b.chunk(size=(2, 3), axis=(0, 1)).map(lambda blk: blk * 2).unchunk()
    assert b.deferred
    assert allclose(out.toarray(), (x + 1) * 2)
    out2 = b.chunk(size=(3,), axis=(0,), padding=1).map(
        lambda blk: blk * 1.0).unchunk()
    assert b.deferred
    assert allclose(out2.toarray(), x + 1)
    out3 = b.stacked(size=3).map(lambda blk: blk - 1).unstack()
    assert b.deferred
    assert allclose(out3.toarray(), x)


def test_swap_fuses_deferred_chain(mesh):
    # swap pulls an unmaterialised chain into its transpose program: the
    # source stays deferred, results match the oracle
    x = _x()
    b = bolt.array(x, mesh).map(lambda v: v * 3)
    assert b.deferred
    s = b.swap((0,), (1,))
    assert b.deferred
    assert allclose(s.toarray(), np.transpose(x * 3, (2, 0, 1)))
    # donation still materialises first (the base buffer may be aliased)
    b2 = bolt.array(x, mesh).map(lambda v: v + 1)
    s2 = b2.swap((0,), (1,), donate=True)
    assert allclose(s2.toarray(), np.transpose(x + 1, (2, 0, 1)))


def test_new_stats_on_pending_filter(mesh):
    # quantile/argmax/cumsum/clip/prod consume a PENDING (lazy-count)
    # filter result the same way reduce/sum do
    x = np.random.RandomState(3).randn(16, 5)
    b = bolt.array(x, mesh)
    f = b.filter(lambda v: v.mean() > 0)
    keep = x[x.mean(axis=1) > 0]
    assert allclose(f.quantile(0.5).toarray(), np.median(keep, axis=0))
    assert allclose(f.argmax(axis=0).toarray(), np.argmax(keep, axis=0))
    assert allclose(f.cumsum(axis=0).toarray(), keep.cumsum(axis=0))
    assert allclose(f.clip(-0.5, 0.5).toarray(), keep.clip(-0.5, 0.5))
    assert allclose(f.prod().toarray(), keep.prod(axis=0))


def test_round3_ops_on_pending_filter_results(mesh):
    # the round-3 surface resolves a PENDING filter result transparently
    # too: set / in-place sort / np-dispatch / item / iter_shards / repeat
    x = np.random.RandomState(91).randn(16, 4, 6)
    keep = x[x.reshape(16, -1).mean(axis=1) > 0]
    n = keep.shape[0]
    assert 2 <= n < 16

    def pending():
        b = bolt.array(x, mesh).filter(lambda v: v.mean() > 0)
        assert b.pending
        return b

    out = pending().set(0, 0.0)
    expect = keep.copy()
    expect[0] = 0.0
    assert allclose(out.toarray(), expect)
    srt = pending()
    assert srt.sort(axis=0) is None
    assert allclose(srt.toarray(), np.sort(keep, axis=0))
    s = np.sum(pending())
    assert s.mode == "tpu"
    assert np.allclose(float(np.asarray(s.toarray())), keep.sum())
    assert abs(pending().item(2) - keep.reshape(-1)[2]) < 1e-12
    walked = np.empty_like(keep)
    for idx, blk in pending().iter_shards():
        walked[idx] = blk
    assert np.allclose(walked, keep)
    assert allclose(pending().repeat(2, axis=1).toarray(),
                    keep.repeat(2, axis=1))
    assert allclose(pending().diagonal(0, 1, 2).toarray(),
                    keep.diagonal(0, 1, 2))
    got = pending().nonzero()
    want = keep.nonzero()
    assert len(got) == len(want)
    assert all(np.array_equal(a, b) for a, b in zip(got, want))


def test_new_ops_on_pending_filter_results(mesh):
    # a filter result is PENDING (survivor count unsynced) until its shape
    # is read; every round-2 op must resolve it transparently
    import bolt_tpu as bolt
    from bolt_tpu.ops import histogram, segment_reduce, topk, unique
    x = np.random.RandomState(90).randn(16, 4, 6)
    keep = x.reshape(16, -1).mean(axis=1) > 0
    xs = x[keep]
    n = xs.shape[0]
    assert 2 <= n < 16   # the filter actually drops something

    def pending():
        b = bolt.array(x, mesh).filter(lambda v: v.mean() > 0)
        assert b.pending
        return b

    out = segment_reduce(pending(), np.arange(n) % 2, op="sum")
    ref = np.stack([xs[np.arange(n) % 2 == g].sum(axis=0) for g in range(2)])
    assert allclose(out.toarray(), ref)

    v, i = topk(pending(), 2, axis=0)
    ref_i = np.argsort(-np.moveaxis(xs, 0, -1), axis=-1, kind="stable")[..., :2]
    assert np.array_equal(np.asarray(i.toarray()),
                          np.moveaxis(ref_i, -1, 0))
    assert allclose(v.toarray(), np.moveaxis(np.take_along_axis(
        np.moveaxis(xs, 0, -1), ref_i, axis=-1), -1, 0))

    c, e = histogram(pending(), bins=5)
    cn, en = np.histogram(xs, bins=5)
    assert np.array_equal(c, cn) and np.allclose(e, en)

    u = unique(pending().map(np.floor))
    assert np.array_equal(u, np.unique(np.floor(xs)))

    assert allclose(pending().ptp(axis=(0,)).toarray(), np.ptp(xs, axis=0))
    assert allclose(pending().var(axis=(0,), ddof=1).toarray(),
                    xs.var(axis=0, ddof=1))
    assert allclose((pending() @ np.ones((6, 2))).toarray(),
                    xs @ np.ones((6, 2)))
    assert allclose(pending().argsort(axis=0, kind="stable").toarray(),
                    xs.argsort(axis=0, kind="stable"))
