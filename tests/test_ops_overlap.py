"""ops.map_overlap / ops.smooth: halo-padded blockwise filtering parity
across backends and against independent NumPy oracles (the reference
ecosystem's spatial-filtering use of chunk padding, SURVEY §2.1)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.ops import convolve, gaussian, map_overlap, smooth
from bolt_tpu.utils import allclose


def _x(shape=(3, 20, 12)):
    rs = np.random.RandomState(11)
    return rs.randn(*shape)


def _conv_same(x, w, axis):
    """Independent oracle: zero-boundary windowed mean via np.convolve."""
    k = np.ones(w) / w
    return np.apply_along_axis(lambda v: np.convolve(v, k, "same"), axis, x)


def test_smooth_matches_convolve_local():
    x = _x()
    out = smooth(bolt.array(x), 5, axis=(0,), size=(4,)).toarray()
    assert allclose(out, _conv_same(x, 5, 1))


def test_smooth_backend_parity(mesh):
    x = _x()
    lout = smooth(bolt.array(x), 3, axis=(0, 1), size=(8, 5)).toarray()
    tout = smooth(bolt.array(x, mesh), 3, axis=(0, 1), size=(8, 5)).toarray()
    assert allclose(lout, tout)
    # separable filter: both axes smoothed, order-independent oracle
    expect = _conv_same(_conv_same(x, 3, 1), 3, 2)
    assert allclose(lout, expect)


def test_smooth_chunking_invariance(mesh):
    # the answer must not depend on the chunk plan (halo correctness at
    # interior block boundaries), including ragged tails
    x = _x((2, 23, 8))
    full = smooth(bolt.array(x, mesh), 7, axis=(0,)).toarray()
    for size in [(23,), (12,), (7,), (5,)]:
        out = smooth(bolt.array(x, mesh), 7, axis=(0,), size=size).toarray()
        assert allclose(out, full)
        lout = smooth(bolt.array(x), 7, axis=(0,), size=size).toarray()
        assert allclose(lout, full)


@pytest.mark.parametrize("mode", ["reflect", "edge"])
def test_smooth_boundary_modes(mesh, mode):
    x = _x((2, 16, 6))
    w, h = 5, 2
    lout = smooth(bolt.array(x), w, axis=(0,), size=(4,), mode=mode).toarray()
    tout = smooth(bolt.array(x, mesh), w, axis=(0,), size=(4,),
                  mode=mode).toarray()
    assert allclose(lout, tout)
    # oracle: pad the FULL axis with the global boundary mode, then the
    # interior of the padded result is the plain windowed mean
    xpad = np.pad(x, ((0, 0), (h, h), (0, 0)), mode=mode)
    expect = sum(xpad[:, o:o + x.shape[1]] for o in range(w)) / w
    assert allclose(lout, expect)


def test_smooth_unsorted_axis_binding(mesh):
    # widths pair with the axes in the ORDER GIVEN: (3, 5) on axis (1, 0)
    # means width 3 on value axis 1 and width 5 on value axis 0
    x = _x((2, 16, 10))
    out = smooth(bolt.array(x), (3, 5), axis=(1, 0), size=(4, 5)).toarray()
    expect = _conv_same(_conv_same(x, 5, 1), 3, 2)
    assert allclose(out, expect)
    tout = smooth(bolt.array(x, mesh), (3, 5), axis=(1, 0),
                  size=(4, 5)).toarray()
    assert allclose(tout, expect)
    # same pairing rule for chunk itself: size/padding follow their axis
    c = bolt.array(x).chunk(size=(2, 9), axis=(1, 0))
    assert c.plan == (9, 2)
    ct = bolt.array(x, mesh, axis=(0,)).chunk(size=(2, 9), axis=(1, 0))
    assert ct.plan == (9, 2)


def test_keys_to_values_size_validation(mesh):
    lc = bolt.array(_x()).chunk(size=(2,), axis=(0,))
    tc = bolt.array(_x(), mesh).chunk(size=(2,), axis=(0,))
    with pytest.raises(ValueError):
        lc.keys_to_values((0,), size=0)
    with pytest.raises(ValueError):
        tc.keys_to_values((0,), size=0)


def test_smooth_sharded_value_axis(mesh2d):
    # sequence-parallel: keys on 'a', the long smoothed axis split over
    # 'b' — halos cross the shard boundary via GSPMD collectives
    x = _x((4, 16, 3))
    # key axis (4) takes 'a'; 'b' stays free for the value shard (the
    # matching search keeps greedy here: 4 % (4*2) != 0)
    b = bolt.array(x, mesh2d, axis=(0,))
    out = smooth(b, 5, axis=(0,), size=(4,), shard={0: "b"}).toarray()
    oracle = smooth(bolt.array(x), 5, axis=(0,), size=(4,)).toarray()
    assert allclose(out, oracle)
    # string form: first chunked axis
    out2 = smooth(b, 5, axis=(0,), size=(4,), shard="b").toarray()
    assert allclose(out2, oracle)
    with pytest.raises(ValueError):
        smooth(bolt.array(x), 3, shard="b")  # local backend has no mesh


def test_smooth_validation():
    b = bolt.array(_x())
    with pytest.raises(ValueError):
        smooth(b, 4)            # even width
    with pytest.raises(ValueError):
        smooth(b, 3, mode="wrap")
    assert allclose(smooth(b, 1).toarray(), _x())  # width 1 = identity


def test_convolve_matches_npconvolve(mesh):
    x = _x((2, 18, 6))
    k = [0.25, 0.5, 0.25]
    lout = convolve(bolt.array(x), k, axis=(0,), size=(5,)).toarray()
    tout = convolve(bolt.array(x, mesh), k, axis=(0,), size=(5,)).toarray()
    assert allclose(lout, tout)
    # correlation orientation == convolution for symmetric kernels; use
    # np.convolve (flipped) with the reversed kernel as the oracle
    expect = np.apply_along_axis(
        lambda v: np.convolve(v, np.asarray(k)[::-1], "same"), 1, x)
    assert allclose(lout, expect)
    # asymmetric kernel: correlation (not flipped)
    ka = [1.0, 0.0, -1.0]
    aout = convolve(bolt.array(x), ka, axis=(0,), size=(7,)).toarray()
    expect = np.apply_along_axis(
        lambda v: np.convolve(v, np.asarray(ka)[::-1], "same"), 1, x)
    assert allclose(aout, expect)


def test_convolve_per_axis_kernels():
    x = _x((2, 12, 10))
    k0, k1 = [0.25, 0.5, 0.25], [0.2, 0.2, 0.2, 0.2, 0.2]
    out = convolve(bolt.array(x), [k0, k1], axis=(0, 1), size=(6, 5)).toarray()
    via_smoothes = convolve(convolve(bolt.array(x), k0, axis=(0,)),
                            k1, axis=(1,)).toarray()
    assert allclose(out, via_smoothes)
    with pytest.raises(ValueError):
        convolve(bolt.array(x), [k0], axis=(0, 1))
    with pytest.raises(ValueError):
        convolve(bolt.array(x), [0.5, 0.5])  # even length
    # a single-tap kernel is a pure scaling, not an identity skip
    assert allclose(convolve(bolt.array(x), [2.0], axis=(0,)).toarray(),
                    x * 2.0)


def test_gaussian_parity(mesh):
    x = _x((2, 40, 4))
    lout = gaussian(bolt.array(x), 1.5, axis=(0,), size=(12,)).toarray()
    tout = gaussian(bolt.array(x, mesh), 1.5, axis=(0,), size=(12,)).toarray()
    assert allclose(lout, tout)
    # oracle: explicit normalised gaussian taps, full-axis correlation
    radius = int(4.0 * 1.5 + 0.5)
    g = np.exp(-0.5 * (np.arange(-radius, radius + 1) / 1.5) ** 2)
    g /= g.sum()
    expect = np.apply_along_axis(
        lambda v: np.convolve(v, g[::-1], "same"), 1, x)
    assert allclose(lout, expect)
    # sigma=0 is the identity
    assert allclose(gaussian(bolt.array(x), 0.0, axis=(0,)).toarray(), x)
    with pytest.raises(ValueError):
        gaussian(bolt.array(x), -1.0, axis=(0,))


def test_map_overlap_generic(mesh):
    # a custom stencil: forward difference needing 1 neighbour
    x = _x((2, 12, 4))

    def np_grad(blk):
        d = np.zeros_like(blk)
        d[:-1] = blk[1:] - blk[:-1]
        return d

    def jnp_grad(blk):
        import jax.numpy as jnp
        return jnp.zeros_like(blk).at[:-1].set(blk[1:] - blk[:-1])

    lout = map_overlap(bolt.array(x), np_grad, 1, axis=(0,),
                       size=(4,)).toarray()
    tout = map_overlap(bolt.array(x, mesh), jnp_grad, 1, axis=(0,),
                       size=(4,)).toarray()
    # interior of each block sees its neighbour: matches the global diff
    # everywhere except the final row of the ARRAY (no neighbour there)
    expect = np.zeros_like(x)
    expect[:, :-1] = x[:, 1:] - x[:, :-1]
    # block-edge rows use halo data, so all rows except the very last of
    # the array must match
    assert allclose(lout[:, :-1], expect[:, :-1])
    assert allclose(tout[:, :-1], expect[:, :-1])
