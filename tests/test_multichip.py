"""Distribution-specific semantics on the fake 8-device mesh: real sharding
layouts, collective-backed ops, multi-axis meshes (the reference covers
distribution semantically via local-mode Spark — SURVEY §4; here we
additionally assert on the placement itself)."""

import os

import numpy as np
import pytest

import jax
import bolt_tpu as bolt
from bolt_tpu.parallel.sharding import key_sharding, key_spec
from bolt_tpu.utils import allclose


def _x(shape=(8, 4, 6)):
    rs = np.random.RandomState(8)
    return rs.randn(*shape)


def test_key_spec_assignment(mesh, mesh2d):
    # 1-d mesh: first divisible key axis takes it
    assert tuple(key_spec(mesh, (8, 4, 6), 1)) == ("k", None, None)
    # indivisible key axis: replicated
    assert tuple(key_spec(mesh, (7, 4), 1)) == (None, None)
    # 2-d mesh: greedy in-order assignment
    assert tuple(key_spec(mesh2d, (8, 4, 6), 2)) == ("a", "b", None)
    # a single key axis absorbs EVERY divisible mesh axis (8 devices busy,
    # not 4): the spec entry is a tuple of mesh axes
    assert tuple(key_spec(mesh2d, (8, 4, 6), 1)) == (("a", "b"), None, None)
    # absorption stops when the combined width stops dividing
    assert tuple(key_spec(mesh2d, (4, 4, 6), 1)) == ("a", None, None)
    # 4 % 4 == 0 takes 'a'; next axis 4 % 2 == 0 takes 'b'
    assert tuple(key_spec(mesh2d, (4, 4, 6), 2)) == ("a", "b", None)


def test_key_spec_matching_beats_greedy_order():
    # mesh axes ordered (2, 4): greedy gives key axis 0 (size 4) the size-2
    # mesh axis 'a' and strands 'b' (4 % (2*4) != 0, and key axis 1 can't
    # take a second chance on 'a').  The matching search finds the full
    # assignment: key 0 -> b(4), key 1 -> a(2) — all 8 devices busy.
    m = jax.make_mesh((2, 4), ("a", "b"))
    assert tuple(key_spec(m, (4, 2, 6), 2)) == ("b", "a", None)
    # single key axis: 'b' alone (4-way) beats greedy's 'a' (2-way);
    # absorption can't rescue greedy because 4 % (2*4) != 0
    assert tuple(key_spec(m, (4, 6), 1)) == ("b", None)
    # greedy already optimal -> spec unchanged by the search
    assert tuple(key_spec(m, (2, 4, 6), 2)) == ("a", "b", None)
    # nothing divides -> still replicated
    assert tuple(key_spec(m, (7, 5), 2)) == (None, None)


def test_matching_assignment_end_to_end():
    m = jax.make_mesh((2, 4), ("a", "b"))
    x = _x((4, 2, 6))
    b = bolt.array(x, m, axis=(0, 1))
    assert len(b._data.addressable_shards) == 8
    assert all(s.data.shape == (1, 1, 6) for s in b._data.addressable_shards)
    assert allclose(b.map(lambda v: v + 1).sum(axis=(0, 1)).toarray(),
                    (x + 1).sum(axis=(0, 1)))


def test_single_key_axis_uses_whole_2d_mesh(mesh2d):
    # end to end: one key axis on the (4, 2) mesh spreads over all 8
    # devices, and collectives still produce oracle answers
    x = _x((16, 4, 6))
    b = bolt.array(x, mesh2d, axis=(0,))
    assert len(b._data.addressable_shards) == 8
    assert all(s.data.shape == (2, 4, 6) for s in b._data.addressable_shards)
    assert allclose(b.map(lambda v: v + 1).sum(axis=(0,)).toarray(),
                    (x + 1).sum(axis=0))
    st = b.stats()
    assert np.allclose(np.asarray(st.mean()), x.mean(axis=0))
    assert np.allclose(np.asarray(st.stdev()), x.std(axis=0), atol=1e-9)


def test_data_actually_distributed(mesh):
    b = bolt.ones((8, 64), mesh)
    shards = b._data.addressable_shards
    assert len(shards) == 8
    assert all(s.data.shape == (1, 64) for s in shards)


def test_map_preserves_sharding(mesh):
    b = bolt.ones((8, 64), mesh)
    out = b.map(lambda v: v * 2)
    assert len(out._data.addressable_shards) == 8
    assert out._data.addressable_shards[0].data.shape == (1, 64)


def test_swap_resharding(mesh):
    # swap moves the sharded axis: data redistributes (all_to_all)
    x = _x((8, 4, 16))
    b = bolt.array(x, mesh)
    s = b.swap((0,), (1,))  # new keys = (16,), new values = (8, 4)
    assert s.shape == (16, 8, 4)
    assert allclose(s.toarray(), np.transpose(x, (2, 0, 1)))
    assert s._data.addressable_shards[0].data.shape == (2, 8, 4)


def test_mesh2d_two_key_axes(mesh2d):
    x = _x((4, 2, 6))
    b = bolt.array(x, mesh2d, axis=(0, 1))
    assert len(b._data.addressable_shards) == 8
    assert b._data.addressable_shards[0].data.shape == (1, 1, 6)
    assert allclose(b.map(lambda v: v + 1, axis=(0, 1)).toarray(), x + 1)
    assert allclose(b.sum().toarray(), x.sum(axis=(0, 1)))
    c = b.stats()
    assert allclose(c.mean(), x.mean(axis=(0, 1)))
    assert allclose(c.variance(), x.var(axis=(0, 1)))


def test_welford_sharded_collectives(mesh):
    # the shard_map Welford path with a genuinely sharded reduce axis
    x = _x((16, 4))
    b = bolt.array(x, mesh)
    c = b.stats()
    assert c.count() == 16
    assert allclose(c.mean(), x.mean(axis=0))
    assert allclose(c.variance(), x.var(axis=0))
    assert allclose(c.max(), x.max(axis=0))


def test_default_mesh_single_device():
    # context=None builds a mesh over all devices
    b = bolt.array(np.ones((8, 3)), mode="tpu")
    assert b.mesh is not None
    assert allclose(b.toarray(), np.ones((8, 3)))


def test_reduce_over_sharded_axis(mesh):
    from operator import add
    x = _x((32, 5))
    b = bolt.array(x, mesh)
    assert allclose(b.reduce(add).toarray(), x.sum(axis=0))


def test_shard_gather_assembly(mesh):
    # the memory-bounded multi-host collect: in a single process every
    # shard is addressable, so assembly happens from local shards alone
    # (zero broadcasts) — correctness of the index-based host assembly
    import bolt_tpu as bolt
    from bolt_tpu.tpu import array as arr
    x = np.arange(40 * 6, dtype=np.float64).reshape(40, 6)
    b = bolt.array(x, mesh)
    out = b._gather_multihost(b._data)
    assert out.dtype == x.dtype
    assert np.array_equal(out, x)
    assert arr._LAST_GATHER_STATS == {
        "regions": 0, "broadcasts": 0, "max_piece_bytes": 0}
    # the cross-process piece-broadcast path (bounded max_piece_bytes,
    # region splitting) is exercised for real in scripts/multihost_smoke.py


@pytest.mark.parametrize("n", [2, 3, 5])
def test_dryrun_multichip_device_counts(n):
    """The full multichip gate at even AND odd device counts (VERDICT r4
    weak-6: the 1-d-mesh branch and the indivisible-key replication
    fallbacks only run when n is odd).  Fresh subprocess per count —
    the virtual device count is fixed at backend init."""
    import subprocess
    import sys
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=%d" % n)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    out = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(%d)" % n],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=420)
    assert out.returncode == 0, out.stderr[-2000:]
