"""Local constructor tests (reference area: ``test/test_local_construct.py``,
SURVEY §4)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.local.array import BoltArrayLocal
from bolt_tpu.utils import allclose


def test_array():
    x = np.arange(12).reshape(3, 4)
    b = bolt.array(x)
    assert isinstance(b, BoltArrayLocal)
    assert allclose(b.toarray(), x)
    b = bolt.array(x, dtype=np.float32)
    assert b.dtype == np.float32


def test_ones_zeros():
    assert allclose(bolt.ones((2, 3)).toarray(), np.ones((2, 3)))
    assert allclose(bolt.zeros((2, 3)).toarray(), np.zeros((2, 3)))
    assert bolt.ones((2, 3)).dtype == np.ones((2, 3)).dtype
    assert bolt.ones((2, 3), dtype=np.int32).dtype == np.int32


def test_concatenate():
    x = np.arange(6).reshape(2, 3)
    out = bolt.concatenate((x, x), axis=1)
    assert allclose(out.toarray(), np.concatenate((x, x), axis=1))
    with pytest.raises(ValueError):
        bolt.concatenate([], axis=0)


def test_mode_dispatch():
    x = np.arange(4.0)
    assert bolt.array(x).mode == "local"
    assert bolt.array(x, mode="local").mode == "local"
    with pytest.raises(ValueError):
        bolt.array(x, mode="nope")
