"""TPU-backend statistics: jnp-lowered mean/var/std/sum/max/min and the
explicit shard_map Welford path (reference area: StatCounter aggregation in
``test/test_spark_basic.py``/functional tests, SURVEY §4; BASELINE config 2).
"""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.utils import allclose


def _x():
    rs = np.random.RandomState(4)
    return rs.randn(8, 4, 5)


@pytest.mark.parametrize("name", ["mean", "var", "std", "sum", "max", "min"])
def test_stats_default_axis(mesh, name):
    x = _x()
    b = bolt.array(x, mesh)
    got = getattr(b, name)().toarray()
    expected = getattr(x, name)(axis=0)
    assert allclose(got, expected)


@pytest.mark.parametrize("name", ["mean", "var", "std", "sum", "max", "min"])
@pytest.mark.parametrize("axis", [(0,), (0, 1), (1, 2), (2,), None])
def test_stats_axes(mesh, name, axis):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    got = getattr(b, name)(axis=axis).toarray()
    np_axis = axis if axis is not None else (0, 1)  # default: all key axes
    expected = np.asarray(getattr(x, name)(axis=np_axis))
    assert allclose(got, expected)


def test_stats_keepdims(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b.mean(axis=(0,), keepdims=True)
    assert out.split == 1
    assert allclose(out.toarray(), x.mean(axis=0, keepdims=True))


def test_stats_split_bookkeeping(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    assert b.sum(axis=(0,)).split == 1
    assert b.sum(axis=(0, 1)).split == 0
    assert b.sum(axis=(2,)).split == 2


def test_welford_stats(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    counter = b.stats()
    assert counter.count() == 8
    assert allclose(counter.mean(), x.mean(axis=0))
    assert allclose(counter.variance(), x.var(axis=0))
    assert allclose(counter.stdev(), x.std(axis=0))
    assert allclose(counter.max(), x.max(axis=0))
    assert allclose(counter.min(), x.min(axis=0))


def test_welford_partial_axis(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    counter = b.stats(axis=(1,))
    assert counter.count() == 4
    assert allclose(counter.mean(), x.mean(axis=1))
    assert allclose(counter.variance(), x.var(axis=1))


def test_welford_value_axis(mesh):
    # stats() accepts value axes, matching mean()/_stat (VERDICT r1 weak-6)
    x = _x()
    b = bolt.array(x, mesh)
    counter = b.stats(axis=(1,))
    assert counter.count() == x.shape[1]
    assert allclose(counter.mean(), x.mean(axis=1))
    assert allclose(counter.variance(), x.var(axis=1))
    assert allclose(counter.max(), x.max(axis=1))
    # mixed key + value axes
    counter = b.stats(axis=(0, 2))
    assert allclose(counter.mean(), x.mean(axis=(0, 2)))
    assert allclose(counter.variance(), x.var(axis=(0, 2)))
    # parity with the local oracle per axis set
    lo = bolt.array(x)
    for axes in [(0,), (1,), (2,), (0, 1), (0, 2), (1, 2)]:
        a = lo.stats(axis=axes)
        t = b.stats(axis=axes)
        assert allclose(a.mean(), t.mean())
        assert allclose(a.variance(), t.variance())
    # out-of-range still rejected
    with pytest.raises(ValueError):
        b.stats(axis=(9,))


def test_welford_cache_bounded(mesh):
    # the welford executable cache is the shared bounded LRU, not an
    # unbounded private dict
    import bolt_tpu.tpu.stats as stats_mod
    assert not hasattr(stats_mod, "_WELFORD_CACHE")


def test_sum_bit_exact_integral(mesh):
    # integral floats: sum is bit-exact regardless of reduction order
    # (BASELINE north-star parity condition for config 1)
    x = np.arange(8.0 * 6).reshape(8, 6)
    b = bolt.array(x, mesh)
    assert allclose(b.sum().toarray(), x.sum(axis=0))
    assert float(b.sum(axis=(0, 1)).toarray()) == float(x.sum())


def test_var_std_ddof(mesh):
    x = _x()
    b, lo = bolt.array(x, mesh), bolt.array(x)
    assert allclose(b.var(axis=(0,), ddof=1).toarray(), x.var(axis=0, ddof=1))
    assert allclose(b.std(axis=(0,), ddof=1).toarray(), x.std(axis=0, ddof=1))
    # the local backend inherits ddof from ndarray: same expression works
    assert allclose(np.asarray(lo.var(axis=0, ddof=1)), x.var(axis=0, ddof=1))
    # default stays population (ddof=0), matching StatCounter
    assert allclose(b.var(axis=(0,)).toarray(), x.var(axis=0))


def test_ptp(mesh):
    x = _x()
    b, lo = bolt.array(x, mesh), bolt.array(x)
    assert allclose(b.ptp(axis=(0,)).toarray(), np.ptp(x, axis=0))
    assert allclose(b.ptp(axis=(0, 1, 2)).toarray(), np.ptp(x))
    # key-axis default on TPU; ndarray-convention (all axes) locally —
    # the documented reduction-family asymmetry
    assert allclose(b.ptp().toarray(), np.ptp(x, axis=0))
    assert float(np.asarray(lo.ptp().toarray())) == np.ptp(x)
    assert allclose(np.asarray(lo.ptp(axis=1).toarray()), np.ptp(x, axis=1))


def test_var_fractional_ddof(mesh):
    x = _x()
    b, lo = bolt.array(x, mesh), bolt.array(x)
    assert allclose(b.var(axis=(0,), ddof=1.5).toarray(),
                    x.var(axis=0, ddof=1.5))
    assert allclose(np.asarray(lo.var(axis=0, ddof=1.5)),
                    x.var(axis=0, ddof=1.5))


def test_welford_survives_kernel_compile_failure(mesh, monkeypatch):
    # the DEFAULT stats() path degrades to the jnp two-pass body when the
    # pallas-backed program fails to compile, memoising the failure so it
    # is paid once (the sepfilter pattern; this toolchain's remote
    # compile helper is flaky)
    import bolt_tpu.tpu.stats as stats_mod
    import bolt_tpu.tpu.array as arr
    real = arr._cached_jit
    exploded = []

    def exploding(key, build):
        if key[0] == "welford" and key[-1] != "nokernel":
            exploded.append(key)
            raise RuntimeError("simulated pallas compile crash")
        return real(key, build)

    monkeypatch.setattr(stats_mod, "_KERNEL_FAILED", set())
    monkeypatch.setattr(stats_mod, "_cached_jit", exploding)
    x = np.random.RandomState(93).randn(32, 4, 128)
    b = bolt.array(x, mesh)
    st = b.stats()
    assert np.allclose(np.asarray(st.mean()), x.mean(axis=0))
    assert np.allclose(np.asarray(st.variance()), x.var(axis=0))
    n_first = len(exploded)
    assert n_first >= 1
    b.stats()                              # memoised: no second attempt
    assert len(exploded) == n_first
