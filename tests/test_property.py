"""Property-based parity tests (hypothesis): random shapes/axes/indices on
the TPU backend must always agree with the NumPy oracle.  Complements the
reference's brute-force enumeration style with randomized coverage."""

import numpy as np
import pytest
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

import bolt_tpu as bolt
from bolt_tpu.utils import allclose

from tests.generic import HYPOTHESIS_SETTINGS as SETTINGS


@st.composite
def shaped_array(draw, min_dims=2, max_dims=4):
    ndim = draw(st.integers(min_dims, max_dims))
    shape = tuple(draw(st.integers(1, 5)) for _ in range(ndim))
    n = int(np.prod(shape))
    seed = draw(st.integers(0, 2 ** 16))
    x = np.random.RandomState(seed).randn(n).reshape(shape)
    return x


@st.composite
def array_and_split(draw):
    x = draw(shaped_array())
    split = draw(st.integers(1, x.ndim - 1))
    return x, split


@given(array_and_split())
@settings(**SETTINGS)
def test_construct_toarray_roundtrip(mesh, case):
    x, split = case
    b = bolt.array(x, mesh, axis=tuple(range(split)))
    assert b.split == split
    assert allclose(b.toarray(), x)


@given(array_and_split(), st.data())
@settings(**SETTINGS)
def test_swap_matches_algebra(mesh, case, data):
    x, split = case
    b = bolt.array(x, mesh, axis=tuple(range(split)))
    nv = x.ndim - split
    kaxes = data.draw(st.sets(st.integers(0, split - 1)).map(sorted))
    vaxes = data.draw(st.sets(st.integers(0, nv - 1)).map(sorted)) if nv else []
    if len(kaxes) == split and len(vaxes) == 0:
        return
    s = b.swap(tuple(kaxes), tuple(vaxes))
    keys_rest = [k for k in range(split) if k not in kaxes]
    values_rest = [v for v in range(nv) if v not in vaxes]
    perm = (keys_rest + [split + v for v in vaxes]
            + list(kaxes) + [split + v for v in values_rest])
    assert s.split == len(keys_rest) + len(vaxes)
    assert allclose(s.toarray(), np.transpose(x, perm))


@given(array_and_split(), st.data())
@settings(**SETTINGS)
def test_getitem_matches_numpy(mesh, case, data):
    x, split = case
    b = bolt.array(x, mesh, axis=tuple(range(split)))
    index = []
    for dim in x.shape:
        kind = data.draw(st.sampled_from(["int", "slice", "list", "all"]))
        if kind == "int":
            index.append(data.draw(st.integers(-dim, dim - 1)))
        elif kind == "slice":
            a = data.draw(st.integers(0, dim))
            c = data.draw(st.integers(1, 3))
            index.append(slice(a, None, c))
        elif kind == "list":
            index.append(data.draw(
                st.lists(st.integers(0, dim - 1), min_size=1, max_size=dim)))
        else:
            index.append(slice(None))
    got = b[tuple(index)].toarray()
    expected = np.asarray(x)
    # orthogonal per-axis application (the backend's documented semantics)
    offset = 0
    for ax, idx in enumerate(index):
        if isinstance(idx, int):
            expected = np.take(expected, idx % x.shape[ax], axis=ax - offset)
            offset += 1
        elif isinstance(idx, slice):
            sl = [slice(None)] * expected.ndim
            sl[ax - offset] = idx
            expected = expected[tuple(sl)]
        else:
            expected = np.take(expected, idx, axis=ax - offset)
    assert allclose(got, expected)


@given(array_and_split(), st.sampled_from(["mean", "sum", "max", "min", "var"]))
@settings(**SETTINGS)
def test_stats_match_numpy(mesh, case, name):
    x, split = case
    b = bolt.array(x, mesh, axis=tuple(range(split)))
    got = getattr(b, name)().toarray()
    expected = getattr(x, name)(axis=tuple(range(split)))
    assert allclose(got, np.asarray(expected))


@given(array_and_split())
@settings(**SETTINGS)
def test_map_reduce_parity(mesh, case):
    x, split = case
    axes = tuple(range(split))
    b = bolt.array(x, mesh, axis=axes)
    got = b.map(lambda v: v * 2 + 1, axis=axes).reduce(np.add, axis=axes)
    assert allclose(got.toarray(), (x * 2 + 1).sum(axis=axes))

@given(array_and_split(), st.data())
@settings(**SETTINGS)
def test_chunk_roundtrip_random_plans(mesh, case, data):
    x, split = case
    b = bolt.array(x, mesh, axis=tuple(range(split)))
    vshape = x.shape[split:]
    if not vshape:
        return
    # a random subset of value axes, random chunk sizes (ragged allowed),
    # random halo padding
    naxes = data.draw(st.integers(1, len(vshape)))
    axes = tuple(sorted(data.draw(
        st.sets(st.integers(0, len(vshape) - 1),
                min_size=naxes, max_size=naxes))))
    sizes = tuple(data.draw(st.integers(1, max(1, vshape[a])))
                  for a in axes)
    pad = data.draw(st.integers(0, 1))
    if pad >= min(sizes):   # framework guard: padding must be < chunk size
        pad = 0
    c = b.chunk(size=sizes, axis=axes, padding=pad if pad else None)
    out = c.map(lambda blk: blk * 2).unchunk()
    assert allclose(out.toarray(), x * 2)


@given(array_and_split(), st.data())
@settings(**SETTINGS)
def test_within_group_shaping(mesh, case, data):
    x, split = case
    b = bolt.array(x, mesh, axis=tuple(range(split)))
    nv = x.ndim - split
    # random within-group permutation
    kperm = data.draw(st.permutations(list(range(split))))
    vperm = data.draw(st.permutations(list(range(nv))))
    perm = tuple(kperm) + tuple(split + v for v in vperm)
    t = b.transpose(*perm)
    assert allclose(t.toarray(), np.transpose(x, perm))
    # value-group flatten via the Values view (order-preserving reshape)
    if nv:
        flat = b.values.reshape(int(np.prod(x.shape[split:])))
        assert allclose(flat.toarray(),
                        x.reshape(x.shape[:split] + (-1,)))


@given(array_and_split(), st.floats(-1.0, 1.0))
@settings(**SETTINGS)
def test_filter_parity_random_threshold(mesh, case, thresh):
    x, split = case
    axes = tuple(range(split))
    b = bolt.array(x, mesh, axis=axes)
    got = b.filter(lambda v: v.mean() > thresh, axis=axes)
    flat = x.reshape((-1,) + x.shape[split:])
    expected = flat[flat.mean(axis=tuple(range(1, flat.ndim))) > thresh]
    assert got.shape == expected.shape
    assert allclose(got.toarray(), expected)


@given(st.integers(1, 20), st.integers(1, 4), st.integers(0, 2 ** 16),
       st.sampled_from([1e-8, 1.0, 1e8]))
@settings(**SETTINGS)
def test_jacobi_eigh_matches_numpy(n, batch, seed, scale):
    # random symmetric batches across sizes (odd and even), scales, and
    # batch dims: eigenvalues must match LAPACK, vectors must diagonalize
    from bolt_tpu.ops import jacobi_eigh
    rs = np.random.RandomState(seed)
    a = rs.randn(batch, n, n) * scale
    a = (a + np.swapaxes(a, -1, -2)) / 2
    w, v = jacobi_eigh(a, vectors=True)
    w, v = np.asarray(w), np.asarray(v)
    ref = np.linalg.eigvalsh(a)
    anorm = np.abs(ref).max() + 1e-300
    assert np.max(np.abs(w - ref)) / anorm < 1e-10
    assert np.max(np.abs(a @ v - v * w[..., None, :])) / anorm < 1e-9


@given(st.integers(8, 40), st.integers(1, 6), st.integers(0, 2 ** 16),
       st.booleans())
@settings(**SETTINGS)
def test_pca_matches_numpy(mesh, n_extra, d, seed, center):
    # random sample/feature sizes: singular values must match float64 SVD
    from bolt_tpu.ops import pca
    n = d + n_extra
    rs = np.random.RandomState(seed)
    x = rs.randn(n, d)
    b = bolt.array(x, mesh, axis=(0,))
    _, _, svals = pca(b, center=center)
    ref = x - x.mean(axis=0, keepdims=True) if center else x
    expect = np.linalg.svd(ref, compute_uv=False)
    assert np.allclose(svals, expect, rtol=1e-8, atol=1e-10 * max(1.0, expect[0]))


@given(st.integers(1, 12), st.integers(0, 2 ** 16))
@settings(**SETTINGS)
def test_tsqr_properties(d, seed):
    from bolt_tpu.ops import tsqr
    import jax.numpy as jnp
    rs = np.random.RandomState(seed)
    x = rs.randn(4 * d + 8, d)
    q, r = tsqr(jnp.asarray(x))
    q, r = np.asarray(q), np.asarray(r)
    assert np.allclose(q.T @ q, np.eye(d), atol=1e-12)
    assert np.allclose(q @ r, x, atol=1e-12)
    assert np.allclose(np.tril(r, -1), 0.0, atol=1e-12)


@given(array_and_split(), st.data())
@settings(**SETTINGS)
def test_order_and_scan_stats_match_numpy(mesh, case, data):
    x, split = case
    b = bolt.array(x, mesh, axis=tuple(range(split)))
    q = data.draw(st.sampled_from([0.0, 0.1, 0.5, 0.75, 1.0]))
    assert allclose(b.quantile(q).toarray(),
                    np.quantile(x, q, axis=tuple(range(split))))
    axis = data.draw(st.integers(-x.ndim, x.ndim - 1))
    assert allclose(b.argmax(axis=axis).toarray(), np.argmax(x, axis=axis))
    assert allclose(b.argmin(axis=axis).toarray(), np.argmin(x, axis=axis))
    assert allclose(b.cumsum(axis=axis).toarray(), x.cumsum(axis=axis))
    assert allclose(b.median(axis=(x.ndim - 1,)).toarray(),
                    np.median(x, axis=x.ndim - 1))
