"""Unit tests for the mergeable StatCounter (reference:
``bolt/spark/statcounter.py`` unit coverage, SURVEY §4)."""

import numpy as np

from bolt_tpu.statcounter import StatCounter
from bolt_tpu.utils import allclose


def _x():
    rs = np.random.RandomState(7)
    return rs.randn(20, 4)


def test_merge_stream():
    x = _x()
    c = StatCounter(values=list(x))
    assert c.count() == 20
    assert allclose(c.mean(), x.mean(axis=0))
    assert allclose(c.variance(), x.var(axis=0))
    assert allclose(c.stdev(), x.std(axis=0))
    assert allclose(c.max(), x.max(axis=0))
    assert allclose(c.min(), x.min(axis=0))


def test_merge_stats_parallel():
    x = _x()
    # split into 3 uneven partitions, combine pairwise (Chan)
    parts = [x[:3], x[3:11], x[11:]]
    counters = [StatCounter(values=list(p)) for p in parts]
    total = counters[0].mergeStats(counters[1]).mergeStats(counters[2])
    assert total.count() == 20
    assert allclose(total.mean(), x.mean(axis=0))
    assert allclose(total.variance(), x.var(axis=0))


def test_merge_empty():
    x = _x()
    a = StatCounter()
    b = StatCounter(values=list(x))
    a.mergeStats(b)
    assert a.count() == 20
    assert allclose(a.mean(), x.mean(axis=0))
    b.mergeStats(StatCounter())
    assert b.count() == 20


def test_requested_subset():
    x = _x()
    c = StatCounter(values=list(x), stats=("mean",))
    assert allclose(c.mean(), x.mean(axis=0))


def test_sample_variance():
    x = _x()
    c = StatCounter(values=list(x))
    assert allclose(c.sampleVariance(), x.var(axis=0, ddof=1))
    assert allclose(c.sampleStdev(), x.std(axis=0, ddof=1))


def test_repr():
    c = StatCounter(values=[1.0, 2.0, 3.0])
    assert "count: 3" in repr(c)
