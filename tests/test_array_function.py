"""``__array_function__``: the non-ufunc numpy API on the TPU backend —
device-served with NUMPY semantics where the dispatch table covers it,
explicit (warned) host fallback otherwise (VERDICT r2 missing-3).  The
local backend is the oracle: it IS an ndarray, so plain numpy defines
every expected value."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.tpu import npdispatch


def _x():
    return np.random.RandomState(31).randn(16, 6, 4)


# (name, call) — run against the TPU bolt array; expectation is the same
# call on the raw numpy array (numpy semantics, not bolt's key-axis
# defaults)
DEVICE_CASES = [
    ("sum", lambda a: np.sum(a)),
    ("sum-axis", lambda a: np.sum(a, axis=1)),
    ("sum-keepdims", lambda a: np.sum(a, axis=(0, 2), keepdims=True)),
    ("prod", lambda a: np.prod(a / 2)),
    ("mean", lambda a: np.mean(a)),
    ("var", lambda a: np.var(a)),
    ("var-ddof", lambda a: np.var(a, ddof=1)),
    ("std-axis", lambda a: np.std(a, axis=0)),
    ("min", lambda a: np.min(a)),
    ("amax", lambda a: np.amax(a, axis=2)),
    ("ptp", lambda a: np.ptp(a, axis=1)),
    ("all", lambda a: np.all(a > -99)),
    ("any", lambda a: np.any(a > 1, axis=0)),
    ("cumsum", lambda a: np.cumsum(a)),
    ("cumsum-axis", lambda a: np.cumsum(a, axis=1)),
    ("cumprod-axis", lambda a: np.cumprod(a, axis=2)),
    ("argmax", lambda a: np.argmax(a)),
    ("argmin-axis", lambda a: np.argmin(a, axis=1)),
    ("quantile", lambda a: np.quantile(a, 0.3)),
    ("quantile-vector", lambda a: np.quantile(a, [0.2, 0.8], axis=0)),
    ("percentile", lambda a: np.percentile(a, 75)),
    ("median", lambda a: np.median(a)),
    ("median-axis", lambda a: np.median(a, axis=1)),
    ("sort", lambda a: np.sort(a, axis=0)),
    ("sort-flat", lambda a: np.sort(a, axis=None)),
    ("argsort", lambda a: np.argsort(a, axis=2, kind="stable")),
    ("take", lambda a: np.take(a, [3, 1], axis=0)),
    ("take-flat", lambda a: np.take(a, [5, 0, 17])),
    ("repeat", lambda a: np.repeat(a, 2, axis=1)),
    ("nonzero", lambda a: np.nonzero(a > 1.5)),
    ("ravel", lambda a: np.ravel(a)),
    ("transpose", lambda a: np.transpose(a, (0, 2, 1))),
    ("squeeze", lambda a: np.squeeze(a[0:1])),
    ("swapaxes", lambda a: np.swapaxes(a, 1, 2)),
    ("count_nonzero", lambda a: np.count_nonzero(np.round(a))),
    ("count_nonzero-axis", lambda a: np.count_nonzero(np.round(a), axis=1)),
    ("diff", lambda a: np.diff(a)),
    ("diff-axis0-n2", lambda a: np.diff(a, n=2, axis=0)),
    ("diff-n0", lambda a: np.diff(a, n=0)),
    ("flip", lambda a: np.flip(a)),
    ("flip-axis", lambda a: np.flip(a, 1)),
    ("flip-neg-axis", lambda a: np.flip(a, (-1, 0))),
    ("moveaxis", lambda a: np.moveaxis(a, 1, 2)),
    ("moveaxis-neg", lambda a: np.moveaxis(a, -1, 1)),
    ("moveaxis-multi", lambda a: np.moveaxis(a, (1, 2), (2, 1))),
    ("clip", lambda a: np.clip(a, -0.5, 0.5)),
    ("round", lambda a: np.round(a, 1)),
    ("real", lambda a: np.real(a)),
    ("imag", lambda a: np.imag(a)),
    ("diagonal", lambda a: np.diagonal(a, 0, 1, 2)),
    ("trace", lambda a: np.trace(a, 0, 1, 2)),
    ("searchsorted", lambda a: np.searchsorted(a, [0.0, 0.5])),
]


@pytest.mark.parametrize("name,call", DEVICE_CASES,
                         ids=[c[0] for c in DEVICE_CASES])
def test_numpy_semantics_parity(mesh, name, call):
    x = _x()
    if name == "searchsorted":
        x = np.sort(x.ravel())
    b = bolt.array(x, mesh)
    expect = call(x)
    got = call(b)

    def norm(v):
        if isinstance(v, tuple):
            return tuple(np.asarray(i) for i in v)
        return np.asarray(v.toarray() if hasattr(v, "toarray") else v)

    g, e = norm(got), norm(expect)
    if isinstance(e, tuple):
        assert all(np.array_equal(a, b_) for a, b_ in zip(g, e)), name
    else:
        assert g.shape == e.shape, (name, g.shape, e.shape)
        assert np.allclose(g, e, equal_nan=True), name


def test_device_served_no_gather(mesh, monkeypatch):
    # the acceptance check: np.sum(b) runs ON DEVICE — no toarray, no
    # __array__, and instrument() shows the stat-family program running
    import bolt_tpu.profile as profile
    x = _x()
    b = bolt.array(x, mesh)
    monkeypatch.setattr(
        type(b), "toarray",
        lambda self: (_ for _ in ()).throw(AssertionError("gathered!")))
    monkeypatch.setattr(
        type(b), "__array__",
        lambda self, dtype=None: (_ for _ in ()).throw(
            AssertionError("implicit __array__!")))
    with profile.instrument() as stats:
        # .cache() dispatches each LAZY stat on device — still no
        # toarray/__array__ anywhere in the path
        out = np.sum(b).cache()
        np.mean(b, axis=0).cache()
        np.sort(b, axis=1)
        np.concatenate([b, b], axis=2)
    assert out.mode == "tpu" and out.split == 0
    assert stats.get("stat", {}).get("calls", 0) >= 2
    assert stats.get("sort", {}).get("calls", 0) == 1
    assert stats.get("concat", {}).get("calls", 0) == 1


def test_np_sort_functional_does_not_mutate(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    s = np.sort(b, axis=0)
    assert np.allclose(b.toarray(), x)              # original untouched
    assert np.allclose(s.toarray(), np.sort(x, axis=0))
    # deferred chain: np.sort of a mapped array leaves the map intact
    m = bolt.array(x, mesh).map(lambda v: v * 2)
    s2 = np.sort(m, axis=0)
    assert np.allclose(s2.toarray(), np.sort(x * 2, axis=0))
    assert np.allclose(m.toarray(), x * 2)


def test_concatenate_mixed_operands(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    lo = bolt.array(x)
    # device-first: stays on device
    out = np.concatenate([b, lo, x], axis=0)
    assert hasattr(out, "mode") and out.mode == "tpu"
    assert np.allclose(out.toarray(), np.concatenate([x, x, x], axis=0))
    # host-first: falls back to plain numpy (host result)
    out2 = np.concatenate([x, b], axis=0)
    assert isinstance(out2, np.ndarray)
    assert np.allclose(out2, np.concatenate([x, x], axis=0))


def test_concatenate_axis_none_and_one_program(mesh):
    # axis=None flattens every operand, like numpy — including mixed
    # ranks and split>1 (r3 review finding: this used to crash)
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    out = np.concatenate([b, b], axis=None)
    assert np.allclose(out.toarray(), np.concatenate([x, x], axis=None))
    assert out.split == 1
    mixed = np.concatenate([b, bolt.array(x[0, 0], mesh)], axis=None)
    assert np.allclose(mixed.toarray(),
                       np.concatenate([x, x[0, 0]], axis=None))
    # n operands are ONE compiled program, not n-1 pairwise copies
    from bolt_tpu.tpu import array as array_mod
    b1 = bolt.array(x, mesh)
    n_before = sum(1 for k in array_mod._JIT_CACHE if k[0] == "concat")
    out = np.concatenate([b1, b1, b1, b1], axis=1)
    assert np.allclose(out.toarray(), np.concatenate([x] * 4, axis=1))
    assert sum(1 for k in array_mod._JIT_CACHE
               if k[0] == "concat") == n_before + 1


class _Duck:
    """A foreign duck array implementing __array_function__."""

    def __array_function__(self, func, types, args, kwargs):
        return "duck-served"


def test_nep18_defers_to_unknown_duck_types(mesh):
    # an operand type we don't recognize gets NotImplemented so ITS
    # handler runs (r3 review finding: bolt used to hijack the call)
    b = bolt.array(_x(), mesh)
    assert np.concatenate([b, _Duck()]) == "duck-served"


def test_searchsorted_rejects_float_sorter(mesh):
    x = np.sort(np.random.RandomState(13).randn(8))
    for b in (bolt.array(x), bolt.array(x, mesh)):
        with pytest.raises(TypeError, match="integer"):
            b.searchsorted(0.0, sorter=np.array([0.2, 2.9, 1.5, 0, 1, 2, 3, 4]))


def test_unsupported_kwargs_fall_back_correctly(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    # out= cannot be served on device; host fallback still honours it
    out = np.zeros(())
    np.sum(b, out=out)
    assert np.allclose(out, x.sum())
    # dtype= falls back and matches numpy exactly
    assert np.allclose(np.sum(b, dtype=np.float32), x.sum(dtype=np.float32))
    # unhandled function (np.trim_zeros) → host path, numpy result
    v = bolt.array(np.array([0.0, 0.0, 1.0, 2.0, 0.0]), mesh)
    st = np.trim_zeros(v)
    assert isinstance(st, np.ndarray)
    assert np.allclose(st, [1.0, 2.0])


def test_implicit_gather_warns_once_above_threshold(mesh, monkeypatch):
    x = _x()
    b = bolt.array(x, mesh)
    monkeypatch.setattr(npdispatch, "IMPLICIT_GATHER_WARN_BYTES", 64)
    monkeypatch.setattr(npdispatch, "_warned", [False])
    with pytest.warns(UserWarning, match="implicitly gathered"):
        np.asarray(b)
    # once per session: the second gather is silent
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        np.asarray(b)
    # explicit toarray never warns
    monkeypatch.setattr(npdispatch, "_warned", [False])
    with _w.catch_warnings():
        _w.simplefilter("error")
        b.toarray()


def test_small_gather_is_silent(mesh, monkeypatch):
    monkeypatch.setattr(npdispatch, "_warned", [False])
    b = bolt.array(_x(), mesh)          # ~3 KB << threshold
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        np.asarray(b)


def test_shape_ndim_size(mesh):
    b = bolt.array(_x(), mesh)
    assert np.shape(b) == (16, 6, 4)
    assert np.ndim(b) == 3
    assert np.size(b) == 384
    assert np.size(b, 1) == 6


def test_np_diff_validation(mesh):
    b = bolt.array(_x(), mesh)
    with pytest.raises(ValueError, match="non-negative"):
        np.diff(b, n=-1)
    with pytest.raises(ValueError):
        np.diff(b, axis=7)
    # prepend/append aren't device-served: host fallback, same answer
    got = np.diff(b, axis=0, prepend=0.0)
    assert np.allclose(got, np.diff(_x(), axis=0, prepend=0.0))
    # bool diff is XOR, like numpy (subtract rejects bool)
    xb = _x() > 0
    gb = np.diff(bolt.array(xb, mesh), axis=0)
    assert gb.dtype == np.bool_
    assert np.array_equal(np.asarray(gb.toarray()), np.diff(xb, axis=0))


def test_np_flip_validation(mesh):
    b = bolt.array(_x(), mesh)
    with pytest.raises(ValueError):
        np.flip(b, 5)                   # out-of-range axis
    with pytest.raises(ValueError):
        np.flip(b, (1, -2))             # duplicate after normalization


def test_np_moveaxis_validation(mesh):
    b = bolt.array(_x(), mesh)
    with pytest.raises(ValueError):
        np.moveaxis(b, 0, -4)            # doubly-negative destination
    with pytest.raises(ValueError):
        np.moveaxis(b, (0, 1), (0, 0))   # repeated destination
    with pytest.raises(ValueError):
        np.moveaxis(b, 5, 0)             # out-of-range source
    with pytest.raises(ValueError):
        np.moveaxis(b, (0, 1), (0,))     # length mismatch


def test_np_split(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    for args in [(4,), (2, 1), (np.array([3, 9]),), ([2, -4],),
                 ([3, 1],)]:
        got = np.split(b, *args) if len(args) == 1 \
            else np.split(b, args[0], axis=args[1])
        want = np.split(x, *args) if len(args) == 1 \
            else np.split(x, args[0], axis=args[1])
        assert len(got) == len(want), args
        for g, w in zip(got, want):
            assert hasattr(g, "mode") and g.mode == "tpu", args
            assert np.allclose(np.asarray(g.toarray()), w), args
    # strict split of a non-dividing count errors like numpy; the
    # array_split form serves it
    with pytest.raises(ValueError, match="equal division"):
        np.split(b, 5)
    got = np.array_split(b, 5)
    want = np.array_split(x, 5)
    assert [g.shape for g in got] == [w.shape for w in want]
    for g, w in zip(got, want):
        assert np.allclose(np.asarray(g.toarray()), w)
    with pytest.raises(ValueError):
        np.split(b, 0)
    # numpy's probe semantics: a 0-d array is a SECTION count, float
    # index entries raise like numpy's slices
    got = np.split(b, np.array(4))
    assert len(got) == 4 and got[0].shape[0] == 4
    with pytest.raises(TypeError):
        np.split(b, [2.5])


def test_np_where(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    # 3-arg: device-served, bolt result, numpy broadcasting
    out = np.where(b > 0, b, 0.0)
    assert hasattr(out, "mode") and out.mode == "tpu" and out.split == 1
    assert np.allclose(np.asarray(out.toarray()), np.where(x > 0, x, 0.0))
    out2 = np.where(x > 1, b, b * -1.0)        # host cond + two device
    assert np.allclose(np.asarray(out2.toarray()),
                       np.where(x > 1, x, -x))
    out3 = np.where(b[0] > 0, 1.0, np.arange(4.0))   # broadcast scalars
    assert np.allclose(np.asarray(out3.toarray()),
                       np.where(x[0] > 0, 1.0, np.arange(4.0)))
    # 1-arg form IS nonzero
    got = np.where(bolt.array((x > 1).astype(int), mesh))
    want = np.where((x > 1).astype(int))
    assert len(got) == len(want)
    assert all(np.array_equal(a, b_) for a, b_ in zip(got, want))
    with pytest.raises(ValueError, match="both or neither"):
        np.where(b, 1.0)
    # a broadcast-prepended axis displaces the keys: split drops to 0
    # even when the leading sizes coincide (r3 review finding)
    cond = np.ones((16, 16, 6, 4), bool)
    out4 = np.where(cond, b, 0.0)
    assert out4.shape == (16, 16, 6, 4) and out4.split == 0
    assert np.allclose(np.asarray(out4.toarray()),
                       np.where(cond, x, 0.0))
    # foreign-mesh operand rejected loudly
    import jax
    other = bolt.array(x, jax.make_mesh((4, 2), ("a", "b")))
    with pytest.raises(ValueError, match="different meshes"):
        np.where(b > 0, b, other)


def test_np_histogram_and_bincount(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    c, e = np.histogram(b, bins=8)
    cn, en = np.histogram(x, bins=8)
    assert np.array_equal(c, cn) and np.allclose(e, en)
    c2, e2 = np.histogram(b, bins=6, range=(-1, 1), density=True)
    cn2, en2 = np.histogram(x, bins=6, range=(-1, 1), density=True)
    assert np.allclose(c2, cn2) and np.allclose(e2, en2)
    # explicit bin-edge arrays fall back to the host path, same answer
    edges = np.linspace(-2, 2, 5)
    c3, e3 = np.histogram(b, bins=edges)
    cn3, _ = np.histogram(x, bins=edges)
    assert np.array_equal(c3, cn3)
    iv = bolt.array((np.abs(x[0]) * 4).astype(np.int64).ravel(), mesh)
    ivn = (np.abs(x[0]) * 4).astype(np.int64).ravel()
    assert np.array_equal(np.bincount(iv), np.bincount(ivn))
    assert np.array_equal(np.bincount(iv, minlength=20),
                          np.bincount(ivn, minlength=20))
    # 2-d input: numpy's exact error on both backends
    with pytest.raises(ValueError):
        np.bincount(bolt.array((np.abs(x) * 4).astype(np.int64), mesh))
    # numpy's edge-case rejections hold on the device path too
    with pytest.raises(ValueError, match="negative"):
        np.bincount(iv, minlength=-1)
    with pytest.raises(ValueError, match="finite"):
        np.histogram(b, bins=4, range=(np.nan, np.nan))
    with pytest.raises(ValueError, match="finite"):
        np.histogram(b, bins=4, range=(0.0, np.inf))


def test_np_unique_and_dot(mesh):
    x = np.floor(_x() * 2)
    b = bolt.array(x, mesh)
    u, c = np.unique(b, return_counts=True)
    un, cn = np.unique(x, return_counts=True)
    assert np.array_equal(u, un) and np.array_equal(c, cn)
    # unsupported unique options take the host path, same answer
    u2, inv = np.unique(b, return_inverse=True)
    un2, invn = np.unique(x, return_inverse=True)
    assert np.array_equal(u2, un2) and np.array_equal(inv, invn)
    # np.dot with a device left operand stays on device
    w = np.random.RandomState(3).randn(4, 2)
    d = np.dot(b, w)
    assert hasattr(d, "mode") and d.mode == "tpu"
    assert np.allclose(d.toarray(), x @ w)


# ----------------------------------------------------------------------
# round 4 (VERDICT r3 next-2): the dispatch tail — stack family, layout
# expanders, contractions, cov/corrcoef.  Each case runs on a split=1
# array over the 1-d mesh AND a split=2 array over the 2-d mesh; the
# expectation is plain numpy on the host array.
# ----------------------------------------------------------------------

def _x2():
    return np.random.RandomState(41).randn(8, 6, 4)


TAIL_CASES = [
    ("expand_dims", lambda a: np.expand_dims(a, 1)),
    ("expand_dims-multi", lambda a: np.expand_dims(a, (0, -1))),
    ("expand_dims-boundary", lambda a: np.expand_dims(a, 2)),
    ("broadcast_to-prepend", lambda a: np.broadcast_to(a, (3,) + np.shape(a))),
    ("broadcast_to-same", lambda a: np.broadcast_to(a, np.shape(a))),
    ("tile-scalar", lambda a: np.tile(a, 2)),
    ("tile-tuple", lambda a: np.tile(a, (2, 1, 3))),
    ("tile-longer", lambda a: np.tile(a, (2, 1, 1, 2))),
    ("roll-flat", lambda a: np.roll(a, 5)),
    ("roll-axis", lambda a: np.roll(a, 3, axis=0)),
    ("roll-multi", lambda a: np.roll(a, (1, -2), axis=(0, 2))),
    ("roll-neg-axis", lambda a: np.roll(a, 2, axis=-1)),
    ("rot90-values", lambda a: np.rot90(a, 1, axes=(1, 2))),
    ("rot90-k2-cross", lambda a: np.rot90(a, 2, axes=(0, 2))),
    ("rot90-k0", lambda a: np.rot90(a, 4, axes=(1, 2))),
    ("pad-scalar", lambda a: np.pad(a, 2)),
    ("pad-pairs", lambda a: np.pad(a, ((1, 2), (0, 1), (2, 0)))),
    ("pad-const", lambda a: np.pad(a, 1, constant_values=7.5)),
    ("pad-reflect", lambda a: np.pad(a, 2, mode="reflect")),
    ("pad-reflect-odd", lambda a: np.pad(a, 2, mode="reflect",
                                         reflect_type="odd")),
    ("pad-symmetric", lambda a: np.pad(a, 1, mode="symmetric")),
    ("pad-wrap", lambda a: np.pad(a, 3, mode="wrap")),
    ("pad-edge", lambda a: np.pad(a, 2, mode="edge")),
    ("stack-0", lambda a: np.stack([a, a])),
    ("stack-mid", lambda a: np.stack([a, a, a], axis=2)),
    ("stack-neg", lambda a: np.stack([a, a], axis=-1)),
    ("vstack", lambda a: np.vstack([a, a])),
    ("hstack", lambda a: np.hstack([a, a])),
    ("dstack", lambda a: np.dstack([a, a])),
    ("append-axis", lambda a: np.append(a, np.ones_like(np.asarray(a)),
                                        axis=1)),
    ("append-flat", lambda a: np.append(a, [1.0, 2.0])),
    ("einsum-explicit", lambda a: np.einsum("ijk,ijk->ij", a, a)),
    ("einsum-contract-keys", lambda a: np.einsum("ijk->k", a)),
    ("einsum-implicit", lambda a: np.einsum("ijk,kl", a,
                                            np.ones((4, 5)))),
    ("einsum-transpose-out", lambda a: np.einsum("ijk->kji", a)),
    ("tensordot-axes", lambda a: np.tensordot(
        a, np.ones((6, 4, 3)), axes=([1, 2], [0, 1]))),
    ("tensordot-int", lambda a: np.tensordot(a, np.ones((6, 4)), axes=2)),
    ("inner-vec", lambda a: np.inner(a, np.arange(4.0))),
    ("outer", lambda a: np.outer(a, np.arange(3.0))),
    ("atleast-1d", lambda a: np.atleast_1d(a)),
    ("atleast-3d", lambda a: np.atleast_3d(a)),
    ("copy", lambda a: np.copy(a)),
]


@pytest.mark.parametrize("layout", ["keys1d", "keys2d"])
@pytest.mark.parametrize("name,call", TAIL_CASES,
                         ids=[c[0] for c in TAIL_CASES])
def test_dispatch_tail_parity(request, layout, name, call):
    if name == "append-flat" and layout == "keys2d":
        # known old-jax residual (seed-present): 0.4.x GSPMD
        # mis-replicates the flatten of a 2-d-sharded key layout inside
        # the fused flat-append program, so the appended values appear
        # once per device group (x4 on the (4, 2) mesh).  Marker-based
        # (not imperative pytest.xfail) so a fix shows up as XPASS.
        from bolt_tpu._compat import OLD_JAX
        request.node.add_marker(pytest.mark.xfail(
            condition=OLD_JAX, strict=False,
            reason="jax 0.4.x GSPMD replicates the keys2d flatten in "
                   "the flat np.append program (values x4); fixed on "
                   "runtimes with jax.shard_map"))
    if layout == "keys1d":
        m, axis = request.getfixturevalue("mesh"), (0,)
    else:
        m, axis = request.getfixturevalue("mesh2d"), (0, 1)
    x = _x2()
    b = bolt.array(x, m, axis=axis)
    if name == "rot90-values" and layout == "keys2d":
        # on the split=2 layout axes (1, 2) straddle the key/value
        # boundary: the odd rotation rejects like transpose does
        with pytest.raises(ValueError, match="swap"):
            call(b)
        return
    expect = call(x)
    got = call(b)
    g = np.asarray(got.toarray() if hasattr(got, "toarray") else got)
    e = np.asarray(expect)
    assert g.shape == e.shape, (name, g.shape, e.shape)
    assert np.allclose(g, e, equal_nan=True), name


@pytest.mark.parametrize("layout", ["keys1d", "keys2d"])
def test_cov_corrcoef_parity(request, layout):
    m = request.getfixturevalue("mesh" if layout == "keys1d" else "mesh2d")
    axis = (0,) if layout == "keys1d" else (0, 1)
    x = np.random.RandomState(42).randn(8, 6)
    b = bolt.array(x, m, axis=axis)
    assert np.allclose(np.cov(b), np.cov(x))
    assert np.allclose(np.cov(b, rowvar=False), np.cov(x, rowvar=False))
    assert np.allclose(np.cov(b, bias=True), np.cov(x, bias=True))
    assert np.allclose(np.cov(b, ddof=0), np.cov(x, ddof=0))
    assert np.allclose(np.corrcoef(b), np.corrcoef(x))
    assert np.allclose(np.corrcoef(b, rowvar=False),
                       np.corrcoef(x, rowvar=False))
    # 1-d: 0-d result, like numpy
    v = x[:, 0]
    bv = bolt.array(v, m) if layout == "keys1d" else bolt.array(v, m)
    assert np.shape(np.cov(bv)) == np.shape(np.cov(v)) == ()
    assert np.allclose(np.cov(bv), np.cov(v))
    assert np.allclose(np.corrcoef(bv), np.corrcoef(v))


def test_dispatch_tail_stays_on_device(mesh, monkeypatch):
    # the acceptance check for the round-4 tail: these calls may not
    # gather — toarray/__array__ are booby-trapped
    x = _x2()
    b = bolt.array(x, mesh)
    monkeypatch.setattr(
        type(b), "toarray",
        lambda self: (_ for _ in ()).throw(AssertionError("gathered!")))
    monkeypatch.setattr(
        type(b), "__array__",
        lambda self, dtype=None: (_ for _ in ()).throw(
            AssertionError("implicit __array__!")))
    np.expand_dims(b, 0)
    np.broadcast_to(b, (2, 8, 6, 4))
    np.tile(b, (2, 1, 1))
    np.roll(b, 3, axis=1)
    np.rot90(b, axes=(1, 2))
    np.pad(b, 1)
    np.stack([b, b], axis=1)
    np.vstack([b, b])
    np.hstack([b, b])
    np.dstack([b, b])
    np.append(b, b, axis=0)
    np.einsum("ijk,ijk->i", b, b)
    np.tensordot(b, np.ones((4, 2)), axes=([2], [0]))
    np.inner(b, np.ones(4))
    np.outer(b, np.ones(3))
    np.copy(b)
    np.atleast_3d(b)


def test_dispatch_tail_deferred_chains_fuse(mesh):
    # a deferred map fuses into the tail's ONE compiled program and the
    # original chain stays intact
    x = _x2()
    b = bolt.array(x, mesh).map(lambda v: v * 2.0)
    out = np.stack([b, b], axis=0)
    assert np.allclose(out.toarray(), np.stack([x * 2, x * 2], axis=0))
    s = np.roll(b, 2, axis=0)
    assert np.allclose(s.toarray(), np.roll(x * 2, 2, axis=0))
    assert np.allclose(b.toarray(), x * 2)


def test_dispatch_tail_rejections(mesh):
    x = _x2()
    b = bolt.array(x, mesh)
    # numpy-exact rejections on the device path
    with pytest.raises(ValueError, match="repeated axis"):
        np.expand_dims(b, (0, 0))
    with pytest.raises(np.exceptions.AxisError):
        np.expand_dims(b, 9)
    with pytest.raises(ValueError):
        np.broadcast_to(b, (2, 2, 2))
    with pytest.raises(np.exceptions.AxisError):
        np.roll(b, 1, axis=5)
    with pytest.raises(ValueError, match="must be different"):
        np.rot90(b, axes=(1, 1))
    with pytest.raises(ValueError, match="len\\(axes\\)"):
        np.rot90(b, axes=(0, 1, 2))
    with pytest.raises(ValueError, match="out of range"):
        np.rot90(b, axes=(0, 5))
    # odd rotations across the key/value boundary: the transpose rule
    with pytest.raises(ValueError, match="swap"):
        np.rot90(b, 1, axes=(0, 1))
    # even rotations are pure flips — allowed across the boundary
    assert np.allclose(np.rot90(b, 2, axes=(0, 1)).toarray(),
                       np.rot90(x, 2, axes=(0, 1)))
    with pytest.raises(ValueError, match="negative"):
        np.pad(b, -1)
    with pytest.raises(TypeError, match="integral"):
        np.pad(b, 1.5)
    with pytest.raises(ValueError, match="unsupported keyword"):
        np.pad(b, 1, mode="edge", constant_values=3)
    with pytest.raises(ValueError, match="same shape"):
        np.stack([b, bolt.array(x[:4], mesh)])
    with pytest.raises(np.exceptions.AxisError):
        np.stack([b, b], axis=7)
    with pytest.raises(ValueError, match="2 dimensions"):
        np.cov(bolt.array(np.random.RandomState(1).randn(2, 3, 4), mesh))
    with pytest.raises(ValueError, match="ddof"):
        np.cov(bolt.array(np.random.RandomState(1).randn(4, 3), mesh),
               ddof=1.5)


def test_dispatch_tail_fallbacks_stay_correct(mesh):
    # unsupported forms take the warned host path but remain
    # numpy-correct
    x = _x2()
    b = bolt.array(x, mesh)
    out = np.einsum("i...,i...->...", b, b)     # ellipsis: device (r4)
    assert hasattr(out, "mode") and out.mode == "tpu"
    assert np.allclose(out.toarray(), np.einsum("i...,i...->...", x, x))
    out2 = np.pad(b, 1, mode="mean")                 # stat mode: host
    assert np.allclose(out2, np.pad(x, 1, mode="mean"))
    out3 = np.pad(b, 1, mode="linear_ramp", end_values=2.0)
    assert np.allclose(out3, np.pad(x, 1, mode="linear_ramp",
                                    end_values=2.0))
    # weighted cov: host path, numpy-exact
    w = np.arange(1, 7)
    out4 = np.cov(bolt.array(x[:, :, 0], mesh), fweights=w)
    assert np.allclose(out4, np.cov(x[:, :, 0], fweights=w))


def test_einsum_key_survival_and_mxu_policy(mesh, mesh2d):
    # keys survive when the anchor's key labels lead the output
    x = _x2()
    b = bolt.array(x, mesh)
    out = np.einsum("ijk,kl->ijl", b, np.ones((4, 3)))
    assert out.split == 1
    assert np.allclose(out.toarray(),
                       np.einsum("ijk,kl->ijl", x, np.ones((4, 3))))
    # keys contracted: re-keyed to split=0
    out2 = np.einsum("ijk->jk", b)
    assert out2.split == 0
    # split=2 anchor over the 2-d mesh, both keys surviving
    b2 = bolt.array(x, mesh2d, axis=(0, 1))
    out3 = np.einsum("ijk,k->ij", b2, np.arange(4.0))
    assert out3.split == 2
    assert np.allclose(out3.toarray(), np.einsum("ijk,k->ij", x,
                                                 np.arange(4.0)))


def test_stack_family_split_bookkeeping(mesh, mesh2d):
    x = _x2()
    b = bolt.array(x, mesh)
    assert np.stack([b, b], axis=0).split == 2     # new leading key axis
    assert np.stack([b, b], axis=1).split == 1     # value-side insert
    assert np.expand_dims(b, 0).split == 2
    assert np.expand_dims(b, 1).split == 1         # at the boundary: value
    assert np.broadcast_to(b, (2,) + x.shape).split == 2
    assert np.tile(b, (3, 1, 1, 1)).split == 2
    b2 = bolt.array(x, mesh2d, axis=(0, 1))
    assert np.stack([b2, b2], axis=1).split == 3   # inserted among keys
    assert np.roll(b2, 1, axis=0).split == 2


def test_dispatch_tail_review_edges(mesh):
    # round-4 review findings: numpy-exact edge behavior
    x = _x2()
    b = bolt.array(x, mesh)
    # empty shift/axis tuples broadcast to zero rolls — unchanged copy
    assert np.allclose(np.roll(b, 1, axis=()).toarray(),
                       np.roll(x, 1, axis=()))
    assert np.allclose(np.roll(b, (), axis=()).toarray(), x)
    assert np.allclose(np.roll(b, (), axis=0).toarray(), x)
    # stack-family shape clashes are numpy's ValueError, not a jax
    # TypeError from inside the trace
    with pytest.raises(ValueError, match="must match exactly"):
        np.vstack([b, np.ones((3, 6, 4))[..., :3]])
    with pytest.raises(ValueError, match="same number of dimensions"):
        np.hstack([b, np.ones(3)])
    # non-default casting routes to the host path so numpy's TypeError
    # is preserved
    with pytest.raises(TypeError, match="Cannot cast"):
        np.stack([b.astype(np.float32), b], casting="no", dtype=np.float64)


# ----------------------------------------------------------------------
# round 4 batch 2: nan-reductions, norms, sampling helpers — device-
# served with numpy semantics, both mesh layouts
# ----------------------------------------------------------------------

def _xnan():
    x = np.random.RandomState(43).randn(8, 6, 4)
    x.ravel()[::17] = np.nan
    return x


TAIL2_CASES = [
    ("nansum", lambda a: np.nansum(a)),
    ("nansum-axis", lambda a: np.nansum(a, axis=1)),
    ("nanmean-keepdims", lambda a: np.nanmean(a, axis=(0, 2),
                                              keepdims=True)),
    ("nanvar-ddof", lambda a: np.nanvar(a, axis=0, ddof=1)),
    ("nanstd", lambda a: np.nanstd(a)),
    ("nanmin-axis", lambda a: np.nanmin(a, axis=2)),
    ("nanmax", lambda a: np.nanmax(a)),
    ("nanprod-axis", lambda a: np.nanprod(a / 2, axis=1)),
    ("nanmedian-axis", lambda a: np.nanmedian(a, axis=0)),
    ("nanquantile", lambda a: np.nanquantile(a, 0.3)),
    ("nanquantile-vector", lambda a: np.nanquantile(a, [0.2, 0.8],
                                                    axis=0)),
]

TAIL2_CLEAN = [
    ("norm-fro-all", lambda a: np.linalg.norm(a)),
    ("norm-axis", lambda a: np.linalg.norm(a, axis=2)),
    ("norm-ord1", lambda a: np.linalg.norm(a, ord=1, axis=1)),
    ("norm-inf", lambda a: np.linalg.norm(a, ord=np.inf, axis=0)),
    ("average", lambda a: np.average(a)),
    ("average-axis", lambda a: np.average(a, axis=1)),
    ("average-weights", lambda a: np.average(
        a, axis=1, weights=np.arange(1.0, 7.0))),
    ("average-full-weights", lambda a: np.average(
        a, weights=np.abs(np.asarray(a)) + 1.0)),
    ("isin", lambda a: np.isin(np.round(a), [0.0, 1.0, -1.0])),
    ("isin-invert", lambda a: np.isin(np.round(a), [0.0], invert=True)),
    ("digitize", lambda a: np.digitize(a, np.linspace(-2, 2, 9))),
    ("digitize-right", lambda a: np.digitize(a, np.linspace(-2, 2, 9),
                                             right=True)),
    ("interp", lambda a: np.interp(a, np.linspace(-3, 3, 11),
                                   np.linspace(0.0, 1.0, 11))),
    ("gradient-axis", lambda a: np.gradient(a, axis=1)),
    ("gradient-spacing", lambda a: np.gradient(a, 0.5, axis=2)),
]


@pytest.mark.parametrize("layout", ["keys1d", "keys2d"])
@pytest.mark.parametrize(
    "name,call", TAIL2_CASES + TAIL2_CLEAN,
    ids=[c[0] for c in TAIL2_CASES + TAIL2_CLEAN])
def test_dispatch_tail2_parity(request, layout, name, call):
    if layout == "keys1d":
        m, axis = request.getfixturevalue("mesh"), (0,)
    else:
        m, axis = request.getfixturevalue("mesh2d"), (0, 1)
    x = _xnan() if (name.startswith("nan")) else _x2()[:8]
    b = bolt.array(x, m, axis=axis)
    expect = call(x)
    got = call(b)

    def norm(v):
        return np.asarray(v.toarray() if hasattr(v, "toarray") else v)

    g, e = norm(got), norm(expect)
    assert g.shape == e.shape, (name, g.shape, e.shape)
    assert np.allclose(g, e, equal_nan=True), name


def test_dispatch_tail2_details(mesh):
    x = _x2()[:8]
    b = bolt.array(x, mesh)
    # gradient over every axis returns a list of device arrays
    outs = np.gradient(b)
    expects = np.gradient(x)
    assert isinstance(outs, list) and len(outs) == 3
    for o, e in zip(outs, expects):
        assert o.mode == "tpu" and o.split == 1
        assert np.allclose(o.toarray(), e)
    # average(returned=True) matches numpy's (avg, sum-of-weights) pair
    avg, scl = np.average(b, axis=0, returned=True)
    ea, es = np.average(x, axis=0, returned=True)
    assert np.allclose(avg.toarray(), ea) and np.allclose(scl, es)
    w = np.arange(1.0, 7.0)
    avg2, scl2 = np.average(b, axis=1, weights=w, returned=True)
    ea2, es2 = np.average(x, axis=1, weights=w, returned=True)
    assert np.allclose(avg2.toarray(), ea2) and np.allclose(scl2, es2)
    # keys survive value-axis reductions, die on key-axis ones
    assert np.nansum(bolt.array(_xnan(), mesh), axis=2).split == 1
    assert np.nansum(bolt.array(_xnan(), mesh), axis=0).split == 0
    assert np.linalg.norm(b, axis=2).split == 1
    # numpy-exact rejections
    with pytest.raises(ValueError, match="Length of weights"):
        np.average(b, axis=1, weights=np.arange(5.0))
    with pytest.raises(ZeroDivisionError):
        np.average(b, axis=1, weights=np.zeros(6))
    with pytest.raises(ValueError, match="at least 2 elements"):
        np.gradient(bolt.array(x[:1], mesh), axis=0)
    with pytest.raises(ValueError, match="same length"):
        np.interp(b, np.arange(4.0), np.arange(5.0))
    with pytest.raises(ValueError, match="1-D"):
        np.interp(b, np.ones((2, 2)), np.ones((2, 2)))
    # nan-aware semantics really differ from the plain reductions here
    xb = bolt.array(_xnan(), mesh)
    assert np.isnan(float(np.asarray(np.sum(xb).toarray())))
    assert not np.isnan(float(np.asarray(np.nansum(xb).toarray())))


def test_dispatch_tail2_split_matches_method_convention(mesh, mesh2d):
    # review finding (round 4): split must follow the AXIS-based rule of
    # BoltArrayTPU._stat, not shape coincidence — square arrays are the
    # trap
    x = np.random.RandomState(44).randn(8, 8, 4)   # square leading dims
    b = bolt.array(x, mesh)
    assert np.nansum(b, axis=0).split == b.sum(axis=0).split == 0
    assert np.nansum(b, axis=1).split == b.sum(axis=1).split == 1
    assert np.nanmean(b, axis=0, keepdims=True).split == \
        b.mean(axis=0, keepdims=True).split == 1
    assert np.linalg.norm(b, axis=0).split == 0
    assert np.linalg.norm(b, axis=2).split == 1
    assert np.average(b, axis=0).split == 0
    b2 = bolt.array(x, mesh2d, axis=(0, 1))
    assert np.nansum(b2, axis=0).split == 1
    assert np.nansum(b2, axis=(0, 1)).split == 0
    assert np.nanvar(b2, axis=2).split == 2
    # vector-q nanquantile prepends a flat KEY axis, the quantile-method
    # convention
    assert np.nanquantile(b, [0.2, 0.8], axis=1).split == \
        b.quantile([0.2, 0.8], axis=1).split == 2
    # integer data: the promoted-float path computes instead of crashing
    ib = bolt.array(np.arange(24).reshape(4, 6), mesh)
    assert np.allclose(np.asarray(np.nanquantile(ib, 0.3).toarray()),
                       np.nanquantile(np.arange(24).reshape(4, 6), 0.3))
    assert np.allclose(np.asarray(np.nanmedian(ib).toarray()),
                       np.median(np.arange(24).reshape(4, 6)))
    # unsorted bins: numpy's exact rejection, not silent garbage
    with pytest.raises(ValueError, match="monotonically"):
        np.digitize(b, np.array([3.0, 1.0, 2.0]))
    # decreasing bins are legal and numpy-identical
    bins = np.array([2.0, 1.0, -1.0, -2.0])
    assert np.array_equal(np.asarray(np.digitize(b, bins).toarray()),
                          np.digitize(x, bins))


# ----------------------------------------------------------------------
# round 4 batch 3: np.linalg decompositions on device (jnp.linalg in
# one fused program; keys survive as batch dims)
# ----------------------------------------------------------------------

def _spd():
    g = np.random.RandomState(45).randn(16, 5, 5)
    return np.einsum("bij,bkj->bik", g, g) + np.eye(5)


def _tall():
    return np.random.RandomState(46).randn(12, 5)


LINALG_CASES = [
    ("inv", lambda a: np.linalg.inv(a), _spd),
    ("det", lambda a: np.linalg.det(a), _spd),
    ("cholesky", lambda a: np.linalg.cholesky(a), _spd),
    ("cholesky-upper", lambda a: np.linalg.cholesky(a, upper=True), _spd),
    ("eigvalsh", lambda a: np.linalg.eigvalsh(a), _spd),
    ("matrix_power", lambda a: np.linalg.matrix_power(a, 3), _spd),
    ("matrix_power-neg", lambda a: np.linalg.matrix_power(a, -1), _spd),
    ("svd-vals", lambda a: np.linalg.svd(a, compute_uv=False), _tall),
    ("qr-r", lambda a: np.abs(np.linalg.qr(a, mode="r")), _tall),
    ("matrix_rank", lambda a: np.linalg.matrix_rank(a), _tall),
    ("pinv", lambda a: np.linalg.pinv(a), _tall),
    ("norm-nuc", lambda a: np.linalg.norm(a, ord="nuc", axis=(0, 1)),
     _tall),
]


@pytest.mark.parametrize("name,call,make", LINALG_CASES,
                         ids=[c[0] for c in LINALG_CASES])
def test_linalg_parity(mesh, name, call, make):
    x = make()
    b = bolt.array(x, mesh)
    e = call(x)
    g = call(b)
    gv = np.asarray(g.toarray() if hasattr(g, "toarray") else g)
    assert gv.shape == np.shape(e), (name, gv.shape, np.shape(e))
    assert np.allclose(gv, e, rtol=1e-6, atol=1e-8), name


def test_linalg_multi_output_and_batch_split(mesh, mesh2d):
    sq, m = _spd(), _tall()
    b = bolt.array(sq, mesh)
    bm = bolt.array(m, mesh)
    # slogdet / eigh / svd / qr return tuples of device arrays
    sgn, ld = np.linalg.slogdet(b)
    esgn, eld = np.linalg.slogdet(sq)
    assert sgn.mode == ld.mode == "tpu"
    assert np.allclose(sgn.toarray(), esgn)
    assert np.allclose(ld.toarray(), eld)
    w, v = np.linalg.eigh(b)
    assert np.allclose(w.toarray(), np.linalg.eigh(sq)[0])
    recon = np.einsum("bij,bj,bkj->bik", np.asarray(v.toarray()),
                      np.asarray(w.toarray()), np.asarray(v.toarray()))
    assert np.allclose(recon, sq)
    u, s, vh = np.linalg.svd(bm)
    assert np.allclose(s.toarray(), np.linalg.svd(m, compute_uv=False))
    assert np.allclose(
        np.asarray(u.toarray())[:, :5] * np.asarray(s.toarray())
        @ np.asarray(vh.toarray()), m)
    q, r = np.linalg.qr(bm)
    assert np.allclose(np.asarray(q.toarray()) @ np.asarray(r.toarray()),
                       m)
    # batched: the leading key axis survives as a batch dim
    assert np.linalg.inv(b).split == 1
    assert np.linalg.eigh(b)[0].split == 1
    # solve with a host rhs stays on device; lstsq returns numpy's
    # 4-tuple with a plain-int rank
    rhs = np.random.RandomState(47).randn(16, 5, 2)
    assert np.allclose(np.linalg.solve(b, rhs).toarray(),
                       np.linalg.solve(sq, rhs))
    vec = np.random.RandomState(48).randn(12)
    x_, res, rank, sv = np.linalg.lstsq(bm, vec, rcond=None)
    ex, eres, erank, esv = np.linalg.lstsq(m, vec, rcond=None)
    assert np.allclose(x_.toarray(), ex) and rank == erank
    assert np.allclose(res.toarray(), eres)
    assert np.allclose(sv.toarray(), esv)
    # 2-d mesh: batch split caps at the batch rank
    b2 = bolt.array(sq, mesh2d, axis=(0,))
    assert np.linalg.det(b2).split == 1


def test_linalg_rejections_and_uplo(mesh):
    m = _tall()
    bm = bolt.array(m, mesh)
    with pytest.raises(np.linalg.LinAlgError, match="square"):
        np.linalg.inv(bm)
    with pytest.raises(np.linalg.LinAlgError, match="square"):
        np.linalg.det(bm)
    with pytest.raises(np.linalg.LinAlgError, match="two-dimensional"):
        np.linalg.svd(bolt.array(m[:, 0], mesh))
    with pytest.raises(ValueError, match="UPLO"):
        np.linalg.eigh(bolt.array(_spd(), mesh), UPLO="X")
    # UPLO reads ONLY the named triangle of an asymmetric input
    asym = np.random.RandomState(49).randn(5, 5)
    ba = bolt.array(asym, mesh)
    for uplo in ("L", "U"):
        assert np.allclose(
            np.asarray(np.linalg.eigvalsh(ba, UPLO=uplo).toarray()),
            np.linalg.eigvalsh(asym, UPLO=uplo)), uplo
    # vector matrix_rank is a plain scalar like numpy
    assert np.linalg.matrix_rank(bolt.array(np.zeros(5), mesh)) == 0
    assert np.linalg.matrix_rank(bolt.array(np.ones(5), mesh)) == 1


def test_batch23_review_edges(mesh):
    # round-4 review findings on batches 2/3: numpy-exact edges
    x = np.random.RandomState(50).randn(8, 6, 4)
    b = bolt.array(x, mesh)
    # positional ddof for nanvar/nanstd (numpy's 5th positional slot)
    assert np.allclose(np.asarray(np.nanvar(b, 0, None, None, 1).toarray()),
                       np.nanvar(x, 0, None, None, 1))
    assert np.allclose(np.asarray(np.nanstd(b, 1, None, None, 1).toarray()),
                       np.nanstd(x, 1, None, None, 1))
    # duplicate consecutive bin edges are legal, like numpy
    bins = np.array([1.0, 1.0, 2.0])
    assert np.array_equal(np.asarray(np.digitize(b, bins).toarray()),
                          np.digitize(x, bins))
    # interp period=0: numpy's exact rejection, not silent NaNs
    with pytest.raises(ValueError, match="non-zero"):
        np.interp(b, np.arange(4.0), np.arange(4.0), period=0)
    # q is a traced operand: sweeping quantiles reuses ONE executable
    # (fresh shape so no earlier test could have seeded the cache entry)
    from bolt_tpu.tpu import array as array_mod
    bq = bolt.array(np.random.RandomState(54).randn(8, 5, 3), mesh)
    n0 = sum(1 for k in array_mod._JIT_CACHE if k[0] == "nanquantile")
    for qv in (0.1, 0.4, 0.9):
        np.nanquantile(bq, qv)
    assert sum(1 for k in array_mod._JIT_CACHE
               if k[0] == "nanquantile") == n0 + 1
    # matrix_rank: rtol is RELATIVE, tol ABSOLUTE, hermitian honoured
    d = np.diag([10.0, 1.0, 0.1])
    bd = bolt.array(d, mesh)
    assert int(np.asarray(np.linalg.matrix_rank(bd, rtol=0.05).toarray())) \
        == np.linalg.matrix_rank(d, rtol=0.05) == 2
    assert int(np.asarray(np.linalg.matrix_rank(bd, tol=0.05).toarray())) \
        == np.linalg.matrix_rank(d, tol=0.05) == 3
    h = np.diag([2.0, -1.0, 1e-12])
    bh = bolt.array(h, mesh)
    assert int(np.asarray(
        np.linalg.matrix_rank(bh, hermitian=True).toarray())) \
        == np.linalg.matrix_rank(h, hermitian=True)
    # lstsq residuals follow numpy's conventions (empty for
    # underdetermined systems)
    u = np.random.RandomState(51).randn(3, 5)
    bu = bolt.array(u, mesh)
    rhs = np.random.RandomState(52).randn(3)
    _, res_g, _, _ = np.linalg.lstsq(bu, rhs, rcond=None)
    _, res_e, _, _ = np.linalg.lstsq(u, rhs, rcond=None)
    assert np.shape(np.asarray(res_g.toarray())) == np.shape(res_e) == (0,)
    # broadcast rhs with extra leading dims: solve re-keys to 0
    sq = _spd()
    bs = bolt.array(sq, mesh)
    rhs2 = np.random.RandomState(53).randn(2, 16, 5, 5)
    out = np.linalg.solve(bs, rhs2)
    assert out.split == 0
    assert np.allclose(out.toarray(), np.linalg.solve(sq, rhs2))
    # eigvalsh is its own single-output program, not eigh-minus-vectors
    from bolt_tpu.tpu import array as am
    np.linalg.eigvalsh(bs)
    assert any(k[0] == "linalg_eigvalsh" for k in am._JIT_CACHE)


# ----------------------------------------------------------------------
# round 4 batch 4: triangles, diagonals, products, selection
# ----------------------------------------------------------------------

TAIL4_CASES = [
    ("tril", lambda a: np.tril(a[:, :, 0])),
    ("tril-k", lambda a: np.tril(a[:, :, 0], -1)),
    ("triu-k", lambda a: np.triu(a[:, :, 0], 2)),
    ("diag-2d", lambda a: np.diag(a[:, :, 0], 1)),
    ("diag-1d", lambda a: np.diag(a[:, 0, 0])),
    ("diagflat", lambda a: np.diagflat(a[:, :2, 0])),
    ("vander", lambda a: np.vander(a[:, 0, 0], 4)),
    ("kron", lambda a: np.kron(a, np.ones((1, 2, 2)))),
    ("select", lambda a: np.select([a > 0.5, a < -0.5], [a, -a],
                                   default=7.0)),
    ("compress", lambda a: np.compress(
        np.array([True, False] * 4), a, axis=0)),
    ("extract", lambda a: np.extract(np.asarray(a) > 0, a)),
    ("convolve", lambda a: np.convolve(a[:, 0, 0],
                                       np.array([0.5, 1.0, 0.5]))),
    ("correlate-full", lambda a: np.correlate(
        a[:, 0, 0], np.array([0.5, 1.0, 0.5]), "full")),
]


@pytest.mark.parametrize("layout", ["keys1d", "keys2d"])
@pytest.mark.parametrize("name,call", TAIL4_CASES,
                         ids=[c[0] for c in TAIL4_CASES])
def test_dispatch_tail4_parity(request, layout, name, call):
    if layout == "keys1d":
        m, axis = request.getfixturevalue("mesh"), (0,)
    else:
        m, axis = request.getfixturevalue("mesh2d"), (0, 1)
    x = _x2()[:8]
    b = bolt.array(x, m, axis=axis)
    if layout == "keys2d" and name in ("diag-1d", "vander", "convolve",
                                       "correlate-full"):
        pytest.skip("1-d slice of a 2-d-keys array has a single key "
                    "axis")
    expect = call(x)
    got = call(b)
    g = np.asarray(got.toarray() if hasattr(got, "toarray") else got)
    e = np.asarray(expect)
    assert g.shape == e.shape, (name, g.shape, e.shape)
    assert np.allclose(g, e, equal_nan=True), name


def test_dispatch_tail4_details(mesh):
    x = _x2()[:8]
    b = bolt.array(x, mesh)
    # compress/extract are static host-condition paths; a device
    # condition (dynamic shape) falls back but stays correct
    cond = np.asarray(x[:, 0, 0]) > 0
    out = np.compress(cond, b, axis=0)
    assert out.mode == "tpu"
    assert np.allclose(out.toarray(), np.compress(cond, x, axis=0))
    dev_cond = (b[:, 0, 0] > 0)
    out2 = np.extract(dev_cond, b)
    assert np.allclose(np.asarray(out2), np.extract(cond, x))
    # numpy-exact rejections
    with pytest.raises(ValueError, match="same length"):
        np.select([b > 0], [b, b])
    with pytest.raises(ValueError, match="one-dimensional"):
        np.vander(b)
    with pytest.raises(ValueError, match="1- or 2-d"):
        np.diag(b)
    with pytest.raises(ValueError, match="mode"):
        np.convolve(b[:, 0, 0], np.ones(3), mode="bogus")
    # split bookkeeping: triangles/diag keep keys, 2-d diag reduces
    assert np.tril(b[:, :, 0]).split == 1
    assert np.diag(b[:, 0, 0]).split == 1
    assert np.diag(b[:, :, 0]).split == 0   # diagonal of keys x values


def test_batch4_review_edges(mesh):
    x = _x2()[:8]
    b = bolt.array(x, mesh)
    # over-long compress condition with trailing False entries is legal
    cond = np.array([True, False] * 4 + [False, False])
    assert np.allclose(np.compress(cond, b, axis=0).toarray(),
                       np.compress(cond, x, axis=0))
    with pytest.raises(IndexError, match="out of bounds"):
        np.compress(np.array([False] * 9 + [True]), b, axis=0)
    # select's default dtype participates in promotion; 0 vs 0.0 must
    # not collide in the executable cache
    iv = bolt.array(np.arange(8), mesh)
    o_int = np.select([iv > 3], [iv], default=0)
    o_flt = np.select([iv > 3], [iv], default=0.0)
    assert np.asarray(o_int.toarray()).dtype.kind == "i"
    assert np.asarray(o_flt.toarray()).dtype.kind == "f"
    # scalar convolve operands promote like numpy
    v = bolt.array(np.arange(6.0), mesh)
    assert np.allclose(np.asarray(np.convolve(v, 2.0).toarray()),
                       np.convolve(np.arange(6.0), 2.0))
    # multi-output linalg results carry numpy's attribute API
    sq = _spd()
    bs = bolt.array(sq, mesh)
    r = np.linalg.slogdet(bs)
    assert np.allclose(np.asarray(r.sign.toarray()),
                       np.linalg.slogdet(sq).sign)
    e = np.linalg.eigh(bs)
    assert np.allclose(np.asarray(e.eigenvalues.toarray()),
                       np.linalg.eigh(sq).eigenvalues)
    s = np.linalg.svd(bolt.array(_tall(), mesh))
    assert hasattr(s, "S") and hasattr(s, "Vh")
    q = np.linalg.qr(bolt.array(_tall(), mesh))
    assert hasattr(q, "Q") and hasattr(q, "R")
    # 1-d inputs get numpy's at-least-two-dimensional message
    with pytest.raises(np.linalg.LinAlgError, match="two-dimensional"):
        np.linalg.inv(bolt.array(np.arange(4.0), mesh))


# ----------------------------------------------------------------------
# round 4 batch 5: np.fft, apply_along_axis, einsum ellipsis
# ----------------------------------------------------------------------

FFT_CASES = [
    ("fft", lambda a: np.fft.fft(a)),
    ("fft-n-axis", lambda a: np.fft.fft(a, n=10, axis=1)),
    ("ifft", lambda a: np.fft.ifft(a, axis=0)),
    ("rfft", lambda a: np.fft.rfft(a)),
    ("irfft-roundtrip", lambda a: np.fft.irfft(np.fft.rfft(a), n=4)),
    ("hfft", lambda a: np.fft.hfft(a)),
    ("ihfft", lambda a: np.fft.ihfft(a)),
    ("fft2", lambda a: np.fft.fft2(a)),
    ("ifft2", lambda a: np.fft.ifft2(a)),
    ("rfft2", lambda a: np.fft.rfft2(a)),
    ("fftn-axes", lambda a: np.fft.fftn(a, axes=(0, 2))),
    ("fftn-s", lambda a: np.fft.fftn(a, s=(6, 3), axes=(1, 2))),
    ("rfftn", lambda a: np.fft.rfftn(a)),
    ("irfftn-roundtrip", lambda a: np.fft.irfftn(np.fft.rfftn(a),
                                                 s=np.shape(a))),
    ("fft-ortho", lambda a: np.fft.fft(a, norm="ortho")),
    ("fft-forward", lambda a: np.fft.fft(a, norm="forward")),
    ("fftshift", lambda a: np.fft.fftshift(a)),
    ("fftshift-axis", lambda a: np.fft.fftshift(a, axes=1)),
    ("ifftshift", lambda a: np.fft.ifftshift(a, axes=(0, 2))),
    ("apply-scalar", lambda a: np.apply_along_axis(
        lambda v: v.sum(), 1, a)),
    ("apply-vector", lambda a: np.apply_along_axis(
        lambda v: v[:2] * 2.0, 2, a)),
    ("apply-matrix", lambda a: np.apply_along_axis(
        lambda v: np.outer(v[:2], v[:2]), 0, a)),
    ("einsum-ellipsis", lambda a: np.einsum("i...,i...->...", a, a)),
    ("einsum-ellipsis-keep", lambda a: np.einsum("...j->...", a)),
    ("einsum-ellipsis-implicit", lambda a: np.einsum("...ij", a)),
    ("einsum-ellipsis-mixed", lambda a: np.einsum(
        "...i,ij->...j", a, np.ones((4, 2)))),
]


@pytest.mark.parametrize("layout", ["keys1d", "keys2d"])
@pytest.mark.parametrize("name,call", FFT_CASES,
                         ids=[c[0] for c in FFT_CASES])
def test_dispatch_tail5_parity(request, layout, name, call):
    if layout == "keys1d":
        m, axis = request.getfixturevalue("mesh"), (0,)
    else:
        m, axis = request.getfixturevalue("mesh2d"), (0, 1)
    x = _x2()[:8]
    b = bolt.array(x, m, axis=axis)
    expect = call(x)
    got = call(b)
    g = np.asarray(got.toarray() if hasattr(got, "toarray") else got)
    e = np.asarray(expect)
    assert g.shape == e.shape, (name, g.shape, e.shape)
    assert np.allclose(g, e, equal_nan=True), name


def test_dispatch_tail5_details(mesh):
    x = _x2()[:8]
    b = bolt.array(x, mesh)
    # fft along a value axis keeps the keys; apply_along_axis keeps the
    # keys ahead of the applied axis
    assert np.fft.fft(b, axis=2).split == 1
    assert np.apply_along_axis(lambda v: v.sum(), 2, b).split == 1
    assert np.apply_along_axis(lambda v: v.sum(), 0, b).split == 0
    # device results really are device-resident
    assert np.fft.fft(b).mode == "tpu"
    # non-traceable func1d takes the warned host fallback, same answer
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        out = np.apply_along_axis(
            lambda v: float(np.asarray(v).sum()), 1, b)
    assert np.allclose(out, np.apply_along_axis(lambda v: v.sum(), 1, x))
    # numpy's explicit-output-needs-ellipsis rule holds (host raises)
    with pytest.raises(ValueError, match="ellipsis"):
        np.einsum("i...,...->i", b, bolt.array(x[0], mesh))
    # einsum ellipsis key survival: broadcast dims lead the output, so
    # keys survive only when the anchor's keys are the leading
    # broadcast/batch labels
    assert np.einsum("i...,i...->...", b, b).split == 0
    assert np.einsum("...k,kj->...j", b, np.ones((4, 3))).split == 1


def test_batch5_review_edges(mesh):
    x = _x2()[:8]
    b = bolt.array(x, mesh)
    # unhashable kwargs VALUES fall back instead of crashing the cache
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        out = np.apply_along_axis(
            lambda v, w=None: v * w[0], 1, b, w=[2.0, 3.0])
    assert np.allclose(np.asarray(out),
                       np.apply_along_axis(
                           lambda v, w=None: v * w[0], 1, x,
                           w=[2.0, 3.0]))
    # explicit EMPTY einsum output still requires '...' when broadcast
    # dims exist — numpy's exact error, not a wrong-shaped result
    b2 = bolt.array(x[:, :, 0], mesh)
    with pytest.raises(ValueError, match="ellipsis"):
        np.einsum("i...->", b2)


# ----------------------------------------------------------------------
# round 4 batch 6: set operations, complex views, cleanup helpers
# ----------------------------------------------------------------------

def test_set_operations_parity(mesh):
    rs = np.random.RandomState(55)
    a = rs.randint(0, 20, 64).astype(float)
    c = rs.randint(10, 30, 48).astype(float)
    ba, bc = bolt.array(a, mesh), bolt.array(c, mesh)
    assert np.array_equal(np.intersect1d(ba, bc), np.intersect1d(a, c))
    assert np.array_equal(np.intersect1d(ba, c), np.intersect1d(a, c))
    assert np.array_equal(np.union1d(ba, bc), np.union1d(a, c))
    assert np.array_equal(np.setdiff1d(ba, bc), np.setdiff1d(a, c))
    assert np.array_equal(np.setxor1d(ba, bc), np.setxor1d(a, c))
    # return_indices: warned host fallback, numpy-exact triple
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        r = np.intersect1d(ba, bc, return_indices=True)
    e = np.intersect1d(a, c, return_indices=True)
    assert all(np.array_equal(np.asarray(i), j) for i, j in zip(r, e))


def test_complex_and_cleanup_parity(mesh, mesh2d):
    x = _x2()[:8]
    for m, axis in ((mesh, (0,)), (mesh2d, (0, 1))):
        b = bolt.array(x, m, axis=axis)
        assert np.allclose(np.asarray(np.sinc(b).toarray()), np.sinc(x))
        assert np.allclose(np.asarray(np.i0(b).toarray()), np.i0(x))
        p = np.cumsum(np.abs(x), axis=2)
        bp = bolt.array(p, m, axis=axis)
        assert np.allclose(np.asarray(np.unwrap(bp).toarray()),
                           np.unwrap(p))
        assert np.allclose(
            np.asarray(np.unwrap(bp, period=3.0, axis=1).toarray()),
            np.unwrap(p, period=3.0, axis=1))
        y = x.copy()
        y[0, 0, 0], y[1, 1, 1], y[2, 2, 2] = np.nan, np.inf, -np.inf
        by = bolt.array(y, m, axis=axis)
        assert np.allclose(np.asarray(np.nan_to_num(by).toarray()),
                           np.nan_to_num(y))
        assert np.allclose(
            np.asarray(np.nan_to_num(by, nan=-1, posinf=9).toarray()),
            np.nan_to_num(y, nan=-1, posinf=9))
        assert np.array_equal(np.asarray(np.isposinf(by).toarray()),
                              np.isposinf(y))
        assert np.array_equal(np.asarray(np.isneginf(by).toarray()),
                              np.isneginf(y))
        z = x[:, :, 0] + 1j * x[:, :, 1]
        bz = bolt.array(z, m, axis=axis)
        assert np.allclose(np.asarray(np.angle(bz).toarray()),
                           np.angle(z))
        assert np.allclose(np.asarray(np.angle(bz, deg=True).toarray()),
                           np.angle(z, deg=True))
        assert np.angle(bz).split == b.split


def test_histogram2d_dd_parity(mesh):
    rs = np.random.RandomState(56)
    x, y = rs.randn(512), rs.randn(512)
    bx, by = bolt.array(x, mesh), bolt.array(y, mesh)
    h, ex, ey = np.histogram2d(bx, by, bins=8)
    hn, exn, eyn = np.histogram2d(x, y, bins=8)
    assert np.allclose(h, hn) and h.dtype == hn.dtype
    assert np.allclose(ex, exn) and np.allclose(ey, eyn)
    h2 = np.histogram2d(bx, by, bins=[4, 6],
                        range=[[-2, 2], [-3, 3]], density=True)[0]
    h2n = np.histogram2d(x, y, bins=[4, 6],
                         range=[[-2, 2], [-3, 3]], density=True)[0]
    assert np.allclose(h2, h2n) and h2.dtype == h2n.dtype
    # per-dimension None range entries: numpy-legal, host fallback
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        hnr = np.histogram2d(bx, by, bins=6, range=[[0, 1], None])[0]
    hnrn = np.histogram2d(x, y, bins=6, range=[[0, 1], None])[0]
    assert np.allclose(hnr, hnrn)
    s = rs.randn(256, 3)
    bs = bolt.array(s, mesh)
    hd, edges = np.histogramdd(bs, bins=4)
    hdn, edgesn = np.histogramdd(s, bins=4)
    assert np.allclose(hd, hdn) and hd.dtype == hdn.dtype
    assert all(np.allclose(a, b_) for a, b_ in zip(edges, edgesn))
    hd2 = np.histogramdd(bs, bins=(3, 4, 5), density=True)[0]
    hd2n = np.histogramdd(s, bins=(3, 4, 5), density=True)[0]
    assert np.allclose(hd2, hd2n)
    # array bin edges: warned host fallback, numpy-exact
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        hfb = np.histogram2d(bx, by, bins=[np.linspace(-2, 2, 5),
                                           np.linspace(-2, 2, 4)])[0]
    hfbn = np.histogram2d(x, y, bins=[np.linspace(-2, 2, 5),
                                      np.linspace(-2, 2, 4)])[0]
    assert np.allclose(hfb, hfbn)


# ----------------------------------------------------------------------
# round 4 batch 8: flips, integration, nan-aware cumulatives/arg stats
# ----------------------------------------------------------------------

TAIL8_CASES = [
    ("flipud", lambda a: np.flipud(a)),
    ("fliplr", lambda a: np.fliplr(a)),
    ("trapezoid", lambda a: np.trapezoid(a)),
    ("trapezoid-dx-axis", lambda a: np.trapezoid(a, dx=0.5, axis=1)),
    ("trapezoid-x", lambda a: np.trapezoid(a, np.linspace(0, 1, 4),
                                           axis=2)),
    ("ediff1d", lambda a: np.ediff1d(a)),
    ("ediff1d-ends", lambda a: np.ediff1d(a, to_end=[9.0],
                                          to_begin=[-1.0, -2.0])),
    ("nancumsum-flat", lambda a: np.nancumsum(a)),
    ("nancumsum-axis", lambda a: np.nancumsum(a, axis=1)),
    ("nancumprod-axis", lambda a: np.nancumprod(a, axis=2)),
    ("nanargmax-flat", lambda a: np.nanargmax(a)),
    ("nanargmax-axis", lambda a: np.nanargmax(a, axis=1)),
    ("nanargmin-axis", lambda a: np.nanargmin(a, axis=0)),
    ("fix", lambda a: np.fix(a * 3)),
]


@pytest.mark.parametrize("name,call", TAIL8_CASES,
                         ids=[c[0] for c in TAIL8_CASES])
def test_dispatch_tail8_parity(mesh, name, call):
    x = _xnan() if "nan" in name else _x2()[:8]
    if name in ("ediff1d", "ediff1d-ends"):
        x = x[:, 0, 0].copy()
    b = bolt.array(x, mesh)
    expect = call(x)
    got = call(b)
    g = np.asarray(got.toarray() if hasattr(got, "toarray") else got)
    e = np.asarray(expect)
    assert g.shape == e.shape, (name, g.shape, e.shape)
    assert np.allclose(g, e, equal_nan=True), name


def test_cross_parity(mesh):
    v3 = np.random.RandomState(57).randn(16, 3)
    b3 = bolt.array(v3, mesh)
    w = np.array([1.0, 0.5, 0.25])
    assert np.allclose(np.asarray(np.cross(b3, w).toarray()),
                       np.cross(v3, w))
    assert np.cross(b3, w).split == 1
    other = np.random.RandomState(58).randn(16, 3)
    assert np.allclose(np.asarray(np.cross(b3, other).toarray()),
                       np.cross(v3, other))
    # 2-vector cross products (scalar result per pair)
    v2 = v3[:, :2]
    b2 = bolt.array(v2, mesh)
    assert np.allclose(np.asarray(np.cross(b2, v2[::-1]).toarray()),
                       np.cross(v2, v2[::-1]))


def test_tail8_split_bookkeeping(mesh):
    x = _xnan()
    b = bolt.array(x, mesh)
    assert np.nancumsum(b, axis=2).split == 1
    assert np.nancumsum(b).split == 1            # flat key convention
    assert np.nanargmax(b, axis=1).split == 1
    assert np.nanargmax(b, axis=0).split == 0
    assert np.trapezoid(b, axis=2).split == 1
    assert np.flipud(b).split == 1


def test_batch8_review_edges(mesh):
    v3 = np.random.RandomState(59).randn(16, 3)
    b3 = bolt.array(v3, mesh)
    # non-default cross axes fall back, numpy-correct
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        out = np.cross(b3, v3[::-1], axisc=0)
    assert np.allclose(out, np.cross(v3, v3[::-1], axisc=0))
    # mixed 2x3 vectors: numpy's deprecated-but-working path
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        mixed = np.cross(bolt.array(v3[:, :2], mesh), np.ones(3))
        expect = np.cross(v3[:, :2], np.ones(3))
    assert np.allclose(np.asarray(mixed), expect)


# ----------------------------------------------------------------------
# round-5 dispatch tail (VERDICT r4 missing-4): take_along_axis,
# lexsort, meshgrid/block/broadcast_arrays, insert/delete/resize, the
# last np.linalg utilities, fft frequency grids, nonsymmetric-eig
# policy — device-served with numpy semantics, both mesh layouts
# ----------------------------------------------------------------------

TAIL9_CASES = [
    ("take_along_axis", lambda a: np.take_along_axis(
        a, np.argsort(np.asarray(a), axis=2), axis=2)),
    ("take_along_axis-key", lambda a: np.take_along_axis(
        a, np.zeros((1, 6, 4), dtype=int), axis=0)),
    ("take_along_axis-neg", lambda a: np.take_along_axis(
        a, np.full((8, 6, 1), -1), axis=2)),
    ("take_along_axis-flat", lambda a: np.take_along_axis(
        a, np.array([0, 17, 5]), axis=None)),
    ("lexsort-seq", lambda a: np.lexsort(
        (np.round(a[:, 0, 0]), np.round(a[:, 1, 0])))),
    ("meshgrid-ij", lambda a: np.meshgrid(
        a[:, 0, 0], np.arange(3.0), indexing="ij")[0]),
    ("meshgrid-xy", lambda a: np.meshgrid(
        a[:, 0, 0], np.arange(3.0), indexing="xy")[1]),
    ("block-flat", lambda a: np.block([a[:, 0, 0], a[:, 1, 1]])),
    ("block-2d", lambda a: np.block(
        [[a[:, :, 0], a[:, :, 1]], [a[:, :, 2], a[:, :, 3]]])),
    ("broadcast_arrays", lambda a: np.broadcast_arrays(
        a, np.ones((1, 6, 1)))[1]),
    ("broadcast_arrays-self", lambda a: np.broadcast_arrays(
        a, np.ones(4))[0]),
    ("insert-int", lambda a: np.insert(a, 2, 5.0, axis=1)),
    ("insert-flat", lambda a: np.insert(a, 3, [1.0, 2.0])),
    ("insert-arr", lambda a: np.insert(a, [1, 3], 0.0, axis=2)),
    ("delete-int", lambda a: np.delete(a, 2, axis=1)),
    ("delete-neg", lambda a: np.delete(a, -1, axis=0)),
    ("delete-slice", lambda a: np.delete(a, slice(1, 4), axis=1)),
    ("delete-arr", lambda a: np.delete(a, [0, 2], axis=2)),
    ("delete-flat", lambda a: np.delete(a, [0, 5, 7])),
    ("resize-up", lambda a: np.resize(a, (10, 6, 4))),
    ("resize-reshape", lambda a: np.resize(a, (4, 12, 4))),
    ("resize-flat", lambda a: np.resize(a, 100)),
    ("linalg-cond", lambda a: np.linalg.cond(
        a[:4, :4, 0] + 3 * np.eye(4))),
    ("linalg-cond-1", lambda a: np.linalg.cond(
        a[:4, :4, 0] + 3 * np.eye(4), p=1)),
    ("linalg-multi_dot", lambda a: np.linalg.multi_dot(
        [a[:, :, 0], np.ones((6, 5)), np.linspace(0, 1, 5)])),
]


@pytest.mark.parametrize("layout", ["keys1d", "keys2d"])
@pytest.mark.parametrize("name,call", TAIL9_CASES,
                         ids=[c[0] for c in TAIL9_CASES])
def test_dispatch_tail9_parity(request, layout, name, call):
    if layout == "keys1d":
        m, axis = request.getfixturevalue("mesh"), (0,)
    else:
        m, axis = request.getfixturevalue("mesh2d"), (0, 1)
    x = _x2()
    b = bolt.array(x, m, axis=axis)
    expect = call(x)
    got = call(b)

    def norm(v):
        return np.asarray(v.toarray() if hasattr(v, "toarray") else v)

    g, e = norm(got), norm(expect)
    assert g.shape == e.shape, (name, g.shape, e.shape)
    assert np.allclose(g, e, equal_nan=True), name


def test_tail9_partition_invariants(mesh):
    """partition's within-partition order is unspecified, so parity is
    the INVARIANT (kth element in sorted place, partitions as sets),
    not array equality."""
    x = _x2()
    b = bolt.array(x, mesh)
    for kth in (0, 3, -1):
        got = np.asarray(np.partition(b, kth, axis=2).toarray())
        k = kth + 4 if kth < 0 else kth
        srt = np.sort(x, axis=2)
        assert np.allclose(got[..., k], srt[..., k])
        assert np.allclose(np.sort(got, axis=2), srt)
        assert (got[..., :k] <= got[..., k:k + 1]).all()
        assert (got[..., k + 1:] >= got[..., k:k + 1]).all()
    # flat + key-axis forms
    gf = np.asarray(np.partition(b, 10, axis=None).toarray())
    assert np.allclose(np.sort(gf), np.sort(x, axis=None))
    assert (gf[:10] <= gf[10]).all()
    g0 = np.asarray(np.partition(b, 2, axis=0).toarray())
    assert np.allclose(g0[2], np.sort(x, axis=0)[2])
    # argpartition: indices select the same invariant values
    ai = np.asarray(np.argpartition(b, 3, axis=2).toarray())
    vals = np.take_along_axis(x, ai, axis=2)
    assert np.allclose(vals[..., 3], np.sort(x, axis=2)[..., 3])
    # kth validation matches numpy on both backends
    lo = bolt.array(x)
    for t in (lo, b):
        with pytest.raises(ValueError, match="out of bounds"):
            np.partition(t, 99, axis=2)


def test_tail9_linalg_details(mesh):
    rs = np.random.RandomState(47)
    A = rs.randn(6, 4, 6, 4) + 5 * np.eye(24).reshape(6, 4, 6, 4)
    bA = bolt.array(A, mesh, axis=(0,))
    got = np.linalg.tensorinv(bA, ind=2)
    assert np.allclose(np.asarray(got.toarray()),
                       np.linalg.tensorinv(A, ind=2), atol=1e-8)
    bvec = rs.randn(6, 4)
    gs = np.linalg.tensorsolve(bA, bolt.array(bvec, mesh))
    assert np.allclose(np.asarray(gs.toarray()),
                       np.linalg.tensorsolve(A, bvec), atol=1e-8)
    with pytest.raises(ValueError, match="Invalid ind"):
        np.linalg.tensorinv(bA, ind=0)
    # nonsymmetric eig: explicit documented policy, not a silent gather
    sq = bolt.array(rs.randn(4, 4), mesh)
    with pytest.raises(NotImplementedError, match="nonsymmetric"):
        np.linalg.eig(sq)
    with pytest.raises(NotImplementedError, match="nonsymmetric"):
        np.linalg.eigvals(sq)
    with pytest.raises(np.linalg.LinAlgError):
        np.linalg.cond(bolt.array(rs.randn(5), mesh))


def test_tail9_fftfreq(mesh):
    # a 0-d device scalar arises from a full reduction
    d = bolt.array(np.full(4, 0.25), mesh).mean()
    assert d.ndim == 0
    got = np.fft.fftfreq(8, d)
    assert np.allclose(np.asarray(got.toarray()), np.fft.fftfreq(8, 0.25))
    got = np.fft.rfftfreq(9, d)
    assert np.allclose(np.asarray(got.toarray()), np.fft.rfftfreq(9, 0.25))


def test_tail9_put_along_axis_policy(mesh):
    b = bolt.array(_x2(), mesh)
    # the host fallback would mutate a discarded copy — loud reject
    with pytest.raises(TypeError, match="immutable"):
        np.put_along_axis(b, np.zeros((8, 6, 1), dtype=int), 0.0, axis=2)
    # numpy target + device indices still works through the host path
    host = _x2()
    idx = bolt.array(np.zeros((8, 6, 1)).astype(int), mesh)
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("ignore")
        np.put_along_axis(host, idx, 7.0, axis=2)
    assert (host[:, :, 0] == 7.0).all()


def test_tail9_validation_parity(mesh):
    x = _x2()
    lo, tp = bolt.array(x), bolt.array(x, mesh)
    for b in (lo, tp):
        with pytest.raises(ValueError):
            np.take_along_axis(b, np.zeros((8, 6), dtype=int), axis=2)
        with pytest.raises(IndexError):
            np.take_along_axis(b, np.full((8, 6, 1), 9), axis=2)
        with pytest.raises(IndexError):
            np.delete(b, 99, axis=0)
        with pytest.raises(IndexError):
            np.insert(b, 99, 0.0, axis=0)
        with pytest.raises(IndexError):
            np.insert(b, [99], 0.0, axis=0)   # array selector too
        with pytest.raises(ValueError):
            np.meshgrid(b[:, 0, 0], np.arange(3.0), indexing="bogus")
    # lexsort ties: stable on both backends
    k1 = np.array([3, 1, 3, 1, 2, 2, 0, 0], dtype=float)
    k2 = np.array([1, 1, 0, 0, 1, 1, 0, 0], dtype=float)
    got = np.lexsort((bolt.array(k1, mesh), bolt.array(k2, mesh)))
    assert np.array_equal(np.asarray(got.toarray()), np.lexsort((k1, k2)))
    # single 2-d key array: rows are the key sequence, last row primary
    karr = np.stack([k1, k2])
    g2 = np.lexsort(bolt.array(karr, mesh))
    assert np.array_equal(np.asarray(g2.toarray()), np.lexsort(karr))


def test_tail9_split_bookkeeping(mesh):
    x = _x2()
    b = bolt.array(x, mesh)
    assert np.take_along_axis(
        b, np.argsort(np.asarray(x), axis=2), axis=2).split == 1
    assert np.partition(b, 2, axis=2).split == 1
    assert np.delete(b, 1, axis=1).split == 1
    assert np.insert(b, 1, 0.0, axis=1).split == 1
    assert np.resize(b, (10, 6, 4)).split == 1
    assert np.linalg.multi_dot([b[:, :, 0], np.ones((6, 2))]).split == 1
    # a 1-d first operand is contracted away: no fabricated key axis
    assert np.linalg.multi_dot(
        [b[:, 0, 0], np.ones((8, 6)), np.ones((6, 2))]).split == 0
    outs = np.broadcast_arrays(b, np.ones(4))
    assert isinstance(outs, tuple) and outs[0].split == 1
    grids = np.meshgrid(b[:, 0, 0], np.arange(3.0))
    assert isinstance(grids, list)


def test_advice_r4_edges(mesh):
    """ADVICE r4 fixes: histogram2d validation + edge dtypes, hstack's
    first-array axis rule."""
    rs = np.random.RandomState(61)
    b16 = bolt.array(rs.randn(16), mesh)
    b8 = bolt.array(rs.randn(8), mesh)
    # mismatched lengths: numpy's eager ValueError, not a trace error
    with pytest.raises(ValueError, match="same length"):
        np.histogram2d(b16, b8)
    # >1-d samples are not silently flattened — numpy rejects them, and
    # the host fallback surfaces its exact error on both backends
    x2 = rs.randn(4, 4)
    with pytest.raises(ValueError):
        np.histogram2d(x2, x2)
    with pytest.raises(ValueError):
        np.histogram2d(bolt.array(x2, mesh), bolt.array(x2, mesh))
    # edges come back float64 even under x64-off production numerics
    h, ex, ey = np.histogram2d(b16, bolt.array(rs.randn(16), mesh))
    assert ex.dtype == np.float64 and ey.dtype == np.float64
    hd, edges = np.histogramdd(bolt.array(rs.randn(16, 3), mesh))
    assert all(e.dtype == np.float64 for e in edges)
    # hstack with a 1-d first operand and 2-d second: numpy's error
    # (decided from the FIRST array alone) on both backends
    for first, second in ((b16, rs.randn(2, 2)),):
        with pytest.raises(ValueError):
            np.hstack([first, second])
        with pytest.raises(ValueError):
            np.hstack([np.asarray(first), second])


def test_every_table_entry_documented():
    """Every ``_TABLE`` entry must appear by name in docs/API.md's
    inventory (VERDICT r4 hygiene: headline claims regenerate from
    artifacts — the doc list cannot silently lag the dispatch table)."""
    import os
    api_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "API.md")
    with open(api_path) as f:
        api = f.read()
    missing = sorted({f.__name__ for f in npdispatch._TABLE
                      if f.__name__ not in api})
    assert not missing, "npdispatch._TABLE entries undocumented in " \
        "docs/API.md: %s" % missing
