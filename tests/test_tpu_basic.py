"""TPU-backend basics: dtype, astype, cache, repr, ufunc-via-map parity
(reference area: ``test/test_spark_basic.py``, SURVEY §4)."""

import numpy as np

import bolt_tpu as bolt
from bolt_tpu.utils import allclose


def _x():
    rs = np.random.RandomState(2)
    return rs.randn(8, 4, 5)


def test_dtype_preserved(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    assert b.dtype == np.float64
    assert b.toarray().dtype == np.float64
    b32 = bolt.array(x.astype(np.float32), mesh)
    assert b32.dtype == np.float32


def test_astype(mesh):
    x = np.arange(16.0).reshape(8, 2)
    b = bolt.array(x, mesh)
    c = b.astype(np.int32)
    assert c.dtype == np.int32
    assert allclose(c.toarray(), x.astype(np.int32))


def test_ufunc_via_map(mesh):
    x = np.abs(_x()) + 0.5
    b = bolt.array(x, mesh)
    for f in (np.sqrt, np.log, np.exp, np.sin):
        assert allclose(b.map(f).toarray(), f(x))


def test_cache_unpersist_repartition(mesh):
    b = bolt.ones((8, 3), mesh)
    assert b.cache() is b
    assert b.unpersist() is b
    assert b.repartition(4) is b


def test_first(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    assert allclose(b.first(), x[0])
    b2 = bolt.array(x, mesh, axis=(0, 1))
    assert allclose(b2.first(), x[0, 0])


def test_repr(mesh):
    b = bolt.ones((8, 3), mesh)
    r = repr(b)
    assert "tpu" in r and "split: 1" in r and "shape" in r


def test_size_ndim(mesh):
    b = bolt.ones((8, 3, 2), mesh)
    assert b.size == 48
    assert b.ndim == 3
    assert np.asarray(b).shape == (8, 3, 2)
