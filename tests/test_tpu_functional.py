"""TPU-backend map/filter/reduce, including non-aligned axes that force an
``_align`` swap (reference area: ``test/test_spark_functional.py``,
SURVEY §4)."""

from operator import add

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.utils import allclose

from tests.generic import filter_suite, map_suite, reduce_suite


def _x():
    rs = np.random.RandomState(3)
    return rs.randn(8, 4, 5)


def test_map(mesh):
    map_suite(_x(), bolt.array(_x(), mesh))


def test_filter(mesh):
    filter_suite(_x(), bolt.array(_x(), mesh))


def test_reduce(mesh):
    reduce_suite(_x(), bolt.array(_x(), mesh))


def test_map_nonaligned_axis(mesh):
    x = _x()
    b = bolt.array(x, mesh)  # keys = (0,)
    # mapping over axis 1 forces an implicit swap (reference _align)
    out = b.map(lambda v: v.sum(), axis=(1,))
    assert out.split == 1
    expected = np.asarray([x[:, i, :].sum() for i in range(x.shape[1])])
    assert allclose(out.toarray(), expected)


def test_map_value_axis_pair(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b.map(lambda v: v * 2, axis=(0, 2))
    # result keys = (axis0, axis2) leading
    expected = np.transpose(x, (0, 2, 1)) * 2
    assert allclose(out.toarray(), expected)


def test_map_value_shape_check(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b.map(lambda v: v.sum(axis=0), value_shape=(5,))
    assert allclose(out.toarray(), np.asarray([v.sum(axis=0) for v in x]))
    with pytest.raises(ValueError):
        b.map(lambda v: v.sum(axis=0), value_shape=(3,))


def test_map_dtype_arg(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b.map(lambda v: v, dtype=np.float32)
    assert out.dtype == np.float32


def test_map_nontraceable_fallback(mesh):
    x = _x()
    b = bolt.array(x, mesh)

    def hostile(v):
        # .item() and float() force concrete values: not jax-traceable
        return np.full((2,), float(np.asarray(v).sum()))

    out = b.map(hostile)
    expected = np.asarray([hostile(v) for v in x])
    assert allclose(out.toarray(), expected)


def test_filter_on_value_axis(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b.filter(lambda v: v[0, 0] > 0, axis=(1,))
    expected = np.asarray([x[:, i, :] for i in range(x.shape[1])
                           if x[0, i, 0] > 0])
    assert allclose(out.toarray(), expected)
    assert out.split == 1


def test_reduce_errors(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    with pytest.raises(ValueError):
        # shape-changing reducer is invalid
        b.reduce(lambda a, c: (a + c)[:2])


def test_reduce_single_record(mesh):
    x = np.ones((1, 3))
    b = bolt.array(x, mesh)
    assert allclose(b.reduce(add).toarray(), x.sum(axis=0))
