"""TPU-backend map/filter/reduce, including non-aligned axes that force an
``_align`` swap (reference area: ``test/test_spark_functional.py``,
SURVEY §4)."""

from operator import add

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.utils import allclose

from tests.generic import filter_suite, map_suite, reduce_suite


def _x():
    rs = np.random.RandomState(3)
    return rs.randn(8, 4, 5)


def test_map(mesh):
    map_suite(_x(), bolt.array(_x(), mesh))


def test_filter(mesh):
    filter_suite(_x(), bolt.array(_x(), mesh))


def test_reduce(mesh):
    reduce_suite(_x(), bolt.array(_x(), mesh))


def test_map_nonaligned_axis(mesh):
    x = _x()
    b = bolt.array(x, mesh)  # keys = (0,)
    # mapping over axis 1 forces an implicit swap (reference _align)
    out = b.map(lambda v: v.sum(), axis=(1,))
    assert out.split == 1
    expected = np.asarray([x[:, i, :].sum() for i in range(x.shape[1])])
    assert allclose(out.toarray(), expected)


def test_map_value_axis_pair(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b.map(lambda v: v * 2, axis=(0, 2))
    # result keys = (axis0, axis2) leading
    expected = np.transpose(x, (0, 2, 1)) * 2
    assert allclose(out.toarray(), expected)


def test_map_value_shape_check(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b.map(lambda v: v.sum(axis=0), value_shape=(5,))
    assert allclose(out.toarray(), np.asarray([v.sum(axis=0) for v in x]))
    with pytest.raises(ValueError):
        b.map(lambda v: v.sum(axis=0), value_shape=(3,))


def test_map_dtype_arg(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b.map(lambda v: v, dtype=np.float32)
    assert out.dtype == np.float32


def test_map_nontraceable_fallback(mesh):
    x = _x()
    b = bolt.array(x, mesh)

    def hostile(v):
        # .item() and float() force concrete values: not jax-traceable
        return np.full((2,), float(np.asarray(v).sum()))

    with pytest.warns(bolt.HostFallbackWarning, match="hostile"):
        out = b.map(hostile)
    expected = np.asarray([hostile(v) for v in x])
    assert allclose(out.toarray(), expected)


def test_filter_nontraceable_fallback_warns(mesh):
    x = _x()
    b = bolt.array(x, mesh)

    def hostile(v):
        return bool(np.asarray(v).sum() > 0)   # np coercion: not traceable

    with pytest.warns(bolt.HostFallbackWarning, match="filter"):
        out = b.filter(hostile)
    expected = np.asarray([v for v in x if v.sum() > 0])
    assert allclose(out.toarray(), expected)


def test_reduce_nontraceable_fallback_warns(mesh):
    x = _x()
    b = bolt.array(x, mesh)

    def hostile(a, c):
        return np.asarray(a) + np.asarray(c)   # np coercion: not traceable

    with pytest.warns(bolt.HostFallbackWarning, match="reduce"):
        out = b.reduce(hostile)
    assert allclose(out.toarray(), x.sum(axis=0))


def test_buggy_traceable_funcs_raise_not_fallback(mesh):
    """A genuine bug in a jax-compatible callable must SURFACE, not silently
    reroute through the 100x-slower host oracle (VERDICT r1 weak-1: only
    trace-type errors may trigger the fallback)."""
    import warnings as _warnings
    x = _x()
    b = bolt.array(x, mesh)
    with _warnings.catch_warnings():
        # any HostFallbackWarning here is itself a failure
        _warnings.simplefilter("error", bolt.HostFallbackWarning)
        with pytest.raises(AttributeError):
            b.map(lambda v: v.nonexistent_attr)          # typo
        with pytest.raises(TypeError):
            b.map(lambda v: v.reshape(3))                # bad reshape
        with pytest.raises((TypeError, ValueError)):
            b.filter(lambda v: (v + np.ones(7)).sum() > 0)  # shape mismatch
        with pytest.raises((TypeError, ValueError)):
            b.reduce(lambda a, c: a @ np.ones((99, 2)))  # bad matmul shapes


def test_filter_on_value_axis(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b.filter(lambda v: v[0, 0] > 0, axis=(1,))
    expected = np.asarray([x[:, i, :] for i in range(x.shape[1])
                           if x[0, i, 0] > 0])
    assert allclose(out.toarray(), expected)
    assert out.split == 1


def test_reduce_errors(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    with pytest.raises(ValueError):
        # shape-changing reducer is invalid
        b.reduce(lambda a, c: (a + c)[:2])


def test_reduce_single_record(mesh):
    x = np.ones((1, 3))
    b = bolt.array(x, mesh)
    assert allclose(b.reduce(add).toarray(), x.sum(axis=0))


# ----------------------------------------------------------------------
# pending (lazy-count) filter semantics: the survivor count syncs to host
# only when the shape is needed, and toarray batches it with the data fetch
# ----------------------------------------------------------------------

def test_filter_is_pending_until_shape_read(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b.filter(lambda v: v.sum() > 0)
    assert out.pending
    expected = np.asarray([v for v in x if v.sum() > 0])
    assert out.shape == expected.shape        # resolves: one scalar sync
    assert not out.pending
    assert allclose(out.toarray(), expected)


def test_filter_toarray_without_prior_resolution(mesh):
    # the batched-fetch fast path: toarray on a still-pending result
    x = _x()
    b = bolt.array(x, mesh)
    out = b.filter(lambda v: v[0, 0] > 0)
    assert out.pending
    expected = np.asarray([v for v in x if v[0, 0] > 0])
    assert allclose(out.toarray(), expected)
    # the fetched count resolves the device side as a side effect, so later
    # consumers pay neither a re-transfer nor a count sync
    assert not out.pending
    assert allclose(out.toarray(), expected)
    assert out.split == 1


def test_filter_repr_does_not_sync(mesh):
    x = _x()
    out = bolt.array(x, mesh).filter(lambda v: v.sum() > 0)
    r = repr(out)
    assert "pending" in r
    assert out.pending  # repr must not have forced the count sync


def test_filter_dtype_known_while_pending(mesh):
    x = _x()
    out = bolt.array(x, mesh).filter(lambda v: v.sum() > 0)
    assert out.dtype == x.dtype
    assert out.pending


def test_filter_fuses_deferred_chain(mesh):
    # map defers; filter consumes the chain inside its own fused program
    x = _x()
    b = bolt.array(x, mesh)
    out = b.map(lambda v: v * 2).map(lambda v: v - 1).filter(
        lambda v: v.sum() > -20)
    y = x * 2 - 1
    expected = np.asarray([v for v in y if v.sum() > -20])
    assert expected.shape[0] not in (0, x.shape[0])  # a real subset
    assert allclose(out.toarray(), expected)


def test_filter_empty_and_full(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    none = b.filter(lambda v: v.sum() > 1e9)
    assert none.shape == (0,) + x.shape[1:]
    assert none.toarray().shape == (0,) + x.shape[1:]
    everything = b.filter(lambda v: v.sum() > -1e9)
    assert allclose(everything.toarray(), x)


def test_filter_chains_into_map(mesh):
    x = _x()
    b = bolt.array(x, mesh)
    out = b.filter(lambda v: v.sum() > 0).map(lambda v: v + 1)
    expected = np.asarray([v + 1 for v in x if v.sum() > 0])
    assert allclose(out.toarray(), expected)


def test_filter_toarray_large_buffer_path(mesh, monkeypatch):
    # above the batched-fetch size cap, toarray resolves first (scalar
    # count sync + sliced fetch) instead of shipping the padded buffer
    import bolt_tpu.tpu.array as mod
    monkeypatch.setattr(mod, "_PENDING_FETCH_MAX_BYTES", 0)
    x = _x()
    out = bolt.array(x, mesh).filter(lambda v: v.sum() > 0)
    expected = np.asarray([v for v in x if v.sum() > 0])
    assert allclose(out.toarray(), expected)
    assert not out.pending


def test_filter_eager_path_for_large_inputs(mesh, monkeypatch):
    # above the fused-path size cap, filter takes the memory-safe
    # two-phase route: eager count sync, survivor-sized gather output
    import bolt_tpu.tpu.array as mod
    monkeypatch.setattr(mod, "_FILTER_FUSED_MAX_BYTES", 0)
    x = _x()
    b = bolt.array(x, mesh)
    out = b.filter(lambda v: v.sum() > 0)
    assert not out.pending  # eager path resolves immediately
    expected = np.asarray([v for v in x if v.sum() > 0])
    assert allclose(out.toarray(), expected)
    assert out.split == 1
    # value-axis filter goes through _align then the same path
    out2 = b.filter(lambda v: v[0, 0] > 0, axis=(1,))
    exp2 = np.asarray([x[:, i, :] for i in range(x.shape[1])
                       if x[0, i, 0] > 0])
    assert allclose(out2.toarray(), exp2)


def test_filter_eager_gather_bucketed_one_executable(mesh, monkeypatch):
    # VERDICT r3 weak-5: two HBM-scale filters with DIFFERENT survivor
    # counts in the same power-of-two band reuse ONE compiled gather —
    # the executable is keyed on the bucket, not the exact count
    import bolt_tpu.tpu.array as mod
    monkeypatch.setattr(mod, "_FILTER_FUSED_MAX_BYTES", 0)
    x = _x()
    b = bolt.array(x, mesh)

    def n_gathers():
        return sum(1 for k in mod._JIT_CACHE if k[0] == "filter-gather")

    # record i sums to 4*i: thresholds drawing 3 and 4 survivors land in
    # the same power-of-two bucket (4)
    x = np.arange(8, dtype=float)[:, None, None] * np.ones((8, 2, 2))
    b = bolt.array(x, mesh)
    before = n_gathers()
    out1 = b.filter(lambda v: v.sum() > 18.0)     # 3 survivors
    out2 = b.filter(lambda v: v.sum() > 14.0)     # 4 survivors
    n1, n2 = out1.shape[0], out2.shape[0]
    assert (n1, n2) == (3, 4)
    assert mod._gather_bucket(n1, x.shape[0]) == \
        mod._gather_bucket(n2, x.shape[0])
    assert n_gathers() == before + 1              # one bucket, one compile
    assert allclose(out1.toarray(), x[5:])
    assert allclose(out2.toarray(), x[4:])


def test_gather_bucket_bands():
    from bolt_tpu.tpu.array import _gather_bucket
    assert _gather_bucket(0, 100) == 1
    assert _gather_bucket(1, 100) == 1
    assert _gather_bucket(3, 100) == 4
    assert _gather_bucket(4, 100) == 4
    assert _gather_bucket(5, 100) == 8
    assert _gather_bucket(97, 100) == 100          # capped at n
