"""Local-backend chunked/stacked views: parity against the TPU backend
(and manual NumPy), so mode-agnostic chunked code has a local oracle.
Superset of the reference, which has ChunkedArray/StackedArray only on the
distributed backend (SURVEY §2.1)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.utils import allclose, prod


def _x(shape=(4, 6, 8)):
    rs = np.random.RandomState(3)
    return rs.randn(*shape)


def _pair(mesh, x, **kw):
    """The same chunk view on both backends."""
    lc = bolt.array(x).chunk(**kw)
    tc = bolt.array(x, mesh).chunk(**kw)
    return lc, tc


def test_plan_parity(mesh):
    x = _x()
    lc, tc = _pair(mesh, x, size=(2, 3), axis=(0, 1))
    assert lc.plan == tc.plan == (2, 3)
    assert lc.padding == tc.padding == (0, 0)
    assert lc.grid == tc.grid == (3, 3)
    assert lc.kshape == (4,) and lc.vshape == (6, 8)
    assert lc.uniform == tc.uniform
    assert lc.mode == "local" and tc.mode == "tpu"
    # MB-budget plans agree (same shared helper)
    lb, tb = _pair(mesh, x, size=str(64 / 1e6))
    assert lb.plan == tb.plan


def test_unchunk_roundtrip():
    x = _x()
    c = bolt.array(x).chunk(size=(2,), axis=(0,))
    out = c.unchunk()
    assert out.mode == "local"
    assert allclose(out.toarray(), x)


def test_map_uniform_shape_change(mesh):
    x = _x()
    f = lambda blk: blk.sum(axis=1, keepdims=True)
    lc, tc = _pair(mesh, x, size=(3,), axis=(0,))
    lout = lc.map(f).unchunk().toarray()
    tout = tc.map(f).unchunk().toarray()
    assert allclose(lout, tout)
    # manual: per (key, chunk) block sum over the second value axis
    expect = np.stack([
        np.concatenate([x[k, i * 3:(i + 1) * 3].sum(axis=1, keepdims=True)
                        for i in range(2)], axis=0) for k in range(4)])
    assert allclose(lout, expect)


def test_map_padded_halo(mesh):
    x = _x()
    # halo-smoothing: with padding=1 each block sees its neighbours'
    # boundary rows, so a local mean filter matches the global one
    def smooth(blk):
        out = np.copy(blk)
        out[1:-1] = (blk[:-2] + blk[1:-1] + blk[2:]) / 3.0
        return out
    lc, tc = _pair(mesh, x, size=(2,), axis=(0,), padding=1)
    lout = lc.map(smooth).unchunk().toarray()
    import jax.numpy as jnp
    def smooth_j(blk):
        return jnp.concatenate(
            [blk[:1], (blk[:-2] + blk[1:-1] + blk[2:]) / 3.0, blk[-1:]],
            axis=0)
    tout = tc.map(smooth_j).unchunk().toarray()
    assert allclose(lout, tout)
    # interior rows (away from the ARRAY edge) match the global filter
    glob = (x[:, :-2] + x[:, 1:-1] + x[:, 2:]) / 3.0
    assert allclose(lout[:, 1:-1], glob)


def test_map_ragged_tail(mesh):
    x = _x((3, 7, 4))
    f = lambda blk: blk * 2.0
    lc, tc = _pair(mesh, x, size=(3,), axis=(0,))
    assert not lc.uniform
    lout = lc.map(f).unchunk().toarray()
    tout = tc.map(f).unchunk().toarray()
    assert allclose(lout, tout)
    assert allclose(lout, x * 2.0)


def test_map_contract_errors():
    x = _x()
    c = bolt.array(x).chunk(size=(2,), axis=(0,), padding=1)
    with pytest.raises(ValueError):
        c.map(lambda blk: blk[:1])       # padded: must preserve shape
    cu = bolt.array(x).chunk(size=(3,), axis=(0,))
    with pytest.raises(ValueError):
        cu.map(lambda blk: blk.sum())    # uniform: must preserve rank
    with pytest.raises(ValueError):
        cu.map(lambda blk: blk, value_shape=(9, 9))


def test_axis_exchange_parity(mesh):
    x = _x()
    lc, tc = _pair(mesh, x, size=(2,), axis=(0,))
    l2 = lc.keys_to_values((0,))
    t2 = tc.keys_to_values((0,))
    assert l2.split == t2.split == 0
    assert l2.plan == t2.plan
    assert allclose(l2.unchunk().toarray(), t2.unchunk().toarray())
    l3 = l2.values_to_keys((1,))
    t3 = t2.values_to_keys((1,))
    assert l3.split == t3.split == 1
    assert l3.plan == t3.plan
    assert allclose(l3.unchunk().toarray(), t3.unchunk().toarray())
    with pytest.raises(ValueError):
        lc.keys_to_values((5,))
    with pytest.raises(ValueError):
        lc.values_to_keys((7,))


def test_chunk_key_axis():
    x = _x()
    # key axis 1: keys move to the front, value axes are the rest
    c = bolt.array(x).chunk(size=(2,), axis=(0,), key_axis=(1,))
    assert c.kshape == (6,) and c.vshape == (4, 8)
    assert allclose(c.unchunk().toarray(), np.transpose(x, (1, 0, 2)))


def test_zero_records():
    x = np.zeros((0, 6, 8))
    out = bolt.array(x).chunk(size=(2,), axis=(0,)).map(
        lambda blk: blk.sum(axis=1, keepdims=True)).unchunk().toarray()
    assert out.shape == (0, 6, 1)


def test_stacked_parity(mesh):
    x = _x((8, 5, 4))
    f = lambda blk: blk - blk.mean(axis=0)
    ls = bolt.array(x).stacked(size=3)
    ts = bolt.array(x, mesh).stacked(size=3)
    assert ls.size == ts.size == 3
    assert ls.nblocks == ts.nblocks == 3
    lout = ls.map(f).unstack().toarray()
    tout = ts.map(f).unstack().toarray()
    assert allclose(lout, tout)
    # manual oracle: blocks of 3 consecutive records
    expect = np.concatenate(
        [x[i:i + 3] - x[i:i + 3].mean(axis=0) for i in (0, 3, 6)])
    assert allclose(lout, expect)


def test_stacked_contract():
    x = _x((6, 4))
    s = bolt.array(x).stacked(size=4)
    with pytest.raises(ValueError):
        s.map(lambda blk: blk[:1])       # must preserve record count
    with pytest.raises(ValueError):
        bolt.array(x).stacked(size=0)
    out = s.map(lambda blk: blk * 2, value_shape=(4,), dtype=np.float32)
    assert out.dtype == np.float32
    assert allclose(out.unstack().toarray(), (x * 2).astype(np.float32))


def test_stacked_zero_records():
    x = np.zeros((0, 4))
    out = bolt.array(x).stacked(size=8).map(
        lambda blk: blk * 2.0).unstack().toarray()
    assert out.shape == (0, 4)


def test_repr():
    c = bolt.array(_x()).chunk(size=(2,), axis=(0,))
    r = repr(c)
    assert "mode: local" in r and "plan" in r
    s = bolt.array(_x()).stacked(size=2)
    assert "mode: local" in repr(s)
