"""quantile/median on both backends and ops.cov/corrcoef: parity against
NumPy (np.quantile / np.cov / np.corrcoef).  Superset of the reference
(Bolt/StatCounter has no quantiles or covariance)."""

import numpy as np
import pytest

import bolt_tpu as bolt
from bolt_tpu.ops import corrcoef, cov
from bolt_tpu.utils import allclose


def _x(shape=(16, 5, 4)):
    rs = np.random.RandomState(21)
    return rs.randn(*shape)


@pytest.mark.parametrize("q", [0.0, 0.25, 0.5, 0.9, 1.0])
def test_quantile_parity(mesh, q):
    x = _x()
    t = bolt.array(x, mesh, axis=(0,)).quantile(q)
    l = bolt.array(x).quantile(q)
    expect = np.quantile(x, q, axis=0)
    assert allclose(t.toarray(), expect)
    assert allclose(l.toarray(), expect)


def test_quantile_axes_and_median(mesh):
    x = _x()
    b = bolt.array(x, mesh, axis=(0, 1))
    # default: all key axes
    assert allclose(b.quantile(0.5).toarray(), np.median(x, axis=(0, 1)))
    assert allclose(b.median().toarray(), np.median(x, axis=(0, 1)))
    # explicit value axis; keepdims
    assert allclose(b.quantile(0.75, axis=(2,)).toarray(),
                    np.quantile(x, 0.75, axis=2))
    assert allclose(b.median(axis=(0,), keepdims=True).toarray(),
                    np.median(x, axis=0, keepdims=True))
    # local axis arg; axis=None means the leading axis (stats convention)
    assert allclose(bolt.array(x).median(axis=(1,)).toarray(),
                    np.median(x, axis=1))
    assert allclose(bolt.array(x).quantile(0.5, axis=None).toarray(),
                    np.median(x, axis=0))
    # a q-sweep hits ONE compiled program (q is a runtime argument)
    bq = bolt.array(x, mesh)
    for q in np.linspace(0.1, 0.9, 5):
        assert allclose(bq.quantile(float(q)).toarray(),
                        np.quantile(x, q, axis=0))
    # a deferred map chain fuses into the quantile program
    assert allclose(bolt.array(x, mesh).map(lambda v: v * 2).median().toarray(),
                    np.median(x * 2, axis=0))


def test_quantile_vector_q(mesh):
    # 1-d q prepends a q axis like np.quantile; on the TPU backend the new
    # axis is a flat KEY axis (filter's output convention)
    x = _x()
    qs = [0.1, 0.5, 0.9]
    expect = np.quantile(x, qs, axis=0)
    t = bolt.array(x, mesh).quantile(qs)
    l = bolt.array(x).quantile(np.asarray(qs))
    assert t.shape == expect.shape and l.shape == expect.shape
    assert t.split == 1
    assert allclose(t.toarray(), expect)
    assert allclose(l.toarray(), expect)
    # keepdims, multi key axes: q axis leads, reduced dims stay as 1s
    b2 = bolt.array(x, mesh, axis=(0, 1))
    e2 = np.quantile(x, qs, axis=(0, 1), keepdims=True)
    t2 = b2.quantile(qs, keepdims=True)
    assert t2.shape == e2.shape and allclose(t2.toarray(), e2)
    assert t2.split == 3                  # q + the two kept key axes
    # value-axis vector quantile keeps the original key axes AFTER q
    t3 = bolt.array(x, mesh).quantile(qs, axis=(2,))
    assert allclose(t3.toarray(), np.quantile(x, qs, axis=2))
    assert t3.split == 2
    # two q-lengths reuse the same _cached_jit entry (jit retraces per aval)
    from bolt_tpu.tpu import array as array_mod
    n_before = sum(1 for k in array_mod._JIT_CACHE if k[0] == "quantile")
    bolt.array(x, mesh).quantile([0.2, 0.4, 0.6, 0.8]).toarray()
    assert sum(1 for k in array_mod._JIT_CACHE
               if k[0] == "quantile") == n_before
    # vector-q median equivalence through quantile; single-element q keeps
    # the axis (numpy semantics)
    t1 = bolt.array(x, mesh).quantile([0.5])
    assert t1.shape == (1,) + x.shape[1:]
    assert allclose(t1.toarray(), np.quantile(x, [0.5], axis=0))


def test_quantile_validation(mesh):
    b = bolt.array(_x(), mesh)
    with pytest.raises(ValueError):
        b.quantile(1.5)
    with pytest.raises(ValueError):
        b.quantile([0.2, 1.8])           # out of range inside a vector
    with pytest.raises(ValueError):
        bolt.array(_x()).quantile((0.2, -0.8))
    with pytest.raises(ValueError):
        b.quantile([[0.2], [0.8]])       # 2-d q rejected on both backends
    with pytest.raises(ValueError):
        bolt.array(_x()).quantile([[0.2], [0.8]])
    with pytest.raises(ValueError):
        b.quantile("half")
    # NaN q is rejected up front on BOTH backends (q is a traced argument
    # on tpu — a NaN past validation would silently return all-NaN)
    with pytest.raises(ValueError):
        b.quantile(float("nan"))
    with pytest.raises(ValueError):
        b.quantile([0.5, float("nan")])
    with pytest.raises(ValueError):
        bolt.array(_x()).quantile(float("nan"))


def test_cov_parity(mesh):
    x = _x((32, 6))
    expect = np.cov(x, rowvar=False)
    t = cov(bolt.array(x, mesh, axis=(0,)))
    l = cov(bolt.array(x))
    assert allclose(t, expect, rtol=1e-6)
    assert allclose(l, expect, rtol=1e-6)
    # multi-axis samples/features flatten like pca's convention
    y = _x((8, 4, 3))
    ty = cov(bolt.array(y, mesh, axis=(0,)))
    assert allclose(ty, np.cov(y.reshape(8, 12), rowvar=False), rtol=1e-6)
    # uncentered second moment; ddof=0
    t0 = cov(bolt.array(x, mesh), center=False, ddof=0)
    assert allclose(t0, x.T @ x / 32, rtol=1e-6)
    # mean comes back on request; deferred chains fuse in
    c, mu = cov(bolt.array(x, mesh).map(lambda v: v + 1), return_mean=True)
    assert allclose(mu, x.mean(axis=0) + 1, rtol=1e-6)
    assert allclose(c, expect, rtol=1e-6)
    with pytest.raises(ValueError):
        cov(bolt.array(_x((1, 4))), ddof=1)
    with pytest.raises(TypeError):
        cov(np.ones((4, 4)))


def test_cov_complex(mesh):
    # np.cov conjugates the SECOND factor; both backends must match it
    rs = np.random.RandomState(13)
    xc = rs.randn(32, 4) + 1j * rs.randn(32, 4)
    expect = np.cov(xc, rowvar=False)
    assert allclose(cov(bolt.array(xc)), expect, rtol=1e-6)
    assert allclose(cov(bolt.array(xc, mesh)), expect, rtol=1e-6)


def test_corrcoef_parity(mesh):
    x = _x((24, 5))
    expect = np.corrcoef(x, rowvar=False)
    assert allclose(corrcoef(bolt.array(x, mesh)), expect, rtol=1e-6)
    assert allclose(corrcoef(bolt.array(x)), expect, rtol=1e-6)
    assert allclose(np.diag(corrcoef(bolt.array(x, mesh))), np.ones(5),
                    rtol=1e-6)


def test_argmax_argmin_parity(mesh):
    x = _x((12, 5, 4))
    b = bolt.array(x, mesh, axis=(0,))
    l = bolt.array(x)                     # inherits ndarray argmax/argmin
    for axis in (None, 0, 1, 2, -1, -2):
        assert allclose(b.argmax(axis=axis).toarray(),
                        np.argmax(x, axis=axis))
        assert allclose(b.argmin(axis=axis).toarray(),
                        np.argmin(x, axis=axis))
        assert allclose(np.asarray(l.argmax(axis=axis)),
                        np.argmax(x, axis=axis))
    # keepdims; split bookkeeping (key axis reduced -> split drops)
    assert allclose(b.argmax(axis=0, keepdims=True).toarray(),
                    np.argmax(x, axis=0, keepdims=True))
    assert b.argmax(axis=0).split == 0
    assert b.argmax(axis=1).split == 1
    # ties resolve to the first occurrence, like numpy
    t = np.zeros((4, 3))
    t[1] = t[3] = 7.0
    bt = bolt.array(t, mesh)
    assert allclose(bt.argmax(axis=0).toarray(), np.argmax(t, axis=0))
    with pytest.raises(ValueError):
        b.argmax(axis=9)
    with pytest.raises(TypeError):
        b.argmax(axis=1.9)               # non-integer axis: ndarray's type


def test_quantile_cov_2d_mesh(mesh2d):
    # multi-axis key sharding: same answers as the 1-axis layout
    x = _x((8, 4, 6))
    b = bolt.array(x, mesh2d, axis=(0, 1))
    assert allclose(b.median().toarray(), np.median(x, axis=(0, 1)))
    assert allclose(b.quantile(0.3, axis=(2,)).toarray(),
                    np.quantile(x, 0.3, axis=2))
    c = cov(b)
    assert allclose(c, np.cov(x.reshape(32, 6), rowvar=False), rtol=1e-6)


def test_ndarray_method_parity(mesh):
    # methods the local backend inherits from ndarray now have TPU twins
    x = np.abs(_x((8, 4, 3))) + 0.5
    b = bolt.array(x, mesh, axis=(0,))
    assert allclose(b.prod().toarray(), x.prod(axis=0))
    assert allclose(b.prod(axis=(1,), keepdims=True).toarray(),
                    x.prod(axis=1, keepdims=True))
    m = b > 1.0
    xm = x > 1.0
    assert allclose(m.all().toarray(), xm.all(axis=0))
    assert allclose(m.any(axis=(0, 2)).toarray(), xm.any(axis=(0, 2)))
    assert allclose(b.clip(0.7, 1.2).toarray(), x.clip(0.7, 1.2))
    # SAME keyword names as ndarray.clip, so portable code uses one form
    assert allclose(b.clip(max=1.0).toarray(), x.clip(max=1.0))
    assert allclose(b.clip(a_max=1.0).toarray(), x.clip(max=1.0))  # alias
    assert allclose(b.round(1).toarray(), x.round(1))
    # int bounds after float bounds keep the int dtype (type-aware cache)
    xi = (x * 10).astype(np.int64)
    bi = bolt.array(xi, mesh)
    ci = bi.clip(0, 9)
    assert ci.dtype == xi.dtype
    assert allclose(ci.toarray(), xi.clip(0, 9))
    # array-valued bounds broadcast against the FULL logical shape, like
    # ndarray.clip — including bounds that span the key axes
    lo = np.full(x.shape[2], 0.8)
    assert allclose(b.clip(min=lo).toarray(), x.clip(min=lo))
    full = np.full(x.shape, 0.9)
    assert allclose(b.clip(min=full).toarray(), x.clip(min=full))
    keyed = np.linspace(0.6, 1.1, x.shape[0]).reshape(-1, 1, 1)
    assert allclose(b.clip(min=keyed).toarray(), x.clip(min=keyed))
    # min > max: numpy's ordering (the upper bound wins)
    assert allclose(b.clip(1.0, 0.8).toarray(), x.clip(1.0, 0.8))
    with pytest.raises(ValueError):
        b.clip()
    with pytest.raises(ValueError):
        b.clip(0.1, a_min=0.2)
    with pytest.raises(TypeError):
        b.round(1.7)                     # like ndarray.round
    # scalar-operator cache is type-aware: b*2 then b*2.0 keep dtypes
    i2 = (bi * 2).toarray()
    f2 = (bi * 2.0).toarray()
    assert i2.dtype == xi.dtype and np.issubdtype(f2.dtype, np.floating)


def test_cumsum_cumprod_parity(mesh):
    x = _x((6, 4, 3))
    b = bolt.array(x, mesh, axis=(0,))
    for axis in (0, 1, 2, -1):
        assert allclose(b.cumsum(axis=axis).toarray(), x.cumsum(axis=axis))
        assert allclose(b.cumprod(axis=axis).toarray(),
                        x.cumprod(axis=axis))
    # axis=None: flattened, single flat key axis (split=1)
    c = b.cumsum()
    assert c.split == 1
    assert allclose(c.toarray(), x.cumsum())
    # deferred chains fuse in
    assert allclose(bolt.array(x, mesh).map(lambda v: v + 1).cumsum(axis=0)
                    .toarray(), (x + 1).cumsum(axis=0))
    with pytest.raises(TypeError):
        b.cumsum(axis=1.5)               # non-integer axis: ndarray's type


# ----------------------------------------------------------------------
# round-2 ndarray-method parity additions: argsort, dot
# ----------------------------------------------------------------------

def test_argsort_parity(mesh):
    x = np.random.RandomState(60).permutation(8 * 5 * 4).reshape(8, 5, 4).astype(np.float64)
    b = bolt.array(x, mesh)
    lo = bolt.array(x)
    # distinct values: any sort kind agrees
    assert allclose(b.argsort().toarray(), x.argsort())          # last axis
    assert allclose(b.argsort(axis=0).toarray(), x.argsort(axis=0))
    assert allclose(b.argsort(axis=-2).toarray(), x.argsort(axis=-2))
    out = b.argsort(axis=None)
    assert out.split == 1
    assert allclose(out.toarray(), x.argsort(axis=None))
    assert allclose(lo.argsort(axis=1).toarray(), x.argsort(axis=1))
    # ties: stable kind is numpy-identical on both backends
    t = np.zeros((6, 3)); t[::2] = 1.0
    bt = bolt.array(t, mesh)
    assert allclose(bt.argsort(axis=0, kind="stable").toarray(),
                    t.argsort(axis=0, kind="stable"))
    with pytest.raises(TypeError):
        b.argsort(axis=1.5)
    # deferred chains fuse in
    assert allclose(bolt.array(x, mesh).map(lambda v: -v).argsort(axis=0)
                    .toarray(), (-x).argsort(axis=0))


def test_dot_parity(mesh):
    rs = np.random.RandomState(61)
    # 2-d @ 2-d
    a, w = rs.randn(8, 5), rs.randn(5, 3)
    b = bolt.array(a, mesh)
    out = b.dot(w)
    assert out.split == 1
    assert allclose(out.toarray(), a.dot(w))
    # 1-d inner product
    v = rs.randn(5)
    bv = bolt.array(rs.randn(5).reshape(5), mesh)
    assert allclose(float(bv.dot(v).toarray()),
                    float(np.asarray(bv.toarray()).dot(v)))
    # 3-d . 2-d: dot ≠ matmul for these ranks in general, but matches numpy
    a3 = rs.randn(8, 4, 5)
    b3 = bolt.array(a3, mesh)
    assert allclose(b3.dot(w).toarray(), a3.dot(w))
    # 3-d . 3-d: the genuinely-different-from-@ case
    c3 = rs.randn(2, 5, 3)
    assert allclose(b3.dot(c3).toarray(), a3.dot(c3))
    # local backend inherits ndarray.dot: same expression both backends
    assert allclose(bolt.array(a3).dot(w).toarray(), b3.dot(w).toarray())
    with pytest.raises(ValueError):       # numpy's type for bad contraction
        b.dot(np.ones((7, 2)))
    with pytest.raises(ValueError):
        bolt.array(a3).dot(np.ones((7, 2)))  # identical on the oracle
    with pytest.raises(ValueError):
        b.argsort(kind='bogus')              # invalid kind, like ndarray
    assert allclose(
        bolt.array(np.zeros((6, 3)), mesh).argsort(axis=0, kind='mergesort')
        .toarray(), np.zeros((6, 3)).argsort(axis=0, kind='mergesort'))


def test_full_constructor(mesh):
    t = bolt.full((8, 4), 2.5, mesh)
    l = bolt.full((8, 4), 2.5)
    assert t.mode == "tpu" and l.mode == "local"
    assert t.dtype == l.dtype == np.float64
    assert allclose(t.toarray(), np.full((8, 4), 2.5))
    assert allclose(l.toarray(), np.full((8, 4), 2.5))
    # numpy's dtype-from-value inference on both backends
    ti = bolt.full((8, 4), 2, mesh)
    assert np.issubdtype(ti.dtype, np.integer)
    assert np.issubdtype(bolt.full((8, 4), 2).dtype, np.integer)
    assert bolt.full((8, 4), 2, mesh, dtype=np.float32).dtype == np.float32


def test_histogram_parity(mesh):
    from bolt_tpu.ops import histogram
    x = np.random.RandomState(70).randn(16, 6, 4)
    tp, lo = bolt.array(x, mesh), bolt.array(x)
    for kwargs in [dict(), dict(bins=7), dict(bins=5, range=(-1.0, 1.0)),
                   dict(bins=4, density=True)]:
        ct, et = histogram(tp, **kwargs)
        cl, el = histogram(lo, **kwargs)
        cn, en = np.histogram(x, **kwargs)
        assert np.allclose(ct, cn) and np.allclose(cl, cn), kwargs
        assert np.allclose(et, en) and np.allclose(el, en), kwargs
    # deferred chains fuse in
    ct, et = histogram(tp.map(lambda v: v * 2), bins=6)
    cn, en = np.histogram(x * 2, bins=6)
    assert np.allclose(ct, cn) and np.allclose(et, en)
    with pytest.raises(ValueError):
        histogram(tp, bins=0)
    with pytest.raises(ValueError):
        histogram(tp, range=(1.0, -1.0))


def test_histogram_numpy_edge_semantics(mesh):
    from bolt_tpu.ops import histogram
    x = np.random.RandomState(71).randn(8, 4)
    tp, lo = bolt.array(x, mesh), bolt.array(x)
    # counts are int64 on BOTH backends (numpy's dtype)
    assert histogram(tp)[0].dtype == np.int64
    assert histogram(lo)[0].dtype == np.int64
    # equal min/max range expands by +-0.5, like numpy's constant case
    ct, et = histogram(tp, bins=3, range=(1.0, 1.0))
    cn, en = np.histogram(x, bins=3, range=(1.0, 1.0))
    assert np.array_equal(ct, cn) and np.allclose(et, en)
    with pytest.raises(ValueError):
        histogram(tp, range=(2.0, -1.0))
    # direct constructor entry point infers dtype like the factory
    from bolt_tpu.tpu.construct import ConstructTPU
    assert np.issubdtype(ConstructTPU.full((4, 2), 3, mesh).dtype, np.integer)
