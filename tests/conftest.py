"""Test configuration: a fake 8-device CPU mesh.

The reference tests distribution with a local-mode SparkContext
(``test/conftest.py :: sc`` fixture, ``local[2]`` — SURVEY §4): same code
paths, no cluster.  The analog here is 8 virtual CPU devices via
``xla_force_host_platform_device_count``, so ``psum``/``all_to_all``/
sharding semantics run for real without TPU hardware.

x64 is enabled so dtypes match the NumPy oracle exactly (the reference is
bit-compatible with numpy defaults; SURVEY §7 "decide early").
"""

import os

# must be appended before the first backend initialisation
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

# the environment pins JAX_PLATFORMS to the TPU plugin at interpreter start;
# tests run on the virtual CPU mesh — EXCEPT under BOLT_TEST_CHIP=1, the
# on-chip correctness gate (scripts/chip_gate.py): real TPU backend with
# production x64-OFF numerics, running only the `-m chip` subset
# (tests/test_chip.py)
CHIP_GATE = os.environ.get("BOLT_TEST_CHIP", "").lower() in ("1", "true",
                                                             "yes")
if not CHIP_GATE:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


# the concurrency suites: every test in these modules runs under the
# armed lockdep witness (bolt_tpu/_lockdep) via the autouse fixture
# below — one observed rank inversion, self-deadlock or
# dispatch-under-lock anywhere in them fails the test that did it
_LOCKDEP_SUITES = frozenset({
    "test_serve", "test_serve_batching", "test_stream",
    "test_supervisor", "test_multistat", "test_parity_locks",
    "test_podwatch",
})


def pytest_collection_modifyitems(config, items):
    """Under the chip gate the CPU-mesh/x64 assumptions of every other
    test are void — deselect everything unmarked so a bare
    ``BOLT_TEST_CHIP=1 pytest`` is safe without the wrapper script's
    ``-m chip`` flag.  Outside it, tag the concurrency suites with the
    ``lockdep`` marker so they run under the armed witness (and are
    selectable standalone via ``pytest -m lockdep``)."""
    if not CHIP_GATE:
        for item in items:
            base = os.path.basename(item.nodeid.split("::", 1)[0])
            if base[:-3] in _LOCKDEP_SUITES:
                item.add_marker(pytest.mark.lockdep)
        return
    skip = pytest.mark.skip(
        reason="BOLT_TEST_CHIP gate runs only the -m chip subset")
    for item in items:
        if "chip" not in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="module", autouse=True)
def _thread_census_gate():
    """Hygiene gate (ISSUE 17): no bolt-owned worker thread may outlive
    its test module.  A short drain window absorbs daemon workers that
    were signalled to exit but not yet reaped when teardown returns."""
    yield
    import time
    from bolt_tpu.obs import thread_census
    census = thread_census()
    deadline = time.monotonic() + 5.0
    while census and time.monotonic() < deadline:
        time.sleep(0.05)
        census = thread_census()
    assert census == {}, "module leaked worker threads: %s" % (census,)


@pytest.fixture(autouse=True)
def _lockdep_witness(request):
    """Arm the runtime lock-hierarchy witness around every ``lockdep``-
    marked test and fail the test on any NEW violation it recorded —
    the suites exercise the real thread pools, so a green run is an
    empirical no-inversion certificate for the lock inventory."""
    if "lockdep" not in request.keywords:
        yield
        return
    from bolt_tpu import _lockdep
    before = len(_lockdep.violations())
    was_enabled = _lockdep.enabled()
    _lockdep.enable()
    try:
        yield
    finally:
        if not was_enabled:
            _lockdep.disable()
    new = _lockdep.violations()[before:]
    assert not new, "lockdep violations during test:\n" + "\n".join(new)


@pytest.fixture(scope="session")
def mesh():
    """1-d 8-device mesh — the default distribution context."""
    return jax.make_mesh((8,), ("k",))


@pytest.fixture(scope="session")
def mesh2d():
    """2-d (4, 2) mesh for multi-axis key sharding."""
    return jax.make_mesh((4, 2), ("a", "b"))
