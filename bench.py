#!/usr/bin/env python
"""Benchmark harness for the BASELINE north-star.

Two measurements:

1. **Config 1 anchor** (``ones((200,200,64,64)).map(x+1).sum()``, 0.66 GB
   float32): runs on both the ``mode='local'`` NumPy oracle and the TPU
   backend.  This is the parity anchor — the result must be bit-exact
   (integral-valued floats; every partial sum is an exact float32).

2. **North-star scale** (same op at 10 GB float32): the array is built
   directly sharded on device and the deferred ``map`` chain fuses with the
   ``sum``, so the 10 GB intermediate never materialises — the pipeline
   reads HBM once.  NumPy is not run at this size (20+ GB host RSS);
   throughput ratio to the NumPy anchor is computed per-byte, which is
   scale-fair for this bandwidth-bound op.

Throughput is measured at steady state: launches are pipelined (dispatch is
async) and the host syncs once at the end, so the per-iteration figure is
compute time, not the host↔device round-trip latency of this environment's
remote tunnel (~60 ms, measured and logged separately as ``synced``).
Every pipelined iteration still reads the full array from HBM.

Prints ONE JSON line:
    {"metric": "northstar_10GB_map_sum_throughput_per_chip",
     "value": <GB/s per chip at 10 GB>, "unit": "GB/s",
     "vs_baseline": <per-byte throughput ratio vs NumPy mode='local'>}
"""

import json
import os
import sys
import threading
import time

import numpy as np

SHAPE1 = (200, 200, 64, 64)            # BASELINE config 1: 0.655 GB f32
SHAPE10 = (3200, 200, 64, 64)          # north-star scale: 10.49 GB f32
DTYPE = np.float32
ITERS = 5


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def _gb(shape):
    return int(np.prod(shape)) * np.dtype(DTYPE).itemsize / 1e9


def bench_local_config1():
    x = np.ones(SHAPE1, DTYPE)
    (x + 1).sum()  # warm (page-in)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = (x + 1).sum(dtype=DTYPE)
        times.append(time.perf_counter() - t0)
    return float(out), min(times)


def bench_tpu(shape, pipe_iters=50):
    import bolt_tpu as bolt

    b = bolt.ones(shape, mode="tpu", dtype=DTYPE)
    b.cache()  # materialise the input; we time the pipeline, not construction
    mapper = lambda v: v + 1
    axes = tuple(range(len(shape)))

    def launch():
        # map defers; sum fuses the chain into one compiled pass over HBM;
        # dispatch is async — the returned array's buffer is a future.
        # cache() forces the LAZY terminal to dispatch (stat results are
        # pending fused-group handles now); the dispatch itself stays
        # async, so launches still pipeline
        return b.map(mapper, axis=(0,)).sum(axis=axes).cache()

    out = float(launch().toarray())  # compile + warm caches

    # latency including the host round-trip (one fetch per iteration)
    times = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        out = float(launch().toarray())
        times.append(time.perf_counter() - t0)
    synced = min(times)

    # pure host-fetch round-trip: re-fetch an already-materialised scalar
    # result (no compute), so it can be subtracted from the pipelined window
    done = launch()
    float(done.toarray())
    rts = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        float(done.toarray())
        rts.append(time.perf_counter() - t0)
    roundtrip = min(rts)

    # steady-state throughput: pipeline the launches, sync once at the end
    # (in-order per-device execution: the last result completing implies all
    # iterations ran; each reads the full array from HBM); the one closing
    # fetch's round-trip is subtracted so the figure is device time only
    t0 = time.perf_counter()
    results = [launch() for _ in range(pipe_iters)]
    out = float(results[-1].toarray())
    steady = (time.perf_counter() - t0 - roundtrip) / pipe_iters
    return out, steady, synced


def _engine_stats():
    """Compile-cache accounting for the result line: hit rate over the
    run, explicit XLA compile seconds, and whether the persistent
    on-disk cache (BOLT_PERSISTENT_CACHE=<dir>) served them."""
    from bolt_tpu import profile
    c = profile.engine_counters()
    lookups = c["hits"] + c["misses"]
    return {
        "cache_hit_rate": round(c["hits"] / lookups, 4) if lookups else None,
        "aot_compiles": c["aot_compiles"],
        "compile_seconds": round(c["compile_seconds"], 3),
        "persistent_hits": c["persistent_hits"],
    }


def main():
    pc = os.environ.get("BOLT_PERSISTENT_CACHE")
    if pc:
        from bolt_tpu import engine
        engine.persistent_cache(pc)
        _log("persistent compile cache: %s" % pc)

    # ---- config 1: parity anchor ------------------------------------
    _log("config 1 %s (%.2f GB): local baseline..." % (SHAPE1, _gb(SHAPE1)))
    local_out, local_t = bench_local_config1()
    local_gbps = _gb(SHAPE1) / local_t
    _log("local: %.3fs (%.2f GB/s)" % (local_t, local_gbps))

    tpu1_out, tpu1_t, tpu1_sync = bench_tpu(SHAPE1)
    _log("tpu:   %.4fs (%.2f GB/s)  [synced incl. host round-trip: %.4fs]"
         % (tpu1_t, _gb(SHAPE1) / tpu1_t, tpu1_sync))

    expected1 = float(np.prod(SHAPE1, dtype=np.float64) * 2.0)
    exact = (tpu1_out == local_out == expected1)
    _log("parity: tpu=%r local=%r expected=%r bit_exact=%r"
         % (tpu1_out, local_out, expected1, exact))
    if not exact:
        _log("WARNING: config-1 parity mismatch")

    # ---- north-star scale: 10 GB ------------------------------------
    _log("north-star %s (%.2f GB): fused map->sum on device..."
         % (SHAPE10, _gb(SHAPE10)))
    try:
        tpu10_out, tpu10_t, tpu10_sync = bench_tpu(SHAPE10)
        gb10 = _gb(SHAPE10)
        gbps10 = gb10 / tpu10_t
        expected10 = float(np.prod(SHAPE10, dtype=np.float64) * 2.0)
        _log("tpu:   %.4fs (%.2f GB/s)  parity=%r  [synced: %.4fs]"
             % (tpu10_t, gbps10, tpu10_out == expected10, tpu10_sync))
        result = {
            "metric": "northstar_10GB_map_sum_throughput_per_chip",
            "value": round(gbps10, 3),
            "unit": "GB/s",
            "vs_baseline": round(gbps10 / local_gbps, 3),
        }
    except Exception as e:  # e.g. HBM-constrained dev environment
        _log("10 GB run failed (%s); reporting config-1 scale" % e)
        result = {
            "metric": "config1_map_sum_throughput_per_chip",
            "value": round(_gb(SHAPE1) / tpu1_t, 3),
            "unit": "GB/s",
            "vs_baseline": round(local_t / tpu1_t, 3),
        }

    result["engine"] = _engine_stats()
    print(json.dumps(result))


def _watchdog(seconds):
    """Emit an explicit-failure JSON line and exit if the run wedges.

    The TPU here is attached through a remote pool with lease semantics; a
    stale grant (e.g. from an earlier killed process) can make backend
    initialisation block indefinitely.  A hung benchmark records nothing —
    an honest error line is strictly more informative."""
    def fire():
        print(json.dumps({
            "metric": "northstar_10GB_map_sum_throughput_per_chip",
            "value": 0, "unit": "GB/s", "vs_baseline": 0,
            "error": "benchmark exceeded %ds (TPU attach/lease wedged?)"
                     % seconds}), flush=True)
        os._exit(2)
    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


if __name__ == "__main__":
    guard = _watchdog(int(os.environ.get("BOLT_BENCH_TIMEOUT", "540")))
    main()
    guard.cancel()
