"""Tracing, timing and debug instrumentation.

The reference ships NO in-repo tracing/profiling — users fall back to the
Spark UI and JVM metrics (SURVEY §5).  The TPU stack does better for free:
``jax.profiler`` captures device traces viewable in TensorBoard/Perfetto,
and XLA programs have precise completion semantics, so wall-clock and GB/s
numbers are meaningful.  This module packages that:

* :func:`trace` — context manager writing a device trace to a log dir.
* :func:`annotate` — names a region so it shows up in the trace timeline.
* :func:`timeit` — robust wall-clock of a function over device arrays,
  fetching results to force completion (NOTE: fetching, not
  ``block_until_ready``, is the reliable barrier on remote-attached
  devices).
* :func:`throughput` — GB/s given bytes touched, the BASELINE "GB/s/chip"
  metric.
* :func:`debug_nans` — toggles jax NaN checking (the race-detector slot in
  SURVEY §5: SPMD is race-free by construction; numeric poison is the
  practical hazard, so that's what debug mode checks).
"""

import time

import numpy as np

import jax


def trace(logdir):
    """Device-trace context manager::

        with bolt_tpu.profile.trace("/tmp/trace"):
            b.map(f).sum().toarray()

    View with TensorBoard's profile plugin or Perfetto."""
    return jax.profiler.trace(logdir)


def annotate(name):
    """Name a region in the device trace timeline."""
    return jax.profiler.TraceAnnotation(name)


def timeit(fn, iters=5, warmup=1):
    """``(result, best_seconds)`` for ``fn()`` over ``iters`` timed runs.

    The result is pulled to the host each run (``jax.device_get``) so the
    timing includes real completion — on remote-attached devices,
    ``block_until_ready`` alone can return before execution finishes.
    """
    result = None
    for _ in range(max(warmup, 0)):
        result = jax.device_get(fn())
    best = float("inf")
    for _ in range(max(iters, 1)):
        t0 = time.perf_counter()
        result = jax.device_get(fn())
        best = min(best, time.perf_counter() - t0)
    return result, best


def throughput(nbytes, seconds):
    """GB/s for ``nbytes`` touched in ``seconds`` (the BASELINE
    "GB/s/chip" metric when run single-chip)."""
    return nbytes / 1e9 / seconds


def array_bytes(barray):
    """Logical payload bytes of a bolt array."""
    return int(np.prod(barray.shape, dtype=np.int64)) * barray.dtype.itemsize


def debug_nans(enable=True):
    """Toggle jax's NaN checking for all subsequently compiled programs."""
    jax.config.update("jax_debug_nans", bool(enable))


def memory_stats(device=None):
    """Per-device memory counters (HBM on TPU) as a dict, or ``{}`` where
    the backend doesn't expose them.  Keys follow the PJRT convention
    (``bytes_in_use``, ``bytes_limit``, ``peak_bytes_in_use``, ...)."""
    d = device if device is not None else jax.local_devices()[0]
    try:
        stats = d.memory_stats()
    except Exception:
        return {}
    return dict(stats) if stats else {}
