"""Constructors for the ``mode='local'`` backend.

Reference: ``bolt/local/construct.py :: ConstructLocal`` (symbol-level
citation, see SURVEY.md §0).
"""

import numpy as np

from bolt_tpu.local.array import BoltArrayLocal


class ConstructLocal:
    """Thin NumPy wrappers returning :class:`BoltArrayLocal`."""

    @staticmethod
    def _argcheck(*args, **kwargs):
        """The local backend is the dispatch fallback; it claims a call only
        when asked for by name (reference: ``bolt/local/construct.py ::
        ConstructLocal._argcheck``)."""
        return kwargs.get("mode") == "local"

    @staticmethod
    def array(a, dtype=None):
        return BoltArrayLocal(np.asarray(a, dtype=dtype))

    @staticmethod
    def ones(shape, dtype=None):
        return BoltArrayLocal(np.ones(shape, dtype=dtype))

    @staticmethod
    def zeros(shape, dtype=None):
        return BoltArrayLocal(np.zeros(shape, dtype=dtype))

    @staticmethod
    def concatenate(arrays, axis=0):
        if not isinstance(arrays, (tuple, list)) or len(arrays) == 0:
            raise ValueError("concatenate requires a non-empty tuple of arrays")
        return BoltArrayLocal(np.concatenate([np.asarray(a) for a in arrays], axis))
