from bolt_tpu.local.array import BoltArrayLocal
from bolt_tpu.local.construct import ConstructLocal

__all__ = ["BoltArrayLocal", "ConstructLocal"]
