"""Checkpoint / restore for distributed bolt arrays.

The reference has NO checkpointing — persistence is ``cache()`` only, and
fault tolerance is inherited from RDD lineage recomputation (SURVEY §5).
On TPU the analog is saving the sharded ``jax.Array`` itself: orbax writes
each shard from the process that owns it (multi-host safe) and restores
onto any compatible mesh, which is strictly more capable than the
reference (a cached RDD dies with the cluster; a checkpoint survives it).

>>> import bolt_tpu as bolt
>>> from bolt_tpu import checkpoint
>>> checkpoint.save("/tmp/ckpt", b)
>>> b2 = checkpoint.load("/tmp/ckpt", context=mesh)
"""

import json
import os

import numpy as np

import jax


def _array_path(path):
    return os.path.join(path, "array")


def _meta_path(path):
    return os.path.join(path, "bolt_meta.json")


def save(path, barray, force=True):
    """Write a ``mode='tpu'`` bolt array (data + split/shape/dtype
    metadata) under the directory ``path``."""
    from bolt_tpu.tpu.array import BoltArrayTPU
    if not isinstance(barray, BoltArrayTPU):
        raise TypeError("checkpoint.save expects a mode='tpu' array; "
                        "got %r" % type(barray).__name__)
    import orbax.checkpoint as ocp
    os.makedirs(path, exist_ok=True)
    ckptr = ocp.Checkpointer(ocp.ArrayCheckpointHandler())
    ckptr.save(os.path.abspath(_array_path(path)), args=ocp.args.ArraySave(barray._data),
               force=force)
    if jax.process_index() == 0:
        # orbax coordinates per-shard ownership; the metadata file has one
        # writer so a shared checkpoint dir never sees interleaved writes
        meta = {"split": barray.split, "shape": list(barray.shape),
                "dtype": str(barray.dtype)}
        with open(_meta_path(path), "w") as f:
            json.dump(meta, f)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("bolt_checkpoint_save")


def load(path, context=None):
    """Restore a bolt array saved by :func:`save`, placing it with the key
    sharding for ``context`` (default mesh when omitted)."""
    import orbax.checkpoint as ocp
    from bolt_tpu.parallel.sharding import key_sharding
    from bolt_tpu.tpu.array import BoltArrayTPU
    from bolt_tpu.tpu.construct import ConstructTPU

    with open(_meta_path(path)) as f:
        meta = json.load(f)
    mesh = ConstructTPU._resolve(context)
    shape = tuple(meta["shape"])
    split = int(meta["split"])
    sharding = key_sharding(mesh, shape, split)
    ckptr = ocp.Checkpointer(ocp.ArrayCheckpointHandler())
    data = ckptr.restore(
        os.path.abspath(_array_path(path)),
        args=ocp.args.ArrayRestore(
            restore_args=ocp.ArrayRestoreArgs(
                sharding=sharding, dtype=np.dtype(meta["dtype"]))))
    return BoltArrayTPU(data, split, mesh)
