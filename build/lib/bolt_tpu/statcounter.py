"""Streaming (single-pass, mergeable) statistics.

Reference: ``bolt/spark/statcounter.py :: StatCounter`` — adapted in the
reference from PySpark's Apache-licensed StatCounter; fields ``n, mu, m2,
maxValue, minValue`` with Welford ``merge`` and Chan ``mergeStats`` parallel
combine (symbol-level citation, SURVEY.md §0).  This implementation is
written fresh against the published Welford/Chan recurrences.

It operates elementwise over ndarrays, so a single counter tracks the
statistics of a whole value block.  The TPU backend computes the same
moments on-device inside ``shard_map`` and combines them with ``psum``
(``bolt_tpu/tpu/stats.py :: welford``), then returns them wrapped in this
class via :meth:`from_moments` — one contract, two execution engines.
"""

import numpy as np

ALL_STATS = ("count", "mean", "var", "std", "min", "max")


class StatCounter:
    """Mergeable first/second-moment accumulator."""

    def __init__(self, values=(), stats="all"):
        self.n = 0
        self.mu = 0.0
        self.m2 = 0.0
        self.maxValue = -np.inf
        self.minValue = np.inf
        if stats == "all":
            stats = ALL_STATS
        self.requested = tuple(stats)
        for v in values:
            self.merge(v)

    # ------------------------------------------------------------------

    def _want(self, *names):
        return any(s in self.requested for s in names)

    def merge(self, value):
        """Fold one observation in (Welford update)."""
        value = np.asarray(value)
        self.n += 1
        if self._want("mean", "var", "std"):
            delta = value - self.mu
            self.mu = self.mu + delta / self.n
            if self._want("var", "std"):
                self.m2 = self.m2 + delta * (value - self.mu)
        if self._want("max"):
            self.maxValue = np.maximum(self.maxValue, value)
        if self._want("min"):
            self.minValue = np.minimum(self.minValue, value)
        return self

    def mergeStats(self, other):
        """Combine with another counter (Chan et al. parallel variance)."""
        if not isinstance(other, StatCounter):
            raise TypeError("can only merge another StatCounter")
        if other.n == 0:
            return self
        if self.n == 0:
            self.n = other.n
            self.mu = np.copy(other.mu) if isinstance(other.mu, np.ndarray) else other.mu
            self.m2 = np.copy(other.m2) if isinstance(other.m2, np.ndarray) else other.m2
            self.maxValue = other.maxValue
            self.minValue = other.minValue
            return self
        n = self.n + other.n
        if self._want("mean", "var", "std"):
            delta = np.asarray(other.mu) - np.asarray(self.mu)
            mu = self.mu + delta * (other.n / n)
            if self._want("var", "std"):
                self.m2 = (self.m2 + other.m2
                           + (delta ** 2) * self.n * other.n / n)
            self.mu = mu
        if self._want("max"):
            self.maxValue = np.maximum(self.maxValue, other.maxValue)
        if self._want("min"):
            self.minValue = np.minimum(self.minValue, other.minValue)
        self.n = n
        return self

    @classmethod
    def from_moments(cls, n, mu, m2, minValue=None, maxValue=None,
                     stats="all"):
        """Wrap precomputed moments (the TPU Welford path lands here)."""
        c = cls(stats=stats)
        c.n = int(n)
        c.mu = mu
        c.m2 = m2
        if minValue is not None:
            c.minValue = minValue
        if maxValue is not None:
            c.maxValue = maxValue
        return c

    # ------------------------------------------------------------------

    def count(self):
        return self.n

    def mean(self):
        return self.mu

    def variance(self):
        """Population variance (ddof=0), matching the reference."""
        if self.n == 0:
            return np.nan
        return self.m2 / self.n

    def sampleVariance(self):
        if self.n <= 1:
            return np.nan
        return self.m2 / (self.n - 1)

    def stdev(self):
        return np.sqrt(self.variance())

    def sampleStdev(self):
        return np.sqrt(self.sampleVariance())

    def max(self):
        return self.maxValue

    def min(self):
        return self.minValue

    def __repr__(self):
        return ("(count: %s, mean: %s, stdev: %s, max: %s, min: %s)"
                % (self.n, self.mu, self.stdev(), self.maxValue, self.minValue))
