from bolt_tpu.tpu.array import BoltArrayTPU
from bolt_tpu.tpu.construct import ConstructTPU

__all__ = ["BoltArrayTPU", "ConstructTPU"]
