"""Axis-group-local shape views.

Reference: ``bolt/spark/shapes.py`` — ``Shapes`` (abstract), ``Keys``,
``Values``: shape/reshape/transpose restricted to one axis group, never
crossing the key/value boundary and never shuffling data (symbol-level
citation, SURVEY.md §0).
"""

from bolt_tpu.utils import argpack, isreshapeable, istransposeable


class Shapes:
    """Base for the ``Keys``/``Values`` views over a
    :class:`~bolt_tpu.tpu.array.BoltArrayTPU`."""

    def __init__(self, barray):
        self._barray = barray

    @property
    def shape(self):
        raise NotImplementedError

    def reshape(self, *shape):
        raise NotImplementedError

    def transpose(self, *axes):
        raise NotImplementedError

    def _check_reshape(self, shape):
        if not isreshapeable(shape, self.shape):
            raise ValueError("cannot reshape %s to %s"
                             % (str(self.shape), str(shape)))

    def _check_transpose(self, axes):
        if not istransposeable(axes, range(len(self.shape))):
            raise ValueError("axes %s is not a permutation of %s axes"
                             % (str(axes), len(self.shape)))

    def __repr__(self):
        return "%s: %s" % (type(self).__name__.lower(), str(self.shape))


class Keys(Shapes):
    """View over the key axes (reference: ``bolt/spark/shapes.py :: Keys``).
    Reshaping keys remaps key tuples without touching any value block."""

    @property
    def shape(self):
        b = self._barray
        return b.shape[:b.split]

    def reshape(self, *shape):
        shape = argpack(shape)
        self._check_reshape(shape)
        b = self._barray
        # the view states the boundary explicitly: every new axis is a key
        return b._reshape_with_split(tuple(shape) + b.shape[b.split:],
                                     len(shape))

    def transpose(self, *axes):
        axes = argpack(axes)
        if len(axes) == 0:
            axes = tuple(reversed(range(len(self.shape))))
        self._check_transpose(axes)
        b = self._barray
        perm = tuple(axes) + tuple(range(b.split, b.ndim))
        return b.transpose(*perm)


class Values(Shapes):
    """View over the value axes (reference: ``bolt/spark/shapes.py ::
    Values``).  Reshaping values reshapes every block in place."""

    @property
    def shape(self):
        b = self._barray
        return b.shape[b.split:]

    def reshape(self, *shape):
        shape = argpack(shape)
        self._check_reshape(shape)
        b = self._barray
        # the view states the boundary explicitly: the split is unchanged
        return b._reshape_with_split(b.shape[:b.split] + tuple(shape),
                                     b.split)

    def transpose(self, *axes):
        axes = argpack(axes)
        if len(axes) == 0:
            axes = tuple(reversed(range(len(self.shape))))
        self._check_transpose(axes)
        b = self._barray
        perm = tuple(range(b.split)) + tuple(b.split + a for a in axes)
        return b.transpose(*perm)
