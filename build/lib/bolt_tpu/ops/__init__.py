from bolt_tpu.ops.kernels import (fused_map_reduce, fused_stats,
                                  svdvals, tallskinny_pca)

__all__ = ["fused_map_reduce", "fused_stats", "svdvals", "tallskinny_pca"]
