"""Compatibility shim: ``import bolt`` works against this framework.

Existing reference user code (``import bolt; bolt.array(x, ctx, axis=(0,))``,
``barray.map(f).reduce(add)``) runs unchanged — the BASELINE north-star's
drop-in requirement — with a ``jax.sharding.Mesh`` taking the SparkContext's
place as the distribution context.
"""

from bolt_tpu import *          # noqa: F401,F403
from bolt_tpu import __version__, __all__  # noqa: F401


def __getattr__(name):
    if name.startswith("__"):
        # never forward dunders: forwarding __path__ would make this shim a
        # pseudo-package and `import bolt.checkpoint` would load modules a
        # second time under a different name
        raise AttributeError(name)
    import bolt_tpu
    return getattr(bolt_tpu, name)
