#!/usr/bin/env python
"""Chaos-injection harness for the resumable streaming executor (ISSUE 9).

Proves the two kill-mid-run contracts end to end:

* **thread-raise variant** (in process): ``_chaos.inject`` fires an
  exception inside an uploader at a chosen slab; with ``stream.retries``
  the run survives it in place, and without retries the run dies having
  checkpointed — the re-run resumes from the last retired slab.  Either
  way the result must be BIT-IDENTICAL to the uninterrupted run.
* **subprocess ``kill -9`` variant**: a child process streams the same
  reduction with ``BOLT_CHAOS=stream.upload:<n>:kill`` in its env and is
  SIGKILLed mid-run — no unwinding, no ``finally`` — then a fresh child
  resumes from the surviving checkpoint.  The harness asserts the
  resumed result is bit-identical AND that recovery wall time stays
  under 1.5x the clean run (the resumed child streams only the
  remaining slabs).

The **matrix** mode (ISSUE 12) sweeps EVERY registered fault seam
(``bolt_tpu._chaos.SEAMS``) × {``raise``, ``kill``} and asserts, for
each cell, either *recovery* (the fault is absorbed in place, or a
re-run resumes bit-identically) or a *pointed error* (the fault
surfaces as a named, actionable exception — never a hang, never silent
corruption).  Seam drivers: the stream/checkpoint seams ride the
subprocess streamed workload; the shuffle seams (ISSUE 18:
``stream.shuffle``/``stream.spill``) ride a forced-spill streamed swap
— raise is absorbed in place by the ``stream.retries`` fence, kill -9
mid-spill resumes from the spill manifest bit-identically; the pod
seams (heartbeat, barrier,
supervisor elect/rejoin) ride a fake-peer pod fixture in a child
process; ``multihost.collective`` rides a REAL 2-process localhost
cluster (skipped without the CPU collective transport).  A seam added
to ``SEAMS`` without a driver here fails its cells loudly.

Usage::

    python scripts/chaos_run.py            # run both variants, assert
    python scripts/chaos_run.py --matrix   # the seam x action sweep
    python scripts/chaos_run.py --child .. # internal: one streamed run

``bench_all.py`` config 10 (``stream_resume``) and the ``perf_regress``
``stream_resume`` family reuse :func:`run_resume_bench`.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

# the child's streamed workload: integer-valued f64 so sums are exact
# under ANY fold order — "bit-identical" is then checkable against both
# the clean child run and the NumPy oracle
N_RECORDS = 64
VSHAPE = (16, 8)
CHUNKS = 8                       # -> 8 slabs
PACE_S = 0.02                    # per-slab storage-fetch pacing: keeps
#                                  the checkpoint cadence ahead of the
#                                  kill (and emulates a real loader)


def _data():
    n = N_RECORDS * int(np.prod(VSHAPE))
    return ((np.arange(n) % 13) - 6).astype(np.float64).reshape(
        (N_RECORDS,) + VSHAPE)


def child_main(argv):
    """One streamed run over the canonical workload: the kill target.
    Writes the result array and a JSON sidecar (in-run wall seconds +
    fault counters) — a SIGKILLed child writes neither, which is the
    point.  ``--arm seam:nth:action[,seam:nth:action...]`` arms fault
    points programmatically (the matrix mode's multi-seam cells; the
    single-seam ``BOLT_CHAOS`` env form still works)."""
    import jax
    import bolt_tpu as bolt
    from bolt_tpu import _chaos, engine
    from bolt_tpu.obs.trace import clock

    args = dict(zip(argv[::2], argv[1::2]))
    ck_dir, out = args["--dir"], args["--out"]
    for spec in filter(None, args.get("--arm", "").split(",")):
        seam, nth, action = spec.split(":")
        _chaos.inject(seam, nth=int(nth), action=action)
    # the stream.encode seam only fires with a codec armed; the matrix
    # cell streams the same integer-valued workload as FLOAT32 under
    # the LOSSLESS delta codec, so the oracle compare stays exact
    codec_name = args.get("--codec") or None
    data = _data()
    if codec_name:
        data = data.astype(np.float32)

    def loader(idx):
        time.sleep(PACE_S)
        return data[idx]

    mesh = jax.make_mesh((jax.device_count(),), ("k",))
    src = bolt.fromcallback(loader, data.shape, mesh, dtype=data.dtype,
                            chunks=CHUNKS, checkpoint=ck_dir,
                            codec=codec_name)
    t0 = clock()
    res = np.asarray(src.sum().toarray())
    wall = clock() - t0
    np.save(out, res)
    ec = engine.counters()
    with open(out + ".json", "w") as f:
        json.dump({"wall": wall, "resumes": ec["stream_resumes"],
                   "retries": ec["stream_retries"],
                   "chunks": ec["stream_chunks"],
                   "checkpoint_bytes": ec["checkpoint_bytes"]}, f)
    return 0


def _run_child(ck_dir, out, chaos=None):
    env = dict(os.environ)
    env["BOLT_STREAM_UPLOAD_THREADS"] = "1"   # deterministic watermark
    env.pop("BOLT_CHAOS", None)
    if chaos:
        env["BOLT_CHAOS"] = chaos
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--dir", ck_dir, "--out", out],
        env=env, capture_output=True, text=True, timeout=600)
    return proc


def run_resume_bench(kill_at=6, workdir=None):
    """The subprocess kill -9 proof, packaged for the bench harness:
    clean child run, SIGKILLed child (``BOLT_CHAOS`` arms the kill at
    upload ``kill_at`` of 8), resumed child.  Returns the measurement
    dict; raises on a child that failed for any reason OTHER than the
    intended kill."""
    from bolt_tpu import checkpoint as ckpt
    workdir = workdir or tempfile.mkdtemp(prefix="bolt-chaos-")
    ck_dir = os.path.join(workdir, "ckpt")
    clean_out = os.path.join(workdir, "clean.npy")
    resume_out = os.path.join(workdir, "resumed.npy")

    proc = _run_child(ck_dir, clean_out)
    if proc.returncode != 0:
        raise RuntimeError("clean chaos child failed:\n%s" % proc.stderr)
    with open(clean_out + ".json") as f:
        clean = json.load(f)

    proc = _run_child(ck_dir, resume_out,
                      chaos="stream.upload:%d:kill" % kill_at)
    killed_rc = proc.returncode
    if killed_rc == 0:
        raise RuntimeError("chaos child was supposed to die and did not")
    if not ckpt.stream_pending(ck_dir):
        raise RuntimeError(
            "killed child left no checkpoint (rc=%s):\n%s"
            % (killed_rc, proc.stderr))

    proc = _run_child(ck_dir, resume_out)
    if proc.returncode != 0:
        raise RuntimeError("resume chaos child failed:\n%s" % proc.stderr)
    with open(resume_out + ".json") as f:
        resumed = json.load(f)

    res_clean = np.load(clean_out)
    res_resumed = np.load(resume_out)
    oracle = _data().sum(axis=0)
    return {
        "clean_s": clean["wall"],
        "recovery_s": resumed["wall"],
        "killed_rc": killed_rc,
        "resumes": resumed["resumes"],
        "slabs_resumed": resumed["chunks"],
        "slabs_total": clean["chunks"],
        "identical": bool(np.array_equal(res_clean, res_resumed)
                          and np.array_equal(res_resumed, oracle)),
        "stale_checkpoint": ckpt.stream_pending(ck_dir),
    }


def run_thread_variant():
    """The in-process half: an uploader RAISES mid-run.  Covers both
    policies — retries absorb the fault in one run; without retries the
    failed run checkpoints and the re-run resumes.  Returns the
    measurement dict (all booleans must be True)."""
    import jax
    import bolt_tpu as bolt
    from bolt_tpu import _chaos as chaos, checkpoint as ckpt, engine, stream

    data = _data()
    mesh = jax.make_mesh((jax.device_count(),), ("k",))

    def make(ck=None):
        return bolt.fromcallback(lambda idx: data[idx], data.shape, mesh,
                                 dtype=np.float64, chunks=CHUNKS,
                                 checkpoint=ck)

    clean = np.asarray(make().sum().toarray())

    # retry policy: the fault is absorbed in-run
    chaos.inject("stream.upload", nth=3)
    c0 = engine.counters()
    with stream.retries(1):
        retried = np.asarray(make().sum().toarray())
    c1 = engine.counters()
    chaos.clear()
    retry_ok = (np.array_equal(retried, clean)
                and c1["stream_retries"] - c0["stream_retries"] == 1)

    # checkpoint + resume: the fault kills the run
    ck_dir = tempfile.mkdtemp(prefix="bolt-chaos-thread-")
    chaos.inject("stream.upload", nth=5)
    died = False
    try:
        with stream.uploaders(1):
            make(ck_dir).sum().cache()
    except chaos.ChaosError:
        died = True
    chaos.clear()
    c2 = engine.counters()
    resumed = np.asarray(make(ck_dir).sum().toarray())
    c3 = engine.counters()
    return {
        "retry_ok": retry_ok,
        "died": died,
        "checkpointed": c2["checkpoint_bytes"] > c1["checkpoint_bytes"],
        "resumed": c3["stream_resumes"] - c2["stream_resumes"] == 1,
        "identical": bool(np.array_equal(resumed, clean)),
        "stale_checkpoint": ckpt.stream_pending(ck_dir),
    }


# ---------------------------------------------------------------------
# the seam x action matrix (ISSUE 12)
# ---------------------------------------------------------------------

# where each streamed-workload seam trips (of 8 slabs): late enough
# that a checkpoint exists, early enough that slabs remain to resume
_STREAM_NTH = {"stream.encode": 5, "stream.upload": 5,
               "stream.dispatch": 4, "stream.fold": 1,
               "stream.checkpoint": 3, "checkpoint.meta": 3,
               "checkpoint.corrupt": 3}
# the shuffle seams (ISSUE 18) ride the forced-spill streamed swap:
# stream.shuffle hits once per slab re-bucket dispatch (8 total),
# stream.spill once per bucket write — nth=12 lands INSIDE a later
# slab's bucket writes with at least one slab already fenced in the
# manifest, whatever bucket width the planner picked for the local
# device count
_SHUFFLE_NTH = {"stream.shuffle": 4, "stream.spill": 12}
_POD_NTH = {"podwatch.heartbeat": 3, "multihost.barrier": 1,
            "supervisor.elect": 1, "supervisor.rejoin": 1}


def _run_stream_child(ck_dir, out, arm="", codec=None):
    env = dict(os.environ)
    env["BOLT_STREAM_UPLOAD_THREADS"] = "1"
    env.pop("BOLT_CHAOS", None)
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--dir", ck_dir, "--out", out, "--arm", arm]
    if codec:
        cmd += ["--codec", codec]
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=600)


def pod_child_main(argv):
    """One matrix cell of the POD seams, run in a CHILD process (the
    kill cells SIGKILL it): a fake 2-member pod — file-transport watch
    plus a beating fake peer — drives the seam's recovery scenario and
    asserts the recovery semantics in raise mode.  ``BOLT_MATRIX_ARM``
    arms the seam; the re-run (arm off) proves the clean scenario
    completes after a kill."""
    import threading
    from bolt_tpu import _chaos
    from bolt_tpu.parallel import multihost, podwatch, supervisor

    seam, mode = argv[0], argv[1]
    hb = os.environ["BOLT_MATRIX_HB"]
    armed = os.environ.get("BOLT_MATRIX_ARM") == "1"
    if armed:
        _chaos.inject(seam, nth=_POD_NTH[seam], action=mode)
    assert podwatch.start(2, 0, dir=hb, interval=0.05, timeout=0.5)
    tr = podwatch._WATCH.transport
    stop = threading.Event()

    def beat():
        seq = 0
        while not stop.is_set():
            seq += 1
            tr.beat(1, seq)
            for gen in range(8):
                tr.barrier_mark("chaos_probe", gen, 1)
            stop.wait(0.03)

    th = threading.Thread(target=beat, daemon=True)
    th.start()

    def wait_for(pred, what, timeout=10.0):
        deadline = time.monotonic() + timeout
        while not pred():
            if time.monotonic() > deadline:
                raise AssertionError("%s never happened" % what)
            time.sleep(0.02)

    try:
        if seam == "podwatch.heartbeat":
            # the beat absorbs a raise IN PLACE: peers stay alive and
            # the watch keeps beating (a kill lands before this check)
            wait_for(lambda: (not armed)
                     or _chaos.stats(seam)[0] >= _POD_NTH[seam] + 2,
                     "post-fault heartbeats")
            assert podwatch.dead_peers() == ()
            assert podwatch._WATCH.beat_errors == (1 if armed else 0)
        elif seam == "multihost.barrier":
            orig = multihost.process_count
            multihost.process_count = lambda: 2
            try:
                pointed = False
                try:
                    multihost.barrier("chaos_probe")
                except _chaos.ChaosError:
                    pointed = True     # the POINTED, named fault
                multihost.barrier("chaos_probe")   # the retry lands
                assert pointed == armed
            finally:
                multihost.process_count = orig
        elif seam in ("supervisor.elect", "supervisor.rejoin"):
            calls = []

            def reform(addr, num_processes, process_id=None,
                       epoch=None, init_timeout=None):
                calls.append(int(num_processes))
                podwatch.notify_reform()
                return process_id

            multihost.reform = reform
            sup = supervisor.Supervisor(backoff=0.1)
            try:
                if seam == "supervisor.elect":
                    # a peer death: attempt 1 trips the seam, the
                    # backoff retry completes the reform
                    wait_for(lambda: 1 in podwatch.alive_peers(),
                             "fake peer alive")
                    podwatch.mark_dead(1)
                    wait_for(lambda: sup.stats()["reforms"] == 1,
                             "supervised reform")
                    assert sup.stats()["backoffs"] == \
                        (1 if armed else 0)
                    assert calls == [1]
                else:
                    # a rejoin announcement: the tripped handler DROPS
                    # it (no thrash); the next announcement is honored
                    wait_for(lambda: 1 in podwatch.alive_peers(),
                             "fake peer alive")
                    if armed:
                        podwatch.rejoin("wX")
                        wait_for(lambda: _chaos.stats(seam)[1] == 1,
                                 "rejoin handler trip")
                        time.sleep(0.3)
                        assert sup.stats()["reforms"] == 0
                    podwatch.rejoin("wY")
                    wait_for(lambda: sup.stats()["reforms"] == 1,
                             "reform-up")
                    assert calls[-1] == 3   # i0 + i1 + the rejoiner
            finally:
                sup.close()
    finally:
        stop.set()
        th.join()
        podwatch.stop()
    print("POD-CELL OK", flush=True)
    return 0


def _pod_cell(seam, mode, workdir):
    """Run one pod-seam cell: the armed child (raise: asserts the
    absorb/retry semantics in place; kill: dies AT the seam), then for
    kill cells a clean re-run proving the scenario completes."""
    import shutil
    hb = os.path.join(workdir, "hb-%s-%s" % (seam.replace(".", "_"),
                                             mode))

    def run(arm):
        env = dict(os.environ)
        env.pop("BOLT_CHAOS", None)
        env["BOLT_MATRIX_HB"] = hb
        env["BOLT_MATRIX_ARM"] = "1" if arm else "0"
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--pod-child",
             seam, mode], env=env, capture_output=True, text=True,
            timeout=120)

    os.makedirs(hb, exist_ok=True)
    try:
        proc = run(arm=True)
        if mode == "raise":
            if proc.returncode != 0:
                return ("FAIL", "raise cell rc=%s:\n%s"
                        % (proc.returncode, proc.stderr[-1500:]))
            return ("recovered", "fault absorbed/retried in place")
        if proc.returncode != -9:
            return ("FAIL", "kill cell rc=%s (expected -9):\n%s"
                    % (proc.returncode, proc.stderr[-1500:]))
        shutil.rmtree(hb, ignore_errors=True)
        os.makedirs(hb, exist_ok=True)
        proc = run(arm=False)
        if proc.returncode != 0:
            return ("FAIL", "post-kill re-run rc=%s:\n%s"
                    % (proc.returncode, proc.stderr[-1500:]))
        return ("recovered", "died at the seam; restarted scenario "
                             "completes")
    finally:
        shutil.rmtree(hb, ignore_errors=True)


def _stream_cell(seam, mode, workdir):
    """Run one stream/checkpoint-seam cell through the subprocess
    streamed workload: the armed child dies (or raises out), then a
    re-run must either RESUME bit-identically or refuse POINTEDLY
    (checkpoint.corrupt names the rotted file)."""
    from bolt_tpu import checkpoint as ckpt
    tag = "%s-%s" % (seam.replace(".", "_"), mode)
    ck = os.path.join(workdir, "ck-" + tag)
    out = os.path.join(workdir, "out-" + tag + ".npy")
    nth = _STREAM_NTH[seam]
    arm = "%s:%d:%s" % (seam, nth, mode)
    # the encode seam streams under the lossless codec (the seam never
    # fires uncompressed); resume must re-encode bit-identically
    codec = "delta-f32" if seam == "stream.encode" else None
    if seam == "checkpoint.corrupt" and mode == "raise":
        # the corruption seam's raise form ROTS the just-written state
        # under the atomic rename and lets the run continue — a later
        # kill leaves the rotted checkpoint for the resume to refuse
        arm += ",stream.upload:7:kill"
    proc = _run_stream_child(ck, out, arm=arm, codec=codec)
    if proc.returncode == 0:
        return ("FAIL", "armed child was supposed to die and did not")
    if mode == "kill" or "," in arm:
        if proc.returncode != -9:
            return ("FAIL", "kill child rc=%s (expected -9):\n%s"
                    % (proc.returncode, proc.stderr[-1500:]))
    elif "ChaosError" not in proc.stderr:
        return ("FAIL", "raise child died WITHOUT the pointed "
                        "ChaosError:\n%s" % proc.stderr[-1500:])
    proc = _run_stream_child(ck, out, codec=codec)
    if seam == "checkpoint.corrupt" and mode == "raise":
        # recovery is impossible by design — the contract is the
        # POINTED refusal naming the file, then a clean restart
        if proc.returncode == 0:
            return ("FAIL", "resume accepted a bit-rotted checkpoint")
        if "CheckpointCorruptError" not in proc.stderr \
                or "stream_state" not in proc.stderr:
            return ("FAIL", "corrupt resume died without the pointed "
                            "refusal:\n%s" % proc.stderr[-1500:])
        import shutil
        shutil.rmtree(ck, ignore_errors=True)
        proc = _run_stream_child(ck, out)
        if proc.returncode != 0:
            return ("FAIL", "clean restart after the refusal failed:"
                            "\n%s" % proc.stderr[-1500:])
        return ("pointed", "rotted shard refused by name; clean "
                           "restart recovers")
    if proc.returncode != 0:
        return ("FAIL", "resume child failed:\n%s"
                % proc.stderr[-1500:])
    if not np.array_equal(np.load(out), _data().sum(axis=0)):
        return ("FAIL", "resumed result differs from the oracle")
    if ckpt.stream_pending(ck):
        return ("FAIL", "resumed run left a stale checkpoint")
    return ("recovered", "re-run resumed bit-identically")


def shuffle_child_main(argv):
    """One streamed FORCED-SPILL swap over the canonical workload (the
    shuffle seams' kill target): ``stream.spill(dir, budget=1)`` makes
    every re-keyed bucket spill through the checkpoint slab format, and
    ``stream.retries(1)`` licenses the in-place retry the raise cells
    assert.  Writes the swapped array plus a JSON sidecar of the
    shuffle/spill counters; a SIGKILLed child writes neither — but its
    spill manifest survives, which is the point."""
    import jax
    import bolt_tpu as bolt
    from bolt_tpu import _chaos, checkpoint as ckpt, engine, stream

    args = dict(zip(argv[::2], argv[1::2]))
    spill_dir, out = args["--dir"], args["--out"]
    for spec in filter(None, args.get("--arm", "").split(",")):
        seam, nth, action = spec.split(":")
        _chaos.inject(seam, nth=int(nth), action=action)
    data = _data()

    def loader(idx):
        time.sleep(PACE_S)
        return data[idx]

    mesh = jax.make_mesh((jax.device_count(),), ("k",))
    src = bolt.fromcallback(loader, data.shape, mesh, dtype=data.dtype,
                            chunks=CHUNKS)
    with stream.retries(1), stream.spill(dir=spill_dir, budget=1):
        res = np.asarray(src.swap((0,), (0,))._data)
    np.save(out, res)
    ckpt.spill_clear(spill_dir)
    ec = engine.counters()
    with open(out + ".json", "w") as f:
        json.dump({"retries": ec["stream_retries"],
                   "resumes": ec["stream_resumes"],
                   "spill_bytes": ec["spill_bytes"],
                   "shuffle_bytes": ec["shuffle_bytes"],
                   "stale_spill": ckpt.spill_pending(spill_dir)}, f)
    return 0


def _run_shuffle_child(spill_dir, out, arm=""):
    env = dict(os.environ)
    env["BOLT_STREAM_UPLOAD_THREADS"] = "1"
    env.pop("BOLT_CHAOS", None)
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--shuffle-child",
         "--dir", spill_dir, "--out", out, "--arm", arm],
        env=env, capture_output=True, text=True, timeout=600)


def _shuffle_cell(seam, mode, workdir):
    """One shuffle-seam cell (ISSUE 18): raise is absorbed IN PLACE by
    the armed ``stream.retries`` fence (same-run bit-identity, no
    stale spill); kill -9 mid-spill leaves the fingerprint directory's
    per-slab manifest, and the re-run RESUMES from it — skipping the
    fenced slabs — bit-identically."""
    from bolt_tpu import checkpoint as ckpt
    tag = "%s-%s" % (seam.replace(".", "_"), mode)
    sp = os.path.join(workdir, "spill-" + tag)
    out = os.path.join(workdir, "out-" + tag + ".npy")
    oracle = np.transpose(_data(), (1, 0, 2))
    proc = _run_shuffle_child(
        sp, out, arm="%s:%d:%s" % (seam, _SHUFFLE_NTH[seam], mode))
    if mode == "raise":
        if proc.returncode != 0:
            return ("FAIL", "raise cell rc=%s:\n%s"
                    % (proc.returncode, proc.stderr[-1500:]))
        with open(out + ".json") as f:
            sidecar = json.load(f)
        if sidecar["retries"] < 1:
            return ("FAIL", "fault was not absorbed by a stream retry")
        if not np.array_equal(np.load(out), oracle):
            return ("FAIL", "retried swap differs from the oracle")
        if sidecar["stale_spill"]:
            return ("FAIL", "run left stale spill files after clear")
        return ("recovered", "fault absorbed in place by the "
                             "stream.retries fence")
    if proc.returncode != -9:
        return ("FAIL", "kill child rc=%s (expected -9):\n%s"
                % (proc.returncode, proc.stderr[-1500:]))
    if not ckpt.spill_pending(sp):
        return ("FAIL", "killed child left no spill manifest to resume")
    proc = _run_shuffle_child(sp, out)
    if proc.returncode != 0:
        return ("FAIL", "resume child failed:\n%s" % proc.stderr[-1500:])
    with open(out + ".json") as f:
        sidecar = json.load(f)
    if not np.array_equal(np.load(out), oracle):
        return ("FAIL", "resumed swap differs from the oracle")
    if sidecar["resumes"] < 1:
        return ("FAIL", "re-run did not adopt the spill manifest")
    if sidecar["stale_spill"]:
        return ("FAIL", "resumed run left stale spill files after clear")
    return ("recovered", "killed mid-spill; re-run resumes from the "
                         "spill manifest bit-identically")


def _collective_cell(seam, mode, workdir):
    """multihost.collective rides a REAL 2-process localhost cluster:
    the armed worker dies at a slab dispatch, the harness raises the
    POINTED error naming it, and a restarted cluster RESUMES from the
    shard checkpoint bit-identically."""
    import jax
    if "jax_cpu_collectives_implementation" not in getattr(
            jax.config, "values", {}):
        return ("skipped", "no CPU cross-process collective transport")
    from bolt_tpu.utils import load_script
    mh = load_script("multihost_harness")
    ck = os.path.join(workdir, "ck-coll-" + mode)
    env = {"BOLT_MH_CKPT": ck, "BOLT_CHECKPOINT_EVERY": "1",
           "BOLT_POD_TIMEOUT": "2"}
    try:
        mh.run_cluster("resume", nproc=2, devs=1, timeout=120, env=env,
                       worker_env={1: {"BOLT_CHAOS":
                                       "%s:3:%s" % (seam, mode)}})
        return ("FAIL", "armed cluster was supposed to fail and did "
                        "not")
    except RuntimeError as exc:
        if "process 1 died" not in str(exc):
            return ("FAIL", "cluster failed WITHOUT naming the dead "
                            "process: %s" % exc)
    res, out, _ = mh.run_cluster("resume", nproc=2, devs=1,
                                 timeout=120, env=env)
    if not all(r["resumes"] >= 1 for r in res):
        return ("FAIL", "restarted cluster did not resume: %s" % res)
    return ("pointed", "harness error names the dead process; "
                       "restarted cluster resumes from the shard "
                       "checkpoint")


def run_matrix():
    """Sweep every registered seam x {raise, kill}; assert recovery or
    a pointed error for each cell.  Returns the process exit code."""
    import shutil
    from bolt_tpu import _chaos
    workdir = tempfile.mkdtemp(prefix="bolt-chaos-matrix-")
    cells = []
    try:
        for seam in _chaos.SEAMS:
            for mode in ("raise", "kill"):
                t0 = time.monotonic()
                if seam in _SHUFFLE_NTH:
                    outcome, detail = _shuffle_cell(seam, mode, workdir)
                elif seam in _STREAM_NTH:
                    outcome, detail = _stream_cell(seam, mode, workdir)
                elif seam in _POD_NTH:
                    outcome, detail = _pod_cell(seam, mode, workdir)
                elif seam == "multihost.collective":
                    outcome, detail = _collective_cell(seam, mode,
                                                       workdir)
                else:
                    outcome, detail = (
                        "FAIL", "no matrix driver for this seam — a "
                                "new chaos.hit() site needs a cell "
                                "here")
                cells.append((seam, mode, outcome, detail))
                print("%-22s %-6s %-10s %5.1fs  %s"
                      % (seam, mode, outcome,
                         time.monotonic() - t0, detail.splitlines()[0]),
                      flush=True)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    bad = [c for c in cells if c[2] == "FAIL"]
    print("== matrix: %d cells, %d recovered, %d pointed, %d skipped, "
          "%d FAILED"
          % (len(cells),
             sum(1 for c in cells if c[2] == "recovered"),
             sum(1 for c in cells if c[2] == "pointed"),
             sum(1 for c in cells if c[2] == "skipped"), len(bad)))
    for seam, mode, _, detail in bad:
        print("-- %s x %s:\n%s" % (seam, mode, detail))
    return 1 if bad else 0


def main():
    print("== thread-raise variant (in process)")
    tv = run_thread_variant()
    print("   %s" % json.dumps(tv))
    ok = (tv["retry_ok"] and tv["died"] and tv["checkpointed"]
          and tv["resumed"] and tv["identical"]
          and not tv["stale_checkpoint"])
    print("   -> %s" % ("OK" if ok else "MISMATCH"))

    print("== subprocess kill -9 variant")
    kv = run_resume_bench()
    print("   %s" % json.dumps(kv))
    bounded = kv["recovery_s"] < 1.5 * kv["clean_s"]
    ok2 = (kv["identical"] and kv["resumes"] >= 1
           and kv["slabs_resumed"] < kv["slabs_total"]
           and not kv["stale_checkpoint"] and bounded)
    print("   recovery %.3fs vs clean %.3fs (gate < 1.5x) -> %s"
          % (kv["recovery_s"], kv["clean_s"],
             "OK" if ok2 else "MISMATCH"))
    return 0 if ok and ok2 else 1


if __name__ == "__main__":
    if "--child" in sys.argv:
        sys.exit(child_main(sys.argv[2:]))
    if "--shuffle-child" in sys.argv:
        sys.exit(shuffle_child_main(sys.argv[2:]))
    if "--pod-child" in sys.argv:
        sys.exit(pod_child_main(sys.argv[2:]))
    if "--matrix" in sys.argv:
        sys.exit(run_matrix())
    sys.exit(main())
