#!/usr/bin/env python
"""Chaos-injection harness for the resumable streaming executor (ISSUE 9).

Proves the two kill-mid-run contracts end to end:

* **thread-raise variant** (in process): ``_chaos.inject`` fires an
  exception inside an uploader at a chosen slab; with ``stream.retries``
  the run survives it in place, and without retries the run dies having
  checkpointed — the re-run resumes from the last retired slab.  Either
  way the result must be BIT-IDENTICAL to the uninterrupted run.
* **subprocess ``kill -9`` variant**: a child process streams the same
  reduction with ``BOLT_CHAOS=stream.upload:<n>:kill`` in its env and is
  SIGKILLed mid-run — no unwinding, no ``finally`` — then a fresh child
  resumes from the surviving checkpoint.  The harness asserts the
  resumed result is bit-identical AND that recovery wall time stays
  under 1.5x the clean run (the resumed child streams only the
  remaining slabs).

Usage::

    python scripts/chaos_run.py            # run both variants, assert
    python scripts/chaos_run.py --child .. # internal: one streamed run

``bench_all.py`` config 10 (``stream_resume``) and the ``perf_regress``
``stream_resume`` family reuse :func:`run_resume_bench`.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

# the child's streamed workload: integer-valued f64 so sums are exact
# under ANY fold order — "bit-identical" is then checkable against both
# the clean child run and the NumPy oracle
N_RECORDS = 64
VSHAPE = (16, 8)
CHUNKS = 8                       # -> 8 slabs
PACE_S = 0.02                    # per-slab storage-fetch pacing: keeps
#                                  the checkpoint cadence ahead of the
#                                  kill (and emulates a real loader)


def _data():
    n = N_RECORDS * int(np.prod(VSHAPE))
    return ((np.arange(n) % 13) - 6).astype(np.float64).reshape(
        (N_RECORDS,) + VSHAPE)


def child_main(argv):
    """One streamed run over the canonical workload: the kill target.
    Writes the result array and a JSON sidecar (in-run wall seconds +
    fault counters) — a SIGKILLed child writes neither, which is the
    point."""
    import jax
    import bolt_tpu as bolt
    from bolt_tpu import engine
    from bolt_tpu.obs.trace import clock

    args = dict(zip(argv[::2], argv[1::2]))
    ck_dir, out = args["--dir"], args["--out"]
    data = _data()

    def loader(idx):
        time.sleep(PACE_S)
        return data[idx]

    mesh = jax.make_mesh((jax.device_count(),), ("k",))
    src = bolt.fromcallback(loader, data.shape, mesh, dtype=np.float64,
                            chunks=CHUNKS, checkpoint=ck_dir)
    t0 = clock()
    res = np.asarray(src.sum().toarray())
    wall = clock() - t0
    np.save(out, res)
    ec = engine.counters()
    with open(out + ".json", "w") as f:
        json.dump({"wall": wall, "resumes": ec["stream_resumes"],
                   "retries": ec["stream_retries"],
                   "chunks": ec["stream_chunks"],
                   "checkpoint_bytes": ec["checkpoint_bytes"]}, f)
    return 0


def _run_child(ck_dir, out, chaos=None):
    env = dict(os.environ)
    env["BOLT_STREAM_UPLOAD_THREADS"] = "1"   # deterministic watermark
    env.pop("BOLT_CHAOS", None)
    if chaos:
        env["BOLT_CHAOS"] = chaos
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--dir", ck_dir, "--out", out],
        env=env, capture_output=True, text=True, timeout=600)
    return proc


def run_resume_bench(kill_at=6, workdir=None):
    """The subprocess kill -9 proof, packaged for the bench harness:
    clean child run, SIGKILLed child (``BOLT_CHAOS`` arms the kill at
    upload ``kill_at`` of 8), resumed child.  Returns the measurement
    dict; raises on a child that failed for any reason OTHER than the
    intended kill."""
    from bolt_tpu import checkpoint as ckpt
    workdir = workdir or tempfile.mkdtemp(prefix="bolt-chaos-")
    ck_dir = os.path.join(workdir, "ckpt")
    clean_out = os.path.join(workdir, "clean.npy")
    resume_out = os.path.join(workdir, "resumed.npy")

    proc = _run_child(ck_dir, clean_out)
    if proc.returncode != 0:
        raise RuntimeError("clean chaos child failed:\n%s" % proc.stderr)
    with open(clean_out + ".json") as f:
        clean = json.load(f)

    proc = _run_child(ck_dir, resume_out,
                      chaos="stream.upload:%d:kill" % kill_at)
    killed_rc = proc.returncode
    if killed_rc == 0:
        raise RuntimeError("chaos child was supposed to die and did not")
    if not ckpt.stream_pending(ck_dir):
        raise RuntimeError(
            "killed child left no checkpoint (rc=%s):\n%s"
            % (killed_rc, proc.stderr))

    proc = _run_child(ck_dir, resume_out)
    if proc.returncode != 0:
        raise RuntimeError("resume chaos child failed:\n%s" % proc.stderr)
    with open(resume_out + ".json") as f:
        resumed = json.load(f)

    res_clean = np.load(clean_out)
    res_resumed = np.load(resume_out)
    oracle = _data().sum(axis=0)
    return {
        "clean_s": clean["wall"],
        "recovery_s": resumed["wall"],
        "killed_rc": killed_rc,
        "resumes": resumed["resumes"],
        "slabs_resumed": resumed["chunks"],
        "slabs_total": clean["chunks"],
        "identical": bool(np.array_equal(res_clean, res_resumed)
                          and np.array_equal(res_resumed, oracle)),
        "stale_checkpoint": ckpt.stream_pending(ck_dir),
    }


def run_thread_variant():
    """The in-process half: an uploader RAISES mid-run.  Covers both
    policies — retries absorb the fault in one run; without retries the
    failed run checkpoints and the re-run resumes.  Returns the
    measurement dict (all booleans must be True)."""
    import jax
    import bolt_tpu as bolt
    from bolt_tpu import _chaos as chaos, checkpoint as ckpt, engine, stream

    data = _data()
    mesh = jax.make_mesh((jax.device_count(),), ("k",))

    def make(ck=None):
        return bolt.fromcallback(lambda idx: data[idx], data.shape, mesh,
                                 dtype=np.float64, chunks=CHUNKS,
                                 checkpoint=ck)

    clean = np.asarray(make().sum().toarray())

    # retry policy: the fault is absorbed in-run
    chaos.inject("stream.upload", nth=3)
    c0 = engine.counters()
    with stream.retries(1):
        retried = np.asarray(make().sum().toarray())
    c1 = engine.counters()
    chaos.clear()
    retry_ok = (np.array_equal(retried, clean)
                and c1["stream_retries"] - c0["stream_retries"] == 1)

    # checkpoint + resume: the fault kills the run
    ck_dir = tempfile.mkdtemp(prefix="bolt-chaos-thread-")
    chaos.inject("stream.upload", nth=5)
    died = False
    try:
        with stream.uploaders(1):
            make(ck_dir).sum().cache()
    except chaos.ChaosError:
        died = True
    chaos.clear()
    c2 = engine.counters()
    resumed = np.asarray(make(ck_dir).sum().toarray())
    c3 = engine.counters()
    return {
        "retry_ok": retry_ok,
        "died": died,
        "checkpointed": c2["checkpoint_bytes"] > c1["checkpoint_bytes"],
        "resumed": c3["stream_resumes"] - c2["stream_resumes"] == 1,
        "identical": bool(np.array_equal(resumed, clean)),
        "stale_checkpoint": ckpt.stream_pending(ck_dir),
    }


def main():
    print("== thread-raise variant (in process)")
    tv = run_thread_variant()
    print("   %s" % json.dumps(tv))
    ok = (tv["retry_ok"] and tv["died"] and tv["checkpointed"]
          and tv["resumed"] and tv["identical"]
          and not tv["stale_checkpoint"])
    print("   -> %s" % ("OK" if ok else "MISMATCH"))

    print("== subprocess kill -9 variant")
    kv = run_resume_bench()
    print("   %s" % json.dumps(kv))
    bounded = kv["recovery_s"] < 1.5 * kv["clean_s"]
    ok2 = (kv["identical"] and kv["resumes"] >= 1
           and kv["slabs_resumed"] < kv["slabs_total"]
           and not kv["stale_checkpoint"] and bounded)
    print("   recovery %.3fs vs clean %.3fs (gate < 1.5x) -> %s"
          % (kv["recovery_s"], kv["clean_s"],
             "OK" if ok2 else "MISMATCH"))
    return 0 if ok and ok2 else 1


if __name__ == "__main__":
    if "--child" in sys.argv:
        sys.exit(child_main(sys.argv[2:]))
    sys.exit(main())
