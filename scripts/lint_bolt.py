#!/usr/bin/env python
"""Repo invariant linter CLI — the ``BLT1xx`` AST rules of
``bolt_tpu/analysis/astlint.py`` plus the concurrency pass of
``bolt_tpu/analysis/concurrency.py`` as a fast standalone gate.

::

    python scripts/lint_bolt.py               # both passes over bolt_tpu/
    python scripts/lint_bolt.py --check       # same, exit 1 on findings
                                              # OR stale pragmas
    python scripts/lint_bolt.py --concurrency # lock-hierarchy pass only
    python scripts/lint_bolt.py --codes       # merged rule table
    python scripts/lint_bolt.py PATH...       # lint specific files/dirs

Runs in milliseconds with NO jax import: both lint modules are
stdlib-only and are loaded straight from their files, skipping the
``bolt_tpu`` package initialisation (which would pull in jax).  The
same rules run in tier-1 as ``pytest -m lint``
(``tests/test_static_analysis.py`` asserts zero findings on the
package).

``--check`` additionally audits every ``# lint: allow(...)`` pragma in
the linted set: a pragma naming an unknown code, or one that no longer
suppresses any finding (the code it excused was fixed or moved), fails
the gate — suppressions must never outlive what they suppress.
"""

import argparse
import importlib.util
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a well-formed diagnostic code; docstrings DESCRIBING the pragma
# syntax ("allow(BLT1xx <reason>)", the parser's own source) parse as
# pseudo-codes and are not audited
_CODE_RE = re.compile(r"^BLT\d{3}$")


def _load(modname, relpath):
    """Load a lint module by path (no ``import bolt_tpu`` — that would
    initialise jax; this gate must stay no-jit and instant)."""
    mod = sys.modules.get(modname)
    if mod is not None:
        return mod
    path = os.path.join(_REPO, "bolt_tpu", "analysis", relpath)
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    spec.loader.exec_module(mod)
    return mod


def _iter_files(paths, astlint):
    for p in paths:
        if os.path.isdir(p):
            for f in astlint.iter_py_files(p):
                yield f
        else:
            yield p


def stale_pragmas(paths, astlint, conc):
    """Audit ``lint: allow`` pragmas: re-lint each pragma-bearing file
    with the pragmas disarmed and require every pragma to (a) name a
    known code and (b) actually suppress a finding on its line."""
    msgs = []
    passes = (astlint,) if conc is None else (astlint, conc)
    for path in _iter_files(paths, astlint):
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        pragmas = astlint._pragma_lines(src)
        if not pragmas:
            continue
        # disarm (same line count, so finding lines stay comparable)
        neutered = src.replace("lint: allow(", "lint: off(")
        hits = set()
        for mod in passes:
            try:
                for f in mod.lint_source(neutered, path):
                    hits.add((f.line, f.code))
            except SyntaxError:
                pass
        for line, code in sorted(pragmas.items()):
            if not _CODE_RE.match(code):
                continue
            if code not in astlint.RULES:
                msgs.append("%s:%d: stale pragma: unknown code %r"
                            % (path, line, code))
            elif (line, code) not in hits:
                msgs.append("%s:%d: stale pragma: allow(%s) no longer "
                            "suppresses any finding — remove it"
                            % (path, line, code))
    return msgs


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="AST linter for the bolt_tpu repo invariants "
                    "(BLT1xx)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the "
                         "bolt_tpu package)")
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero when any finding OR stale "
                         "pragma is reported (the CI/tier-1 gate mode)")
    ap.add_argument("--codes", action="store_true",
                    help="print the merged rule table and exit")
    ap.add_argument("--concurrency", action="store_true",
                    help="run only the lock-hierarchy pass "
                         "(BLT111-BLT114)")
    args = ap.parse_args(argv)

    astlint = _load("bolt_astlint", "astlint.py")
    conc = _load("bolt_concurrency", "concurrency.py")
    if args.codes:
        for code in sorted(astlint.RULES):
            print("%s  %s" % (code, astlint.RULES[code]))
        return 0

    paths = args.paths or [os.path.join(_REPO, "bolt_tpu")]
    if args.concurrency:
        findings = conc.lint_paths(paths)
    else:
        findings = astlint.lint_paths(paths) + conc.lint_paths(paths)
        findings.sort(key=lambda f: (f.path, f.line, f.col))
    for f in findings:
        print(f.render())
    n = len(findings)
    print("%d finding(s) over %s" % (n, ", ".join(paths)))
    stale = []
    if args.check:
        stale = stale_pragmas(paths, astlint,
                              conc if not args.concurrency else conc)
        for msg in stale:
            print(msg)
        if stale:
            print("%d stale pragma(s)" % len(stale))
    if args.check and (n or stale):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
