#!/usr/bin/env python
"""Repo invariant linter CLI — the ``BLT1xx`` AST rules of
``bolt_tpu/analysis/astlint.py`` as a fast standalone gate.

::

    python scripts/lint_bolt.py             # lint bolt_tpu/, print findings
    python scripts/lint_bolt.py --check     # same, exit 1 on any finding
    python scripts/lint_bolt.py --codes     # print the rule table
    python scripts/lint_bolt.py PATH...     # lint specific files/dirs

Runs in milliseconds with NO jax import: ``astlint`` is stdlib-only and
is loaded straight from its file, skipping the ``bolt_tpu`` package
initialisation (which would pull in jax).  The same rules run in tier-1
as ``pytest -m lint`` (``tests/test_static_analysis.py`` asserts zero
findings on the package).
"""

import argparse
import importlib.util
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_astlint():
    """Load astlint by path (no ``import bolt_tpu`` — that would
    initialise jax; this gate must stay no-jit and instant)."""
    path = os.path.join(_REPO, "bolt_tpu", "analysis", "astlint.py")
    spec = importlib.util.spec_from_file_location("bolt_astlint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="AST linter for the bolt_tpu repo invariants "
                    "(BLT1xx)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the "
                         "bolt_tpu package)")
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero when any finding is reported "
                         "(the CI/tier-1 gate mode)")
    ap.add_argument("--codes", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    astlint = _load_astlint()
    if args.codes:
        for code in sorted(astlint.RULES):
            print("%s  %s" % (code, astlint.RULES[code]))
        return 0

    paths = args.paths or [os.path.join(_REPO, "bolt_tpu")]
    findings = astlint.lint_paths(paths)
    for f in findings:
        print(f.render())
    n = len(findings)
    print("%d finding(s) over %s" % (n, ", ".join(paths)))
    if args.check and n:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
