#!/usr/bin/env python
"""Per-op-family perf-regression harness (round 2, VERDICT r1 next-2).

Measures steady-state device throughput for each core op family at
device-dominated sizes (every config ≥ ~0.9 GB, so the ~3 ms dispatch
floor of this environment's remote attach is <10% of any timing), prints
one JSON line per family, writes ``PERF.json``, and — when a committed
``PERF_BASELINE.json`` exists — reports any family slower than baseline
by more than ``THRESHOLD`` (exit code 2, so CI can warn without
conflating regressions with failures).

BASELINE CONVENTION: the committed baseline records a conservative
LOW-WATER mark per family — the worst throughput observed across
healthy measurement windows — because this environment's attach-window
variance spans 2-4× on some families (swap measured 148-655 GB/s in one
day with identical code).  The gate therefore fires on genuine
collapses, not on drawing an unlucky window against a lucky baseline.
A plain ``--rebaseline`` records the CURRENT window; hand-adjust toward
the low-water mark after collecting a few runs.

Usage::

    python scripts/perf_regress.py              # measure + compare
    python scripts/perf_regress.py --rebaseline # overwrite the baseline
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import bolt_tpu as bolt  # noqa: E402

THRESHOLD = 0.25   # fractional slowdown vs baseline that counts as a regression
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "PERF.json")
BASE = os.path.join(ROOT, "PERF_BASELINE.json")


# ONE timing harness: bench_all's pipelined steady-state methodology
# (closing-probe round-trip measured and subtracted; keep_all=False frees
# the warm result and in-flight handles for multi-GB outputs)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from bench_all import timed_tpu  # noqa: E402


def steady(launch, iters=6, keep_all=True):
    _, sec = timed_tpu(launch, iters=iters, keep_all=keep_all)
    return sec


# Every family generates its data ON DEVICE (bolt.randn/ones): shipping a
# 2 GB host array through this environment's ~17 MB/s attach tunnel would
# take ~2 minutes and measure the tunnel.  ``bytes`` is the logical input
# size — the GB/s figures are per-pass-over-the-input throughput,
# comparable across rounds, not absolute HBM traffic.

MAPSUM_FN = lambda v: v + 1
FILTER_PRED = lambda v: v.mean() > 0


def fam_map_sum():
    shape = (8192, 256, 256)                      # 2.1 GB f32
    b = bolt.ones(shape, mode="tpu", dtype=np.float32).cache()
    return int(np.prod(shape)) * 4, steady(
        lambda: b.map(MAPSUM_FN).sum(axis=(0, 1, 2)))


def fam_stats_welford():
    # the shard_map Welford (pallas fused_welford engages — 128-aligned
    # minor dim); times the compiled program via the executable cache,
    # with the same probe-roundtrip subtraction as every other family
    # (folding the ~65 ms tunnel sync into /iters would mostly measure
    # the attach link)
    from bolt_tpu.tpu.array import _JIT_CACHE
    shape = (8192, 256, 256)
    nbytes = int(np.prod(shape)) * 4
    b = bolt.ones(shape, mode="tpu", dtype=np.float32).cache()
    b.stats()
    prog = next(v for k, v in _JIT_CACHE.items() if k[0] == "welford")
    data = b._data
    probe = jax.jit(lambda t: t[0].ravel()[0])
    warm = prog(data)
    jax.device_get(probe(warm))
    rts = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(probe(warm))
        rts.append(time.perf_counter() - t0)
    rt = min(rts)
    iters = 6
    t0 = time.perf_counter()
    for _ in range(iters):
        r = prog(data)
    jax.device_get(probe(r))
    return nbytes, (time.perf_counter() - t0 - rt) / iters


def fam_swap():
    shape = (1024, 128, 64, 64)                   # 2.1 GB
    b = bolt.randn(shape, mode="tpu", axis=(0, 1), seed=3,
                   dtype=np.float32).cache()
    return int(np.prod(shape)) * 4, steady(
        lambda: b.swap((0,), (0,)), iters=5, keep_all=False)


def fam_filter_fused():
    shape = (14336, 256, 64)                      # 0.94 GB
    b = bolt.randn(shape, mode="tpu", seed=4, dtype=np.float32).cache()
    return int(np.prod(shape)) * 4, steady(
        lambda: b.filter(FILTER_PRED), iters=5)


def fam_matmul():
    # the MXU path (highest precision, numpy-parity default); the weight
    # is device-resident — a host ndarray operand would re-upload per call
    n = 8192                                      # 0.8 GB of operands
    w = bolt.randn((n, n), mode="tpu", seed=8, dtype=np.float32).tojax()
    b = bolt.randn((n, n), mode="tpu", seed=7, dtype=np.float32).cache()
    return 2 * n * n * 4, steady(
        lambda: b @ w, iters=5, keep_all=False)


def fam_halo_gaussian():
    from bolt_tpu.ops import gaussian
    shape = (64, 2048, 4096)                      # 2.1 GB
    b = bolt.randn(shape, mode="tpu", seed=6, dtype=np.float32).cache()
    return int(np.prod(shape)) * 4, steady(
        lambda: gaussian(b, sigma=2.0, axis=(0, 1), size="64"),
        iters=4, keep_all=False)


def fam_segment_reduce():
    from bolt_tpu.ops import segment_reduce
    # few records x big blocks: the public API uploads labels per call,
    # so the label vector is kept tiny (32 KB) — a 131072-label variant
    # measured the tunnel (~30 of 39 ms/iter), not the scatter combine
    shape = (8192, 1024, 64)                      # 2.1 GB
    b = bolt.randn(shape, mode="tpu", seed=9, dtype=np.float32).cache()
    labels = np.arange(shape[0]) % 256

    return int(np.prod(shape)) * 4, steady(
        lambda: segment_reduce(b, labels, num_segments=256, op="sum"),
        iters=5)


def fam_pca():
    from bolt_tpu.ops import pca
    b = bolt.randn((33554432, 16), mode="tpu", seed=5).cache()  # 2.1 GB

    def run_pca():
        scores, comps, svals = pca(b, k=4, center=True)
        return scores
    return 33554432 * 16 * 4, steady(run_pca, iters=3, keep_all=False)


FAMILIES = [
    ("map_sum", fam_map_sum),
    ("stats_welford", fam_stats_welford),
    ("swap", fam_swap),
    ("filter_fused", fam_filter_fused),
    ("matmul", fam_matmul),
    ("halo_gaussian", fam_halo_gaussian),
    ("segment_reduce", fam_segment_reduce),
    ("pca", fam_pca),
]


def main():
    rebase = "--rebaseline" in sys.argv
    only = None
    for arg in sys.argv[1:]:
        if arg.startswith("--only="):
            only = set(arg.split("=", 1)[1].split(","))
    # start from the committed baseline plus any previous partial
    # measurement (fresher wins), so a run cut short by a wall-clock
    # budget (remote-attach variance is 2-10x) resumes instead of losing
    # everything, and `--rebaseline --only=fam` never wipes the other
    # families' baselines; results are flushed after EVERY family
    results = {}
    for path in (BASE, OUT):
        if os.path.exists(path):
            with open(path) as f:
                results.update(json.load(f))
    failed = []
    for name, fam in FAMILIES:
        if only is not None and name not in only:
            continue
        try:
            nbytes, sec = fam()
        except Exception as e:   # one broken family must not lose the rest
            print("family %s FAILED: %s" % (name, e), file=sys.stderr)
            failed.append(name)
            # purge any stale number: a broken family must not regression-
            # gate on data from a previous run
            results.pop(name, None)
            continue
        gbps = nbytes / sec / 1e9
        results[name] = {"s_per_iter": round(sec, 5), "bytes": nbytes,
                         "gbps": round(gbps, 1)}
        print(json.dumps({"family": name, **results[name]}), flush=True)
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1, sort_keys=True)

    if rebase or not os.path.exists(BASE):
        with open(BASE, "w") as f:
            json.dump(results, f, indent=1, sort_keys=True)
        print("baseline written to", BASE, file=sys.stderr)
        return 2 if failed else 0

    with open(BASE) as f:
        base = json.load(f)
    regressed = []
    for name, r in results.items():
        b = base.get(name)
        if b and r["gbps"] < b["gbps"] * (1 - THRESHOLD):
            regressed.append((name, b["gbps"], r["gbps"]))
    for name, was, now in regressed:
        print("REGRESSION %s: %.1f -> %.1f GB/s" % (name, was, now),
              file=sys.stderr)
    bad = bool(regressed or failed)
    print("perf_regress:", "FAIL" if bad else "OK", file=sys.stderr)
    return 2 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
