#!/usr/bin/env python
"""Per-op-family perf-regression harness (round 2, VERDICT r1 next-2).

Measures steady-state device throughput for each core op family at
device-dominated sizes (every config ≥ ~0.9 GB, so the ~3 ms dispatch
floor of this environment's remote attach is <10% of any timing), prints
one JSON line per family, writes ``PERF.json``, and — when a committed
``PERF_BASELINE.json`` exists — reports any family slower than baseline
by more than ``THRESHOLD`` (exit code 2, so CI can warn without
conflating regressions with failures).

BASELINE CONVENTION: the committed baseline records a conservative
LOW-WATER mark per family — the worst throughput observed across
healthy measurement windows — because this environment's attach-window
variance spans 2-4× on some families (swap measured 148-655 GB/s in one
day with identical code).  The gate therefore fires on genuine
collapses, not on drawing an unlucky window against a lucky baseline.
A plain ``--rebaseline`` records the CURRENT window; hand-adjust toward
the low-water mark after collecting a few runs.

Usage::

    python scripts/perf_regress.py              # measure + compare
    python scripts/perf_regress.py --rebaseline # overwrite the baseline
    python scripts/perf_regress.py --trace out.json  # + obs timeline:
        # Chrome trace-event export of the whole run, and each family's
        # PERF.json entry gains a span-derived "phases" breakdown
    python scripts/perf_regress.py --families=a,b    # measure a subset:
        # comma list of family names; a token "platform:cpu" expands to
        # every committed family whose last entry was measured on that
        # backend — so a real-chip window re-measures exactly the
        # container-tagged families without a full sweep
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import bolt_tpu as bolt  # noqa: E402

THRESHOLD = 0.25   # fractional slowdown vs baseline that counts as a regression
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "PERF.json")
BASE = os.path.join(ROOT, "PERF_BASELINE.json")

# Chip resource peaks (v5e, per chip), for the %-of-peak accounting
# (VERDICT r3 next-1: GB/s is the wrong axis for MXU-bound families).
# HBM: 819 GB/s from the v5e spec (16 GB HBM2E); the best one-pass
# number this framework has measured on this chip is 616 GB/s (~75%),
# so treat ~0.75 as the practical per-op ceiling when reading pct_hbm.
# MXU: 197 bf16 TFLOP/s.  f32 matmuls run on the MXU as bf16-pass
# decompositions: precision="default" 1 pass, "high" 3 (error ~f32),
# "highest" 6 (ulp-level) -> the f32-highest peak is 197/6 = 32.8.
HBM_PEAK_GBPS = 819.0
MXU_PEAK_TFLOPS = {"bf16": 197.0, "f32_high": 197.0 / 3, "f32_highest": 197.0 / 6}


# TIMING (reworked round 3, VERDICT r2 #7): this environment's attach
# tunnel has a LARGE, NOISY fetch/dispatch latency (measured 28-110 ms
# for one host round-trip, varying minute to minute).  The round-2
# methodology — few iterations plus a measured-and-subtracted probe
# round-trip — left a residual of tens of ms whenever the round-trip
# drifted between its measurement and its use, which silently turned
# sub-5 GB families into LATENCY measurements: map_sum read 99.9 GB/s
# and filter 31 GB/s while the same programs measure 366 / ~110 GB/s
# with the fetch amortized (a bare 2-pass COPY "measured" 30 GB/s under
# the old scheme — the smoking gun).  Two fetch-proof forms replace it:
#
# * ``steady_amortized`` — queue many independent launches, ONE closing
#   fetch; bias <= round-trip/iters (~2.3 ms at the default 48; the
#   pca family accepts ~14 ms at iters=8 against its 0.23 s/iter
#   signal).  For families whose outputs are small (reductions) so
#   queued results can't fill HBM.
# * ``steady_chain`` — each launch consumes the previous result, so at
#   most two buffers are ever alive regardless of queue depth; same
#   single amortized fetch.  For families with input-sized outputs
#   (swap, matmul, halo, filter-via-padded-buffer).

_PROBE = jax.jit(lambda t: t.ravel()[0])


def _tiny(r):
    """Reduce a family result to a one-scalar fetch (families return a
    bolt array, a jax array, or a tuple whose head is one)."""
    if isinstance(r, tuple):
        r = r[0]
    return _PROBE(r.tojax() if hasattr(r, "tojax") else r)


def steady_amortized(launch, iters=48):
    jax.device_get(_tiny(launch()))          # compile + drain
    t0 = time.perf_counter()
    for _ in range(iters):
        r = launch()
    jax.device_get(_tiny(r))
    return (time.perf_counter() - t0) / iters


def steady_chain(x0, step, iters=24, warm=4):
    x = x0
    for _ in range(warm):                    # compile the cycle's programs
        x = step(x)
    jax.device_get(_tiny(x))                 # drain
    t0 = time.perf_counter()
    for _ in range(iters):
        x = step(x)
    jax.device_get(_tiny(x))
    return (time.perf_counter() - t0) / iters


# Every family generates its data ON DEVICE (bolt.randn/ones): shipping a
# 2 GB host array through this environment's ~17 MB/s attach tunnel would
# take ~2 minutes and measure the tunnel.  ``bytes`` is the logical input
# size — the GB/s figures are per-pass-over-the-input throughput,
# comparable across rounds, not absolute HBM traffic.

MAPSUM_FN = lambda v: v + 1
FILTER_PRED = lambda v: v.mean() > 0


def fam_map_sum():
    shape = (8192, 256, 256)                      # 2.1 GB f32
    b = bolt.ones(shape, mode="tpu", dtype=np.float32).cache()
    # .cache() forces the LAZY stat terminal to dispatch (async) so
    # every queued launch really runs — stat results are pending
    # fused-group handles since the bolt.compute layer
    return int(np.prod(shape)) * 4, steady_amortized(
        lambda: b.map(MAPSUM_FN).sum(axis=(0, 1, 2)).cache()), {
        "bound": "hbm",
        "traffic": (1.0, "one fused read pass; output is a scalar")}


def fam_stats_welford():
    # the shard_map Welford (pallas fused_welford engages — 128-aligned
    # minor dim); times the compiled program via the executable cache,
    # with the same probe-roundtrip subtraction as every other family
    # (folding the ~65 ms tunnel sync into /iters would mostly measure
    # the attach link)
    from bolt_tpu.tpu.array import _JIT_CACHE
    shape = (8192, 256, 256)
    nbytes = int(np.prod(shape)) * 4
    b = bolt.ones(shape, mode="tpu", dtype=np.float32).cache()
    b.stats()
    prog = next(v for k, v in _JIT_CACHE.items() if k[0] == "welford")
    data = b._data
    return nbytes, steady_amortized(lambda: prog(data)), {
        "bound": "hbm",
        "traffic": (1.0, "one fused pallas read pass; moments are tiny")}


def fam_swap():
    shape = (1024, 128, 64, 64)                   # 2.1 GB
    b = bolt.randn(shape, mode="tpu", axis=(0, 1), seed=3,
                   dtype=np.float32).cache()
    # NOT a chain: chained swaps rotate through arrangements whose
    # transposes cost wildly different amounts (some move the minor
    # dim), which would measure a layout mix instead of THE exchange.
    # Amortized queueing is safe — the runtime keeps ~2 executions in
    # flight, so 2.1 GB outputs never stack (measured: no OOM at 48).
    return int(np.prod(shape)) * 4, steady_amortized(
        lambda: b.swap((0,), (0,)), iters=48), {
        "bound": "hbm",
        "traffic": (2.0, "read + transposed write per byte (single "
                         "chip; a mesh's all_to_all exchange rides on "
                         "top)")}


def fam_filter_fused():
    from bolt_tpu.tpu.array import BoltArrayTPU
    shape = (14336, 256, 64)                      # 0.94 GB
    b = bolt.randn(shape, mode="tpu", seed=4, dtype=np.float32).cache()

    def step(arr):
        # the padded compaction buffer has the input's shape, so the
        # chain feeds each filter the previous one's buffer (garbage
        # rows are data like any other) — one cached program throughout.
        # filter() now defers; _resolve_fpending dispatches the
        # compaction program without syncing the count
        out = arr.filter(FILTER_PRED)
        out._resolve_fpending()
        return BoltArrayTPU(out._pending[0], 1, arr.mesh)

    return int(np.prod(shape)) * 4, steady_chain(b, step, iters=24), {
        "bound": "hbm",
        "traffic": (3.0, "materialising filter: mask + count + compact "
                         "= ~3 passes over the input (round-3 measured "
                         "~330 GB/s real traffic); reduction terminals "
                         "take the 1-pass filter_sum_fused path instead")}


def fam_filter_sum_fused():
    # the ISSUE-1 fused terminal: filter(...).sum() folds the predicate
    # mask into the reduction combine — ONE pass over the input, no
    # compaction buffer ever materialises (engine.py + _fused_filter_stat)
    shape = (14336, 256, 64)                      # 0.94 GB
    b = bolt.randn(shape, mode="tpu", seed=4, dtype=np.float32).cache()
    return int(np.prod(shape)) * 4, steady_amortized(
        lambda: b.filter(FILTER_PRED).sum().cache(), iters=32), {
        "bound": "hbm",
        "traffic": (1.0, "single fused mask+reduce pass; the (256, 64) "
                         "output is ~0.003% of the input")}


def fam_matmul():
    # the MXU path (highest precision, numpy-parity default); the weight
    # is device-resident — a host ndarray operand would re-upload per call
    n = 8192                                      # 0.8 GB of operands
    # x @ w keeps the shape: chain the product through itself; w is
    # scaled so the chain's magnitude stays ~O(1) per link (a randn
    # product grows ~sqrt(n)x per matmul — 16 links would reach f32 inf)
    w = bolt.randn((n, n), mode="tpu", seed=8, dtype=np.float32).tojax() \
        * np.float32(1.0 / np.sqrt(n))
    b = bolt.randn((n, n), mode="tpu", seed=7, dtype=np.float32).cache()
    sec = steady_chain(b, lambda x: x @ w, iters=16)
    return 2 * n * n * 4, sec, {"bound": "mxu", "flops": 2 * n ** 3,
                                "precision": "f32_highest"}


def fam_matmul_bf16():
    # the MXU's native mode: bf16 operands, precision="default" (one
    # MXU pass — dot(precision=) is the public opt-in, tpu/array.py).
    # This is the family that can approach the chip's 197 TFLOP/s.
    n = 8192
    w = (bolt.randn((n, n), mode="tpu", seed=8, dtype=np.float32).tojax()
         * np.float32(1.0 / np.sqrt(n))).astype(jnp.bfloat16)
    b = bolt.randn((n, n), mode="tpu", seed=7,
                   dtype=np.float32).astype(jnp.bfloat16).cache()
    sec = steady_chain(b, lambda x: x.dot(w, precision="default"), iters=24)
    return 2 * n * n * 2, sec, {"bound": "mxu", "flops": 2 * n ** 3,
                                "precision": "bf16"}


def fam_halo_gaussian():
    from bolt_tpu.ops import gaussian
    shape = (64, 2048, 4096)                      # 2.1 GB
    b = bolt.randn(shape, mode="tpu", seed=6, dtype=np.float32).cache()
    return int(np.prod(shape)) * 4, steady_chain(
        b, lambda x: gaussian(x, sigma=2.0, axis=(0, 1), size="64"),
        iters=12), {
        "bound": "hbm",
        "traffic": (4.0, "two per-axis kernel passes (sublane window + "
                         "lane band matmul), each read + write")}


def fam_segment_reduce():
    from bolt_tpu.ops import segment_reduce
    # few records x big blocks: the public API uploads labels per call,
    # so the label vector is kept tiny (32 KB) — a 131072-label variant
    # measured the tunnel (~30 of 39 ms/iter), not the scatter combine
    shape = (8192, 1024, 64)                      # 2.1 GB
    b = bolt.randn(shape, mode="tpu", seed=9, dtype=np.float32).cache()
    labels = np.arange(shape[0]) % 256

    return int(np.prod(shape)) * 4, steady_amortized(
        lambda: segment_reduce(b, labels, num_segments=256, op="sum"),
        iters=32), {
        "bound": "hbm",
        "traffic": (1.0, "one matmul read pass (one-hot path); the "
                         "(256, V) output is ~3% of the input")}


def fam_pca():
    from bolt_tpu.ops import pca
    b = bolt.randn((33554432, 16), mode="tpu", seed=5).cache()  # 2.1 GB

    def run_pca():
        # fetch=False: the async path — the default's batched host fetch
        # of comps/svals is ONE tunnel round-trip per call, which on this
        # attach would dominate the measurement (~0.1 s vs the program's
        # tens of ms); the family gates the compiled program
        scores, comps, svals = pca(b, k=4, center=True, fetch=False)
        return svals            # scores stay sharded in HBM; probe the
                                # small vector so queued iterations don't
                                # stack score buffers
    n, d, k = 33554432, 16, 4
    sec = steady_amortized(run_pca, iters=8)
    # Gram 2nd^2 + projection 2ndk (+ the d x d eigh, negligible):
    # arithmetic intensity (d + k)/4 ~ 5 flops/byte << the chip's ~240
    # flops/byte balance point -> HBM-bound by design (the Gram route's
    # whole point is one pass over the data)
    return n * d * 4, sec, {"bound": "hbm",
                            "flops": 2 * n * d * d + 2 * n * d * k,
                            "precision": "f32_highest",
                            "traffic": (3.0, "mean + Gram + projection "
                                             "each read the input once "
                                             "(center=True)")}


def fam_svdvals():
    from bolt_tpu.ops import svdvals
    # batched tall-skinny Gram route (BASELINE config 5b's per-chunk SVD
    # shape): d=64 is the largest dim the jacobi router accepts, batch 64
    # puts it on the jacobi path; intensity d/2 = 32 flops/byte -> still
    # HBM-bound (balance point ~240), reported as such
    batch, n, d = 64, 131072, 64                  # 2.1 GB f32
    x = bolt.randn((batch, n, d), mode="tpu", seed=12,
                   dtype=np.float32).tojax()
    fn = jax.jit(svdvals)
    jax.block_until_ready(fn(x))
    sec = steady_amortized(lambda: fn(x), iters=24)
    return batch * n * d * 4, sec, {"bound": "hbm",
                                    "flops": 2 * batch * n * d * d,
                                    "precision": "f32_highest",
                                    "traffic": (1.0, "one Gram read "
                                                     "pass")}


def fam_jacobi_eigh():
    from bolt_tpu.ops.linalg import jacobi_eigh
    # the batched small-matrix eigensolver (the PCA family's (d, d)
    # kernel, stress-shaped: many matrices).  Neither HBM- nor MXU-bound:
    # the sweep chain is a fixed-length sequential scan of gather +
    # elementwise rounds — its wall clock is round-count x per-round
    # latency, so the family gates regressions in the schedule/rotation
    # formulation, not a bandwidth number.
    batch, n = 16384, 16                          # 67 MB of matrices
    g = bolt.randn((batch, n, n), mode="tpu", seed=13,
                   dtype=np.float32).tojax()
    g = g + jnp.swapaxes(g, -1, -2)               # symmetric
    fn = jax.jit(jacobi_eigh)
    jax.block_until_ready(fn(g))
    sec = steady_amortized(lambda: fn(g), iters=24)
    # ~12 B m^2 flops per rotation round x sweeps*(m-1) rounds (+trig);
    # the sweep count comes from the solver's own default so a retune
    # there keeps this estimate honest
    from bolt_tpu.ops.linalg import _default_sweeps
    sweeps = _default_sweeps(n, jnp.float32)
    flops = sweeps * (n - 1) * 12 * batch * n * n
    return batch * n * n * 4, sec, {"bound": "latency", "flops": flops,
                                    "precision": "f32"}


def fam_stream_sum():
    # the streaming out-of-core executor, ISSUE-5 form: host-resident
    # data streamed through the N-way UPLOADER POOL (workers produce and
    # upload slabs concurrently as per-device sub-blocks, a re-sequencer
    # keeps the fold in slab order), slab programs dispatched ASYNC into
    # the bounded in-flight window with the level-0 fold fused in (slab
    # buffers donated, the ring recycles).  This family gauges the
    # host->device INGEST link with compute overlapped — transfer-bound
    # by design, so regressions here mean the pipeline stopped hiding
    # the upload (the chip-side program itself is fam_map_sum's).  The
    # s_per_iter is one full streamed pass, not a queued steady-state
    # launch: a streamed run syncs once, on its final result.
    from bolt_tpu import stream as _stream
    shape = (4096, 256, 64)                       # 0.27 GB over the link
    x = (np.arange(np.prod(shape), dtype=np.int64) % 251).astype(
        np.float32).reshape(shape)

    def run():
        src = bolt.fromcallback(lambda idx: x[idx], shape, mode="tpu",
                                dtype=np.float32, chunks=512)
        return src.chunk(size=(64,), axis=(0,)).map(MAPSUM_FN).sum()

    with _stream.uploaders(4):
        jax.device_get(_tiny(run()))              # compile slab programs
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            jax.device_get(_tiny(run()))
            best = min(best, time.perf_counter() - t0)
    eff = bolt.profile.overlap_efficiency()
    ec = bolt.profile.engine_counters()
    return int(np.prod(shape)) * 4, best, {
        "bound": "transfer",
        "overlap_efficiency": round(eff, 3),
        # the parallel-ingest pipeline's shape, recorded with the number
        # (ISSUE 5): configured pool 4, the OBSERVED concurrent-uploader
        # high-water, and the async dispatch window's peak
        "upload_threads": ec["stream_upload_threads"],
        "inflight_high_water": ec["stream_inflight_high_water"],
        "prefetch_depth": ec["stream_prefetch_depth"],
        "traffic": (1.0, "one host->device pass per byte through the "
                         "uploader pool, overlapped with one fused "
                         "on-device map+sum read pass; level-0 fold "
                         "fused into the slab dispatch, pair partials "
                         "merge on device, one value block returns")}


def fam_stream_codec():
    # the ISSUE-14 compressed-ingest family: the SAME transfer-bound
    # streamed reduction as fam_stream_sum with the bf16 ingest codec
    # armed — uploader workers ENCODE each slab on host, HALF the bytes
    # cross the link (the transfer counters are the proof), and the
    # slab program DECODES on device fused into the fold (zero extra
    # HBM passes).  s_per_iter is the ENCODED pass; the family records
    # the raw pass, the coded-over-raw wall speedup (the bytes-win this
    # attach realises), the measured wire-bytes ratio, and the lossless
    # delta-f32 leg's bit-identity — the accuracy contract's anchor.
    from bolt_tpu import stream as _stream
    shape = (4096, 256, 64)                       # 0.27 GB raw
    x = (np.arange(np.prod(shape), dtype=np.int64) % 251).astype(
        np.float32).reshape(shape)

    def run(codec=None):
        src = bolt.fromcallback(lambda idx: x[idx], shape, mode="tpu",
                                dtype=np.float32, chunks=512,
                                codec=codec)
        return src.chunk(size=(64,), axis=(0,)).map(MAPSUM_FN).sum()

    def best_of(codec, n=3):
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            jax.device_get(_tiny(run(codec)))
            best = min(best, time.perf_counter() - t0)
        return best

    with _stream.uploaders(4):
        for cdc in (None, "bf16", "delta-f32"):
            jax.device_get(_tiny(run(cdc)))       # compile slab programs
        er0 = bolt.profile.engine_counters()
        raw_s = best_of(None)
        ec0 = bolt.profile.engine_counters()
        coded_s = best_of("bf16")
        ec1 = bolt.profile.engine_counters()
        ref = np.asarray(run(None).toarray())
        lossless = np.asarray(run("delta-f32").toarray())
    ratio = ((ec1["codec_bytes_wire"] - ec0["codec_bytes_wire"])
             / max(1, ec1["codec_bytes_raw"] - ec0["codec_bytes_raw"]))
    # the LINK observable: seconds spent inside counted transfers per
    # pass — on a host where produce/encode hide behind a real PCIe/DCN
    # link this is the bound the codec halves; on this container the
    # ratio shows the win even when the wall is produce-bound
    link_raw = (ec0["transfer_seconds"] - er0["transfer_seconds"]) / 3
    link_coded = (ec1["transfer_seconds"] - ec0["transfer_seconds"]) / 3
    eff = bolt.profile.overlap_efficiency()
    return int(np.prod(shape)) * 4, coded_s, {
        "bound": "transfer",
        "codec": "bf16",
        "raw_s": round(raw_s, 5),
        "coded_over_raw": round(raw_s / coded_s, 2),
        "wire_bytes_ratio": round(ratio, 3),
        "link_seconds_raw": round(link_raw, 5),
        "link_seconds_coded": round(link_coded, 5),
        "link_raw_over_coded": round(link_raw / max(link_coded, 1e-9),
                                     2),
        "lossless_bit_identical": bool(np.array_equal(lossless, ref)),
        "overlap_efficiency": round(eff, 3),
        "encode_seconds": round(
            ec1["codec_encode_seconds"] - ec0["codec_encode_seconds"],
            5),
        "traffic": (0.5, "wire bytes = codec ratio x raw bytes: one "
                         "host->device pass per WIRE byte (bf16 = "
                         "0.5x the raw f32), encoded per slab on the "
                         "uploader workers, decoded on device fused "
                         "into the fold — the gbps figure stays "
                         "per-RAW-pass so it is comparable with "
                         "stream_sum's")}


def fam_stream_swap():
    # the ISSUE-18 out-of-core shuffle family: a swap RECORDED on a
    # streamed source resolves through the two-phase shuffle — phase 1
    # re-buckets each uploaded slab on device the moment it lands,
    # phase 2 concatenates the resident buckets — so the re-axis
    # overlaps ingest instead of waiting for full HBM residency.
    # s_per_iter is the STREAMED swap end to end (produce + upload +
    # re-bucket + concat); the family records the materialise-first
    # wall it replaces (cache() everything, then the in-memory swap),
    # the forced-spill leg (budget ~ one bucket: every re-keyed bucket
    # rides the checkpoint-slab spill files and phase 2 re-streams
    # them from disk), the shuffle/spill byte gauges, and bit-identity
    # of EVERY leg against the transpose oracle — a shuffle moves
    # bytes, it never rounds.
    import shutil
    import tempfile
    from bolt_tpu import stream as _stream

    shape = (2048, 256, 64)                       # 128 MB raw
    x = (np.arange(np.prod(shape), dtype=np.int64) % 251).astype(
        np.float32).reshape(shape)

    def streamed():
        src = bolt.fromcallback(lambda idx: x[idx], shape, mode="tpu",
                                dtype=np.float32, chunks=256)
        return src.swap((0,), (0,))

    def materialised():
        src = bolt.fromcallback(lambda idx: x[idx], shape, mode="tpu",
                                dtype=np.float32, chunks=256)
        src.cache()                               # full HBM residency
        return src.swap((0,), (0,))

    def best_of(run, n=3):
        best, out = float("inf"), None
        for _ in range(n):
            t0 = time.perf_counter()
            out = np.asarray(run()._data)
            best = min(best, time.perf_counter() - t0)
        return best, out

    with _stream.uploaders(4):
        np.asarray(streamed()._data)              # compile both phases
        streamed_s, got = best_of(streamed)
        mat_s, ref = best_of(materialised)
        td = tempfile.mkdtemp(prefix="bolt-perf-spill-")
        try:
            with _stream.spill(dir=td, budget=1):
                t0 = time.perf_counter()
                spilled = np.asarray(streamed()._data)
                spill_s = time.perf_counter() - t0
            sc = bolt.profile.engine_counters()
        finally:
            shutil.rmtree(td, ignore_errors=True)
    bit = (np.array_equal(got, ref) and np.array_equal(spilled, ref)
           and np.array_equal(ref, np.transpose(x, (1, 0, 2))))
    eff = bolt.profile.overlap_efficiency()
    return int(np.prod(shape)) * 4, streamed_s, {
        "bound": "transfer",
        "materialised_s": round(mat_s, 5),
        "streamed_over_materialised": round(streamed_s / mat_s, 2),
        "spill_s": round(spill_s, 5),
        "spill_bytes": int(sc["spill_bytes"]),
        "shuffle_bytes": int(sc["shuffle_bytes"]),
        "bit_identical": bool(bit),
        "overlap_efficiency": round(eff, 3),
        "traffic": (2.0, "one host->device pass per input byte plus "
                         "the on-device re-bucket (read + transposed "
                         "write; a mesh's all_to_all exchange rides on "
                         "top); the forced-spill leg adds a disk round "
                         "trip per byte past the budget")}


def fam_multi_stat_fused():
    # the ISSUE-7 fused multi-stat terminal: bolt.compute(m.sum(),
    # m.var(), m.min(), m.max()) — four terminals from ONE read of a
    # >= 1 GB input (the bytes-read model: 1 fused dispatch over the
    # chain = 1 input pass, vs 4 standalone passes).  The family also
    # records per-terminal-count scaling (1/2/4 fused terminals): on
    # HBM-bound hardware the fused time should stay ~flat with N while
    # the sequential cost grows ~Nx.
    shape = (8192, 256, 128)                      # 1.07 GB f32
    b = bolt.ones(shape, mode="tpu", dtype=np.float32).cache()

    def launch_n(n):
        m = b.map(MAPSUM_FN)
        hs = [m.sum(), m.var(), m.min(), m.max()][:n]
        bolt.compute(*hs)
        return hs[-1]

    def launch_seq():
        # the pre-fusion cost model: resolve one terminal at a time,
        # each singleton group dispatching its own standalone pass
        m = b.map(MAPSUM_FN)
        m.sum().cache()
        m.var().cache()
        m.min().cache()
        return m.max().cache()

    scaling = {}
    for n in (1, 2, 4):
        scaling[str(n)] = round(
            steady_amortized(lambda n=n: launch_n(n), iters=8), 5)
    sec = scaling["4"]
    seq4 = steady_amortized(launch_seq, iters=8)
    ec = bolt.profile.engine_counters()
    return int(np.prod(shape)) * 4, sec, {
        "bound": "hbm",
        "terminals": 4,
        "sequential_4_s": round(seq4, 5),
        "seq_over_fused": round(seq4 / sec, 2),
        "terminal_scaling_s": scaling,
        "fused_stat_groups": ec["fused_stat_groups"],
        "fused_stat_terminals": ec["fused_stat_terminals"],
        "traffic": (1.0, "ONE fused read pass serves all 4 terminals "
                         "(sum/var/min/max); the sequential form costs "
                         "4 passes — the bytes-read model the "
                         "multi_stat_fused bench gate enforces")}


def fam_serve_smallreq():
    # the ISSUE-13 continuous micro-batching family: a firehose of
    # SMALL same-shape map->sum requests against ONE serve worker,
    # where per-request dispatch overhead (program launch + the
    # 8-device collective rendezvous), not bytes, is the roofline.
    # s_per_iter is the BATCHED saturated drain wall (queue pre-filled
    # behind a parked worker = high offered QPS; the drain measures
    # aggregate server throughput); the family records the unbatched
    # drain, the batched-over-unbatched scaling factor (the >= 3x
    # acceptance gate), p50/p99 latency at a sweep of offered QPS for
    # BOTH modes (the low-QPS p50 must hold < 1.2x with batching
    # armed), realised batch occupancy, and dispatches-per-request.
    import threading
    from bolt_tpu import serve as _serve
    from bolt_tpu.tpu import batched as _batched
    shape = (128, 32)
    nreq, nb = 256, 8
    bs = [bolt.randn(shape, mode="tpu", seed=140 + i,
                     dtype=np.float32).cache() for i in range(nb)]

    def make(i=0):
        return bs[i % nb].map(MAPSUM_FN).sum()

    for i in range(nb):
        jax.device_get(_tiny(make(i).cache().tojax()))

    def saturated(sv):
        # server-side drain window: gate opening -> last finished_s
        # (the client's result-collection loop stays outside)
        best = float("inf")
        for _ in range(3):
            gate = threading.Event()
            blocker = sv.submit(gate.wait)       # parks the ONE worker
            futs = [sv.submit(make(i), tenant="t%d" % (i % 4))
                    for i in range(nreq)]
            t0 = time.perf_counter()
            gate.set()
            [f.result(timeout=600) for f in futs]
            best = min(best, max(f.finished_s for f in futs) - t0)
            blocker.result(timeout=30)
        return best

    def qps_curve(sv, levels=(100, 1000, 100000), n=24):
        curve = {}
        [sv.submit(make()).result(timeout=60) for _ in range(5)]
        for qps in levels:
            period = 1.0 / qps
            futs = []
            for i in range(n):
                t0 = time.perf_counter()
                futs.append(sv.submit(make(i), tenant="t%d" % (i % 4)))
                dt = period - (time.perf_counter() - t0)
                if dt > 0:
                    time.sleep(dt)
            for f in futs:
                f.result(timeout=120)
            lats = sorted(f.finished_s - f.submitted_s for f in futs)
            curve[str(qps)] = {
                "p50_s": round(lats[len(lats) // 2], 6),
                "p99_s": round(lats[min(len(lats) - 1,
                                        int(len(lats) * 0.99))], 6)}
        return curve

    with _serve.serving(workers=1, queue_limit=2 * nreq) as sv:
        [f.result(timeout=60) for f in
         [sv.submit(make(i)) for i in range(16)]]
        unbatched = saturated(sv)
        curve_off = qps_curve(sv)
    with _serve.serving(workers=1, queue_limit=2 * nreq,
                        batching={"max_batch": 16,
                                  "linger": 0.002}) as sv:
        _batched.warm(make, buckets=sv.batching.buckets)
        [f.result(timeout=60) for f in
         [sv.submit(make(i)) for i in range(16)]]
        # the counter window covers ONLY the saturated drain rounds:
        # warm()'s throwaway bucket dispatches, the warmup submits and
        # the qps-curve traffic must not contaminate the recorded
        # occupancy/dispatch metrics
        ec0 = bolt.profile.engine_counters()
        batched = saturated(sv)
        ec1 = bolt.profile.engine_counters()
        curve_on = qps_curve(sv)
        occ = (sv.stats()["batching"].get("occupancy") or {})
    dreq = max(1, ec1["batched_requests"] - ec0["batched_requests"])
    nbytes = int(np.prod(shape)) * 4
    return nreq * nbytes, batched, {
        "bound": "dispatch",
        "requests": nreq,
        "unbatched_s": round(unbatched, 5),
        "batched_over_unbatched": round(unbatched / batched, 2),
        "batch_occupancy_mean": occ.get("mean"),
        "dispatches_per_request": round(
            (ec1["dispatches"] - ec0["dispatches"]) / float(dreq), 4),
        "batched_dispatches": ec1["batched_dispatches"]
        - ec0["batched_dispatches"],
        "batched_requests": ec1["batched_requests"]
        - ec0["batched_requests"],
        "qps_curve_batched": curve_on,
        "qps_curve_unbatched": curve_off,
        "p50_low_qps_ratio": round(
            curve_on["100"]["p50_s"] / curve_off["100"]["p50_s"], 3),
        "traffic": (1.0, "N tiny same-shape requests; throughput is "
                         "bounded by per-request dispatch overhead, "
                         "which the coalesced stacked dispatch "
                         "amortises across the bucket width — the "
                         "gbps figure is incidental (requests are "
                         "KB-scale)")}


def fam_serve_multitenant():
    # the ISSUE-8 multi-tenant serving layer: N tenants submit
    # IDENTICAL streamed reductions over storage-latency-bound sources
    # (a per-slab sleep emulates the object-store fetch a production
    # loader pays; that wait is what the scheduler's concurrency
    # recovers — the on-device program is fam_map_sum's).  s_per_iter
    # is the CONCURRENT wall for all N tenants; the family records the
    # serialised one-at-a-time wall, the aggregate-over-serialised
    # scaling factor (the >= 2.5x acceptance gate), p50/p99 per-job
    # latency over two rounds, and the admission/arbiter shape.
    from bolt_tpu import serve as _serve
    from bolt_tpu.obs import metrics as _metrics
    tenants = 4
    shape = (1024, 256, 64)                       # 64 MB per tenant
    x = (np.arange(np.prod(shape), dtype=np.int64) % 251).astype(
        np.float32).reshape(shape)
    lat = float(os.environ.get("BOLT_SERVE_BENCH_LATENCY", "0.025"))

    def read(idx):
        time.sleep(lat)                  # emulated storage fetch latency
        return x[idx]

    def make():
        src = bolt.fromcallback(read, shape, mode="tpu",
                                dtype=np.float32, chunks=128)  # 8 slabs
        return src.map(MAPSUM_FN).sum()

    jax.device_get(_tiny(make().cache().tojax()))  # compile slab programs
    t0 = time.perf_counter()
    for _ in range(tenants):
        jax.device_get(_tiny(make().cache().tojax()))
    serialized = time.perf_counter() - t0

    lats = []
    best = float("inf")
    _metrics.registry().gauge("serve.queue_depth_high_water").reset()
    with _serve.serving(workers=tenants, queue_limit=2 * tenants) as sv:
        for _ in range(2):                        # two rounds: 8 jobs
            t0 = time.perf_counter()
            futs = [sv.submit(make(), tenant="t%d" % i)
                    for i in range(tenants)]
            [f.result(timeout=600) for f in futs]
            best = min(best, time.perf_counter() - t0)
            lats += [f.finished_s - f.submitted_s for f in futs]
        # p50/p99-vs-offered-QPS (ISSUE 13 rides along): jobs paced at
        # each offered rate, latency distribution per level — the
        # saturation knee is where p99 detaches from p50
        curve = {}
        for qps in (1, 4, 16):
            period = 1.0 / qps
            cfuts = []
            for i in range(8):
                t0 = time.perf_counter()
                cfuts.append(sv.submit(make(), tenant="t%d" % (i % 4)))
                dt = period - (time.perf_counter() - t0)
                if dt > 0:
                    time.sleep(dt)
            for f in cfuts:
                f.result(timeout=600)
            clats = sorted(f.finished_s - f.submitted_s for f in cfuts)
            curve[str(qps)] = {
                "p50_s": round(clats[len(clats) // 2], 5),
                "p99_s": round(clats[-1], 5)}
        st = sv.stats()
    lats.sort()
    nbytes = int(np.prod(shape)) * 4
    return tenants * nbytes, best, {
        "bound": "transfer",
        "tenants": tenants,
        "qps_curve": curve,
        "p50_s": round(lats[len(lats) // 2], 5),
        "p99_s": round(lats[min(len(lats) - 1,
                                int(len(lats) * 0.99))], 5),
        "serialized_s": round(serialized, 5),
        "aggregate_over_serialized": round(serialized / best, 2),
        "queue_depth_high_water": st["queue_depth_high_water"],
        "arbiter_waits": st["arbiter"]["waits"],
        "traffic": (1.0, "N identical streamed reductions, one "
                         "host->device pass per tenant byte; the "
                         "aggregate GB/s is all tenants' bytes over the "
                         "concurrent wall — scaling over the serialised "
                         "baseline is the multi-tenant win, slab "
                         "ingest latency emulated at %gs" % lat)}


def fam_stream_resume():
    # the ISSUE-9 fault-tolerance family: an injected uploader death
    # kills a resumable streamed reduction mid-run; the re-run resumes
    # from the last retired-slab checkpoint.  s_per_iter is RECOVERY —
    # the resumed run's wall clock (it streams only the remaining
    # slabs, so recovery_over_clean < 1 is the healthy shape; > 1.5
    # means resume stopped saving work).  The retry leg rides along:
    # one injected fault absorbed in-run by stream.retries(1), counted.
    import tempfile
    from bolt_tpu import _chaos as chaos
    from bolt_tpu import checkpoint as ckpt
    from bolt_tpu import stream as _stream
    shape = (2048, 256, 64)                       # 128 MB, 8 slabs
    x = (np.arange(np.prod(shape), dtype=np.int64) % 251).astype(
        np.float32).reshape(shape)

    def make(ck=None):
        src = bolt.fromcallback(lambda idx: x[idx], shape, mode="tpu",
                                dtype=np.float32, chunks=256,
                                checkpoint=ck)
        return src.map(MAPSUM_FN).sum()

    jax.device_get(_tiny(make().cache().tojax()))     # compile
    t0 = time.perf_counter()
    ref = make().cache()
    jax.device_get(_tiny(ref.tojax()))
    clean = time.perf_counter() - t0

    d = tempfile.mkdtemp(prefix="bolt-perf-resume-")
    ec0 = bolt.profile.engine_counters()
    chaos.inject("stream.upload", nth=6)              # die at slab 6/8
    try:
        with _stream.uploaders(1):
            make(d).cache()
    except Exception:
        pass
    finally:
        chaos.clear()
    t0 = time.perf_counter()
    out = make(d).cache()
    jax.device_get(_tiny(out.tojax()))
    recovery = time.perf_counter() - t0
    ec1 = bolt.profile.engine_counters()
    identical = bool(np.array_equal(np.asarray(ref.toarray()),
                                    np.asarray(out.toarray())))

    chaos.inject("stream.upload", nth=3)              # the retry leg
    try:
        with _stream.retries(1):
            jax.device_get(_tiny(make().cache().tojax()))
    finally:
        chaos.clear()
    ec2 = bolt.profile.engine_counters()
    return int(np.prod(shape)) * 4, recovery, {
        "bound": "transfer",
        "recovery_seconds": round(recovery, 5),
        "clean_seconds": round(clean, 5),
        "recovery_over_clean": round(recovery / clean, 2),
        "resumes": ec1["stream_resumes"] - ec0["stream_resumes"],
        "retries": ec2["stream_retries"] - ec1["stream_retries"],
        "checkpoint_bytes": ec1["checkpoint_bytes"],
        "bit_identical": identical,
        "stale_checkpoint": ckpt.stream_pending(d),
        "traffic": (1.0, "recovery pass: only the slabs past the "
                         "retired-slab checkpoint re-stream; the gbps "
                         "figure is input bytes over RECOVERY wall, so "
                         "it exceeds the clean-run link rate when "
                         "resume is doing its job")}


def fam_multihost_stream():
    # the ISSUE-10 pod-scale family: a REAL 2-process jax.distributed
    # localhost CPU cluster streams the per-process fromcallback
    # reduction (each process produces and uploads ONLY its shard of
    # every slab; the cross-host fold is the shard_map slab program's
    # psum).  s_per_iter is the CLUSTER wall (max across workers) for
    # one warmed streamed pass; the family records per-process GB/s
    # (each process's own ingest link) and the aggregate-vs-single-
    # process ratio (the scale-out observable: > 1 means the pod
    # ingests faster than one process feeding the same devices).
    import shutil
    from bolt_tpu.utils import load_script
    mh = load_script("multihost_harness")
    env = {"BOLT_MH_NKEYS": "4096", "BOLT_MH_VDIM": "256",
           "BOLT_MH_CHUNKS": "512"}
    res, out, _ = mh.run_cluster("bench", nproc=2, devs=1, env=env)
    res1, out1, _ = mh.run_cluster("bench", nproc=1, devs=2, env=env)
    ref = np.load(os.path.join(out1, "bench_sum.0.npy"))
    identical = all(np.array_equal(np.load(os.path.join(
        out, "bench_sum.%d.npy" % p)), ref) for p in (0, 1))
    shutil.rmtree(out, ignore_errors=True)
    shutil.rmtree(out1, ignore_errors=True)
    wall = max(r["wall_s"] for r in res)
    single = res1[0]["wall_s"]
    nbytes = 4096 * 256 * 4
    return nbytes, wall, {
        "bound": "transfer",
        "processes": 2,
        "per_process_gbps": [
            round(r["transfer_bytes"] / r["wall_s"] / 1e9, 2)
            for r in res],
        "single_process_s": round(single, 5),
        "aggregate_over_single": round(single / wall, 2),
        "warm_recompiles": sum(r["recompiles_warm"] for r in res),
        "bit_identical": identical,
        "traffic": (1.0, "one host->device pass per byte, SPLIT across "
                         "processes (each ships its own shard); the "
                         "cross-host fold is one psum per slab riding "
                         "the shard_map slab program")}


def fam_multihost_resume():
    # the ISSUE-11 pod fault-tolerance family: kill -9 of ONE process
    # in a REAL 3-process localhost cluster; every survivor raises the
    # watchdog's PeerLostError, reforms onto the 2 survivors
    # (multihost.reform) and resumes from the rendezvous-consistent
    # checkpoint.  s_per_iter is RECOVERY — the survivors' wall from
    # learning of the loss to the resumed bit-identical result
    # (barrier probe + reform + resume); recovery_over_clean < 2.0 is
    # the healthy shape (the clean run is the unkilled 2-process
    # baseline of the same paced workload).  detection_seconds is the
    # heartbeat verdict latency (<= 2x BOLT_POD_TIMEOUT by contract).
    from bolt_tpu.utils import load_script
    mh = load_script("multihost_harness")
    r = mh.run_reform_bench()
    nbytes = 96 * 8 * 4               # the paced workload's input pass
    return nbytes, r["recovery_s"], {
        "bound": "recovery",
        "detection_seconds": round(r["detection_s"], 5),
        "reform_seconds": round(r["reform_s"], 5),
        "resume_seconds": round(r["resume_s"], 5),
        "barrier_seconds": round(r["barrier_s"], 5),
        "clean_seconds": round(r["clean_s"], 5),
        "recovery_over_clean": round(r["recovery_over_clean"], 2),
        "pod_timeout_seconds": r["pod_timeout"],
        "victim_rc": r["victim_rc"],
        "survivors": r["survivors"],
        "resumes_sum": r["sum_resumes"],
        "resumes_stats": r["stats_resumes"],
        "bit_identical": r["bit_identical"],
        "stale_checkpoint_files": len(r["stale_checkpoint_files"]),
        "traffic": (1.0, "recovery leg: the survivors re-stream only "
                         "the slabs past the last rendezvous-"
                         "consistent watermark, on the SHRUNK 2-"
                         "process mesh (topology remap); wall is "
                         "dominated by the paced loader + the reform "
                         "bring-up, not bytes")}


def fam_multihost_elastic():
    # the ISSUE-12 self-healing family: kill -9 of ONE process under
    # Server(supervise=True) in a REAL 3-process localhost cluster —
    # the supervisor shrinks the pod 3->2 automatically (zero caller
    # intervention), a restarted replacement process rejoins
    # mid-stream and the pod re-expands 2->3.  s_per_iter is the whole
    # ELASTIC SCENARIO wall (shrink recovery + rejoin quiesce/grow +
    # the clean fused-stats leg); scenario_over_clean < 2.5 is the
    # healthy shape against the unkilled 3-process run of the same
    # paced workload.  detection_seconds is the heartbeat verdict
    # latency (<= 2x BOLT_POD_TIMEOUT by contract), reform/rejoin/
    # recovery_seconds the auto-reform drive, the rejoin-triggered
    # recovery and the full shrink pause->resume wall,
    # precollective_seconds the CLOSED pre-collective death bound (a
    # peer dead before the first collective raises PeerLostError here,
    # not at gloo's ~30s connect timeout).
    from bolt_tpu.utils import load_script
    mh = load_script("multihost_harness")
    r = mh.run_supervise_bench()
    p = mh.run_precollective_probe()
    nbytes = 96 * 8 * 4               # one paced workload's input pass
    return nbytes, r["scenario_s"], {
        "bound": "recovery",
        "detection_seconds": round(r["detection_s"], 5),
        "reform_seconds": round(r["reform_s"], 5),
        "rejoin_seconds": round(r["rejoin_s"], 5),
        "recovery_seconds": round(r["recovery_s"], 5),
        # None on the degraded paths (no rejoiner result / the kill
        # raced past the rendezvous) — keep the record instead of
        # crashing the family exactly when it would show a regression
        "attach_seconds": (round(r["attach_s"], 5)
                           if r["attach_s"] is not None else None),
        "precollective_seconds": (round(p["pre_elapsed"], 5)
                                  if p["pre_elapsed"] is not None
                                  else None),
        "clean_seconds": round(r["clean_s"], 5),
        "scenario_over_clean": round(r["scenario_over_clean"], 2),
        "pod_timeout_seconds": r["pod_timeout"],
        "victim_rc": r["victim_rc"],
        "survivors": r["survivors"],
        "rejoined": r["rejoined"],
        "nproc_final": r["nproc_final"],
        "resumes_a": r["a_resumes"],
        "resumes_b": r["b_resumes"],
        "bit_identical": r["bit_identical"],
        "stale_markers": r["stale_markers"],
        "traffic": (1.0, "elastic leg: survivors re-stream only the "
                         "slabs past each recovery's checkpoint "
                         "watermark — first on the SHRUNK 2-process "
                         "mesh, then on the re-expanded 3-process one "
                         "(the same psum-replicated topology remap "
                         "both ways); wall is dominated by the paced "
                         "loader + two reform bring-ups, not bytes")}


def fam_pca_default():
    # the SAME pca program under the bolt.precision("default") scope —
    # PERF.json records both policy modes for the precision-bound
    # families (VERDICT r4 weak-3/4; measured 2.47x on chip, sv within
    # 2e-5)
    with bolt.precision("default"):
        return fam_pca()


def fam_halo_gaussian_default():
    with bolt.precision("default"):
        return fam_halo_gaussian()


FAMILIES = [
    ("map_sum", fam_map_sum),
    ("stats_welford", fam_stats_welford),
    ("swap", fam_swap),
    ("filter_fused", fam_filter_fused),
    ("filter_sum_fused", fam_filter_sum_fused),
    ("matmul", fam_matmul),
    ("matmul_bf16", fam_matmul_bf16),
    ("halo_gaussian", fam_halo_gaussian),
    ("halo_gaussian_default", fam_halo_gaussian_default),
    ("segment_reduce", fam_segment_reduce),
    ("pca", fam_pca),
    ("pca_default", fam_pca_default),
    ("svdvals", fam_svdvals),
    ("jacobi_eigh", fam_jacobi_eigh),
    ("stream_sum", fam_stream_sum),
    ("stream_codec", fam_stream_codec),
    ("stream_swap", fam_stream_swap),
    ("multi_stat_fused", fam_multi_stat_fused),
    ("serve_multitenant", fam_serve_multitenant),
    ("serve_smallreq", fam_serve_smallreq),
    ("stream_resume", fam_stream_resume),
    ("multihost_stream", fam_multihost_stream),
    ("multihost_resume", fam_multihost_resume),
    ("multihost_elastic", fam_multihost_elastic),
]


def print_table():
    """Markdown perf table regenerated FROM PERF.json (BASELINE.md
    pastes this between its PERF_TABLE markers — headline numbers come
    from the artifact, never from memory)."""
    with open(OUT) as f:
        results = json.load(f)
    print("| family | bound | GB/s (per input pass) | eff GB/s "
          "(real traffic) | % of bound | TFLOP/s | % MXU peak |")
    print("|---|---|---|---|---|---|---|")
    for name in sorted(results):
        if name.startswith("_"):
            continue               # metadata entries (_engine), not families
        r = results[name]
        # roofline percentages only mean something on a tpu window; a
        # cpu-container entry shows the platform tag where the % would
        # go (committed pre-fix entries may still carry the keys)
        chip = r.get("platform", "tpu") == "tpu"
        pct = (r.get("pct_of_bound", r.get("pct_mxu_peak", "")) if chip
               else "(%s)" % r.get("platform"))
        print("| %s | %s | %s | %s | %s | %s | %s |" % (
            name, r.get("bound", ""), r.get("gbps", ""),
            r.get("effective_gbps", ""), pct,
            r.get("tflops", ""),
            r.get("pct_mxu_peak", "") if chip else ""))


def _phase_breakdown(spans):
    """Span-derived phase totals for one family: per span name, summed
    wall seconds over the family's spans.  Names nest (engine.dispatch
    runs inside stream.compute), so entries overlap — this is a
    breakdown by PHASE, not a partition of the family's wall clock."""
    tot = {}
    for s in spans:
        d = s.duration
        if d:
            tot[s.name] = tot.get(s.name, 0.0) + d
    return {k: round(v, 5) for k, v in sorted(tot.items())}


def main():
    if "--table" in sys.argv:
        print_table()
        return 0
    from bolt_tpu import obs as _obs
    trace_path = _obs.trace_arg(sys.argv)
    obs = None
    if trace_path:
        obs = _obs
        obs.clear()
        obs.enable(ring=65536)
    # BOLT_PERSISTENT_CACHE=<dir> wires the run to the on-disk XLA cache:
    # a warm perf run then skips every compile (persistent_hits in the
    # _engine entry confirms it), so short wall-clock budgets go to
    # measurement instead of compilation
    pc = os.environ.get("BOLT_PERSISTENT_CACHE")
    if pc:
        from bolt_tpu import engine
        engine.persistent_cache(pc)
    rebase = "--rebaseline" in sys.argv
    only = None
    for arg in sys.argv[1:]:
        if arg.startswith("--only="):
            only = set(arg.split("=", 1)[1].split(","))
        elif arg.startswith("--families="):
            # the targeted re-measurement door (ISSUE 13 satellite): a
            # comma list of family names, each token either a literal
            # name or "platform:<tag>" — the latter expands to every
            # committed family whose last PERF.json/baseline entry was
            # measured on that backend, so a future real-chip window
            # can re-run exactly the platform-"cpu"-tagged families
            # (`--families=platform:cpu`) without a full sweep
            sel = set()
            committed = {}
            for path in (BASE, OUT):
                if os.path.exists(path):
                    with open(path) as f:
                        committed.update(json.load(f))
            known = {name for name, _ in FAMILIES}
            literal = set()
            for tok in arg.split("=", 1)[1].split(","):
                tok = tok.strip()
                if not tok:
                    continue
                if tok.startswith("platform:"):
                    plat = tok.split(":", 1)[1]
                    # expansion keeps only families that still EXIST —
                    # a stale committed entry must not fail the run
                    sel |= {name for name, entry in committed.items()
                            if name in known and isinstance(entry, dict)
                            and entry.get("platform") == plat}
                else:
                    literal.add(tok)
                    sel.add(tok)
            unknown = sorted(literal - known)
            if unknown:
                print("--families: unknown famil%s %s (known: %s)"
                      % ("y" if len(unknown) == 1 else "ies",
                         ",".join(unknown),
                         ",".join(sorted(known))), file=sys.stderr)
                return 1
            only = sel if only is None else (only | sel)
            if not only:
                print("--families matched nothing (token list: %r)"
                      % arg.split("=", 1)[1], file=sys.stderr)
                return 1
    # start from the committed baseline plus any previous partial
    # measurement (fresher wins), so a run cut short by a wall-clock
    # budget (remote-attach variance is 2-10x) resumes instead of losing
    # everything, and `--rebaseline --only=fam` never wipes the other
    # families' baselines; results are flushed after EVERY family
    results = {}
    for path in (BASE, OUT):
        if os.path.exists(path):
            with open(path) as f:
                results.update(json.load(f))
    failed = []
    measured = set()   # families ACTUALLY run this invocation — the
                       # status report covers only these (seeded baseline
                       # entries would otherwise compare to themselves)
    last_sid = 0       # obs-span watermark: spans above it belong to the
                       # family currently measuring (--trace mode)
    for name, fam in FAMILIES:
        if only is not None and name not in only:
            continue
        try:
            out = fam()
        except Exception as e:   # one broken family must not lose the rest
            print("family %s FAILED: %s" % (name, e), file=sys.stderr)
            failed.append(name)
            # purge any stale number: a broken family must not regression-
            # gate on data from a previous run
            results.pop(name, None)
            if obs is not None:
                # consume the broken family's spans: its compiles and
                # any leaked opens must not land in the NEXT family's
                # "phases" attribution
                last_sid = max((s.sid for s in obs.spans()),
                               default=last_sid)
            continue
        phases = None
        if obs is not None:
            fam_spans = [s for s in obs.spans() if s.sid > last_sid]
            last_sid = max((s.sid for s in fam_spans), default=last_sid)
            phases = _phase_breakdown(fam_spans)
            leaked = obs.active_count()
            if leaked:
                print("family %s leaked %d active span(s)"
                      % (name, leaked), file=sys.stderr)
        nbytes, sec = out[0], out[1]
        meta = out[2] if len(out) > 2 else {"bound": "hbm"}
        gbps = nbytes / sec / 1e9
        entry = {"s_per_iter": round(sec, 5), "bytes": nbytes,
                 "gbps": round(gbps, 1), "bound": meta["bound"],
                 # which backend actually measured this window: chip
                 # numbers and cpu-container numbers must never be
                 # confused when read back (low-water marks are per
                 # platform in spirit)
                 "platform": jax.default_backend()}
        for key in ("upload_threads", "inflight_high_water",
                    "prefetch_depth", "terminals", "terminal_scaling_s",
                    "sequential_4_s", "seq_over_fused",
                    "fused_stat_groups", "fused_stat_terminals",
                    "tenants", "p50_s", "p99_s", "serialized_s",
                    "aggregate_over_serialized",
                    "queue_depth_high_water", "arbiter_waits",
                    "recovery_seconds", "clean_seconds",
                    "recovery_over_clean", "resumes", "retries",
                    "checkpoint_bytes", "bit_identical",
                    "stale_checkpoint", "processes", "per_process_gbps",
                    "single_process_s", "aggregate_over_single",
                    "warm_recompiles",
                    # multihost_resume (ISSUE 11): the pod recovery
                    # phase breakdown and its hygiene observables
                    "detection_seconds", "reform_seconds",
                    "resume_seconds", "barrier_seconds",
                    "pod_timeout_seconds", "victim_rc", "survivors",
                    "resumes_sum", "resumes_stats",
                    "stale_checkpoint_files",
                    # multihost_elastic (ISSUE 12): the self-healing
                    # 3->2->3 phase breakdown — auto-reform, rejoin
                    # re-expansion, the closed pre-collective bound —
                    # and its hygiene observables
                    "rejoin_seconds", "attach_seconds",
                    "precollective_seconds", "scenario_over_clean",
                    "rejoined", "nproc_final", "resumes_a",
                    "resumes_b", "stale_markers",
                    # serve_smallreq (ISSUE 13): continuous
                    # micro-batching observables — aggregate scaling,
                    # occupancy, amortised dispatch count, and the
                    # p50/p99-vs-offered-QPS curves for both modes
                    # (serve_multitenant gains "qps_curve" too)
                    # stream_codec (ISSUE 14): compressed-ingest
                    # observables — the raw-vs-encoded walls, the
                    # measured wire-bytes ratio, the lossless leg's
                    # bit-identity, the host encode cost
                    "codec", "raw_s", "coded_over_raw",
                    "wire_bytes_ratio", "lossless_bit_identical",
                    "encode_seconds", "link_seconds_raw",
                    "link_seconds_coded", "link_raw_over_coded",
                    "requests", "unbatched_s", "batched_over_unbatched",
                    "batch_occupancy_mean", "dispatches_per_request",
                    "batched_dispatches", "batched_requests",
                    "qps_curve", "qps_curve_batched",
                    "qps_curve_unbatched", "p50_low_qps_ratio",
                    # stream_swap (ISSUE 18): out-of-core shuffle
                    # observables — the materialise-first wall it
                    # replaces, the forced-spill leg, and the
                    # shuffle/spill byte gauges
                    "materialised_s", "streamed_over_materialised",
                    "spill_s", "spill_bytes", "shuffle_bytes"):
            if meta.get(key) is not None:
                entry[key] = meta[key]
        if phases:
            # --trace mode: span-derived per-phase wall totals for the
            # family (engine.lower/compile vs dispatch vs stream
            # ingest/compute — where this family's time actually went)
            entry["phases"] = phases
        # %-of-peak on the axis that bounds the family (VERDICT r3
        # next-1): HBM families get pct_hbm_peak, MXU families get
        # TFLOP/s against the per-precision MXU peak; latency-bound
        # families (sequential scan chains) get neither — their gate is
        # s_per_iter.  ROOFLINE percentages exist ONLY for tpu-measured
        # windows: a cpu-container number divided by the v5e HBM peak
        # reads as a 0.1%-of-peak "regression" that never happened, so
        # non-tpu platforms suppress them (the ISSUE 14 reporting fix)
        # and the status line labels the window instead.
        on_chip = entry["platform"] == "tpu"
        if meta["bound"] == "hbm" and on_chip:
            entry["pct_hbm_peak"] = round(100.0 * gbps / HBM_PEAK_GBPS, 1)
        if meta.get("overlap_efficiency") is not None:
            # streaming families: fraction of ingest hidden behind
            # compute (bolt_tpu.profile.overlap_efficiency)
            entry["overlap_efficiency"] = meta["overlap_efficiency"]
        if meta.get("traffic"):
            # HONEST effective-traffic accounting (VERDICT r4 weak-2):
            # gbps above is per-pass-over-the-INPUT; multi-pass families
            # (swap ~2x, filter ~3x, halo ~4x) move more HBM bytes than
            # the input per iteration, and the machine-readable % must
            # say so instead of hiding it in prose
            mult, model = meta["traffic"]
            eff = nbytes * mult
            entry["effective_bytes"] = int(eff)
            entry["effective_gbps"] = round(eff / sec / 1e9, 1)
            if meta["bound"] == "hbm" and on_chip:
                # the %-of-bound denominator is the HBM peak; transfer-
                # bound families (stream_sum) have no meaningful HBM %,
                # and non-tpu windows have no meaningful roofline at all
                entry["pct_of_bound"] = round(
                    100.0 * entry["effective_gbps"] / HBM_PEAK_GBPS, 1)
            entry["traffic_model"] = model
        if meta.get("flops"):
            tf = meta["flops"] / sec / 1e12
            entry["tflops"] = round(tf, 2)
            peak = MXU_PEAK_TFLOPS.get(meta.get("precision"))
            if peak and meta["bound"] == "mxu":
                entry["precision"] = meta["precision"]
                entry["pct_mxu_peak"] = round(100.0 * tf / peak, 1)
        results[name] = entry
        measured.add(name)
        print(json.dumps({"family": name, **entry}), flush=True)
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1, sort_keys=True)

    # executor-layer accounting rides along with the perf numbers: the
    # engine's compile-cache hit rate says whether the run amortised its
    # XLA compiles (a healthy steady-state run is hit-dominated), and
    # compile/lower seconds quantify the one-time cost the persistent
    # cache removes from warm processes.  SKIPPED when this invocation
    # saw no engine activity in-process (an --only= run of a
    # subprocess-only family like multihost_stream) — an all-zeros
    # snapshot must not clobber the committed real one.
    ec = bolt.profile.engine_counters()
    lookups = ec["hits"] + ec["misses"]
    if lookups == 0 and ec["transfer_bytes"] == 0:
        print("(_engine snapshot skipped: no in-process engine "
              "activity this run — an --only= run of a subprocess "
              "family keeps the committed snapshot)", file=sys.stderr)
    else:
        results["_engine"] = {
            "hits": ec["hits"], "misses": ec["misses"],
            "hit_rate": round(ec["hits"] / lookups, 4) if lookups
            else None,
            "aot_compiles": ec["aot_compiles"],
            "compile_seconds": round(ec["compile_seconds"], 3),
            "lower_seconds": round(ec["lower_seconds"], 3),
            "persistent_hits": ec["persistent_hits"],
            "persistent_misses": ec["persistent_misses"],
            "donations": ec["donations"],
            "transfer_bytes": ec["transfer_bytes"],
            "transfer_seconds": round(ec["transfer_seconds"], 3),
            "stream_chunks": ec["stream_chunks"],
            "stream_upload_threads": ec["stream_upload_threads"],
            "stream_inflight_high_water":
                ec["stream_inflight_high_water"],
            "overlap_efficiency": round(
                bolt.profile.overlap_efficiency(ec), 4),
        }
        print(json.dumps({"family": "_engine", **results["_engine"]}),
              flush=True)
    with open(OUT, "w") as f:
        json.dump(results, f, indent=1, sort_keys=True)

    if obs is not None:
        obs.to_chrome(path=trace_path)
        obs.disable()
        print("obs timeline written to %s (load in chrome://tracing or "
              "Perfetto)" % trace_path, file=sys.stderr)

    if rebase or not os.path.exists(BASE):
        with open(BASE, "w") as f:
            json.dump(results, f, indent=1, sort_keys=True)
        print("baseline written to", BASE, file=sys.stderr)
        return 2 if failed else 0

    with open(BASE) as f:
        base = json.load(f)
    # Per-family status against the low-water mark, printed EVERY run
    # (VERDICT r3 weak-2: a below-water family must be visible even when
    # it is inside the 25% regression gate — no more "all above" claims
    # drifting from the committed data).
    regressed, below = [], []
    for name in sorted(measured):
        r = results[name]
        b = base.get(name)
        if not b or "gbps" not in b:
            # covers seeded pending_measurement entries that carry a
            # traffic model but no measured number yet
            print("family %-15s %8.1f GB/s   (no low-water mark yet)"
                  % (name, r["gbps"]), file=sys.stderr)
            continue
        ok = r["gbps"] >= b["gbps"]
        if not ok:
            below.append(name)
            if r["gbps"] < b["gbps"] * (1 - THRESHOLD):
                regressed.append((name, b["gbps"], r["gbps"]))
        # pct_of_bound exists only for hbm-bound TPU-measured families
        # — a recovery-bound family (multihost_elastic) or a cpu
        # container window (every PR 6-14 family until a chip refresh)
        # still reports its effective rate, LABELLED by platform so a
        # cpu number can never read as a %-of-HBM-peak regression
        if "pct_of_bound" in r and r.get("platform") == "tpu":
            eff = ("  [eff %.0f GB/s = %.0f%% of bound]"
                   % (r["effective_gbps"], r["pct_of_bound"]))
        elif "effective_gbps" in r:
            eff = "  [eff %.0f GB/s%s]" % (
                r["effective_gbps"],
                "" if r.get("platform") == "tpu"
                else ", %s window — no roofline %%"
                % r.get("platform", "?"))
        else:
            eff = ""
        print("family %-15s %8.1f GB/s vs low-water %6.1f -> %s%s"
              % (name, r["gbps"], b["gbps"],
                 "above" if ok else "BELOW (%.0f%%)"
                 % (100.0 * r["gbps"] / b["gbps"]), eff), file=sys.stderr)
    for name, was, now in regressed:
        print("REGRESSION %s: %.1f -> %.1f GB/s" % (name, was, now),
              file=sys.stderr)
    n_meas = len([n for n in measured if n in base])
    if below:
        print("%d/%d measured families at-or-above low-water; below: %s"
              % (n_meas - len(below), n_meas, ",".join(below)),
              file=sys.stderr)
    else:
        print("all %d measured families at-or-above low-water" % n_meas,
              file=sys.stderr)
    bad = bool(regressed or failed)
    print("perf_regress:", "FAIL" if bad else "OK", file=sys.stderr)
    return 2 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
