#!/usr/bin/env python
"""Multi-host smoke test: the full pipeline on a 2-process CPU mesh.

The reference's multi-node story is the Spark cluster manager; ours is
``jax.distributed`` + one global mesh spanning hosts (SURVEY §2.5, §7 hard
part 6).  This script launches TWO OS processes, each owning 4 virtual CPU
devices, forms the 8-device global mesh, and runs construct → map → sum →
Welford stats → toarray across it — collectives ride the (simulated) DCN.

Run directly: ``python scripts/multihost_smoke.py`` (scale up with
``SMOKE_NPROC=4 SMOKE_DEVS=2``).
"""

import os
import subprocess
import sys

# override with SMOKE_NPROC / SMOKE_DEVS for wider topologies
NPROC = int(os.environ.get("SMOKE_NPROC", "2"))
DEVS_PER_PROC = int(os.environ.get("SMOKE_DEVS", "4"))


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _init_distributed(nproc, devs, port_env, pid):
    """Shared per-process preamble (worker AND reload stages): the
    platform must be forced to virtual CPU BEFORE any backend query, and
    the coordinator joined before the repo import."""
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=%d" % devs)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:%s" % os.environ[port_env],
        num_processes=nproc, process_id=pid)
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return jax


def worker(pid):
    jax = _init_distributed(NPROC, DEVS_PER_PROC, "SMOKE_PORT", pid)
    import numpy as np
    import bolt_tpu as bolt
    from bolt_tpu.parallel import make_mesh

    ndev = NPROC * DEVS_PER_PROC
    assert len(jax.devices()) == ndev, jax.devices()
    mesh = make_mesh((ndev,), ("k",))

    # the key axis scales with the topology so any SMOKE_NPROC/SMOKE_DEVS
    # combination shards cleanly
    nkeys = 2 * ndev
    x = np.arange(nkeys * 6 * 4, dtype=np.float64).reshape(nkeys, 6, 4)
    b = bolt.array(x, mesh)
    if NPROC > 1:
        assert not b._data.is_fully_addressable

    m = b.map(lambda v: v * 2 + 1)
    total = m.sum(axis=(0, 1, 2))
    expected = (x * 2 + 1).sum()
    got = float(np.asarray(jax.device_get(total._data)))
    assert got == expected, (got, expected)

    st = b.stats()
    assert np.allclose(np.asarray(st.mean()), x.mean(axis=0))

    # order statistics over the cross-process key axis: the device-side
    # sort/gather spans the (simulated) DCN
    md = m.median()
    assert np.allclose(md.toarray(), np.median(x * 2 + 1, axis=0))

    s = b.swap((0,), (1,))
    assert s.shape == (4, nkeys, 6)

    full = m.toarray()  # cross-host gather path
    assert np.allclose(full, x * 2 + 1)

    # out= target: the cross-host gather writes into a caller buffer
    # (memmap-style) instead of allocating the full array itself
    target = np.zeros(m.shape, m.dtype)
    got = m.toarray(out=target)
    assert got is target and np.allclose(target, x * 2 + 1)

    # iter_shards: every process walks ONLY its own shards, no DCN at
    # all; the union across processes is the whole array
    count = 0
    for index, block in b.iter_shards():
        assert np.allclose(block, x[index])
        count += block.size
    assert count == x.size // max(1, NPROC) or NPROC == 1

    # first(): the one-record fetch must work when the first shard lives
    # on another process (jax replicates the int-indexed record)
    assert np.allclose(b.first(), x[0])

    # grouped reduction: the scatter combine spans processes (records of
    # one group live on different hosts' shards)
    from bolt_tpu.ops import segment_reduce
    glabels = np.arange(nkeys) % 3
    gout = np.asarray(segment_reduce(b, glabels, op="sum").toarray())
    gexp = np.stack([x[glabels == g].sum(axis=0) for g in range(3)])
    assert np.allclose(gout, gexp)

    # memory-bounded cross-host collect: force the slab path and assert
    # no single device-side transfer carried the whole array (the VERDICT
    # r1 scenario was process_allgather replicating a 1 TB array on every
    # host; here shard-bytes accounting stands in for an RSS cap)
    from bolt_tpu.tpu import array as _arr
    big_np = np.arange(nkeys * 16, dtype=np.float64).reshape(nkeys, 16)
    big = bolt.array(big_np, mesh)
    if NPROC > 1:
        assert not big._data.is_fully_addressable
        # byte math on the DEVICE dtype (x64-off narrows f64 -> f32)
        nbytes = big.size * big.dtype.itemsize
        rowbytes = nbytes // big.shape[0]
        old = _arr._GATHER_SLAB_BYTES
        _arr._GATHER_SLAB_BYTES = rowbytes      # force region splitting
        try:
            got = big.toarray()
        finally:
            _arr._GATHER_SLAB_BYTES = old
        assert np.array_equal(got, big_np)
        st = _arr._LAST_GATHER_STATS
        # remote regions were broadcast in sub-region pieces, every piece
        # within the budget — no transfer ever approached the full array
        assert st["regions"] >= NPROC - 1, st
        assert st["broadcasts"] > st["regions"], st
        assert 0 < st["max_piece_bytes"] <= rowbytes, (st, rowbytes)

    # checkpoint written from mesh A (every process saves only the shards
    # it owns), restored onto mesh B with a different topology
    from bolt_tpu import checkpoint
    ckpt_dir = os.environ["SMOKE_CKPT"]
    checkpoint.save(ckpt_dir, m.cache())
    if ndev % 2 == 0 and ndev > 1:
        mesh_b = make_mesh((2, ndev // 2), ("p", "q"))
        restored = checkpoint.load(ckpt_dir, context=mesh_b)
        assert restored.split == m.split
        assert restored.mesh is not mesh and restored.shape == m.shape
        assert np.allclose(restored.toarray(), x * 2 + 1)
        # the restored array is live on the new mesh, not just readable
        assert np.allclose(restored.sum().toarray(), (x * 2 + 1).sum(axis=0))
    # (tempdir cleanup lives in main()'s finally, so failed/timed-out
    # runs don't leak checkpoint dirs in /tmp)

    # the sharded loader: each PROCESS's callback must be invoked only
    # for its own devices' shards — the full array is never assembled in
    # any single process
    src = np.arange(nkeys * 3, dtype=np.float64).reshape(nkeys, 3)
    calls = []

    def loader(idx):
        calls.append(idx)
        return src[idx]

    ld = bolt.fromcallback(loader, src.shape, mesh)
    n_local = len(jax.local_devices())
    assert len(calls) == n_local, (len(calls), n_local)
    rows_seen = sum(len(range(*c[0].indices(nkeys))) for c in calls)
    assert rows_seen == nkeys // NPROC, (rows_seen, nkeys, NPROC)
    assert np.array_equal(ld.toarray(), src)

    # whole-array PCA: the Gram partial products combine with an
    # all-reduce that rides the (simulated) DCN between the processes
    from bolt_tpu.ops import pca
    rs = np.random.RandomState(3)
    px = rs.randn(4 * ndev, 3)
    pb = bolt.array(px, mesh)
    scores, comps, svals = pca(pb, k=2, center=True)
    pxc = px - px.mean(axis=0)
    assert np.allclose(svals, np.linalg.svd(pxc, compute_uv=False)[:2])
    assert scores.shape == (4 * ndev, 2)

    # sequence-parallel smoothing: the long value axis is split across
    # the second mesh axis (so across PROCESSES when NPROC>1) and the
    # filter halos ride the inserted neighbour collectives over DCN
    if ndev % 2 == 0 and ndev > 1:
        from bolt_tpu.ops import smooth
        mesh2 = make_mesh((ndev // 2, 2), ("k2", "v"))
        ylen = 24
        y = np.arange(ndev * ylen * 3, dtype=np.float64).reshape(
            ndev, ylen, 3)
        b2 = bolt.array(y, mesh2, axis=(0,))
        sm = smooth(b2, 5, axis=(0,), size=(6,), shard={0: "v"}).toarray()
        ypad = np.pad(y, ((0, 0), (2, 2), (0, 0)))
        expect = sum(ypad[:, o:o + ylen] for o in range(5)) / 5
        assert np.allclose(sm, expect)

    print("worker %d OK" % pid, flush=True)


def reload_worker(pid):
    """Stage 2 (VERDICT r3 next-9): restore the stage-1 checkpoint with
    a DIFFERENT process count — the written-by-N, read-by-M path (here
    N=NPROC processes wrote it, one process with all devices reads it:
    the common cluster-job → single-host-analysis flow)."""
    nproc = int(os.environ["SMOKE_RELOAD_NPROC"])
    devs = int(os.environ["SMOKE_RELOAD_DEVS"])
    jax = _init_distributed(nproc, devs, "SMOKE_PORT2", pid)
    import numpy as np
    from bolt_tpu import checkpoint
    from bolt_tpu.parallel import make_mesh

    ndev = nproc * devs
    assert len(jax.devices()) == ndev, jax.devices()
    mesh = make_mesh((ndev,), ("k",))
    nkeys = int(os.environ["SMOKE_NKEYS"])
    x = np.arange(nkeys * 6 * 4, dtype=np.float64).reshape(nkeys, 6, 4)
    restored = checkpoint.load(os.environ["SMOKE_CKPT"], context=mesh)
    assert restored.shape == (nkeys, 6, 4), restored.shape
    assert np.allclose(restored.toarray(), x * 2 + 1)
    # live on the new mesh, not just readable
    assert np.allclose(restored.sum().toarray(), (x * 2 + 1).sum(axis=0))
    print("reload worker %d OK" % pid, flush=True)


def main():
    import tempfile
    env = dict(os.environ)
    env["SMOKE_PORT"] = str(_free_port())  # never collide with a stale run
    env["SMOKE_CKPT"] = tempfile.mkdtemp(prefix="bolt_smoke_ckpt_")
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker", str(pid)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in range(NPROC)]
    ok = True
    try:
        for pid, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                ok = False
                print("--- worker %d TIMED OUT ---" % pid)
                continue
            text = out.decode(errors="replace")
            if p.returncode != 0 or ("worker %d OK" % pid) not in text:
                ok = False
                print("--- worker %d FAILED (rc=%s) ---" % (pid, p.returncode))
                print(text[-4000:])
        # stage 2: the checkpoint written by NPROC processes restores in
        # ONE process owning all the devices (differing process counts)
        if ok:
            env["SMOKE_PORT2"] = str(_free_port())
            env["SMOKE_RELOAD_NPROC"] = "1"
            env["SMOKE_RELOAD_DEVS"] = str(NPROC * DEVS_PER_PROC)
            env["SMOKE_NKEYS"] = str(2 * NPROC * DEVS_PER_PROC)
            rp = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--reload", "0"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            procs.append(rp)      # the finally cleanup must cover stage 2
            try:
                out, _ = rp.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                ok = False
                out = b""
                print("--- reload worker TIMED OUT ---")
            text = out.decode(errors="replace")
            if rp.returncode != 0 or "reload worker 0 OK" not in text:
                ok = False
                print("--- reload worker FAILED (rc=%s) ---" % rp.returncode)
                print(text[-4000:])
    finally:
        # never orphan a worker holding the coordinator port
        for p in procs:
            if p.poll() is None:
                p.kill()
        import shutil
        shutil.rmtree(env["SMOKE_CKPT"], ignore_errors=True)
    print("multihost smoke:", "PASS" if ok else "FAIL")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--worker":
        worker(int(sys.argv[2]))
    elif len(sys.argv) > 2 and sys.argv[1] == "--reload":
        reload_worker(int(sys.argv[2]))
    else:
        main()
