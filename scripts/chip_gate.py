#!/usr/bin/env python
"""One-command on-chip correctness gate (VERDICT r3 next-3).

Runs the ``-m chip`` parity subset (``tests/test_chip.py``) against the
REAL TPU with production numerics — x64 OFF, the actual XLA:TPU/Mosaic
lowering — the configuration the CPU-mesh suite structurally cannot
exercise.  Appends a one-line record to ``docs/STATUS.md`` so each
round's run is auditable.

Usage::

    python scripts/chip_gate.py            # run + record
    python scripts/chip_gate.py --no-record
"""

import datetime
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    env = dict(os.environ, BOLT_TEST_CHIP="1")
    # the gate must see the real backend: strip the CPU-mesh overrides a
    # caller's shell may carry
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "-m", "chip", "-q",
         "tests/test_chip.py", "tests/test_chip_matrix.py"],
        cwd=ROOT, env=env, capture_output=True, text=True)
    tail = (proc.stdout.strip().splitlines() or ["(no output)"])[-1]
    print(proc.stdout[-4000:])
    if proc.returncode != 0:
        print(proc.stderr[-4000:], file=sys.stderr)
    line = "- %s chip gate: %s (rc=%d)" % (
        datetime.date.today().isoformat(), tail, proc.returncode)
    print(line)
    if "--no-record" not in sys.argv:
        _record(line)
    return proc.returncode


HEADING = "## Chip gate runs"


def _record(line):
    """Append under a dedicated STATUS.md section (created on first
    run) — a blind file append would land the record inside whatever
    list happens to end the document."""
    path = os.path.join(ROOT, "docs", "STATUS.md")
    with open(path) as f:
        text = f.read()
    if HEADING not in text:
        text = text.rstrip("\n") + "\n\n%s\n\n%s\n" % (HEADING, line)
    else:
        head, _, rest = text.partition(HEADING)
        # insert before the NEXT section heading, not at end-of-file —
        # sections added below the gate log must not swallow records
        nxt = rest.find("\n## ")
        if nxt == -1:
            text = head + HEADING + rest.rstrip("\n") + "\n" + line + "\n"
        else:
            text = (head + HEADING + rest[:nxt].rstrip("\n") + "\n" + line
                    + "\n" + rest[nxt:])
    with open(path, "w") as f:
        f.write(text)


if __name__ == "__main__":
    sys.exit(main())
